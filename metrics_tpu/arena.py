"""Multi-tenant metric arenas: one vmapped program for N concurrent suites.

Production serving means per-user / per-cohort / per-model metric streams —
millions of independent suite instances with tiny states, which a Python
loop would feed one dispatch at a time. :class:`MetricArena` stacks the
functional states (:mod:`metrics_tpu.functional_core`) of N same-config
tenants on a leading axis and drives them with **engine-cached vmapped
donated programs**: ``update(tenant_ids, *batch)``, ``compute()``,
``reset(mask)`` and the per-cohort streaming views each lower to one
program over the whole stack, whatever N is.

The pure kernels that get vmapped are exactly the ones the stateful API
dispatches (``metric_functions`` — one code path, no drift;
``BootStrapper``'s clone fan-out delegates to the same
:func:`stack_states` helper). Three disciplines keep the arena
production-shaped:

- **Slab-bucketed shapes.** Capacity only ever takes the values
  ``slab * 2**k`` (the deferral layer's power-of-two bucketing —
  ``engine.pow2_chunks`` also chunks ragged update batches), so however
  tenants come and go the program cache sees a bounded set of state
  shapes: zero retraces within a slab bucket, one build per program kind
  per new bucket. Removed tenant ids recycle through a free list; a
  per-tenant reset mask clears rows without perturbing neighbours.
- **Slab-granular durability.** ``save()`` writes one CRC-framed journal
  record per slab (``journal.pack_raw_record`` — the sync-pack byte
  discipline), each with its own atomic-write generation ring. A torn
  slab record **demotes to its previous good generation** on
  ``restore()``; neighbouring slabs are never torn with it.
- **Arena-native streaming.** Per-cohort ``Windowed``/``Decayed`` views
  and drift reports run over the stacked states as fused programs
  (segment-reduce merge + vmapped compute), and cohort values land in the
  fleet exposition as ``metrics_tpu_metric_value{tenant_cohort=...}``.

Metrics whose states are ``cat`` lists (the raw-row curve family — AUROC,
ROC, …) cannot ride a fixed-shape stack for ``update``; the arena routes
them through a **row lane** (per-tenant pure-kernel updates, list appends)
and still batches ``compute`` by stacking same-layout tenants and vmapping
the compute kernel per group. Array-state suites get the full fused lane.

Env knobs (shared warn-once parsers — a garbage value warns naming it):
``METRICS_TPU_ARENA_SLAB`` (initial slab size, default 256) and
``METRICS_TPU_ARENA_JOURNAL_EVERY`` (auto-save every N updates when a
``journal_path`` is set; 0 — the default — disables).
"""
from __future__ import annotations

import os
from collections import deque
from typing import Any, Callable, Deque, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from metrics_tpu import functional_core as _funcore
from metrics_tpu.ops import engine as _engine
from metrics_tpu.ops import faults as _faults
from metrics_tpu.ops import journal as _journal
from metrics_tpu.ops import telemetry as _telemetry
from metrics_tpu.parallel import sync as _psync

__all__ = [
    "MetricArena",
    "arena_default_slab",
    "arena_journal_every",
    "arena_snapshot",
    "arena_stats",
    "stack_states",
    "unstack_states",
]

# Arena-plane counters (merged into ``engine.engine_stats()`` and the
# telemetry snapshot; zeroed through the shared reset registry). Every key
# rides the ``arena_`` counter prefix.
_counters: Dict[str, int] = {
    # tenant lifecycle
    "arena_tenants_added": 0,
    "arena_tenants_removed": 0,
    "arena_ids_recycled": 0,
    "arena_grows": 0,
    "arena_shrinks": 0,
    # the vmapped hot path
    "arena_updates": 0,
    "arena_update_chunks": 0,
    "arena_row_updates": 0,
    "arena_computes": 0,
    "arena_resets": 0,
    # streaming views over the stack
    "arena_closes": 0,
    "arena_decay_ticks": 0,
    "arena_cohort_programs": 0,
    "arena_drift_reports": 0,
    # slab-granular durability
    "arena_slab_saves": 0,
    "arena_slab_bytes_written": 0,
    "arena_slab_restores": 0,
    "arena_slab_demotions": 0,
    "arena_slab_prunes": 0,
}

#: Live arena registry: one JSON-safe block per arena name (capacity, tenant
#: count, newest per-cohort values keyed by close id). Carried inside the
#: ``streaming`` telemetry block and rendered fleet-wide as
#: ``metrics_tpu_metric_value{tenant_cohort=...}``.
_ARENAS: Dict[str, Dict[str, Any]] = {}


def arena_stats() -> Dict[str, int]:
    """Arena-plane event counters (folded into ``engine_stats()``): tenant
    lifecycle (adds/removes/recycles, slab grows/shrinks), vmapped program
    traffic (updates and their pow2 chunks, row-lane updates, computes,
    resets), streaming views (closes, decay ticks, cohort programs, drift
    reports), and slab-journal traffic (saves, bytes, restores, demotions).

    Example:
        >>> from metrics_tpu import arena_stats
        >>> arena_stats()["arena_updates"] >= 0
        True
    """
    return dict(_counters)


def _reset_arena() -> None:
    for key in _counters:
        _counters[key] = 0
    _ARENAS.clear()


_telemetry.register_reset("arena", _reset_arena)


def arena_snapshot() -> Dict[str, Any]:
    """The JSON-safe ``arenas`` sub-block the streaming telemetry snapshot
    carries: per arena name — capacity/slab facts, live tenant count, the
    close id, and the newest per-cohort computed scalar values."""
    return {
        name: dict(block, cohorts={k: dict(v) for k, v in block.get("cohorts", {}).items()})
        for name, block in _ARENAS.items()
    }


# ------------------------------------------------------------------ env knobs
class _ArenaWarnOwner:
    """Warn-dedupe anchor for this module's env-knob parse warnings."""


_SLAB_WARN_OWNER = _ArenaWarnOwner()
_JOURNAL_WARN_OWNER = _ArenaWarnOwner()


def arena_default_slab() -> int:
    """Default slab size (tenant rows per journal record, and the capacity
    quantum) when :class:`MetricArena` is constructed without ``slab``
    (``METRICS_TPU_ARENA_SLAB``, default 256, floor 1). An unparseable
    value warns once naming it and falls back."""
    return max(1, _psync._env_int("METRICS_TPU_ARENA_SLAB", 256, owner=_SLAB_WARN_OWNER))


def arena_journal_every() -> int:
    """Auto-journal cadence in updates (``METRICS_TPU_ARENA_JOURNAL_EVERY``,
    default 0 = off, floor 0) for arenas constructed with a
    ``journal_path`` and no explicit ``journal_every``."""
    return max(0, _psync._env_int("METRICS_TPU_ARENA_JOURNAL_EVERY", 0, owner=_JOURNAL_WARN_OWNER))


# ----------------------------------------------------------------- tree utils
def stack_states(states: Sequence[Any]) -> Any:
    """THE stacking code path: N same-structure state trees become one tree
    whose every leaf carries a new leading axis (``jnp.stack`` leaf-wise).
    The arena stacks tenants through here, and ``BootStrapper``'s fused
    clone fan-out stacks its clones through here — one implementation, so
    the two cannot drift.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import stack_states
        >>> stack_states([{"s": jnp.ones(2)}, {"s": jnp.zeros(2)}])["s"].shape
        (2, 2)
    """
    return jax.tree.map(lambda *xs: jnp.stack(xs), *states)


def unstack_states(stacked: Any, n: int) -> List[Any]:
    """Inverse of :func:`stack_states`: split the leading axis back into
    ``n`` per-instance state trees (leaf views, no copies).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import stack_states, unstack_states
        >>> rows = unstack_states(stack_states([{"s": jnp.ones(2)}] * 3), 3)
        >>> len(rows), rows[0]["s"].shape
        (3, (2,))
    """
    return [jax.tree.map(lambda x: x[i], stacked) for i in range(n)]


_SEP = "\x1f"  # flat-name separator (unit separator: never in a state name)


def _flatten_state(tree: Dict[str, Any]) -> Dict[str, Any]:
    """Flatten the (≤2-level) functional state dict into ``{name: leaf}``
    with collection members joined by an unprintable separator — the slab
    record layout and the per-leaf walk the fused programs share."""
    flat: Dict[str, Any] = {}
    for key, value in tree.items():
        if isinstance(value, dict):
            for sub, leaf in value.items():
                flat[f"{key}{_SEP}{sub}"] = leaf
        else:
            flat[key] = value
    return flat


def _unflatten_state(flat: Dict[str, Any], like: Dict[str, Any]) -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    for key, value in like.items():
        if isinstance(value, dict):
            out[key] = {sub: flat[f"{key}{_SEP}{sub}"] for sub in value}
        else:
            out[key] = flat[key]
    return out


def _has_list_state(tree: Any) -> bool:
    if isinstance(tree, dict):
        return any(_has_list_state(v) for v in tree.values())
    return isinstance(tree, list)


def _mask_broadcast(mask: jax.Array, ndim: int) -> jax.Array:
    return mask.reshape(mask.shape + (1,) * (ndim - 1))


def _min_identity(dtype: Any) -> Any:
    dt = jnp.dtype(dtype)
    if dt == jnp.bool_:
        return False
    if jnp.issubdtype(dt, jnp.floating):
        return -jnp.inf
    return jnp.iinfo(dt).min


def _max_identity(dtype: Any) -> Any:
    dt = jnp.dtype(dtype)
    if dt == jnp.bool_:
        return True
    if jnp.issubdtype(dt, jnp.floating):
        return jnp.inf
    return jnp.iinfo(dt).max


def _safe_name(name: Any) -> str:
    from metrics_tpu import streaming as _streaming

    return _streaming._safe_name(name)


_ANON_SEQ = [0]


# ------------------------------------------------------------------ the arena
class MetricArena:
    """N same-config metric suites as ONE leading-axis device state.

    ``template`` is a ``Metric`` or ``MetricCollection`` describing every
    tenant's configuration; its pure functional kernels
    (:func:`metrics_tpu.functional_core.metric_functions`) are what the
    arena vmaps. Tenants are integer ids handed out by :meth:`add` (and
    recycled by :meth:`remove` through a free list); ``capacity`` rounds up
    to the slab bucket ``slab * 2**k`` so the engine's program cache sees at
    most one build per program kind per bucket.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import MeanMetric
        >>> from metrics_tpu.arena import MetricArena
        >>> arena = MetricArena(MeanMetric(), capacity=4, slab=4)
        >>> ids = arena.add(3)
        >>> arena.update(ids, jnp.asarray([[1.0], [2.0], [3.0]]))
        >>> [round(float(v), 1) for v in arena.compute(ids)]
        [1.0, 2.0, 3.0]
    """

    def __init__(
        self,
        template: Any,
        capacity: int = 0,
        *,
        name: Optional[str] = None,
        slab: Optional[int] = None,
        cohort: Optional[str] = None,
        journal_path: Optional[str] = None,
        journal_every: Optional[int] = None,
        window_slots: int = 8,
    ) -> None:
        fns = _funcore.metric_functions(template)
        self._template = template
        self._init_fn, self._update_fn, self._compute_fn = fns
        self._key = _funcore._export_key(template)
        if name is None:
            _ANON_SEQ[0] += 1
            name = f"{type(template).__name__}_arena{_ANON_SEQ[0]}"
        self._name = _safe_name(name)
        self._slab = max(1, int(slab)) if slab else arena_default_slab()
        self._default_cohort = str(cohort) if cohort is not None else "default"
        self._journal_path = str(journal_path) if journal_path else None
        self._journal_every = (
            max(0, int(journal_every)) if journal_every is not None else arena_journal_every()
        )
        self._updates_since_save = 0
        self._window_slots = max(1, int(window_slots))
        self._closes = 0
        #: ring of per-close, per-cohort merged host states (the arena's
        #: window arithmetic — re-merged by spec at window_values() time)
        self._ring: Deque[Tuple[int, Dict[str, Dict[str, Any]]]] = deque(maxlen=self._window_slots)

        with jax.ensure_compile_time_eval():
            self._proto = self._init_fn()
        self._fused = not _has_list_state(self._proto)
        self._spec_tree = self._build_spec_tree()
        self._flat_proto = _flatten_state(self._proto)
        self._flat_specs = _flatten_state(self._spec_tree)
        self._decay_validated: Dict[float, float] = {}

        self._capacity = 0
        self._stacked: Optional[Dict[str, Any]] = None  # fused lane
        self._rows: List[Optional[Dict[str, Any]]] = []  # row lane
        self._live = np.zeros((0,), dtype=bool)
        self._counts = np.zeros((0,), dtype=np.int64)
        self._cohorts: np.ndarray = np.empty((0,), dtype=object)
        self._free: List[int] = []  # recycled ids, descending (pop() = lowest)
        self._watermark = 0  # never-issued id frontier
        self._grow_to(self._bucket_capacity(max(int(capacity), 1)))

    # ------------------------------------------------------------- properties
    @property
    def name(self) -> str:
        return self._name

    @property
    def capacity(self) -> int:
        """Allocated tenant rows — always ``slab * 2**k``."""
        return self._capacity

    @property
    def slab_size(self) -> int:
        return self._slab

    @property
    def slabs(self) -> int:
        return self._capacity // self._slab

    @property
    def tenants(self) -> int:
        """Live tenant count."""
        return int(self._live.sum())

    @property
    def fused(self) -> bool:
        """True when every state is a fixed-shape array (the vmapped donated
        lane); False routes updates through the per-tenant row lane."""
        return self._fused

    @property
    def window_id(self) -> int:
        return self._closes

    def live_ids(self) -> np.ndarray:
        """Live tenant ids, ascending."""
        return np.nonzero(self._live)[0].astype(np.int64)

    def cohort_of(self, tenant_id: int) -> str:
        self._check_live(np.asarray([tenant_id]))
        return self._cohorts[int(tenant_id)] or self._default_cohort

    # ------------------------------------------------------- capacity buckets
    def _bucket_capacity(self, n: int) -> int:
        """The smallest ``slab * 2**k`` covering ``n`` tenants — the bounded
        shape set the program cache keys on (same power-of-two discipline as
        ``engine.pow2_chunks``)."""
        slabs = max(1, -(-int(n) // self._slab))
        return self._slab * (1 << (slabs - 1).bit_length())

    def _grow_to(self, new_cap: int) -> None:
        old_cap = self._capacity
        if new_cap <= old_cap:
            return
        pad = new_cap - old_cap
        if self._fused:
            if self._stacked is None:
                self._stacked = {
                    k: jnp.broadcast_to(p, (new_cap,) + p.shape)
                    for k, p in self._flat_proto.items()
                }
            else:
                self._stacked = {
                    k: jnp.concatenate(
                        [leaf, jnp.broadcast_to(self._flat_proto[k], (pad,) + self._flat_proto[k].shape)]
                    )
                    for k, leaf in self._stacked.items()
                }
        else:
            self._rows.extend([None] * pad)
        self._live = np.concatenate([self._live, np.zeros(pad, dtype=bool)])
        self._counts = np.concatenate([self._counts, np.zeros(pad, dtype=np.int64)])
        self._cohorts = np.concatenate([self._cohorts, np.full(pad, None, dtype=object)])
        self._capacity = new_cap
        if old_cap:
            _counters["arena_grows"] += 1

    def _maybe_shrink(self) -> None:
        """Shrink trailing slabs when no live tenant occupies them — ids are
        stable (no compaction), so only the empty tail can be released."""
        live = np.nonzero(self._live)[0]
        high = int(live.max()) + 1 if live.size else 1
        new_cap = self._bucket_capacity(high)
        if new_cap >= self._capacity:
            return
        if self._fused:
            self._stacked = {k: leaf[:new_cap] for k, leaf in self._stacked.items()}
        else:
            del self._rows[new_cap:]
        self._live = self._live[:new_cap]
        self._counts = self._counts[:new_cap]
        self._cohorts = self._cohorts[:new_cap]
        self._free = [i for i in self._free if i < new_cap]
        self._watermark = min(self._watermark, new_cap)
        self._capacity = new_cap
        _counters["arena_shrinks"] += 1

    # -------------------------------------------------------- tenant lifecycle
    def add(self, count: int = 1, *, cohort: Optional[str] = None) -> List[int]:
        """Allocate ``count`` tenant ids (free-list recycles removed ids
        first; fresh ids grow the stack in slab buckets). ``cohort`` labels
        every allocated tenant for the per-cohort streaming views."""
        count = int(count)
        if count < 1:
            raise ValueError(f"add() needs a positive tenant count, got {count}")
        ids: List[int] = []
        while self._free and len(ids) < count:
            ids.append(self._free.pop())
            _counters["arena_ids_recycled"] += 1
        fresh = count - len(ids)
        if fresh:
            needed = self._watermark + fresh
            if needed > self._capacity:
                self._grow_to(self._bucket_capacity(needed))
            ids.extend(range(self._watermark, needed))
            self._watermark = needed
        label = str(cohort) if cohort is not None else None
        idx = np.asarray(ids, dtype=np.int64)
        self._live[idx] = True
        self._counts[idx] = 0
        self._cohorts[idx] = label
        if not self._fused:
            for tid in ids:
                self._rows[tid] = self._fresh_row()
        _counters["arena_tenants_added"] += len(ids)
        return ids

    def remove(self, tenant_ids: Any) -> None:
        """Retire tenants: their rows reset (isolated by mask), their ids go
        back on the free list, and fully-empty trailing slabs shrink off."""
        ids = self._as_ids(tenant_ids)
        self._check_live(ids)
        self.reset(tenant_ids=ids)
        self._live[ids] = False
        self._cohorts[ids] = None
        if not self._fused:
            for tid in ids.tolist():
                self._rows[tid] = None
        self._free = sorted(set(self._free).union(ids.tolist()), reverse=True)
        _counters["arena_tenants_removed"] += int(ids.size)
        self._maybe_shrink()

    def _fresh_row(self) -> Dict[str, Any]:
        with jax.ensure_compile_time_eval():
            return self._init_fn()

    def _as_ids(self, tenant_ids: Any) -> np.ndarray:
        ids = np.asarray(tenant_ids, dtype=np.int64).ravel()
        if ids.size == 0:
            raise ValueError("empty tenant id list")
        if np.unique(ids).size != ids.size:
            raise ValueError("duplicate tenant ids in one call (scatter order would be undefined)")
        if ids.min() < 0 or ids.max() >= self._capacity:
            raise ValueError(
                f"tenant id out of range [0, {self._capacity}): {ids.min()}..{ids.max()}"
            )
        return ids

    def _check_live(self, ids: np.ndarray) -> None:
        dead = ids[~self._live[ids]]
        if dead.size:
            raise ValueError(f"tenant id(s) {dead.tolist()} are not live (add() them first)")

    # ------------------------------------------------------------ the hot path
    def update(self, tenant_ids: Any, *args: Any, **kwargs: Any) -> None:
        """Apply one batch per tenant: every array leaf of ``args``/``kwargs``
        carries a leading axis of ``len(tenant_ids)``. The fused lane runs
        gather → ``vmap(update)`` → scatter as engine-cached donated
        programs, with ragged tenant counts split into ``pow2_chunks``
        buckets so the shape set stays bounded; the row lane applies the
        same pure kernel per tenant (``cat``-state suites)."""
        ids = self._as_ids(tenant_ids)
        self._check_live(ids)
        t0 = _telemetry.now() if _telemetry.armed else 0.0
        chunks = 0
        if self._fused:
            off = 0
            for size in _engine.pow2_chunks(int(ids.size)):
                sl = slice(off, off + size)
                chunk_ids = jnp.asarray(ids[sl].astype(np.int32))
                chunk_batch = jax.tree.map(lambda leaf: leaf[sl], (args, kwargs))
                exe = self._update_exe(size)
                self._stacked = exe.run(self._stacked, (chunk_ids,) + chunk_batch)
                chunks += 1
                off += size
            _counters["arena_update_chunks"] += chunks
        else:
            for pos, tid in enumerate(ids.tolist()):
                row_batch = jax.tree.map(lambda leaf: leaf[pos], (args, kwargs))
                row_args, row_kwargs = row_batch
                self._rows[tid] = self._update_fn(self._rows[tid], *row_args, **row_kwargs)
            _counters["arena_row_updates"] += int(ids.size)
        self._counts[ids] += 1
        _counters["arena_updates"] += 1
        if t0 and _telemetry.armed:
            _telemetry.emit(
                "arena-update", self._name, "arena", t0, _telemetry.now() - t0,
                {
                    "tenants": int(ids.size),
                    "chunks": chunks,
                    "capacity": self._capacity,
                    "lane": "fused" if self._fused else "rows",
                },
            )
        self._updates_since_save += 1
        if (
            self._journal_path
            and self._journal_every
            and self._updates_since_save >= self._journal_every
        ):
            self.save()

    def _update_exe(self, chunk: int) -> Any:
        update_fn = self._update_fn
        proto = self._proto

        def build() -> Tuple[Callable, Any, Dict[str, Any]]:
            def step(stacked: Dict[str, Any], ids: jax.Array, a: tuple, k: dict):
                sub = _unflatten_state(
                    {name: jnp.take(leaf, ids, axis=0) for name, leaf in stacked.items()}, proto
                )
                new = jax.vmap(lambda s, aa, kk: update_fn(s, *aa, **kk))(sub, a, k)
                flat_new = _flatten_state(new)
                return {name: leaf.at[ids].set(flat_new[name]) for name, leaf in stacked.items()}

            return step, None, {}

        return _engine.acquire_keyed(("arena-update", self._key, self._capacity, chunk), build)

    def compute(self, tenant_ids: Optional[Any] = None) -> Any:
        """Per-tenant computed values with a leading axis aligned to
        ``tenant_ids`` (default: every live tenant ascending — pair with
        :meth:`live_ids`). One vmapped program over the whole stack per
        capacity bucket; row-lane tenants batch per state layout."""
        ids = self.live_ids() if tenant_ids is None else self._as_ids(tenant_ids)
        if ids.size == 0:
            raise ValueError("compute() on an empty arena (no live tenants)")
        self._check_live(ids)
        _counters["arena_computes"] += 1
        if self._fused:
            exe = self._compute_exe()
            values = exe(self._stacked)
            sel = jnp.asarray(ids.astype(np.int32))
            return jax.tree.map(lambda v: jnp.take(jnp.asarray(v), sel, axis=0), values)
        # row lane: group same-layout tenants, stack each group, vmap once
        groups: Dict[Any, List[int]] = {}
        for pos, tid in enumerate(ids.tolist()):
            leaves, treedef = jax.tree.flatten(self._rows[tid])
            sig = (treedef, tuple((tuple(l.shape), jnp.dtype(l.dtype).name) for l in leaves))
            groups.setdefault(sig, []).append(pos)
        per_pos: List[Any] = [None] * int(ids.size)
        compute_fn = self._compute_fn
        for sig, positions in groups.items():
            stacked = stack_states([self._rows[int(ids[p])] for p in positions])

            def build() -> Tuple[Callable, Any, Dict[str, Any]]:
                def step(st):
                    return jax.vmap(lambda s: compute_fn(s, axis_name=None))(st)

                return step, None, {}

            exe = _engine.acquire_keyed(
                ("arena-compute-rows", self._key, len(positions), sig), build, donate=False
            )
            vals = exe(stacked)
            for i, p in enumerate(positions):
                per_pos[p] = jax.tree.map(lambda v: v[i], vals)
        return jax.tree.map(lambda *xs: jnp.stack(xs), *per_pos)

    def _compute_exe(self) -> Any:
        compute_fn = self._compute_fn
        proto = self._proto

        def build() -> Tuple[Callable, Any, Dict[str, Any]]:
            def step(stacked: Dict[str, Any]):
                tree = _unflatten_state(stacked, proto)
                return jax.vmap(lambda s: compute_fn(s, axis_name=None))(tree)

            return step, None, {}

        return _engine.acquire_keyed(
            ("arena-compute", self._key, self._capacity), build, donate=False
        )

    def reset(self, mask: Optional[Any] = None, *, tenant_ids: Optional[Any] = None) -> None:
        """Reset selected tenants to their init state through one donated
        masked program — tenant A's reset never perturbs tenant B (the
        unmasked rows pass through untouched, bit-exact). ``mask`` is a
        length-``capacity`` bool vector; ``tenant_ids`` is the sparse
        equivalent; neither resets every live tenant."""
        if mask is not None and tenant_ids is not None:
            raise ValueError("pass mask OR tenant_ids, not both")
        if mask is not None:
            m = np.asarray(mask, dtype=bool).ravel()
            if m.size != self._capacity:
                raise ValueError(f"mask has {m.size} rows, arena capacity is {self._capacity}")
        else:
            m = np.zeros(self._capacity, dtype=bool)
            ids = self.live_ids() if tenant_ids is None else self._as_ids(tenant_ids)
            m[ids] = True
        if self._fused:
            exe = self._reset_exe()
            self._stacked = exe.run(self._stacked, (jnp.asarray(m),))
        else:
            for tid in np.nonzero(m)[0].tolist():
                if self._rows[tid] is not None:
                    self._rows[tid] = self._fresh_row()
        self._counts[m] = 0
        _counters["arena_resets"] += 1

    def _reset_exe(self) -> Any:
        flat_proto = self._flat_proto

        def build() -> Tuple[Callable, Any, Dict[str, Any]]:
            def step(stacked: Dict[str, Any], m: jax.Array):
                return {
                    name: jnp.where(_mask_broadcast(m, leaf.ndim), flat_proto[name], leaf)
                    for name, leaf in stacked.items()
                }

            return step, None, {}

        return _engine.acquire_keyed(("arena-reset", self._key, self._capacity), build)

    def precompile(self, *args: Any, batch: Optional[int] = None, **kwargs: Any) -> Dict[str, Any]:
        """AOT-warm the arena's gather → ``vmap(update)`` → scatter, fused
        compute and mask-reset programs for the current capacity — without
        touching a single tenant's state (everything lowers from
        :class:`jax.ShapeDtypeStruct` declarations, so no example data is
        dispatched and nothing needs rolling back).

        ``args``/``kwargs`` mirror one :meth:`update` call's batch: leaves
        (arrays or ``ShapeDtypeStruct``) carry a leading tenant axis. The
        update program is warmed for every ``pow2_chunks`` bucket of that
        batch size (``batch`` overrides it; defaults to one slab), exactly
        the shape set live ragged traffic dispatches. With the persistent
        program cache enabled, warmed programs load from (or store to) the
        on-disk tier — :attr:`~metrics_tpu.ops.engine.Executable.cache_source`
        per program lands in the returned ``sources`` map alongside the
        ``compiles`` / ``progcache_hits`` / ``progcache_stores`` deltas.

        The row lane (``cat``-state suites) dispatches per-tenant eager
        kernels, not engine-cached arena programs — it reports ``skipped``."""
        before = _engine.program_summary()
        stats0 = _engine.engine_stats()

        def _report(sources: Dict[str, str], skipped: Optional[str] = None) -> Dict[str, Any]:
            after = _engine.program_summary()
            stats1 = _engine.engine_stats()
            out = {
                "programs": after["count"] - before["count"],
                "compiles": after["compiles"] - before["compiles"],
                "progcache_hits": int(stats1.get("progcache_hits", 0))
                - int(stats0.get("progcache_hits", 0)),
                "progcache_stores": int(stats1.get("progcache_stores", 0))
                - int(stats0.get("progcache_stores", 0)),
                "sources": sources,
            }
            if skipped:
                out["skipped"] = skipped
            return out

        if not self._fused:
            return _report(
                {}, "row lane (cat-state suites) dispatches eager per-tenant kernels"
            )
        if self._stacked is None:
            return _report({}, "no capacity reserved yet — add a tenant first")
        state_s = {
            k: jax.ShapeDtypeStruct(tuple(leaf.shape), leaf.dtype)
            for k, leaf in self._stacked.items()
        }
        if batch is not None:
            n = int(batch)
        else:
            dims = [
                int(tuple(leaf.shape)[0])
                for leaf in jax.tree.leaves((args, kwargs))
                if hasattr(leaf, "shape") and len(tuple(leaf.shape)) >= 1
            ]
            n = dims[0] if dims else self._slab
        sources: Dict[str, str] = {}
        for c in sorted(set(_engine.pow2_chunks(n))):

            def _chunk(leaf: Any, c: int = c) -> Any:
                if hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
                    return jax.ShapeDtypeStruct((c,) + tuple(leaf.shape)[1:], leaf.dtype)
                return leaf

            a_s, k_s = jax.tree.map(_chunk, (args, kwargs))
            ids_s = jax.ShapeDtypeStruct((c,), jnp.int32)
            sources[f"arena-update/{c}"] = self._update_exe(c).precompile(
                state_s, (ids_s, a_s, k_s)
            )
        sources["arena-compute"] = self._compute_exe().precompile(state_s)
        sources["arena-reset"] = self._reset_exe().precompile(
            state_s, (jax.ShapeDtypeStruct((self._capacity,), jnp.bool_),)
        )
        return _report(sources)

    # ------------------------------------------------------ per-tenant states
    def tenant_state(self, tenant_id: int) -> Dict[str, Any]:
        """One tenant's functional state tree (a view of the stack) — the
        bridge back to ``host_handoff``/per-instance tooling."""
        ids = self._as_ids([tenant_id])
        self._check_live(ids)
        tid = int(ids[0])
        if self._fused:
            return jax.tree.map(lambda leaf: leaf[tid], _unflatten_state(self._stacked, self._proto))
        return jax.tree.map(lambda leaf: leaf, self._rows[tid])

    # --------------------------------------------------------- cohort streaming
    def _build_spec_tree(self) -> Dict[str, Any]:
        if _funcore._is_collection(self._template):
            return {
                name: {s: str(spec) for s, spec in m._reduction_specs.items()}
                for name, m in self._template.items(keep_base=True, copy_state=False)
            }
        return {s: str(spec) for s, spec in self._template._reduction_specs.items()}

    def _check_cohort_mergeable(self, what: str) -> None:
        for name, spec in self._flat_specs.items():
            if spec not in ("sum", "mean", "max", "min"):
                raise ValueError(
                    f"{what} needs cohort-mergeable states (sum/mean/max/min); "
                    f"state {name.replace(_SEP, '.')} of {self._name} reduces by {spec!r}"
                )

    def _effective_cohorts(self, ids: np.ndarray) -> np.ndarray:
        """Cohort labels for ``ids`` with unlabelled rows mapped to the
        default cohort — one vectorized pass, no per-tenant Python loop."""
        raw = self._cohorts[ids]
        return np.where((raw == None) | (raw == ""), self._default_cohort, raw).astype(str)  # noqa: E711 — elementwise None test on an object array

    def _cohort_layout(self) -> Tuple[List[str], np.ndarray]:
        """(sorted cohort labels, per-row segment index) — dead rows land in
        the drop segment ``len(cohorts)``. Vectorized (``np.unique`` over the
        live rows' labels): one window close at a million tenants is numpy
        work, not millions of interpreter iterations."""
        live_ids = np.nonzero(self._live)[0]
        if not live_ids.size:
            return [], np.zeros(self._capacity, dtype=np.int32)
        labels_arr, inverse = np.unique(self._effective_cohorts(live_ids), return_inverse=True)
        seg = np.full(self._capacity, len(labels_arr), dtype=np.int32)
        seg[live_ids] = inverse.astype(np.int32)
        return labels_arr.tolist(), seg

    def _cohort_exe(self, num_cohorts: int) -> Any:
        flat_specs = self._flat_specs
        compute_fn = self._compute_fn
        proto = self._proto

        def build() -> Tuple[Callable, Any, Dict[str, Any]]:
            def step(stacked: Dict[str, Any], seg: jax.Array, live: jax.Array, w: jax.Array):
                n = num_cohorts + 1  # +1 drop segment for dead rows
                wsum = jax.ops.segment_sum(w, seg, num_segments=n)[:num_cohorts]
                merged: Dict[str, Any] = {}
                for name, leaf in stacked.items():
                    spec = flat_specs[name]
                    if spec == "sum":
                        z = jnp.where(_mask_broadcast(live, leaf.ndim), leaf, jnp.zeros((), leaf.dtype))
                        merged[name] = jax.ops.segment_sum(z, seg, num_segments=n)[:num_cohorts]
                    elif spec == "mean":
                        wb = _mask_broadcast(w, leaf.ndim)
                        num = jax.ops.segment_sum(
                            leaf.astype(jnp.float32) * wb, seg, num_segments=n
                        )[:num_cohorts]
                        den = jnp.maximum(_mask_broadcast(wsum, leaf.ndim), 1.0)
                        merged[name] = (num / den).astype(leaf.dtype)
                    elif spec == "max":
                        z = jnp.where(
                            _mask_broadcast(live, leaf.ndim), leaf, jnp.asarray(_min_identity(leaf.dtype), leaf.dtype)
                        )
                        merged[name] = jax.ops.segment_max(z, seg, num_segments=n)[:num_cohorts]
                    else:  # min
                        z = jnp.where(
                            _mask_broadcast(live, leaf.ndim), leaf, jnp.asarray(_max_identity(leaf.dtype), leaf.dtype)
                        )
                        merged[name] = jax.ops.segment_min(z, seg, num_segments=n)[:num_cohorts]
                values = jax.vmap(lambda s: compute_fn(s, axis_name=None))(
                    _unflatten_state(merged, proto)
                )
                return merged, values

            return step, None, {}

        return _engine.acquire_keyed(
            ("arena-cohort", self._key, self._capacity, num_cohorts), build, donate=False
        )

    def cohort_values(self) -> Dict[str, Any]:
        """Per-cohort computed values, merged across each cohort's tenants
        as ONE fused program (spec-faithful segment reduce — ``sum`` adds,
        ``mean`` weights by per-tenant update counts, ``max``/``min`` take
        extrema — then a vmapped compute over the C merged states). Also
        refreshes this arena's exposition block."""
        self._check_cohort_mergeable("cohort_values()")
        if not self._fused:
            raise ValueError(
                f"cohort_values() needs the fused lane; arena {self._name!r} carries "
                "cat/list states (row lane)"
            )
        labels, seg = self._cohort_layout()
        if not labels:
            return {}
        _, values = self._cohort_step(labels, seg)
        out = self._slice_cohort_values(labels, values)
        self._publish(cohorts=out)
        return out

    def _cohort_step(self, labels: List[str], seg: np.ndarray) -> Tuple[Dict[str, Any], Any]:
        exe = self._cohort_exe(len(labels))
        w = (self._counts * self._live).astype(np.float32)
        merged, values = exe(
            self._stacked, jnp.asarray(seg), jnp.asarray(self._live), jnp.asarray(w)
        )
        _counters["arena_cohort_programs"] += 1
        return merged, values

    def _slice_cohort_values(self, labels: List[str], values: Any) -> Dict[str, Any]:
        return {
            label: jax.tree.map(lambda v: jnp.asarray(v)[i], values)
            for i, label in enumerate(labels)
        }

    def close_window(self) -> Dict[str, Any]:
        """Close one arena-wide window: merge every cohort's tenants (one
        fused program), push the merged per-cohort states into the window
        ring, reset every live tenant's accumulation (the next stride
        starts clean), and publish the close's per-cohort values keyed by
        the close id. Returns ``{window, cohorts, slots}``."""
        self._check_cohort_mergeable("close_window()")
        if not self._fused:
            raise ValueError(
                f"close_window() needs the fused lane; arena {self._name!r} carries "
                "cat/list states (row lane)"
            )
        t0 = _telemetry.now() if _telemetry.armed else 0.0
        labels, seg = self._cohort_layout()
        close_id = self._closes + 1
        slot: Dict[str, Dict[str, Any]] = {}
        values: Dict[str, Any] = {}
        if labels:
            merged, vals = self._cohort_step(labels, seg)
            counts = np.zeros(len(labels), dtype=np.int64)
            np.add.at(counts, seg[self._live], self._counts[self._live])
            for i, label in enumerate(labels):
                slot[label] = {
                    "states": {name: np.asarray(leaf[i]) for name, leaf in merged.items()},
                    "count": int(counts[i]),
                }
            values = self._slice_cohort_values(labels, vals)
            self.reset()  # every live tenant starts the next stride clean
        self._closes = close_id
        self._ring.append((close_id, slot))
        _counters["arena_closes"] += 1
        from metrics_tpu import streaming as _streaming

        self._publish(
            cohorts=values,
            values_entry=(close_id, {c: _streaming._scalar_map(v) for c, v in values.items()}),
        )
        if t0 and _telemetry.armed:
            _telemetry.emit(
                "arena-close", self._name, "arena", t0, _telemetry.now() - t0,
                {"window": close_id, "cohorts": len(labels), "slots": len(self._ring)},
            )
        return {"window": close_id, "cohorts": values, "slots": len(self._ring)}

    def window_values(self) -> Dict[str, Any]:
        """Per-cohort windowed values: re-merge the retained ring slots
        (spec-faithful, like the streaming plane's ``_merge_record``) and
        compute — a cohort's window value is exactly what one fresh suite
        fed the retained strides would compute."""
        folded: Dict[str, Tuple[Dict[str, np.ndarray], int]] = {}
        for _, slot in self._ring:
            for label, entry in slot.items():
                if label not in folded:
                    folded[label] = (
                        {k: np.array(v, copy=True) for k, v in entry["states"].items()},
                        int(entry["count"]),
                    )
                    continue
                acc, c_acc = folded[label]
                c_inc = int(entry["count"])
                for name, inc in entry["states"].items():
                    spec = self._flat_specs[name]
                    if spec == "sum":
                        acc[name] = acc[name] + inc
                    elif spec == "mean":
                        total = max(c_acc + c_inc, 1)
                        acc[name] = (c_acc * acc[name] + c_inc * inc) / total
                    elif spec == "max":
                        acc[name] = np.maximum(acc[name], inc)
                    else:
                        acc[name] = np.minimum(acc[name], inc)
                folded[label] = (acc, c_acc + c_inc)
        out: Dict[str, Any] = {}
        for label, (flat, _count) in folded.items():
            state = _unflatten_state({k: jnp.asarray(v) for k, v in flat.items()}, self._proto)
            out[label] = self._compute_fn(state, axis_name=None)
        return out

    def decay_tick(self, halflife: float) -> None:
        """One EMA tick over the WHOLE arena: every tenant's every state
        scales by ``0.5 ** (1 / halflife)`` through one donated program —
        the arena-native ``Decayed`` view. Requires every state to reduce by
        ``sum`` over a floating dtype (same contract as ``Decayed``)."""
        halflife = float(halflife)
        if not halflife > 0:
            raise ValueError(f"halflife must be a positive update count, got {halflife}")
        decay = self._decay_validated.get(halflife)
        if decay is None:
            if not self._fused:
                raise ValueError(
                    f"decay_tick() needs the fused lane; arena {self._name!r} carries "
                    "cat/list states (row lane)"
                )
            for name, spec in self._flat_specs.items():
                if spec != "sum":
                    raise ValueError(
                        f"decay_tick() requires sum-reduction states; "
                        f"{name.replace(_SEP, '.')} reduces by {spec!r}"
                    )
                if not jnp.issubdtype(self._flat_proto[name].dtype, jnp.floating):
                    raise ValueError(
                        f"decay_tick() requires floating states; {name.replace(_SEP, '.')} is "
                        f"{self._flat_proto[name].dtype} (an integer count cannot decay exactly)"
                    )
            decay = float(0.5 ** (1.0 / halflife))
            self._decay_validated[halflife] = decay

        def build() -> Tuple[Callable, Any, Dict[str, Any]]:
            def step(stacked: Dict[str, Any]):
                return {k: v * jnp.asarray(decay, v.dtype) for k, v in stacked.items()}

            return step, None, {}

        exe = _engine.acquire_keyed(("arena-decay", self._key, self._capacity, decay), build)
        self._stacked = exe.run(self._stacked)
        _counters["arena_decay_ticks"] += 1

    def cohort_drift(
        self, cohort: str, reference: Optional[Any] = None, *, bins: Optional[int] = None
    ) -> Dict[str, Any]:
        """PSI/KS of one cohort's stacked raw states against another cohort
        (``reference`` as a label) or an explicit sample — scores land in
        the streaming registry as ``metrics_tpu_drift_score{name=
        "<arena>/<cohort>"}``."""
        from metrics_tpu import streaming as _streaming

        current = self._cohort_sample(str(cohort))
        if reference is None:
            raise ValueError("cohort_drift needs a reference cohort label or sample")
        ref = self._cohort_sample(str(reference)) if isinstance(reference, str) else reference
        _counters["arena_drift_reports"] += 1
        return _streaming.drift_report(
            current, ref, bins=bins, name=f"{self._name}/{_safe_name(cohort)}"
        )

    def _cohort_sample(self, cohort: str) -> np.ndarray:
        live_ids = np.nonzero(self._live)[0]
        ids = live_ids[self._effective_cohorts(live_ids) == cohort].tolist() if live_ids.size else []
        if not ids:
            raise ValueError(f"cohort {cohort!r} has no live tenants in arena {self._name!r}")
        rows: List[np.ndarray] = []
        if self._fused:
            for leaf in self._stacked.values():
                arr = np.asarray(leaf[np.asarray(ids)], dtype=np.float64).ravel()
                if arr.size:
                    rows.append(arr)
        else:
            for tid in ids:
                for leaf in jax.tree.leaves(self._rows[tid]):
                    arr = np.asarray(leaf, dtype=np.float64).ravel()
                    if arr.size:
                        rows.append(arr)
        return np.concatenate(rows) if rows else np.zeros((0,), dtype=np.float64)

    def _publish(
        self,
        *,
        cohorts: Optional[Dict[str, Any]] = None,
        values_entry: Optional[Tuple[int, Dict[str, Dict[str, float]]]] = None,
    ) -> None:
        from metrics_tpu import streaming as _streaming

        block = _ARENAS.setdefault(self._name, {"name": self._name, "values": {}})
        block.update(
            capacity=self._capacity,
            tenants=self.tenants,
            slab=self._slab,
            slabs=self.slabs,
            window=self._closes,
            lane="fused" if self._fused else "rows",
        )
        if cohorts is not None:
            block["cohorts"] = {
                _safe_name(c): _streaming._scalar_map(v) for c, v in cohorts.items()
            }
        if values_entry is not None:
            close_id, per_cohort = values_entry
            block["values"][str(close_id)] = per_cohort
            keep = _streaming.window_values_kept()
            for wid in sorted(block["values"], key=int)[:-keep]:
                del block["values"][wid]

    # ------------------------------------------------------------- durability
    def _slab_path(self, path: str, k: int) -> str:
        return f"{path}.slab{k}"

    def _scan_generations(self) -> int:
        # tolerate rings widened by a previous METRICS_TPU_JOURNAL_GENERATIONS
        return _journal.journal_generations() + 8

    def _slab_on_disk(self, path: str, k: int) -> bool:
        base = self._slab_path(path, k)
        return any(
            os.path.exists(_journal._gen_path(base, g)) for g in range(self._scan_generations())
        )

    def _prune_stale_slabs(self, path: str) -> None:
        """Unlink slab files (and their generation rings) beyond the current
        slab count — after a shrink, a stale higher-numbered record must not
        survive for :meth:`restore` to walk onto and resurrect removed
        tenants."""
        gens = self._scan_generations()
        k = self.slabs
        while True:
            base = self._slab_path(path, k)
            stale = [
                _journal._gen_path(base, g)
                for g in range(gens)
                if os.path.exists(_journal._gen_path(base, g))
            ]
            if not stale:
                return
            for gpath in stale:
                try:
                    os.remove(gpath)
                except OSError:  # pragma: no cover - best-effort cleanup; restore ignores stale slabs anyway
                    pass
            _counters["arena_slab_prunes"] += 1
            k += 1

    def save(self, path: Optional[str] = None) -> int:
        """Persist the arena as ONE CRC-framed journal record per slab (each
        with its own atomic-write generation ring) — slab-granular
        durability: a crash tears at most the slab being written, and that
        slab demotes to its previous good generation on :meth:`restore`.
        Slab files beyond the current slab count (left behind by a shrink)
        are unlinked afterwards so a later restore cannot resurrect retired
        tenants. Returns total bytes written."""
        path = str(path) if path else self._journal_path
        if not path:
            raise ValueError("this arena was constructed without journal_path")
        if not self._fused:
            raise ValueError(
                f"arena {self._name!r} carries cat/list states; the slab byte layout "
                "needs fixed-shape array states (journal the tenants individually)"
            )
        t0 = _telemetry.now() if _telemetry.armed else 0.0
        total = 0
        S = self._slab
        host = {name: np.asarray(leaf) for name, leaf in self._stacked.items()}
        statics = self._static_attrs()
        for k in range(self.slabs):
            sl = slice(k * S, (k + 1) * S)
            arrays = {name: arr[sl] for name, arr in host.items()}
            record = _journal.pack_raw_record(
                arrays,
                manifest_extra={
                    "arena": {
                        "name": self._name,
                        "slab": k,
                        "slab_size": S,
                        "capacity": self._capacity,
                        "live": [int(b) for b in self._live[sl]],
                        "counts": [int(c) for c in self._counts[sl]],
                        "cohorts": self._cohorts[sl].tolist(),
                        "static_attrs": statics,
                    },
                    "epoch": _psync.world_epoch(),
                },
            )
            _journal.write_record(self._slab_path(path, k), record)
            total += len(record)
            _counters["arena_slab_saves"] += 1
        _counters["arena_slab_bytes_written"] += total
        self._prune_stale_slabs(path)
        self._updates_since_save = 0
        if t0 and _telemetry.armed:
            _telemetry.emit(
                "arena-journal", self._name, "arena", t0, _telemetry.now() - t0,
                {"op": "save", "slabs": self.slabs, "bytes": total},
            )
        return total

    def _static_attrs(self) -> Dict[str, Dict[str, Any]]:
        if _funcore._is_collection(self._template):
            return {
                name: _journal._static_attrs(m)
                for name, m in self._template.items(keep_base=True, copy_state=False)
            }
        return {"": _journal._static_attrs(self._template)}

    def _apply_static_attrs(self, statics: Dict[str, Dict[str, Any]]) -> None:
        if _funcore._is_collection(self._template):
            members = dict(self._template.items(keep_base=True, copy_state=False))
            for name, attrs in (statics or {}).items():
                node = members.get(name)
                if node is not None:
                    for key, value in (attrs or {}).items():
                        setattr(node, key, value)
        else:
            for key, value in (statics or {}).get("", {}).items():
                setattr(self._template, key, value)

    def _check_slab_layout(self, arrays: Dict[str, np.ndarray]) -> None:
        """A record whose state names/shapes/dtypes do not match the template
        config must demote like any other corrupt record — name-only matching
        would silently leave mismatched states at init values."""
        if set(arrays) != set(self._flat_proto):
            missing = sorted(n.replace(_SEP, ".") for n in set(self._flat_proto) - set(arrays))
            unknown = sorted(n.replace(_SEP, ".") for n in set(arrays) - set(self._flat_proto))
            raise ValueError(
                f"slab record layout mismatch vs the template config "
                f"(missing states: {missing or None}, unknown states: {unknown or None})"
            )
        for name, proto in self._flat_proto.items():
            arr = arrays[name]
            want = (self._slab,) + tuple(np.shape(proto))
            have_dtype = np.asarray(arr).dtype
            want_dtype = np.asarray(proto).dtype
            if tuple(arr.shape) != want or have_dtype != want_dtype:
                raise ValueError(
                    f"slab record state {name.replace(_SEP, '.')} is "
                    f"{have_dtype}{tuple(arr.shape)}, template wants {want_dtype}{want}"
                )

    def _recover_slab(
        self, base: str, gens: int
    ) -> Tuple[Optional[Tuple[Dict[str, Any], Dict[str, np.ndarray]]], int]:
        """Walk one slab's generation ring newest-first; return the first
        generation that verifies (record, demotions-counted) or ``None`` if
        every generation is torn."""
        demotions = 0
        for g in range(gens):
            gpath = _journal._gen_path(base, g)
            if not os.path.exists(gpath):
                continue
            try:
                with open(gpath, "rb") as fh:
                    data = fh.read()
                manifest, payload = _journal.decode_record(data, origin=repr(gpath))
                arrays = _journal.unpack_raw_record(manifest, payload)
                meta = manifest.get("arena") or {}
                if int(meta.get("slab_size", self._slab)) != self._slab:
                    raise ValueError(
                        f"slab record carries slab_size={meta.get('slab_size')}, "
                        f"arena uses {self._slab}"
                    )
                self._check_slab_layout(arrays)
            except Exception as exc:  # noqa: BLE001 — demote to the previous generation of THIS slab
                demotions += 1
                _counters["arena_slab_demotions"] += 1
                _faults.note_fault(
                    _faults.classify(exc, "journal"), site="journal-load", owner=self, error=exc
                )
                _faults.warn_fault(
                    self,
                    "journal",
                    f"Arena slab record {gpath!r} failed verification "
                    f"({type(exc).__name__}: {exc}); demoting to the previous good "
                    "generation of this slab (other slabs are unaffected).",
                )
                continue
            return (meta, arrays), demotions
        return None, demotions

    def restore(self, path: Optional[str] = None) -> Dict[str, Any]:
        """Rebuild the stack from the per-slab records. Each slab walks its
        generation ring newest-first: a torn, checksum-failed or
        layout-mismatched generation classifies a ``journal`` fault, counts
        an ``arena_slab_demotions`` and demotes to the previous good
        generation OF THAT SLAB — other slabs restore untouched. The newest
        good slab-0 record is AUTHORITATIVE for the arena extent (``save()``
        rewrites every slab), so stale higher-numbered slab files — left by
        a crash between a shrink's save and its prune, or by an older writer
        — never resurrect removed tenants. A slab with no good generation
        resets to init (its tenants report dead). Returns ``{slabs,
        demotions, tenants}``."""
        path = str(path) if path else self._journal_path
        if not path:
            raise ValueError("this arena was constructed without journal_path")
        if not self._fused:
            raise ValueError(
                f"arena {self._name!r} carries cat/list states; the slab byte layout "
                "needs fixed-shape array states (restore the tenants individually)"
            )
        t0 = _telemetry.now() if _telemetry.armed else 0.0
        gens = self._scan_generations()
        if not self._slab_on_disk(path, 0):
            raise _journal.JournalFault(
                f"no arena slab records found at {path!r}", site="journal-load"
            )
        recovered: Dict[int, Tuple[Dict[str, Any], Dict[str, np.ndarray]]] = {}
        rec0, demotions = self._recover_slab(self._slab_path(path, 0), gens)
        if rec0 is not None:
            recovered[0] = rec0
            slab_count = max(1, int(rec0[0].get("capacity", self._slab)) // self._slab)
        else:
            # slab 0 demoted all the way out: no authoritative extent — fall
            # back to walking the slab files upward until one is missing
            slab_count = 1
            while self._slab_on_disk(path, slab_count):
                slab_count += 1
        for k in range(1, slab_count):
            if not self._slab_on_disk(path, k):
                continue  # a missing slab resets to init (its tenants report dead)
            rec, dem = self._recover_slab(self._slab_path(path, k), gens)
            demotions += dem
            if rec is not None:
                recovered[k] = rec
        cap = slab_count * self._slab
        # rebuild the stack host-side, then land it as one device tree
        S = self._slab
        host = {
            name: np.broadcast_to(np.asarray(p), (cap,) + p.shape).copy()
            for name, p in self._flat_proto.items()
        }
        live = np.zeros(cap, dtype=bool)
        counts = np.zeros(cap, dtype=np.int64)
        cohorts = np.full(cap, None, dtype=object)
        for k, (meta, arrays) in recovered.items():
            sl = slice(k * S, (k + 1) * S)
            for name in host:
                host[name][sl] = arrays[name]  # layout validated per generation
            live[sl] = np.asarray(meta.get("live", [0] * S), dtype=bool)[: S]
            counts[sl] = np.asarray(meta.get("counts", [0] * S), dtype=np.int64)[: S]
            labels = (list(meta.get("cohorts") or []) + [None] * S)[:S]
            cohorts[sl] = np.asarray(labels, dtype=object)
            self._apply_static_attrs(meta.get("static_attrs") or {})
            _counters["arena_slab_restores"] += 1
        self._capacity = cap
        self._stacked = {name: jnp.asarray(arr) for name, arr in host.items()}
        self._live = live
        self._counts = counts
        self._cohorts = cohorts
        self._watermark = cap
        self._free = sorted(np.nonzero(~live)[0].tolist(), reverse=True)
        if t0 and _telemetry.armed:
            _telemetry.emit(
                "arena-journal", self._name, "arena", t0, _telemetry.now() - t0,
                {"op": "restore", "slabs": slab_count, "demotions": demotions},
            )
        return {"slabs": slab_count, "demotions": demotions, "tenants": self.tenants}

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (
            f"MetricArena({self._name!r}, tenants={self.tenants}, "
            f"capacity={self._capacity}, slab={self._slab}, "
            f"lane={'fused' if self._fused else 'rows'})"
        )
