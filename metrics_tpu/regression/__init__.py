from metrics_tpu.regression.advanced import (
    CosineSimilarity,
    ExplainedVariance,
    PearsonCorrCoef,
    R2Score,
    SpearmanCorrCoef,
    TweedieDevianceScore,
)
from metrics_tpu.regression.basic import (
    MeanAbsoluteError,
    MeanAbsolutePercentageError,
    MeanSquaredError,
    MeanSquaredLogError,
    SymmetricMeanAbsolutePercentageError,
    WeightedMeanAbsolutePercentageError,
)

__all__ = [
    "CosineSimilarity",
    "ExplainedVariance",
    "MeanAbsoluteError",
    "MeanAbsolutePercentageError",
    "MeanSquaredError",
    "MeanSquaredLogError",
    "PearsonCorrCoef",
    "R2Score",
    "SpearmanCorrCoef",
    "SymmetricMeanAbsolutePercentageError",
    "TweedieDevianceScore",
    "WeightedMeanAbsolutePercentageError",
]
