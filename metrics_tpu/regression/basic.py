"""Elementwise-error regression modules.

Parity: reference `regression/{mse,mae,log_mse,mape,symmetric_mape,wmape}.py`.
"""
from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp

from metrics_tpu.functional.regression.basic import (
    _mean_absolute_error_compute,
    _mean_absolute_error_update,
    _mean_absolute_percentage_error_compute,
    _mean_absolute_percentage_error_update,
    _mean_squared_error_compute,
    _mean_squared_error_update,
    _mean_squared_log_error_compute,
    _mean_squared_log_error_update,
    _symmetric_mape_update,
    _weighted_mape_compute,
    _weighted_mape_update,
)
from metrics_tpu.metric import Metric


class MeanSquaredError(Metric):
    """MSE (or RMSE with ``squared=False``).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import MeanSquaredError
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> mean_squared_error = MeanSquaredError()
        >>> mean_squared_error(preds, target)
        Array(0.375, dtype=float32)
    """

    is_differentiable = True
    higher_is_better = False
    full_state_update = False

    def __init__(self, squared: bool = True, num_outputs: int = 1, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not isinstance(num_outputs, int) or num_outputs < 1:
            raise ValueError(f"Expected num_outputs to be a positive integer but got {num_outputs}")
        self.num_outputs = num_outputs
        shape = () if num_outputs == 1 else (num_outputs,)
        self.add_state("sum_squared_error", default=jnp.zeros(shape), dist_reduce_fx="sum")
        self.add_state("total", default=jnp.asarray(0, dtype=jnp.int32), dist_reduce_fx="sum")
        self.squared = squared

    def update(self, preds, target) -> None:
        sum_squared_error, n_obs = _mean_squared_error_update(preds, target, self.num_outputs)
        self.sum_squared_error = self.sum_squared_error + sum_squared_error
        self.total = self.total + n_obs

    def compute(self) -> jax.Array:
        return _mean_squared_error_compute(self.sum_squared_error, self.total, self.squared)


class MeanAbsoluteError(Metric):
    """MAE.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import MeanAbsoluteError
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> mean_absolute_error = MeanAbsoluteError()
        >>> mean_absolute_error(preds, target)
        Array(0.5, dtype=float32)
    """

    is_differentiable = True
    higher_is_better = False
    full_state_update = False

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("sum_abs_error", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("total", default=jnp.asarray(0, dtype=jnp.int32), dist_reduce_fx="sum")

    def update(self, preds, target) -> None:
        sum_abs_error, n_obs = _mean_absolute_error_update(preds, target)
        self.sum_abs_error = self.sum_abs_error + sum_abs_error
        self.total = self.total + n_obs

    def compute(self) -> jax.Array:
        return _mean_absolute_error_compute(self.sum_abs_error, self.total)


class MeanSquaredLogError(Metric):
    """MSLE.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import MeanSquaredLogError
        >>> preds = jnp.asarray([2.5, 5.0, 4.0, 8.0])
        >>> target = jnp.asarray([3.0, 5.0, 2.5, 7.0])
        >>> mean_squared_log_error = MeanSquaredLogError()
        >>> round(float(mean_squared_log_error(preds, target)), 4)
        0.0397
    """

    is_differentiable = True
    higher_is_better = False
    full_state_update = False

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("sum_squared_log_error", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("total", default=jnp.asarray(0, dtype=jnp.int32), dist_reduce_fx="sum")

    def update(self, preds, target) -> None:
        sum_squared_log_error, n_obs = _mean_squared_log_error_update(preds, target)
        self.sum_squared_log_error = self.sum_squared_log_error + sum_squared_log_error
        self.total = self.total + n_obs

    def compute(self) -> jax.Array:
        return _mean_squared_log_error_compute(self.sum_squared_log_error, self.total)


class MeanAbsolutePercentageError(Metric):
    """MAPE.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import MeanAbsolutePercentageError
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> mape = MeanAbsolutePercentageError()
        >>> round(float(mape(preds, target)), 4)
        0.3274
    """

    is_differentiable = True
    higher_is_better = False
    full_state_update = False

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("sum_abs_per_error", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("total", default=jnp.asarray(0, dtype=jnp.int32), dist_reduce_fx="sum")

    def update(self, preds, target) -> None:
        sum_abs_per_error, n_obs = _mean_absolute_percentage_error_update(preds, target)
        self.sum_abs_per_error = self.sum_abs_per_error + sum_abs_per_error
        self.total = self.total + n_obs

    def compute(self) -> jax.Array:
        return _mean_absolute_percentage_error_compute(self.sum_abs_per_error, self.total)


class SymmetricMeanAbsolutePercentageError(Metric):
    """SMAPE.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import SymmetricMeanAbsolutePercentageError
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> smape = SymmetricMeanAbsolutePercentageError()
        >>> round(float(smape(preds, target)), 4)
        0.5788
    """

    is_differentiable = True
    higher_is_better = False
    full_state_update = False

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("sum_abs_per_error", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("total", default=jnp.asarray(0, dtype=jnp.int32), dist_reduce_fx="sum")

    def update(self, preds, target) -> None:
        sum_abs_per_error, n_obs = _symmetric_mape_update(preds, target)
        self.sum_abs_per_error = self.sum_abs_per_error + sum_abs_per_error
        self.total = self.total + n_obs

    def compute(self) -> jax.Array:
        return self.sum_abs_per_error / self.total


class WeightedMeanAbsolutePercentageError(Metric):
    """WMAPE.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import WeightedMeanAbsolutePercentageError
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> wmape = WeightedMeanAbsolutePercentageError()
        >>> round(float(wmape(preds, target)), 4)
        0.16
    """

    is_differentiable = True
    higher_is_better = False
    full_state_update = False

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("sum_abs_error", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("sum_scale", default=jnp.asarray(0.0), dist_reduce_fx="sum")

    def update(self, preds, target) -> None:
        sum_abs_error, sum_scale = _weighted_mape_update(preds, target)
        self.sum_abs_error = self.sum_abs_error + sum_abs_error
        self.sum_scale = self.sum_scale + sum_scale

    def compute(self) -> jax.Array:
        return _weighted_mape_compute(self.sum_abs_error, self.sum_scale)


__all__ = [
    "MeanSquaredError",
    "MeanAbsoluteError",
    "MeanSquaredLogError",
    "MeanAbsolutePercentageError",
    "SymmetricMeanAbsolutePercentageError",
    "WeightedMeanAbsolutePercentageError",
]
