"""Moment/correlation regression modules.

Parity: reference `regression/{explained_variance,r2,pearson,spearman,
cosine_similarity,tweedie_deviance}.py`. ``PearsonCorrCoef`` declares its moment
states with ``dist_reduce_fx=None`` so cross-device sync stacks per-device stats
for the pairwise parallel merge (reference `regression/pearson.py:109-114`).
"""
from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from metrics_tpu.functional.regression.correlation import (
    _cosine_similarity_compute,
    _pearson_corrcoef_compute,
    _pearson_corrcoef_update,
    _pearson_final_aggregation,
    _spearman_corrcoef_compute,
)
from metrics_tpu.utils.checks import _check_same_shape
from metrics_tpu.utils.data import dim_zero_cat_ravel
from metrics_tpu.functional.regression.moments import (
    _explained_variance_compute,
    _explained_variance_update,
    _r2_score_compute,
    _r2_score_update,
    _tweedie_deviance_score_compute,
    _tweedie_deviance_score_update,
)
from metrics_tpu.metric import Metric
from metrics_tpu.utils.data import dim_zero_cat


class CosineSimilarity(Metric):
    """Accumulated row-wise cosine similarity.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import CosineSimilarity
        >>> preds = jnp.asarray([[2.0, 0.0], [1.0, 1.0]])
        >>> target = jnp.asarray([[1.0, 0.0], [1.0, 0.0]])
        >>> cosine_similarity = CosineSimilarity(reduction='mean')
        >>> round(float(cosine_similarity(preds, target)), 4)
        0.8536
    """

    is_differentiable = True
    higher_is_better = True
    full_state_update = True

    def __init__(self, reduction: str = "sum", **kwargs: Any) -> None:
        super().__init__(**kwargs)
        allowed_reduction = ("sum", "mean", "none", None)
        if reduction not in allowed_reduction:
            raise ValueError(f"Expected argument `reduction` to be one of {allowed_reduction} but got {reduction}")
        self.reduction = reduction
        self.add_state("preds", [], dist_reduce_fx="cat")
        self.add_state("target", [], dist_reduce_fx="cat")

    def update(self, preds, target) -> None:
        # raw-row buffering: the float32 cast is deferred to observation time
        # (see `Metric._canonicalize_list_states`) — update is two appends
        _check_same_shape(preds, target)
        self.preds.append(preds)
        self.target.append(target)

    def _canonicalize_list_states(self) -> None:
        if not isinstance(self.preds, list):
            return  # post-sync "cat" reduction left one bare canonical array
        for i in range(len(self.preds)):
            self.preds[i] = self.preds[i].astype(np.float32)
            self.target[i] = self.target[i].astype(np.float32)

    def compute(self) -> jax.Array:
        preds = dim_zero_cat(self.preds).astype(jnp.float32)
        target = dim_zero_cat(self.target).astype(jnp.float32)
        return _cosine_similarity_compute(preds, target, self.reduction)


class ExplainedVariance(Metric):
    """Streaming explained variance.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import ExplainedVariance
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> explained_variance = ExplainedVariance()
        >>> round(float(explained_variance(preds, target)), 4)
        0.9572
    """

    is_differentiable = True
    higher_is_better = True
    full_state_update = False

    def __init__(self, multioutput: str = "uniform_average", **kwargs: Any) -> None:
        super().__init__(**kwargs)
        allowed_multioutput = ("raw_values", "uniform_average", "variance_weighted")
        if multioutput not in allowed_multioutput:
            raise ValueError(f"Invalid input to argument `multioutput`. Choose one of the following: {allowed_multioutput}")
        self.multioutput = multioutput
        self.add_state("sum_error", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("sum_squared_error", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("sum_target", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("sum_squared_target", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("n_obs", default=jnp.asarray(0.0), dist_reduce_fx="sum")

    def update(self, preds, target) -> None:
        n_obs, sum_error, sum_squared_error, sum_target, sum_squared_target = _explained_variance_update(preds, target)
        self.n_obs = self.n_obs + n_obs
        self.sum_error = self.sum_error + sum_error
        self.sum_squared_error = self.sum_squared_error + sum_squared_error
        self.sum_target = self.sum_target + sum_target
        self.sum_squared_target = self.sum_squared_target + sum_squared_target

    def compute(self) -> jax.Array:
        return _explained_variance_compute(
            self.n_obs,
            self.sum_error,
            self.sum_squared_error,
            self.sum_target,
            self.sum_squared_target,
            self.multioutput,
        )


class R2Score(Metric):
    """Streaming R² (optionally adjusted, multioutput).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import R2Score
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> r2score = R2Score()
        >>> round(float(r2score(preds, target)), 4)
        0.9486
    """

    is_differentiable = True
    higher_is_better = True
    full_state_update = False

    def __init__(
        self,
        num_outputs: int = 1,
        adjusted: int = 0,
        multioutput: str = "uniform_average",
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.num_outputs = num_outputs
        if adjusted < 0 or not isinstance(adjusted, int):
            raise ValueError("`adjusted` parameter should be an integer larger or equal to 0.")
        self.adjusted = adjusted
        allowed_multioutput = ("raw_values", "uniform_average", "variance_weighted")
        if multioutput not in allowed_multioutput:
            raise ValueError(
                f"Invalid input to argument `multioutput`. Choose one of the following: {allowed_multioutput}"
            )
        self.multioutput = multioutput

        shape = () if num_outputs == 1 else (num_outputs,)
        self.add_state("sum_squared_error", default=jnp.zeros(shape), dist_reduce_fx="sum")
        self.add_state("sum_error", default=jnp.zeros(shape), dist_reduce_fx="sum")
        self.add_state("residual", default=jnp.zeros(shape), dist_reduce_fx="sum")
        self.add_state("total", default=jnp.asarray(0, dtype=jnp.int32), dist_reduce_fx="sum")

    def update(self, preds, target) -> None:
        sum_squared_obs, sum_obs, rss, n_obs = _r2_score_update(preds, target)
        self.sum_squared_error = self.sum_squared_error + sum_squared_obs
        self.sum_error = self.sum_error + sum_obs
        self.residual = self.residual + rss
        self.total = self.total + n_obs

    def compute(self) -> jax.Array:
        return _r2_score_compute(
            self.sum_squared_error, self.sum_error, self.residual, self.total, self.adjusted, self.multioutput
        )


class PearsonCorrCoef(Metric):
    """Streaming Pearson correlation with cross-device parallel merge.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import PearsonCorrCoef
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> pearson = PearsonCorrCoef()
        >>> round(float(pearson(preds, target)), 4)
        0.9849
    """

    is_differentiable = True
    higher_is_better = None
    full_state_update = True

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        # dist_reduce_fx=None: sync stacks per-device stats; compute merges them
        self.add_state("mean_x", default=jnp.asarray(0.0), dist_reduce_fx=None)
        self.add_state("mean_y", default=jnp.asarray(0.0), dist_reduce_fx=None)
        self.add_state("var_x", default=jnp.asarray(0.0), dist_reduce_fx=None)
        self.add_state("var_y", default=jnp.asarray(0.0), dist_reduce_fx=None)
        self.add_state("corr_xy", default=jnp.asarray(0.0), dist_reduce_fx=None)
        self.add_state("n_total", default=jnp.asarray(0.0), dist_reduce_fx=None)

    def update(self, preds, target) -> None:
        self.mean_x, self.mean_y, self.var_x, self.var_y, self.corr_xy, self.n_total = _pearson_corrcoef_update(
            preds, target, self.mean_x, self.mean_y, self.var_x, self.var_y, self.corr_xy, self.n_total
        )

    def compute(self) -> jax.Array:
        if isinstance(self.var_x, jax.Array) and self.var_x.ndim > 0 and self.var_x.shape[0] > 1:
            # synced: stacked per-device stats -> pairwise merge
            var_x, var_y, corr_xy, n_total = _pearson_final_aggregation(
                self.mean_x, self.mean_y, self.var_x, self.var_y, self.corr_xy, self.n_total
            )
        else:
            var_x, var_y, corr_xy, n_total = self.var_x, self.var_y, self.corr_xy, self.n_total
        return _pearson_corrcoef_compute(var_x, var_y, corr_xy, n_total)


class SpearmanCorrCoef(Metric):
    """Spearman rank correlation over all accumulated samples.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import SpearmanCorrCoef
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> spearman = SpearmanCorrCoef()
        >>> round(float(spearman(preds, target)), 4)
        1.0
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = False

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("preds", [], dist_reduce_fx="cat")
        self.add_state("target", [], dist_reduce_fx="cat")

    def update(self, preds, target) -> None:
        # raw-row buffering: dtype/shape checks are metadata-only; the squeeze
        # is validated here from shapes and applied at observation time
        if preds.dtype != target.dtype:
            raise TypeError(
                "Expected `preds` and `target` to have the same data type."
                f" Got preds: {preds.dtype} and target: {target.dtype}."
            )
        _check_same_shape(preds, target)
        squeezed = tuple(d for d in preds.shape if d != 1)
        if len(squeezed) > 1:
            raise ValueError("Expected both predictions and target to be 1 dimensional tensors.")
        self.preds.append(preds)
        self.target.append(target)

    def _canonicalize_list_states(self) -> None:
        if not isinstance(self.preds, list):
            return  # post-sync "cat" reduction left one bare canonical array
        for i in range(len(self.preds)):
            self.preds[i] = self.preds[i].reshape(-1)
            self.target[i] = self.target[i].reshape(-1)

    def compute(self) -> jax.Array:
        return _spearman_corrcoef_compute(
            dim_zero_cat_ravel(self.preds), dim_zero_cat_ravel(self.target)
        )


class TweedieDevianceScore(Metric):
    """Mean Tweedie deviance with parameterized power.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import TweedieDevianceScore
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> target = jnp.asarray([3.0, 0.5, 2.0, 7.0])
        >>> deviance_score = TweedieDevianceScore(power=0)
        >>> round(float(deviance_score(preds, target)), 4)
        0.375
    """

    is_differentiable = True
    higher_is_better = False
    full_state_update = False

    def __init__(self, power: float = 0.0, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if 0 < power < 1:
            raise ValueError(f"Deviance Score is not defined for power={power}.")
        self.power = power
        self.add_state("sum_deviance_score", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("num_observations", default=jnp.asarray(0, dtype=jnp.int32), dist_reduce_fx="sum")

    def update(self, preds, targets) -> None:
        sum_deviance_score, num_observations = _tweedie_deviance_score_update(preds, targets, self.power)
        self.sum_deviance_score = self.sum_deviance_score + sum_deviance_score
        self.num_observations = self.num_observations + num_observations

    def compute(self) -> jax.Array:
        return _tweedie_deviance_score_compute(self.sum_deviance_score, self.num_observations)


__all__ = [
    "CosineSimilarity",
    "ExplainedVariance",
    "R2Score",
    "PearsonCorrCoef",
    "SpearmanCorrCoef",
    "TweedieDevianceScore",
]
