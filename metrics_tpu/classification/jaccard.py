"""JaccardIndex module metric (reference `classification/jaccard.py`)."""
from __future__ import annotations

from typing import Any, Optional

import jax

from metrics_tpu.classification.confusion_matrix import ConfusionMatrix
from metrics_tpu.functional.classification.jaccard import _jaccard_from_confmat


class JaccardIndex(ConfusionMatrix):
    """Jaccard index (IoU) from an accumulated confusion matrix.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import JaccardIndex
        >>> target = jnp.asarray([[0, 1], [1, 1]])
        >>> preds = jnp.asarray([[0, 1], [0, 1]])
        >>> jaccard = JaccardIndex(num_classes=2)
        >>> round(float(jaccard(preds, target)), 4)
        0.5833
    """

    is_differentiable: Optional[bool] = False
    higher_is_better: Optional[bool] = True
    full_state_update: Optional[bool] = False

    def __init__(
        self,
        num_classes: int,
        average: Optional[str] = "macro",
        ignore_index: Optional[int] = None,
        absent_score: float = 0.0,
        threshold: float = 0.5,
        multilabel: bool = False,
        **kwargs: Any,
    ) -> None:
        super().__init__(
            num_classes=num_classes,
            normalize=None,
            threshold=threshold,
            multilabel=multilabel,
            **kwargs,
        )
        self.average = average
        self.ignore_index = ignore_index
        self.absent_score = absent_score

    def compute(self) -> jax.Array:
        return _jaccard_from_confmat(
            self.confmat, self.num_classes, self.average, self.ignore_index, self.absent_score
        )


__all__ = ["JaccardIndex"]
