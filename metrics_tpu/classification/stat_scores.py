"""StatScores module metric.

Parity: reference `classification/stat_scores.py:155-260` — tensor+"sum" states
for micro/macro reduces, list+"cat" states for samplewise reduces.
"""
from __future__ import annotations

from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp

from metrics_tpu.functional.classification.stat_scores import (
    _stat_scores_compute,
    _stat_scores_update,
)
from metrics_tpu.metric import Metric
from metrics_tpu.utils.enums import AverageMethod, MDMCAverageMethod


class StatScores(Metric):
    """Accumulates tp/fp/tn/fn; ``compute`` returns ``[..., 5]`` with support.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import StatScores
        >>> preds = jnp.asarray([1, 0, 2, 1])
        >>> target = jnp.asarray([1, 1, 2, 0])
        >>> stat_scores = StatScores(reduce='micro')
        >>> stat_scores(preds, target)
        Array([2, 2, 6, 2, 4], dtype=int32)
    """

    is_differentiable: Optional[bool] = False
    higher_is_better: Optional[bool] = None
    full_state_update: Optional[bool] = False

    def __init__(
        self,
        threshold: float = 0.5,
        top_k: Optional[int] = None,
        reduce: str = "micro",
        num_classes: Optional[int] = None,
        ignore_index: Optional[int] = None,
        mdmc_reduce: Optional[str] = None,
        multiclass: Optional[bool] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)

        self.reduce = reduce
        self.mdmc_reduce = mdmc_reduce
        self.num_classes = num_classes
        self.threshold = threshold
        self.multiclass = multiclass
        self.ignore_index = ignore_index
        self.top_k = top_k

        if reduce not in ("micro", "macro", "samples"):
            raise ValueError(f"The `reduce` {reduce} is not valid.")
        if mdmc_reduce not in (None, "samplewise", "global"):
            raise ValueError(f"The `mdmc_reduce` {mdmc_reduce} is not valid.")
        if reduce == "macro" and (not num_classes or num_classes < 1):
            raise ValueError("When you set `reduce` as 'macro', you have to provide the number of classes.")
        if num_classes and ignore_index is not None and (not ignore_index < num_classes or num_classes == 1):
            raise ValueError(f"The `ignore_index` {ignore_index} is not valid for inputs with {num_classes} classes")

        if mdmc_reduce != "samplewise" and reduce != "samples":
            shape = () if reduce == "micro" else (num_classes,)
            for s in ("tp", "fp", "tn", "fn"):
                self.add_state(s, default=jnp.zeros(shape, dtype=jnp.int32), dist_reduce_fx="sum")
        else:
            for s in ("tp", "fp", "tn", "fn"):
                self.add_state(s, default=[], dist_reduce_fx="cat")

    def update(self, preds, target) -> None:
        tp, fp, tn, fn = _stat_scores_update(
            preds,
            target,
            reduce=self.reduce,
            mdmc_reduce=self.mdmc_reduce,
            threshold=self.threshold,
            num_classes=self.num_classes,
            top_k=self.top_k,
            multiclass=self.multiclass,
            ignore_index=self.ignore_index,
        )
        if self.reduce != AverageMethod.SAMPLES and self.mdmc_reduce != MDMCAverageMethod.SAMPLEWISE:
            self.tp = self.tp + tp
            self.fp = self.fp + fp
            self.tn = self.tn + tn
            self.fn = self.fn + fn
        else:
            self.tp.append(tp)
            self.fp.append(fp)
            self.tn.append(tn)
            self.fn.append(fn)

    def _get_final_stats(self) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
        out = []
        for s in (self.tp, self.fp, self.tn, self.fn):
            out.append(jnp.concatenate([jnp.atleast_1d(v) for v in s]) if isinstance(s, list) else s)
        return tuple(out)

    def compute(self) -> jax.Array:
        tp, fp, tn, fn = self._get_final_stats()
        return _stat_scores_compute(tp, fp, tn, fn)


__all__ = ["StatScores"]
