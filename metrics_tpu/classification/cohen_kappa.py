"""CohenKappa module metric (reference `classification/cohen_kappa.py`)."""
from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp

from metrics_tpu.functional.classification.cohen_kappa import _cohen_kappa_compute, _cohen_kappa_update
from metrics_tpu.metric import Metric


class CohenKappa(Metric):
    """Cohen's kappa from an accumulated confusion matrix.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import CohenKappa
        >>> target = jnp.asarray([1, 1, 0, 0])
        >>> preds = jnp.asarray([0, 1, 0, 0])
        >>> cohenkappa = CohenKappa(num_classes=2)
        >>> cohenkappa(preds, target)
        Array(0.5, dtype=float32)
    """

    is_differentiable: Optional[bool] = False
    higher_is_better: Optional[bool] = True
    full_state_update: Optional[bool] = False

    def __init__(
        self,
        num_classes: int,
        weights: Optional[str] = None,
        threshold: float = 0.5,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.num_classes = num_classes
        self.weights = weights
        self.threshold = threshold

        allowed_weights = ("linear", "quadratic", "none", None)
        if self.weights not in allowed_weights:
            raise ValueError(f"Argument weights needs to one of the following: {allowed_weights}")

        self.add_state("confmat", default=jnp.zeros((num_classes, num_classes), dtype=jnp.int32), dist_reduce_fx="sum")

    def update(self, preds, target) -> None:
        confmat = _cohen_kappa_update(preds, target, self.num_classes, self.threshold)
        self.confmat = self.confmat + confmat

    def compute(self) -> jax.Array:
        return _cohen_kappa_compute(self.confmat, self.weights)


__all__ = ["CohenKappa"]
