"""AUC module metric (reference `classification/auc.py`)."""
from __future__ import annotations

from typing import Any, Optional

import jax

from metrics_tpu.functional.classification.auc import _auc_compute, _auc_update
from metrics_tpu.metric import Metric
from metrics_tpu.utils.data import dim_zero_cat


class AUC(Metric):
    """Area under any accumulated (x, y) curve.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import AUC
        >>> auc = AUC(reorder=True)
        >>> auc.update(jnp.asarray([0.0, 1.0, 2.0, 3.0]), jnp.asarray([0.0, 1.0, 2.0, 2.0]))
        >>> auc.compute()
        Array(4., dtype=float32)
    """

    is_differentiable: Optional[bool] = False
    higher_is_better: Optional[bool] = None
    full_state_update: Optional[bool] = False

    def __init__(self, reorder: bool = False, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.reorder = reorder
        self.add_state("x", default=[], dist_reduce_fx="cat")
        self.add_state("y", default=[], dist_reduce_fx="cat")

    def update(self, preds, target) -> None:
        x, y = _auc_update(preds, target)
        self.x.append(x)
        self.y.append(y)

    def compute(self) -> jax.Array:
        import jax.numpy as jnp

        x = dim_zero_cat(self.x).astype(jnp.float32)
        y = dim_zero_cat(self.y).astype(jnp.float32)
        if self.reorder:
            order = jnp.argsort(x, stable=True)
            x, y = x[order], y[order]
        return _auc_compute(x, y)


__all__ = ["AUC"]
