"""CalibrationError module metric (reference `classification/calibration_error.py`).

TPU-first redesign: the reference accumulates RAW ``confidences``/
``accuracies`` lists (`calibration_error.py:77-80` adds them to cat states) —
O(N) memory, unbounded shapes, an all_gather to sync. But every supported norm
(l1/l2/max) is a function of the PER-BIN sums only, and the bin boundaries are
a fixed uniform grid, so per-element bucketization commutes with batching:
three ``(n_bins,)`` sum states carry the identical information with O(1)
memory, a single ``psum`` to sync, and a fully jittable fixed-shape update
(the cat formulation can never fuse — its pytree grows every step).
"""
from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from metrics_tpu.functional.classification.calibration_error import (
    _bin_sums,
    _ce_from_bin_sums,
    _ce_update,
)
from metrics_tpu.metric import Metric


class CalibrationError(Metric):
    """Expected/max/RMS calibration error over accumulated per-bin statistics.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import CalibrationError
        >>> preds = jnp.asarray([0.25, 0.35, 0.8, 0.9])
        >>> target = jnp.asarray([0, 0, 1, 1])
        >>> metric = CalibrationError(n_bins=3, norm='l1')
        >>> round(float(metric(preds, target)), 4)
        0.225
    """

    is_differentiable: Optional[bool] = False
    higher_is_better: Optional[bool] = False
    full_state_update: Optional[bool] = False
    DISTANCES = {"l1", "l2", "max"}

    def __init__(self, n_bins: int = 15, norm: str = "l1", **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if norm not in self.DISTANCES:
            raise ValueError(f"Norm {norm} is not supported. Please select from l1, l2, or max. ")
        if not isinstance(n_bins, int) or n_bins <= 0:
            raise ValueError(f"Expected argument `n_bins` to be a int larger than 0 but got {n_bins}")
        self.n_bins = n_bins
        self.norm = norm
        # host-resident (numpy): a static trace constant — a device array here
        # would force a D2H fetch at every jit trace that closes over it
        # (docs/performance.md "The D2H sync cliff")
        self.bin_boundaries = np.linspace(0, 1, n_bins + 1, dtype=np.float32)
        # counts AND accuracy sums are int32 — both integer-valued, so they
        # accumulate exactly to 2^31 samples per bin (a float32 running sum
        # stops incrementing at 2^24). conf_bin is a float32 sum of values in
        # [0, 1]: once a bin's sum passes ~2^24 its per-sample additions lose
        # low bits, bounding the per-bin mean-confidence error at roughly
        # n_updates · ulp(sum) / count — negligible below tens of millions of
        # samples per bin, documented rather than hidden.
        self.add_state("count_bin", jnp.zeros(n_bins, dtype=jnp.int32), dist_reduce_fx="sum")
        self.add_state("conf_bin", jnp.zeros(n_bins, dtype=jnp.float32), dist_reduce_fx="sum")
        self.add_state("acc_bin", jnp.zeros(n_bins, dtype=jnp.int32), dist_reduce_fx="sum")

    def update(self, preds, target) -> None:
        confidences, accuracies = _ce_update(preds, target)
        count, conf, acc = _bin_sums(confidences, accuracies, self.bin_boundaries)
        self.count_bin = self.count_bin + count
        self.conf_bin = self.conf_bin + conf
        # accuracies are exact 0/1 floats; the per-batch sum is integer-valued
        # and well under 2^24, so the int32 cast is exact
        self.acc_bin = self.acc_bin + acc.astype(jnp.int32)

    def compute(self) -> jax.Array:
        # parity with the cat-state formulation (and the reference), which
        # raised from concatenating an empty list — a silent all-NaN would
        # hide the misuse. The python-level count check keeps the common
        # module path free of device reads; the state-sum check covers
        # `as_functions` exports (whose bare clone has no update count) and
        # only runs when the python count says "never updated". Under jit the
        # values are unknowable: the traced result is NaN, as for any 0/0.
        if self._update_count == 0 and not isinstance(self.count_bin, jax.core.Tracer):
            if int(jnp.sum(self.count_bin)) == 0:
                raise ValueError("No samples to compute calibration error over; call `update` first")
        return _ce_from_bin_sums(self.count_bin, self.conf_bin, self.acc_bin, self.norm)


__all__ = ["CalibrationError"]
