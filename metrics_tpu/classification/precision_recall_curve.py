"""PrecisionRecallCurve module metric (exact, cat-states).

Parity: reference `classification/precision_recall_curve.py` — raw preds/target
accumulated as list states (``dist_reduce_fx="cat"``), exact curve at compute.
"""
from __future__ import annotations

from typing import Any, List, Optional, Tuple, Union

import jax

from metrics_tpu.classification._raw_state import _RawPairStateMixin
from metrics_tpu.functional.classification.precision_recall_curve import (
    _precision_recall_curve_compute,
    _precision_recall_curve_update,
)
from metrics_tpu.metric import Metric


class PrecisionRecallCurve(_RawPairStateMixin, Metric):
    """Exact PR curve from all accumulated scores (epoch-end, eager).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import PrecisionRecallCurve
        >>> preds = jnp.asarray([0.0, 1.0, 2.0, 3.0])
        >>> target = jnp.asarray([0, 1, 1, 0])
        >>> pr_curve = PrecisionRecallCurve(pos_label=1)
        >>> precision, recall, thresholds = pr_curve(preds, target)
        >>> precision
        Array([0.6666667, 0.5      , 0.       , 1.       ], dtype=float32)
        >>> recall
        Array([1. , 0.5, 0. , 0. ], dtype=float32)
        >>> thresholds
        Array([1., 2., 3.], dtype=float32)
    """

    is_differentiable: Optional[bool] = False
    higher_is_better: Optional[bool] = None
    full_state_update: Optional[bool] = False

    def __init__(
        self,
        num_classes: Optional[int] = None,
        pos_label: Optional[int] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.num_classes = num_classes
        self.pos_label = pos_label
        self.add_state("preds", default=[], dist_reduce_fx="cat")
        self.add_state("target", default=[], dist_reduce_fx="cat")

    def update(self, preds, target) -> None:
        # raw-row buffering: metadata-only validation here, layout transform
        # deferred to observation time (see `_raw_state.py`)
        preds, target, num_classes, pos_label = _precision_recall_curve_update(
            preds, target, self.num_classes, self.pos_label, format_tensors=False
        )
        self.preds.append(preds)
        self.target.append(target)
        self.num_classes = num_classes
        self.pos_label = pos_label

    def _format_row(self, preds, target):
        p, t, _, _ = _precision_recall_curve_update(
            preds, target, self.num_classes, self.pos_label, warn=False
        )
        return p, t

    def compute(self) -> Union[Tuple[jax.Array, ...], Tuple[List[jax.Array], ...]]:
        preds, target = self._cat_raw()
        preds, target, num_classes, pos_label = _precision_recall_curve_update(
            preds, target, self.num_classes, self.pos_label, warn=False
        )
        return _precision_recall_curve_compute(preds, target, num_classes, pos_label)


__all__ = ["PrecisionRecallCurve"]
