"""AUROC module metric (reference `classification/auroc.py`)."""
from __future__ import annotations

from typing import Any, Optional

import jax

from metrics_tpu.classification._raw_state import _RawPairStateMixin
from metrics_tpu.functional.classification.auroc import _auroc_compute, _auroc_format, _auroc_update
from metrics_tpu.metric import Metric
from metrics_tpu.utils.enums import AverageMethod


class AUROC(_RawPairStateMixin, Metric):
    """Area under the ROC curve from accumulated scores.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import AUROC
        >>> preds = jnp.asarray([0.13, 0.26, 0.08, 0.19, 0.34])
        >>> target = jnp.asarray([0, 0, 1, 1, 1])
        >>> auroc = AUROC(pos_label=1)
        >>> auroc(preds, target)
        Array(0.5, dtype=float32)
    """

    is_differentiable: Optional[bool] = False
    higher_is_better: Optional[bool] = True
    full_state_update: Optional[bool] = False

    def __init__(
        self,
        num_classes: Optional[int] = None,
        pos_label: Optional[int] = None,
        average: Optional[str] = "macro",
        max_fpr: Optional[float] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.num_classes = num_classes
        self.pos_label = pos_label
        self.average = average
        self.max_fpr = max_fpr

        allowed_average = (None, AverageMethod.MACRO, AverageMethod.WEIGHTED, AverageMethod.MICRO, AverageMethod.NONE)
        if self.average not in allowed_average:
            raise ValueError(
                f"Argument `average` expected to be one of the following: {allowed_average} but got {average}"
            )
        if self.max_fpr is not None and (not isinstance(max_fpr, float) or not 0 < max_fpr <= 1):
            raise ValueError(f"`max_fpr` should be a float in range (0, 1], got: {max_fpr}")

        self.mode = None
        self.add_state("preds", default=[], dist_reduce_fx="cat")
        self.add_state("target", default=[], dist_reduce_fx="cat")

    def update(self, preds, target) -> None:
        # raw-row buffering: mode resolution + validation here, layout
        # transform deferred to observation time (see `_raw_state.py`)
        preds, target, mode = _auroc_update(preds, target, format_tensors=False)
        self.preds.append(preds)
        self.target.append(target)
        if self.mode and self.mode != mode:
            raise ValueError(
                "The mode of data (binary, multi-label, multi-class) should be constant, but changed"
                f" between batches from {self.mode} to {mode}"
            )
        self.mode = mode

    def _format_row(self, preds, target):
        # rows were validated at update; apply only the mode-resolved layout
        # transform (no per-row value-check reads at sync/checkpoint time)
        if self.mode is None:
            p, t, _ = _auroc_update(preds, target)
            return p, t
        return _auroc_format(preds, target, self.mode)

    def compute(self) -> jax.Array:
        # preds may be a list of per-batch arrays OR a bare array (post-sync
        # cat states are reduced to one array) — guard emptiness explicitly
        have_data = (
            len(self.preds) > 0 if isinstance(self.preds, (list, tuple)) else self.preds.size > 0
        )
        if not self.mode and not have_data:
            raise RuntimeError("You have to have determined mode.")
        preds, target = self._cat_raw()
        mode = self.mode
        if mode is None:
            # state restored in a fresh process: re-derive the mode (and
            # format) from the stored canonical arrays
            preds, target, mode = _auroc_update(preds, target)
        else:
            preds, target = _auroc_format(preds, target, mode)
        return _auroc_compute(
            preds, target, mode, self.num_classes, self.pos_label, self.average, self.max_fpr
        )


__all__ = ["AUROC"]
