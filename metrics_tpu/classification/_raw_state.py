"""Raw-row buffering shared by the exact-curve module family.

The exact curve metrics (PrecisionRecallCurve / ROC / AUROC /
AveragePrecision — reference `classification/precision_recall_curve.py`,
`roc.py`, `auroc.py`, `avg_precision.py`) accumulate every score as
list ("cat") states. The reference canonicalizes per update; through a
remote TPU backend those per-step reshape/cast dispatches cost hundreds of
µs each (docs/performance.md), so here ``update`` appends the RAW inputs —
a ~1 µs list append — after metadata-only validation, and the layout
transform runs at observation time:

- ``compute``: one concat per state, then ONE formatting program over the
  concatenated array (the transform commutes with batch concatenation —
  pinned by ``tests/bases/test_raw_state_deferral.py``);
- sync / ``state_dict`` / pickling: per-row via
  :meth:`Metric._canonicalize_list_states` (rows must share rank for the
  pad-to-max gather protocol, and checkpoints keep the canonical layout).

Rows of heterogeneous trailing shape (a multidim extra dim that varies
across batches) cannot concat raw; those fall back to per-row
canonicalization first.
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import numpy as np

from metrics_tpu.utils.data import dim_zero_cat


class _RawPairStateMixin:
    """Deferred canonicalization for metrics buffering raw (preds, target) rows.

    Subclasses define ``_format_row(preds, target) -> (preds, target)``, the
    idempotent canonical per-row transform.
    """

    def _format_row(self, preds, target) -> Tuple[jax.Array, jax.Array]:
        raise NotImplementedError

    def _build_update_lane(self, args: tuple, kwargs: dict) -> Optional[callable]:
        """Dispatch-engine host fast lane: after one eager-validated update
        per signature, a same-signature update is two raw list appends plus
        one guard branch — the mode/shape validation is a pure function of
        the signature, already licensed by the eager pass, and inferred
        attrs (``mode``/``num_classes``/``pos_label``) were set by it."""
        if kwargs or len(args) != 2:
            return None
        specs = []
        for v in args:
            if isinstance(v, jax.core.Tracer) or not isinstance(v, (jax.Array, np.ndarray)):
                return None
            specs.append((type(v), v.shape, v.dtype))
        (cp, sp, dp), (ct, st, dt) = specs
        guard = self._lane_guard()

        def lane(largs: tuple, lkwargs: dict) -> bool:
            if lkwargs or len(largs) != 2:
                return False
            p, t = largs
            if (
                type(p) is not cp
                or p.shape != sp
                or p.dtype != dp
                or type(t) is not ct
                or t.shape != st
                or t.dtype != dt
            ):
                return False
            if not guard():
                return False
            self._update_count += 1
            self._computed = None
            self.preds.append(p)
            self.target.append(t)
            return True

        return lane

    def _canonicalize_list_states(self) -> None:
        if not isinstance(self.preds, list):
            # post-sync: the "cat" reduction already concatenated the rows
            # into one bare (canonical) array — nothing to canonicalize
            return
        for i in range(len(self.preds)):
            self.preds[i], self.target[i] = self._format_row(self.preds[i], self.target[i])

    def _cat_raw(self) -> Tuple[jax.Array, jax.Array]:
        """Concatenate buffered rows, canonicalizing per row only if shapes force it."""
        if not isinstance(self.preds, list):
            return self.preds, self.target
        if (
            len({tuple(p.shape[1:]) for p in self.preds}) > 1
            or len({tuple(t.shape[1:]) for t in self.target}) > 1
        ):
            self._canonicalize_list_states()
        return dim_zero_cat(self.preds), dim_zero_cat(self.target)


__all__ = ["_RawPairStateMixin"]
