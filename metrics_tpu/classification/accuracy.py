"""Accuracy module metric.

Parity: reference `classification/accuracy.py:31-260` (stat-score states plus
``correct``/``total`` sum states for subset-accuracy mode).
"""
from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp

from metrics_tpu.classification.stat_scores import StatScores
from metrics_tpu.functional.classification.accuracy import (
    _accuracy_compute,
    _check_subset_validity,
    _mode,
    _subset_accuracy_compute,
    _subset_accuracy_update,
)
from metrics_tpu.utils.enums import DataType


class Accuracy(StatScores):
    """Accuracy (micro/macro/weighted/samplewise, top-k, subset mode).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import Accuracy
        >>> target = jnp.asarray([0, 1, 2, 3])
        >>> preds = jnp.asarray([0, 2, 1, 3])
        >>> accuracy = Accuracy()
        >>> accuracy(preds, target)
        Array(0.5, dtype=float32)
    """

    is_differentiable: Optional[bool] = False
    higher_is_better: Optional[bool] = True
    full_state_update: Optional[bool] = False

    def __init__(
        self,
        threshold: float = 0.5,
        num_classes: Optional[int] = None,
        average: Optional[str] = "micro",
        # the CLASS defaults to None (reference `classification/accuracy.py:168`)
        # while the FUNCTIONAL accuracy defaults to "global"
        # (`functional/classification/accuracy.py:262`) — a reference asymmetry
        # the full-grid enumeration pinned
        mdmc_average: Optional[str] = None,
        ignore_index: Optional[int] = None,
        top_k: Optional[int] = None,
        multiclass: Optional[bool] = None,
        subset_accuracy: bool = False,
        **kwargs: Any,
    ) -> None:
        allowed_average = ("micro", "macro", "weighted", "samples", "none", None)
        if average not in allowed_average:
            raise ValueError(f"The `average` has to be one of {allowed_average}, got {average}.")

        super().__init__(
            reduce="macro" if average in ("weighted", "none", None) else average,
            mdmc_reduce=mdmc_average,
            threshold=threshold,
            top_k=top_k,
            num_classes=num_classes,
            multiclass=multiclass,
            ignore_index=ignore_index,
            **kwargs,
        )
        self.average = average
        self.add_state("correct", default=jnp.asarray(0, dtype=jnp.int32), dist_reduce_fx="sum")
        self.add_state("total", default=jnp.asarray(0, dtype=jnp.int32), dist_reduce_fx="sum")
        self.subset_accuracy = subset_accuracy
        self.mode: Optional[DataType] = None

    def update(self, preds, target) -> None:
        mode = _mode(preds, target, self.threshold, self.top_k, self.num_classes, self.multiclass, self.ignore_index)
        if not self.mode:
            self.mode = mode
        elif self.mode != mode:
            raise ValueError(f"You can not use {mode} inputs with {self.mode} inputs.")

        if self.subset_accuracy and _check_subset_validity(self.mode):
            correct, total = _subset_accuracy_update(
                preds, target, threshold=self.threshold, top_k=self.top_k, ignore_index=self.ignore_index
            )
            self.correct = self.correct + correct
            self.total = self.total + total
        else:
            # reference parity (`functional/classification/accuracy.py:104-105`):
            # accuracy deliberately rejects top_k on multilabel inputs (the
            # subset path above raises the same error inside
            # `_subset_accuracy_update`, matching the reference's `:228-229`)
            if self.mode == DataType.MULTILABEL and self.top_k:
                raise ValueError(
                    "You can not use the `top_k` parameter to calculate accuracy for multi-label inputs."
                )
            super().update(preds, target)

    def compute(self) -> jax.Array:
        if self.subset_accuracy and _check_subset_validity(self.mode):
            return _subset_accuracy_compute(self.correct, self.total)
        tp, fp, tn, fn = self._get_final_stats()
        return _accuracy_compute(tp, fp, tn, fn, self.average, self.mdmc_reduce, self.mode)


__all__ = ["Accuracy"]
