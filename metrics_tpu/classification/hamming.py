"""HammingDistance module metric (reference `classification/hamming.py`)."""
from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp

from metrics_tpu.functional.classification.hamming import (
    _hamming_distance_compute,
    _hamming_distance_update,
)
from metrics_tpu.metric import Metric


class HammingDistance(Metric):
    """Share of wrongly predicted labels over all label positions.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import HammingDistance
        >>> target = jnp.asarray([[0, 1], [1, 1]])
        >>> preds = jnp.asarray([[0, 1], [0, 1]])
        >>> hamming = HammingDistance()
        >>> hamming(preds, target)
        Array(0.25, dtype=float32)
    """

    is_differentiable: Optional[bool] = False
    higher_is_better: Optional[bool] = False
    full_state_update: Optional[bool] = False

    def __init__(self, threshold: float = 0.5, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("correct", default=jnp.asarray(0, dtype=jnp.int32), dist_reduce_fx="sum")
        self.add_state("total", default=jnp.asarray(0, dtype=jnp.int32), dist_reduce_fx="sum")
        self.threshold = threshold

    def update(self, preds, target) -> None:
        correct, total = _hamming_distance_update(preds, target, self.threshold)
        self.correct = self.correct + correct
        self.total = self.total + total

    def compute(self) -> jax.Array:
        return _hamming_distance_compute(self.correct, self.total)


__all__ = ["HammingDistance"]
