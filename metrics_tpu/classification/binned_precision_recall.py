"""Binned (fixed-threshold-grid) PR curve family — the jit-native curve path.

Parity: reference `classification/binned_precision_recall.py:46-302`
(``BinnedPrecisionRecallCurve`` states `:119-180`, ``BinnedAveragePrecision``,
``BinnedRecallAtFixedPrecision``).

TPU-first rework: the reference iterates thresholds one at a time "to conserve
memory" (`:160-166`); here the (N, C) x (T,) comparison is one batched
tensor contraction ``TPs[c,t] = Σ_n target[n,c]·(preds[n,c] ≥ thr[t])`` —
static ``(C, T)`` state, a single fused XLA kernel per update, MXU-eligible.
This is the blessed fast path for curve metrics on TPU (SURVEY §2.2).
"""
from __future__ import annotations

from typing import Any, List, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from metrics_tpu.functional.classification.average_precision import (
    _average_precision_compute_with_precision_recall,
)
from metrics_tpu.metric import Metric
from metrics_tpu.ops.binned import binned_curve_counts
from metrics_tpu.utils.data import to_onehot

METRIC_EPS = 1e-6


def _recall_at_precision(
    precision: jax.Array, recall: jax.Array, thresholds: jax.Array, min_precision: float
) -> Tuple[jax.Array, jax.Array]:
    # lexicographic max over (recall, precision, threshold) among points with
    # precision >= min_precision (matches reference `max(...)` at `:30-34`),
    # expressed as staged masked maxima so it stays jit-safe
    n = thresholds.shape[0]
    ok = precision[:n] >= min_precision
    rec = jnp.where(ok, recall[:n], -jnp.inf)
    rmax = jnp.max(rec)
    any_ok = jnp.isfinite(rmax)
    cand = ok & (rec == rmax)
    pmax = jnp.max(jnp.where(cand, precision[:n], -jnp.inf))
    cand = cand & (precision[:n] == pmax)
    tbest = jnp.max(jnp.where(cand, thresholds, -jnp.inf))
    max_recall = jnp.where(any_ok, rmax, 0.0)
    best_threshold = jnp.where((max_recall == 0.0) | ~any_ok, 1e6, tbest)
    return max_recall, best_threshold


class BinnedPrecisionRecallCurve(Metric):
    """Constant-memory PR curve over a fixed threshold grid.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import BinnedPrecisionRecallCurve
        >>> preds = jnp.asarray([0.1, 0.4, 0.35, 0.8])
        >>> target = jnp.asarray([0, 0, 1, 1])
        >>> metric = BinnedPrecisionRecallCurve(num_classes=1, thresholds=5)
        >>> precision, recall, thresholds = metric(preds, target)
        >>> precision
        Array([0.5000001 , 0.66666675, 1.        , 1.        , 1.        ,
               1.        ], dtype=float32)
        >>> recall
        Array([0.9999995 , 0.9999995 , 0.49999976, 0.49999976, 0.        ,
               0.        ], dtype=float32)
        >>> thresholds
        Array([0.  , 0.25, 0.5 , 0.75, 1.  ], dtype=float32)
    """

    is_differentiable: Optional[bool] = False
    higher_is_better: Optional[bool] = None
    full_state_update: Optional[bool] = False

    def __init__(
        self,
        num_classes: int,
        thresholds: Union[int, jax.Array, List[float]] = 100,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.num_classes = num_classes
        # thresholds live on HOST (numpy): they're a static hyperparameter that
        # jit traces bake in as a constant, and embedding a DEVICE array as a
        # compile-time constant forces a device->host fetch at trace time —
        # which on tunneled backends permanently degrades blocking-sync cost
        # for the whole session (docs/performance.md "The D2H sync cliff")
        if isinstance(thresholds, int):
            self.num_thresholds = thresholds
            self.thresholds = np.linspace(0, 1.0, thresholds, dtype=np.float32)
        elif thresholds is not None:
            if not isinstance(thresholds, (list, np.ndarray, jnp.ndarray, jax.Array)):
                raise ValueError("Expected argument `thresholds` to either be an integer, list of floats or a tensor")
            self.thresholds = np.asarray(thresholds, dtype=np.float32)
            self.num_thresholds = self.thresholds.size

        for name in ("TPs", "FPs", "FNs"):
            self.add_state(
                name,
                default=jnp.zeros((num_classes, self.num_thresholds), dtype=jnp.float32),
                dist_reduce_fx="sum",
            )

    def update(self, preds, target) -> None:
        if preds.ndim == target.ndim == 1:
            preds = preds.reshape(-1, 1)
            target = target.reshape(-1, 1)
        if preds.ndim == target.ndim + 1:
            target = to_onehot(target, num_classes=self.num_classes)

        t = (target == 1).astype(jnp.float32)  # (N, C)
        # one fused MXU compare-contract program (metrics_tpu/ops/binned.py)
        tps, fps, fns = binned_curve_counts(preds, t, self.thresholds)
        self.TPs = self.TPs + tps
        self.FPs = self.FPs + fps
        self.FNs = self.FNs + fns

    def compute(self) -> Union[Tuple[jax.Array, ...], Tuple[List[jax.Array], ...]]:
        precisions = (self.TPs + METRIC_EPS) / (self.TPs + self.FPs + METRIC_EPS)
        recalls = self.TPs / (self.TPs + self.FNs + METRIC_EPS)
        precisions = jnp.concatenate([precisions, jnp.ones((self.num_classes, 1), dtype=precisions.dtype)], axis=1)
        recalls = jnp.concatenate([recalls, jnp.zeros((self.num_classes, 1), dtype=recalls.dtype)], axis=1)
        thresholds = jnp.asarray(self.thresholds)  # host constant -> device array for the API
        if self.num_classes == 1:
            return precisions[0, :], recalls[0, :], thresholds
        return list(precisions), list(recalls), [thresholds for _ in range(self.num_classes)]


class BinnedAveragePrecision(BinnedPrecisionRecallCurve):
    """Average precision from the binned curve (constant memory).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import BinnedAveragePrecision
        >>> preds = jnp.asarray([0.1, 0.4, 0.35, 0.8])
        >>> target = jnp.asarray([0, 0, 1, 1])
        >>> metric = BinnedAveragePrecision(num_classes=1, thresholds=5)
        >>> metric(preds, target)
        Array(0.833333, dtype=float32)
    """

    def compute(self) -> Union[List[jax.Array], jax.Array]:
        precisions, recalls, _ = super().compute()
        return _average_precision_compute_with_precision_recall(
            precisions, recalls, self.num_classes, average=None
        )


class BinnedRecallAtFixedPrecision(BinnedPrecisionRecallCurve):
    """Highest recall (and its threshold) with precision >= min_precision.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import BinnedRecallAtFixedPrecision
        >>> preds = jnp.asarray([0.1, 0.4, 0.35, 0.8])
        >>> target = jnp.asarray([0, 0, 1, 1])
        >>> metric = BinnedRecallAtFixedPrecision(num_classes=1, min_precision=0.5, thresholds=5)
        >>> metric(preds, target)
        (Array(0.9999995, dtype=float32), Array(0.25, dtype=float32))
    """

    def __init__(
        self,
        num_classes: int,
        min_precision: float,
        thresholds: Union[int, jax.Array, List[float]] = 100,
        **kwargs: Any,
    ) -> None:
        super().__init__(num_classes=num_classes, thresholds=thresholds, **kwargs)
        self.min_precision = min_precision

    def compute(self) -> Tuple[jax.Array, jax.Array]:
        precisions, recalls, thresholds = super().compute()
        if self.num_classes == 1:
            return _recall_at_precision(precisions, recalls, thresholds, self.min_precision)
        recalls_at_p = []
        thresholds_at_p = []
        for i in range(self.num_classes):
            r, t = _recall_at_precision(precisions[i], recalls[i], thresholds[i], self.min_precision)
            recalls_at_p.append(r)
            thresholds_at_p.append(t)
        return jnp.stack(recalls_at_p), jnp.stack(thresholds_at_p)


__all__ = ["BinnedPrecisionRecallCurve", "BinnedAveragePrecision", "BinnedRecallAtFixedPrecision"]
