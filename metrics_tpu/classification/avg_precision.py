"""AveragePrecision module metric (reference `classification/avg_precision.py`)."""
from __future__ import annotations

from typing import Any, List, Optional, Union

import jax

from metrics_tpu.classification._raw_state import _RawPairStateMixin
from metrics_tpu.functional.classification.average_precision import (
    _average_precision_compute,
    _average_precision_update,
)
from metrics_tpu.functional.classification.precision_recall_curve import _precision_recall_curve_update
from metrics_tpu.metric import Metric


class AveragePrecision(_RawPairStateMixin, Metric):
    """Average precision from accumulated scores.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import AveragePrecision
        >>> preds = jnp.asarray([0.1, 0.4, 0.35, 0.8])
        >>> target = jnp.asarray([0, 0, 1, 1])
        >>> average_precision = AveragePrecision(pos_label=1)
        >>> average_precision(preds, target)
        Array(0.8333334, dtype=float32)
    """

    is_differentiable: Optional[bool] = False
    higher_is_better: Optional[bool] = True
    full_state_update: Optional[bool] = False

    def __init__(
        self,
        num_classes: Optional[int] = None,
        pos_label: Optional[int] = None,
        average: Optional[str] = "macro",
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.num_classes = num_classes
        self.pos_label = pos_label
        allowed_average = ("micro", "macro", "weighted", "none", None)
        if average not in allowed_average:
            raise ValueError(f"Expected argument `average` to be one of {allowed_average} but got {average}")
        self.average = average

        self.add_state("preds", default=[], dist_reduce_fx="cat")
        self.add_state("target", default=[], dist_reduce_fx="cat")

    def update(self, preds, target) -> None:
        # raw-row buffering: metadata-only validation here, layout transform
        # deferred to observation time (see `_raw_state.py`)
        preds, target, num_classes, pos_label = _average_precision_update(
            preds, target, self.num_classes, self.pos_label, self.average, format_tensors=False
        )
        self.preds.append(preds)
        self.target.append(target)
        self.num_classes = num_classes
        self.pos_label = pos_label

    def _format_row(self, preds, target):
        p, t, _, _ = _precision_recall_curve_update(
            preds, target, self.num_classes, self.pos_label, warn=False
        )
        return p, t

    def compute(self) -> Union[jax.Array, List[jax.Array]]:
        preds, target = self._cat_raw()
        preds, target, num_classes, pos_label = _precision_recall_curve_update(
            preds, target, self.num_classes, self.pos_label, warn=False
        )
        return _average_precision_compute(preds, target, num_classes, pos_label, self.average)


__all__ = ["AveragePrecision"]
