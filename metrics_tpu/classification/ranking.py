"""Multilabel ranking module metrics (reference `classification/ranking.py`)."""
from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp

from metrics_tpu.functional.classification.ranking import (
    _coverage_error_compute,
    _coverage_error_update,
    _label_ranking_average_precision_compute,
    _label_ranking_average_precision_update,
    _label_ranking_loss_compute,
    _label_ranking_loss_update,
)
from metrics_tpu.metric import Metric


class _RankingBase(Metric):
    is_differentiable: Optional[bool] = False
    full_state_update: Optional[bool] = False

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("measure", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("total", jnp.asarray(0, dtype=jnp.int32), dist_reduce_fx="sum")
        self.add_state("sample_weight", jnp.asarray(0.0), dist_reduce_fx="sum")
        self._weighted = False


class CoverageError(_RankingBase):
    """Average depth of ranking needed to cover all relevant labels.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import CoverageError
        >>> preds = jnp.asarray([[-0.25, 0.50, 0.10], [-0.05, 0.75, 0.95]])
        >>> target = jnp.asarray([[1, 0, 1], [0, 1, 0]])
        >>> metric = CoverageError()
        >>> metric(preds, target)
        Array(2.5, dtype=float32)
    """

    higher_is_better: Optional[bool] = False

    def update(self, preds, target, sample_weight: Optional[jax.Array] = None) -> None:
        measure, total, weight = _coverage_error_update(preds, target, sample_weight)
        self.measure = self.measure + measure
        self.total = self.total + total
        if weight is not None:
            self._weighted = True
            self.sample_weight = self.sample_weight + weight

    def compute(self) -> jax.Array:
        return _coverage_error_compute(self.measure, self.total, self.sample_weight if self._weighted else None)


class LabelRankingAveragePrecision(_RankingBase):
    """Label ranking average precision for multilabel data.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import LabelRankingAveragePrecision
        >>> preds = jnp.asarray([[0.75, 0.05, 0.35], [0.45, 0.80, 0.90]])
        >>> target = jnp.asarray([[1, 0, 0], [0, 0, 1]])
        >>> metric = LabelRankingAveragePrecision()
        >>> metric(preds, target)
        Array(1., dtype=float32)
    """

    higher_is_better: Optional[bool] = True

    def update(self, preds, target, sample_weight: Optional[jax.Array] = None) -> None:
        measure, total, weight = _label_ranking_average_precision_update(preds, target, sample_weight)
        self.measure = self.measure + measure
        self.total = self.total + total
        if weight is not None:
            self._weighted = True
            self.sample_weight = self.sample_weight + weight

    def compute(self) -> jax.Array:
        return _label_ranking_average_precision_compute(
            self.measure, self.total, self.sample_weight if self._weighted else None
        )


class LabelRankingLoss(_RankingBase):
    """Average number of wrongly-ordered label pairs.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import LabelRankingLoss
        >>> preds = jnp.asarray([[0.75, 0.05, 0.35], [0.45, 0.80, 0.90]])
        >>> target = jnp.asarray([[1, 0, 0], [0, 0, 1]])
        >>> metric = LabelRankingLoss()
        >>> metric(preds, target)
        Array(0., dtype=float32)
    """

    higher_is_better: Optional[bool] = False

    def update(self, preds, target, sample_weight: Optional[jax.Array] = None) -> None:
        measure, total, weight = _label_ranking_loss_update(preds, target, sample_weight)
        self.measure = self.measure + measure
        self.total = self.total + total
        if weight is not None:
            self._weighted = True
            self.sample_weight = self.sample_weight + weight

    def compute(self) -> jax.Array:
        return _label_ranking_loss_compute(self.measure, self.total, self.sample_weight if self._weighted else None)


__all__ = ["CoverageError", "LabelRankingAveragePrecision", "LabelRankingLoss"]
