"""Option-string enums with forgiving parsing.

Parity: reference `src/torchmetrics/utilities/enums.py:18-83`.
"""
from __future__ import annotations

from enum import Enum
from typing import Optional, Union


class EnumStr(str, Enum):
    """String enum accepting case-insensitive, ``-``/``_``-agnostic values."""

    @classmethod
    def from_str(cls, value: str) -> Optional["EnumStr"]:
        try:
            return cls[value.replace("-", "_").upper()]
        except KeyError:
            return None

    @classmethod
    def from_str_or_raise(cls, value: Union[str, "EnumStr", None], arg: str = "value") -> "EnumStr":
        if isinstance(value, cls):
            return value
        if value is None:
            raise ValueError(f"`{arg}` must be one of {[e.value for e in cls]}, got None")
        member = cls.from_str(str(value))
        if member is None:
            raise ValueError(f"`{arg}` must be one of {[e.value for e in cls]}, got {value!r}")
        return member

    @staticmethod
    def _canon(value: str) -> str:
        return value.replace("-", "_").lower()

    def __eq__(self, other: object) -> bool:
        if isinstance(other, Enum):
            other = other.value
        if isinstance(other, str):
            return self._canon(self.value) == self._canon(other)
        return NotImplemented

    def __hash__(self) -> int:
        return hash(self._canon(self.value))


class DataType(EnumStr):
    """Classification input kinds recognised by the input-format engine."""

    BINARY = "binary"
    MULTILABEL = "multi-label"
    MULTICLASS = "multi-class"
    MULTIDIM_MULTICLASS = "multi-dim multi-class"


class AverageMethod(EnumStr):
    MICRO = "micro"
    MACRO = "macro"
    WEIGHTED = "weighted"
    NONE = "none"
    SAMPLES = "samples"


class MDMCAverageMethod(EnumStr):
    GLOBAL = "global"
    SAMPLEWISE = "samplewise"


__all__ = ["EnumStr", "DataType", "AverageMethod", "MDMCAverageMethod"]
