"""Small numerically-careful compute helpers.

Parity: reference `src/torchmetrics/utilities/compute.py` (``_safe_xlogy`` etc.).
"""
from __future__ import annotations

import jax.numpy as jnp
from jax import Array


def _safe_matmul(x: Array, y: Array) -> Array:
    return jnp.matmul(x, y)


def _safe_xlogy(x: Array, y: Array) -> Array:
    """x * log(y), defined as 0 where x == 0 (even if y <= 0)."""
    safe_y = jnp.where(x == 0, jnp.ones_like(y), y)
    return jnp.where(x == 0, jnp.zeros_like(x), x * jnp.log(safe_y))


def _safe_divide(num: Array, denom: Array) -> Array:
    """num / denom with 0 where denom == 0 (the reference's `_safe_divide`)."""
    num = jnp.asarray(num, dtype=jnp.result_type(num, jnp.float32))
    denom = jnp.asarray(denom, dtype=jnp.result_type(denom, jnp.float32))
    return jnp.where(denom == 0, jnp.zeros_like(num), num / jnp.where(denom == 0, jnp.ones_like(denom), denom))


def _auc_compute(x: Array, y: Array, reorder: bool = False) -> Array:
    """Trapezoidal area under (x, y); optionally sort by x first.

    Parity: reference `functional/classification/auc.py`. Direction (ascending or
    descending x) is resolved from the data like the reference; under jit this is
    a traced sign, handled with ``jnp.where`` instead of python branching.
    """
    if reorder:
        order = jnp.argsort(x, stable=True)
        x, y = x[order], y[order]
    dx = jnp.diff(x)
    # +1 if x ascending, -1 if descending; mixed directions integrate as-is.
    direction = jnp.where(jnp.all(dx <= 0), -1.0, 1.0)
    return direction * jnp.trapezoid(y, x)


__all__ = ["_safe_xlogy", "_safe_divide", "_auc_compute", "_safe_matmul"]
