"""Small numerically-careful compute helpers.

Parity: reference `src/torchmetrics/utilities/compute.py` (``_safe_xlogy`` etc.).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import Array


def high_precision(fn):
    """Run ``fn`` with float32 contractions at full (HIGHEST) precision.

    XLA's TPU default lowers float32 matmuls/convs to bf16 MXU passes, which
    quantizes metric values onto a coarse grid (measured: pairwise cosine
    similarities landing on exact 1/256 steps, count contractions losing
    integer exactness above 256). Metrics are measurements — every contraction
    in this library opts into HIGHEST precision. This is a trace-time config,
    so it composes with ``jit`` and costs nothing on CPU.
    """

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        with jax.default_matmul_precision("highest"):
            return fn(*args, **kwargs)

    return wrapper


def _safe_matmul(x: Array, y: Array) -> Array:
    with jax.default_matmul_precision("highest"):
        return jnp.matmul(x, y)


def _safe_xlogy(x: Array, y: Array) -> Array:
    """x * log(y), defined as 0 where x == 0 (even if y <= 0)."""
    safe_y = jnp.where(x == 0, jnp.ones_like(y), y)
    return jnp.where(x == 0, jnp.zeros_like(x), x * jnp.log(safe_y))


def _safe_divide(num: Array, denom: Array) -> Array:
    """num / denom with 0 where denom == 0 (the reference's `_safe_divide`)."""
    num = jnp.asarray(num, dtype=jnp.result_type(num, jnp.float32))
    denom = jnp.asarray(denom, dtype=jnp.result_type(denom, jnp.float32))
    return jnp.where(denom == 0, jnp.zeros_like(num), num / jnp.where(denom == 0, jnp.ones_like(denom), denom))


def _auc_compute(x: Array, y: Array, reorder: bool = False) -> Array:
    """Trapezoidal area under (x, y); optionally sort by x first.

    Parity: reference `functional/classification/auc.py`. Direction (ascending or
    descending x) is resolved from the data like the reference; under jit this is
    a traced sign, handled with ``jnp.where`` instead of python branching.
    """
    if reorder:
        order = jnp.argsort(x, stable=True)
        x, y = x[order], y[order]
    dx = jnp.diff(x)
    # +1 if x ascending, -1 if descending; mixed directions integrate as-is.
    direction = jnp.where(jnp.all(dx <= 0), -1.0, 1.0)
    return direction * jnp.trapezoid(y, x)


__all__ = ["high_precision", "_safe_xlogy", "_safe_divide", "_auc_compute", "_safe_matmul"]
