"""Process-zero-gated logging/warning helpers.

Parity: reference `src/torchmetrics/utilities/prints.py:22-50`, which keys off the
``LOCAL_RANK`` env var. On TPU the authoritative identity is
``jax.process_index()``; we fall back to env vars before JAX is initialised so that
importing this module never forces backend initialisation.
"""
from __future__ import annotations

import logging
import os
import warnings
from functools import partial, wraps
from typing import Any, Callable

log = logging.getLogger("metrics_tpu")


def _process_index() -> int:
    # Avoid initialising the JAX backend just to emit a warning: trust the
    # standard launcher env vars first.
    for var in ("JAX_PROCESS_INDEX", "LOCAL_RANK", "RANK"):
        if var in os.environ:
            try:
                return int(os.environ[var])
            except ValueError:
                continue
    return 0


def rank_zero_only(fn: Callable) -> Callable:
    """Call ``fn`` only on process 0."""

    @wraps(fn)
    def wrapped(*args: Any, **kwargs: Any) -> Any:
        if _process_index() == 0:
            return fn(*args, **kwargs)
        return None

    return wrapped


@rank_zero_only
def rank_zero_warn(message: str, *args: Any, stacklevel: int = 4, **kwargs: Any) -> None:
    warnings.warn(message, *args, stacklevel=stacklevel, **kwargs)


@rank_zero_only
def rank_zero_info(message: str, *args: Any, **kwargs: Any) -> None:
    log.info(message, *args, **kwargs)


@rank_zero_only
def rank_zero_debug(message: str, *args: Any, **kwargs: Any) -> None:
    log.debug(message, *args, **kwargs)


_future_warning = partial(warnings.warn, category=FutureWarning)

__all__ = [
    "rank_zero_only",
    "rank_zero_warn",
    "rank_zero_info",
    "rank_zero_debug",
]
