"""Classification input validation + canonicalization engine.

Parity: reference `src/torchmetrics/utilities/checks.py` —
``_input_format_classification`` (`:313-454`), ``_check_classification_inputs``
(`:206`), ``_check_shape_and_type_consistency`` (`:68`), plus the retrieval input
checks (`:534`).

Inputs are classified into one of four :class:`DataType` cases and converted to
canonical **binary int tensors** of shape ``(N, C)`` (or ``(N, C, X)`` for
multi-dim multi-class) by thresholding, one-hot, or top-k selection.

TPU-first rework:
- shape/dtype validation is static and always runs (jit-safe);
- value-dependent validation (label ranges, probability bounds) runs only on
  concrete arrays — under ``jit`` tracing the values are unknowable, so those
  checks are skipped, matching the "traceable with static shapes" contract;
- ``num_classes`` inference from data maxima is eager-only; under jit, pass
  ``num_classes`` explicitly (a shape-defining value must be static on TPU).
"""
from __future__ import annotations

from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from metrics_tpu.utils.data import select_topk, to_onehot
from metrics_tpu.utils.enums import DataType


def _is_concrete(*arrays) -> bool:
    return not any(isinstance(a, jax.core.Tracer) for a in arrays)


@jax.jit
def _minmax_pair(preds, target):
    """min/max of both inputs as ONE device program → one host transfer.

    The value checks need up to four scalar reductions; issuing them as
    separate eager ops costs a blocking device sync each (hundreds of ms per
    update on remote/tunneled backends). Fused + jitted they are a single
    tiny program and a single 4-float transfer.
    """
    as_f32 = lambda x: x.astype(jnp.float32)  # noqa: E731
    return jnp.stack(
        [as_f32(target.min()), as_f32(target.max()), as_f32(preds.min()), as_f32(preds.max())]
    )


_BENIGN_STATS = np.array([0.0, 0.0, 0.0, 1.0], dtype=np.float32)  # t_min, t_max, p_min, p_max
_validation_mode: Optional[str] = None  # resolved lazily from env
# insertion-ordered signature memory for "first" mode; bounded FIFO so shape
# churn (e.g. ragged final batches every epoch) can't grow it without limit —
# an evicted signature simply gets value-checked again, the safe direction
_seen_check_keys: dict = {}
_SEEN_KEYS_CAP = 4096
_eviction_count = 0
_eviction_warned = False
# Metric._wrap_update points this at the instance whose eager update is
# running, scoping "first"-mode signature memory PER METRIC: a fresh instance
# always gets its first-update validation even if another instance already
# saw the same input signature. Bare functional calls (no instance) fall back
# to the process-global cache above.
_check_owner = None
_cache_generation = 0  # bumped by set_validation_mode to invalidate owner caches


def set_validation_mode(mode: str) -> None:
    """Control value-dependent input validation: ``"full"`` (the default —
    every update checked, strict reference parity), ``"first"`` (first update
    per input signature fully validated, skipped after), or ``"off"``.

    Shape/dtype validation always runs; this only gates checks that must read
    data values (label ranges, probability bounds). Each such read costs one
    blocking device→host sync — microseconds locally, but a full network
    round-trip per ``update()`` on remote/tunneled TPU backends. ``"first"``
    keeps reference-grade misuse errors on the first occurrence of every input
    signature at zero steady-state cost, and is what enables the fused
    one-program update/forward paths and the deferred micro-batched dispatch
    queue — opt in with ``METRICS_TPU_VALIDATION=first`` (or this function)
    on throughput-critical loops. The default stays ``"full"`` so a later
    invalid batch (e.g. a NaN reaching ``CatMetric(nan_strategy='error')``)
    raises on the offending call out of the box.
    """
    if mode not in ("full", "first", "off"):
        raise ValueError(f"validation mode must be 'full', 'first' or 'off', got {mode!r}")
    global _validation_mode, _eviction_count, _eviction_warned, _cache_generation
    _validation_mode = mode
    _seen_check_keys.clear()
    _cache_generation += 1  # invalidates every per-instance cache lazily
    _eviction_count = 0
    _eviction_warned = False


def _get_validation_mode() -> str:
    # "full" by default (advisor round-5: later invalid batches must surface
    # out of the box); "first" — the fused/deferred fast-path mode — is an
    # explicit opt-in via METRICS_TPU_VALIDATION=first or set_validation_mode
    global _validation_mode
    if _validation_mode is None:
        import os

        _validation_mode = os.environ.get("METRICS_TPU_VALIDATION", "full")
        if _validation_mode not in ("full", "first", "off"):
            _validation_mode = "full"
    return _validation_mode


def _should_value_check(preds, target, key_extra=()) -> bool:
    global _eviction_count, _eviction_warned
    mode = _get_validation_mode()
    if mode == "off":
        return False
    if mode == "full":
        return True
    if not _is_concrete(preds, target):
        # a traced update never value-checks; do NOT consume the signature —
        # a later eager update with the same shapes must still get checked
        return False
    key = (preds.shape, str(preds.dtype), target.shape, str(target.dtype), key_extra)
    owner = _check_owner
    if owner is not None:
        cache = owner.__dict__.get("_value_check_seen")
        if cache is None or owner.__dict__.get("_value_check_gen") != _cache_generation:
            cache = {}
            owner.__dict__["_value_check_seen"] = cache
            owner.__dict__["_value_check_gen"] = _cache_generation
            # a mode switch starts a fresh diagnostic epoch for this owner
            owner.__dict__["_value_check_evictions"] = 0
            owner.__dict__["_value_check_evict_warned"] = False
    else:
        cache = _seen_check_keys
    if key in cache:
        return False
    cache[key] = None
    while len(cache) > _SEEN_KEYS_CAP:
        cache.pop(next(iter(cache)))
        if owner is not None:
            # PER-OWNER diagnostics: the warning names the churning metric
            # instance and fires once per owner, so a service with several
            # metrics (one of them fed a pathological input stream) can
            # attribute the churn instead of learning about it once globally
            count = owner.__dict__.get("_value_check_evictions", 0) + 1
            owner.__dict__["_value_check_evictions"] = count
            if count > _SEEN_KEYS_CAP and not owner.__dict__.get("_value_check_evict_warned"):
                owner.__dict__["_value_check_evict_warned"] = True
                from metrics_tpu.utils.prints import rank_zero_warn

                rank_zero_warn(
                    f"Validation mode 'first' has evicted more than"
                    f" {_SEEN_KEYS_CAP} input signatures for metric"
                    f" `{type(owner).__name__}` (id 0x{id(owner):x}): this"
                    " instance churns through more distinct input"
                    " shapes/dtypes than the cache holds, so evicted"
                    " signatures are re-validated (re-paying the device sync"
                    " 'first' mode is meant to elide). Pad/bucket this"
                    " metric's inputs to stable shapes, or set"
                    " METRICS_TPU_VALIDATION=off if inputs are already"
                    " trusted.",
                    UserWarning,
                )
        else:
            _eviction_count += 1
            if _eviction_count > _SEEN_KEYS_CAP and not _eviction_warned:
                _eviction_warned = True
                from metrics_tpu.utils.prints import rank_zero_warn

                rank_zero_warn(
                    "Validation mode 'first' has evicted more than"
                    f" {_SEEN_KEYS_CAP} input signatures from its seen-signature"
                    " cache: this pipeline churns through more distinct input"
                    " shapes/dtypes than the cache holds, so evicted signatures"
                    " are re-validated (re-paying the device sync 'first' mode is"
                    " meant to elide). Pad/bucket inputs to stable shapes, or set"
                    " METRICS_TPU_VALIDATION=off if inputs are already trusted.",
                    UserWarning,
                )
    return True


class _ValueStats:
    """Lazily fetched (t_min, t_max, p_min, p_max) shared across check stages.

    When the validation mode gates this signature out, benign values that pass
    every check are returned without touching the device (target stats 0 —
    below every class bound; preds in [0, 1]).
    """

    __slots__ = ("_preds", "_target", "_vals")

    def __init__(self, preds, target, force: bool = False, key_extra=()) -> None:
        self._preds, self._target = preds, target
        self._vals = (
            None if (force or _should_value_check(preds, target, key_extra)) else _BENIGN_STATS
        )

    @property
    def is_real(self) -> bool:
        """True when the stats reflect actual data (not the benign skip values)."""
        return self._vals is not _BENIGN_STATS

    def _fetch(self) -> np.ndarray:
        if self._vals is None:
            if _is_concrete(self._preds, self._target):
                self._vals = np.asarray(_minmax_pair(self._preds, self._target))
            else:
                # mixed concrete/traced pair: the fused kernel would hand back
                # a tracer (np.asarray would raise). Read each concrete side
                # on the host (jnp reductions would be staged by the ambient
                # trace even on concrete data); the traced side reports benign
                # values, matching the per-side concreteness guards upstream.
                vals = _BENIGN_STATS.copy()
                if _is_concrete(self._target) and self._target.size > 0:
                    host = np.asarray(self._target)
                    vals[0], vals[1] = float(host.min()), float(host.max())
                if _is_concrete(self._preds) and self._preds.size > 0:
                    host = np.asarray(self._preds)
                    vals[2], vals[3] = float(host.min()), float(host.max())
                self._vals = vals
        return self._vals

    @property
    def target_min(self) -> float:
        return float(self._fetch()[0])

    @property
    def target_max(self) -> float:
        return float(self._fetch()[1])

    @property
    def preds_min(self) -> float:
        return float(self._fetch()[2])

    @property
    def preds_max(self) -> float:
        return float(self._fetch()[3])


def _check_same_shape(preds, target) -> None:
    if preds.shape != target.shape:
        raise RuntimeError(
            f"Predictions and targets are expected to have the same shape, got {preds.shape} and {target.shape}"
        )


def _check_for_empty(preds, target) -> bool:
    return preds.size == 0 and target.size == 0


def _squeeze_excess_dims(preds, target):
    """Drop all size-1 dims except the leading N dim (reference `_input_squeeze`).

    Type-preserving (host arrays stay host) and dispatch-free when there is
    nothing to squeeze — this sits on eager per-update hot paths.
    """
    if preds.shape[:1] == (1,):
        preds = preds.squeeze()[None]
        target = target.squeeze()[None]
    else:
        if 1 in preds.shape:
            preds = preds.squeeze()
        if 1 in target.shape:
            target = target.squeeze()
    return preds, target


def _basic_validation(preds, target, threshold, multiclass, ignore_index, stats=None) -> None:
    if _check_for_empty(preds, target):
        return
    if jnp.issubdtype(target.dtype, jnp.floating):
        raise ValueError("The `target` has to be an integer tensor.")
    preds_float = jnp.issubdtype(preds.dtype, jnp.floating)
    if preds.shape[0] != target.shape[0]:
        raise ValueError("The `preds` and `target` should have the same first dimension.")
    if not _is_concrete(preds, target):
        return  # value checks need concrete data
    stats = stats or _ValueStats(preds, target)
    if ignore_index is None and stats.target_min < 0:
        raise ValueError("The `target` has to be a non-negative tensor.")
    if ignore_index is not None and ignore_index >= 0 and stats.target_min < 0:
        raise ValueError("The `target` has to be a non-negative tensor.")
    if not preds_float and stats.preds_min < 0:
        raise ValueError("If `preds` are integers, they have to be non-negative.")
    if multiclass is False and stats.target_max > 1:
        raise ValueError("If you set `multiclass=False`, then `target` should not exceed 1.")
    if multiclass is False and not preds_float and stats.preds_max > 1:
        raise ValueError("If you set `multiclass=False` and `preds` are integers, then `preds` should not exceed 1.")


def _case_and_implied_classes(preds, target, stats=None) -> Tuple[DataType, int]:
    """Resolve the input case from shapes/dtypes (reference `:68-121`)."""
    preds_float = jnp.issubdtype(preds.dtype, jnp.floating)
    if stats is None:
        stats = _ValueStats(preds, target)
    if preds.ndim == target.ndim:
        if preds.shape != target.shape:
            raise ValueError(
                f"The `preds` and `target` should have the same shape, got {preds.shape} and {target.shape}."
            )
        if preds_float and target.size > 0 and _is_concrete(target) and stats.target_max > 1:
            raise ValueError(
                "If `preds` and `target` are of shape (N, ...) and `preds` are floats, `target` should be binary."
            )
        if preds.ndim == 1 and preds_float:
            case = DataType.BINARY
        elif preds.ndim == 1 and not preds_float:
            case = DataType.MULTICLASS
        elif preds.ndim > 1 and preds_float:
            case = DataType.MULTILABEL
        else:
            case = DataType.MULTIDIM_MULTICLASS
        implied_classes = int(np.prod(preds.shape[1:])) if preds.size > 0 else 0
    elif preds.ndim == target.ndim + 1:
        if not preds_float:
            raise ValueError("If `preds` have one dimension more than `target`, `preds` should be a float tensor.")
        if preds.shape[2:] != target.shape[1:]:
            raise ValueError(
                "If `preds` have one dimension more than `target`, the shape of `preds` should be"
                " (N, C, ...), and the shape of `target` should be (N, ...)."
            )
        implied_classes = preds.shape[1] if preds.size > 0 else 0
        case = DataType.MULTICLASS if preds.ndim == 2 else DataType.MULTIDIM_MULTICLASS
    else:
        raise ValueError(
            "Either `preds` and `target` both should have the (same) shape (N, ...), or `target` should be (N, ...)"
            " and `preds` should be (N, C, ...)."
        )
    return case, implied_classes


def _validate_num_classes(case, preds, target, num_classes, multiclass, implied_classes, stats=None) -> None:
    if case == DataType.BINARY:
        if num_classes > 2:
            raise ValueError("Your data is binary, but `num_classes` is larger than 2.")
        if num_classes == 2 and not multiclass:
            raise ValueError(
                "Your data is binary and `num_classes=2`, but `multiclass` is not True."
                " Set it to True if you want to transform binary data to multi-class format."
            )
        if num_classes == 1 and multiclass:
            raise ValueError(
                "You have binary data and have set `multiclass=True`, but `num_classes` is 1."
            )
    elif case in (DataType.MULTICLASS, DataType.MULTIDIM_MULTICLASS):
        if num_classes == 1 and multiclass is not False:
            raise ValueError(
                "You have set `num_classes=1`, but predictions are integers."
                " If you want to convert (multi-dimensional) multi-class data with 2 classes"
                " to binary/multi-label, set `multiclass=False`."
            )
        if num_classes > 1:
            if multiclass is False and implied_classes != num_classes:
                raise ValueError(
                    "You have set `multiclass=False`, but the implied number of classes"
                    " (from shape of inputs) does not match `num_classes`."
                )
            if target.size > 0 and _is_concrete(target) and num_classes <= (stats or _ValueStats(preds, target)).target_max:
                raise ValueError("The highest label in `target` should be smaller than `num_classes`.")
            if preds.shape != target.shape and num_classes != implied_classes:
                raise ValueError("The size of C dimension of `preds` does not match `num_classes`.")
    elif case == DataType.MULTILABEL:
        if multiclass and num_classes != 2:
            raise ValueError(
                "Your have set `multiclass=True`, but `num_classes` is not equal to 2."
            )
        if not multiclass and num_classes != implied_classes:
            raise ValueError("The implied number of classes (from shape of inputs) does not match num_classes.")


def _validate_top_k(top_k, case, implied_classes, multiclass, preds_float) -> None:
    if case == DataType.BINARY:
        raise ValueError("You can not use `top_k` parameter with binary data.")
    if not isinstance(top_k, int) or top_k <= 0:
        raise ValueError("The `top_k` has to be an integer larger than 0.")
    if not preds_float:
        raise ValueError("You have set `top_k`, but you do not have probability predictions.")
    if multiclass is False:
        raise ValueError("If you set `multiclass=False`, you can not set `top_k`.")
    if case == DataType.MULTILABEL and multiclass:
        raise ValueError(
            "If you want to transform multi-label data to 2 class multi-dimensional"
            " multi-class data using `multiclass=True`, you can not use `top_k`."
        )
    if top_k >= implied_classes:
        raise ValueError("The `top_k` has to be strictly smaller than the `C` dimension of `preds`.")


def _check_classification_inputs(
    preds,
    target,
    threshold: float,
    num_classes: Optional[int],
    multiclass: Optional[bool],
    top_k: Optional[int],
    ignore_index: Optional[int] = None,
    stats: Optional[_ValueStats] = None,
) -> DataType:
    """Full input validation; returns the resolved :class:`DataType` case."""
    if stats is None:
        stats = _ValueStats(
            preds, target, key_extra=(threshold, num_classes, multiclass, top_k, ignore_index)
        )
    _basic_validation(preds, target, threshold, multiclass, ignore_index, stats)
    case, implied_classes = _case_and_implied_classes(preds, target, stats)

    if preds.shape != target.shape:
        if multiclass is False and implied_classes != 2:
            raise ValueError(
                "You have set `multiclass=False`, but have more than 2 classes in your data,"
                " based on the C dimension of `preds`."
            )
        if target.size > 0 and _is_concrete(target) and stats.target_max >= implied_classes:
            raise ValueError(
                "The highest label in `target` should be smaller than the size of the `C` dimension of `preds`."
            )

    if num_classes:
        _validate_num_classes(case, preds, target, num_classes, multiclass, implied_classes, stats)

    if top_k is not None:
        _validate_top_k(top_k, case, implied_classes, multiclass, jnp.issubdtype(preds.dtype, jnp.floating))

    return case


def _classification_case(preds, target, threshold: float = 0.5) -> DataType:
    """Resolve the :class:`DataType` case with full validation but NO formatting.

    The raw-row buffering paths (e.g. `classification/auroc.py`) need the
    input case for mode-consistency checks at ``update`` time while deferring
    the layout transform to observation time; this runs the same validation
    as :func:`_input_format_classification` (value checks honoring the
    validation mode) without dispatching any formatting ops.
    """
    preds = preds if isinstance(preds, (jax.Array, np.ndarray)) else np.asarray(preds)
    target = target if isinstance(target, (jax.Array, np.ndarray)) else np.asarray(target)
    preds, target = _squeeze_excess_dims(preds, target)
    return _check_classification_inputs(
        preds, target, threshold=threshold, num_classes=None, multiclass=None, top_k=None
    )


def _input_format_classification(
    preds,
    target,
    threshold: float = 0.5,
    top_k: Optional[int] = None,
    num_classes: Optional[int] = None,
    multiclass: Optional[bool] = None,
    ignore_index: Optional[int] = None,
) -> Tuple[jax.Array, jax.Array, DataType]:
    """Canonicalize (preds, target) to binary int tensors ``(N, C)``/``(N, C, X)``.

    Same contract as reference ``_input_format_classification``
    (`utilities/checks.py:313-454`): binary -> ``(N, 1)`` thresholded; multi-class
    -> one-hot/top-k ``(N, C)``; multi-label -> thresholded ``(N, C)`` (extra dims
    flattened); multi-dim multi-class -> ``(N, C, X)``. The ``multiclass`` flag
    force-converts between views.
    """
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    preds, target = _squeeze_excess_dims(preds, target)
    if preds.dtype == jnp.float16:
        preds = preds.astype(jnp.float32)

    stats = _ValueStats(
        preds, target, key_extra=(threshold, num_classes, multiclass, top_k, ignore_index)
    )
    case = _check_classification_inputs(
        preds,
        target,
        threshold=threshold,
        num_classes=num_classes,
        multiclass=multiclass,
        top_k=top_k,
        ignore_index=ignore_index,
        stats=stats,
    )

    if case in (DataType.BINARY, DataType.MULTILABEL) and not top_k:
        preds = (preds >= threshold).astype(jnp.int32) if jnp.issubdtype(preds.dtype, jnp.floating) else preds
        num_classes = num_classes if not multiclass else 2

    if case == DataType.MULTILABEL and top_k:
        preds = select_topk(preds, top_k)

    if case in (DataType.MULTICLASS, DataType.MULTIDIM_MULTICLASS) or multiclass:
        if jnp.issubdtype(preds.dtype, jnp.floating):
            num_classes = preds.shape[1]
            preds = select_topk(preds, top_k or 1)
        else:
            if num_classes is None:
                if not _is_concrete(preds, target):
                    raise ValueError(
                        "`num_classes` must be given explicitly for label inputs under jit tracing"
                        " (class count defines the output shape, which must be static on TPU)."
                    )
                # inference, not validation: needs REAL values — reuse the
                # already-fetched stats when possible, force-fetch otherwise
                _s = stats if stats.is_real else _ValueStats(preds, target, force=True)
                num_classes = int(max(_s.preds_max, _s.target_max) + 1)
            preds = to_onehot(preds, max(2, num_classes))
        target = to_onehot(target, max(2, int(num_classes) if num_classes else 2))

        if multiclass is False:
            preds, target = preds[:, 1, ...], target[:, 1, ...]

    if not _check_for_empty(preds, target):
        if (case in (DataType.MULTICLASS, DataType.MULTIDIM_MULTICLASS) and multiclass is not False) or multiclass:
            target = target.reshape(target.shape[0], target.shape[1], -1)
            preds = preds.reshape(preds.shape[0], preds.shape[1], -1)
        else:
            target = target.reshape(target.shape[0], -1)
            preds = preds.reshape(preds.shape[0], -1)

    if preds.ndim > 2 and preds.shape[-1] == 1:
        preds, target = jnp.squeeze(preds, -1), jnp.squeeze(target, -1)

    return preds.astype(jnp.int32), target.astype(jnp.int32), case


def _input_squeeze(preds, target):
    return _squeeze_excess_dims(jnp.asarray(preds), jnp.asarray(target))


def _check_retrieval_metadata(
    indexes,
    preds,
    target,
    allow_non_binary_target: bool = False,
    ignore_index: Optional[int] = None,
) -> Tuple[Any, Any, Any]:
    """Fail-fast validation for retrieval triples WITHOUT canonicalizing.

    The module path (`retrieval/base.py`) buffers RAW rows and defers
    flatten/cast/ignore-filtering to observation time (sync/state_dict/
    compute), so its ``update`` must not dispatch device ops. This runs the
    same checks as :func:`_check_retrieval_inputs` — shape/dtype checks from
    array metadata only, the binary-target value check honoring the
    validation mode — and returns the inputs untouched (host arrays stay on
    host, device arrays stay device-committed, no reshape/cast dispatches).

    Note one deliberate divergence: the "batch left empty by ignore_index
    filtering" raise is value-dependent (it needs a read of ``target``), so
    like every value check it follows the validation mode — ``full``
    (default) raises on every such batch exactly like
    :func:`_check_retrieval_inputs`; under ``first``/``off`` a gated-off
    all-ignored batch is buffered and simply contributes no rows at compute.
    """
    indexes = indexes if isinstance(indexes, (jax.Array, np.ndarray)) else np.asarray(indexes)
    preds = preds if isinstance(preds, (jax.Array, np.ndarray)) else np.asarray(preds)
    target = target if isinstance(target, (jax.Array, np.ndarray)) else np.asarray(target)

    if indexes.shape != preds.shape or preds.shape != target.shape:
        raise ValueError("`indexes`, `preds` and `target` must be of the same shape")
    if not jnp.issubdtype(indexes.dtype, jnp.integer):
        raise ValueError("`indexes` must be a tensor of long integers")
    if not jnp.issubdtype(preds.dtype, jnp.floating):
        raise ValueError("`preds` must be a tensor of floats")
    target_is_float = jnp.issubdtype(target.dtype, jnp.floating)
    if not (jnp.issubdtype(target.dtype, jnp.integer) or target.dtype == jnp.bool_ or target_is_float):
        raise ValueError("`target` must be a tensor of booleans, integers or floats")
    if preds.size == 0:
        raise ValueError("`indexes`, `preds` and `target` must be non-empty")

    # value-dependent checks (binary target range; batch left empty by
    # ignore_index filtering) — one fused read, honoring the validation mode
    needs_range = not allow_non_binary_target
    if (
        _is_concrete(target)
        and (needs_range or ignore_index is not None)
        and _should_value_check(preds, target, key_extra=("retrieval", ignore_index))
    ):
        if isinstance(target, np.ndarray):
            t = target.reshape(-1)
            if ignore_index is not None:
                t = t[t != ignore_index]
            if t.size == 0:
                raise ValueError("`indexes`, `preds` and `target` must be non-empty")
            if needs_range and (t.max() > 1 or t.min() < 0):
                raise ValueError("`target` must contain binary values")
        else:
            t = target.reshape(-1).astype(jnp.float32)
            valid = jnp.ones_like(t, dtype=bool) if ignore_index is None else (target.reshape(-1) != ignore_index)
            stats = np.asarray(
                jnp.stack(
                    [
                        valid.any().astype(jnp.float32),
                        jnp.where(valid, t, jnp.inf).min(),
                        jnp.where(valid, t, -jnp.inf).max(),
                    ]
                )
            )
            if not stats[0]:
                raise ValueError("`indexes`, `preds` and `target` must be non-empty")
            if needs_range and (stats[2] > 1 or stats[1] < 0):
                raise ValueError("`target` must contain binary values")

    return indexes, preds, target


def _check_retrieval_inputs(
    indexes,
    preds,
    target,
    allow_non_binary_target: bool = False,
    ignore_index: Optional[int] = None,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Validate and flatten retrieval triples (reference `checks.py:534-590`)."""
    if indexes.shape != preds.shape or preds.shape != target.shape:
        raise ValueError("`indexes`, `preds` and `target` must be of the same shape")
    if not jnp.issubdtype(indexes.dtype, jnp.integer):
        raise ValueError("`indexes` must be a tensor of long integers")
    if not jnp.issubdtype(preds.dtype, jnp.floating):
        raise ValueError("`preds` must be a tensor of floats")
    target_is_float = jnp.issubdtype(target.dtype, jnp.floating)
    if not (jnp.issubdtype(target.dtype, jnp.integer) or target.dtype == jnp.bool_ or target_is_float):
        raise ValueError("`target` must be a tensor of booleans, integers or floats")

    indexes = indexes.reshape(-1)
    preds = preds.reshape(-1).astype(jnp.float32)
    target = target.reshape(-1)

    if ignore_index is not None:
        valid = target != ignore_index
        if _is_concrete(target):
            indexes, preds, target = indexes[valid], preds[valid], target[valid]

    if preds.size == 0:
        raise ValueError("`indexes`, `preds` and `target` must be non-empty")

    # float relevance targets are allowed like the reference
    # (`utilities/checks.py:507-527`): the "binary" requirement constrains
    # VALUES to [0, 1], not the dtype. The read is a blocking D2H sync
    # (~100 ms/update through a tunnel), so it honors the validation mode:
    # "first" checks once per input signature, "off" never
    if (
        _is_concrete(target)
        and not allow_non_binary_target
        and target.size
        and _should_value_check(preds, target, key_extra=("retrieval", ignore_index))
    ):
        tmin, tmax = np.asarray(jnp.stack([target.min(), target.max()]))
        if tmax > 1 or tmin < 0:
            raise ValueError("`target` must contain binary values")

    if target_is_float:
        target = target.astype(jnp.float32)
    else:
        target = target.astype(jnp.int32)
    return indexes.astype(jnp.int32) if indexes.dtype != jnp.int64 else indexes, preds, target


def _allclose_recursive(res1, res2, atol: float = 1e-6) -> bool:
    if isinstance(res1, (list, tuple)):
        return all(_allclose_recursive(r1, r2, atol) for r1, r2 in zip(res1, res2))
    if isinstance(res1, dict):
        return all(_allclose_recursive(res1[k], res2[k], atol) for k in res1)
    return bool(jnp.allclose(jnp.asarray(res1), jnp.asarray(res2), atol=atol))


def check_forward_full_state_property(
    metric_class,
    init_args: Optional[dict] = None,
    input_args: Optional[dict] = None,
    num_update_to_compare=(10, 100, 1000),
    reps: int = 5,
) -> None:
    """Empirically decide whether ``full_state_update=False`` is safe + faster.

    Parity: reference ``check_forward_full_state_property``
    (`utilities/checks.py:627-729`). Runs the metric's ``forward`` in both
    modes: if the two-update (full-state) and single-update (reduce-state)
    paths agree on every step, times both over ``num_update_to_compare`` steps
    and prints the recommended ``full_state_update`` setting.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import ConfusionMatrix
        >>> from metrics_tpu.utils.checks import check_forward_full_state_property
        >>> check_forward_full_state_property(
        ...     ConfusionMatrix,
        ...     init_args={'num_classes': 3},
        ...     input_args={'preds': jnp.asarray([0, 2, 1]), 'target': jnp.asarray([0, 1, 1])},
        ...     num_update_to_compare=(2,), reps=1,
        ... )  # doctest: +ELLIPSIS
        Full state for 2 steps took: ...
        Partial state for 2 steps took: ...
        Recommended setting `full_state_update=...`
    """
    from time import perf_counter

    init_args = init_args or {}
    input_args = input_args or {}

    class FullState(metric_class):
        full_state_update = True

    class PartState(metric_class):
        full_state_update = False

    fullstate = FullState(**init_args)
    partstate = PartState(**init_args)

    equal = True
    for _ in range(num_update_to_compare[0]):
        out1 = fullstate(**input_args)
        try:  # failure usually means update depends on pre-existing state
            out2 = partstate(**input_args)
        except Exception:  # invlint: allow(INV201) — intentional probe: a raising partial-state update IS the diagnostic signal (full_state_update=True is recommended below)
            equal = False
            break
        equal = equal and _allclose_recursive(out1, out2)

    if equal:
        res1 = fullstate.compute()
        try:
            res2 = partstate.compute()
        except Exception:  # invlint: allow(INV201) — intentional probe: a raising partial-state compute IS the diagnostic signal, not a fault to classify
            equal = False
        else:
            equal = equal and _allclose_recursive(res1, res2)

    if not equal:
        print("Recommended setting `full_state_update=True`")
        return

    timings = np.zeros((2, len(num_update_to_compare), reps))
    for i, metric in enumerate([fullstate, partstate]):
        metric.reset()  # drop state accumulated during the equality phase
        for j, steps in enumerate(num_update_to_compare):
            for r in range(reps):
                start = perf_counter()
                for _ in range(steps):
                    _ = metric(**input_args)
                timings[i, j, r] = perf_counter() - start
                metric.reset()

    mean = timings.mean(-1)
    std = timings.std(-1)
    for j, steps in enumerate(num_update_to_compare):
        print(f"Full state for {steps} steps took: {mean[0, j]:0.3f}+-{std[0, j]:0.3f}")
        print(f"Partial state for {steps} steps took: {mean[1, j]:0.3f}+-{std[1, j]:0.3f}")
    faster = bool(mean[1, -1] < mean[0, -1])
    print(f"Recommended setting `full_state_update={not faster}`")


def is_overridden(method_name: str, instance: object, parent: type) -> bool:
    """True when ``instance``'s ``method_name`` overrides ``parent``'s.

    Parity: reference `utilities/checks.py:730-752` (sans mock support —
    unwraps ``functools.wraps`` chains and ``partial``\\s before comparing).
    """
    from functools import partial

    instance_attr = getattr(instance, method_name, None)
    if instance_attr is None:
        return False
    while hasattr(instance_attr, "__wrapped__"):
        instance_attr = instance_attr.__wrapped__
    if isinstance(instance_attr, partial):
        instance_attr = instance_attr.func
    parent_attr = getattr(parent, method_name, None)
    if parent_attr is None:
        raise ValueError("The parent should define the method")
    return getattr(instance_attr, "__func__", instance_attr) is not getattr(
        parent_attr, "__func__", parent_attr
    )


__all__ = [
    "check_forward_full_state_property",
    "_input_format_classification",
    "_check_classification_inputs",
    "_check_same_shape",
    "_check_retrieval_inputs",
    "_input_squeeze",
    "is_overridden",
]
