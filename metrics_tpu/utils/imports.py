"""Optional-dependency feature gates.

Parity: reference `src/torchmetrics/utilities/imports.py:26-124` (~30 availability
flags). Here the flags gate host-side helpers (NLTK stemmer, HF transformers for
BERTScore/InfoLM, reference DSP packages) — the compute path only needs JAX.
"""
from __future__ import annotations

import importlib
import importlib.util
from functools import lru_cache


@lru_cache()
def package_available(name: str) -> bool:
    """True if ``import name`` would succeed (spec lookup only, no import)."""
    try:
        return importlib.util.find_spec(name) is not None
    except (ModuleNotFoundError, ValueError):
        return False


@lru_cache()
def module_available(path: str) -> bool:
    """True if the dotted module path is importable (checks every parent)."""
    parts = path.split(".")
    for i in range(1, len(parts) + 1):
        if not package_available(".".join(parts[:i])):
            return False
    return True


@lru_cache()
def _try_import(name: str):
    try:
        return importlib.import_module(name)
    except Exception:  # invlint: allow(INV201) — availability probe: any import failure means "not installed", never a fault
        return None


@lru_cache()
def compare_version(package: str, op, version: str) -> bool:
    """True if ``package`` is installed and ``op(its_version, version)``.

    Parity: reference `utilities/imports.py` ``_compare_version`` (lru-cached;
    False rather than raising when the package is absent or unversioned).
    """
    if not package_available(package):
        return False
    try:
        import importlib.metadata as _im

        have = _im.version(package)
    except Exception:  # invlint: allow(INV201) — availability probe: an unversioned package compares False by contract
        return False
    from packaging.version import Version

    try:
        return bool(op(Version(have), Version(version)))
    except Exception:  # invlint: allow(INV201) — availability probe: an unparseable version string compares False by contract
        return False


_SCIPY_AVAILABLE = package_available("scipy")
_SKLEARN_AVAILABLE = package_available("sklearn")
_NLTK_AVAILABLE = package_available("nltk")
_REGEX_AVAILABLE = package_available("regex")
_TRANSFORMERS_AVAILABLE = package_available("transformers")
_FLAX_AVAILABLE = package_available("flax")
_PESQ_AVAILABLE = package_available("pesq")
_PYSTOI_AVAILABLE = package_available("pystoi")
_PYCOCOTOOLS_AVAILABLE = package_available("pycocotools")
_TORCH_AVAILABLE = package_available("torch")

__all__ = [
    "package_available",
    "module_available",
    "compare_version",
    "_SCIPY_AVAILABLE",
    "_SKLEARN_AVAILABLE",
    "_NLTK_AVAILABLE",
    "_REGEX_AVAILABLE",
    "_TRANSFORMERS_AVAILABLE",
    "_FLAX_AVAILABLE",
    "_PESQ_AVAILABLE",
    "_PYSTOI_AVAILABLE",
    "_PYCOCOTOOLS_AVAILABLE",
    "_TORCH_AVAILABLE",
]
