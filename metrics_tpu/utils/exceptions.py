"""User-facing error types and the classified failure-domain hierarchy.

Parity: reference ``src/torchmetrics/utilities/exceptions.py:15-17`` provides
only ``MetricsUserError``. The failure-domain classes below are the TPU-side
extension: every fallback ladder in the dispatch stack (``ops/engine.py``,
``Metric``'s fused paths, ``MetricCollection``'s suite flushes,
``parallel/sync.py``) classifies what failed instead of catching bare
``Exception``, so telemetry, warning dedupe, and the recovery policy can key
on the *domain* of a failure rather than its string. The machinery that
consumes these classes (injection sites, degradation ladders, counters) lives
in :mod:`metrics_tpu.ops.faults`; this module stays dependency-free so the
exception types are importable from anywhere without cycles.
"""
from __future__ import annotations


class MetricsUserError(Exception):
    """Raised on incorrect use of the metrics API (e.g. double ``sync()``)."""


# --------------------------------------------------------------- fault domains
#: Canonical failure-domain names, in ladder-relevant order. Every
#: :class:`FaultError` subclass carries one of these as ``domain``.
FAULT_DOMAINS = (
    "trace",
    "compile",
    "runtime",
    "donation",
    "host",
    "sync",
    "journal",
    "ingest",
)


class FaultError(Exception):
    """Base of the classified failure-domain hierarchy.

    ``domain`` names which stage of the dispatch stack failed (one of
    :data:`FAULT_DOMAINS`); ``site`` optionally names the injection/observation
    site that raised (``"probe"``, ``"flush-chunk-2"``, ``"sync-gather"``, …).
    ``recoverable`` states whether the degradation ladder may re-probe the
    demoted path after clean steps: trace failures are structural (the same
    configuration will fail the same way), while compile/runtime/donation
    failures can be transient (HBM pressure, a backend hiccup) and earn a
    recovery edge.
    """

    domain: str = "runtime"
    recoverable: bool = True

    def __init__(self, message: str = "", *, site: str | None = None):
        super().__init__(message or f"{type(self).__name__} at site {site!r}")
        self.site = site


class TraceFault(FaultError):
    """Trace-time failure: the program cannot even ``eval_shape`` with these
    inputs. Structural — silent decline, never retried for the same config."""

    domain = "trace"
    recoverable = False


class CompileFault(FaultError):
    """Compile-time failure: the trace was fine but XLA lowering/compilation
    failed (e.g. resource exhaustion while building the executable)."""

    domain = "compile"


class RuntimeFault(FaultError):
    """Execution failure of an already-compiled program."""

    domain = "runtime"


class DonationFault(FaultError):
    """Buffer-donation violation: a donated input was reused, double-donated,
    or the donated twin failed where the plain twin would not."""

    domain = "donation"


class HostOffloadFault(FaultError):
    """Host-memory offload failure (``compute_on_cpu`` device→host moves,
    host-staged pending buffers)."""

    domain = "host"


class SyncFault(FaultError):
    """Distributed synchronisation failure: a cross-process gather/collective
    died or the sync configuration is invalid for the live world size."""

    domain = "sync"


class SyncConfigFault(SyncFault, ValueError):
    """Invalid sync *configuration* for the live world (e.g. a
    ``process_group`` index outside ``[0, world_size)`` at sync time).

    Also a ``ValueError`` so config-validation callers that predate the
    taxonomy keep catching it; structural, so never retried.
    """

    recoverable = False


class SyncTimeoutFault(SyncFault):
    """A blocking collective exceeded its watchdog deadline
    (``METRICS_TPU_SYNC_DEADLINE_MS``): a peer rank hung or died
    mid-collective. Raised by the watchdog *instead of hanging forever*;
    transient by nature (the peer may restart, the transport may heal), so
    the degraded-compute ladder may recover."""


class EpochFault(SyncFault):
    """A collective was attempted under a **stale world epoch**: membership
    changed (a peer was declared dead, or a rank rejoined) between the moment
    the sync protocol captured its epoch fence and the collective being
    issued. Raised by the fence *instead of pairing with the wrong cohort*
    (or hanging against a dead peer) — local state is intact and the sync is
    retryable at the current epoch, so the degraded-compute tier may catch it
    like any transport fault. Never retried inside one protocol attempt: the
    stale cohort can never pair, so the retry ladder re-raises it
    immediately (the caller re-enters at the current epoch instead)."""


class IngestFault(FaultError):
    """Ingestion-gateway admission failure: a payload was shed under overload
    (bounded staging watermarks, degraded-tier load shedding) or quarantined
    as poison (schema mismatch against the pinned fingerprint, NaN/Inf storm).
    Never surfaces mid-suite — the gateway settles every offered row into the
    accounting identity and routes the event through the taxonomy instead of
    raising into the caller's update loop."""

    domain = "ingest"


class JournalFault(FaultError):
    """State-journal failure: a record could not be written, or a stored
    record is torn / checksum-failed / layout-incompatible on load. Load
    corruption demotes to the previous good generation; only when every
    generation is bad does the classified fault surface to the caller."""

    domain = "journal"


__all__ = [
    "FAULT_DOMAINS",
    "CompileFault",
    "DonationFault",
    "EpochFault",
    "FaultError",
    "HostOffloadFault",
    "IngestFault",
    "JournalFault",
    "MetricsUserError",
    "RuntimeFault",
    "SyncConfigFault",
    "SyncFault",
    "SyncTimeoutFault",
    "TraceFault",
]
