"""User-facing error types.

Parity: reference `src/torchmetrics/utilities/exceptions.py:15-17`.
"""


class MetricsUserError(Exception):
    """Raised on incorrect use of the metrics API (e.g. double ``sync()``)."""


__all__ = ["MetricsUserError"]
