"""Array/data manipulation helpers shared across metrics.

Parity: reference `src/torchmetrics/utilities/data.py` (dim_zero_* at `:36-62`,
``to_onehot``/``select_topk``/``to_categorical``, ``apply_to_collection`` `:160`,
``get_group_indexes`` `:210-233`, ``_bincount`` `:244-264`).

TPU-first notes:
- every device op is a pure ``jnp`` function with static output shapes, so each is
  jit/vmap/shard_map-safe;
- ``_bincount`` needs no determinism workaround: XLA scatter-add is deterministic
  (the reference's CUDA fallback loop at `data.py:244-264` is dropped by design);
- ``get_group_indexes`` stays host-side (used only for eager grouping); the jitted
  path uses segment reductions from :mod:`metrics_tpu.ops.segments`.
"""
from __future__ import annotations

from collections.abc import Mapping, Sequence
from typing import Any, Callable, Dict, List, Optional, Union

import jax
import numpy as np
import jax.numpy as jnp
from jax import Array

TensorOrList = Union[Array, List[Array]]


def dim_zero_cat(x: TensorOrList) -> Array:
    """Concatenate a (possibly list-kind) state along dim 0."""
    # np.ndarray included: a post-reduction/restored state may be a bare HOST
    # array, which must not fall through to the list branch (whose emptiness
    # test would raise "truth value of an array is ambiguous")
    if isinstance(x, (jnp.ndarray, jax.Array, np.ndarray)) and not isinstance(x, (list, tuple)):
        return x
    x = [jnp.atleast_1d(v) for v in x]
    if not x:
        raise ValueError("No samples to concatenate")
    return jnp.concatenate(x, axis=0)


def dim_zero_cat_ravel(x: TensorOrList) -> Array:
    """Flatten each buffered row, then concatenate.

    The raw-row buffering paths (deferred canonicalization — see
    `Metric._canonicalize_list_states`) store rows of arbitrary rank; this
    canonicalizes them to one 1-D array in a single concat, accepting host
    numpy rows alongside device arrays. A post-sync reduced state (bare
    array) is flattened and returned as-is.
    """
    if isinstance(x, (jnp.ndarray, jax.Array, np.ndarray)) and not isinstance(x, (list, tuple)):
        return jnp.ravel(x)
    if not x:
        raise ValueError("No samples to concatenate")
    return jnp.concatenate([jnp.ravel(jnp.asarray(v)) for v in x])


def dim_zero_sum(x: Array) -> Array:
    return jnp.sum(x, axis=0)


def dim_zero_mean(x: Array) -> Array:
    return jnp.mean(x, axis=0)


def dim_zero_max(x: Array) -> Array:
    return jnp.max(x, axis=0)


def dim_zero_min(x: Array) -> Array:
    return jnp.min(x, axis=0)


def _flatten(x: Sequence) -> list:
    """One-level list flatten."""
    return [item for sublist in x for item in sublist]


def _flatten_dict(x: Dict) -> Dict:
    """Flatten dict-of-dicts one level; non-dict values pass through."""
    out: Dict = {}
    for key, value in x.items():
        if isinstance(value, dict):
            out.update(value)
        else:
            out[key] = value
    return out


def to_onehot(label_tensor: Array, num_classes: int) -> Array:
    """Integer labels ``(N, ...)`` -> one-hot ``(N, C, ...)``.

    Mirrors reference ``to_onehot`` (`utilities/data.py:65-106`) including the
    dim-1 insertion point for multi-dim inputs.
    """
    onehot = jax.nn.one_hot(label_tensor, num_classes, dtype=jnp.int32)
    # one_hot appends the class axis last; the convention is (N, C, extra...).
    return jnp.moveaxis(onehot, -1, 1)


def select_topk(prob_tensor: Array, topk: int = 1, dim: int = 1) -> Array:
    """Binary mask of the top-k entries along ``dim`` (reference `data.py:109-137`)."""
    if topk == 1:  # cheap argmax path
        idx = jnp.argmax(prob_tensor, axis=dim, keepdims=True)
        mask = jnp.zeros_like(prob_tensor, dtype=jnp.int32)
        return jnp.put_along_axis(mask, idx, 1, axis=dim, inplace=False)
    _, idx = jax.lax.top_k(jnp.moveaxis(prob_tensor, dim, -1), topk)
    mask = jnp.zeros(jnp.moveaxis(prob_tensor, dim, -1).shape, dtype=jnp.int32)
    mask = jnp.put_along_axis(mask, idx, 1, axis=-1, inplace=False)
    return jnp.moveaxis(mask, -1, dim)


def to_categorical(x: Array, argmax_dim: int = 1) -> Array:
    """Probabilities -> integer labels via argmax (reference `data.py:140-157`)."""
    return jnp.argmax(x, axis=argmax_dim)


def apply_to_collection(
    data: Any,
    dtype: Union[type, tuple],
    function: Callable,
    *args: Any,
    wrong_dtype: Optional[Union[type, tuple]] = None,
    **kwargs: Any,
) -> Any:
    """Recursively apply ``function`` to all ``dtype`` leaves of a collection.

    Parity: reference `utilities/data.py:160-207`.
    """
    if isinstance(data, dtype) and (wrong_dtype is None or not isinstance(data, wrong_dtype)):
        return function(data, *args, **kwargs)
    if isinstance(data, Mapping):
        return type(data)({k: apply_to_collection(v, dtype, function, *args, **kwargs) for k, v in data.items()})
    if isinstance(data, tuple) and hasattr(data, "_fields"):  # namedtuple
        return type(data)(*(apply_to_collection(d, dtype, function, *args, **kwargs) for d in data))
    if isinstance(data, Sequence) and not isinstance(data, str):
        return type(data)(apply_to_collection(d, dtype, function, *args, **kwargs) for d in data)
    return data


def get_group_indexes(indexes: Array) -> List[Array]:
    """Host-side grouping of sample rows by integer query id.

    Parity: reference `utilities/data.py:210-233`. Only valid on concrete arrays
    (eager epoch-end paths); jitted retrieval kernels use segment reductions
    instead (`metrics_tpu/ops/segments.py`).
    """
    import numpy as np

    idx = np.asarray(indexes)
    if idx.ndim != 1:
        idx = idx.reshape(-1)
    groups: Dict[int, List[int]] = {}
    for i, v in enumerate(idx.tolist()):
        groups.setdefault(int(v), []).append(i)
    return [jnp.asarray(v, dtype=jnp.int32) for v in groups.values()]


def _squeeze_if_scalar(data: Any) -> Any:
    return apply_to_collection(data, jax.Array, lambda x: jnp.squeeze(x) if x.ndim == 1 and x.shape[0] == 1 else x)


def _bincount(x: Array, minlength: int) -> Array:
    """Deterministic bincount with a static ``minlength`` (jit-safe).

    The reference needs a CUDA-determinism fallback (`utilities/data.py:244-264`);
    XLA scatter-add is deterministic so no workaround is needed. Delegates to
    :func:`metrics_tpu.ops.fused_bincount` so both the default XLA path and the
    opt-in Pallas MXU path (``METRICS_TPU_ENABLE_PALLAS=1``) share one
    semantics: out-of-range ids (e.g. ``ignore_index`` sentinels) are dropped,
    never clipped into bin 0.
    """
    from metrics_tpu.ops import fused_bincount

    return fused_bincount(x, minlength)


def allclose(x: Array, y: Array, rtol: float = 1e-5, atol: float = 1e-8) -> bool:
    if x.shape != y.shape:
        return False
    return bool(jnp.allclose(x, y, rtol=rtol, atol=atol))


# --------------------------------------------------------------- string states
# Text metrics accumulate sentences. To make them first-class syncable metric
# states (reference keeps python lists the sync engine can't see for chrf/bert),
# strings are packed into 1-D uint8 arrays using the bytes 0xFF (record
# separator) and 0xFE (group separator) — both invalid in UTF-8, so they can
# never collide with content. Packed arrays are closed under concatenation:
# cat(pack(a), pack(b)) == pack(a + b), which is exactly the "cat" state
# contract the cross-device gather protocol needs.
_REC_SEP = 0xFF
_GRP_SEP = 0xFE


def pack_strings(strings: Sequence[str]) -> np.ndarray:
    data = bytearray()
    for s in strings:
        data += s.encode("utf-8") + bytes([_REC_SEP])
    return np.frombuffer(bytes(data), dtype=np.uint8)


def unpack_strings(arr: Array) -> List[str]:
    b = bytes(bytearray(np.asarray(arr, dtype=np.uint8)))
    return [chunk.decode("utf-8") for chunk in b.split(bytes([_REC_SEP]))[:-1]]


def pack_string_groups(groups: Sequence[Sequence[str]]) -> np.ndarray:
    data = bytearray()
    for group in groups:
        for s in group:
            data += s.encode("utf-8") + bytes([_REC_SEP])
        data += bytes([_GRP_SEP])
    return np.frombuffer(bytes(data), dtype=np.uint8)


def unpack_string_groups(arr: Array) -> List[List[str]]:
    b = bytes(bytearray(np.asarray(arr, dtype=np.uint8)))
    return [
        [chunk.decode("utf-8") for chunk in group.split(bytes([_REC_SEP]))[:-1]]
        for group in b.split(bytes([_GRP_SEP]))[:-1]
    ]


__all__ = [
    "dim_zero_cat",
    "dim_zero_sum",
    "dim_zero_mean",
    "dim_zero_max",
    "dim_zero_min",
    "to_onehot",
    "select_topk",
    "to_categorical",
    "apply_to_collection",
    "get_group_indexes",
    "allclose",
    "pack_strings",
    "unpack_strings",
    "pack_string_groups",
    "unpack_string_groups",
]
