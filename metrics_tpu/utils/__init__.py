from metrics_tpu.utils.data import (
    apply_to_collection,
    dim_zero_cat,
    dim_zero_max,
    dim_zero_mean,
    dim_zero_min,
    dim_zero_sum,
    select_topk,
    to_categorical,
    to_onehot,
)
from metrics_tpu.utils.exceptions import MetricsUserError
from metrics_tpu.utils.prints import rank_zero_debug, rank_zero_info, rank_zero_only, rank_zero_warn

__all__ = [
    "apply_to_collection",
    "dim_zero_cat",
    "dim_zero_max",
    "dim_zero_mean",
    "dim_zero_min",
    "dim_zero_sum",
    "select_topk",
    "to_categorical",
    "to_onehot",
    "MetricsUserError",
    "rank_zero_debug",
    "rank_zero_info",
    "rank_zero_only",
    "rank_zero_warn",
]
