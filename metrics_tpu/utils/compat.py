"""jax version-drift shims, applied once at package import.

The codebase targets the jax that ships top-level ``jax.shard_map(f, mesh=…,
in_specs=…, out_specs=…, check_vma=…)``. Older/newer toolchain images in the
deployment fleet carry only ``jax.experimental.shard_map.shard_map`` (same
semantics; the replication check is spelled ``check_rep``). Installing the
alias here keeps every SPMD call site — ``__graft_entry__`` and the mesh
tests — source-identical across images.
"""
from __future__ import annotations

import jax

__all__ = ["install"]


def install() -> None:
    """Idempotently install missing jax aliases for this process."""
    if not hasattr(jax, "shard_map"):
        try:
            from jax.experimental.shard_map import shard_map as _shard_map
        except Exception:  # pragma: no cover — invlint: allow(INV201) — no shard_map anywhere: leave jax as-is (probe, not a fault)
            return

        def shard_map(f, mesh=None, in_specs=None, out_specs=None, check_vma=None, **kw):
            if check_vma is not None and "check_rep" not in kw:
                kw["check_rep"] = check_vma
            return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw)

        jax.shard_map = shard_map
