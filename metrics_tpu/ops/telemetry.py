"""Flight recorder: one telemetry plane for dispatch, sync, faults, journal.

Five subsystems grew their own counters (engine deferral, coalesced sync,
fault ladders, sync deadlines/degrade, the journal) but no shared *timeline*:
``engine_stats()`` says how many collectives ran, never when, how long, or
around which flush. This module is the missing plane, in three layers:

- **Span recorder** — a bounded ring of ``(step, owner, lane, site,
  t_start, dur, attrs)`` events. ``step`` is the SAME monotonic fault/sync
  event index the ``failure_log`` ring stamps (:func:`metrics_tpu.ops.faults
  .current_step`), so spans order against recorded faults without a second
  clock. Every instrumented boundary the stack already names emits here:
  engine enqueue/flush/build/compile/dispatch and the host fast lane, sync
  pack/metadata/payload-gather/unpack plus deadline timeouts, degraded
  serves and ladder demotions/promotions, journal save/load/demote. The
  hot-path contract mirrors ``faults.armed``: call sites guard with ``if
  telemetry.armed:`` — disarmed (``METRICS_TPU_TELEMETRY=0``) costs one
  module-attribute read and allocates nothing; armed, one span is one tuple
  append into a ``deque`` (the ``telemetry_overhead`` bench row pins
  armed≈disarmed on the hot deferred loop).

- **Reset registry** — every counter-owning module registers its zeroing
  callback here at import (:func:`register_reset`), so
  ``engine.reset_stats()`` resets the WHOLE plane through one walk instead
  of the historical per-module drift (engine zeroed its own counters;
  sync/fault resets were bolted on; the span ring would have been a third).
  ``reset_all(reset_warnings=True)`` additionally clears the
  ``faults.warn_fault`` once-per-owner dedupe markers (opt-in — chaos/CI
  sweeps re-observe warnings deterministically; default keeps the
  warn-once lifetime). The monotonic step index never resets.

- **Faces** — :func:`snapshot` (alias ``telemetry_snapshot``): ONE merged,
  schema-stable dict — a strict superset of ``engine_stats()`` (which
  already folds fault + sync + journal counters) plus the span-ring
  counters, the program-ledger summary and a global sync-health block —
  THE monitoring surface, with :func:`prometheus_text` rendering its
  numeric keys as a Prometheus-style exposition. :func:`export_trace`
  writes the ring as Chrome-trace/Perfetto JSON (one track per owner,
  nested slices; the program ledger joined under ``programLedger``) —
  summarized offline by ``tools/trace_report.py``. See
  docs/observability.md.
"""
from __future__ import annotations

import json
import os
import time
from collections import deque
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

__all__ = [
    "SPAN_SITES",
    "SYNC_PHASE_SITES",
    "armed",
    "clear_spans",
    "emit",
    "export_trace",
    "is_counter_key",
    "now",
    "prometheus_text",
    "register_reset",
    "register_warning_reset",
    "reset_all",
    "set_telemetry",
    "snapshot",
    "spans",
    "sync_phase_stats",
    "telemetry_stats",
]

#: Every instrumented span site, by subsystem — the rows of the
#: docs/observability.md site table. Instant sites (dur == 0) are marked.
SPAN_SITES = {
    # engine (ops/engine.py + the deferral layer)
    "engine-enqueue": "one eager call enqueued into a pending queue (instant)",
    "engine-flush": "a pending queue flushed as stacked scan program(s)",
    "engine-build": "a program-cache miss traced a new program (build closure)",
    "engine-compile": "a dispatch compiled a new aval signature (trace+compile+run wall)",
    "engine-dispatch": "one cached-program execution (dispatch wall; completion is async)",
    "host-lane": "one host fast-lane update (list append tier, instant)",
    # sync (parallel/sync.py + parallel/bucketing.py)
    "sync-pack": "coalesced pack: tree walk + bitcast-concat program",
    "sync-metadata": "coalesced metadata exchange (dyn-shape lane)",
    "sync-payload-gather": "coalesced payload all-gather",
    "sync-unpack": "coalesced unpack + reduce (donated program + dyn entries)",
    "sync-gather": "per-state gather_all_tensors exchange (shape + payload)",
    "sync-timeout": "a blocking collective hit the watchdog deadline (instant)",
    "sync-degrade-serve": "compute() served a local-only degraded value (instant)",
    "sync-quorum-serve": "compute() served the surviving-quorum aggregate (instant)",
    # world membership (parallel/sync.py + collections.py)
    "epoch-bump": "the world epoch advanced on a membership transition (instant)",
    "peer-dead": "a peer rank was declared dead (instant)",
    "peer-rejoin": "a rank's dead mark cleared in the membership registry (instant)",
    "rank-rejoin": "a restarted rank restored its journal and re-entered the world",
    "checkpoint-barrier": "a fleet-wide journal at one agreed monotonic step",
    # fault ladders (ops/faults.py)
    "fault": "one classified fault recorded (instant; mirrors failure_log)",
    "ladder-demote": "a per-owner lane demoted (instant)",
    "ladder-promote": "a per-owner lane re-promoted (instant)",
    # journal (ops/journal.py)
    "journal-save": "one crash-consistent record packed + atomically written",
    "journal-load": "one record verified + restored",
    "journal-demote": "a journal generation failed verification (instant)",
    # suite (collections.py)
    "suite-sync": "one whole-suite sync (coalesced + individual members)",
    # fleet plane (ops/fleetobs.py)
    "fleet-gather": "one fleet metadata/blob exchange (length + padded payload)",
    "fleet-snapshot": "one cross-rank snapshot gather + merge",
    "fleet-trace": "one cross-rank span-ring gather + merged trace export",
}

#: The sync-protocol phases the fleet straggler report attributes
#: (per-rank duration statistics reduced from the span ring — see
#: :func:`sync_phase_stats` and ``ops/fleetobs.py``).
SYNC_PHASE_SITES = (
    "sync-pack",
    "sync-metadata",
    "sync-payload-gather",
    "sync-unpack",
    "sync-gather",
    "suite-sync",
)

# ------------------------------------------------------------------ the gate
#: Hot-path guard (same shape as ``faults.armed``): call sites check this one
#: module attribute before calling :func:`emit`, so a disarmed recorder costs
#: a single predicate and allocates nothing.
armed: bool = os.environ.get("METRICS_TPU_TELEMETRY", "1") not in ("0", "false", "off")

_DEFAULT_CAP = 4096

#: Newest membership transitions carried in ``snapshot()['sync_health']`` —
#: bounded so the fleet gather's payload stays small (the full 64-entry log
#: stays on ``world_health()``).
_TRANSITIONS_CAP = 32


def _env_cap() -> int:
    try:
        return max(16, int(os.environ.get("METRICS_TPU_TELEMETRY_SPANS", str(_DEFAULT_CAP))))
    except ValueError:
        return _DEFAULT_CAP


_ring: "deque[tuple]" = deque(maxlen=_env_cap())
_emitted: List[int] = [0]  # list cell: emit() stays a closure-free hot path

#: Monotonic fault/sync event index provider — rebound by ``ops/faults`` at
#: import to its ``current_step`` so spans and ``failure_log`` entries share
#: one ordering axis (telemetry must not import faults: faults imports us).
_step_provider: Callable[[], int] = lambda: 0  # noqa: E731


def now() -> float:
    """The span clock (``time.perf_counter`` — monotonic, sub-µs)."""
    return time.perf_counter()


def set_telemetry(enabled: Optional[bool] = None, *, span_cap: Optional[int] = None) -> None:
    """Override the recorder at runtime (None leaves a knob unchanged; takes
    precedence over ``METRICS_TPU_TELEMETRY`` / ``_TELEMETRY_SPANS``).
    Shrinking ``span_cap`` re-rings the newest spans; the counters survive.

    Example:
        >>> from metrics_tpu import set_telemetry
        >>> set_telemetry(False)   # disarm: every site is one predicate check
        >>> set_telemetry(True, span_cap=4096)
    """
    global armed, _ring
    if enabled is not None:
        armed = bool(enabled)
    if span_cap is not None:
        cap = max(16, int(span_cap))
        if cap != _ring.maxlen:
            _ring = deque(_ring, maxlen=cap)


class _SpanRingWarnOwner:
    """Warn-dedupe anchor for the ring-overflow warning (``faults.warn_fault``
    keeps its once-per-domain marker on the owner instance)."""


_OVERFLOW_WARN_OWNER = _SpanRingWarnOwner()
_overflow_warned: List[bool] = [False]


def _warn_overflow() -> None:
    # no-silent-caps: truncation must be visible once. Runtime-deferred
    # import — faults imports us at module load, so the cold overflow branch
    # is the only place this module may reach back into it.
    from metrics_tpu.ops import faults as _faults

    _faults.warn_fault(
        _OVERFLOW_WARN_OWNER,
        "telemetry",
        f"The telemetry span ring overflowed its {_ring.maxlen}-span capacity; the oldest "
        "spans are being dropped (counted in spans_dropped). Raise METRICS_TPU_TELEMETRY_SPANS "
        "or set_telemetry(span_cap=...) to retain a longer window.",
    )


def _reset_overflow_warning() -> None:
    _overflow_warned[0] = False


def emit(
    site: str,
    owner: Any = None,
    lane: Optional[str] = None,
    t_start: float = 0.0,
    dur: float = 0.0,
    attrs: Optional[Dict[str, Any]] = None,
) -> None:
    """Record one span. Callers guard with ``if telemetry.armed:`` — this
    function assumes the recorder is armed and does no re-check, keeping the
    armed path at one tuple append. ``t_start=0.0`` stamps "now" (an instant
    event); ``owner`` may be the owning instance (stored as its type name)
    or a pre-rendered string."""
    _emitted[0] += 1
    if len(_ring) == _ring.maxlen and not _overflow_warned[0]:
        _overflow_warned[0] = True
        _warn_overflow()
    _ring.append(
        (
            _step_provider(),
            owner if (owner is None or type(owner) is str) else type(owner).__name__,
            lane,
            site,
            t_start if t_start else time.perf_counter(),
            dur,
            attrs,
        )
    )


_SPAN_KEYS = ("step", "owner", "lane", "site", "t_start", "dur", "attrs")


def spans() -> List[Dict[str, Any]]:
    """The recorded spans, oldest first, as schema-stable dicts (keys:
    ``step, owner, lane, site, t_start, dur, attrs``)."""
    return [dict(zip(_SPAN_KEYS, row)) for row in _ring]


def clear_spans() -> None:
    _ring.clear()
    _emitted[0] = 0


def sync_phase_stats() -> Dict[str, Dict[str, float]]:
    """Per-phase duration statistics for the sync-protocol span sites
    (:data:`SYNC_PHASE_SITES`), reduced from the current span ring — the
    per-rank plane the fleet straggler report compares across ranks
    (``ops/fleetobs.py``). Schema-stable: every phase is always present
    (zeros when no span of that site is retained); values are ring-windowed,
    so they can fall as old spans drop — gauges, never counters."""
    agg: Dict[str, Dict[str, float]] = {
        site: {"count": 0, "total_s": 0.0, "mean_s": 0.0, "max_s": 0.0}
        for site in SYNC_PHASE_SITES
    }
    for row in _ring:
        site, dur = row[3], row[5]
        if site not in agg or dur <= 0:
            continue
        d = agg[site]
        d["count"] += 1
        d["total_s"] += dur
        if dur > d["max_s"]:
            d["max_s"] = dur
    for d in agg.values():
        if d["count"]:
            d["mean_s"] = d["total_s"] / d["count"]
    return agg


def telemetry_stats() -> Dict[str, Any]:
    """Recorder-plane counters (merged into :func:`snapshot`)."""
    return {
        "telemetry_armed": armed,
        "spans_recorded": _emitted[0],
        "spans_retained": len(_ring),
        "spans_dropped": max(0, _emitted[0] - len(_ring)),
        "span_ring_cap": _ring.maxlen,
    }


# -------------------------------------------------------------- reset registry
_resets: List[Tuple[str, Callable[[], None]]] = []
_warning_resets: List[Tuple[str, Callable[[], None]]] = []


def _register(registry: List[Tuple[str, Callable[[], None]]], name: str, fn: Callable[[], None]) -> None:
    for i, (n, _) in enumerate(registry):
        if n == name:
            registry[i] = (name, fn)
            return
    registry.append((name, fn))


def register_reset(name: str, fn: Callable[[], None]) -> None:
    """Register one module's counter-zeroing callback (idempotent per name;
    modules call this at import). ``engine.reset_stats()`` walks the registry
    so no per-module reset can drift out of the set again."""
    _register(_resets, name, fn)


def register_warning_reset(name: str, fn: Callable[[], None]) -> None:
    """Register a warn-dedupe-clearing callback, run only under the explicit
    ``reset_warnings=True`` opt-in (warn-once markers outliving counter
    windows is the DEFAULT contract; chaos/CI sweeps opt out)."""
    _register(_warning_resets, name, fn)


def reset_all(reset_warnings: bool = False) -> None:
    """Zero every registered counter plane (spans included) in one walk.
    The monotonic fault/sync step index is deliberately NOT reset — each
    registered callback preserves it. ``reset_warnings=True`` additionally
    clears the registered warn-once dedupe markers."""
    for _, fn in _resets:
        fn()
    if reset_warnings:
        for _, fn in _warning_resets:
            fn()


register_reset("telemetry", clear_spans)
# overflow warn-once clears only under the explicit reset_warnings opt-in —
# a plain counter reset must not resurrect the truncation warning
register_warning_reset("telemetry", _reset_overflow_warning)


# --------------------------------------------------------------------- faces
def snapshot() -> Dict[str, Any]:
    """ONE merged, schema-stable monitoring dict — a strict superset of
    ``engine.engine_stats()``'s keys (cache + deferral + fault + sync +
    journal counters and the ``failure_log`` ring) plus:

    - the recorder counters (:func:`telemetry_stats`),
    - ``programs`` — the program-ledger summary (count, compiles, compile
      wall seconds, hits, donated/plain runs; per-program detail lives in
      ``engine.program_report()``),
    - ``sync_health`` — the global health block (monotonic event step,
      degraded serves, deadline timeouts, per-domain fault counts folded
      from the log),
    - ``snapshot_schema`` — bumped only on breaking key changes.

    This replaces the three-module counter scavenger hunt: scrape THIS (or
    its :func:`prometheus_text` rendering) and nothing else.

    Example:
        >>> from metrics_tpu import telemetry_snapshot
        >>> snap = telemetry_snapshot()
        >>> snap["snapshot_schema"]
        1
        >>> sorted(snap["programs"])
        ['compile_time_s', 'compiles', 'count', 'donated_runs', 'hits', 'plain_runs']
    """
    from metrics_tpu.ops import engine as _engine

    from metrics_tpu.parallel import sync as _world

    out: Dict[str, Any] = {"snapshot_schema": 1}
    out.update(_engine.engine_stats())
    out.update(telemetry_stats())
    out["monotonic_step"] = _step_provider()
    out["programs"] = _engine.program_summary()
    domain_counts: Dict[str, int] = {}
    for entry in out.get("failure_log", ()):
        domain_counts[entry["domain"]] = domain_counts.get(entry["domain"], 0) + 1
    wh = _world.world_health()
    last_good = wh.get("last_good_sync_step")
    out["sync_health"] = {
        "monotonic_step": _step_provider(),
        # every key below is a typed Prometheus gauge (prometheus_text
        # flattens this block as metrics_tpu_sync_health_*): the health
        # surface a scrape can alert on, not just raw event counters
        "degraded": bool(wh.get("degraded")),
        "epoch": int(wh.get("epoch", 0)),
        "dead_ranks": len(wh.get("dead_ranks") or ()),
        "consecutive_timeouts": int(wh.get("consecutive_timeouts", 0)),
        # -1 = "no full-world sync completed yet" (None would drop out of
        # the numeric exposition entirely, hiding exactly the alarming case)
        "last_good_sync_step": -1 if last_good is None else int(last_good),
        "sync_degraded_serves": out.get("sync_degraded_serves", 0),
        "sync_quorum_serves": out.get("sync_quorum_serves", 0),
        "sync_deadline_timeouts": out.get("sync_deadline_timeouts", 0),
        "fault_domain_counts": domain_counts,
        # the bounded membership transition log (epoch bumps, peer-dead /
        # rejoin records), each entry stamped with the shared monotonic step
        # — the fleet merge orders membership events against spans with it
        "transitions": [dict(t) for t in (wh.get("transitions") or ())[-_TRANSITIONS_CAP:]],
    }
    # per-phase sync span statistics (the straggler-attribution plane) —
    # ring-windowed gauges, one block per SYNC_PHASE_SITES entry
    out["sync_phase_stats"] = sync_phase_stats()
    return out


#: Exported name matching the issue-surface spelling; ``telemetry.snapshot()``
#: and ``telemetry.telemetry_snapshot()`` are the same callable.
telemetry_snapshot = snapshot


def _flat_numeric(prefix: str, value: Any) -> Iterator[Tuple[str, float]]:
    if isinstance(value, bool):
        yield prefix, 1.0 if value else 0.0
    elif isinstance(value, (int, float)) and value is not None:
        yield prefix, float(value)
    elif isinstance(value, dict):
        for k, v in value.items():
            key = f"{prefix}_{k}" if prefix else str(k)
            yield from _flat_numeric(key, v)


_COUNTER_PREFIXES = (
    "builds", "hits", "deferred_", "fault_", "sync_", "journal_", "fleet_",
    "spans_recorded", "spans_dropped", "monotonic_step",
)
# prefix matches that are NOT monotonically increasing (ratios recompute
# per scrape and can fall; counter semantics — rate()/reset detection —
# would read garbage off them)
_GAUGE_SUFFIXES = ("_ratio",)
# the flattened sync_health block is health STATE, not event counts: the
# degraded flag clears, dead ranks rejoin, suspicion resets — every key
# scrapes as a gauge even though the "sync_" prefix matches above. The
# sync_phase_stats block is ring-windowed (old spans drop), so its counts
# and totals can fall too.
_GAUGE_PREFIXES = ("sync_health_", "sync_phase_stats_")


def is_counter_key(key: str) -> bool:
    """Whether a flattened snapshot key carries monotonic counter semantics
    (vs gauge). The ONE classification the Prometheus exposition and the
    fleet merge (counters summed, gauges min/median/max — ``ops/fleetobs``)
    both ride, so a scrape and a fleet aggregate can never disagree about
    what a key means."""
    return (
        key.startswith(_COUNTER_PREFIXES)
        and not key.endswith(_GAUGE_SUFFIXES)
        and not key.startswith(_GAUGE_PREFIXES)
    )


def prometheus_text(data: Optional[Dict[str, Any]] = None) -> str:
    """Render :func:`snapshot` (or ``data``) as a Prometheus-style text
    exposition: every numeric key (nested dicts flattened with ``_``) becomes
    one ``metrics_tpu_<key> <value>`` sample with a ``# TYPE`` line
    (monotonic counters as ``counter``, the rest as ``gauge``). Non-numeric
    values (the failure log, per-program rows) are omitted — they belong to
    the trace, not the scrape.

    Example:
        >>> from metrics_tpu import prometheus_text
        >>> text = prometheus_text()
        >>> text.splitlines()[0].startswith("# TYPE metrics_tpu_")
        True
        >>> "metrics_tpu_sync_payload_collectives" in text
        True
    """
    data = snapshot() if data is None else data
    lines: List[str] = []
    for key, value in sorted(_flat_numeric("", {k: v for k, v in data.items() if k != "failure_log"})):
        name = "metrics_tpu_" + "".join(c if (c.isalnum() or c == "_") else "_" for c in key)
        kind = "counter" if is_counter_key(key) else "gauge"
        # integers render exactly ('%g' rounds to 6 significant digits — a
        # multi-MiB byte counter would scrape off by thousands); floats keep
        # repr's round-trip precision
        rendered = str(int(value)) if float(value).is_integer() else repr(float(value))
        lines.append(f"# TYPE {name} {kind}")
        lines.append(f"{name} {rendered}")
    return "\n".join(lines) + "\n"


def _json_safe(value: Any) -> Any:
    if isinstance(value, (str, int, float, bool)) or value is None:
        return value
    if isinstance(value, dict):
        return {str(k): _json_safe(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [_json_safe(v) for v in value]
    try:
        return float(value)  # numpy scalars
    except Exception:  # noqa: BLE001 — repr is always renderable
        return repr(value)


def trace_events(rows: Optional[List[Dict[str, Any]]] = None) -> List[Dict[str, Any]]:
    """The ring as Chrome-trace events: one ``pid``, one ``tid`` (track) per
    owner, complete (``ph: X``) slices for timed spans and instant (``ph:
    i``) marks for zero-duration ones, timestamps in µs from the earliest
    span — sorted, so Perfetto (and the export-round-trip test) sees
    monotonic ``ts``."""
    rows = spans() if rows is None else rows
    if not rows:
        return []
    t0 = min(r["t_start"] for r in rows)
    tids: Dict[str, int] = {}
    events: List[Dict[str, Any]] = []
    for r in rows:
        owner = r["owner"] or "global"
        tid = tids.setdefault(owner, len(tids) + 1)
        args: Dict[str, Any] = {"step": r["step"]}
        if r["lane"]:
            args["lane"] = r["lane"]
        if r["attrs"]:
            args.update(_json_safe(r["attrs"]))
        ev: Dict[str, Any] = {
            "name": r["site"],
            "cat": r["lane"] or "span",
            "pid": 0,
            "tid": tid,
            "ts": round((r["t_start"] - t0) * 1e6, 3),
            "args": args,
        }
        if r["dur"] > 0:
            ev["ph"] = "X"
            ev["dur"] = round(r["dur"] * 1e6, 3)
        else:
            ev["ph"] = "i"
            ev["s"] = "t"
        events.append(ev)
    events.sort(key=lambda e: e["ts"])
    meta: List[Dict[str, Any]] = [
        {"ph": "M", "name": "process_name", "pid": 0, "tid": 0, "ts": 0, "args": {"name": "metrics_tpu"}}
    ]
    for owner, tid in sorted(tids.items(), key=lambda kv: kv[1]):
        meta.append(
            {"ph": "M", "name": "thread_name", "pid": 0, "tid": tid, "ts": 0, "args": {"name": owner}}
        )
    return meta + events


def export_trace(path: str) -> int:
    """Write the recorded spans as a Chrome-trace/Perfetto JSON file — load
    it at https://ui.perfetto.dev (or ``chrome://tracing``) to see the whole
    run as a timeline: flush chunks, collectives and compiles as nested
    slices per owner track, instant marks for faults/demotions/timeouts.
    The program ledger rides along under ``programLedger`` and the numeric
    snapshot under ``snapshot`` (``tools/trace_report.py`` summarizes both).
    Returns the number of span events written.

    Example:
        >>> import os, tempfile
        >>> from metrics_tpu import export_trace
        >>> path = os.path.join(tempfile.mkdtemp(), "trace.json")
        >>> _ = export_trace(path)
        >>> os.path.exists(path)
        True
    """
    from metrics_tpu.ops import engine as _engine

    events = trace_events()
    snap = snapshot()
    doc = {
        "displayTimeUnit": "ms",
        "otherData": {"generator": "metrics_tpu.ops.telemetry", "schema": 1},
        "programLedger": _json_safe(_engine.program_report()),
        "snapshot": _json_safe({k: v for k, v in snap.items() if k != "failure_log"}),
        "traceEvents": events,
    }
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(doc, fh, separators=(",", ":"))
    return max(0, len(events) - sum(1 for e in events if e["ph"] == "M"))
