"""Flight recorder: one telemetry plane for dispatch, sync, faults, journal.

Five subsystems grew their own counters (engine deferral, coalesced sync,
fault ladders, sync deadlines/degrade, the journal) but no shared *timeline*:
``engine_stats()`` says how many collectives ran, never when, how long, or
around which flush. This module is the missing plane, in three layers:

- **Span recorder** — a bounded ring of ``(step, owner, lane, site,
  t_start, dur, attrs)`` events. ``step`` is the SAME monotonic fault/sync
  event index the ``failure_log`` ring stamps (:func:`metrics_tpu.ops.faults
  .current_step`), so spans order against recorded faults without a second
  clock. Every instrumented boundary the stack already names emits here:
  engine enqueue/flush/build/compile/dispatch and the host fast lane, sync
  pack/metadata/payload-gather/unpack plus deadline timeouts, degraded
  serves and ladder demotions/promotions, journal save/load/demote. The
  hot-path contract mirrors ``faults.armed``: call sites guard with ``if
  telemetry.armed:`` — disarmed (``METRICS_TPU_TELEMETRY=0``) costs one
  module-attribute read and allocates nothing; armed, one span is one tuple
  append into a ``deque`` (the ``telemetry_overhead`` bench row pins
  armed≈disarmed on the hot deferred loop).

- **Reset registry** — every counter-owning module registers its zeroing
  callback here at import (:func:`register_reset`), so
  ``engine.reset_stats()`` resets the WHOLE plane through one walk instead
  of the historical per-module drift (engine zeroed its own counters;
  sync/fault resets were bolted on; the span ring would have been a third).
  ``reset_all(reset_warnings=True)`` additionally clears the
  ``faults.warn_fault`` once-per-owner dedupe markers (opt-in — chaos/CI
  sweeps re-observe warnings deterministically; default keeps the
  warn-once lifetime). The monotonic step index never resets.

- **Latency histogram plane** — the ring answers "what happened recently";
  it cannot answer "what is p99 sync latency over this process's life",
  because old spans drop. Every *timed* span therefore also lands in a
  fixed log2-spaced-bucket histogram per site (:data:`_HIST_BOUNDS_S`, 1 µs
  to ~134 s plus ``+Inf``), accumulated for the FULL process lifetime —
  never windowed. The armed hot path stays one bucket-index increment per
  span emit (buckets preallocated per registered site, zero allocation);
  :func:`latency_stats` reads exact bucket counts plus interpolated
  p50/p95/p99 per site, :func:`prometheus_text` renders them as cumulative
  ``le``-labelled **histogram** families, and — bucket counts being plain
  counters — ``fleet_snapshot()`` sums them EXACTLY across ranks (the
  windowed phase means can only be min/median/max'd). Declared per-phase
  SLO budgets (``METRICS_TPU_SLO_<PHASE>_MS``) count violations through
  the ``slo_violations_*`` counter family and warn once per owner+phase.

- **Faces** — :func:`snapshot` (alias ``telemetry_snapshot``): ONE merged,
  schema-stable dict — a strict superset of ``engine_stats()`` (which
  already folds fault + sync + journal counters) plus the span-ring
  counters, the latency histogram plane, the program-ledger summary and a
  global sync-health block — THE monitoring surface, with
  :func:`prometheus_text` rendering its numeric keys as a Prometheus-style
  exposition (counter/gauge scalars plus the ``le``-labelled histogram
  families). :func:`export_trace` writes the ring as Chrome-trace/Perfetto
  JSON (one track per owner, nested slices; the program ledger joined
  under ``programLedger``) — summarized offline by
  ``tools/trace_report.py``. See docs/observability.md.
"""
from __future__ import annotations

import json
import os
import time
from bisect import bisect_left
from collections import deque
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

__all__ = [
    "LatencyHistogram",
    "SPAN_SITES",
    "SYNC_PHASE_SITES",
    "armed",
    "clear_spans",
    "device_dispatch_stats",
    "emit",
    "export_trace",
    "observe_device_dispatch",
    "is_counter_key",
    "is_histogram_sample_key",
    "latency_stats",
    "now",
    "prometheus_text",
    "register_reset",
    "register_warning_reset",
    "reset_all",
    "reset_latency",
    "set_telemetry",
    "slo_limit_s",
    "slo_violations",
    "snapshot",
    "spans",
    "sync_phase_stats",
    "telemetry_stats",
]

#: Every instrumented span site, by subsystem — the rows of the
#: docs/observability.md site table. Instant sites (dur == 0) are marked.
SPAN_SITES = {
    # engine (ops/engine.py + the deferral layer)
    "engine-enqueue": "one eager call enqueued into a pending queue (instant)",
    "engine-flush": "a pending queue flushed as stacked scan program(s)",
    "engine-build": "a program-cache miss traced a new program (build closure)",
    "engine-compile": "a dispatch compiled a new aval signature (trace+compile+run wall)",
    "engine-dispatch": "one cached-program execution (ASYNC host wall: the span ends "
    "when XLA accepts the dispatch, not when the device finishes — it "
    "under-measures device time; see device-dispatch)",
    "device-dispatch": "one sampled DEVICE-INCLUSIVE dispatch wall: a probed "
    "execution forced with block_until_ready (METRICS_TPU_DEVICE_PROBE_EVERY)",
    "host-lane": "one host fast-lane update (list append tier, instant)",
    # sync (parallel/sync.py + parallel/bucketing.py)
    "sync-pack": "coalesced pack: tree walk + bitcast-concat program",
    "sync-metadata": "coalesced metadata exchange (dyn-shape lane)",
    "sync-payload-gather": "coalesced payload all-gather (attr overlapped=true "
    "when it ran in flight on the async dispatcher thread)",
    "sync-unpack": "coalesced unpack + reduce (donated program + dyn entries)",
    "sync-gather": "per-state gather_all_tensors exchange (shape + payload)",
    "sync-dispatch": "async sync dispatched: pack + handoff to the dispatcher "
    "thread (the collective is now in flight)",
    "sync-force": "async sync forced: wait-for-wire + fence re-check + apply "
    "(attr waited_s = the wall actually blocked on)",
    "sync-quantize": "quantized payload lane encode (METRICS_TPU_SYNC_QUANT; "
    "attrs carry before/after bytes)",
    "sync-timeout": "a blocking collective hit the watchdog deadline (instant)",
    "sync-degrade-serve": "compute() served a local-only degraded value (instant)",
    "sync-quorum-serve": "compute() served the surviving-quorum aggregate (instant)",
    # world membership (parallel/sync.py + collections.py)
    "epoch-bump": "the world epoch advanced on a membership transition (instant)",
    "peer-dead": "a peer rank was declared dead (instant)",
    "peer-rejoin": "a rank's dead mark cleared in the membership registry (instant)",
    "rank-rejoin": "a restarted rank restored its journal and re-entered the world",
    "checkpoint-barrier": "a fleet-wide journal at one agreed monotonic step",
    # fault ladders (ops/faults.py)
    "fault": "one classified fault recorded (instant; mirrors failure_log)",
    "ladder-demote": "a per-owner lane demoted (instant)",
    "ladder-promote": "a per-owner lane re-promoted (instant)",
    # journal (ops/journal.py)
    "journal-save": "one crash-consistent record packed + atomically written",
    "journal-load": "one record verified + restored",
    "journal-demote": "a journal generation failed verification (instant)",
    # suite (collections.py)
    "suite-step": "one whole-suite update/forward call (enqueue + any nested flush)",
    "suite-sync": "one whole-suite sync (coalesced + individual members)",
    # fleet plane (ops/fleetobs.py)
    "fleet-gather": "one fleet metadata/blob exchange (length + padded payload)",
    "fleet-snapshot": "one cross-rank snapshot gather + merge",
    "fleet-trace": "one cross-rank span-ring gather + merged trace export",
    # streaming plane (streaming.py)
    "window-close": "one fleet-agreed window close: close-id agreement + "
    "payload sync + ring-slot pack (+ slot persistence when journaling)",
    "drift-report": "one PSI/KS drift computation over binned raw states",
    # functional core (functional_core.py)
    "funcore-handoff": "an in-graph state tree landed back into the stateful "
    "shell (epoch-fenced; pending async sync cancelled; instant)",
    # tenant arenas (arena.py)
    "arena-update": "one multi-tenant arena update: pow2-chunked gather + "
    "vmapped kernel + scatter over the stacked tenant states",
    "arena-close": "one arena-wide window close: fused per-cohort merge + "
    "vmapped compute + ring slot + live-tenant reset",
    "arena-journal": "one slab-granular arena save or restore (one CRC-framed "
    "record per slab, per-slab generation demotion)",
    # persistent program cache (ops/progcache.py)
    "progcache-load": "one persistent program-cache load: read + validate one "
    "CRC-framed entry, deserialize the exported module, AOT-compile the "
    "rehydration wrapper (XLA served from the compilation cache)",
    "progcache-store": "one persistent program-cache store: export + "
    "serialize a freshly compiled program, CRC-frame it, atomic write + "
    "size-capped LRU sweep",
    # ingestion gateway (ingest.py)
    "ingest-offer": "one payload offered at the gateway door: fingerprint "
    "check + stage/coalesce/shed/quarantine settlement",
    "ingest-flush": "one staging drain: staged payloads routed into target "
    "update() dispatches (arena pow2-chunked or suite deferral)",
    # kernel autotuner (ops/autotune.py)
    "autotune-sweep": "one variant sweep for a (kernel, shape class): every "
    "registered variant timed through real Executable dispatch, checked "
    "against the reference's exactness contract, scored vs roofline_peaks()",
    "autotune-install": "a sweep winner installed into the selection table "
    "(persisted into the progcache store when enabled; instant)",
    # FID host fallback (image/generative.py)
    "fid-host-sqrtm": "FID's host-side float64 fallback on non-f64 backends: "
    "covariances + eigh trace-sqrtm in numpy LAPACK (the wall perf_report "
    "attributes to the host phase)",
}

#: The sync-protocol phases the fleet straggler report attributes
#: (per-rank duration statistics reduced from the span ring — see
#: :func:`sync_phase_stats` and ``ops/fleetobs.py``).
SYNC_PHASE_SITES = (
    "sync-pack",
    "sync-metadata",
    "sync-payload-gather",
    "sync-unpack",
    "sync-gather",
    "suite-sync",
    "sync-dispatch",
    "sync-force",
    "sync-quantize",
)

# ------------------------------------------------------------------ the gate
#: Hot-path guard (same shape as ``faults.armed``): call sites check this one
#: module attribute before calling :func:`emit`, so a disarmed recorder costs
#: a single predicate and allocates nothing.
armed: bool = os.environ.get("METRICS_TPU_TELEMETRY", "1") not in ("0", "false", "off")

_DEFAULT_CAP = 4096

#: Newest membership transitions carried in ``snapshot()['sync_health']`` —
#: bounded so the fleet gather's payload stays small (the full 64-entry log
#: stays on ``world_health()``).
_TRANSITIONS_CAP = 32


class _TelemetryWarnOwner:
    """Warn-dedupe anchor for this module's env-knob parse warnings
    (``faults.warn_fault`` keeps its once-per-domain marker on the owner)."""


_ENV_WARN_OWNER = _TelemetryWarnOwner()

# Env parses that run at module-import time cannot reach ``faults.warn_fault``
# (faults imports us — warn_fault is not defined yet mid-import), so their
# warn-once messages queue here as ``(env_name, message)`` and drain at the
# first cold surface (``snapshot``/``latency_stats``/``set_telemetry``).
# warn_fault's owner+domain dedupe (domain = the env name) keeps each knob's
# warning at once per process.
_pending_env_warnings: List[Tuple[str, str]] = []


def _flush_env_warnings() -> None:
    if not _pending_env_warnings:
        return
    from metrics_tpu.ops import faults as _faults

    while _pending_env_warnings:
        env_name, message = _pending_env_warnings.pop(0)
        _faults.warn_fault(_ENV_WARN_OWNER, f"env:{env_name}", message)


def _env_cap() -> int:
    """Span-ring capacity (``METRICS_TPU_TELEMETRY_SPANS``). The same
    warn-once contract as ``parallel/sync.py``'s ``_env_int``: unset/blank
    is the default, a garbage value warns once NAMING the offending value
    (queued — this runs at import) and falls back to the default."""
    raw = os.environ.get("METRICS_TPU_TELEMETRY_SPANS")
    if raw is None or not raw.strip():
        return _DEFAULT_CAP
    try:
        return max(16, int(raw))
    except ValueError:
        _pending_env_warnings.append(
            (
                "METRICS_TPU_TELEMETRY_SPANS",
                f"METRICS_TPU_TELEMETRY_SPANS={raw!r} is not an integer; falling back "
                f"to the default span-ring capacity ({_DEFAULT_CAP}).",
            )
        )
        return _DEFAULT_CAP


_ring: "deque[tuple]" = deque(maxlen=_env_cap())
_emitted: List[int] = [0]  # list cell: emit() stays a closure-free hot path

#: Monotonic fault/sync event index provider — rebound by ``ops/faults`` at
#: import to its ``current_step`` so spans and ``failure_log`` entries share
#: one ordering axis (telemetry must not import faults: faults imports us).
_step_provider: Callable[[], int] = lambda: 0  # noqa: E731


def now() -> float:
    """The span clock (``time.perf_counter`` — monotonic, sub-µs)."""
    return time.perf_counter()


# ------------------------------------------------------- latency histograms
#: Log2-spaced latency bucket UPPER bounds in seconds (1 µs doubling to
#: ~134 s; observations above the last bound land in the implicit ``+Inf``
#: bucket). The ONE layout every latency histogram rides — the per-site
#: plane, the bench-row histograms and the fleet merge — kept a PURE literal
#: so ``tools/invlint/registry.py`` can extract it statically (INV303:
#: bounds must stay positive and strictly increasing, or the cumulative
#: ``le`` exposition stops being monotone).
_HIST_BOUNDS_S = (
    1e-06, 2e-06, 4e-06, 8e-06, 1.6e-05, 3.2e-05, 6.4e-05, 0.000128,
    0.000256, 0.000512, 0.001024, 0.002048, 0.004096, 0.008192, 0.016384,
    0.032768, 0.065536, 0.131072, 0.262144, 0.524288, 1.048576, 2.097152,
    4.194304, 8.388608, 16.777216, 33.554432, 67.108864, 134.217728,
)
#: Prometheus family stem for the per-site histograms
#: (``metrics_tpu_latency_seconds{site=...,le=...}``).
_HIST_FAMILY = "latency_seconds"
#: The snapshot key the plane lives under; its flattened sample keys
#: (``latency_stats_<site>_buckets_<le>`` / ``_count`` / ``_sum_s``) MUST
#: classify as counters (``is_counter_key``) so the fleet merge sums them
#: exactly — INV303 pins that statically.
_HIST_SNAPSHOT_KEY = "latency_stats"

#: Bucket labels: one ``le`` label per finite bound (its repr — exact float
#: round-trip), then ``+Inf``. Order IS the cumulative exposition order.
_HIST_LABELS = tuple(repr(b) for b in _HIST_BOUNDS_S) + ("+Inf",)
_N_BUCKETS = len(_HIST_BOUNDS_S) + 1

#: The per-PROGRAM device-time histogram family prefix: every sampled
#: device-inclusive dispatch (``METRICS_TPU_DEVICE_PROBE_EVERY``) lands both
#: in the aggregate ``device-dispatch`` site histogram and in a per-program
#: site named ``device-dispatch:<program>`` (program = the executable's kind
#: plus its cache-key digest), so :func:`latency_stats` / the fleet merge /
#: the exposition carry per-program device percentiles on the SAME bucket
#: layout. Kept a PURE literal so ``tools/invlint/registry.py`` extracts it
#: statically (INV303 pins that the derived sample keys classify as
#: counters and that the prefix stays label-safe).
_DEVICE_HIST_SITE = "device-dispatch"


def _bucket_quantile(counts: List[int], total: int, q: float, max_s: float) -> float:
    """Interpolated quantile from per-bucket counts: find the bucket holding
    rank ``q*total`` and interpolate linearly inside it (a log2 bucket is at
    most 2x wide, so the estimate is within 2x of exact — the documented
    resolution caveat). The ``+Inf`` bucket (and every estimate) clamps to
    the exact observed maximum."""
    rank = q * total
    cum = 0.0
    for i, c in enumerate(counts):
        if not c:
            continue
        prev = cum
        cum += c
        if cum >= rank:
            lo = _HIST_BOUNDS_S[i - 1] if i > 0 else 0.0
            hi = _HIST_BOUNDS_S[i] if i < len(_HIST_BOUNDS_S) else max_s
            est = lo + (hi - lo) * ((rank - prev) / c)
            return min(max_s, est) if max_s > 0 else est
    return max_s


class LatencyHistogram:
    """One fixed log2-bucket latency histogram on the shared layout
    (:data:`_HIST_BOUNDS_S`). The per-site plane, the bench rows
    (``bench.py`` / ``tools/bench_sweep.py`` percentile columns) and the
    fleet merge all ride instances of this class, so every percentile the
    tree reports is computed the same way.

    Example:
        >>> from metrics_tpu.ops.telemetry import LatencyHistogram
        >>> h = LatencyHistogram()
        >>> for ms in (1, 1, 2, 40):
        ...     h.observe(ms / 1000.0)
        >>> block = h.stats()
        >>> block["count"], block["max_s"]
        (4, 0.04)
        >>> block["p50_s"] <= block["p95_s"] <= block["p99_s"] <= block["max_s"]
        True
    """

    __slots__ = ("counts", "sum_s", "max_s")

    def __init__(self) -> None:
        self.counts: List[int] = [0] * _N_BUCKETS
        self.sum_s = 0.0
        self.max_s = 0.0

    def observe(self, dur_s: float) -> None:
        """Record one duration (seconds; non-positive values are ignored —
        instants carry no latency). One bucket-index increment."""
        if dur_s <= 0.0:
            return
        self.counts[bisect_left(_HIST_BOUNDS_S, dur_s)] += 1
        self.sum_s += dur_s
        if dur_s > self.max_s:
            self.max_s = dur_s

    def stats(self) -> Dict[str, Any]:
        """The schema-stable per-site block: exact ``count``/``sum_s``/
        ``max_s``/``buckets`` (counters — the fleet merge sums them) plus
        interpolated ``p50_s``/``p95_s``/``p99_s`` (gauges)."""
        total = sum(self.counts)
        block: Dict[str, Any] = {
            "count": total,
            "sum_s": self.sum_s,
            "max_s": self.max_s,
            "p50_s": 0.0,
            "p95_s": 0.0,
            "p99_s": 0.0,
            "buckets": dict(zip(_HIST_LABELS, self.counts)),
        }
        if total:
            for q, key in ((0.5, "p50_s"), (0.95, "p95_s"), (0.99, "p99_s")):
                block[key] = _bucket_quantile(self.counts, total, q, self.max_s)
        return block


#: The per-site plane, preallocated for every registered site so the armed
#: hot path never allocates (an unregistered site allocates once, cold).
_site_hists: Dict[str, LatencyHistogram] = {site: LatencyHistogram() for site in SPAN_SITES}


# ------------------------------------------------------------- SLO budgets
class _SLOWarnOwner:
    """Per-site warn-dedupe anchor for SLO violations emitted with no owner
    instance (``faults.warn_fault`` stores its marker on the owner)."""


_SLO_UNSET = object()
#: site -> parsed budget in seconds (None = no SLO declared/off). Lazily
#: filled on a site's first timed span; cleared by :func:`reset_latency` so
#: tests and redeploys re-read the environment.
_slo_limits: Dict[str, Any] = {}
_slo_violations: Dict[str, int] = {}
_slo_warn_owners: Dict[str, _SLOWarnOwner] = {}


def _slo_env_name(site: str) -> str:
    return "METRICS_TPU_SLO_" + site.upper().replace("-", "_") + "_MS"


def slo_limit_s(site: str) -> Optional[float]:
    """The declared latency budget for ``site`` in seconds
    (``METRICS_TPU_SLO_<PHASE>_MS`` with the site name uppercased and
    ``-`` -> ``_``; e.g. ``METRICS_TPU_SLO_SYNC_PAYLOAD_GATHER_MS=80``), or
    None when unset/non-positive. An unparseable value warns once (naming
    the offending value) and leaves the budget OFF. Cached per site until
    :func:`reset_latency`."""
    limit = _slo_limits.get(site, _SLO_UNSET)
    if limit is not _SLO_UNSET:
        return limit
    env_name = _slo_env_name(site)
    raw = os.environ.get(env_name)
    limit = None
    if raw is not None and raw.strip():
        try:
            ms = float(raw)
            limit = ms / 1000.0 if ms > 0 else None
        except (TypeError, ValueError):
            # cold (once per site): runtime-deferred faults import, the same
            # seam the ring-overflow warning uses
            from metrics_tpu.ops import faults as _faults

            _faults.warn_fault(
                _ENV_WARN_OWNER,
                f"env:{env_name}",
                f"{env_name}={raw!r} is not a number; the {site} latency SLO stays OFF.",
            )
    _slo_limits[site] = limit
    return limit


def _note_slo_violation(site: str, owner: Any, dur: float, limit: float) -> None:
    """Count one budget violation and warn ONCE per owner+phase (the warn
    marker rides the emitting owner when there is one, else a per-site
    module anchor; ``reset_stats(reset_warnings=True)`` re-arms it)."""
    _slo_violations[site] = _slo_violations.get(site, 0) + 1
    from metrics_tpu.ops import faults as _faults

    if owner is None or type(owner) is str:
        anchor = _slo_warn_owners.get(site)
        if anchor is None:
            anchor = _slo_warn_owners[site] = _SLOWarnOwner()
    else:
        anchor = owner
    _faults.warn_fault(
        anchor,
        f"slo:{site}",
        f"The {site} span ran {dur * 1e3:.3f} ms, over its declared "
        f"{limit * 1e3:.3f} ms budget ({_slo_env_name(site)}); violations count "
        "in the slo_violations_* family and in sync_health.",
    )


def set_telemetry(enabled: Optional[bool] = None, *, span_cap: Optional[int] = None) -> None:
    """Override the recorder at runtime (None leaves a knob unchanged; takes
    precedence over ``METRICS_TPU_TELEMETRY`` / ``_TELEMETRY_SPANS``).
    Shrinking ``span_cap`` re-rings the newest spans; the counters survive.

    Example:
        >>> from metrics_tpu import set_telemetry
        >>> set_telemetry(False)   # disarm: every site is one predicate check
        >>> set_telemetry(True, span_cap=4096)
    """
    global armed, _ring
    _flush_env_warnings()
    if enabled is not None:
        armed = bool(enabled)
    if span_cap is not None:
        cap = max(16, int(span_cap))
        if cap != _ring.maxlen:
            _ring = deque(_ring, maxlen=cap)


class _SpanRingWarnOwner:
    """Warn-dedupe anchor for the ring-overflow warning (``faults.warn_fault``
    keeps its once-per-domain marker on the owner instance)."""


_OVERFLOW_WARN_OWNER = _SpanRingWarnOwner()
_overflow_warned: List[bool] = [False]


def _warn_overflow() -> None:
    # no-silent-caps: truncation must be visible once. Runtime-deferred
    # import — faults imports us at module load, so the cold overflow branch
    # is the only place this module may reach back into it.
    from metrics_tpu.ops import faults as _faults

    _faults.warn_fault(
        _OVERFLOW_WARN_OWNER,
        "telemetry",
        f"The telemetry span ring overflowed its {_ring.maxlen}-span capacity; the oldest "
        "spans are being dropped (counted in spans_dropped). Raise METRICS_TPU_TELEMETRY_SPANS "
        "or set_telemetry(span_cap=...) to retain a longer window.",
    )


def _reset_overflow_warning() -> None:
    _overflow_warned[0] = False


def emit(
    site: str,
    owner: Any = None,
    lane: Optional[str] = None,
    t_start: float = 0.0,
    dur: float = 0.0,
    attrs: Optional[Dict[str, Any]] = None,
) -> None:
    """Record one span. Callers guard with ``if telemetry.armed:`` — this
    function assumes the recorder is armed and does no re-check, keeping the
    armed path at one tuple append (plus, for timed spans only, one bucket
    increment into the site's full-lifetime latency histogram and the SLO
    budget check). ``t_start=0.0`` stamps "now" (an instant event);
    ``owner`` may be the owning instance (stored as its type name) or a
    pre-rendered string."""
    _emitted[0] += 1
    if len(_ring) == _ring.maxlen and not _overflow_warned[0]:
        _overflow_warned[0] = True
        _warn_overflow()
    _ring.append(
        (
            _step_provider(),
            owner if (owner is None or type(owner) is str) else type(owner).__name__,
            lane,
            site,
            t_start if t_start else time.perf_counter(),
            dur,
            attrs,
        )
    )
    if dur > 0.0:
        # full-lifetime latency plane: instants (dur == 0) carry no latency
        # and skip this entirely, so the hottest site (engine-enqueue) pays
        # nothing. Registered sites are preallocated — zero allocation here.
        h = _site_hists.get(site)
        if h is None:  # unregistered site: allocate once, cold
            h = _site_hists.setdefault(site, LatencyHistogram())
        h.counts[bisect_left(_HIST_BOUNDS_S, dur)] += 1
        h.sum_s += dur
        if dur > h.max_s:
            h.max_s = dur
        limit = _slo_limits.get(site, _SLO_UNSET)
        if limit is _SLO_UNSET:
            limit = slo_limit_s(site)
        if limit is not None and dur > limit:
            _note_slo_violation(site, owner, dur, limit)


def observe_device_dispatch(program: str, t_start: float, dur_s: float) -> None:
    """Land one PROBED, device-inclusive dispatch wall (``engine``'s sampled
    ``block_until_ready`` path). Two observations from one measurement:

    - a timed ``device-dispatch`` span (aggregate site histogram + trace
      slice + SLO budget, via :func:`emit` — distinct from the async
      host-wall ``engine-dispatch`` span, which starts at the same instant
      but ends when XLA *accepts* the dispatch);
    - the per-program full-lifetime family ``device-dispatch:<program>``
      (:data:`_DEVICE_HIST_SITE`), the probed-latency plane
      ``engine.program_report()`` joins with XLA cost analysis into the
      roofline ledger.

    Callers guard with ``if telemetry.armed:`` like every other emit site.
    """
    emit(_DEVICE_HIST_SITE, program, "engine", t_start, dur_s, {"program": program})
    site = _DEVICE_HIST_SITE + ":" + program
    h = _site_hists.get(site)
    if h is None:  # one cold allocation per program, never on later probes
        h = _site_hists.setdefault(site, LatencyHistogram())
    h.observe(dur_s)


def device_dispatch_stats() -> Dict[str, Dict[str, Any]]:
    """The per-program probed device-time plane: ``{program: stats block}``
    for every ``device-dispatch:<program>`` family with at least one probe
    (same block schema as :func:`latency_stats` sites)."""
    prefix = _DEVICE_HIST_SITE + ":"
    out: Dict[str, Dict[str, Any]] = {}
    for site in sorted(_site_hists):
        if site.startswith(prefix):
            h = _site_hists[site]
            if h.max_s > 0.0:
                out[site[len(prefix):]] = h.stats()
    return out


_SPAN_KEYS = ("step", "owner", "lane", "site", "t_start", "dur", "attrs")


def spans() -> List[Dict[str, Any]]:
    """The recorded spans, oldest first, as schema-stable dicts (keys:
    ``step, owner, lane, site, t_start, dur, attrs``)."""
    return [dict(zip(_SPAN_KEYS, row)) for row in _ring]


def clear_spans() -> None:
    _ring.clear()
    _emitted[0] = 0


def sync_phase_stats() -> Dict[str, Dict[str, float]]:
    """Per-phase duration statistics for the sync-protocol span sites
    (:data:`SYNC_PHASE_SITES`), reduced from the current span ring — the
    per-rank plane the fleet straggler report compares across ranks
    (``ops/fleetobs.py``). Schema-stable: every phase is always present
    (zeros when no span of that site is retained); values are ring-windowed,
    so they can fall as old spans drop — gauges, never counters."""
    agg: Dict[str, Dict[str, float]] = {
        site: {"count": 0, "total_s": 0.0, "mean_s": 0.0, "max_s": 0.0}
        for site in SYNC_PHASE_SITES
    }
    for row in _ring:
        site, dur = row[3], row[5]
        if site not in agg or dur <= 0:
            continue
        d = agg[site]
        d["count"] += 1
        d["total_s"] += dur
        if dur > d["max_s"]:
            d["max_s"] = dur
    for d in agg.values():
        if d["count"]:
            d["mean_s"] = d["total_s"] / d["count"]
    return agg


def latency_stats() -> Dict[str, Dict[str, Any]]:
    """The full-lifetime latency histogram plane: one block per span site
    that has observed at least one timed span (sites with no observations
    are omitted — the fleet gather must not ship ~30 all-zero histograms),
    each with exact ``count``/``sum_s``/``max_s``/``buckets`` (counters:
    never windowed, summed exactly across ranks by ``fleet_snapshot()``)
    and bucket-interpolated ``p50_s``/``p95_s``/``p99_s`` (gauges; a log2
    bucket is at most 2x wide — see docs/observability.md for the
    resolution caveat). Unlike :func:`sync_phase_stats` these never decay
    when old spans drop from the ring.

    Example:
        >>> from metrics_tpu.ops import telemetry
        >>> telemetry.emit("suite-sync", None, "sync", telemetry.now(), 0.002)
        >>> block = telemetry.latency_stats()["suite-sync"]
        >>> block["count"] >= 1 and block["buckets"]["0.002048"] >= 1
        True
    """
    _flush_env_warnings()
    out: Dict[str, Dict[str, Any]] = {}
    for site in sorted(_site_hists):
        h = _site_hists[site]
        if h.max_s > 0.0:
            out[site] = h.stats()
    return out


def slo_violations() -> Dict[str, int]:
    """Per-site SLO budget violation counts (plus ``total``) — the
    ``slo_violations_*`` counter family."""
    out = {"total": sum(_slo_violations.values())}
    for site in sorted(_slo_violations):
        out[site] = _slo_violations[site]
    return out


def reset_latency() -> None:
    """Zero the latency histogram plane and the SLO violation counters, and
    drop the cached SLO budgets so the environment is re-read (part of the
    registered ``engine.reset_stats()`` walk; warn-once markers survive
    unless ``reset_warnings=True``)."""
    for h in _site_hists.values():
        h.counts = [0] * _N_BUCKETS
        h.sum_s = 0.0
        h.max_s = 0.0
    _slo_violations.clear()
    _slo_limits.clear()


def telemetry_stats() -> Dict[str, Any]:
    """Recorder-plane counters (merged into :func:`snapshot`)."""
    return {
        "telemetry_armed": armed,
        "spans_recorded": _emitted[0],
        "spans_retained": len(_ring),
        "spans_dropped": max(0, _emitted[0] - len(_ring)),
        "span_ring_cap": _ring.maxlen,
    }


# -------------------------------------------------------------- reset registry
_resets: List[Tuple[str, Callable[[], None]]] = []
_warning_resets: List[Tuple[str, Callable[[], None]]] = []


def _register(registry: List[Tuple[str, Callable[[], None]]], name: str, fn: Callable[[], None]) -> None:
    for i, (n, _) in enumerate(registry):
        if n == name:
            registry[i] = (name, fn)
            return
    registry.append((name, fn))


def register_reset(name: str, fn: Callable[[], None]) -> None:
    """Register one module's counter-zeroing callback (idempotent per name;
    modules call this at import). ``engine.reset_stats()`` walks the registry
    so no per-module reset can drift out of the set again."""
    _register(_resets, name, fn)


def register_warning_reset(name: str, fn: Callable[[], None]) -> None:
    """Register a warn-dedupe-clearing callback, run only under the explicit
    ``reset_warnings=True`` opt-in (warn-once markers outliving counter
    windows is the DEFAULT contract; chaos/CI sweeps opt out)."""
    _register(_warning_resets, name, fn)


def reset_all(reset_warnings: bool = False) -> None:
    """Zero every registered counter plane (spans included) in one walk.
    The monotonic fault/sync step index is deliberately NOT reset — each
    registered callback preserves it. ``reset_warnings=True`` additionally
    clears the registered warn-once dedupe markers."""
    for _, fn in _resets:
        fn()
    if reset_warnings:
        for _, fn in _warning_resets:
            fn()


def _reset_telemetry_plane() -> None:
    clear_spans()
    reset_latency()


register_reset("telemetry", _reset_telemetry_plane)
# overflow warn-once clears only under the explicit reset_warnings opt-in —
# a plain counter reset must not resurrect the truncation warning
register_warning_reset("telemetry", _reset_overflow_warning)


# --------------------------------------------------------------------- faces
def snapshot() -> Dict[str, Any]:
    """ONE merged, schema-stable monitoring dict — a strict superset of
    ``engine.engine_stats()``'s keys (cache + deferral + fault + sync +
    journal counters and the ``failure_log`` ring) plus:

    - the recorder counters (:func:`telemetry_stats`),
    - ``programs`` — the program-ledger summary (count, compiles, compile
      wall seconds, hits, donated/plain runs; per-program detail lives in
      ``engine.program_report()``),
    - ``sync_health`` — the global health block (monotonic event step,
      degraded serves, deadline timeouts, per-domain fault counts folded
      from the log),
    - ``snapshot_schema`` — bumped only on breaking key changes.

    This replaces the three-module counter scavenger hunt: scrape THIS (or
    its :func:`prometheus_text` rendering) and nothing else.

    Example:
        >>> from metrics_tpu import telemetry_snapshot
        >>> snap = telemetry_snapshot()
        >>> snap["snapshot_schema"]
        1
        >>> sorted(snap["programs"])  # doctest: +NORMALIZE_WHITESPACE
        ['cache_load_time_s', 'compile_time_s', 'compiles', 'count',
         'donated_runs', 'hits', 'plain_runs']
    """
    from metrics_tpu.ops import engine as _engine

    from metrics_tpu.parallel import sync as _world

    _flush_env_warnings()
    out: Dict[str, Any] = {"snapshot_schema": 1}
    out.update(_engine.engine_stats())
    out.update(telemetry_stats())
    out["monotonic_step"] = _step_provider()
    out["programs"] = _engine.program_summary()
    domain_counts: Dict[str, int] = {}
    for entry in out.get("failure_log", ()):
        domain_counts[entry["domain"]] = domain_counts.get(entry["domain"], 0) + 1
    wh = _world.world_health()
    last_good = wh.get("last_good_sync_step")
    out["sync_health"] = {
        "monotonic_step": _step_provider(),
        # every key below is a typed Prometheus gauge (prometheus_text
        # flattens this block as metrics_tpu_sync_health_*): the health
        # surface a scrape can alert on, not just raw event counters
        "degraded": bool(wh.get("degraded")),
        "epoch": int(wh.get("epoch", 0)),
        "dead_ranks": len(wh.get("dead_ranks") or ()),
        "consecutive_timeouts": int(wh.get("consecutive_timeouts", 0)),
        # -1 = "no full-world sync completed yet" (None would drop out of
        # the numeric exposition entirely, hiding exactly the alarming case)
        "last_good_sync_step": -1 if last_good is None else int(last_good),
        "sync_degraded_serves": out.get("sync_degraded_serves", 0),
        "sync_quorum_serves": out.get("sync_quorum_serves", 0),
        "sync_deadline_timeouts": out.get("sync_deadline_timeouts", 0),
        # total SLO budget violations, folded in as health STATE (the
        # per-phase counter family lives under slo_violations_*)
        "slo_violations": sum(_slo_violations.values()),
        "fault_domain_counts": domain_counts,
        # the in-flight async-sync block (dispatched, not yet forced): count,
        # the oldest future's age in monotonic steps, and its dispatch epoch
        # — a dispatch epoch behind the live epoch means the force WILL
        # fence-trip; every key is a gauge (futures force and leave)
        "inflight": _world.inflight_stats(),
        # the bounded membership transition log (epoch bumps, peer-dead /
        # rejoin records), each entry stamped with the shared monotonic step
        # — the fleet merge orders membership events against spans with it
        "transitions": [dict(t) for t in (wh.get("transitions") or ())[-_TRANSITIONS_CAP:]],
    }
    # per-phase sync span statistics (the straggler-attribution plane) —
    # ring-windowed gauges, one block per SYNC_PHASE_SITES entry
    out["sync_phase_stats"] = sync_phase_stats()
    # the full-lifetime latency histogram plane (exact bucket counters +
    # interpolated percentiles) and the SLO violation counter family —
    # additive keys: the snapshot stays a strict engine_stats superset
    out[_HIST_SNAPSHOT_KEY] = latency_stats()
    out["slo_violations"] = slo_violations()
    # the model-monitoring plane: per-window ids/boundaries/values and drift
    # scores (streaming.py). The window_*/drift_* EVENT counters already rode
    # in through engine_stats(); this block is window STATE — its flattened
    # keys start "streaming_" and scrape as gauges (window values and drift
    # scores move both ways)
    from metrics_tpu import streaming as _streaming

    out["streaming"] = _streaming.streaming_snapshot()
    # the ingestion-gateway plane: staging occupancy, degraded flags and
    # quarantine depth per live gateway (ingest.py). The ingest_* EVENT
    # counters already rode in through engine_stats(); this block is gateway
    # STATE — its flattened keys start "ingest_state_" and scrape as gauges
    # (staging drains, degraded clears, quarantine rings rotate)
    from metrics_tpu import ingest as _ingest

    out["ingest_state"] = _ingest.ingest_state()
    return out


#: Exported name matching the issue-surface spelling; ``telemetry.snapshot()``
#: and ``telemetry.telemetry_snapshot()`` are the same callable.
telemetry_snapshot = snapshot


def _flat_numeric(prefix: str, value: Any) -> Iterator[Tuple[str, float]]:
    if isinstance(value, bool):
        yield prefix, 1.0 if value else 0.0
    elif isinstance(value, (int, float)) and value is not None:
        yield prefix, float(value)
    elif isinstance(value, dict):
        for k, v in value.items():
            key = f"{prefix}_{k}" if prefix else str(k)
            yield from _flat_numeric(key, v)


_COUNTER_PREFIXES = (
    "builds", "hits", "deferred_", "fault_", "sync_", "journal_", "fleet_",
    "latency_", "slo_", "spans_recorded", "spans_dropped", "monotonic_step",
    # the performance-attribution plane: device-probe events, memoized
    # program cost-analysis lowers, perf-report invocations — all monotonic
    "device_", "program_", "perf_",
    # the streaming plane's event counters: window closes / slots packed /
    # ring demotions / epoch trips, drift reports (streaming.py)
    "window_", "drift_",
    # the functional core's host-visible events: export builds/hits, API
    # calls (eager or trace-time), hand-backs (functional_core.py)
    "funcore_",
    # the tenant-arena plane: lifecycle, vmapped program traffic, slab
    # journal bytes/demotions (arena.py)
    "arena_",
    # the persistent program cache: entry hits/misses/stores, classified
    # demotions, size-cap evictions (ops/progcache.py)
    "progcache_",
    # the ingestion gateway's settlement counters: offered / admitted /
    # coalesced / shed / quarantined rows and flush traffic (ingest.py)
    "ingest_",
    # the kernel autotuner: sweeps, candidates timed, installs,
    # disqualifications, table hits, persists/restores (ops/autotune.py)
    "autotune_",
    # the FID host-f64 fallback: eigh/sqrtm invocations and their
    # accumulated wall seconds (image/generative.py)
    "fid_",
)
# prefix matches that are NOT monotonically increasing (ratios recompute
# per scrape and can fall; counter semantics — rate()/reset detection —
# would read garbage off them). The latency percentiles (p50/p95/p99 and
# the per-site max) re-interpolate per read.
_GAUGE_SUFFIXES = ("_ratio", "_p50_s", "_p95_s", "_p99_s", "_max_s")
# the flattened sync_health block is health STATE, not event counts: the
# degraded flag clears, dead ranks rejoin, suspicion resets — every key
# scrapes as a gauge even though the "sync_" prefix matches above. The
# sync_phase_stats block is ring-windowed (old spans drop), so its counts
# and totals can fall too. The flattened streaming block is window STATE
# (window ids jump on rejoin, per-window values and drift scores move both
# ways) — the value-gauge carve-out beside the window_*/drift_* counters.
_GAUGE_PREFIXES = ("sync_health_", "sync_phase_stats_", "streaming_", "ingest_state_")


def is_counter_key(key: str) -> bool:
    """Whether a flattened snapshot key carries monotonic counter semantics
    (vs gauge). The ONE classification the Prometheus exposition and the
    fleet merge (counters summed, gauges min/median/max — ``ops/fleetobs``)
    both ride, so a scrape and a fleet aggregate can never disagree about
    what a key means."""
    return (
        key.startswith(_COUNTER_PREFIXES)
        and not key.endswith(_GAUGE_SUFFIXES)
        and not key.startswith(_GAUGE_PREFIXES)
    )


def is_histogram_sample_key(key: str) -> bool:
    """Whether a flattened snapshot key is a histogram SAMPLE (a bucket
    count, ``_count`` or ``_sum_s`` under the latency plane). These carry
    counter semantics (:func:`is_counter_key` is True — the INV303 pin),
    but they never travel as flat scalars: the exposition renders them only
    inside the ``le``-labelled histogram families, and the fleet plane
    merges them structurally (``fleetobs.merge_latency_stats`` — exact
    bucket sums) while excluding the whole plane from its flat
    counter/gauge walk. The third classification beside counter/gauge;
    ``ops/fleetobs`` rides the same predicate defensively so a hand-fed
    snapshot cannot leak histogram samples into a scalar family."""
    if not key.startswith(_HIST_SNAPSHOT_KEY + "_"):
        return False
    return "_buckets_" in key or key.endswith(("_count", "_sum_s"))


def _render_value(value: float) -> str:
    # integers render exactly ('%g' rounds to 6 significant digits — a
    # multi-MiB byte counter would scrape off by thousands); floats keep
    # repr's round-trip precision
    return str(int(value)) if float(value).is_integer() else repr(float(value))


def _histogram_exposition_lines(
    stats: Dict[str, Any],
    family: str = "",
    label_for: Optional[Callable[[str], str]] = None,
) -> List[str]:
    """Render a :func:`latency_stats`-shaped block as Prometheus histogram
    families: one ``# TYPE ... histogram`` header, then per site the
    CUMULATIVE ``le``-labelled ``_bucket`` samples ending at ``+Inf``
    (== ``_count``), ``_sum`` and ``_count`` — plus one gauge family per
    interpolated percentile (``<family>_p50``/``_p95``/``_p99``/``_max``).
    ``label_for`` maps a stats key to its label body (default
    ``site="<key>"``; the fleet exposition adds a ``rank`` label). Sites
    render in the dict's insertion order (:func:`latency_stats` sorts)."""
    lines: List[str] = []
    if not stats:
        return lines
    name = family or ("metrics_tpu_" + _HIST_FAMILY)
    labels = label_for or (lambda site: f'site="{site}"')
    lines.append(f"# TYPE {name} histogram")
    for site, block in stats.items():
        block = block or {}
        buckets = block.get("buckets") or {}
        base = labels(site)
        cum = 0
        for label in _HIST_LABELS:
            cum += int(buckets.get(label, 0))
            lines.append(f'{name}_bucket{{{base},le="{label}"}} {cum}')
        lines.append(f'{name}_sum{{{base}}} {_render_value(float(block.get("sum_s", 0.0)))}')
        lines.append(f'{name}_count{{{base}}} {int(block.get("count", 0))}')
    for stat_key, suffix in (("p50_s", "p50"), ("p95_s", "p95"), ("p99_s", "p99"), ("max_s", "max")):
        lines.append(f"# TYPE {name}_{suffix} gauge")
        for site, block in stats.items():
            value = float((block or {}).get(stat_key, 0.0))
            lines.append(f"{name}_{suffix}{{{labels(site)}}} {_render_value(value)}")
    return lines


def prometheus_text(data: Optional[Dict[str, Any]] = None) -> str:
    """Render :func:`snapshot` (or ``data``) as a Prometheus-style text
    exposition: every numeric key (nested dicts flattened with ``_``)
    becomes one ``metrics_tpu_<key> <value>`` sample with a ``# TYPE`` line
    (monotonic counters as ``counter``, the rest as ``gauge``), and the
    latency plane renders as cumulative ``le``-labelled **histogram**
    families (``metrics_tpu_latency_seconds{site=...,le=...}`` with
    ``_sum``/``_count``, percentiles as site-labelled gauges). Non-numeric
    values (the failure log, per-program rows) are omitted — they belong to
    the trace, not the scrape.

    Example:
        >>> from metrics_tpu import prometheus_text
        >>> text = prometheus_text()
        >>> text.splitlines()[0].startswith("# TYPE metrics_tpu_")
        True
        >>> "metrics_tpu_sync_payload_collectives" in text
        True
    """
    data = snapshot() if data is None else data
    lines: List[str] = []
    flat_src = {k: v for k, v in data.items() if k not in ("failure_log", _HIST_SNAPSHOT_KEY)}
    for key, value in sorted(_flat_numeric("", flat_src)):
        name = "metrics_tpu_" + "".join(c if (c.isalnum() or c == "_") else "_" for c in key)
        kind = "counter" if is_counter_key(key) else "gauge"
        lines.append(f"# TYPE {name} {kind}")
        lines.append(f"{name} {_render_value(value)}")
    lines.extend(_histogram_exposition_lines(data.get(_HIST_SNAPSHOT_KEY) or {}))
    return "\n".join(lines) + "\n"


def _json_safe(value: Any) -> Any:
    if isinstance(value, (str, int, float, bool)) or value is None:
        return value
    if isinstance(value, dict):
        return {str(k): _json_safe(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [_json_safe(v) for v in value]
    try:
        return float(value)  # numpy scalars
    except Exception:  # noqa: BLE001 — repr is always renderable
        return repr(value)


def trace_events(rows: Optional[List[Dict[str, Any]]] = None) -> List[Dict[str, Any]]:
    """The ring as Chrome-trace events: one ``pid``, one ``tid`` (track) per
    owner, complete (``ph: X``) slices for timed spans and instant (``ph:
    i``) marks for zero-duration ones, timestamps in µs from the earliest
    span — sorted, so Perfetto (and the export-round-trip test) sees
    monotonic ``ts``."""
    rows = spans() if rows is None else rows
    if not rows:
        return []
    t0 = min(r["t_start"] for r in rows)
    tids: Dict[str, int] = {}
    events: List[Dict[str, Any]] = []
    for r in rows:
        owner = r["owner"] or "global"
        tid = tids.setdefault(owner, len(tids) + 1)
        args: Dict[str, Any] = {"step": r["step"]}
        if r["lane"]:
            args["lane"] = r["lane"]
        if r["attrs"]:
            args.update(_json_safe(r["attrs"]))
        ev: Dict[str, Any] = {
            "name": r["site"],
            "cat": r["lane"] or "span",
            "pid": 0,
            "tid": tid,
            "ts": round((r["t_start"] - t0) * 1e6, 3),
            "args": args,
        }
        if r["dur"] > 0:
            ev["ph"] = "X"
            ev["dur"] = round(r["dur"] * 1e6, 3)
        else:
            ev["ph"] = "i"
            ev["s"] = "t"
        events.append(ev)
    events.sort(key=lambda e: e["ts"])
    meta: List[Dict[str, Any]] = [
        {"ph": "M", "name": "process_name", "pid": 0, "tid": 0, "ts": 0, "args": {"name": "metrics_tpu"}}
    ]
    for owner, tid in sorted(tids.items(), key=lambda kv: kv[1]):
        meta.append(
            {"ph": "M", "name": "thread_name", "pid": 0, "tid": tid, "ts": 0, "args": {"name": owner}}
        )
    return meta + events


def export_trace(path: str) -> int:
    """Write the recorded spans as a Chrome-trace/Perfetto JSON file — load
    it at https://ui.perfetto.dev (or ``chrome://tracing``) to see the whole
    run as a timeline: flush chunks, collectives and compiles as nested
    slices per owner track, instant marks for faults/demotions/timeouts.
    The program ledger rides along under ``programLedger`` and the numeric
    snapshot under ``snapshot`` (``tools/trace_report.py`` summarizes both).
    Returns the number of span events written.

    Example:
        >>> import os, tempfile
        >>> from metrics_tpu import export_trace
        >>> path = os.path.join(tempfile.mkdtemp(), "trace.json")
        >>> _ = export_trace(path)
        >>> os.path.exists(path)
        True
    """
    from metrics_tpu.ops import engine as _engine

    events = trace_events()
    snap = snapshot()
    doc = {
        "displayTimeUnit": "ms",
        "otherData": {"generator": "metrics_tpu.ops.telemetry", "schema": 1},
        "programLedger": _json_safe(_engine.program_report()),
        "snapshot": _json_safe({k: v for k, v in snap.items() if k != "failure_log"}),
        "traceEvents": events,
    }
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(doc, fh, separators=(",", ":"))
    return max(0, len(events) - sum(1 for e in events if e["ph"] == "M"))
