"""Backend dispatch for custom device kernels.

Pallas kernels here are **measured-opt-in**, not default-on. On the chip this
framework was tuned on, XLA's own lowerings win the histogram benchmarks
(scatter-add bincount: ~10 us for N=1e6/L=16384 vs ~76 us for the Pallas
one-hot-matmul kernel, which does O(N*L) compare work) — consistent with the
design rule "don't hand-schedule what the compiler already does". The kernels
stay in-tree, correctness-tested in interpret mode and runnable on real TPUs,
as the escape hatch for toolchains/shapes where XLA's scatter regresses:
set ``METRICS_TPU_ENABLE_PALLAS=1`` to route wide histograms through them.
"""
from __future__ import annotations

import os

import jax

_PALLAS_BACKENDS = ("tpu",)


def pallas_enabled() -> bool:
    """True when the opt-in Pallas kernel path should be used for this process."""
    if os.environ.get("METRICS_TPU_ENABLE_PALLAS") != "1":
        return False
    try:
        return jax.default_backend() in _PALLAS_BACKENDS
    except Exception:  # invlint: allow(INV201) — backend-init probe: failure means "no Pallas"; the lax path is always correct
        return False
