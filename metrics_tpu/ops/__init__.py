"""Low-level device kernels and the dispatch engine: Pallas MXU histogram,
binned-curve counts, segment reductions, donated-state program cache, the
failure-domain engine (classified faults, degradation ladders,
deterministic fault injection), the crash-consistent state journal, and the
telemetry flight recorder (span ring, program ledger, trace export)."""
from metrics_tpu.ops._dispatch import pallas_enabled
from metrics_tpu.ops.binned import binned_curve_counts
from metrics_tpu.ops.engine import (
    Executable,
    acquire,
    acquire_keyed,
    config_fingerprint,
    donation_supported,
    engine_stats,
    export_trace,
    program_report,
    program_summary,
    reset_engine,
    reset_stats,
)
from metrics_tpu.ops.faults import (
    FAULT_SITES,
    fault_stats,
    inject_faults,
    reset_warn_dedupe,
    set_recovery_policy,
)
from metrics_tpu.ops.fleetobs import (
    export_fleet_trace,
    fleet_perf_report,
    fleet_prometheus_text,
    fleet_snapshot,
    straggler_report,
)
from metrics_tpu.ops.perf import perf_report
from metrics_tpu.ops.journal import journal_generations, journal_stats, journalable
from metrics_tpu.ops.telemetry import (
    SPAN_SITES,
    prometheus_text,
    set_telemetry,
    telemetry_snapshot,
)
from metrics_tpu.ops.histogram import fused_bincount
from metrics_tpu.ops.segments import (
    segment_count,
    segment_cumsum,
    segment_max,
    segment_ranks,
    segment_starts,
    segment_sum,
)

__all__ = [
    "pallas_enabled",
    "binned_curve_counts",
    "fused_bincount",
    "segment_count",
    "segment_cumsum",
    "segment_max",
    "segment_ranks",
    "segment_starts",
    "segment_sum",
    "Executable",
    "acquire",
    "acquire_keyed",
    "config_fingerprint",
    "donation_supported",
    "engine_stats",
    "export_trace",
    "program_report",
    "program_summary",
    "reset_engine",
    "reset_stats",
    "FAULT_SITES",
    "fault_stats",
    "inject_faults",
    "reset_warn_dedupe",
    "set_recovery_policy",
    "journal_generations",
    "journal_stats",
    "journalable",
    "SPAN_SITES",
    "prometheus_text",
    "set_telemetry",
    "telemetry_snapshot",
    "export_fleet_trace",
    "fleet_perf_report",
    "fleet_prometheus_text",
    "fleet_snapshot",
    "perf_report",
    "straggler_report",
]
