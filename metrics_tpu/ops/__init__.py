"""Low-level device kernels (segment reductions, sorting helpers, Pallas ops)."""
