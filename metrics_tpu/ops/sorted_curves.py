"""Exact AUROC / average precision as static-shape device kernels.

The exact ROC / PR *curves* have data-dependent length (one point per distinct
score — reference `functional/classification/precision_recall_curve.py:49-51`),
which is why the eager curve path refuses to trace. But the *areas* under them
are scalars, so the integrals can be computed with fully static shapes: sort
(static N), identify tie runs with segment reductions (num_segments = N,
static), and integrate analytically.

- AUROC uses the midrank (Mann–Whitney U) identity: with average ranks over
  tied scores, ``AUC = (Σ ranks(positives) − P(P+1)/2) / (P·N_neg)`` — exactly
  the trapezoidal area of the tie-collapsed ROC curve.
- Average precision uses the step-interpolated sum ``Σ_g ΔTP_g · P_g`` over
  tie groups ``g``, rewritten per-element as ``Σ_i y_i · P_end(i) / P`` where
  ``P_end(i)`` is precision at the END of i's tie group (so ties contribute
  at the group precision, matching the distinct-threshold collapse).

Everything is sort + cumsum + segment reductions: O(N log N), jittable,
shard_map-safe — this is what lets exact AUROC/AP run inside fused SPMD
programs where the reference must leave the device.

**Autotuned formulations** (:mod:`metrics_tpu.ops.autotune`, armed via
``METRICS_TPU_AUTOTUNE``): the reference AUROC path argsorts and then
scatters midranks back to the original order; the ``single_sort`` variant
derives ranks, tie runs, and the U-statistic entirely in sorted space (no
scatter — the sum is order-invariant), and the ``packed_sort`` variant
fuses score and label into ONE multi-operand ``lax.sort`` over sortable
score bits (integer tie detection, the gather fused into the sort). Both
declare a small float-summation tolerance; with the autotuner off the
reference path below is byte-identical to what always ran.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from metrics_tpu.ops import autotune as _autotune
from metrics_tpu.utils.compute import high_precision


def _tie_run_ids(sorted_vals: jax.Array) -> jax.Array:
    """0-based run index per element of an already-sorted vector, ties sharing a run."""
    boundary = jnp.concatenate([jnp.ones((1,), bool), sorted_vals[1:] != sorted_vals[:-1]])
    return jnp.cumsum(boundary) - 1


def midranks(x: jax.Array) -> jax.Array:
    """Average 1-based ranks of ``x`` (ascending), ties sharing their midrank."""
    n = x.shape[0]
    order = jnp.argsort(x)
    run_id = _tie_run_ids(x[order])
    pos = jnp.arange(n, dtype=jnp.float32)
    run_count = jax.ops.segment_sum(jnp.ones(n, jnp.float32), run_id, num_segments=n)
    run_first = jax.ops.segment_min(pos, run_id, num_segments=n)
    # 1-based midrank of a run starting at f (0-based) with c members: f + (c+1)/2
    mid_sorted = run_first[run_id] + (run_count[run_id] + 1.0) * 0.5
    return jnp.zeros(n, jnp.float32).at[order].set(mid_sorted)


def _auroc_from_rank_sum(rank_sum_pos: jax.Array, n_pos: jax.Array, n: int) -> jax.Array:
    """AUROC from the midrank sum over positives (Mann–Whitney U identity)."""
    n_neg = n - n_pos
    u = rank_sum_pos - n_pos * (n_pos + 1.0) * 0.5
    denom = n_pos * n_neg
    return jnp.where(denom > 0, u / jnp.maximum(denom, 1.0), jnp.nan)


def _auroc_midranks(preds: jax.Array, y: jax.Array) -> jax.Array:
    """Reference formulation: midranks scattered back to input order."""
    ranks = midranks(preds)
    return _auroc_from_rank_sum(jnp.sum(ranks * y), jnp.sum(y), y.shape[0])


def _sorted_midranks(run_id: jax.Array, n: int) -> jax.Array:
    """1-based midrank per SORTED position, from tie-run ids (no scatter)."""
    pos = jnp.arange(n, dtype=jnp.float32)
    run_count = jax.ops.segment_sum(jnp.ones(n, jnp.float32), run_id, num_segments=n)
    run_first = jax.ops.segment_min(pos, run_id, num_segments=n)
    return run_first[run_id] + (run_count[run_id] + 1.0) * 0.5


def _auroc_single_sort(preds: jax.Array, y: jax.Array) -> jax.Array:
    """Single-sort variant: ranks, tie runs, and the U-statistic all derived
    in sorted space — the rank sum is order-invariant, so the reference's
    ``.at[order].set`` scatter back to input order disappears."""
    n = preds.shape[0]
    order = jnp.argsort(preds)
    sy = y[order]
    run_id = _tie_run_ids(preds[order])
    mid = _sorted_midranks(run_id, n)
    return _auroc_from_rank_sum(jnp.sum(mid * sy), jnp.sum(sy), n)


def _sortable_score_keys(preds: jax.Array) -> jax.Array:
    """Monotone uint32 image of float32 scores: unsigned-ascending key order
    == float-ascending value order, and bit-equality == float tie (``-0.0``
    folds to ``+0.0`` first so the zero tie run stays one run). NaN scores
    sort by payload sign instead of last — callers with NaN scores keep the
    reference variant."""
    p = jnp.where(preds == 0.0, jnp.float32(0.0), preds)
    ub = jax.lax.bitcast_convert_type(p, jnp.uint32)
    sign = ub >> jnp.uint32(31)
    return jnp.where(sign == jnp.uint32(1), ~ub, ub | jnp.uint32(1 << 31))


def _auroc_packed_sort(preds: jax.Array, y: jax.Array) -> jax.Array:
    """Key-packed variant: ONE multi-operand ``lax.sort`` over sortable
    score bits carries the labels along (the gather is fused into the sort)
    and tie runs come from integer bit-equality."""
    n = preds.shape[0]
    keys = _sortable_score_keys(preds)
    sorted_keys, sy = jax.lax.sort((keys, y), num_keys=1)
    run_id = _tie_run_ids(sorted_keys)
    mid = _sorted_midranks(run_id, n)
    return _auroc_from_rank_sum(jnp.sum(mid * sy), jnp.sum(sy), n)


@high_precision
def binary_auroc_sorted(preds: jax.Array, target: jax.Array) -> jax.Array:
    """Exact binary AUROC via midranks. Returns NaN when a class is empty."""
    preds = jnp.asarray(preds).reshape(-1).astype(jnp.float32)
    y = jnp.asarray(target).reshape(-1).astype(jnp.float32)
    if preds.shape[0] == 0:  # empty shard: no data ⇒ undefined, like an empty class
        return jnp.asarray(jnp.nan, dtype=jnp.float32)
    variant = _autotune.dispatch("auroc_sort", (preds, y))
    if variant == "single_sort":
        return _auroc_single_sort(preds, y)
    if variant == "packed_sort":
        return _auroc_packed_sort(preds, y)
    return _auroc_midranks(preds, y)


@high_precision
def binary_average_precision_sorted(preds: jax.Array, target: jax.Array) -> jax.Array:
    """Exact binary AP (step interpolation, distinct-threshold collapse).

    Returns NaN when there are no positives, matching the eager curve path
    (`functional/classification/average_precision.py` → 0/0 recall).
    """
    preds = jnp.asarray(preds).reshape(-1).astype(jnp.float32)
    y = jnp.asarray(target).reshape(-1).astype(jnp.float32)
    n = preds.shape[0]
    if n == 0:  # empty shard: no data ⇒ undefined, like a positives-free input
        return jnp.asarray(jnp.nan, dtype=jnp.float32)
    if _autotune.dispatch("ap_sort", (preds, y)) == "packed_sort":
        return _ap_packed_sort(preds, y)
    order = jnp.argsort(-preds)
    ys = y[order]
    ps = preds[order]
    run_id = _tie_run_ids(ps)
    return _ap_from_descending(ys, run_id, n)


def _ap_from_descending(ys: jax.Array, run_id: jax.Array, n: int) -> jax.Array:
    """AP from descending-sorted labels + tie-run ids (shared tail of both
    formulations: run-END precisions are intra-run-order invariant)."""
    cum_tp = jnp.cumsum(ys)
    cnt = jnp.arange(1, n + 1, dtype=jnp.float32)
    run_tp_end = jax.ops.segment_max(cum_tp, run_id, num_segments=n)
    run_cnt_end = jax.ops.segment_max(cnt, run_id, num_segments=n)
    prec_end = run_tp_end[run_id] / run_cnt_end[run_id]  # precision at i's group end
    n_pos = cum_tp[-1]
    ap = jnp.sum(ys * prec_end) / jnp.maximum(n_pos, 1.0)
    return jnp.where(n_pos > 0, ap, jnp.nan)


def _ap_argsort(preds: jax.Array, y: jax.Array) -> jax.Array:
    """Reference formulation: descending argsort + two gathers."""
    order = jnp.argsort(-preds)
    return _ap_from_descending(y[order], _tie_run_ids(preds[order]), preds.shape[0])


def _ap_packed_sort(preds: jax.Array, y: jax.Array) -> jax.Array:
    """Key-packed variant: complemented sortable score bits sort descending
    in ONE multi-operand ``lax.sort`` carrying the labels; tie runs come
    from integer bit-equality (run-end precisions are unchanged by the
    intra-run order, so the value matches the argsort path)."""
    desc_keys = ~_sortable_score_keys(preds)
    sorted_keys, ys = jax.lax.sort((desc_keys, y), num_keys=1)
    return _ap_from_descending(ys, _tie_run_ids(sorted_keys), preds.shape[0])


def _one_vs_rest(preds: jax.Array, target: jax.Array, num_classes: int) -> jax.Array:
    """(N, C) one-hot of an int target, or target itself if already 2D."""
    if target.ndim == preds.ndim:
        return target.astype(jnp.float32)
    return jax.nn.one_hot(target, num_classes, dtype=jnp.float32)


def multiclass_auroc_sorted(
    preds: jax.Array, target: jax.Array, num_classes: int, average: str = "macro"
) -> jax.Array:
    """Per-class one-vs-rest exact AUROC with macro/weighted/none averaging.

    Degenerate classes (no positives or no negatives) score 0.0 and stay in
    the macro mean — matching the eager curve path, where a flat ROC for an
    unobserved class integrates to 0 (so jit and eager agree on identical
    inputs). In the weighted average an unobserved class has support 0 and
    drops out, mirroring `functional/classification/auroc.py:93-107`.
    """
    onehot = _one_vs_rest(preds, target, num_classes)
    scores = jax.vmap(binary_auroc_sorted, in_axes=(1, 1))(preds, onehot)
    scores = jnp.nan_to_num(scores, nan=0.0)
    if average in ("none", None):
        return scores
    if average == "macro":
        return jnp.mean(scores)
    if average == "weighted":
        support = onehot.sum(axis=0)
        return jnp.sum(scores * support) / jnp.maximum(support.sum(), 1.0)
    raise ValueError(f"Unsupported average {average!r} for traced AUROC")


def multiclass_average_precision_sorted(
    preds: jax.Array, target: jax.Array, num_classes: int, average: str = "macro"
) -> jax.Array:
    """Per-class one-vs-rest exact AP with micro/macro/weighted/none averaging."""
    onehot = _one_vs_rest(preds, target, num_classes)
    if average == "micro":
        return binary_average_precision_sorted(preds.reshape(-1), onehot.reshape(-1))
    scores = jax.vmap(binary_average_precision_sorted, in_axes=(1, 1))(preds, onehot)
    if average in ("none", None):
        return scores
    valid = ~jnp.isnan(scores)
    safe = jnp.where(valid, scores, 0.0)
    if average == "macro":
        return jnp.sum(safe) / jnp.maximum(valid.sum(), 1)
    if average == "weighted":
        support = onehot.sum(axis=0)
        w = support / jnp.maximum(support.sum(), 1.0)
        return jnp.sum(jnp.where(valid, scores * w, 0.0))
    raise ValueError(f"Unsupported average {average!r} for traced AP")


# ---------------------------------------------------------------- autotuner
# Variant registration (consulted only while METRICS_TPU_AUTOTUNE is armed).
# Exactness contract: the non-reference formulations reduce identical terms
# in a different order, so they declare a small float-summation tolerance;
# registered fns take the normalized (float32[n], float32[n]) signature the
# public entry points establish before dispatching.
_SORT_TOL = 1e-4
_autotune.register_variant("auroc_sort", "midranks", _auroc_midranks, reference=True)
_autotune.register_variant("auroc_sort", "single_sort", _auroc_single_sort, tolerance=_SORT_TOL)
_autotune.register_variant("auroc_sort", "packed_sort", _auroc_packed_sort, tolerance=_SORT_TOL)
_autotune.register_variant("ap_sort", "argsort", _ap_argsort, reference=True)
_autotune.register_variant("ap_sort", "packed_sort", _ap_packed_sort, tolerance=_SORT_TOL)


__all__ = [
    "midranks",
    "binary_auroc_sorted",
    "binary_average_precision_sorted",
    "multiclass_auroc_sorted",
    "multiclass_average_precision_sorted",
]
