"""Exact AUROC / average precision as static-shape device kernels.

The exact ROC / PR *curves* have data-dependent length (one point per distinct
score — reference `functional/classification/precision_recall_curve.py:49-51`),
which is why the eager curve path refuses to trace. But the *areas* under them
are scalars, so the integrals can be computed with fully static shapes: sort
(static N), identify tie runs with segment reductions (num_segments = N,
static), and integrate analytically.

- AUROC uses the midrank (Mann–Whitney U) identity: with average ranks over
  tied scores, ``AUC = (Σ ranks(positives) − P(P+1)/2) / (P·N_neg)`` — exactly
  the trapezoidal area of the tie-collapsed ROC curve.
- Average precision uses the step-interpolated sum ``Σ_g ΔTP_g · P_g`` over
  tie groups ``g``, rewritten per-element as ``Σ_i y_i · P_end(i) / P`` where
  ``P_end(i)`` is precision at the END of i's tie group (so ties contribute
  at the group precision, matching the distinct-threshold collapse).

Everything is sort + cumsum + segment reductions: O(N log N), jittable,
shard_map-safe — this is what lets exact AUROC/AP run inside fused SPMD
programs where the reference must leave the device.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from metrics_tpu.utils.compute import high_precision


def _tie_run_ids(sorted_vals: jax.Array) -> jax.Array:
    """0-based run index per element of an already-sorted vector, ties sharing a run."""
    boundary = jnp.concatenate([jnp.ones((1,), bool), sorted_vals[1:] != sorted_vals[:-1]])
    return jnp.cumsum(boundary) - 1


def midranks(x: jax.Array) -> jax.Array:
    """Average 1-based ranks of ``x`` (ascending), ties sharing their midrank."""
    n = x.shape[0]
    order = jnp.argsort(x)
    run_id = _tie_run_ids(x[order])
    pos = jnp.arange(n, dtype=jnp.float32)
    run_count = jax.ops.segment_sum(jnp.ones(n, jnp.float32), run_id, num_segments=n)
    run_first = jax.ops.segment_min(pos, run_id, num_segments=n)
    # 1-based midrank of a run starting at f (0-based) with c members: f + (c+1)/2
    mid_sorted = run_first[run_id] + (run_count[run_id] + 1.0) * 0.5
    return jnp.zeros(n, jnp.float32).at[order].set(mid_sorted)


@high_precision
def binary_auroc_sorted(preds: jax.Array, target: jax.Array) -> jax.Array:
    """Exact binary AUROC via midranks. Returns NaN when a class is empty."""
    preds = jnp.asarray(preds).reshape(-1).astype(jnp.float32)
    y = jnp.asarray(target).reshape(-1).astype(jnp.float32)
    if preds.shape[0] == 0:  # empty shard: no data ⇒ undefined, like an empty class
        return jnp.asarray(jnp.nan, dtype=jnp.float32)
    ranks = midranks(preds)
    n_pos = jnp.sum(y)
    n_neg = y.shape[0] - n_pos
    u = jnp.sum(ranks * y) - n_pos * (n_pos + 1.0) * 0.5
    denom = n_pos * n_neg
    return jnp.where(denom > 0, u / jnp.maximum(denom, 1.0), jnp.nan)


@high_precision
def binary_average_precision_sorted(preds: jax.Array, target: jax.Array) -> jax.Array:
    """Exact binary AP (step interpolation, distinct-threshold collapse).

    Returns NaN when there are no positives, matching the eager curve path
    (`functional/classification/average_precision.py` → 0/0 recall).
    """
    preds = jnp.asarray(preds).reshape(-1).astype(jnp.float32)
    y = jnp.asarray(target).reshape(-1).astype(jnp.float32)
    n = preds.shape[0]
    if n == 0:  # empty shard: no data ⇒ undefined, like a positives-free input
        return jnp.asarray(jnp.nan, dtype=jnp.float32)
    order = jnp.argsort(-preds)
    ys = y[order]
    ps = preds[order]
    cum_tp = jnp.cumsum(ys)
    cnt = jnp.arange(1, n + 1, dtype=jnp.float32)
    run_id = _tie_run_ids(ps)
    run_tp_end = jax.ops.segment_max(cum_tp, run_id, num_segments=n)
    run_cnt_end = jax.ops.segment_max(cnt, run_id, num_segments=n)
    prec_end = run_tp_end[run_id] / run_cnt_end[run_id]  # precision at i's group end
    n_pos = cum_tp[-1]
    ap = jnp.sum(ys * prec_end) / jnp.maximum(n_pos, 1.0)
    return jnp.where(n_pos > 0, ap, jnp.nan)


def _one_vs_rest(preds: jax.Array, target: jax.Array, num_classes: int) -> jax.Array:
    """(N, C) one-hot of an int target, or target itself if already 2D."""
    if target.ndim == preds.ndim:
        return target.astype(jnp.float32)
    return jax.nn.one_hot(target, num_classes, dtype=jnp.float32)


def multiclass_auroc_sorted(
    preds: jax.Array, target: jax.Array, num_classes: int, average: str = "macro"
) -> jax.Array:
    """Per-class one-vs-rest exact AUROC with macro/weighted/none averaging.

    Degenerate classes (no positives or no negatives) score 0.0 and stay in
    the macro mean — matching the eager curve path, where a flat ROC for an
    unobserved class integrates to 0 (so jit and eager agree on identical
    inputs). In the weighted average an unobserved class has support 0 and
    drops out, mirroring `functional/classification/auroc.py:93-107`.
    """
    onehot = _one_vs_rest(preds, target, num_classes)
    scores = jax.vmap(binary_auroc_sorted, in_axes=(1, 1))(preds, onehot)
    scores = jnp.nan_to_num(scores, nan=0.0)
    if average in ("none", None):
        return scores
    if average == "macro":
        return jnp.mean(scores)
    if average == "weighted":
        support = onehot.sum(axis=0)
        return jnp.sum(scores * support) / jnp.maximum(support.sum(), 1.0)
    raise ValueError(f"Unsupported average {average!r} for traced AUROC")


def multiclass_average_precision_sorted(
    preds: jax.Array, target: jax.Array, num_classes: int, average: str = "macro"
) -> jax.Array:
    """Per-class one-vs-rest exact AP with micro/macro/weighted/none averaging."""
    onehot = _one_vs_rest(preds, target, num_classes)
    if average == "micro":
        return binary_average_precision_sorted(preds.reshape(-1), onehot.reshape(-1))
    scores = jax.vmap(binary_average_precision_sorted, in_axes=(1, 1))(preds, onehot)
    if average in ("none", None):
        return scores
    valid = ~jnp.isnan(scores)
    safe = jnp.where(valid, scores, 0.0)
    if average == "macro":
        return jnp.sum(safe) / jnp.maximum(valid.sum(), 1)
    if average == "weighted":
        support = onehot.sum(axis=0)
        w = support / jnp.maximum(support.sum(), 1.0)
        return jnp.sum(jnp.where(valid, scores * w, 0.0))
    raise ValueError(f"Unsupported average {average!r} for traced AP")


__all__ = [
    "midranks",
    "binary_auroc_sorted",
    "binary_average_precision_sorted",
    "multiclass_auroc_sorted",
    "multiclass_average_precision_sorted",
]
