"""Persistent cross-process program cache — the fleet cold-start plane.

The engine's program cache (:mod:`metrics_tpu.ops.engine`) is in-memory
only: every replica of a fleet re-traces and re-compiles every fused
program at boot, and a rolling restart pays that cost once per replaced
replica. This module adds the missing persistence tier underneath it:

- **Store**: after a fresh compile, the plain twin is exported at the
  just-compiled abstract signature (``jax.export``) and the serialized
  StableHLO module lands in a CRC-framed on-disk entry stamped with the
  store version, the backend platform, and ``jax.__version__``. Entries
  are keyed by exactly the identity ``acquire_keyed`` uses — ``(kind,
  config-fingerprint digest, abstract-signature digest)`` — so a second
  process with the same configuration resolves the same files.
- **Load**: on a would-be jit-cache miss, ``Executable._dispatch``
  consults the store *before* tracing. A hit deserializes the exported
  module and AOT-compiles a thin rehydration wrapper
  (``jax.jit(exported.call, ...).lower(...).compile()``) — no re-trace
  of metric code, and the wrapper's XLA compile is served by JAX's own
  persistent compilation cache (enabled under ``<store>/xla`` whenever
  the progcache is on), so a warmed boot performs **zero XLA compiles**.
- **Never a wrong program**: any truncated, bit-flipped, version- or
  backend-mismatched entry raises a classified :class:`JournalFault`
  and demotes the store's ``progcache`` fault-ladder lane — traffic
  falls back to a fresh compile with bit-identical results, warns once,
  and the ladder re-probes after clean operations. Program kinds whose
  export is unsupported (e.g. host callbacks) are remembered per kind
  and fall back to JAX's persistent compilation cache alone.

Everything is **off by default** (``METRICS_TPU_PROGCACHE=1`` opts in;
``METRICS_TPU_PROGCACHE_DIR`` and ``METRICS_TPU_PROGCACHE_MAX_MB`` size
and place the store) — with the knob unset, no directory is created, no
index is scanned, and the dispatch hot path is untouched. The on-disk
footprint is LRU-capped: entries are aged by mtime (touched on every
load), and a store that would exceed the cap evicts oldest-first,
counting ``progcache_evictions`` and logging what was dropped.
"""
from __future__ import annotations

import hashlib
import json
import os
import re
import struct
import time
import zlib
from typing import Any, Dict, FrozenSet, Optional, Set, Tuple

import jax
import numpy as np

from metrics_tpu.ops import faults as _faults
from metrics_tpu.ops import telemetry as _telemetry
from metrics_tpu.utils.exceptions import JournalFault

__all__ = [
    "abstract_signature",
    "build_aot",
    "configure",
    "decode_entry",
    "enabled",
    "cache_dir",
    "load_program",
    "max_cap_mb",
    "progcache_stats",
    "signature_digest",
    "store_program",
    "stored_sigs",
]

# ------------------------------------------------------------- entry framing
# Same framing discipline as ops/journal.py: fixed header, CRC32 over the
# JSON manifest and the payload separately, atomic tmp+fsync+replace writes.
_MAGIC = b"MTPC"
_VERSION = 1
_HEADER = struct.Struct("<4sIIQII")  # magic, version, manifest_len, payload_len, crc_m, crc_p
_SUFFIX = ".mpc"
_KIND_SAFE = re.compile(r"[^A-Za-z0-9_.]")

# ------------------------------------------------------------------ counters
_counters: Dict[str, int] = {
    "progcache_hits": 0,
    "progcache_misses": 0,
    "progcache_stores": 0,
    "progcache_demotions": 0,
    "progcache_evictions": 0,
    "progcache_bytes_stored": 0,
}


def progcache_stats() -> Dict[str, int]:
    """Monotonic event counters, merged into ``engine.engine_stats()``:
    ``progcache_hits`` (persistent entries rehydrated into the AOT lane),
    ``progcache_misses`` (consults that found no usable entry — a fresh
    compile followed), ``progcache_stores`` / ``progcache_bytes_stored``
    (entries written), ``progcache_demotions`` (corrupt/stale/mismatched
    entries or failed stores, each classified through the fault ladder)
    and ``progcache_evictions`` (size-cap LRU removals)."""
    return dict(_counters)


def _zero_counters() -> None:
    for key in _counters:
        _counters[key] = 0


_telemetry.register_reset("progcache", _zero_counters)


class _ProgCacheOwner:
    """Ladder + warn-dedupe anchor for the store (one lane per process —
    the store is process-global, so its health is too)."""


_OWNER = _ProgCacheOwner()
_ENABLE_WARN_OWNER = _ProgCacheOwner()
_CAP_WARN_OWNER = _ProgCacheOwner()
_EVICT_WARN_OWNER = _ProgCacheOwner()
_JAXCACHE_WARN_OWNER = _ProgCacheOwner()

#: program kinds whose ``jax.export`` failed in this process: skipped on
#: later stores (JAX's persistent compilation cache still covers their
#: XLA compiles — the documented fallback tier for unexportable programs)
_export_unsupported: Set[str] = set()

# ------------------------------------------------------------------- knobs
_override: Dict[str, Any] = {}
_TRUE_TOKENS = ("1", "true", "on", "yes")
_FALSE_TOKENS = ("0", "false", "off", "no")


def _parse_bool(raw: str) -> bool:
    token = raw.strip().lower()
    if token in _TRUE_TOKENS:
        return True
    if token in _FALSE_TOKENS:
        return False
    raise ValueError(raw)


def enabled() -> bool:
    """Whether the persistent tier is active (``METRICS_TPU_PROGCACHE``,
    default **off** — tier-1 behavior is byte-identical with the knob
    unset). Read per consult through the shared warn-once env parser."""
    if "enabled" in _override:
        return bool(_override["enabled"])
    from metrics_tpu.parallel import sync as _psync

    return bool(
        _psync._env_parse(
            "METRICS_TPU_PROGCACHE",
            False,
            _parse_bool,
            "a boolean (0/1/on/off)",
            owner=_ENABLE_WARN_OWNER,
        )
    )


def cache_dir() -> str:
    """Root of the on-disk store (``METRICS_TPU_PROGCACHE_DIR``; defaults
    under the user cache directory). Nothing is created until the first
    enabled store."""
    if "dir" in _override:
        return str(_override["dir"])
    raw = os.environ.get("METRICS_TPU_PROGCACHE_DIR", "")
    if raw and raw.strip():
        return raw.strip()
    return os.path.join(os.path.expanduser("~"), ".cache", "metrics_tpu", "progcache")


def max_cap_mb() -> int:
    """On-disk size cap in MB (``METRICS_TPU_PROGCACHE_MAX_MB``, default
    512; ``0`` or negative disables the cap). Enforced oldest-first after
    every store — never silently: each eviction counts and is logged."""
    if "max_mb" in _override:
        return int(_override["max_mb"])
    from metrics_tpu.parallel import sync as _psync

    return int(_psync._env_int("METRICS_TPU_PROGCACHE_MAX_MB", 512, owner=_CAP_WARN_OWNER))


def configure(
    *,
    enabled: Optional[bool] = None,  # noqa: A002 — mirrors the knob name
    cache_dir: Optional[str] = None,  # noqa: A002
    max_mb: Optional[int] = None,
    reset: bool = False,
) -> None:
    """Runtime override of the env knobs (tests, certifications, and boot
    scripts that place the store explicitly). ``reset=True`` first clears
    every override AND the store's process-local health state — the
    ``progcache`` ladder lane, the per-kind export-unsupported memo, and
    the directory index — so a re-pointed store starts clean."""
    global _index
    if reset:
        _override.clear()
        _export_unsupported.clear()
        _OWNER.__dict__.pop("_fault_ladders", None)
        _jax_cache_dir[0] = None
    if enabled is not None:
        _override["enabled"] = bool(enabled)
    if cache_dir is not None:
        _override["dir"] = str(cache_dir)
    if max_mb is not None:
        _override["max_mb"] = int(max_mb)
    _index = None
    _sizes.clear()


# ------------------------------------------- JAX persistent-cache fallback
_jax_cache_dir: list = [None]


def _configure_jax_cache(root: str) -> None:
    """Point JAX's own persistent compilation cache under the store — the
    fallback tier: rehydration-wrapper compiles (and any program whose
    export is unsupported) hit it by module hash, so even the XLA compile
    of a wrapper is served from disk on a warmed boot."""
    target = os.path.join(root, "xla")
    if _jax_cache_dir[0] == target:
        return
    try:
        jax.config.update("jax_compilation_cache_dir", target)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        _jax_cache_dir[0] = target
    except Exception as err:  # noqa: BLE001 — older jax: progcache still works
        _jax_cache_dir[0] = target
        _faults.warn_fault(
            _JAXCACHE_WARN_OWNER,
            "journal",
            f"could not enable JAX's persistent compilation cache under {target!r} "
            f"({type(err).__name__}: {err}); exported-module loads still skip tracing "
            "but wrapper XLA compiles will be fresh.",
        )


# ------------------------------------------------------------ ladder lane
def _lane_armed() -> bool:
    """The store's ``progcache`` fault-ladder lane: demoted by a failed
    load/store, re-probed (and promoted) after the recovery-policy count
    of clean would-be consults — standard ladder semantics, one lane for
    the whole store."""
    lad = _faults.ladder(_OWNER, "progcache")
    if not lad.demoted:
        return True
    if lad.note_clean():
        lad.promote()
        return True
    return False


# -------------------------------------------------------------- signatures
def abstract_signature(state: Any, args: tuple, kwargs: dict) -> Tuple[Any, tuple, dict]:
    """The call's abstract signature: array leaves (concrete arrays or
    ``ShapeDtypeStruct`` declarations) become ``ShapeDtypeStruct``; python
    leaves pass through (they trace exactly as they would at dispatch)."""

    def leaf(x: Any) -> Any:
        if isinstance(x, (jax.Array, np.ndarray, np.generic)):
            return jax.ShapeDtypeStruct(np.shape(x), x.dtype)
        return x

    return jax.tree.map(leaf, (state, args, kwargs))


def signature_digest(state: Any, args: tuple = (), kwargs: Optional[dict] = None) -> str:
    """Stable digest of the abstract call signature — the third component
    of the on-disk key. Arrays digest as (shape, dtype, weak_type); python
    leaves by ``repr`` (they are trace-time constants); the treedef string
    pins the structure. Deterministic across processes by construction."""
    leaves, treedef = jax.tree_util.tree_flatten((state, args, kwargs or {}))
    parts = [str(treedef)]
    for x in leaves:
        shape = getattr(x, "shape", None)
        dtype = getattr(x, "dtype", None)
        if shape is not None and dtype is not None:
            parts.append(f"{tuple(shape)}:{dtype}:{bool(getattr(x, 'weak_type', False))}")
        else:
            parts.append(repr(x))
    return hashlib.sha1("|".join(parts).encode()).hexdigest()[:16]


# ------------------------------------------------------------- entry codec
def _frame_entry(manifest: Dict[str, Any], payload: bytes) -> bytes:
    mbytes = json.dumps(manifest, sort_keys=True).encode()
    return (
        _HEADER.pack(
            _MAGIC, _VERSION, len(mbytes), len(payload), zlib.crc32(mbytes), zlib.crc32(payload)
        )
        + mbytes
        + payload
    )


def decode_entry(data: bytes, origin: str = "<bytes>") -> Tuple[Dict[str, Any], bytes]:
    """Validate and split one framed entry. Every defect — truncation, bad
    magic, unknown store version, CRC mismatch — raises a classified
    :class:`JournalFault` (site ``progcache-load``); the caller demotes to
    a fresh compile, never executes suspect bytes."""
    if len(data) < _HEADER.size:
        raise JournalFault(
            f"progcache entry {origin} truncated: {len(data)} bytes < {_HEADER.size}-byte header",
            site="progcache-load",
        )
    magic, version, mlen, plen, crc_m, crc_p = _HEADER.unpack_from(data)
    if magic != _MAGIC:
        raise JournalFault(
            f"progcache entry {origin} has bad magic {magic!r}", site="progcache-load"
        )
    if version != _VERSION:
        raise JournalFault(
            f"progcache entry {origin} has store version {version}, this build reads {_VERSION}",
            site="progcache-load",
        )
    end = _HEADER.size + mlen + plen
    if len(data) < end:
        raise JournalFault(
            f"progcache entry {origin} truncated: {len(data)} bytes < {end} framed",
            site="progcache-load",
        )
    mbytes = data[_HEADER.size : _HEADER.size + mlen]
    payload = bytes(data[_HEADER.size + mlen : end])
    if zlib.crc32(mbytes) != crc_m:
        raise JournalFault(
            f"progcache entry {origin} manifest CRC mismatch", site="progcache-load"
        )
    if zlib.crc32(payload) != crc_p:
        raise JournalFault(
            f"progcache entry {origin} payload CRC mismatch", site="progcache-load"
        )
    return json.loads(mbytes.decode()), payload


def _validate_manifest(
    manifest: Dict[str, Any], kind: str, key_digest: str, sig: str, origin: str
) -> None:
    backend = jax.default_backend()
    if manifest.get("backend") != backend:
        raise JournalFault(
            f"progcache entry {origin} was built for backend "
            f"{manifest.get('backend')!r}, this process runs {backend!r}",
            site="progcache-load",
        )
    if manifest.get("jax_version") != jax.__version__:
        raise JournalFault(
            f"progcache entry {origin} was built under jax "
            f"{manifest.get('jax_version')!r}, this process runs {jax.__version__!r}",
            site="progcache-load",
        )
    if (manifest.get("kind"), manifest.get("key"), manifest.get("sig")) != (
        kind,
        key_digest,
        sig,
    ):
        raise JournalFault(
            f"progcache entry {origin} is keyed "
            f"({manifest.get('kind')}, {manifest.get('key')}, {manifest.get('sig')}), "
            f"expected ({kind}, {key_digest}, {sig})",
            site="progcache-load",
        )


# ---------------------------------------------------------------- the index
_index: Optional[Dict[Tuple[str, str], Set[str]]] = None
_sizes: Dict[str, int] = {}


def _fname_kind(kind: str) -> str:
    return _KIND_SAFE.sub("_", kind)


def _entry_name(kind: str, key_digest: str, sig: str) -> str:
    return f"{_fname_kind(kind)}-{key_digest}-{sig}{_SUFFIX}"


def _ensure_index() -> Dict[Tuple[str, str], Set[str]]:
    global _index
    if _index is not None:
        return _index
    _index = {}
    root = cache_dir()
    try:
        names = os.listdir(root)
    except OSError:
        return _index
    for name in names:
        if not name.endswith(_SUFFIX):
            continue
        parts = name[: -len(_SUFFIX)].rsplit("-", 2)
        if len(parts) != 3:
            continue
        _index.setdefault((parts[0], parts[1]), set()).add(parts[2])
        try:
            _sizes[name] = os.path.getsize(os.path.join(root, name))
        except OSError:
            pass
    return _index


def _drop_indexed(name: str) -> None:
    _sizes.pop(name, None)
    parts = name[: -len(_SUFFIX)].rsplit("-", 2)
    if len(parts) == 3 and _index is not None:
        sigs = _index.get((parts[0], parts[1]))
        if sigs is not None:
            sigs.discard(parts[2])


def stored_sigs(kind: str, key_digest: str) -> FrozenSet[str]:
    """Signature digests the store holds for one program identity. Empty
    (and free of any disk probe) when the progcache is disabled."""
    if not enabled():
        return frozenset()
    return frozenset(_ensure_index().get((_fname_kind(kind), key_digest), ()))


def note_miss() -> None:
    """Count one consult that found no usable entry (the fresh compile
    that follows is the cache miss cost)."""
    _counters["progcache_misses"] += 1


# ----------------------------------------------------------- store / load
def _write_entry(kind: str, key_digest: str, sig: str, payload: bytes) -> int:
    """Frame + atomically write one entry, then sweep the size cap.
    Returns the framed byte count. Raises on any IO failure."""
    if _faults.armed:
        _faults.maybe_fail("progcache-store")
    manifest = {
        "kind": kind,
        "key": key_digest,
        "sig": sig,
        "backend": jax.default_backend(),
        "jax_version": jax.__version__,
        "store_version": _VERSION,
        "created": time.time(),
    }
    data = _frame_entry(manifest, payload)
    root = cache_dir()
    os.makedirs(root, exist_ok=True)
    _configure_jax_cache(root)
    name = _entry_name(kind, key_digest, sig)
    path = os.path.join(root, name)
    tmp = path + ".tmp"
    with open(tmp, "wb") as fh:
        fh.write(data)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)
    _ensure_index().setdefault((_fname_kind(kind), key_digest), set()).add(sig)
    _sizes[name] = len(data)
    _evict_over_cap(root, keep=name)
    return len(data)


def _evict_over_cap(root: str, keep: Optional[str] = None) -> None:
    cap_mb = max_cap_mb()
    if cap_mb <= 0:
        return
    cap = cap_mb * 1024 * 1024
    try:
        names = [n for n in os.listdir(root) if n.endswith(_SUFFIX)]
    except OSError:
        return
    entries = []
    total = 0
    for n in names:
        try:
            st = os.stat(os.path.join(root, n))
        except OSError:
            continue
        entries.append((st.st_mtime, n, st.st_size))
        total += st.st_size
    if total <= cap:
        return
    entries.sort()  # oldest mtime first — loads touch their entry, so this is LRU
    dropped = []
    for _mtime, n, size in entries:
        if total <= cap:
            break
        if n == keep:
            continue
        try:
            os.remove(os.path.join(root, n))
        except OSError:
            continue
        total -= size
        dropped.append(n)
        _counters["progcache_evictions"] += 1
        _drop_indexed(n)
    if dropped:
        if _telemetry.armed:
            _telemetry.emit(
                "progcache-store",
                "evict",
                "progcache",
                attrs={"evicted": dropped[:16], "count": len(dropped)},
            )
        _faults.warn_fault(
            _EVICT_WARN_OWNER,
            "journal",
            f"progcache size cap ({cap_mb} MB) evicted {len(dropped)} entry(ies) "
            f"oldest-first: {', '.join(dropped[:4])}"
            + ("…" if len(dropped) > 4 else "")
            + " — raise METRICS_TPU_PROGCACHE_MAX_MB to keep warm boots compile-free.",
        )


def store_program(
    kind: str, key_digest: str, jit_fn: Any, state: Any, args: tuple, kwargs: dict
) -> Optional[str]:
    """Export ``jit_fn`` (the plain twin) at the call's signature and
    persist the serialized module. Returns the signature digest on success,
    None otherwise — an export failure marks the *kind* unsupported (JAX's
    persistent compilation cache remains its tier), an IO failure demotes
    the whole ``progcache`` lane. Never raises into the dispatch path."""
    if not enabled() or kind in _export_unsupported or not _lane_armed():
        return None
    t0 = time.perf_counter()
    sig = signature_digest(state, args, kwargs)
    try:
        from jax import export as _jexport

        state_s, args_s, kwargs_s = abstract_signature(state, args, kwargs)
        exported = _jexport.export(jit_fn)(state_s, *args_s, **kwargs_s)
        payload = exported.serialize()
    except Exception as err:  # noqa: BLE001 — unexportable program kind
        _export_unsupported.add(kind)
        _counters["progcache_demotions"] += 1
        domain = _faults.classify(err, "compile")
        _faults.note_fault(domain, site="progcache-store", owner=_OWNER, error=err)
        _faults.warn_fault(
            _OWNER,
            domain,
            f"progcache cannot export programs of kind {kind!r} "
            f"({type(err).__name__}: {err}); this kind rides JAX's persistent "
            "compilation cache only.",
        )
        return None
    try:
        nbytes = _write_entry(kind, key_digest, sig, payload)
    except Exception as err:  # noqa: BLE001 — disk trouble: demote the lane
        _counters["progcache_demotions"] += 1
        _faults.demote(
            _OWNER,
            "progcache",
            err,
            default_domain="journal",
            site="progcache-store",
            warn=(
                f"progcache store failed for {kind}:{key_digest}:{sig} "
                f"({type(err).__name__}: {err}); demoting the persistent tier — "
                "traffic serves fresh compiles until the lane recovers."
            ),
        )
        return None
    _counters["progcache_stores"] += 1
    _counters["progcache_bytes_stored"] += nbytes
    if _telemetry.armed:
        _telemetry.emit(
            "progcache-store",
            kind,
            "progcache",
            t0,
            time.perf_counter() - t0,
            {"key": key_digest, "sig": sig, "bytes": nbytes},
        )
    return sig


def _rehydrate(payload: bytes, donate: bool, avals: Optional[Tuple[Any, tuple, dict]]):
    """Deserialize one exported module and AOT-compile the rehydration
    wrapper. With no caller avals, the signature is reconstructed from the
    exported module's own ``in_avals``/``in_tree`` (the warm-from-store
    path, where no example inputs exist yet)."""
    from jax import export as _jexport

    exported = _jexport.deserialize(payload)
    wrapper = jax.jit(exported.call, donate_argnums=(0,) if donate else ())
    if avals is None:
        structs = [
            jax.ShapeDtypeStruct(a.shape, a.dtype) for a in exported.in_avals
        ]
        (lower_args, lower_kwargs) = jax.tree_util.tree_unflatten(exported.in_tree, structs)
        return wrapper.lower(*lower_args, **lower_kwargs).compile()
    state_s, args_s, kwargs_s = avals
    return wrapper.lower(state_s, *args_s, **kwargs_s).compile()


def load_program(
    kind: str,
    key_digest: str,
    sig: str,
    *,
    donate: bool,
    state: Any = None,
    args: tuple = (),
    kwargs: Optional[dict] = None,
) -> Optional[Tuple[Any, float]]:
    """Rehydrate one persistent entry into an AOT-compiled callable.
    Returns ``(compiled, load_seconds)``, or None after counting and
    classifying a demotion (corrupt bytes, stale stamps, deserialization
    or wrapper-compile failure — the caller falls back to a fresh compile,
    never to a suspect program)."""
    if not enabled() or not _lane_armed():
        return None
    t0 = time.perf_counter()
    name = _entry_name(kind, key_digest, sig)
    path = os.path.join(cache_dir(), name)
    try:
        if _faults.armed:
            _faults.maybe_fail("progcache-load")
        _configure_jax_cache(cache_dir())
        with open(path, "rb") as fh:
            data = fh.read()
        manifest, payload = decode_entry(data, origin=name)
        _validate_manifest(manifest, kind, key_digest, sig, origin=name)
        avals = None
        if state is not None or args or kwargs:
            avals = abstract_signature(state, args, kwargs or {})
        compiled = _rehydrate(payload, donate, avals)
        try:
            os.utime(path)  # LRU recency for the size-cap sweep
        except OSError:
            pass
    except Exception as err:  # noqa: BLE001 — every load defect demotes
        _counters["progcache_demotions"] += 1
        _drop_indexed(name)
        _faults.demote(
            _OWNER,
            "progcache",
            err,
            default_domain="journal",
            site="progcache-load",
            warn=(
                f"progcache entry {name} failed to load ({type(err).__name__}: {err}); "
                "demoting to a fresh compile — results are unaffected."
            ),
        )
        return None
    dur = time.perf_counter() - t0
    _counters["progcache_hits"] += 1
    if _telemetry.armed:
        _telemetry.emit(
            "progcache-load",
            kind,
            "progcache",
            t0,
            dur,
            {"key": key_digest, "sig": sig, "donated": donate},
        )
    return compiled, dur


def build_aot(
    kind: str,
    key_digest: str,
    jit_fn: Any,
    *,
    lanes: Tuple[bool, ...],
    state: Any,
    args: tuple = (),
    kwargs: Optional[dict] = None,
    persist: bool = True,
) -> Optional[Tuple[Dict[bool, Any], float, str]]:
    """AOT-build one program signature ahead of traffic: export the plain
    twin once at the declared signature, optionally persist the entry, and
    compile one rehydration wrapper per requested donation lane. The
    callable served is ALWAYS the exported module — the exact artifact a
    warmed boot loads — so AOT-precompiled and persistent-loaded traffic
    execute identical programs. Returns ``({donate: compiled}, seconds,
    sig)`` or None (kind unexportable — counted + warned once)."""
    kwargs = kwargs or {}
    t0 = time.perf_counter()
    sig = signature_digest(state, args, kwargs)
    try:
        from jax import export as _jexport

        state_s, args_s, kwargs_s = abstract_signature(state, args, kwargs)
        exported = _jexport.export(jit_fn)(state_s, *args_s, **kwargs_s)
        payload = exported.serialize()
        compiled = {
            donate: _rehydrate(payload, donate, (state_s, args_s, kwargs_s))
            for donate in lanes
        }
    except Exception as err:  # noqa: BLE001
        _export_unsupported.add(kind)
        _counters["progcache_demotions"] += 1
        domain = _faults.classify(err, "compile")
        _faults.note_fault(domain, site="progcache-store", owner=_OWNER, error=err)
        _faults.warn_fault(
            _OWNER,
            domain,
            f"progcache cannot AOT-export programs of kind {kind!r} "
            f"({type(err).__name__}: {err}); they compile lazily at first dispatch.",
        )
        return None
    if persist and enabled() and _lane_armed():
        try:
            nbytes = _write_entry(kind, key_digest, sig, payload)
            _counters["progcache_stores"] += 1
            _counters["progcache_bytes_stored"] += nbytes
        except Exception as err:  # noqa: BLE001
            _counters["progcache_demotions"] += 1
            _faults.demote(
                _OWNER,
                "progcache",
                err,
                default_domain="journal",
                site="progcache-store",
                warn=(
                    f"progcache store failed for {kind}:{key_digest}:{sig} "
                    f"({type(err).__name__}: {err}); the AOT program still serves "
                    "in-memory, but the next boot will recompile it."
                ),
            )
    dur = time.perf_counter() - t0
    if _telemetry.armed:
        _telemetry.emit(
            "progcache-store",
            kind,
            "progcache",
            t0,
            dur,
            {"key": key_digest, "sig": sig, "aot": True, "lanes": len(compiled)},
        )
    return compiled, dur, sig
