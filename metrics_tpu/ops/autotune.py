"""Roofline-guided kernel autotuner — variant sweeps for the heavy metrics.

The roofline ledger (:func:`metrics_tpu.ops.engine.roofline_peaks` +
``program_report``) classifies every cached program against the machine
peaks, but until now each heavy kernel ran whatever single formulation
was written first. This module closes the loop:

- **Registry**: each heavy kernel declares named *variants* — mathematically
  equivalent formulations with an explicit exactness contract versus the
  reference variant (``tolerance=None`` means bit-exact; a float ``t`` means
  ``allclose(rtol=t, atol=t)``). The reference variant is always the floor:
  it is never disqualified and serves whenever no winner is installed.
- **Sweep harness** (:func:`sweep`): each candidate is dispatched through a
  real :class:`~metrics_tpu.ops.engine.Executable` (so compiles, dispatch
  tallies and the sampled device probes all land in the ordinary program
  ledger), its output is checked against the reference under the declared
  contract, and its best-of wall is scored as achieved FLOP/s / bytes/s
  against :func:`engine.roofline_peaks`. A variant that errors at dispatch
  or fails its exactness check is **disqualified** — classified through the
  ``autotune-sweep`` fault site and the module's ``autotune`` ladder lane —
  and never installed.
- **Selection table**: winners are kept per ``(kernel, shape class)`` (pow2
  shape buckets, so ragged production shapes reuse one sweep). Installed
  selections change the engine's acquire keys (a digest of the table is
  appended while the autotuner is armed), so stale traces are invalidated
  and the next acquisition bakes the winning formulation.
- **Persistence**: when the persistent program cache is enabled
  (:mod:`metrics_tpu.ops.progcache`), the selection table is exported into
  the store as a CRC-stamped JSON sidecar. A warm boot restores it before
  the first consult — **zero sweeps**, counter-pinned by the dryrun
  certification.

Everything is **off by default**: ``METRICS_TPU_AUTOTUNE`` (read through the
shared warn-once env parsers) gates the whole plane, and with the knob unset
every consult is one predicate — behavior and compiled programs are
byte-identical to the untuned build (zero sweeps, zero installs).
"""
from __future__ import annotations

import hashlib
import json
import os
import time
import zlib
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from metrics_tpu.ops import faults as _faults
from metrics_tpu.ops import telemetry as _telemetry
from metrics_tpu.utils.exceptions import JournalFault, RuntimeFault

__all__ = [
    "autotune_stats",
    "configure",
    "dispatch",
    "enabled",
    "ensure",
    "kernels",
    "load_registrations",
    "register_kernel",
    "register_variant",
    "selection_digest",
    "selection_table",
    "shape_class",
    "sweep",
    "variants",
]

# ------------------------------------------------------------------ counters
_counters: Dict[str, int] = {
    "autotune_sweeps": 0,
    "autotune_candidates": 0,
    "autotune_installs": 0,
    "autotune_disqualified": 0,
    "autotune_hits": 0,
    "autotune_persists": 0,
    "autotune_restores": 0,
}


def autotune_stats() -> Dict[str, int]:
    """Monotonic event counters, merged into ``engine.engine_stats()``:
    ``autotune_sweeps`` (sweep harness runs), ``autotune_candidates``
    (variants timed), ``autotune_installs`` (selections recorded),
    ``autotune_disqualified`` (variants that errored or failed exactness),
    ``autotune_hits`` (consults served from the selection table),
    ``autotune_persists`` / ``autotune_restores`` (selection-table writes
    to / entries restored from the progcache store)."""
    return dict(_counters)


def _zero_counters() -> None:
    for key in _counters:
        _counters[key] = 0


_telemetry.register_reset("autotune", _zero_counters)


class _AutotuneOwner:
    """Ladder + warn-dedupe anchor (one ``autotune`` lane per process — the
    selection table is process-global, so its health is too)."""


_OWNER = _AutotuneOwner()
_ENABLE_WARN_OWNER = _AutotuneOwner()
_PERSIST_WARN_OWNER = _AutotuneOwner()

# ------------------------------------------------------------------- registry
class _Variant:
    __slots__ = ("name", "fn", "tolerance", "reference", "host")

    def __init__(self, name: str, fn: Callable, tolerance: Optional[float], reference: bool, host: bool):
        self.name = name
        self.fn = fn
        self.tolerance = tolerance  # None = bit-exact contract
        self.reference = reference
        self.host = host  # host-side numpy variant: timed eagerly, never jitted


class _Kernel:
    __slots__ = ("name", "variants", "reference", "classify")

    def __init__(self, name: str, classify: Optional[Callable]):
        self.name = name
        self.variants: "Dict[str, _Variant]" = {}
        self.reference: Optional[str] = None
        self.classify = classify


_KERNELS: Dict[str, _Kernel] = {}


def register_kernel(name: str, *, classify: Optional[Callable] = None) -> None:
    """Declare a tunable kernel family. ``classify(args) -> str`` overrides
    the default pow2 shape-class bucketing (:func:`shape_class`)."""
    if name not in _KERNELS:
        _KERNELS[name] = _Kernel(name, classify)
    elif classify is not None:
        _KERNELS[name].classify = classify


def register_variant(
    kernel: str,
    name: str,
    fn: Callable,
    *,
    tolerance: Optional[float] = None,
    reference: bool = False,
    host: bool = False,
) -> None:
    """Register one named variant under ``kernel``. Exactly one variant per
    kernel must be the ``reference`` — it defines correct output (its own
    ``tolerance`` is ignored) and is the selection floor. ``tolerance=None``
    declares a bit-exact contract; a float ``t`` declares
    ``allclose(rtol=t, atol=t)`` versus the reference."""
    register_kernel(kernel)
    k = _KERNELS[kernel]
    if reference:
        if k.reference is not None and k.reference != name:
            raise ValueError(f"kernel {kernel!r} already has reference {k.reference!r}")
        k.reference = name
    k.variants[name] = _Variant(name, fn, tolerance, reference, host)


def kernels() -> Tuple[str, ...]:
    """Registered kernel family names."""
    return tuple(_KERNELS)


def load_registrations() -> Tuple[str, ...]:
    """Import every in-tree module that registers kernel variants, so the
    full registry is populated without the caller having touched each metric
    surface first (sweep drivers, certifications, bench). Returns
    :func:`kernels` afterwards."""
    import metrics_tpu.detection.mean_ap  # noqa: F401 — registers map_box_iou
    import metrics_tpu.image.generative  # noqa: F401 — registers fid_sqrtm
    import metrics_tpu.ops.binned  # noqa: F401 — registers binned_counts
    import metrics_tpu.ops.histogram  # noqa: F401 — registers bincount
    import metrics_tpu.ops.sorted_curves  # noqa: F401 — registers auroc_sort/ap_sort

    return kernels()


def variants(kernel: str) -> Tuple[str, ...]:
    """Registered variant names for ``kernel`` (reference first)."""
    k = _KERNELS[kernel]
    names = sorted(k.variants, key=lambda n: (not k.variants[n].reference, n))
    return tuple(names)


# ------------------------------------------------------------------- the gate
_TRUE_TOKENS = ("1", "true", "on", "yes")
_FALSE_TOKENS = ("0", "false", "off", "no")


def _parse_bool(raw: str) -> bool:
    token = raw.strip().lower()
    if token in _TRUE_TOKENS:
        return True
    if token in _FALSE_TOKENS:
        return False
    raise ValueError(raw)


#: Hot-path guard (same shape as ``faults.armed``): consults check this one
#: module attribute, so the disabled autotuner costs a single predicate and
#: compiled programs stay byte-identical to the untuned build.
active: bool = False
_enabled_known: bool = False
_override: Dict[str, Any] = {}


def _init_enabled() -> None:
    global active, _enabled_known
    if "enabled" in _override:
        val = bool(_override["enabled"])
    else:
        from metrics_tpu.parallel import sync as _psync

        val = bool(
            _psync._env_parse(
                "METRICS_TPU_AUTOTUNE",
                False,
                _parse_bool,
                "a boolean (0/1/on/off)",
                owner=_ENABLE_WARN_OWNER,
            )
        )
    active = val
    _enabled_known = True
    _sync_engine_hooks()


def enabled() -> bool:
    """Whether the autotuner is armed (``METRICS_TPU_AUTOTUNE``, default
    **off** — with the knob unset every consult is one predicate and the
    compiled programs are byte-identical to the untuned build). Read once
    per process through the shared warn-once env parser; override with
    :func:`configure`."""
    if not _enabled_known:
        _init_enabled()
    return active


def configure(*, enabled: Optional[bool] = None, reset: bool = False) -> None:  # noqa: A002 — mirrors the knob name
    """Runtime override of the env knob (tests, certifications, bench).
    ``reset=True`` first clears the override, the selection table, the
    swept-class memo, the restore attempt and the ``autotune`` ladder lane —
    a re-armed autotuner starts clean (counters are NOT touched; that is
    ``engine.reset_stats()``'s job)."""
    global _enabled_known, active
    if reset:
        _override.clear()
        _SELECTIONS.clear()
        _SWEPT.clear()
        _SWEEP_RESULTS.clear()
        _restore_state[0] = False
        _digest_cache[0] = None
        _OWNER.__dict__.pop("_fault_ladders", None)
        _enabled_known = False
        active = False
    if enabled is not None:
        _override["enabled"] = bool(enabled)
        _enabled_known = False
    if not _enabled_known:
        _init_enabled()
    else:
        _sync_engine_hooks()


# ----------------------------------------------------------- selection table
#: (kernel, shape_class) -> winning variant name (reference names included:
#: a reference win is still a recorded selection, so the class never re-sweeps)
_SELECTIONS: Dict[Tuple[str, str], str] = {}
_SWEPT: set = set()
_SWEEP_RESULTS: Dict[Tuple[str, str], Dict[str, Any]] = {}
_digest_cache: list = [None]
_restore_state: list = [False]
#: per-trace consult log: (kernel -> variant) consulted while tracing, drained
#: into ``Executable.variant`` by the engine's compile-detection hook
_trace_consults: Dict[str, str] = {}


def selection_table() -> Dict[str, str]:
    """The installed selections, as ``"kernel|shape_class" -> variant``."""
    return {f"{k}|{sc}": v for (k, sc), v in sorted(_SELECTIONS.items())}


def selection_digest() -> str:
    """Stable digest of the selection table — appended to the engine's
    acquire keys while the autotuner is armed, so an install invalidates
    stale traces and identical tables resolve identical persistent-cache
    entries across processes."""
    if _digest_cache[0] is None:
        blob = json.dumps(selection_table(), sort_keys=True).encode()
        _digest_cache[0] = hashlib.sha1(blob).hexdigest()[:12]
    return _digest_cache[0]


def _engine_key_suffix() -> tuple:
    return ("autotune", selection_digest())


def _engine_note_compile(exe: Any) -> None:
    """Drain the trace-time consult log into the just-compiled program's
    ledger row (``program_report`` ``variant`` column)."""
    if _trace_consults:
        exe.variant = ",".join(f"{k}={v}" for k, v in sorted(_trace_consults.items()))
        _trace_consults.clear()


def _sync_engine_hooks() -> None:
    from metrics_tpu.ops import engine as _engine

    if active:
        _engine._autotune_key = _engine_key_suffix
        _engine._autotune_note = _engine_note_compile
    else:
        _engine._autotune_key = None
        _engine._autotune_note = None


# --------------------------------------------------------------- shape class
def _pow2(n: int) -> int:
    return max(1, 1 << (int(n) - 1).bit_length()) if n > 0 else 0


def shape_class(*args: Any) -> str:
    """Default shape-class bucketing: array args as ``dtype[pow2-dims]``,
    python leaves by ``repr`` (trace-time constants). Ragged production
    shapes land in O(log^2) classes, so one sweep covers a bucket."""
    parts: List[str] = []
    for a in args:
        shape = getattr(a, "shape", None)
        dtype = getattr(a, "dtype", None)
        if shape is not None and dtype is not None:
            dims = "x".join(str(_pow2(d)) for d in shape)
            parts.append(f"{dtype}[{dims}]")
        else:
            parts.append(repr(a))
    return ",".join(parts)


def _classify(kernel: str, args: tuple) -> str:
    k = _KERNELS[kernel]
    if k.classify is not None:
        return str(k.classify(args))
    return shape_class(*args)


# ------------------------------------------------------------------ consults
def dispatch(kernel: str, args: tuple, *, sweep_on_miss: bool = False) -> Optional[str]:
    """The call-site consult: which variant should serve this call?

    Returns ``None`` for the reference path — always when the autotuner is
    disabled (one predicate, byte-identical programs), when no selection is
    installed for this ``(kernel, shape class)``, or when the installed
    winner IS the reference. Works under tracing (shape classes come from
    static shapes); ``sweep_on_miss=True`` lets an eager call site with
    concrete inputs trigger the sweep for a first-seen shape class (skipped
    while the ``autotune`` ladder lane is demoted)."""
    if not active:
        if _enabled_known:
            return None
        _init_enabled()
        if not active:
            return None
    if kernel not in _KERNELS:
        return None
    _maybe_restore()
    sc = _classify(kernel, args)
    name = _SELECTIONS.get((kernel, sc))
    if name is None:
        if sweep_on_miss and (kernel, sc) not in _SWEPT and _lane_clean() and _concrete(args):
            try:
                sweep(kernel, args)
            except Exception as err:  # noqa: BLE001 — a failed sweep must never
                # break the caller: demote the lane (blocks further auto-sweeps
                # until it re-probes clean) and serve the reference
                _faults.demote(
                    _OWNER, "autotune", err,
                    default_domain="runtime", site="autotune-sweep",
                    warn=f"autotune sweep for {kernel!r} failed ({type(err).__name__}: {err}); "
                    "serving the reference variant",
                )
            name = _SELECTIONS.get((kernel, sc))
        if name is None:
            return None
    _counters["autotune_hits"] += 1
    k = _KERNELS[kernel]
    if name not in k.variants:
        # a restored selection naming a variant this build doesn't register:
        # the reference is always the floor
        return None
    import jax

    if not jax.core.trace_state_clean():
        _trace_consults[kernel] = name
    if k.variants[name].reference:
        return None
    return name


def ensure(kernel: str, *args: Any) -> Optional[str]:
    """Sweep-if-needed for one concrete call signature: returns the installed
    winner for ``(kernel, shape_class(args))``, sweeping first when the class
    has never been swept. ``None`` when the autotuner is disabled."""
    if not enabled():
        return None
    _maybe_restore()
    sc = _classify(kernel, args)
    if (kernel, sc) not in _SWEPT:
        sweep(kernel, args)
    return _SELECTIONS.get((kernel, sc))


def _concrete(args: tuple) -> bool:
    import jax

    return not any(isinstance(a, jax.core.Tracer) for a in args)


def _lane_clean() -> bool:
    lad = _faults.ladder(_OWNER, "autotune")
    if not lad.demoted:
        return True
    if lad.note_clean():
        lad.promote()
        return True
    return False


# -------------------------------------------------------------- the harness
def _is_array(x: Any) -> bool:
    import jax

    return isinstance(x, (jax.Array, np.ndarray, np.generic))


def _outputs_match(ref: Any, out: Any, tolerance: Optional[float]) -> bool:
    """The exactness contract: ``tolerance=None`` ⇒ bit-exact (NaNs equal);
    a float ``t`` ⇒ ``allclose(rtol=t, atol=t, equal_nan=True)`` per leaf."""
    import jax

    ref_leaves, ref_tree = jax.tree_util.tree_flatten(ref)
    out_leaves, out_tree = jax.tree_util.tree_flatten(out)
    if ref_tree != out_tree or len(ref_leaves) != len(out_leaves):
        return False
    for a, b in zip(ref_leaves, out_leaves):
        a = np.asarray(a)
        b = np.asarray(b)
        if a.shape != b.shape or a.dtype != b.dtype:
            return False
        if tolerance is None:
            if np.issubdtype(a.dtype, np.floating):
                same = (a == b) | (np.isnan(a) & np.isnan(b))
                if not bool(np.all(same)):
                    return False
            elif not np.array_equal(a, b):
                return False
        elif not np.allclose(b, a, rtol=tolerance, atol=tolerance, equal_nan=True):
            return False
    return True


def _time_candidate(run: Callable[[], Any], trials: int) -> float:
    import jax

    best = float("inf")
    for _ in range(max(1, trials)):
        t0 = time.perf_counter()
        out = run()
        jax.block_until_ready(out)
        best = min(best, time.perf_counter() - t0)
    return best


def sweep(kernel: str, args: tuple, *, trials: int = 3) -> Dict[str, Any]:
    """Run the variant sweep for ``(kernel, shape_class(args))`` on concrete
    inputs and install the winner.

    Every registered variant is built and timed through a real
    :class:`~metrics_tpu.ops.engine.Executable` (kind ``autotune:<kernel>``,
    keyed by variant + shape class — host variants are timed eagerly), its
    output checked against the reference under the declared exactness
    contract, and its best-of wall scored as achieved FLOP/s and bytes/s
    (from the reference program's XLA cost analysis) against
    :func:`engine.roofline_peaks`. Disqualified variants (dispatch error,
    injected ``autotune-sweep`` fault, or exactness failure) demote
    classified and are never installed; the reference is always the floor.
    Returns the sweep report; the same class never re-sweeps (consult
    :func:`selection_table`)."""
    import jax
    import jax.numpy as jnp

    from metrics_tpu.ops import engine as _engine

    if not enabled():
        raise RuntimeError("autotune.sweep requires METRICS_TPU_AUTOTUNE (or configure(enabled=True))")
    k = _KERNELS[kernel]
    if k.reference is None:
        raise ValueError(f"kernel {kernel!r} has no reference variant")
    sc = _classify(kernel, args)
    if (kernel, sc) in _SWEPT:
        return _SWEEP_RESULTS[(kernel, sc)]
    t_sweep = time.perf_counter()
    _counters["autotune_sweeps"] += 1

    array_idx = [i for i, a in enumerate(args) if _is_array(a)]
    dev_args = tuple(jnp.asarray(args[i]) for i in array_idx)
    host_args = tuple(args)

    def _make_step(fn: Callable) -> Callable:
        def step(state: Any, *arrs: Any) -> Any:
            full = list(args)
            for i, arr in zip(array_idx, arrs):
                full[i] = arr
            return fn(*full)

        return step

    static_key = tuple(repr(args[i]) for i in range(len(args)) if i not in array_idx)
    peaks = _engine.roofline_peaks()
    names = variants(kernel)  # reference first
    rows: List[Dict[str, Any]] = []
    ref_out: Any = None
    ref_analysis: Optional[Dict[str, Any]] = None
    disqualified = 0

    for name in names:
        v = k.variants[name]
        _counters["autotune_candidates"] += 1
        row: Dict[str, Any] = {
            "variant": name,
            "reference": v.reference,
            "ok": False,
            "exact": None,
            "wall_s": None,
            "score": 0.0,
            "compute_utilization": 0.0,
            "memory_utilization": 0.0,
        }
        try:
            if v.host:
                run = lambda fn=v.fn: fn(*host_args)  # noqa: E731
                out = run()
            else:
                exe = _engine.acquire_keyed(
                    (f"autotune:{kernel}", name, sc) + static_key,
                    lambda fn=v.fn: (_make_step(fn), None, {"autotune": True}),
                    donate=False,
                )
                exe.variant = name
                run = lambda e=exe: e(None, *dev_args)  # noqa: E731
                out = run()  # warmup: compile lands in the ledger, not the timing
                jax.block_until_ready(out)
            if v.reference:
                ref_out = out
                if not v.host:
                    ref_analysis = _engine._analyze(exe)
            else:
                # the injection point: a poisoned candidate dies HERE, after
                # the reference is already banked — the floor is never at risk
                if _faults.armed:
                    _faults.maybe_fail("autotune-sweep")
                row["exact"] = _outputs_match(ref_out, out, v.tolerance)
                if not row["exact"]:
                    raise RuntimeFault(
                        f"autotune variant {kernel}:{name} failed its exactness contract "
                        f"(tolerance={v.tolerance!r}) vs reference {k.reference!r}",
                        site="autotune-sweep",
                    )
            t0 = time.perf_counter()
            wall = _time_candidate(run, trials)
            if not v.host:
                # feed the probed device plane: sweep timings are real
                # device-inclusive walls, so the candidates' roofline rows
                # classify like any probed program
                _telemetry.observe_device_dispatch(exe.probe_key, t0, wall)
            row["wall_s"] = wall
            flops = float((ref_analysis or {}).get("flops", 0.0) or 0.0)
            nbytes = float((ref_analysis or {}).get("bytes_accessed", 0.0) or 0.0)
            if peaks.get("calibrated") and wall > 0 and (flops > 0 or nbytes > 0):
                u_c = flops / wall / peaks["peak_flops_per_s"]
                u_m = nbytes / wall / peaks["peak_bytes_per_s"]
                row["compute_utilization"] = round(u_c, 6)
                row["memory_utilization"] = round(u_m, 6)
                row["score"] = max(u_c, u_m)
            elif wall > 0:
                # uncalibrated / unanalyzed: 1/wall is the same argmax —
                # achieved work per second with the (fixed) algorithmic
                # numerator divided out
                row["score"] = 1.0 / wall
            row["ok"] = True
        except Exception as err:  # noqa: BLE001 — a bad candidate is a
            # classified disqualification, never a sweep abort
            if v.reference:
                raise  # the reference failing means the kernel itself is broken
            disqualified += 1
            _counters["autotune_disqualified"] += 1
            row["error"] = f"{type(err).__name__}: {str(err)[:160]}"
            _faults.demote(
                _OWNER, "autotune", err,
                default_domain="runtime", site="autotune-sweep",
                warn=f"autotune variant {kernel}:{name} disqualified "
                f"({type(err).__name__}: {str(err)[:120]}); the reference variant remains the floor",
            )
        rows.append(row)

    winner = k.reference
    best = next(r for r in rows if r["reference"])
    for row in rows:
        if row["ok"] and not row["reference"] and row["score"] > best["score"]:
            winner = row["variant"]
            best = row
    _install(kernel, sc, winner)
    report = {
        "kernel": kernel,
        "shape_class": sc,
        "winner": winner,
        "reference": k.reference,
        "candidates": rows,
        "disqualified": disqualified,
    }
    _SWEPT.add((kernel, sc))
    _SWEEP_RESULTS[(kernel, sc)] = report
    if _telemetry.armed:
        _telemetry.emit(
            "autotune-sweep", kernel, "autotune", t_sweep, time.perf_counter() - t_sweep,
            {"shape_class": sc, "winner": winner, "candidates": len(rows), "disqualified": disqualified},
        )
    return report


def _install(kernel: str, sc: str, winner: str) -> None:
    _SELECTIONS[(kernel, sc)] = winner
    _digest_cache[0] = None
    _counters["autotune_installs"] += 1
    if _telemetry.armed:
        now = time.perf_counter()
        _telemetry.emit(
            "autotune-install", kernel, "autotune", now, 0.0,
            {"shape_class": sc, "variant": winner},
        )
    _persist()


# --------------------------------------------------------------- persistence
_TABLE_FILE = "autotune_selections.json"
_TABLE_VERSION = 1


def _table_path() -> str:
    from metrics_tpu.ops import progcache as _progcache

    return os.path.join(_progcache.cache_dir(), _TABLE_FILE)


def _persist() -> None:
    """Export the selection table into the progcache store (atomic tmp +
    fsync + replace, CRC-stamped) so a warm boot restores it at zero sweeps.
    No-op while the persistent cache is disabled; failures demote classified
    (the in-memory table keeps serving)."""
    from metrics_tpu.ops import progcache as _progcache

    if not _progcache.enabled():
        return
    import jax

    try:
        sel_blob = json.dumps(selection_table(), sort_keys=True)
        doc = {
            "magic": "MTAT",
            "version": _TABLE_VERSION,
            "backend": jax.default_backend(),
            "jax": jax.__version__,
            "selections": json.loads(sel_blob),
            "crc": zlib.crc32(sel_blob.encode()),
        }
        path = _table_path()
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(doc, fh, sort_keys=True)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, path)
        _counters["autotune_persists"] += 1
    except Exception as err:  # noqa: BLE001 — persistence is best-effort;
        # the in-memory table keeps serving and the next install retries
        _faults.demote(
            _PERSIST_WARN_OWNER, "autotune", err,
            default_domain="journal", site="autotune-sweep",
            warn=f"could not persist the autotune selection table "
            f"({type(err).__name__}: {str(err)[:120]}); selections stay in-memory only",
        )


def _maybe_restore() -> None:
    """Load the persisted selection table on the first consult of an armed
    process (warm boot = zero sweeps). Corrupt tables demote classified and
    are ignored; a backend/version mismatch is simply a cold start."""
    if _restore_state[0]:
        return
    _restore_state[0] = True
    from metrics_tpu.ops import progcache as _progcache

    if not _progcache.enabled():
        return
    path = _table_path()
    if not os.path.exists(path):
        return
    import jax

    try:
        with open(path, "r", encoding="utf-8") as fh:
            doc = json.load(fh)
        if doc.get("magic") != "MTAT" or int(doc.get("version", -1)) != _TABLE_VERSION:
            raise JournalFault(
                f"autotune selection table {path} has unknown framing "
                f"(magic={doc.get('magic')!r}, version={doc.get('version')!r})",
                site="autotune-sweep",
            )
        selections = doc.get("selections", {})
        sel_blob = json.dumps(selections, sort_keys=True)
        if zlib.crc32(sel_blob.encode()) != int(doc.get("crc", -1)):
            raise JournalFault(
                f"autotune selection table {path} CRC mismatch", site="autotune-sweep"
            )
        if doc.get("backend") != jax.default_backend():
            return  # another machine's winners: sweep fresh, never mis-serve
        restored = 0
        for key, variant in selections.items():
            kernel, _, sc = key.partition("|")
            if not kernel or not sc:
                continue
            _SELECTIONS[(kernel, sc)] = str(variant)
            _SWEPT.add((kernel, sc))
            restored += 1
        if restored:
            _digest_cache[0] = None
            _counters["autotune_restores"] += restored
    except Exception as err:  # noqa: BLE001 — a suspect table is never
        # served: demote classified and sweep fresh
        _faults.demote(
            _OWNER, "autotune", err,
            default_domain="journal", site="autotune-sweep",
            warn=f"could not restore the autotune selection table "
            f"({type(err).__name__}: {str(err)[:120]}); sweeping fresh",
        )
