"""Segment reductions over contiguous (sorted) group ids.

The reference groups retrieval rows with a host-side python dict loop
(`src/torchmetrics/utilities/data.py:210-233` ``get_group_indexes``) and then
launches one kernel per query group. On TPU the grouped evaluation is one
device program: rows are sorted by group id, and every per-group quantity
becomes a segment reduction. All helpers assume ``segment_ids`` is sorted
ascending and dense in ``[0, num_segments)`` — callers establish this with one
``argsort`` (see :mod:`metrics_tpu.retrieval.base`). Helpers that need
counts/starts accept them precomputed so a caller evaluating several
reductions over the same segmentation dispatches each O(R) pass once.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def segment_sum(data: jax.Array, segment_ids: jax.Array, num_segments: int) -> jax.Array:
    """Sum of ``data`` rows per segment (deterministic XLA scatter-add)."""
    return jax.ops.segment_sum(data, segment_ids, num_segments=num_segments, indices_are_sorted=True)


def segment_max(data: jax.Array, segment_ids: jax.Array, num_segments: int) -> jax.Array:
    return jax.ops.segment_max(data, segment_ids, num_segments=num_segments, indices_are_sorted=True)


def segment_count(segment_ids: jax.Array, num_segments: int) -> jax.Array:
    """Number of rows in each segment."""
    return segment_sum(jnp.ones_like(segment_ids, dtype=jnp.int32), segment_ids, num_segments)


def segment_starts(
    segment_ids: jax.Array, num_segments: int, counts: Optional[jax.Array] = None
) -> jax.Array:
    """Index of the first row of each segment (== exclusive cumsum of counts)."""
    if counts is None:
        counts = segment_count(segment_ids, num_segments)
    return jnp.cumsum(counts) - counts


def segment_ranks(
    segment_ids: jax.Array, num_segments: int, starts: Optional[jax.Array] = None
) -> jax.Array:
    """1-based rank of every row within its segment (row order preserved)."""
    if starts is None:
        starts = segment_starts(segment_ids, num_segments)
    return jnp.arange(segment_ids.shape[0], dtype=jnp.int32) - starts[segment_ids] + 1


def segment_cumsum(data: jax.Array, segment_ids: jax.Array, num_segments: int) -> jax.Array:
    """Inclusive cumsum of ``data`` restarting at every segment boundary.

    Implemented as a segmented associative scan (flag-reset operator), NOT as
    ``global_cumsum - offset_at_start``: the subtraction form loses float32
    precision catastrophically for groups late in a large stream (each group's
    values become the difference of two huge prefix sums), while the segmented
    scan only ever accumulates within a group.
    """
    del num_segments  # segment boundaries are derived from the ids directly
    if data.shape[0] == 0:
        return data
    is_start = jnp.concatenate([jnp.ones((1,), jnp.bool_), segment_ids[1:] != segment_ids[:-1]])

    def combine(a, b):
        av, af = a
        bv, bf = b
        return jnp.where(bf, bv, av + bv), af | bf

    out, _ = jax.lax.associative_scan(combine, (data, is_start))
    return out


__all__ = [
    "segment_sum",
    "segment_max",
    "segment_count",
    "segment_starts",
    "segment_ranks",
    "segment_cumsum",
]
