"""Performance attribution: the step-latency decomposition over the span
ring, the roofline ledger join, and ranked optimization opportunities.

The flight recorder answers "what happened"; the latency histograms answer
"how slow"; this module answers **"where does the time actually go"** — the
question every open perf item (async pipelined sync, AOT cold-start caching,
in-graph state) must answer before and after its change. Three layers:

- **Interval-exclusive phase decomposition** — every timed span in the ring
  is attributed to exactly ONE phase by a nesting scan over the recorded
  ``(t_start, dur)`` intervals: a child span's duration is subtracted from
  its nearest enclosing ancestor, so summing phases never double-counts
  (an ``engine-dispatch`` nested in an ``engine-flush`` nested in a
  ``suite-step`` contributes once, to ``dispatch``). Phases:

  ========== =====================================================
  phase       exclusive time of
  ========== =====================================================
  enqueue     ``suite-step`` spans (validation + queue append — the
              per-call python cost left after nested spans are removed)
  flush       ``engine-flush`` (stack/bucket/host-stage overhead)
  trace       ``engine-build`` (program construction closures)
  compile     ``engine-compile`` (first-call trace+XLA wall)
  dispatch    ``engine-dispatch`` (ASYNC host wall — tagged
              ``async_host_wall``; under-measures device)
  device      ``device-dispatch`` probes' excess over their host
              dispatch sibling (the measured device-only wall; only
              probed dispatches add real wall)
  pack        ``sync-pack`` (tree walk + bitcast-concat program)
  serialize   ``sync-metadata`` (dyn-shape / cross-check exchanges)
  wire        ``sync-payload-gather`` + per-state ``sync-gather`` (the
              blocking collective itself; its effective bytes/s comes
              from the spans' byte attrs — the share the 69 ms sync
              wall actually spends on the wire)
  unpack      ``sync-unpack`` (slice/bitcast/reduce programs)
  orchestrate ``suite-sync`` residual (member walk, eligibility,
              snapshot bookkeeping around the sync phases)
  host        every other timed span (journal saves, fleet gathers,
              observation-time work outside the suite parents)
  ========== =====================================================

- **Reconciliation** — the phase sum equals the top-level span wall by
  construction; against an EXTERNALLY measured wall (pass
  ``measured_wall_s``) the coverage states how much of real time the spans
  explain. The certification drives a live suite loop and requires
  coverage within :data:`TOLERANCE`.

- **Roofline + opportunities** — ``engine.program_report()``'s per-program
  roofline join (probed device p50 x XLA cost analysis -> achieved FLOP/s,
  achieved bytes/s, bound classification) rides along under ``programs``,
  and ``opportunities`` ranks the heaviest phases with the evidence for
  each (bytes over the wire at the effective bandwidth, compile events and
  their wall, dispatch counts) — the queryable answer to "what should the
  next perf PR attack".

``fleet_perf_report()`` (``ops/fleetobs.py``) merges every rank's report;
``tools/trace_report.py --perf`` renders the same decomposition offline
from an exported trace file. See docs/performance.md "Where the time goes".
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from metrics_tpu.ops import telemetry as _telemetry

__all__ = [
    "PHASES",
    "SITE_PHASES",
    "TOLERANCE",
    "perf_report",
    "perf_stats",
    "phase_columns",
    "reset_perf_stats",
]

#: Reconciliation tolerance: phases must cover the measured wall within
#: this relative share (the certification pins it over a live suite loop).
TOLERANCE = 0.15

#: Span site -> phase. Sites absent here fold into the ``host`` phase.
#: ``sync-force``'s EXCLUSIVE time (its wall minus the nested unpack) is the
#: wait the caller actually blocked on for an in-flight collective — the
#: non-hidden wire; ``sync-dispatch``'s exclusive residual (after the nested
#: pack) is async bookkeeping; ``sync-quantize`` is payload serialization
#: work like the metadata exchange.
SITE_PHASES = {
    "suite-step": "enqueue",
    "engine-flush": "flush",
    "engine-build": "trace",
    "engine-compile": "compile",
    "engine-dispatch": "dispatch",
    "device-dispatch": "device",
    "suite-sync": "orchestrate",
    "sync-pack": "pack",
    "sync-metadata": "serialize",
    "sync-quantize": "serialize",
    "sync-payload-gather": "wire",
    "sync-gather": "wire",
    "sync-unpack": "unpack",
    "sync-dispatch": "orchestrate",
    "sync-force": "wire",
}

#: Every phase, in report order. ``step`` phases then ``sync`` phases then
#: the catch-all.
PHASES = (
    "enqueue", "flush", "trace", "compile", "dispatch", "device",
    "pack", "serialize", "wire", "unpack", "orchestrate", "host",
)

_STEP_PHASES = ("enqueue", "flush", "trace", "compile", "dispatch", "device")
_SYNC_PHASES = ("pack", "serialize", "wire", "unpack", "orchestrate")

_counters: Dict[str, int] = {"perf_reports": 0}


def perf_stats() -> Dict[str, int]:
    return dict(_counters)


def reset_perf_stats() -> None:
    for key in _counters:
        _counters[key] = 0


_telemetry.register_reset("perf", reset_perf_stats)


def _exclusive_spans(rows: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Attribute every timed span its EXCLUSIVE duration (own wall minus the
    wall of spans nested inside it) via one stack scan over the interval
    tree. Host-side spans are emitted single-threaded, so intervals either
    nest or are disjoint; ties at the same start (a probed
    ``device-dispatch`` and its ``engine-dispatch`` sibling share
    ``t_start``) order the longer interval as the parent.

    Spans tagged ``overlapped`` in their attrs — the async sync lane's
    in-flight wire spans, emitted from the dispatcher thread — COEXIST with
    host compute instead of nesting inside it: a ``sync-dispatch`` →
    ``sync-force`` pair brackets an overlapped interval. They are excluded
    from the nesting scan (their wall would otherwise be double-counted
    against whatever host span they land inside, blowing the reconciliation)
    and returned with ``exclusive_s == 0`` and ``overlapped: True`` so the
    wire evidence can account them separately — the force span's exclusive
    wait is the only wall the host actually paid."""
    timed = [r for r in rows if (r.get("dur") or 0.0) > 0.0]
    inflight = [r for r in timed if (r.get("attrs") or {}).get("overlapped")]
    timed = [r for r in timed if not (r.get("attrs") or {}).get("overlapped")]
    timed.sort(key=lambda r: (r["t_start"], -(r["t_start"] + r["dur"])))
    eps = 1e-9
    stack: List[Tuple[float, Dict[str, Any]]] = []
    out: List[Dict[str, Any]] = []
    for r in timed:
        start = float(r["t_start"])
        dur = float(r["dur"])
        while stack and start >= stack[-1][0] - eps:
            stack.pop()
        rec = {
            "site": r.get("site"),
            "dur": dur,
            "attrs": r.get("attrs") or {},
            "child_s": 0.0,
            "parent": stack[-1][1]["site"] if stack else None,
            "top": not stack,
        }
        if stack:
            stack[-1][1]["child_s"] += dur
        out.append(rec)
        stack.append((start + dur, rec))
    for rec in out:
        rec["exclusive_s"] = max(0.0, rec["dur"] - rec["child_s"])
    for r in inflight:
        out.append(
            {
                "site": r.get("site"),
                "dur": float(r["dur"]),
                "attrs": r.get("attrs") or {},
                "child_s": 0.0,
                "parent": None,
                "top": False,
                "overlapped": True,
                "exclusive_s": 0.0,
            }
        )
    return out


def _phase_of(site: Any) -> str:
    return SITE_PHASES.get(site, "host")


def phase_columns(
    before: Dict[str, Dict[str, Any]], after: Dict[str, Dict[str, Any]]
) -> Dict[str, float]:
    """Per-phase total milliseconds between two ``telemetry.latency_stats()``
    snapshots — the cheap windowed phase columns ``tools/bench_sweep.py``
    archives per row and ``tools/sweep_regress.py --explain`` consumes.
    INCLUSIVE sums (no interval data in a histogram): a flush's nested
    dispatches count in both ``flush`` and ``dispatch`` — consistent across
    artifacts, which is all a round-over-round delta needs."""
    out: Dict[str, float] = {}
    for site, block in after.items():
        if site.startswith(_telemetry._DEVICE_HIST_SITE + ":"):
            continue  # per-program families: the aggregate site carries them
        prev = float((before.get(site) or {}).get("sum_s", 0.0))
        delta = float(block.get("sum_s", 0.0)) - prev
        if delta > 0:
            phase = _phase_of(site)
            out[phase] = out.get(phase, 0.0) + delta * 1000.0
    return {k: round(v, 4) for k, v in sorted(out.items())}


#: The span sites that ARE payload transports (carry wire bytes and count
#: as collectives in the wire evidence); the in-flight metadata/cross-check
#: exchanges and the force's wait wall are wire-phase time, not collectives.
_WIRE_TRANSPORT_SITES = ("sync-payload-gather", "sync-gather")


def _wire_evidence(recs: List[Dict[str, Any]], wire_s: float, sync_wall_s: float) -> Dict[str, Any]:
    nbytes = 0
    collectives = 0
    overlapped_s = 0.0
    waited_s = 0.0
    blocking_transport_s = 0.0
    for rec in recs:
        if rec.get("overlapped"):
            # in-flight wire spans (dispatcher thread): their wall coexists
            # with host compute — accounted here, never against host wall
            overlapped_s += rec["dur"]
            if rec["site"] in _WIRE_TRANSPORT_SITES:
                nbytes += int(rec["attrs"].get("bytes", 0) or 0)
                collectives += 1
            continue
        if rec["site"] == "sync-force":
            waited_s += float(rec["attrs"].get("waited_s", 0.0) or 0.0)
        elif _phase_of(rec["site"]) == "wire":
            collectives += 1
            nbytes += int(rec["attrs"].get("bytes", 0) or 0)
            blocking_transport_s += rec["exclusive_s"]
    # effective rate divides by TRANSPORT wall only: blocking transport
    # spans plus in-flight spans. The sync-force wait is wire-phase TIME for
    # attribution, but it covers the same window the in-flight span already
    # measures — adding it would double-count and understate the rate.
    transport_s = blocking_transport_s + overlapped_s
    # the hidden fraction: how much of the in-flight wire wall the host never
    # blocked on (waited_s is the force-side wait actually paid). 0.0 with no
    # async syncs in the window; >= 0.5 is the certification bar on the
    # simulated slow transport.
    hidden = 0.0
    if overlapped_s > 0:
        hidden = max(0.0, min(1.0, (overlapped_s - waited_s) / overlapped_s))
    return {
        "bytes_gathered": nbytes,
        "collectives": collectives,
        "effective_bytes_per_s": (nbytes / transport_s) if transport_s > 0 else 0.0,
        "wire_share_of_sync": (wire_s / sync_wall_s) if sync_wall_s > 0 else 0.0,
        "overlapped_wire_s": round(overlapped_s, 6),
        "forced_wait_s": round(waited_s, 6),
        "wire_hidden_fraction": round(hidden, 4),
    }


def _reconcile(attributed_s: float, measured_s: float) -> Dict[str, Any]:
    coverage = (attributed_s / measured_s) if measured_s > 0 else 0.0
    return {
        "attributed_s": round(attributed_s, 6),
        "measured_wall_s": round(measured_s, 6),
        "coverage": round(coverage, 4),
        "tolerance": TOLERANCE,
        "within_tolerance": measured_s > 0 and abs(coverage - 1.0) <= TOLERANCE,
    }


def _opportunity(phase: str, block: Dict[str, Any], report: Dict[str, Any]) -> str:
    """One evidence sentence per ranked phase (the 'why' next to the
    'where') — each names the roadmap lever that attacks it."""
    total_ms = block["total_s"] * 1e3
    n = block["spans"]
    if phase == "wire":
        w = report["sync"]["wire"]
        mbps = w["effective_bytes_per_s"] / 1e6
        if w.get("overlapped_wire_s", 0.0) > 0:
            return (
                f"{w['bytes_gathered']} B over {w['collectives']} collective(s) at "
                f"{mbps:.1f} MB/s effective; {w['wire_hidden_fraction']:.0%} of the "
                f"in-flight wire wall hidden behind compute (async sync) — raise the "
                "overlap window or shrink the payload (METRICS_TPU_SYNC_QUANT)"
            )
        return (
            f"{w['bytes_gathered']} B over {w['collectives']} collective(s) at "
            f"{mbps:.1f} MB/s effective — overlap the gather (sync_async futures) "
            "or shrink the payload (METRICS_TPU_SYNC_QUANT), ROADMAP #3"
        )
    if phase == "compile":
        return (
            f"{n} compile event(s), {total_ms:.1f} ms — AOT precompile + a "
            "persistent cross-process program cache removes this from steady "
            "state, ROADMAP #4"
        )
    if phase == "dispatch":
        mean_us = (block["total_s"] / n * 1e6) if n else 0.0
        return (
            f"{n} program dispatch(es), mean {mean_us:.1f} us host wall — raise "
            "the deferral window or arena-batch same-config suites, ROADMAP #2"
        )
    if phase == "device":
        worst = ""
        for row in report.get("programs") or ():
            rl = row.get("roofline") or {}
            if rl.get("bound") in ("compute-bound", "memory-bound"):
                worst = (
                    f"; heaviest: {row.get('program')} {rl['bound']} at "
                    f"{rl['achieved_flops_per_s'] / 1e9:.2f} GFLOP/s"
                )
                break
        return f"{n} probed dispatch(es), {total_ms:.1f} ms device-only wall{worst}"
    if phase == "enqueue":
        return (
            f"{n} suite step(s), {total_ms:.1f} ms host enqueue/validation — "
            "moving the step in-graph (state-as-pytree core) removes the "
            "per-call python entirely, ROADMAP #1"
        )
    if phase in ("pack", "unpack", "serialize"):
        return f"{n} span(s), {total_ms:.1f} ms {phase} work around the collective"
    if phase == "orchestrate":
        return f"{total_ms:.1f} ms suite-sync residual (member walk + eligibility)"
    if phase == "flush":
        return f"{n} flush(es), {total_ms:.1f} ms stacking/bucketing beyond the programs dispatched"
    return f"{n} span(s), {total_ms:.1f} ms"


def perf_report(
    measured_wall_s: Optional[float] = None,
    top: int = 5,
) -> Dict[str, Any]:
    """The step-latency decomposition: where the time in the current span
    ring actually went, reconciled and ranked.

    ``measured_wall_s`` (optional) is an externally measured end-to-end wall
    for the same window (e.g. ``perf_counter`` around the driven loop after
    ``clear_spans()``); the top-level reconciliation then states how much of
    REAL time the spans explain — the certification requires coverage
    within :data:`TOLERANCE` over a live suite loop. Without it, the
    reconciliation is against the top-level span wall (coverage 1.0 by
    construction — phase exactness, not coverage, is the claim). The scan
    reads THIS process's span ring; cross-rank views go through
    ``fleet_perf_report()`` (per-rank reports merged — never one scan over
    clock-skewed multi-rank rings) or ``trace_report.py --perf`` (per-pid
    scans over an exported trace).

    Returns a schema-stable dict: ``phases`` (every phase's exclusive
    seconds + span count), ``step`` / ``sync`` sub-blocks with their own
    walls and reconciliations (sync carries the ``wire`` evidence:
    bytes gathered, effective bytes/s, wire share), ``programs`` (the
    roofline ledger join from ``engine.program_report``), ``device_probe``
    (sampling state), and ``opportunities`` — the top-``top`` phases by
    exclusive time, each with its evidence sentence.

    Example:
        >>> from metrics_tpu import perf_report
        >>> report = perf_report()
        >>> report["perf_schema"]
        1
        >>> sorted(report["phases"]) == sorted(PHASES)
        True
        >>> 0.0 <= report["sync"]["wire"]["wire_share_of_sync"] <= 1.0
        True
    """
    from metrics_tpu.ops import engine as _engine

    _counters["perf_reports"] += 1
    recs = _exclusive_spans(_telemetry.spans())
    phases: Dict[str, Dict[str, Any]] = {
        p: {"total_s": 0.0, "spans": 0} for p in PHASES
    }
    top_level_s = 0.0
    step_wall_s = 0.0
    sync_wall_s = 0.0
    for rec in recs:
        if rec.get("overlapped"):
            continue  # in-flight wire: accounted in the wire evidence block
        block = phases[_phase_of(rec["site"])]
        block["total_s"] += rec["exclusive_s"]
        block["spans"] += 1
        if rec["top"]:
            top_level_s += rec["dur"]
            if rec["site"] == "suite-sync":
                sync_wall_s += rec["dur"]
            else:
                step_wall_s += rec["dur"]

    stats = _engine.engine_stats()
    step_attr = sum(phases[p]["total_s"] for p in _STEP_PHASES)
    sync_attr = sum(phases[p]["total_s"] for p in _SYNC_PHASES)
    wire_s = phases["wire"]["total_s"]

    report: Dict[str, Any] = {
        "perf_schema": 1,
        "spans_decomposed": len(recs),
        "phases": {
            p: {"total_s": round(b["total_s"], 6), "spans": b["spans"]}
            for p, b in phases.items()
        },
        "step": {
            "measured_wall_s": round(step_wall_s, 6),
            "steps": phases["enqueue"]["spans"],
            "phases": {p: round(phases[p]["total_s"], 6) for p in _STEP_PHASES},
        },
        "sync": {
            "measured_wall_s": round(sync_wall_s, 6),
            "syncs": phases["orchestrate"]["spans"],
            "phases": {p: round(phases[p]["total_s"], 6) for p in _SYNC_PHASES},
            "wire": _wire_evidence(recs, wire_s, sync_wall_s),
            "reconciliation": _reconcile(sync_attr, sync_wall_s),
        },
        "reconciliation": _reconcile(
            sum(b["total_s"] for b in phases.values()),
            top_level_s if measured_wall_s is None else float(measured_wall_s),
        ),
        "device_probe": {
            "every": _engine.device_probe_every(),
            "probes": stats.get("device_probes", 0),
        },
        "programs": _engine.program_report(analyze=True),
    }
    ranked = sorted(
        ((p, b) for p, b in phases.items() if b["total_s"] > 0),
        key=lambda kv: -kv[1]["total_s"],
    )
    total = sum(b["total_s"] for b in phases.values()) or 1.0
    report["opportunities"] = [
        {
            "phase": p,
            "total_s": round(b["total_s"], 6),
            "share": round(b["total_s"] / total, 4),
            "evidence": _opportunity(p, b, report),
        }
        for p, b in ranked[: max(1, top)]
    ]
    return report
