"""Failure-domain engine: classification, degradation ladders, fault injection.

Every fallback in the dispatch stack routes through this module instead of
rolling its own "fail once → warn forever" logic:

- **Classification** (:func:`classify`): maps a raised exception onto one of
  the failure domains declared in :mod:`metrics_tpu.utils.exceptions`
  (``trace`` / ``compile`` / ``runtime`` / ``donation`` / ``host`` /
  ``sync``), so ``engine.py``, ``Metric``'s fused paths,
  ``MetricCollection``'s flush fallbacks and ``parallel/sync.py`` stop
  treating every ``Exception`` identically. The domain decides telemetry,
  warning dedupe, and whether the ladder may recover.

- **Degradation ladder** (:class:`Ladder`, :func:`demote`,
  :func:`ladder`): a per-owner-per-lane state machine over the tiers
  ``fused → chunked → eager → host``. A demotion records its domain; when the
  domain is recoverable (compile/runtime/donation — transient by nature, e.g.
  HBM pressure during compile), the owner earns a **recovery edge**: after N
  clean steps at the degraded tier (``METRICS_TPU_FAULT_RECOVERY_STEPS``,
  default 8, doubling per repeated failure up to a cap — exponential backoff)
  the demoted path is re-armed and re-probed. Trace-domain demotions (an
  untraceable configuration) never recover: the same config would fail the
  same way, and the silent-decline contract stays intact.

- **Deterministic fault injection** (:func:`inject_faults`,
  ``METRICS_TPU_FAULTS``): named sites instrumented throughout the stack
  (``probe``, ``compile``, ``flush-chunk-<k>``, ``donation``,
  ``sync-gather``, ``host-offload``) fire classified exceptions on demand, so
  every ladder transition is testable without a flaky backend. When no plan
  is armed the per-site check is a single module-attribute read
  (:data:`armed`), keeping the hot paths at their measured cost
  (``bench.py`` ``fault_overhead`` row).

- **Telemetry**: per-domain fault counters and a bounded ``failure_log``
  ring buffer, surfaced through ``engine.engine_stats()``; plus
  :func:`warn_fault`, which dedupes fallback warnings per ``owner+domain``
  (a pathological loop used to emit one warning per step).
"""
from __future__ import annotations

import os
import time
import weakref
from collections import deque
from contextlib import contextmanager
from typing import Any, Dict, Iterator, List, Optional

from metrics_tpu.ops import telemetry as _telemetry
from metrics_tpu.utils.exceptions import (
    FAULT_DOMAINS,
    CompileFault,
    DonationFault,
    EpochFault,
    FaultError,
    HostOffloadFault,
    IngestFault,
    JournalFault,
    RuntimeFault,
    SyncFault,
    TraceFault,
)
from metrics_tpu.utils.prints import rank_zero_warn

__all__ = [
    "FAULT_SITES",
    "Ladder",
    "TIERS",
    "armed",
    "classify",
    "clear_fault_state",
    "current_step",
    "demote",
    "fault_stats",
    "inject_faults",
    "ladder",
    "maybe_fail",
    "note_fault",
    "recovery_steps",
    "reset_warn_dedupe",
    "set_recovery_policy",
    "tick",
    "warn_fault",
]

# ------------------------------------------------------------------ the tiers
#: Degradation-ladder tiers, best first. ``fused`` is the single-dispatch (or
#: deferred micro-batched) program path; ``chunked`` the stacked-scan flush /
#: batched API; ``eager`` the per-op validated path; ``host`` the pure-host
#: fallback (list appends, host counters) that cannot fail on the device.
TIERS = ("fused", "chunked", "eager", "host")

#: Named injection sites instrumented across the stack. ``flush-chunk-<k>``
#: is the indexed family (``flush-chunk`` matches every chunk). ``sync-pack``
#: fires at the entry of the coalesced bucketed-sync pack phase
#: (``parallel/bucketing.py``) — before any collective, so an injected fault
#: exercises the demote-to-per-state ladder with local state intact.
#: ``journal-write`` fires before a journal record's temp file is written
#: (previous generations stay intact by construction); ``journal-load`` fires
#: before a stored record is read, modelling an unreadable newest generation.
#: ``epoch-fence`` models a membership change racing a collective: the
#: injected ``EpochFault`` is what the real fence raises when a protocol's
#: entry epoch goes stale mid-flight. ``progcache-load``/``progcache-store``
#: fire before a persistent program-cache entry is read/written: a load
#: failure demotes the store's ``progcache`` ladder lane so traffic falls
#: back to fresh compiles (never a wrong program). ``ingest-admit`` fires at
#: the gateway door before a payload is staged (modelling poison admission);
#: ``ingest-shed`` fires in the overload shed/flush path — both are settled
#: into the gateway's exact accounting instead of raising into the caller.
#: ``autotune-sweep`` fires while a non-reference kernel variant is being
#: evaluated: the injected fault disqualifies that candidate classified and
#: the reference variant keeps serving (the autotuner's floor is never at
#: risk from a poisoned variant).
FAULT_SITES = (
    "probe",
    "compile",
    "flush-chunk",
    "donation",
    "sync-gather",
    "sync-pack",
    "epoch-fence",
    "host-offload",
    "journal-write",
    "journal-load",
    "progcache-load",
    "progcache-store",
    "ingest-admit",
    "ingest-shed",
    "autotune-sweep",
)

_SITE_DEFAULT_EXC = {
    "probe": TraceFault,
    "compile": CompileFault,
    "flush-chunk": RuntimeFault,
    "donation": DonationFault,
    "sync-gather": SyncFault,
    # runtime domain: recoverable, so the sync-pack ladder earns the
    # demote -> clean-syncs -> re-promote edge
    "sync-pack": RuntimeFault,
    # sync domain: a stale-epoch collective attempt (membership changed
    # mid-protocol) — the fence raises it instead of issuing
    "epoch-fence": EpochFault,
    "host-offload": HostOffloadFault,
    "journal-write": JournalFault,
    "journal-load": JournalFault,
    # journal domain: a persistent program-cache entry is an on-disk record
    # with the same corruption surface as a journal record — and the same
    # recovery story (demote to a fresh compile, never a wrong program)
    "progcache-load": JournalFault,
    "progcache-store": JournalFault,
    # ingest domain: admission-control events — a payload rejected at the
    # gateway door (poison quarantine) or evicted from staging under overload
    "ingest-admit": IngestFault,
    "ingest-shed": IngestFault,
    # runtime domain: a kernel-variant candidate dying mid-sweep — the
    # autotuner disqualifies it classified and the reference stays the floor
    "autotune-sweep": RuntimeFault,
}

_DOMAIN_EXC = {
    "trace": TraceFault,
    "compile": CompileFault,
    "runtime": RuntimeFault,
    "donation": DonationFault,
    "host": HostOffloadFault,
    "sync": SyncFault,
    "journal": JournalFault,
    "ingest": IngestFault,
}


# ------------------------------------------------------------- classification
def classify(exc: BaseException, default: str = "runtime") -> str:
    """Map a raised exception to a failure domain.

    Classified :class:`FaultError`\\ s carry their own domain. For foreign
    exceptions the verdict is structural where possible — jax trace errors
    (concretization, tracer leaks) are ``trace``; XLA messages naming
    compilation or resource exhaustion are ``compile``; deleted/donated
    buffer complaints are ``donation`` — and falls back to ``default``
    (the catching site knows which stage it was executing).
    """
    if isinstance(exc, FaultError):
        return exc.domain
    try:
        import jax

        trace_types = tuple(
            t
            for t in (
                getattr(jax.errors, "TracerArrayConversionError", None),
                getattr(jax.errors, "TracerBoolConversionError", None),
                getattr(jax.errors, "TracerIntegerConversionError", None),
                getattr(jax.errors, "ConcretizationTypeError", None),
                getattr(jax.errors, "UnexpectedTracerError", None),
            )
            if t is not None
        )
        if trace_types and isinstance(exc, trace_types):
            return "trace"
    except Exception:  # pragma: no cover - jax always importable in-tree
        pass
    # structural stdlib mappings: a TimeoutError is deadline/hang shaped (the
    # watchdog's SyncTimeoutFault is already classified above via FaultError);
    # any other OSError/IOError is host-or-disk I/O — journal when the
    # catching site is storage, otherwise the site's default I/O-ish domain.
    if isinstance(exc, TimeoutError):
        return "sync"
    if isinstance(exc, OSError):
        return default if default in ("journal", "host", "sync") else "journal"
    text = f"{type(exc).__name__}: {exc}".lower()
    if "donat" in text or "deleted" in text or "buffer has been deleted" in text:
        return "donation"
    if "compil" in text or "resource_exhausted" in text or "out of memory" in text:
        return "compile"
    if "tracer" in text or "abstract" in text:
        return "trace"
    return default if default in FAULT_DOMAINS else "runtime"


def domain_recoverable(domain: str) -> bool:
    """Whether the ladder may re-probe after a failure in ``domain``.

    Trace failures are structural (same config → same failure) and stay
    declined; everything else can be transient and earns a recovery edge.
    """
    return domain != "trace"


# ------------------------------------------------------------------ telemetry
_FAILURE_LOG_CAP = 64

_counters: Dict[str, int] = {f"fault_{d}": 0 for d in FAULT_DOMAINS}
_counters.update({"fault_demotions": 0, "fault_promotions": 0, "fault_injected": 0})
_failure_log: "deque[Dict[str, Any]]" = deque(maxlen=_FAILURE_LOG_CAP)

# Monotonic event index shared by the failure log and the sync-health
# surface: every recorded fault AND every recorded good sync advances it, so
# ``Metric.sync_health()`` can report "last-good sync step" relative to the
# ring entries without a separate per-owner counter. Never reset (not even by
# ``clear_fault_state``) — monotonicity is the whole point.
_monotonic_step: int = 0


def tick() -> int:
    """Advance and return the monotonic fault/sync event index."""
    global _monotonic_step
    _monotonic_step += 1
    return _monotonic_step


def current_step() -> int:
    """The current monotonic event index (last value :func:`tick` returned)."""
    return _monotonic_step


# telemetry spans are stamped with THIS index (one ordering axis for the span
# ring and the failure log); telemetry cannot import us — we import it
_telemetry._step_provider = current_step


def note_fault(
    domain: str,
    *,
    site: Optional[str] = None,
    owner: Any = None,
    error: Optional[BaseException] = None,
) -> None:
    """Count one fault in its domain and append it to the ring buffer (each
    entry stamped with the monotonic ``step`` index)."""
    key = f"fault_{domain}"
    if key not in _counters:
        key = "fault_runtime"
    _counters[key] += 1
    _failure_log.append(
        {
            "step": tick(),
            "domain": domain,
            "site": site,
            "owner": type(owner).__name__ if owner is not None else None,
            "error": f"{type(error).__name__}: {error}" if error is not None else None,
        }
    )
    if _telemetry.armed:
        _telemetry.emit(
            "fault",
            owner,
            domain,
            attrs={"site": site, "error": type(error).__name__ if error is not None else None},
        )


def fault_stats() -> Dict[str, Any]:
    """Per-domain fault counters plus demotion/promotion totals and the
    bounded ``failure_log`` ring buffer (newest last). Merged into
    ``engine.engine_stats()``."""
    out: Dict[str, Any] = dict(_counters)
    out["failure_log"] = list(_failure_log)
    return out


def clear_fault_state() -> None:
    """Zero the module-global counters and drop the failure log (tests;
    called by ``engine.reset_engine``). Per-owner state — ladders and
    warn-dedupe markers — lives on the owner instances themselves and is
    untouched: an already-demoted metric keeps its ladder (and its backoff)
    until it recovers or is rebuilt."""
    for key in _counters:
        _counters[key] = 0
    _failure_log.clear()


_telemetry.register_reset("faults", clear_fault_state)


# ------------------------------------------------------- warning hygiene
# Weak registry of every owner carrying a warn-dedupe marker: the markers
# themselves live on the instances (dying with them — no id-reuse leak), but
# chaos/CI sweeps need to clear them deterministically between scenarios
# without holding the owners alive. `reset_warn_dedupe` (the
# `reset_stats(reset_warnings=True)` opt-in) walks this set.
_warned_owners: "weakref.WeakSet" = weakref.WeakSet()


def reset_warn_dedupe() -> None:
    """Clear every live owner's ``warn_fault`` dedupe markers, so the next
    fault in any domain warns again. Explicit opt-in
    (``engine.reset_stats(reset_warnings=True)``) — the default warn-once
    lifetime deliberately survives counter resets: an operator zeroing a
    counter window must not re-trigger a warning storm."""
    for owner in list(_warned_owners):
        warned = getattr(owner, "_fault_warned", None)
        if warned is not None:
            warned.clear()


_telemetry.register_warning_reset("faults", reset_warn_dedupe)


def warn_fault(owner: Any, domain: str, message: str) -> bool:
    """Emit ``message`` once per ``owner+domain``; later faults in the same
    domain on the same owner only count in telemetry.

    The dedupe marker lives on the owner itself (not a global id-keyed map,
    which would leak across id reuse), so it dies with the instance —
    ``reset_warn_dedupe`` (via ``engine.reset_stats(reset_warnings=True)``)
    is the explicit opt-in that clears the markers early. Returns
    True when the warning was actually emitted.
    """
    warned = owner.__dict__.get("_fault_warned") if owner is not None else None
    if warned is None:
        warned = set()
        if owner is not None:
            object.__setattr__(owner, "_fault_warned", warned)
    if owner is not None:
        try:
            _warned_owners.add(owner)
        except TypeError:  # non-weakrefable owner: marker still dedupes
            pass
    if domain in warned:
        return False
    warned.add(domain)
    rank_zero_warn(
        message
        + f" [fault domain: {domain}; further {domain}-domain warnings for this owner are "
        "suppressed — see engine_stats()['failure_log']]"
    )
    return True


# ----------------------------------------------------------- recovery policy
_recovery_steps: Optional[int] = None
_recovery_max_exponent: int = 6


def recovery_steps() -> int:
    """Clean steps required at a degraded tier before the first re-probe
    (``METRICS_TPU_FAULT_RECOVERY_STEPS``, default 8). Doubles per repeated
    failure of the same lane — exponential backoff — up to
    ``base * 2**max_exponent``. ``0`` disables recovery entirely (the
    pre-ladder permanent-demotion behavior)."""
    global _recovery_steps
    if _recovery_steps is None:
        try:
            _recovery_steps = max(0, int(os.environ.get("METRICS_TPU_FAULT_RECOVERY_STEPS", "8")))
        except ValueError:
            _recovery_steps = 8
    return _recovery_steps


def set_recovery_policy(steps: Optional[int] = None, *, max_exponent: Optional[int] = None) -> None:
    """Override the recovery policy at runtime (None leaves a knob unchanged;
    takes precedence over the environment variable)."""
    global _recovery_steps, _recovery_max_exponent
    if steps is not None:
        _recovery_steps = max(0, int(steps))
    if max_exponent is not None:
        _recovery_max_exponent = max(0, int(max_exponent))


# ----------------------------------------------------------------- the ladder
class Ladder:
    """Degradation state for one owner lane (``update`` / ``forward`` /
    ``defer`` / ``many`` / ``suite`` / ``host`` …).

    Explicit state machine over :data:`TIERS`:

    - ``demote(domain, to=...)`` — a classified failure moves the lane down
      and records the domain. Repeated failures double the re-probe
      threshold (exponential backoff).
    - ``note_clean()`` — one successful step at the degraded tier. Returns
      True when the recovery edge fires: the owner should re-arm the demoted
      path (and re-probe it before trusting it).
    - ``promote()`` — the owner re-armed the path; the lane returns to its
      best tier. A later failure demotes again with a doubled threshold.
    """

    __slots__ = ("lane", "tier", "domain", "failures", "clean", "threshold", "history")

    def __init__(self, lane: str):
        self.lane = lane
        self.tier = TIERS[0]
        self.domain: Optional[str] = None
        self.failures = 0
        self.clean = 0
        self.threshold = 0
        self.history: List[str] = []

    @property
    def demoted(self) -> bool:
        return self.tier != TIERS[0]

    @property
    def recoverable(self) -> bool:
        return (
            self.demoted
            and self.domain is not None
            and domain_recoverable(self.domain)
            and recovery_steps() > 0
        )

    def demote(self, domain: str, to: str = "eager") -> None:
        self.domain = domain
        self.tier = to if to in TIERS else "eager"
        self.failures += 1
        self.clean = 0
        base = recovery_steps()
        exponent = min(self.failures - 1, _recovery_max_exponent)
        self.threshold = base * (2**exponent) if base else 0
        self.history.append(f"demote:{domain}:{self.tier}")
        if len(self.history) > 32:
            del self.history[:-32]
        _counters["fault_demotions"] += 1
        if _telemetry.armed:
            _telemetry.emit(
                "ladder-demote",
                None,
                self.lane,
                attrs={"domain": domain, "tier": self.tier, "failures": self.failures},
            )

    def note_clean(self, n: int = 1) -> bool:
        if not self.recoverable:
            return False
        self.clean += n
        return self.clean >= self.threshold

    def promote(self) -> None:
        self.tier = TIERS[0]
        self.clean = 0
        self.history.append("promote")
        if len(self.history) > 32:
            del self.history[:-32]
        _counters["fault_promotions"] += 1
        if _telemetry.armed:
            _telemetry.emit("ladder-promote", None, self.lane, attrs={"failures": self.failures})


def ladder(owner: Any, lane: str) -> Ladder:
    """The per-owner ladder for ``lane``, created on first use. Stored in the
    owner's ``__dict__`` (bypassing any ``__setattr__`` barrier) so it dies
    with the instance and survives pickling drops."""
    ladders = owner.__dict__.get("_fault_ladders")
    if ladders is None:
        ladders = {}
        object.__setattr__(owner, "_fault_ladders", ladders)
    lad = ladders.get(lane)
    if lad is None:
        lad = Ladder(lane)
        ladders[lane] = lad
    return lad


def demote(
    owner: Any,
    lane: str,
    exc: BaseException,
    *,
    default_domain: str = "runtime",
    tier: str = "eager",
    site: Optional[str] = None,
    warn: Optional[str] = None,
    count: bool = True,
) -> str:
    """One-call failure handling: classify ``exc``, count it, demote the
    owner's ``lane`` ladder, and (optionally) emit the owner+domain-deduped
    warning. Returns the classified domain so callers can branch.

    ``count=False`` skips the per-domain counter + ring entry — for callers
    reacting to a failure that was ALREADY recorded at its raise site (the
    degraded-compute and auto-journal handlers), so one incident never shows
    up twice in ``engine_stats()``. The demotion itself still counts in
    ``fault_demotions``."""
    domain = classify(exc, default_domain)
    if count:
        note_fault(domain, site=site, owner=owner, error=exc)
    ladder(owner, lane).demote(domain, to=tier)
    if warn:
        warn_fault(owner, domain, warn)
    return domain


# ----------------------------------------------------------- fault injection
class _Plan:
    """One armed injection: fire ``count`` classified exceptions at ``site``."""

    __slots__ = ("site", "remaining", "exc_type", "message", "fired")

    def __init__(self, site: str, count: int, exc_type: type, message: Optional[str]):
        self.site = site
        self.remaining = count
        self.exc_type = exc_type
        self.message = message
        self.fired = 0


_plans: Dict[str, List[_Plan]] = {}

#: Hot-path guard: call sites check ``faults.armed`` (one attribute read)
#: before calling :func:`maybe_fail`, so the instrumentation costs nothing
#: when no plan (and no ``METRICS_TPU_FAULTS``) is active.
armed: bool = False


def _rearm() -> None:
    global armed
    armed = bool(_plans)


def _site_exc(site: str, domain: Optional[str]) -> type:
    if domain is not None:
        return _DOMAIN_EXC.get(domain, RuntimeFault)
    family = site.rsplit("-", 1)[0] if site.startswith("flush-chunk") else site
    return _SITE_DEFAULT_EXC.get(family, _SITE_DEFAULT_EXC.get(site, RuntimeFault))


@contextmanager
def inject_faults(
    site: str,
    count: int = 1,
    *,
    domain: Optional[str] = None,
    message: Optional[str] = None,
) -> Iterator[_Plan]:
    """Deterministically fire ``count`` classified failures at ``site``.

    ``site`` is one of :data:`FAULT_SITES` (``flush-chunk`` fires at every
    chunk; ``flush-chunk-2`` only at chunk index 2). ``domain`` overrides the
    site's default exception class. The yielded plan exposes ``fired`` for
    assertions. Plans nest and stack (multiple contexts on the same site fire
    in installation order)::

        with inject_faults("flush-chunk-1") as plan:
            metric.compute()            # flush: chunk 1 dies, ladder engages
        assert plan.fired == 1
    """
    plan = _Plan(site, count, _site_exc(site, domain), message)
    _plans.setdefault(site, []).append(plan)
    _rearm()
    try:
        yield plan
    finally:
        stack = _plans.get(site)
        if stack is not None:
            try:
                stack.remove(plan)
            except ValueError:
                pass
            if not stack:
                _plans.pop(site, None)
        _rearm()


def _env_plans() -> None:
    """``METRICS_TPU_FAULTS="site[:count[:domain]],..."`` arms plans at import
    (e.g. ``probe:1,sync-gather:2:sync``) — the no-code-change hook for
    soak/chaos runs."""
    spec = os.environ.get("METRICS_TPU_FAULTS", "")
    if not spec:
        return
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        fields = part.split(":")
        site = fields[0]
        try:
            count = int(fields[1]) if len(fields) > 1 and fields[1] else 1
        except ValueError:
            count = 1
        domain = fields[2] if len(fields) > 2 and fields[2] else None
        _plans.setdefault(site, []).append(_Plan(site, count, _site_exc(site, domain), None))
    _rearm()


_env_plans()


def maybe_fail(site: str, index: Optional[int] = None) -> None:
    """Fire the next armed plan matching ``site`` (or ``site-<index>``), if
    any. Call sites guard with ``if faults.armed:`` so this function only
    runs while an injection context (or the env hook) is active."""
    if not _plans:
        return
    names = (site,) if index is None else (f"{site}-{index}", site)
    for name in names:
        stack = _plans.get(name)
        if not stack:
            continue
        for plan in stack:
            if plan.remaining > 0:
                plan.remaining -= 1
                plan.fired += 1
                _counters["fault_injected"] += 1
                exc = plan.exc_type(
                    plan.message or f"injected {plan.exc_type.__name__} at site {name!r}",
                    site=name,
                )
                raise exc
    return


# ------------------------------------------------------------- retry helpers
def retry_with_backoff(fn, *, attempts: int, base_delay_s: float, owner: Any = None, site: str = "sync-gather"):
    """Run ``fn()`` with up to ``attempts`` retries and exponential backoff,
    counting every failure in the sync domain. Raises the LAST failure,
    classified, when the budget is exhausted. Used by
    ``parallel.sync.gather_all_tensors`` — a transient DCN hiccup retries
    instead of poisoning the sync; local state is untouched on failure
    because the caller snapshots before gathering."""
    delay = base_delay_s
    last: Optional[BaseException] = None
    for attempt in range(attempts + 1):
        try:
            return fn()
        except EpochFault:
            # the epoch fence already classified and counted it; a re-issued
            # collective at a stale epoch can never pair with the new cohort,
            # so the retry budget does not apply — the caller re-enters the
            # whole protocol at the current epoch instead
            raise
        except Exception as exc:  # noqa: BLE001 — classified + rethrown below
            last = exc
            note_fault(classify(exc, "sync"), site=site, owner=owner, error=exc)
            if attempt == attempts:
                break
            time.sleep(delay)
            delay *= 2
    if isinstance(last, FaultError):
        raise last
    raise SyncFault(
        f"distributed gather failed after {attempts + 1} attempt(s): "
        f"{type(last).__name__}: {last}",
        site=site,
    ) from last
