"""Fused (weighted) bincount — the scatter-free TPU histogram.

Reference counterpart: `src/torchmetrics/utilities/data.py:244-264` (torch
``bincount`` plus a CUDA-determinism fallback loop). On TPU, scatter-adds
serialize poorly; the MXU-native formulation is a one-hot contraction:

    counts[l] = sum_i w[i] * [x[i] == l]  ==  (w @ one_hot(x, L))[l]

The Pallas kernel tiles ``x`` into ``(1, TN)`` strips and the label axis into
``(1, TL)`` strips, materializes each one-hot tile only in VMEM, and feeds the
``(1, TN) x (TN, TL)`` product to the MXU, accumulating the output strip
in-place across the N-grid dimension. HBM traffic is O(N + L) instead of the
O(N*L) a materialized one-hot would cost — but compare work is still O(N*L),
so on chips where XLA's scatter-add is fast this kernel loses (measured: 76 us
vs 10 us at N=1e6, L=16384); hence it is opt-in via METRICS_TPU_ENABLE_PALLAS
(see `ops/_dispatch.py`). The XLA fallback is a deterministic segment-sum.

Accumulation is float32: counts are exact while each bin stays below 2**24
per update call (callers accumulate across updates in int32/float64 state).
"""
from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

from metrics_tpu.ops import autotune as _autotune
from metrics_tpu.ops._dispatch import pallas_enabled

_TN = 512  # elements of x per grid step
_TL = 512  # label-axis strip width


def _bincount_kernel(x_ref, w_ref, out_ref, *, tl: int):
    import jax.experimental.pallas as pl

    lj = pl.program_id(0)
    ni = pl.program_id(1)

    @pl.when(ni == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    x = x_ref[...]  # (1, TN) int32
    labels = lj * tl + jax.lax.broadcasted_iota(jnp.int32, (x.shape[1], tl), 1)
    # transpose, not x[0, :, None]: integer indexing lowers to an unsupported
    # gather inside Mosaic; transpose+broadcast stays on the VPU
    onehot = (jnp.transpose(x) == labels).astype(jnp.float32)  # (TN, TL)
    out_ref[...] += jnp.dot(w_ref[...], onehot, preferred_element_type=jnp.float32)


def _pallas_weighted_bincount(
    x: jax.Array, weights: jax.Array, length: int, *, interpret: bool = False
) -> jax.Array:
    import jax.experimental.pallas as pl

    n = x.shape[0]
    np_ = -(-n // _TN) * _TN
    lp = -(-length // _TL) * _TL
    # out-of-range pad sentinel: never equals a real (non-negative) label
    x = jnp.pad(x.astype(jnp.int32), (0, np_ - n), constant_values=-1).reshape(1, np_)
    w = jnp.pad(weights.astype(jnp.float32), (0, np_ - n)).reshape(1, np_)

    out = pl.pallas_call(
        partial(_bincount_kernel, tl=_TL),
        grid=(lp // _TL, np_ // _TN),
        in_specs=[
            pl.BlockSpec((1, _TN), lambda lj, ni: (0, ni)),
            pl.BlockSpec((1, _TN), lambda lj, ni: (0, ni)),
        ],
        out_specs=pl.BlockSpec((1, _TL), lambda lj, ni: (0, lj)),
        out_shape=jax.ShapeDtypeStruct((1, lp), jnp.float32),
        interpret=interpret,
    )(x, w)
    return out[0, :length]


def fused_bincount(
    x: jax.Array,
    length: int,
    weights: Optional[jax.Array] = None,
    *,
    force_xla: bool = False,
) -> jax.Array:
    """``bincount(x, weights, minlength=length)`` with a Pallas MXU path on TPU.

    ``x`` is flattened; entries outside ``[0, length)`` are ignored in BOTH
    dispatch paths (the `ignore_index = -1` sentinel convention — unlike
    ``jnp.bincount``, which clips them into bin 0). Returns float32 when
    ``weights`` is given, int32 otherwise. The XLA path is exact for unweighted
    counts (int32 accumulation); the Pallas path accumulates in float32 and is
    exact while each bin stays below 2**24 per call.
    """
    x = jnp.asarray(x).reshape(-1)

    if pallas_enabled() and not force_xla and x.size >= _TN:
        if weights is not None:
            w = jnp.asarray(weights).reshape(-1).astype(jnp.float32)
        else:
            w = jnp.ones_like(x, dtype=jnp.float32)
        counts = _pallas_weighted_bincount(x, w, length)
        if weights is None:
            return jnp.round(counts).astype(jnp.int32)
        return counts

    if weights is None:
        # unweighted counts are pure integers — the one path where every
        # autotuner formulation is bit-exact by construction
        variant = _autotune.dispatch("bincount", (x, length))
        if variant == "scatter_add":
            return _bincount_scatter_add(x, length)
        if variant == "onehot_matmul":
            return _bincount_onehot_matmul(x, length)
        return _bincount_segment_sum(x, length)
    valid = (x >= 0) & (x < length)
    idx = jnp.where(valid, x, 0)
    w = jnp.asarray(weights).reshape(-1).astype(jnp.float32)
    return jax.ops.segment_sum(jnp.where(valid, w, 0.0), idx, num_segments=length)


def _bincount_segment_sum(x: jax.Array, length: int) -> jax.Array:
    """Reference formulation: deterministic XLA segment-sum."""
    valid = (x >= 0) & (x < length)
    idx = jnp.where(valid, x, 0)
    w_int = valid.astype(jnp.int32)
    return jax.ops.segment_sum(w_int, idx, num_segments=length)


def _bincount_scatter_add(x: jax.Array, length: int) -> jax.Array:
    """Scatter-add formulation: a direct indexed-add histogram."""
    valid = (x >= 0) & (x < length)
    idx = jnp.where(valid, x, 0)
    return jnp.zeros((length,), jnp.int32).at[idx].add(valid.astype(jnp.int32))


def _bincount_onehot_matmul(x: jax.Array, length: int) -> jax.Array:
    """One-hot contraction formulation: ``valid @ one_hot(x, L)`` on the
    MXU — O(N·L) compares but scatter-free (counts below 2**24 are exact
    in the f32 accumulator)."""
    valid = (x >= 0) & (x < length)
    idx = jnp.where(valid, x, 0)
    onehot = (idx[:, None] == jnp.arange(length, dtype=idx.dtype)[None, :]).astype(jnp.float32)
    counts = jnp.matmul(
        valid.astype(jnp.float32)[None, :], onehot, precision=jax.lax.Precision.HIGHEST
    )[0]
    return counts.astype(jnp.int32)


# Bit-exact contract (tolerance None): unweighted counts are integers in
# int32 or an exact-below-2**24 f32 accumulator, whatever the formulation.
_autotune.register_variant("bincount", "segment_sum", _bincount_segment_sum, reference=True)
_autotune.register_variant("bincount", "scatter_add", _bincount_scatter_add)
_autotune.register_variant("bincount", "onehot_matmul", _bincount_onehot_matmul)


__all__ = ["fused_bincount"]
