"""Binned-curve count accumulation — one fused compare-contract program.

Reference counterpart: `src/torchmetrics/classification/binned_precision_recall.py:160-180`
(a python loop over thresholds "to conserve memory", O(T) kernel launches).
Here the whole update is ONE XLA program:

    TP[c,t] = sum_n target[n,c] * (preds[n,c] >= thr[t])

expressed as a compare + ``einsum('nc,nct->ct')`` contraction. XLA maps the
contraction onto the MXU and fuses the comparison into it, so the (N,C,T)
intermediate is never materialized in HBM.

Measured on a real TPU chip (N=8192, C=128, T=100, 50-rep mean): this path runs
at the device dispatch floor (~2.4 ms), while the "smart" alternative —
bucketize via ``jnp.searchsorted`` + scatter histogram, O(N*C*log T) — takes
~78 ms because XLA lowers searchsorted to a serial binary-search scan on TPU.
The asymptotically-better algorithm loses by 30x: let the MXU brute-force it.

That 30x is a *measurement on one chip*, not a law: the memory-vs-compute
tradeoff flips with the bins×batch shape and the backend. The bucketize
formulations stay in-tree as autotuner variants (``scatter_add`` /
``segment_sum``, :mod:`metrics_tpu.ops.autotune`): ascending-threshold
bucketing + a per-(class, bucket) histogram + a reversed cumulative sum
recovers exactly the ``>=``-counts — bit-exact for {0,1} targets, O(N·C·logT
+ C·T) work instead of the einsum's O(N·C·T). The sweep decides per shape
class; with ``METRICS_TPU_AUTOTUNE`` off the einsum below always runs.
"""
from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from metrics_tpu.ops import autotune as _autotune
from metrics_tpu.utils.compute import high_precision


@high_precision
def binned_curve_counts(
    preds: jax.Array, target: jax.Array, thresholds: jax.Array
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Per-threshold TP/FP/FN counts for a batch.

    Args:
        preds: ``(N, C)`` float scores.
        target: ``(N, C)`` {0,1} labels.
        thresholds: ``(T,)`` threshold grid.

    Returns:
        ``(TPs, FPs, FNs)`` each of shape ``(C, T)`` float32, where
        ``TPs[c, t] = sum_n target[n,c] * (preds[n,c] >= thresholds[t])`` etc.
    """
    preds = jnp.asarray(preds, dtype=jnp.float32)
    target = jnp.asarray(target, dtype=jnp.float32)
    thresholds = jnp.asarray(thresholds, dtype=jnp.float32)

    variant = _autotune.dispatch("binned_counts", (preds, target, thresholds))
    if variant == "scatter_add":
        return _binned_bucketize(preds, target, thresholds, via_segment_sum=False)
    if variant == "segment_sum":
        return _binned_bucketize(preds, target, thresholds, via_segment_sum=True)
    return _binned_onehot_matmul(preds, target, thresholds)


@high_precision
def _binned_onehot_matmul(
    preds: jax.Array, target: jax.Array, thresholds: jax.Array
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Reference formulation: compare + MXU einsum contraction."""
    ge = (preds[:, :, None] >= thresholds[None, None, :]).astype(jnp.float32)
    tps = jnp.einsum("nc,nct->ct", target, ge)
    ge_total = jnp.einsum("nct->ct", ge)
    pos_total = target.sum(axis=0)[:, None]  # (C, 1)
    fps = ge_total - tps
    fns = pos_total - tps
    return tps, fps, fns


@high_precision
def _binned_bucketize(
    preds: jax.Array, target: jax.Array, thresholds: jax.Array, *, via_segment_sum: bool
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Bucketize formulation: per-(class, bucket) histogram + reversed
    cumulative sum recovers the ``>=``-counts without the O(N·C·T) compare
    tensor. Thresholds are sorted internally (results mapped back through
    the permutation), so any threshold grid matches the einsum; sums of
    {0,1} values below 2**24 per cell are exact in f32 in any order, which
    is what makes the contract bit-exact."""
    n, c = preds.shape
    t = thresholds.shape[0]
    order = jnp.argsort(thresholds)
    sorted_thr = thresholds[order]
    # bucket of each score: how many (sorted) thresholds are <= it
    idx = jnp.searchsorted(sorted_thr, preds.reshape(-1), side="right").reshape(n, c)
    flat = (idx * c + jnp.arange(c, dtype=idx.dtype)[None, :]).reshape(-1)
    if via_segment_sum:
        tp_hist = jax.ops.segment_sum(target.reshape(-1), flat, num_segments=(t + 1) * c)
        all_hist = jax.ops.segment_sum(jnp.ones(n * c, jnp.float32), flat, num_segments=(t + 1) * c)
    else:
        tp_hist = jnp.zeros((t + 1) * c, jnp.float32).at[flat].add(target.reshape(-1))
        all_hist = jnp.zeros((t + 1) * c, jnp.float32).at[flat].add(1.0)
    tp_hist = tp_hist.reshape(t + 1, c)
    all_hist = all_hist.reshape(t + 1, c)
    # preds >= sorted_thr[j]  ⇔  bucket > j: a suffix sum over buckets j+1..T
    tp_ge = jnp.cumsum(tp_hist[::-1], axis=0)[::-1][1:]  # (T, C), sorted order
    all_ge = jnp.cumsum(all_hist[::-1], axis=0)[::-1][1:]
    inv = jnp.argsort(order)  # back to the caller's threshold order
    tps = tp_ge[inv].T  # (C, T)
    ge_total = all_ge[inv].T
    pos_total = target.sum(axis=0)[:, None]  # (C, 1)
    fps = ge_total - tps
    fns = pos_total - tps
    return tps, fps, fns


def _binned_scatter_add(
    preds: jax.Array, target: jax.Array, thresholds: jax.Array
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    return _binned_bucketize(preds, target, thresholds, via_segment_sum=False)


def _binned_segment_sum(
    preds: jax.Array, target: jax.Array, thresholds: jax.Array
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    return _binned_bucketize(preds, target, thresholds, via_segment_sum=True)


# Bit-exact contract (tolerance None): every formulation sums the same {0,1}
# indicator terms; f32 integer-valued sums below 2**24 are order-invariant.
# Fractional targets would break that — the sweep's exactness check
# disqualifies the bucketize variants on such inputs, reference serves.
_autotune.register_variant("binned_counts", "onehot_matmul", _binned_onehot_matmul, reference=True)
_autotune.register_variant("binned_counts", "scatter_add", _binned_scatter_add)
_autotune.register_variant("binned_counts", "segment_sum", _binned_segment_sum)


__all__ = ["binned_curve_counts"]
