"""Binned-curve count accumulation — one fused compare-contract program.

Reference counterpart: `src/torchmetrics/classification/binned_precision_recall.py:160-180`
(a python loop over thresholds "to conserve memory", O(T) kernel launches).
Here the whole update is ONE XLA program:

    TP[c,t] = sum_n target[n,c] * (preds[n,c] >= thr[t])

expressed as a compare + ``einsum('nc,nct->ct')`` contraction. XLA maps the
contraction onto the MXU and fuses the comparison into it, so the (N,C,T)
intermediate is never materialized in HBM.

Measured on a real TPU chip (N=8192, C=128, T=100, 50-rep mean): this path runs
at the device dispatch floor (~2.4 ms), while the "smart" alternative —
bucketize via ``jnp.searchsorted`` + scatter histogram, O(N*C*log T) — takes
~78 ms because XLA lowers searchsorted to a serial binary-search scan on TPU.
The asymptotically-better algorithm loses by 30x: let the MXU brute-force it.
"""
from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from metrics_tpu.utils.compute import high_precision


@high_precision
def binned_curve_counts(
    preds: jax.Array, target: jax.Array, thresholds: jax.Array
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Per-threshold TP/FP/FN counts for a batch.

    Args:
        preds: ``(N, C)`` float scores.
        target: ``(N, C)`` {0,1} labels.
        thresholds: ``(T,)`` threshold grid.

    Returns:
        ``(TPs, FPs, FNs)`` each of shape ``(C, T)`` float32, where
        ``TPs[c, t] = sum_n target[n,c] * (preds[n,c] >= thresholds[t])`` etc.
    """
    preds = jnp.asarray(preds, dtype=jnp.float32)
    target = jnp.asarray(target, dtype=jnp.float32)
    thresholds = jnp.asarray(thresholds, dtype=jnp.float32)

    ge = (preds[:, :, None] >= thresholds[None, None, :]).astype(jnp.float32)
    tps = jnp.einsum("nc,nct->ct", target, ge)
    ge_total = jnp.einsum("nct->ct", ge)
    pos_total = target.sum(axis=0)[:, None]  # (C, 1)
    fps = ge_total - tps
    fns = pos_total - tps
    return tps, fps, fns


__all__ = ["binned_curve_counts"]
