"""Crash-consistent state journal: one flat byte record per snapshot.

A process crash must not silently discard every accumulated metric state.
This module serializes a ``Metric``/``MetricCollection``'s reduce-path states
into ONE flat byte record by reusing the coalesced-sync pack/manifest
machinery (:mod:`metrics_tpu.parallel.bucketing`): the same
``tree_nodes`` → ``_collect`` → bitcast-to-uint8 pack walk that feeds the
payload all-gather feeds the journal payload, so **restore is bit-exact vs
the live state by construction** — the bytes on disk are exactly the bytes a
sync would have exchanged.

Record format (little-endian)::

    MAGIC(4) | version(u32) | manifest_len(u32) | payload_len(u64)
    | crc32(manifest)(u32) | crc32(payload)(u32) | manifest(JSON) | payload

Durability contract (the compiler-first caching pattern of
arXiv:2603.09555, generalized: any durable artifact must verify on load and
demote to a known-good tier, never crash or silently corrupt):

- **Atomic writes**: the record is written to ``<path>.tmp``, fsynced, and
  ``os.replace``d into place — a crash mid-write leaves the previous
  generation untouched, never a torn newest record.
- **Bounded generation ring**: each save rotates ``<path>`` → ``<path>.g1``
  → ``<path>.g2`` … up to ``METRICS_TPU_JOURNAL_GENERATIONS`` (default 3;
  the oldest generation falls off the end).
- **Verified loads**: magic/version/length/CRC32 all check before a single
  state is touched, and every ``setattr`` happens only after the whole
  record parses — a bad record never half-restores. A torn or
  checksum-failed generation classifies as a ``journal``-domain fault
  (``engine_stats()`` counters + failure log) and **demotes to the previous
  good generation**; only when every generation is bad does the classified
  :class:`~metrics_tpu.utils.exceptions.JournalFault` surface.

Fault sites: ``journal-write`` (before the temp file is written — an
injected fault models a full disk with previous generations intact) and
``journal-load`` (before a record is read — models an unreadable newest
generation). The suite-level auto-journal hook
(``MetricCollection.journal(path, every_n)``) routes write failures through
the owner's ``journal`` ladder lane instead of raising, so a broken disk
degrades journaling (warn once, re-probe after the recovery edge) without
taking down the update loop.
"""
from __future__ import annotations

import json
import os
import struct
import zlib
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from metrics_tpu.ops import telemetry as _telemetry
from metrics_tpu.parallel import bucketing as _bucketing
from metrics_tpu.utils.exceptions import JournalFault

__all__ = [
    "journal_generations",
    "journal_stats",
    "journalable",
    "load_nodes",
    "pack_raw_record",
    "pack_record",
    "read_record",
    "restored_meta",
    "save_nodes",
    "stage_states",
    "unpack_raw_record",
    "world_meta",
    "write_record",
]

# Journal-plane counters (merged into ``engine.engine_stats()`` and the
# telemetry snapshot; zeroed through the shared reset registry). The fault
# classifications stay in ``fault_journal`` — these count the HEALTHY traffic
# a fault-only view is blind to.
_counters: Dict[str, int] = {
    "journal_saves": 0,
    "journal_loads": 0,
    "journal_bytes_written": 0,
    "journal_load_demotions": 0,
}


def journal_stats() -> Dict[str, int]:
    """Healthy-path journal counters: records saved/restored, bytes written,
    and load-time generation demotions (each demotion also classifies a
    ``journal`` fault — this counter is the cheap scrape)."""
    return dict(_counters)


def _reset_journal_stats() -> None:
    for key in _counters:
        _counters[key] = 0


_telemetry.register_reset("journal", _reset_journal_stats)

_MAGIC = b"MTJL"
_VERSION = 1
_HEADER = struct.Struct("<4sIIQII")  # magic, version, manifest_len, payload_len, crc_m, crc_p


def journal_generations() -> int:
    """Size of the on-disk generation ring (``METRICS_TPU_JOURNAL_GENERATIONS``,
    default 3, floor 1)."""
    try:
        return max(1, int(os.environ.get("METRICS_TPU_JOURNAL_GENERATIONS", "3")))
    except ValueError:
        return 3


def _gen_path(path: str, gen: int) -> str:
    return path if gen == 0 else f"{path}.g{gen}"


def journalable(nodes: Sequence[Any]) -> Optional[str]:
    """None when every node's every state can ride the byte record, else the
    reason it cannot (non-``cat`` list states lose their row structure through
    the concatenating pack; non-array leaves and sub-byte dtypes cannot
    bitcast). Unlike ``bucketing.coalescible`` this does NOT gate on sync
    semantics (``_sync_dist`` overrides journal fine — the journal never
    gathers)."""
    import jax

    for node in nodes:
        for name in node._reductions:
            spec = node._reduction_specs[name]
            value = getattr(node, name)
            rows = value if isinstance(value, list) else [value]
            if isinstance(value, list) and spec != "cat" and value:
                return (
                    f"state {type(node).__name__}.{name} is a non-'cat' list state; its row "
                    "structure would not survive the concatenating byte pack"
                )
            for row in rows:
                if not isinstance(row, (jax.Array, np.ndarray)) or isinstance(row, jax.core.Tracer):
                    return f"state {type(node).__name__}.{name} holds a non-array leaf"
                if not _bucketing._packable_dtype(row.dtype):
                    return (
                        f"state {type(node).__name__}.{name} has dtype {row.dtype} which the "
                        "bitcast packing cannot carry"
                    )
    return None


_SCALAR_TYPES = (bool, int, float, str, type(None))


def _static_attrs(node: Any) -> Dict[str, Any]:
    """One node's public scalar attributes — the update-inferred static
    hyperparameter surface ``_propagate_static_attrs`` manages for the fused
    paths, restricted to exactly JSON-round-trippable scalars (tuples and
    other containers are skipped: JSON would hand them back as lists and
    silently change their type)."""
    state_names = set(node._reduction_specs)
    out: Dict[str, Any] = {}
    for key, value in node.__dict__.items():
        if key.startswith("_") or key in state_names:
            continue
        if isinstance(value, _SCALAR_TYPES):
            out[key] = value
    return out


# ------------------------------------------------------------------- encoding
def pack_record(nodes: Sequence[Any], manifest_extra: Optional[Dict[str, Any]] = None) -> bytes:
    """Serialize every reduce-path state of ``nodes`` into one byte record.

    The caller must have flushed/canonicalized every node (``save_nodes``
    does). Reuses the coalesced-sync pack: ``bucketing._collect`` builds the
    layout manifest, ``bucketing._pack`` bitcasts and concatenates every
    state into one flat uint8 buffer (bit-exact for every fixed-width dtype;
    the engine-cached pack program is shared with the sync path).

    ``manifest_extra`` adds JSON-serializable keys to the manifest — the
    world-membership stamps (``epoch``, ``barrier_step``, …) ride here.
    Reserved structural keys (``entries``, ``version``, …) cannot be
    overridden, and :func:`decode_record` tolerates any extra key it does
    not know (forward compatibility: an older reader restores a newer
    writer's record, ignoring the stamps it cannot interpret)."""
    reason = journalable(nodes)
    if reason is not None:
        raise JournalFault(f"cannot journal this state tree: {reason}", site="journal-write")
    entries, values = _bucketing._collect(nodes)
    packed, _ = _bucketing._pack(entries, values)
    payload = np.asarray(packed).tobytes()

    manifest_entries: List[Dict[str, Any]] = []
    vi = 0
    for e in entries:
        row: Dict[str, Any] = {"node": e.node_idx, "name": e.name, "kind": e.kind, "spec": e.spec}
        if e.kind != "empty":
            value = values[vi]
            vi += 1
            row["dtype"] = jnp.dtype(value.dtype).name
            row["shape"] = [int(d) for d in value.shape]
        manifest_entries.append(row)
    manifest = {
        "version": _VERSION,
        "nodes": [type(n).__name__ for n in nodes],
        "update_counts": [int(n._update_count) for n in nodes],
        "entries": manifest_entries,
        # update-inferred static hyperparameters (Accuracy's `mode`, the
        # curve family's inferred `num_classes`/`pos_label`, …) live as plain
        # public scalars on the instance, not registered states — compute()
        # after a crash-restore needs them back (str-enums round-trip through
        # JSON as their string values; equality still holds)
        "static_attrs": [_static_attrs(n) for n in nodes],
        # host-side extra state a subclass declares crash-critical (e.g.
        # BootStrapper's numpy RNG stream — see Metric._journal_extra)
        "extras": [n._journal_extra() for n in nodes],
    }
    if manifest_extra:
        for key, value in manifest_extra.items():
            # extra stamps never shadow the structural schema
            manifest.setdefault(key, value)
    return _frame_record(manifest, payload)


def _frame_record(manifest: Dict[str, Any], payload: bytes) -> bytes:
    """CRC-frame one ``(manifest, payload)`` pair into the on-disk record
    layout — the shared tail of :func:`pack_record` and
    :func:`pack_raw_record`, so every record kind verifies through the same
    :func:`decode_record` discipline."""
    mbytes = json.dumps(manifest, separators=(",", ":")).encode("utf-8")
    header = _HEADER.pack(
        _MAGIC, _VERSION, len(mbytes), len(payload), zlib.crc32(mbytes), zlib.crc32(payload)
    )
    return header + mbytes + payload


def pack_raw_record(
    arrays: Dict[str, Any], manifest_extra: Optional[Dict[str, Any]] = None
) -> bytes:
    """Serialize a flat ``{name: array}`` dict into one CRC-framed record.

    The node-less twin of :func:`pack_record` for callers whose durable unit
    is a plain array layout rather than a ``Metric`` tree — the tenant
    arena's per-slab state records (:mod:`metrics_tpu.arena`) ride this.
    Entries are typed ``kind="raw"``; the payload is the concatenation of
    each array's native bytes in manifest order (bit-exact for every
    fixed-width dtype, bfloat16 included — the bitcast unpack is shared with
    the sync plane). Atomic write + generation ring + verified load all come
    from the shared :func:`write_record` / :func:`decode_record` machinery.
    """
    entries: List[Dict[str, Any]] = []
    chunks: List[bytes] = []
    for name in arrays:
        arr = np.asarray(arrays[name])
        entries.append(
            {
                "node": 0,
                "name": str(name),
                "kind": "raw",
                "dtype": jnp.dtype(arr.dtype).name,
                "shape": [int(d) for d in arr.shape],
            }
        )
        chunks.append(np.ascontiguousarray(arr).tobytes())
    manifest: Dict[str, Any] = {"version": _VERSION, "raw": True, "entries": entries}
    if manifest_extra:
        for key, value in manifest_extra.items():
            manifest.setdefault(key, value)
    return _frame_record(manifest, b"".join(chunks))


def unpack_raw_record(manifest: Dict[str, Any], payload: bytes) -> Dict[str, np.ndarray]:
    """Decode a :func:`pack_raw_record` payload back into ``{name: array}``.

    Expects the ``(manifest, payload)`` pair :func:`decode_record` already
    CRC-verified; raises the classified :class:`JournalFault` on any layout
    mismatch (non-raw entries, overrun, unclaimed bytes) — the same
    all-or-nothing posture as :func:`stage_states`."""

    def _bad(why: str) -> JournalFault:
        return JournalFault(f"raw journal record is corrupt: {why}", site="journal-load")

    if not manifest.get("raw"):
        raise _bad("manifest is not a raw record (missing the 'raw' stamp)")
    buf = jnp.asarray(np.frombuffer(payload, np.uint8))
    out: Dict[str, np.ndarray] = {}
    off = 0
    for e in manifest["entries"]:
        if e.get("kind") != "raw":
            raise _bad(f"entry {e.get('name')!r} has kind {e.get('kind')!r}, expected 'raw'")
        shape, dtype = tuple(e["shape"]), e["dtype"]
        n = _bucketing._byte_len(shape, dtype)
        if off + n > len(payload):
            raise _bad(f"entry {e['name']!r} overruns the payload")
        out[e["name"]] = np.asarray(_bucketing._from_bytes(buf[off : off + n], shape, dtype))
        off += n
    if off != len(payload):
        raise _bad(f"record carries {len(payload) - off} unclaimed payload bytes")
    return out


def decode_record(data: bytes, origin: str = "<bytes>") -> Tuple[Dict[str, Any], bytes]:
    """Verify and split one record into ``(manifest, payload)``; raises the
    classified :class:`JournalFault` on ANY corruption — truncation, foreign
    magic, version skew, or a CRC mismatch on either part.

    The manifest check is deliberately asymmetric: **unknown extra keys are
    tolerated** (forward compatibility — a newer writer may stamp
    world-membership metadata like ``epoch``/``barrier_step`` that an older
    reader must ignore, not reject), but the structural ``entries`` table is
    required — a CRC-valid record without it cannot restore anything and
    classifies as corrupt."""

    def _bad(why: str) -> JournalFault:
        return JournalFault(f"journal record {origin} is corrupt: {why}", site="journal-load")

    if len(data) < _HEADER.size:
        raise _bad(f"truncated header ({len(data)} bytes)")
    magic, version, mlen, plen, crc_m, crc_p = _HEADER.unpack_from(data)
    if magic != _MAGIC:
        raise _bad(f"bad magic {magic!r}")
    if version != _VERSION:
        raise _bad(f"unsupported version {version}")
    if len(data) != _HEADER.size + mlen + plen:
        raise _bad(f"torn record ({len(data)} bytes, header promises {_HEADER.size + mlen + plen})")
    mbytes = data[_HEADER.size : _HEADER.size + mlen]
    payload = data[_HEADER.size + mlen :]
    if zlib.crc32(mbytes) != crc_m:
        raise _bad("manifest checksum mismatch")
    if zlib.crc32(payload) != crc_p:
        raise _bad("payload checksum mismatch")
    try:
        manifest = json.loads(mbytes.decode("utf-8"))
    except ValueError as err:  # pragma: no cover - crc makes this near-impossible
        raise _bad(f"manifest does not parse: {err}") from err
    if not isinstance(manifest, dict) or not isinstance(manifest.get("entries"), list):
        raise _bad("manifest has no entries table")
    return manifest, payload


def stage_states(
    nodes: Sequence[Any], manifest: Dict[str, Any], payload: bytes
) -> List[Tuple[int, str, Any]]:
    """Validate and decode one record's payload against the live ``nodes``
    WITHOUT mutating anything: returns ``(node_index, state_name, value)``
    staging rows (dyn/cat entries as single-row lists, empties as ``[]``).

    The shared first half of :func:`restore_nodes` — and the entry the
    streaming window plane (:mod:`metrics_tpu.streaming`) re-accumulates
    through, merging a ring slot's states into a scratch clone instead of
    overwriting them. Raises the classified :class:`JournalFault` on any
    layout mismatch, leaving every node untouched."""

    def _bad(why: str) -> JournalFault:
        return JournalFault(f"journal record does not match this state tree: {why}", site="journal-load")

    # the whole tree must match, node for node — a record from a smaller or
    # differently-composed suite would otherwise "restore" while leaving the
    # extra live nodes silently untouched (a partial restore IS corruption)
    live_types = [type(n).__name__ for n in nodes]
    rec_types = manifest.get("nodes")
    if rec_types is not None and list(rec_types) != live_types:
        raise _bad(
            f"record holds {len(rec_types)} node(s) {rec_types}, live tree is "
            f"{len(live_types)} node(s) {live_types} (construction mismatch)"
        )

    buf = jnp.asarray(np.frombuffer(payload, np.uint8))
    staged: List[Tuple[int, str, Any]] = []
    off = 0
    for e in manifest["entries"]:
        idx, name, kind = e["node"], e["name"], e["kind"]
        if not (0 <= idx < len(nodes)):
            raise _bad(f"entry {name!r} addresses node {idx} of {len(nodes)}")
        node = nodes[idx]
        if name not in node._defaults:
            raise _bad(f"{type(node).__name__} has no state {name!r}")
        if kind == "empty":
            staged.append((idx, name, []))
            continue
        shape, dtype = tuple(e["shape"]), e["dtype"]
        n = _bucketing._byte_len(shape, dtype)
        if off + n > len(payload):
            raise _bad(f"entry {name!r} overruns the payload")
        value = _bucketing._from_bytes(buf[off : off + n], shape, dtype)
        off += n
        if kind == "dyn":
            # cat list state: restored as the single pre-concatenated row the
            # pack wrote — dim_zero_cat of [concat] == concat, so compute()
            # is bit-exact vs the multi-row live buffer
            staged.append((idx, name, [value]))
        else:
            current = getattr(node, name)
            if not isinstance(current, list) and jnp.dtype(jnp.asarray(current).dtype).name != dtype:
                raise _bad(
                    f"{type(node).__name__}.{name} is {jnp.asarray(current).dtype} live but "
                    f"{dtype} in the record (construction mismatch)"
                )
            staged.append((idx, name, value))
    if off != len(payload):
        raise _bad(f"record carries {len(payload) - off} unclaimed payload bytes")
    return staged


def restore_nodes(nodes: Sequence[Any], manifest: Dict[str, Any], payload: bytes) -> None:
    """Apply a decoded record to ``nodes`` — all-or-nothing.

    Every segment is sliced, bitcast back through the same
    ``bucketing._from_bytes`` the sync unpack uses, and staged
    (:func:`stage_states`); ``setattr`` runs only after the WHOLE record
    parses, so a layout-incompatible record (classified
    :class:`JournalFault`) leaves every node untouched."""
    staged = stage_states(nodes, manifest, payload)
    counts = manifest.get("update_counts", [])
    statics = manifest.get("static_attrs", [])
    extras = manifest.get("extras", [])
    for idx, name, value in staged:
        setattr(nodes[idx], name, value)
    for i, node in enumerate(nodes):
        if i < len(statics) and statics[i]:
            for key, value in statics[i].items():
                setattr(node, key, value)
        if i < len(extras) and extras[i]:
            node._journal_restore_extra(extras[i])
        if i < len(counts):
            node._update_count = int(counts[i])
        node._computed = None
        node._is_synced = False
        node._cache = None


# ------------------------------------------------------------------- disk I/O
def write_record(path: str, data: bytes, generations: Optional[int] = None) -> None:
    """Atomically persist one record and rotate the generation ring.

    Write-to-temp + fsync + ``os.replace`` — a crash at any point leaves a
    consistent ring (the previous newest generation survives until the final
    rename). The ``journal-write`` fault site fires before any byte is
    written, so an injected fault models a failed write with the ring
    intact."""
    from metrics_tpu.ops import faults as _faults

    if _faults.armed:
        _faults.maybe_fail("journal-write")
    cap = generations if generations is not None else journal_generations()
    directory = os.path.dirname(os.path.abspath(path))
    os.makedirs(directory, exist_ok=True)
    tmp = f"{path}.tmp"
    with open(tmp, "wb") as fh:
        fh.write(data)
        fh.flush()
        os.fsync(fh.fileno())
    for gen in range(cap - 1, 0, -1):
        src = _gen_path(path, gen - 1)
        if os.path.exists(src):
            os.replace(src, _gen_path(path, gen))
    os.replace(tmp, path)


def read_record(path: str) -> Tuple[Dict[str, Any], bytes]:
    """Read and verify ONE generation file (no ring demotion — that is
    :func:`load_nodes`). I/O errors and corruption both raise the classified
    :class:`JournalFault`."""
    from metrics_tpu.ops import faults as _faults

    if _faults.armed:
        _faults.maybe_fail("journal-load")
    try:
        with open(path, "rb") as fh:
            data = fh.read()
    except OSError as err:
        raise JournalFault(
            f"journal record {path!r} is unreadable: {type(err).__name__}: {err}",
            site="journal-load",
        ) from err
    return decode_record(data, origin=repr(path))


# ---------------------------------------------------------------- owner-level
#: Manifest stamps the membership layer reads back at rejoin time. Unknown
#: to older readers by design (decode_record tolerates them).
_META_KEYS = ("epoch", "last_good_sync_step", "monotonic_step", "barrier_step", "world_size", "barrier")


def world_meta(owner: Any) -> Dict[str, Any]:
    """The default world-membership manifest stamps for one save: the current
    epoch, the owner's last completed sync step, and the global monotonic
    event step — what ``rejoin`` compares against a survivor handoff to
    decide whose record is newer."""
    from metrics_tpu.ops import faults as _faults
    from metrics_tpu.parallel import sync as _sync

    return {
        "epoch": _sync.world_epoch(),
        "last_good_sync_step": owner.__dict__.get("_last_good_sync_step"),
        "monotonic_step": _faults.current_step(),
    }


def restored_meta(owner: Any) -> Dict[str, Any]:
    """The membership stamps of the record ``owner`` last restored (empty
    before any load). ``MetricCollection.rejoin`` reads this to compare the
    local journal against the fleet."""
    return dict(owner.__dict__.get("_journal_meta") or {})


def save_nodes(owner: Any, nodes: Sequence[Any], path: str, manifest_extra: Optional[Dict[str, Any]] = None) -> int:
    """Snapshot ``nodes`` to ``path`` (rotating the ring); returns the record
    size in bytes. Any failure raises classified with the ring intact. The
    manifest carries the :func:`world_meta` membership stamps (plus any
    caller ``manifest_extra``, which wins on key overlap)."""
    from metrics_tpu.ops import faults as _faults

    t0 = _telemetry.now() if _telemetry.armed else 0.0
    try:
        for n in nodes:
            n._defer_barrier()
            n._canonicalize_list_states()
        extra = world_meta(owner)
        if manifest_extra:
            extra.update(manifest_extra)
        data = pack_record(nodes, manifest_extra=extra)
        write_record(path, data)
    except Exception as exc:  # noqa: BLE001 — classified + rethrown
        domain = _faults.classify(exc, "journal")
        _faults.note_fault(domain, site="journal-write", owner=owner, error=exc)
        if isinstance(exc, JournalFault):
            raise
        raise JournalFault(
            f"journal save to {path!r} failed: {type(exc).__name__}: {exc}",
            site="journal-write",
        ) from exc
    _counters["journal_saves"] += 1
    _counters["journal_bytes_written"] += len(data)
    if t0 and _telemetry.armed:
        _telemetry.emit(
            "journal-save", owner, "journal", t0, _telemetry.now() - t0,
            {"bytes": len(data), "nodes": len(nodes)},
        )
    return len(data)


def load_nodes(owner: Any, nodes: Sequence[Any], path: str) -> int:
    """Restore ``nodes`` from the newest good generation at ``path``.

    Walks the ring newest-first: a torn/checksum-failed/unreadable generation
    records a classified ``journal`` fault (+ one owner-deduped warning) and
    **demotes to the previous generation**. Returns the generation index that
    restored (0 = newest). Raises :class:`JournalFault` only when no
    generation verifies."""
    from metrics_tpu.ops import faults as _faults

    last: Optional[BaseException] = None
    t0 = _telemetry.now() if _telemetry.armed else 0.0
    # scan a few generations past the configured cap: the ring size may have
    # been lowered between runs, and stale-but-good older files are still a
    # better tier than a crash
    for gen in range(journal_generations() + 8):
        gpath = _gen_path(path, gen)
        if not os.path.exists(gpath):
            continue
        try:
            manifest, payload = read_record(gpath)
            restore_nodes(nodes, manifest, payload)
            # stash the restored record's membership stamps for rejoin
            object.__setattr__(
                owner,
                "_journal_meta",
                {k: manifest[k] for k in _META_KEYS if k in manifest},
            )
        except Exception as exc:  # noqa: BLE001 — demote to the previous generation
            last = exc
            _counters["journal_load_demotions"] += 1
            _faults.note_fault(
                _faults.classify(exc, "journal"), site="journal-load", owner=owner, error=exc
            )
            if _telemetry.armed:
                _telemetry.emit(
                    "journal-demote", owner, "journal",
                    attrs={"generation": gen, "error": type(exc).__name__},
                )
            _faults.warn_fault(
                owner,
                "journal",
                f"Journal generation {gpath!r} failed verification "
                f"({type(exc).__name__}: {exc}); demoting to the previous good generation.",
            )
            continue
        _counters["journal_loads"] += 1
        if t0 and _telemetry.armed:
            _telemetry.emit(
                "journal-load", owner, "journal", t0, _telemetry.now() - t0,
                {"generation": gen, "bytes": len(payload), "nodes": len(nodes)},
            )
        return gen
    if last is not None:
        if isinstance(last, JournalFault):
            raise last
        raise JournalFault(
            f"every journal generation at {path!r} failed verification; last error: "
            f"{type(last).__name__}: {last}",
            site="journal-load",
        ) from last
    raise JournalFault(f"no journal record found at {path!r}", site="journal-load")
