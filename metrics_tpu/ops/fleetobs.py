"""Fleet observability: cross-rank snapshot aggregation, straggler
attribution, merged multi-rank traces.

The flight recorder (:mod:`metrics_tpu.ops.telemetry`) sees exactly one
process, yet every hard fleet question — who is slow, where sync time goes
per rank, whether a degraded cohort is healthy enough to serve — spans the
whole world, especially once elastic membership makes the world dynamic.
This module is the fleet plane on top of the local one, in three faces:

- :func:`fleet_snapshot` — ONE epoch-fenced, deadline-guarded host gather
  of every rank's JSON-serialized ``telemetry_snapshot()`` (the same
  ``_host_allgather`` + ``run_with_deadline`` + ``check_epoch`` ladder every
  other collective protocol rides), merged into a schema-stable dict with
  per-rank planes, aggregate planes (counters summed exactly; gauges
  min/median/max; the full-lifetime latency histograms merged by EXACT
  bucket sums with fleet percentiles re-interpolated from the merged
  buckets — :func:`merge_latency_stats`), dead-rank placeholders sourced
  from the membership registry, the straggler report, and
  ``world_health()`` folded in. With a world size of 1 the local plane is
  served directly — ZERO collectives.

- **Straggler attribution** — every rank's snapshot carries its
  ``sync_phase_stats`` block (per-phase span duration statistics:
  pack / metadata / payload-gather / unpack, reduced from the span ring);
  :func:`straggler_report` compares them across ranks and names the slowest
  ranks per phase with deviation-from-median scores.
  :func:`fleet_prometheus_text` renders the fleet view as a Prometheus
  exposition with ``rank`` (and ``phase``) labels.

- :func:`export_fleet_trace` — gather the span rings, align ranks on the
  shared monotonic axis using paired payload-gather spans (identical
  ``seq`` ordinals — collectives issue in lockstep) as clock-offset
  anchors, and emit ONE Perfetto JSON with one *process per rank*, so a
  cross-rank sync timeline is visible in a single view.

Transport note: in a live world with declared-dead ranks, the gather rows
are the SURVIVORS in ascending rank order (the same re-formed-transport
convention the quorum tier uses); dead ranks appear as placeholder planes
and are excluded from every aggregate. See docs/observability.md
("Fleet plane").
"""
from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from metrics_tpu.ops import telemetry as _telemetry

__all__ = [
    "FLEET_SCHEMA",
    "export_fleet_trace",
    "fleet_perf_report",
    "fleet_prometheus_text",
    "fleet_snapshot",
    "fleet_stats",
    "fleet_world",
    "local_rank",
    "merge_latency_stats",
    "merge_snapshots",
    "reset_fleet_stats",
    "straggler_report",
    "straggler_threshold",
]

#: Bumped only on breaking key changes to the :func:`fleet_snapshot` schema.
FLEET_SCHEMA = 1

# ------------------------------------------------------------------ counters
_counters: Dict[str, int] = {
    "fleet_snapshots": 0,
    "fleet_trace_exports": 0,
    "fleet_gathers": 0,
    "fleet_gather_bytes": 0,
}


def fleet_stats() -> Dict[str, int]:
    """Fleet-plane counters (surfaced inside :func:`fleet_snapshot`)."""
    return dict(_counters)


def reset_fleet_stats() -> None:
    for key in _counters:
        _counters[key] = 0


_telemetry.register_reset("fleetobs", reset_fleet_stats)


class _FleetWarnOwner:
    """Warn-dedupe anchor for fleet env-knob / merge warnings."""


_THRESHOLD_WARN_OWNER = _FleetWarnOwner()
_MERGE_WARN_OWNER = _FleetWarnOwner()
# distinct owner: the snapshot row-count warning and the trace dropped-row
# warning are different conditions — sharing one warn_fault slot would let
# whichever fires first permanently suppress the other
_TRACE_DROP_WARN_OWNER = _FleetWarnOwner()

#: Keys that are monotonic counters on ONE rank but must NOT sum across the
#: fleet: every rank carries the same kind of event axis, and "3 ranks at
#: step 100" is step skew (a min/median/max gauge signal), not 300 events.
FLEET_GAUGE_KEYS = frozenset({"monotonic_step"})


def _fleet_is_counter(key: str) -> bool:
    """The fleet-merge counter predicate: :func:`telemetry.is_counter_key`
    (the Prometheus-typing predicate) minus :data:`FLEET_GAUGE_KEYS` — the
    keys whose cross-rank sum is meaningless."""
    return _telemetry.is_counter_key(key) and key not in FLEET_GAUGE_KEYS


# ------------------------------------------------------------------ the world
def fleet_world() -> int:
    """The world the fleet plane gathers over: the live process count, or the
    membership registry's known (declared or transition-promoted) world when
    that is larger — a degraded cohort keeps its original rank numbering, and
    simulated/fake worlds declare themselves via ``set_expected_world``. A
    plain single process with no known world is a fleet of one: every face
    serves the local plane with ZERO collectives."""
    from metrics_tpu.parallel import sync as _sync

    return max(_sync.world_size(), _sync._membership.known_world or 1)


def local_rank() -> int:
    """This process's rank in the fleet (0 in a single-process world)."""
    from metrics_tpu.parallel import sync as _sync

    if _sync.distributed_available():
        import jax

        return int(jax.process_index())
    return 0


def straggler_threshold() -> float:
    """Deviation-from-median above which a rank is flagged as a straggler
    (``METRICS_TPU_STRAGGLER_THRESHOLD``, default 0.5 — 50% slower than the
    fleet median for some sync phase). An unparseable value warns once and
    uses the default."""
    from metrics_tpu.parallel import sync as _sync

    return max(
        0.0, _sync._env_float("METRICS_TPU_STRAGGLER_THRESHOLD", 0.5, owner=_THRESHOLD_WARN_OWNER)
    )


def _participant_ranks(world: int, dead: Any) -> List[int]:
    """The ranks a host gather's rows map to: survivors ascending (the
    re-formed-transport convention — see ``sync.surviving_members``)."""
    dead = set(int(r) for r in (dead or ()))
    return [r for r in range(world) if r not in dead]


# ------------------------------------------------------------------ transport
def _gather_blobs(blob: bytes, *, owner: Any = None, site: str = "fleet-gather") -> List[bytes]:
    """All-gather one variable-length byte blob from every rank.

    Two host exchanges (a length vector, then the max-length-padded payload)
    riding the full collective-protocol ladder: the epoch fence is captured
    at entry and re-checked inside the retried closure before each issue,
    every blocking exchange runs under the watchdog deadline, and both
    collective slots are audited against the fence stamp. Returns one
    ``bytes`` entry per gather row (row order = survivors ascending)."""
    from metrics_tpu.ops import faults as _faults
    from metrics_tpu.parallel import bucketing as _bucketing
    from metrics_tpu.parallel import sync as _sync

    # collectives pair by issue order: any in-flight async sync must land
    # before this blocking exchange issues (see sync.drain_inflight)
    _sync.drain_inflight()
    fence = _sync.world_epoch()
    t0 = _telemetry.now() if _telemetry.armed else 0.0
    local_vec = np.frombuffer(blob, np.uint8)

    def _attempt() -> List[bytes]:
        _sync.check_epoch(fence, site=site, owner=owner)
        lengths_rows = np.asarray(
            _sync.run_with_deadline(
                lambda: _bucketing._host_allgather(np.asarray([len(blob)], np.int64)),
                site=site,
            )
        )
        _sync.note_collective("shape", epoch=fence)
        lengths = lengths_rows.reshape(lengths_rows.shape[0], -1)[:, 0].astype(np.int64)
        max_len = max(1, int(lengths.max()))
        padded = np.zeros(max_len, np.uint8)
        padded[: len(blob)] = local_vec
        rows = np.asarray(
            _sync.run_with_deadline(lambda: _bucketing._host_allgather(padded), site=site)
        )
        _sync.note_collective("payload", nbytes=int(rows.size), epoch=fence)
        n = min(rows.shape[0], lengths.shape[0])
        return [rows[i, : int(lengths[i])].astype(np.uint8).tobytes() for i in range(n)]

    out = _faults.retry_with_backoff(
        _attempt,
        attempts=_sync.sync_retries(),
        base_delay_s=_sync.sync_backoff_s(),
        owner=owner,
        site=site,
    )
    _counters["fleet_gathers"] += 1
    _counters["fleet_gather_bytes"] += sum(len(b) for b in out)
    if t0 and _telemetry.armed:
        _telemetry.emit(
            "fleet-gather", owner, "sync", t0, _telemetry.now() - t0,
            {"rows": len(out), "bytes": sum(len(b) for b in out), "epoch": fence},
        )
    return out


def _local_plane_text() -> str:
    """This rank's snapshot plane as its wire JSON: ``telemetry_snapshot()``
    minus the ``failure_log`` ring (per-entry error strings belong to the
    local trace, not the fleet gather — the per-domain counts already travel
    inside ``sync_health.fault_domain_counts``). The gather blob and the
    local plane both come from this ONE serialization, so they are
    byte-identical by construction."""
    snap = _telemetry.snapshot()
    plane = {k: v for k, v in snap.items() if k != "failure_log"}
    return json.dumps(_telemetry._json_safe(plane), separators=(",", ":"))


def _local_plane() -> Dict[str, Any]:
    return json.loads(_local_plane_text())


def _is_live_plane(plane: Any) -> bool:
    return isinstance(plane, dict) and not plane.get("dead") and not plane.get("missing") and not plane.get("corrupt")


# ------------------------------------------------------------------ the merge
def _median(values: List[float]) -> float:
    vals = sorted(values)
    n = len(vals)
    if not n:
        return 0.0
    mid = n // 2
    return float(vals[mid]) if n % 2 else float(vals[mid - 1] + vals[mid]) / 2.0


def merge_latency_stats(planes: Dict[int, Dict[str, Any]]) -> Dict[str, Any]:
    """Merge the per-rank full-lifetime latency histogram planes
    (``latency_stats`` blocks) into one fleet histogram per site. Bucket
    counts, ``count`` and ``sum_s`` are plain counters on a SHARED bucket
    layout, so the merge is an EXACT sum — no min/median/max approximation,
    unlike the ring-windowed ``sync_phase_stats`` gauges. ``max_s`` maxes,
    and the fleet percentiles are re-interpolated from the MERGED bucket
    counts (never averaged across ranks — an average of per-rank p99s is
    not a fleet p99). Dead/missing/corrupt placeholder planes are excluded."""
    merged: Dict[str, _telemetry.LatencyHistogram] = {}
    known = set(_telemetry._HIST_LABELS)
    for _, plane in sorted(planes.items()):
        if not _is_live_plane(plane):
            continue
        for site, block in (plane.get("latency_stats") or {}).items():
            if not isinstance(block, dict):
                continue
            buckets = block.get("buckets") or {}
            if not set(buckets) <= known:
                # a mixed-version fleet shipped a DIFFERENT bucket layout:
                # merging its sums while dropping its unknown buckets would
                # corrupt the exact-sum contract silently — skip the block
                # whole and warn once (no-silent-caps)
                from metrics_tpu.ops import faults as _faults

                _faults.warn_fault(
                    _MERGE_WARN_OWNER,
                    "fleet-merge-layout",
                    f"A rank's {site!r} latency histogram carries bucket labels "
                    "outside this build's layout (a mixed-version fleet?); its "
                    "block is excluded from the fleet merge rather than summed "
                    "inconsistently.",
                )
                continue
            h = merged.setdefault(site, _telemetry.LatencyHistogram())
            for i, label in enumerate(_telemetry._HIST_LABELS):
                h.counts[i] += int(buckets.get(label, 0))
            h.sum_s += float(block.get("sum_s", 0.0))
            h.max_s = max(h.max_s, float(block.get("max_s", 0.0)))
    return {site: merged[site].stats() for site in sorted(merged)}


def merge_snapshots(planes: Dict[int, Dict[str, Any]]) -> Dict[str, Any]:
    """Reduce per-rank snapshot planes into the aggregate plane: every
    flattened numeric key classified by the SAME predicate the Prometheus
    exposition types with (:func:`metrics_tpu.ops.telemetry.is_counter_key`)
    — **counters summed exactly** (the dryrun certification pins aggregate ==
    sum of per-rank), gauges reduced to ``min``/``median``/``max``. The
    shared-monotonic-axis keys (:data:`FLEET_GAUGE_KEYS`) reduce as gauges —
    cross-rank step skew is the signal, a sum would be noise. The latency
    histogram planes additionally merge structurally under ``latency_stats``
    (exact bucket sums + fleet percentiles re-interpolated from the merged
    buckets — :func:`merge_latency_stats`). Dead / missing / corrupt
    placeholder planes are excluded."""
    counters: Dict[str, float] = {}
    gauge_values: Dict[str, List[float]] = {}
    merged_ranks: List[int] = []
    for rank, plane in sorted(planes.items()):
        if not _is_live_plane(plane):
            continue
        merged_ranks.append(rank)
        # the latency histogram plane merges STRUCTURALLY below (exact bucket
        # sums, percentiles re-interpolated); flattening it here too would
        # duplicate the bucket counters and min/median/max the per-rank
        # percentiles — the meaningless reduction this module exists to avoid
        numeric = {
            k: v
            for k, v in plane.items()
            if k not in ("failure_log", _telemetry._HIST_SNAPSHOT_KEY)
        }
        for key, value in _telemetry._flat_numeric("", numeric):
            if _fleet_is_counter(key):
                counters[key] = counters.get(key, 0) + value
            else:
                gauge_values.setdefault(key, []).append(value)
    # integer counters stay integers (floats are exact below 2**53; a fleet
    # of byte counters sums well inside that)
    counters_out: Dict[str, Any] = {
        k: int(v) if float(v).is_integer() else v for k, v in sorted(counters.items())
    }
    gauges_out: Dict[str, Dict[str, float]] = {
        k: {"min": float(min(v)), "median": _median(v), "max": float(max(v))}
        for k, v in sorted(gauge_values.items())
    }
    return {
        "counters": counters_out,
        "gauges": gauges_out,
        "latency_stats": merge_latency_stats(planes),
        "ranks_merged": merged_ranks,
    }


def merge_streaming(planes: Dict[int, Dict[str, Any]]) -> Dict[str, Any]:
    """Merge the per-rank ``streaming`` snapshot blocks (the model-monitoring
    plane — ``metrics_tpu.streaming``) into one fleet view.

    Window values are **fleet-agreed** (a close merges the stride state
    through one payload collective before packing, so every live rank's
    block for a given close id is identical) — the merge takes the first
    live rank's block per window rather than re-reducing, and spends its
    effort on the one thing that CAN differ: the window id each rank has
    reached. ``window_skew`` attributes that — per window name, the agreed
    (max) id, the max cross-rank skew, and each rank's lag behind the
    agreed id. A rank lagging its peers' window ids is a rank whose close
    loop stalled — the streaming twin of the straggler report."""
    windows: Dict[str, Dict[str, Any]] = {}
    drift: Dict[str, Dict[str, float]] = {}
    arenas: Dict[str, Dict[str, Any]] = {}
    per_rank_ids: Dict[str, Dict[int, int]] = {}
    for rank, plane in sorted(planes.items()):
        if not _is_live_plane(plane):
            continue
        block = plane.get("streaming")
        if not isinstance(block, dict):
            continue
        for name, win in (block.get("windows") or {}).items():
            if not isinstance(win, dict):
                continue
            windows.setdefault(name, win)
            try:
                per_rank_ids.setdefault(name, {})[rank] = int(win.get("window", 0))
            except (TypeError, ValueError):
                continue
        for name, scores in (block.get("drift") or {}).items():
            if isinstance(scores, dict):
                drift.setdefault(name, scores)
        # arena blocks ride the same first-live-rank discipline as window
        # values: every rank publishing an arena name holds that arena's
        # own state, and duplicate names across ranks are the same logical
        # arena restored fleet-wide
        for name, arena in (block.get("arenas") or {}).items():
            if isinstance(arena, dict):
                arenas.setdefault(name, arena)
    window_skew: Dict[str, Dict[str, Any]] = {}
    for name, ids in sorted(per_rank_ids.items()):
        agreed = max(ids.values())
        window_skew[name] = {
            "agreed": agreed,
            "max_skew": agreed - min(ids.values()),
            "per_rank_lag": {r: agreed - wid for r, wid in sorted(ids.items())},
        }
    return {"windows": windows, "drift": drift, "arenas": arenas, "window_skew": window_skew}


def straggler_report(planes: Dict[int, Dict[str, Any]]) -> Dict[str, Any]:
    """Name the slowest ranks per sync phase, with deviation scores — both
    mean-based and **tail-aware**.

    Each live plane carries two per-phase latency views: the ring-windowed
    ``sync_phase_stats`` means and the full-lifetime ``latency_stats``
    percentiles. For every phase with data the report records the per-rank
    means, the fleet median, the slowest rank and its deviation
    ``(mean - median) / median`` — and, beside it, the per-rank **p95**
    latencies with the analogous tail deviation ``(p95 - median_p95) /
    median_p95`` (a rank whose mean looks fine but whose tail is 10x the
    fleet's is exactly the straggler the mean hides). ``stragglers`` lists
    the ranks whose worst deviation on EITHER measure exceeds
    :func:`straggler_threshold`, worst first; ``ranked`` orders every
    attributed rank the same way, naming the measure that flagged it."""
    live = {
        r: p
        for r, p in planes.items()
        if _is_live_plane(p) and isinstance(p.get("sync_phase_stats"), dict)
    }
    threshold = straggler_threshold()
    phases: Dict[str, Dict[str, Any]] = {}
    worst: Dict[int, Tuple[float, str, str]] = {}

    def _attribute(deviations: Dict[int, float], site: str, measure: str) -> None:
        for r, d in deviations.items():
            if r not in worst or d > worst[r][0]:
                worst[r] = (d, site, measure)

    for site in _telemetry.SYNC_PHASE_SITES:
        per_rank = {}
        per_rank_p95 = {}
        for r, plane in live.items():
            block = (plane.get("sync_phase_stats") or {}).get(site) or {}
            if float(block.get("count", 0)) > 0:
                per_rank[r] = float(block.get("mean_s", 0.0))
            lat = (plane.get("latency_stats") or {}).get(site) or {}
            if float(lat.get("count", 0)) > 0:
                per_rank_p95[r] = float(lat.get("p95_s", 0.0))
        entry: Dict[str, Any] = {
            "per_rank_mean_s": per_rank,
            "median_s": 0.0,
            "slowest_rank": None,
            "slowest_mean_s": 0.0,
            "deviation": 0.0,
            "per_rank_deviation": {},
            # the tail-aware plane (full-lifetime histogram p95 per rank)
            "per_rank_p95_s": per_rank_p95,
            "p95_median_s": 0.0,
            "tail_slowest_rank": None,
            "tail_deviation": 0.0,
            "per_rank_tail_deviation": {},
        }
        if per_rank:
            med = _median(list(per_rank.values()))
            deviations = {
                r: (v - med) / max(med, 1e-12) for r, v in per_rank.items()
            }
            slowest = max(per_rank, key=lambda r: per_rank[r])
            entry.update(
                median_s=med,
                slowest_rank=slowest,
                slowest_mean_s=per_rank[slowest],
                deviation=deviations[slowest],
                per_rank_deviation=deviations,
            )
            _attribute(deviations, site, "mean_s")
        if per_rank_p95:
            med95 = _median(list(per_rank_p95.values()))
            tail_devs = {
                r: (v - med95) / max(med95, 1e-12) for r, v in per_rank_p95.items()
            }
            tail_slowest = max(per_rank_p95, key=lambda r: per_rank_p95[r])
            entry.update(
                p95_median_s=med95,
                tail_slowest_rank=tail_slowest,
                tail_deviation=tail_devs[tail_slowest],
                per_rank_tail_deviation=tail_devs,
            )
            _attribute(tail_devs, site, "p95_s")
        phases[site] = entry
    ranked = [
        {"rank": r, "phase": site, "deviation": d, "measure": measure}
        for r, (d, site, measure) in sorted(worst.items(), key=lambda kv: -kv[1][0])
    ]
    return {
        "phases": phases,
        "ranked": ranked,
        "threshold": threshold,
        "stragglers": [row["rank"] for row in ranked if row["deviation"] >= threshold],
    }


# ------------------------------------------------------------------ the faces
def fleet_snapshot() -> Dict[str, Any]:
    """ONE merged fleet monitoring dict — the cross-rank face of
    ``telemetry_snapshot()``.

    In a multi-rank world, every rank's JSON-serialized snapshot rides one
    epoch-fenced, deadline-guarded blob gather (two collective slots: a
    length exchange + the padded payload — see :func:`_gather_blobs`) —
    a **collective**: every live rank must call it in lockstep, like
    ``sync()`` or ``checkpoint_barrier()``, so invoke it from the
    coordinated serving/eval loop, never from an unsynchronized per-rank
    poller. With a world size of 1 the local plane is served directly and
    **zero collectives are issued**. Keys:

    - ``fleet_schema`` — :data:`FLEET_SCHEMA`; bumped on breaking changes.
    - ``world_size`` / ``rank`` / ``epoch`` / ``gathered``.
    - ``ranks`` — per-rank planes keyed by rank: each live rank's snapshot
      (minus the ``failure_log`` ring); declared-dead ranks get a
      ``{"dead": True, ...}`` placeholder sourced from the membership
      registry; ranks the gather could not produce a row for get
      ``{"missing": True}``; an undecodable row gets ``{"corrupt": True}``.
    - ``aggregate`` — :func:`merge_snapshots` over the live planes
      (counters summed exactly; gauges min/median/max).
    - ``stragglers`` — :func:`straggler_report`.
    - ``streaming`` — :func:`merge_streaming`: the model-monitoring plane
      (fleet-agreed window values, drift scores, per-rank window-skew
      attribution).
    - ``world_health`` — the membership registry surface, folded in.
    - ``fleet_stats`` — this plane's own counters.

    Example:
        >>> from metrics_tpu import fleet_snapshot
        >>> snap = fleet_snapshot()     # single process: local plane only
        >>> snap["fleet_schema"]
        1
        >>> snap["rank"] in snap["ranks"]
        True
    """
    from metrics_tpu.parallel import sync as _sync

    t0 = _telemetry.now() if _telemetry.armed else 0.0
    wh = _sync.world_health()
    world = fleet_world()
    rank = local_rank()
    dead = set(wh.get("dead_ranks") or ())
    plane_text = _local_plane_text()
    gathered = False
    planes: Dict[int, Dict[str, Any]] = {}
    if world > 1:
        # the local plane arrives back through its own gather row — no
        # second parse of the multi-KB snapshot on the collective path
        payloads = _gather_blobs(plane_text.encode("utf-8"), site="fleet-snapshot")
        participants = _participant_ranks(world, dead)
        if len(payloads) != len(participants):
            # a row count the registry did not predict (e.g. a fake world
            # narrower than the declared one): map rows positionally and
            # mark the unaccounted-for live ranks missing
            from metrics_tpu.ops import faults as _faults

            _faults.warn_fault(
                _MERGE_WARN_OWNER,
                "sync",
                f"fleet_snapshot gathered {len(payloads)} row(s) but the membership "
                f"registry expects {len(participants)} live rank(s) of {world}; mapping "
                "rows to the lowest live ranks and marking the rest missing.",
            )
        for r, raw in zip(participants, payloads):
            try:
                decoded = json.loads(raw.decode("utf-8"))
                if not isinstance(decoded, dict):
                    raise ValueError(f"rank plane must be an object, got {type(decoded).__name__}")
                planes[r] = decoded
            except (ValueError, UnicodeDecodeError):
                planes[r] = {"corrupt": True, "rank": r}
        for r in participants:
            if r not in planes:
                planes[r] = {"missing": True, "rank": r}
        gathered = True
    else:
        planes[rank] = json.loads(plane_text)
    # dead-rank placeholders, sourced from the membership registry: the
    # aggregate excludes them, the schema still names them
    for r in sorted(dead):
        if r not in planes:
            rec = (wh.get("peers") or {}).get(r) or {}
            planes[r] = {
                "dead": True,
                "rank": r,
                "declared_dead_epoch": rec.get("declared_dead_epoch"),
                "timeouts": rec.get("timeouts", 0),
            }
    _counters["fleet_snapshots"] += 1
    out = {
        "fleet_schema": FLEET_SCHEMA,
        "world_size": world,
        "rank": rank,
        "epoch": int(wh.get("epoch", 1)),
        "gathered": gathered,
        "dead_ranks": sorted(dead),
        "ranks": planes,
        "aggregate": merge_snapshots(planes),
        "stragglers": straggler_report(planes),
        "streaming": merge_streaming(planes),
        "world_health": wh,
        "fleet_stats": fleet_stats(),
    }
    if t0 and _telemetry.armed:
        _telemetry.emit(
            "fleet-snapshot", None, "sync", t0, _telemetry.now() - t0,
            {"world": world, "gathered": gathered, "ranks": len(planes)},
        )
    return out


def _prom_name(key: str) -> str:
    return "metrics_tpu_fleet_" + "".join(c if (c.isalnum() or c == "_") else "_" for c in key)


def fleet_prometheus_text(snap: Optional[Dict[str, Any]] = None) -> str:
    """Render a :func:`fleet_snapshot` as a Prometheus exposition with
    ``rank`` (and ``phase``) labels — the scrape face of the fleet plane.

    Families: fleet-level gauges (``world_size``, ``dead_ranks``, ``epoch``,
    ``gathered``), the aggregate counters (``metrics_tpu_fleet_<key>``,
    typed ``counter``) and aggregate gauges (``_min``/``_median``/``_max``),
    per-rank liveness/health gauges (``rank`` label), the per-rank sync
    phase statistics (``rank`` + ``phase`` labels, mean AND full-lifetime
    p95), the straggler deviation scores (mean-based and tail-aware), the
    model-monitoring families (``metrics_tpu_metric_value{name,window}``
    per-window metric values, ``metrics_tpu_drift_score{name,kind}`` PSI/KS
    scores, ``metrics_tpu_fleet_window_id{name}`` and the per-rank
    ``metrics_tpu_fleet_window_skew{rank,name}`` lag attribution), the
    ingestion-gateway families (``metrics_tpu_ingest_staging_rows`` /
    ``_staging_bytes`` / ``_degraded`` / ``_quarantine_depth``, with
    ``rank`` + ``gateway`` labels), and
    the latency **histogram** families: the fleet-merged
    ``metrics_tpu_fleet_latency_seconds{site=...,le=...}`` (exact bucket
    sums across ranks) and the rank-labelled
    ``metrics_tpu_fleet_rank_latency_seconds{rank=...,site=...,le=...}``.
    Samples of one family are grouped under a single ``# TYPE`` line, as
    the text format requires.

    .. warning:: With no ``snap`` argument this calls
       :func:`fleet_snapshot`, which in a multi-rank world is a
       **collective** — every live rank must enter it in lockstep, so do
       NOT wire the no-arg form into an independently-scraped per-rank
       ``/metrics`` endpoint. Gather once at a coordinated point in the
       serving loop and render the result (``fleet_prometheus_text(snap)``)
       from the scrape handler; the per-rank local exposition
       (:func:`metrics_tpu.prometheus_text`) needs no coordination.

    Example:
        >>> from metrics_tpu import fleet_prometheus_text
        >>> text = fleet_prometheus_text()
        >>> text.splitlines()[0]
        '# TYPE metrics_tpu_fleet_world_size gauge'
        >>> 'metrics_tpu_fleet_rank_live{rank="' in text
        True
    """
    snap = fleet_snapshot() if snap is None else snap
    families: List[Tuple[str, str, List[str]]] = []  # (name, kind, sample lines)

    def family(name: str, kind: str, samples: List[Tuple[str, float]]) -> None:
        if not samples:
            return
        lines = []
        for labels, value in samples:
            rendered = str(int(value)) if float(value).is_integer() else repr(float(value))
            lines.append(f"{name}{labels} {rendered}")
        families.append((name, kind, lines))

    family("metrics_tpu_fleet_world_size", "gauge", [("", snap["world_size"])])
    family("metrics_tpu_fleet_dead_ranks", "gauge", [("", len(snap["dead_ranks"]))])
    family("metrics_tpu_fleet_epoch", "gauge", [("", snap["epoch"])])
    family("metrics_tpu_fleet_gathered", "gauge", [("", 1 if snap["gathered"] else 0)])

    agg = snap.get("aggregate") or {}
    for key, value in (agg.get("counters") or {}).items():
        # histogram samples render as le-labelled families below, never as
        # flat counter scalars — the same is_histogram_sample_key carve-out
        # prometheus_text applies, so the two expositions cannot disagree
        if _telemetry.is_histogram_sample_key(key):
            continue
        family(_prom_name(key), "counter", [("", float(value))])
    for key, stats in (agg.get("gauges") or {}).items():
        for stat in ("min", "median", "max"):
            family(f"{_prom_name(key)}_{stat}", "gauge", [("", float(stats[stat]))])

    ranks = snap.get("ranks") or {}
    live_samples, dead_samples, degraded_samples = [], [], []
    phase_samples: Dict[str, List[Tuple[str, float]]] = {
        "count": [], "mean": [], "max": [], "total": [], "p95": []
    }
    per_rank_latency: Dict[str, Dict[str, Any]] = {}
    for rank in sorted(ranks):
        plane = ranks[rank]
        label = f'{{rank="{rank}"}}'
        alive = _is_live_plane(plane)
        live_samples.append((label, 1 if alive else 0))
        dead_samples.append((label, 1 if (isinstance(plane, dict) and plane.get("dead")) else 0))
        if alive:
            health = plane.get("sync_health") or {}
            degraded_samples.append((label, 1 if health.get("degraded") else 0))
            stats = plane.get("sync_phase_stats") or {}
            latency = plane.get("latency_stats") or {}
            for site in _telemetry.SYNC_PHASE_SITES:
                block = stats.get(site) or {}
                if not float(block.get("count", 0)):
                    continue
                plabel = f'{{rank="{rank}",phase="{site}"}}'
                phase_samples["count"].append((plabel, float(block.get("count", 0))))
                phase_samples["mean"].append((plabel, float(block.get("mean_s", 0.0))))
                phase_samples["max"].append((plabel, float(block.get("max_s", 0.0))))
                phase_samples["total"].append((plabel, float(block.get("total_s", 0.0))))
                lat = latency.get(site) or {}
                if float(lat.get("count", 0)) > 0:
                    # tail-aware twin of the mean sample: full-lifetime p95
                    phase_samples["p95"].append((plabel, float(lat.get("p95_s", 0.0))))
            for site, block in latency.items():
                # composite key carries rank + site through the shared
                # histogram renderer ('\x00' cannot appear in a site name)
                per_rank_latency[f"{rank}\x00{site}"] = block
    family("metrics_tpu_fleet_rank_live", "gauge", live_samples)
    family("metrics_tpu_fleet_rank_dead", "gauge", dead_samples)
    family("metrics_tpu_fleet_rank_degraded", "gauge", degraded_samples)
    family("metrics_tpu_fleet_sync_phase_count", "gauge", phase_samples["count"])
    family("metrics_tpu_fleet_sync_phase_mean_seconds", "gauge", phase_samples["mean"])
    family("metrics_tpu_fleet_sync_phase_max_seconds", "gauge", phase_samples["max"])
    family("metrics_tpu_fleet_sync_phase_total_seconds", "gauge", phase_samples["total"])
    family("metrics_tpu_fleet_sync_phase_p95_seconds", "gauge", phase_samples["p95"])

    stragglers = snap.get("stragglers") or {}
    dev_samples, tail_samples = [], []
    for site, entry in (stragglers.get("phases") or {}).items():
        for rank, dev in (entry.get("per_rank_deviation") or {}).items():
            dev_samples.append((f'{{rank="{rank}",phase="{site}"}}', float(dev)))
        for rank, dev in (entry.get("per_rank_tail_deviation") or {}).items():
            tail_samples.append((f'{{rank="{rank}",phase="{site}"}}', float(dev)))
    family("metrics_tpu_fleet_straggler_deviation", "gauge", dev_samples)
    family("metrics_tpu_fleet_straggler_tail_deviation", "gauge", tail_samples)
    flagged = [(f'{{rank="{r}"}}', 1.0) for r in stragglers.get("stragglers") or ()]
    family("metrics_tpu_fleet_straggler_flagged", "gauge", flagged)

    # the model-monitoring families (streaming.py): fleet-agreed per-window
    # METRIC VALUES (the first exposition of metric values, not system
    # telemetry), the agreed window ids, drift scores, and per-rank
    # window-skew attribution — names per the docs/observability.md table
    streaming = snap.get("streaming") or {}
    value_samples, id_samples = [], []
    for wname, block in (streaming.get("windows") or {}).items():
        if not isinstance(block, dict):
            continue
        id_samples.append((f'{{name="{wname}"}}', float(block.get("window", 0))))
        for wid, values in (block.get("values") or {}).items():
            for key, value in (values or {}).items():
                label_name = wname if key == "value" else f"{wname}.{key}"
                value_samples.append(
                    (f'{{name="{label_name}",window="{wid}"}}', float(value))
                )
    # the tenant-arena cohorts (arena.py): every cohort's newest computed
    # values join the SAME metric-value family, disambiguated by the
    # tenant_cohort label — one dashboard family for singleton windows and
    # million-tenant arenas alike
    tenant_samples = []
    for aname, block in (streaming.get("arenas") or {}).items():
        if not isinstance(block, dict):
            continue
        tenant_samples.append((f'{{name="{aname}"}}', float(block.get("tenants", 0))))
        for cohort, scalars in (block.get("cohorts") or {}).items():
            for key, value in (scalars or {}).items():
                label_name = aname if key == "value" else f"{aname}.{key}"
                value_samples.append(
                    (f'{{name="{label_name}",tenant_cohort="{cohort}"}}', float(value))
                )
        for wid, per_cohort in (block.get("values") or {}).items():
            for cohort, scalars in (per_cohort or {}).items():
                for key, value in (scalars or {}).items():
                    label_name = aname if key == "value" else f"{aname}.{key}"
                    value_samples.append(
                        (
                            f'{{name="{label_name}",tenant_cohort="{cohort}",window="{wid}"}}',
                            float(value),
                        )
                    )
    family("metrics_tpu_metric_value", "gauge", value_samples)
    family("metrics_tpu_fleet_window_id", "gauge", id_samples)
    family("metrics_tpu_fleet_arena_tenants", "gauge", tenant_samples)
    drift_samples = []
    for dname, scores in (streaming.get("drift") or {}).items():
        for kind in ("psi", "ks"):
            if isinstance(scores, dict) and kind in scores:
                drift_samples.append(
                    (f'{{name="{dname}",kind="{kind}"}}', float(scores[kind]))
                )
    family("metrics_tpu_drift_score", "gauge", drift_samples)
    skew_samples = []
    for wname, entry in (streaming.get("window_skew") or {}).items():
        for rank, lag in (entry.get("per_rank_lag") or {}).items():
            skew_samples.append((f'{{rank="{rank}",name="{wname}"}}', float(lag)))
    family("metrics_tpu_fleet_window_skew", "gauge", skew_samples)

    # the ingestion-gateway families (ingest.py): per-rank, per-gateway
    # staging occupancy, degraded-tier flags and quarantine depth — the
    # admission-control surface a fleet dashboard alerts on (the ingest_*
    # settlement counters already aggregate above as metrics_tpu_fleet_*)
    ingest_samples: Dict[str, List[Tuple[str, float]]] = {
        "staging_rows": [], "staging_bytes": [], "degraded": [], "quarantine_depth": []
    }
    for rank in sorted(ranks):
        plane = ranks[rank]
        if not _is_live_plane(plane):
            continue
        gw_blocks = ((plane.get("ingest_state") or {}).get("gateways")) or {}
        for gname, st in gw_blocks.items():
            if not isinstance(st, dict):
                continue
            glabel = f'{{rank="{rank}",gateway="{gname}"}}'
            ingest_samples["staging_rows"].append((glabel, float(st.get("staging_rows", 0))))
            ingest_samples["staging_bytes"].append((glabel, float(st.get("staging_bytes", 0))))
            ingest_samples["degraded"].append((glabel, 1.0 if st.get("degraded") else 0.0))
            ingest_samples["quarantine_depth"].append(
                (glabel, float(st.get("quarantine_depth", 0)))
            )
    family("metrics_tpu_ingest_staging_rows", "gauge", ingest_samples["staging_rows"])
    family("metrics_tpu_ingest_staging_bytes", "gauge", ingest_samples["staging_bytes"])
    family("metrics_tpu_ingest_degraded", "gauge", ingest_samples["degraded"])
    family("metrics_tpu_ingest_quarantine_depth", "gauge", ingest_samples["quarantine_depth"])

    lines: List[str] = []
    for name, kind, samples in families:
        lines.append(f"# TYPE {name} {kind}")
        lines.extend(samples)
    # histogram families LAST (the scalar families above stay one TYPE line +
    # unlabelled/labelled samples; the renderer below emits its own headers):
    # the fleet-merged histograms (exact bucket sums, le-labelled) and the
    # rank-labelled per-rank histograms — same renderer prometheus_text uses,
    # so the local and fleet expositions cannot disagree about layout
    lines.extend(
        _telemetry._histogram_exposition_lines(
            agg.get("latency_stats") or {}, family="metrics_tpu_fleet_latency_seconds"
        )
    )

    def _rank_site_label(key: str) -> str:
        rank, site = key.split("\x00", 1)
        return f'rank="{rank}",site="{site}"'

    lines.extend(
        _telemetry._histogram_exposition_lines(
            per_rank_latency,
            family="metrics_tpu_fleet_rank_latency_seconds",
            label_for=_rank_site_label,
        )
    )
    return "\n".join(lines) + "\n"


def fleet_perf_report() -> Dict[str, Any]:
    """The cross-rank face of :func:`metrics_tpu.ops.perf.perf_report`:
    every rank's step-latency decomposition merged into one fleet view.

    In a multi-rank world each rank's locally-computed report rides ONE
    epoch-fenced, deadline-guarded blob gather (:func:`_gather_blobs`) — a
    **collective**, every live rank in lockstep, like ``fleet_snapshot()``.
    With a world size of 1 the local report is served directly, zero
    collectives. Keys: per-rank ``reports`` (corrupt rows get a
    ``{"corrupt": True}`` placeholder), ``aggregate_phases`` — per-phase
    exclusive seconds SUMMED EXACTLY across live ranks (phase time is a
    duration counter over each rank's window, so the sum is the fleet's
    total attributed wall), and ``slowest_rank_per_phase`` — the rank
    spending the most wall in each phase, the per-phase twin of the
    straggler report.

    Example:
        >>> from metrics_tpu import fleet_perf_report
        >>> report = fleet_perf_report()   # single process: local only
        >>> report["gathered"], report["rank"] in report["reports"]
        (False, True)
    """
    from metrics_tpu.ops import perf as _perf
    from metrics_tpu.parallel import sync as _sync

    local = _perf.perf_report()
    wh = _sync.world_health()
    world = fleet_world()
    rank = local_rank()
    dead = set(wh.get("dead_ranks") or ())
    reports: Dict[int, Dict[str, Any]] = {}
    gathered = False
    if world > 1:
        blob = json.dumps(_telemetry._json_safe(local), separators=(",", ":")).encode("utf-8")
        payloads = _gather_blobs(blob, site="fleet-snapshot")
        for r, raw in zip(_participant_ranks(world, dead), payloads):
            try:
                decoded = json.loads(raw.decode("utf-8"))
                if not isinstance(decoded, dict):
                    raise ValueError("rank report must be an object")
                reports[r] = decoded
            except (ValueError, UnicodeDecodeError):
                reports[r] = {"corrupt": True, "rank": r}
        gathered = True
    else:
        reports[rank] = local
    agg: Dict[str, float] = {p: 0.0 for p in _perf.PHASES}
    slowest: Dict[str, Tuple[int, float]] = {}
    for r, rep in sorted(reports.items()):
        if not _is_live_plane(rep):
            continue
        for p, block in (rep.get("phases") or {}).items():
            t = float((block or {}).get("total_s", 0.0))
            if p not in agg:
                continue  # unknown phase (mixed-version fleet): neither table
            agg[p] += t
            if t > 0 and (p not in slowest or t > slowest[p][1]):
                slowest[p] = (r, t)
    return {
        "fleet_schema": FLEET_SCHEMA,
        "world_size": world,
        "rank": rank,
        "gathered": gathered,
        "reports": reports,
        "aggregate_phases": {p: round(v, 6) for p, v in agg.items()},
        "slowest_rank_per_phase": {
            p: {"rank": r, "total_s": round(t, 6)} for p, (r, t) in sorted(slowest.items())
        },
    }


# ----------------------------------------------------------- merged trace
def _anchor_points(rows: List[Dict[str, Any]]) -> Dict[Tuple[str, int], float]:
    """Clock-alignment anchors: payload-collective spans carrying the
    lockstep ``seq`` ordinal. Every rank blocks inside the same collective,
    so same-seq spans mark (approximately) the same wall moment; the median
    pairwise difference recovers the per-rank clock offset."""
    anchors: Dict[Tuple[str, int], float] = {}
    for row in rows:
        attrs = row.get("attrs") or {}
        if row.get("site") in ("sync-payload-gather", "sync-gather") and "seq" in attrs:
            anchors[(row["site"], int(attrs["seq"]))] = float(row["t_start"]) + float(
                row.get("dur") or 0.0
            )
    return anchors


def export_fleet_trace(path: str) -> int:
    """Gather every rank's span ring and write ONE merged Perfetto JSON with
    one **process per rank** (``pid`` = rank, per-owner threads inside it),
    so a cross-rank sync timeline — who entered the collective late, whose
    unpack ran long — is visible in a single view.

    Ranks are aligned on the shared monotonic axis: paired payload-gather
    spans (identical lockstep ``seq`` ordinals) act as clock-offset anchors,
    and each rank's timestamps shift by the median anchor difference against
    the lowest-ranked participant (recorded under
    ``otherData.clock_offsets_s``; alignment is approximate — anchors mark
    the collective's *completion*, which skews by per-rank unblock order).
    With a world size of 1 the local ring exports directly, zero
    collectives. Returns the number of span events written; the output
    passes ``tools/trace_report.py --check``.

    Example:
        >>> import os, tempfile
        >>> from metrics_tpu import export_fleet_trace
        >>> path = os.path.join(tempfile.mkdtemp(), "fleet-trace.json")
        >>> _ = export_fleet_trace(path)
        >>> os.path.exists(path)
        True
    """
    from metrics_tpu.parallel import sync as _sync

    t0 = _telemetry.now() if _telemetry.armed else 0.0
    wh = _sync.world_health()
    world = fleet_world()
    rank = local_rank()
    dead = set(wh.get("dead_ranks") or ())
    local_doc = {
        "rank": rank,
        "spans": _telemetry.spans(),
        "snapshot": {k: v for k, v in _telemetry.snapshot().items() if k != "failure_log"},
    }
    docs: Dict[int, Dict[str, Any]] = {}
    if world > 1:
        blob = json.dumps(_telemetry._json_safe(local_doc), separators=(",", ":")).encode("utf-8")
        payloads = _gather_blobs(blob, site="fleet-trace")
        participants = _participant_ranks(world, dead)
        dropped: List[int] = []
        mismatched: List[int] = []
        for r, raw in zip(participants, payloads):
            try:
                decoded = json.loads(raw.decode("utf-8"))
                if isinstance(decoded, dict) and isinstance(decoded.get("spans"), list):
                    # rows key POSITIONALLY (survivors ascending — the same
                    # mapping fleet_snapshot uses); a row claiming another
                    # rank's number must not overwrite that rank's ring
                    if decoded.get("rank") not in (None, r):
                        mismatched.append(r)
                    docs[r] = decoded
                else:
                    dropped.append(r)
            except (ValueError, UnicodeDecodeError):
                dropped.append(r)
        if dropped or mismatched:
            # no-silent-caps: a rank whose ring was lost in transit must not
            # read as "that rank emitted no spans"
            from metrics_tpu.ops import faults as _faults

            detail = []
            if dropped:
                detail.append(f"dropped undecodable row(s) for rank(s) {dropped}")
            if mismatched:
                detail.append(
                    f"row(s) at position(s) {mismatched} claimed a different rank "
                    "(kept under their positional rank)"
                )
            _faults.warn_fault(
                _TRACE_DROP_WARN_OWNER,
                "sync",
                "export_fleet_trace " + "; ".join(detail) + "; the merged trace may "
                "omit or misattribute those processes.",
            )
        if rank not in docs:
            docs[rank] = local_doc
    else:
        docs[rank] = local_doc

    # ---- clock alignment against the lowest-ranked participant ----
    ref = min(docs)
    ref_anchors = _anchor_points(docs[ref]["spans"])
    offsets: Dict[int, float] = {}
    for r, doc in sorted(docs.items()):
        if r == ref:
            offsets[r] = 0.0
            continue
        anchors = _anchor_points(doc["spans"])
        shared = sorted(set(ref_anchors) & set(anchors))
        offsets[r] = (
            _median([ref_anchors[k] - anchors[k] for k in shared]) if shared else 0.0
        )

    # ---- one process per rank ----
    aligned: List[Tuple[float, Dict[str, Any]]] = []
    meta: List[Dict[str, Any]] = []
    next_tid = 1
    for r, doc in sorted(docs.items()):
        meta.append(
            {"ph": "M", "name": "process_name", "pid": r, "tid": 0, "ts": 0,
             "args": {"name": f"rank {r}"}}
        )
        tids: Dict[str, int] = {}
        for row in doc["spans"]:
            owner = row.get("owner") or "global"
            tid = tids.get(owner)
            if tid is None:
                tid = tids[owner] = next_tid
                next_tid += 1
                meta.append(
                    {"ph": "M", "name": "thread_name", "pid": r, "tid": tid, "ts": 0,
                     "args": {"name": owner}}
                )
            args: Dict[str, Any] = {"step": row.get("step"), "rank": r}
            if row.get("lane"):
                args["lane"] = row["lane"]
            if row.get("attrs"):
                args.update(_telemetry._json_safe(row["attrs"]))
            ev: Dict[str, Any] = {
                "name": row.get("site"),
                "cat": row.get("lane") or "span",
                "pid": r,
                "tid": tid,
                "args": args,
            }
            dur = float(row.get("dur") or 0.0)
            if dur > 0:
                ev["ph"] = "X"
                ev["dur"] = round(dur * 1e6, 3)
            else:
                ev["ph"] = "i"
                ev["s"] = "t"
            aligned.append((float(row["t_start"]) + offsets[r], ev))

    n_events = len(aligned)
    t_min = min(t for t, _ in aligned) if aligned else 0.0
    events: List[Dict[str, Any]] = []
    for t, ev in sorted(aligned, key=lambda kv: kv[0]):
        ev["ts"] = round(max(0.0, t - t_min) * 1e6, 3)
        events.append(ev)

    merged = merge_snapshots(
        {r: {k: v for k, v in (doc.get("snapshot") or {}).items()} for r, doc in docs.items()}
    )
    doc_out = {
        "displayTimeUnit": "ms",
        "otherData": {
            "generator": "metrics_tpu.ops.fleetobs",
            "schema": FLEET_SCHEMA,
            "ranks": sorted(docs),
            "dead_ranks": sorted(dead),
            "clock_offsets_s": {str(r): offsets[r] for r in sorted(offsets)},
        },
        # the exact-summed counter plane plus the structurally-merged latency
        # histograms, so the trace report's latency digest works on a merged
        # fleet trace too
        "snapshot": dict(merged["counters"], latency_stats=merged["latency_stats"]),
        "traceEvents": meta + events,
    }
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(doc_out, fh, separators=(",", ":"))
    _counters["fleet_trace_exports"] += 1
    if t0 and _telemetry.armed:
        _telemetry.emit(
            "fleet-trace", None, "sync", t0, _telemetry.now() - t0,
            {"world": world, "ranks": len(docs), "events": n_events},
        )
    return n_events
