"""Donated-state dispatch engine — one owner for every fused metric program.

Four call sites used to roll their own program construction and caching:
``Metric`` (fused bare-update / fused forward / batched-scan programs),
``MetricCollection`` (whole-suite forward and scan), the fan-out wrappers
(`wrappers/_fanout.py` weighted-row and vmapped clone programs) and
``BootStrapper``'s clone programs on top of them. Each cached per *instance*,
compiled without donation, and re-compiled per identically-configured
instance. This module centralizes all of that behind two primitives:

- :func:`acquire` — a **cross-instance program cache** keyed by
  ``(program kind, config fingerprint, structural extras)``. The fingerprint
  digests the metric class, its public hyperparameters and its state
  registry, recursing into child metrics, so the N bootstrap clones of one
  base config, the members of a MetricCollection, and repeated constructions
  of the same metric class share ONE compiled program (XLA's jit cache then
  dedupes avals within it). A second same-config instance compiles zero new
  programs — observable via :func:`engine_stats` and the shared jitted
  callable's ``_cache_size``.

- :class:`Executable` — every cached program carries a **donated** twin
  (``jax.jit(..., donate_argnums=(0,))`` over the state tree) next to the
  plain one. Fused steps donate the incoming state buffers so XLA writes the
  new state in place instead of allocating a fresh tree per step — the
  update/forward hot path stops paying an alloc+copy per leaf per step.
  Donation is applied only when provably safe for that call
  (:func:`state_donatable`): every leaf a concrete, strongly-typed, live
  ``jax.Array`` and no buffer appearing twice in the tree (compute groups
  share leaves across collection members; donating a shared buffer twice is
  an XLA runtime error). Unsafe calls silently use the plain twin — same
  trace, same numbers.

Donation makes the PREVIOUS state buffers invalid. The metric instance
replaces its state attributes immediately after every fused step, and
``Metric._wrap_compute`` decouples any compute result that aliases a live
state leaf, so user-held compute values survive later donated steps. Raw
state references captured via direct attribute access before a fused step
are not protected — hold ``compute()`` results, not state leaves.
"""
from __future__ import annotations

import hashlib
from collections import OrderedDict
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import numpy as np

__all__ = [
    "Executable",
    "acquire",
    "acquire_keyed",
    "config_fingerprint",
    "donation_supported",
    "engine_stats",
    "reset_engine",
    "state_donatable",
    "state_intact",
]


# --------------------------------------------------------------- donation probe
_donation_supported: Optional[bool] = None


def donation_supported() -> bool:
    """Whether this backend actually consumes donated buffers (probed once).

    Backends without donation support leave the input alive and warn per
    call; probing once lets the engine route every call through the plain
    twin there, keeping the fast path warning-free.
    """
    global _donation_supported
    if _donation_supported is None:
        try:
            import warnings

            import jax.numpy as jnp

            probe = jax.jit(lambda s: s + 1, donate_argnums=(0,))
            x = jnp.zeros((), jnp.float32)
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                probe(x)
            _donation_supported = bool(x.is_deleted())
        except Exception:  # noqa: BLE001 — any probe failure → plain programs
            _donation_supported = False
    return _donation_supported


def state_donatable(state: Any, avoid_ids: Optional[frozenset] = None) -> bool:
    """True when donating ``state``'s buffers is provably safe for this call.

    Requires every leaf to be a concrete, live, strongly-typed ``jax.Array``
    and every buffer to appear exactly once: compute groups alias one leaf
    across several collection members, and XLA rejects donating the same
    buffer twice at runtime; weak-typed leaves are refused donation by jax
    with a per-call warning. ``avoid_ids`` lists buffers that must never be
    donated — callers pass their registered default-state arrays, which
    ``reset()`` re-issues as live state and must therefore outlive any step.
    """
    seen_ids = set()
    for leaf in jax.tree.flatten(state)[0]:
        if not isinstance(leaf, jax.Array) or isinstance(leaf, jax.core.Tracer):
            return False
        if getattr(leaf, "weak_type", False) or leaf.is_deleted():
            return False
        i = id(leaf)
        if i in seen_ids or (avoid_ids is not None and i in avoid_ids):
            return False
        seen_ids.add(i)
    return True


def state_intact(state: Any) -> bool:
    """True when no state leaf has been deleted (post-failure fallback guard:
    an eager retry over donated-away buffers would raise a confusing
    deleted-buffer error instead of the original one)."""
    for leaf in jax.tree.flatten(state)[0]:
        if isinstance(leaf, jax.Array) and not isinstance(leaf, jax.core.Tracer) and leaf.is_deleted():
            return False
    return True


# ----------------------------------------------------------------- fingerprints
def _value_digest(value: Any, depth: int = 0) -> Any:
    """Collision-safe digest of one hyperparameter value.

    ``repr`` alone is NOT enough for arrays: numpy truncates reprs past
    1000 elements, so two metrics differing only in the middle of a long
    ``thresholds`` array would fingerprint equal and silently share a
    program baking the wrong constants. Arrays digest by full content hash;
    containers recurse (bounded); everything else falls back to repr.
    """
    if isinstance(value, (jax.Array, np.ndarray, np.generic)) and not isinstance(
        value, jax.core.Tracer
    ):
        host = np.asarray(value)
        return ("array", host.shape, str(host.dtype), hashlib.sha1(host.tobytes()).hexdigest())
    if depth < 3 and isinstance(value, (list, tuple)):
        return (type(value).__name__, tuple(_value_digest(v, depth + 1) for v in value))
    if depth < 3 and isinstance(value, dict):
        return (
            "dict",
            tuple(sorted((repr(k), _value_digest(v, depth + 1)) for k, v in value.items())),
        )
    return repr(value)


def config_fingerprint(metric: Any) -> tuple:
    """Hashable digest of everything a fused program bakes in.

    Covers the concrete class, every public non-state attribute (scalar
    hyperparameters by ``repr``; array-valued ones like ``thresholds`` by
    full content hash — see :func:`_value_digest`; the same surface whose
    mutation bumps ``_fused_version``), the state registry (names, reduction
    specs, default avals), and — recursively — every child metric. Two
    instances with equal fingerprints trace to the same program; an
    attribute whose repr embeds an object address simply keys a private
    cache slot (correct, just unshared). Distributed-transport knobs are
    excluded: they gate *whether* a fused path runs, never what the program
    computes.
    """
    cls = type(metric)
    skip = ("update", "compute", "compute_on_cpu", "process_group", "dist_sync_fn")
    defaults = getattr(metric, "_defaults", {})
    attrs = tuple(
        (k, _value_digest(v))
        for k, v in sorted(metric.__dict__.items())
        if not k.startswith("_") and k not in defaults and k not in skip
    )
    states = tuple(
        (
            name,
            metric._reduction_specs.get(name),
            "list"
            if isinstance(default, list)
            else (tuple(default.shape), str(default.dtype)),
        )
        for name, default in sorted(defaults.items())
    )
    children = tuple(
        (name, config_fingerprint(child)) for name, child in metric._named_child_metrics()
    )
    return (cls.__module__, cls.__qualname__, attrs, states, children)


# --------------------------------------------------------------- program cache
class Executable:
    """A cached fused program: donated fast path plus its plain twin.

    Calling executes the donated twin when :func:`state_donatable` passes for
    this call's state tree (and the backend supports donation), else the
    plain twin — one trace, two compiled aliasing policies. ``template``
    carries the bare metric clone(s) the step closure runs on (callers
    propagate update-inferred static attrs from it); ``aux`` holds
    build-time facts like ``needs_count``.
    """

    __slots__ = ("donated", "plain", "template", "aux", "__weakref__")

    def __init__(self, donated: Optional[Callable], plain: Callable, template: Any, aux: Dict[str, Any]):
        self.donated = donated
        self.plain = plain
        self.template = template
        self.aux = aux

    def __call__(self, state: Any, *args: Any, **kwargs: Any) -> Any:
        # plain twin: trace/probe-friendly (``jax.eval_shape`` over an
        # Executable exercises exactly the math the donated twin compiles)
        return self.plain(state, *args, **kwargs)

    def run(
        self,
        state: Any,
        args: tuple = (),
        kwargs: Optional[dict] = None,
        *,
        donate: bool = True,
        avoid_ids: Optional[frozenset] = None,
    ) -> Any:
        """Execute with in-place state: the donated twin when safe for THIS
        call's buffers, else the plain twin — same trace either way."""
        kwargs = kwargs or {}
        if (
            donate
            and self.donated is not None
            and donation_supported()
            and state_donatable(state, avoid_ids)
        ):
            return self.donated(state, *args, **kwargs)
        return self.plain(state, *args, **kwargs)

    def compiled_signatures(self) -> int:
        """Number of aval signatures compiled across both twins — lets tests
        assert a second same-config instance added zero compiles."""
        count = 0
        for fn in (self.donated, self.plain):
            size = getattr(fn, "_cache_size", None)
            if callable(size):
                count += size()
        return count


_PROGRAM_CACHE: "OrderedDict[tuple, Executable]" = OrderedDict()
_CACHE_CAP = 256
_stats = {"builds": 0, "hits": 0}


def acquire(
    owner: Any,
    kind: str,
    build: Callable[[], Tuple[Callable, Any, Dict[str, Any]]],
    *,
    extra_key: tuple = (),
    donate: bool = True,
) -> Executable:
    """Fetch (or build once) the fused program for ``owner``'s configuration.

    ``build()`` returns ``(step_fn, template, aux)`` where ``step_fn`` takes
    the state tree as its first argument. The compiled pair is cached under
    ``(kind, fingerprint(owner), *extra_key)`` with LRU eviction, so every
    identically-configured instance — bootstrap clones, collection members,
    re-constructions — reuses one program object and its jit aval cache.
    """
    return acquire_keyed((kind, config_fingerprint(owner)) + tuple(extra_key), build, donate=donate)


def acquire_keyed(
    key: tuple,
    build: Callable[[], Tuple[Callable, Any, Dict[str, Any]]],
    *,
    donate: bool = True,
) -> Executable:
    """:func:`acquire` for callers that assemble their own cache key —
    MetricCollection keys by its members' fingerprints, the fan-out wrappers
    by wrapper + clone fingerprints."""
    exe = _PROGRAM_CACHE.get(key)
    if exe is not None:
        _stats["hits"] += 1
        _PROGRAM_CACHE.move_to_end(key)
        return exe
    _stats["builds"] += 1
    step, template, aux = build()
    exe = Executable(
        jax.jit(step, donate_argnums=(0,)) if donate else None,
        jax.jit(step),
        template,
        aux,
    )
    _PROGRAM_CACHE[key] = exe
    while len(_PROGRAM_CACHE) > _CACHE_CAP:
        _PROGRAM_CACHE.popitem(last=False)
    return exe


def engine_stats() -> Dict[str, int]:
    """Cache effectiveness counters: ``builds`` (distinct programs traced),
    ``hits`` (program acquisitions served from cache), ``cached`` (live)."""
    return {"builds": _stats["builds"], "hits": _stats["hits"], "cached": len(_PROGRAM_CACHE)}


def reset_engine() -> None:
    """Drop every cached program and zero the counters (tests; and the escape
    hatch after a backend restart invalidates compiled executables)."""
    _PROGRAM_CACHE.clear()
    _stats["builds"] = 0
    _stats["hits"] = 0
