"""Donated-state dispatch engine — one owner for every fused metric program.

Four call sites used to roll their own program construction and caching:
``Metric`` (fused bare-update / fused forward / batched-scan programs),
``MetricCollection`` (whole-suite forward and scan), the fan-out wrappers
(`wrappers/_fanout.py` weighted-row and vmapped clone programs) and
``BootStrapper``'s clone programs on top of them. Each cached per *instance*,
compiled without donation, and re-compiled per identically-configured
instance. This module centralizes all of that behind two primitives:

- :func:`acquire` — a **cross-instance program cache** keyed by
  ``(program kind, config fingerprint, structural extras)``. The fingerprint
  digests the metric class, its public hyperparameters and its state
  registry, recursing into child metrics, so the N bootstrap clones of one
  base config, the members of a MetricCollection, and repeated constructions
  of the same metric class share ONE compiled program (XLA's jit cache then
  dedupes avals within it). A second same-config instance compiles zero new
  programs — observable via :func:`engine_stats` and the shared jitted
  callable's ``_cache_size``.

- :class:`Executable` — every cached program carries a **donated** twin
  (``jax.jit(..., donate_argnums=(0,))`` over the state tree) next to the
  plain one. Fused steps donate the incoming state buffers so XLA writes the
  new state in place instead of allocating a fresh tree per step — the
  update/forward hot path stops paying an alloc+copy per leaf per step.
  Donation is applied only when provably safe for that call
  (:func:`state_donatable`): every leaf a concrete, strongly-typed, live
  ``jax.Array`` and no buffer appearing twice in the tree (compute groups
  share leaves across collection members; donating a shared buffer twice is
  an XLA runtime error). Unsafe calls silently use the plain twin — same
  trace, same numbers.

Donation makes the PREVIOUS state buffers invalid. The metric instance
replaces its state attributes immediately after every fused step, and
``Metric._wrap_compute`` decouples any compute result that aliases a live
state leaf, so user-held compute values survive later donated steps. Raw
state references captured via direct attribute access before a fused step
are not protected — hold ``compute()`` results, not state leaves.

Deferred micro-batched dispatch (the third tier, on top of the two above):
even a fused single-step program pays one backend round trip per call, which
bounds any eager loop at ``1000/program_roundtrip_ms`` steps/s. The deferral
layer removes the per-call dispatch entirely: eligible ``update``/``forward``
calls enqueue their (host-staged) arguments into a per-owner
:class:`PendingQueue` instead of dispatching, and the queue flushes as ONE
stacked ``lax.scan`` program — the same donated-state scan programs the
batched ``update_many``/``forward_many`` API compiles — when a size/age
threshold trips or when state is observed. Observation is total by
construction: while a queue is pending, the owner's state attributes are
POPPED out of its ``__dict__`` into the queue's backing store, so *any*
state read (``compute``, ``sync``, ``reset``, pickling, ``state_dict``,
direct attribute access) lands in ``Metric.__getattr__`` and flushes in
enqueue order — results stay bit-exact with the step-by-step eager path.
``forward`` returns a :class:`LazyValue` handle that forces the flush only
when its value is actually read, so update-only loops pay ~zero dispatches
until observation. Chunk lengths are bucketed to powers of two
(order-preserving consecutive slices), bounding the scan compile cache to
~log2(max_pending) shapes per signature, so ragged flush points (a
mid-queue observation) never trigger unbounded recompiles.
``METRICS_TPU_DEFER=0`` (or :func:`set_deferred_dispatch`) restores the
per-call fused dispatch behavior exactly.
"""
from __future__ import annotations

import enum
import hashlib
import os
import sys
import time
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import numpy as np

from metrics_tpu.ops import faults as _faults
from metrics_tpu.ops import progcache as _progcache
from metrics_tpu.ops import telemetry as _telemetry

__all__ = [
    "Executable",
    "LazyValue",
    "PendingQueue",
    "acquire",
    "acquire_keyed",
    "config_fingerprint",
    "defer_enabled",
    "defer_max_age_s",
    "defer_max_pending",
    "device_probe_every",
    "donation_supported",
    "engine_stats",
    "export_trace",
    "pow2_chunks",
    "program_report",
    "program_summary",
    "reset_engine",
    "reset_stats",
    "roofline_peaks",
    "set_deferred_dispatch",
    "set_device_probe",
    "set_roofline_peaks",
    "state_donatable",
    "state_intact",
    "warm_programs",
]


# --------------------------------------------------------------- donation probe
_donation_supported: Optional[bool] = None


def donation_supported() -> bool:
    """Whether this backend actually consumes donated buffers (probed once).

    Backends without donation support leave the input alive and warn per
    call; probing once lets the engine route every call through the plain
    twin there, keeping the fast path warning-free.
    """
    global _donation_supported
    if _donation_supported is None:
        try:
            import warnings

            import jax.numpy as jnp

            probe = jax.jit(lambda s: s + 1, donate_argnums=(0,))
            x = jnp.zeros((), jnp.float32)
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                probe(x)
            _donation_supported = bool(x.is_deleted())
        except Exception:  # noqa: BLE001 — any probe failure → plain programs
            _donation_supported = False
    return _donation_supported


def state_donatable(state: Any, avoid_ids: Optional[frozenset] = None) -> bool:
    """True when donating ``state``'s buffers is provably safe for this call.

    Requires every leaf to be a concrete, live, strongly-typed ``jax.Array``
    and every buffer to appear exactly once: compute groups alias one leaf
    across several collection members, and XLA rejects donating the same
    buffer twice at runtime; weak-typed leaves are refused donation by jax
    with a per-call warning. ``avoid_ids`` lists buffers that must never be
    donated — callers pass their registered default-state arrays, which
    ``reset()`` re-issues as live state and must therefore outlive any step.
    """
    seen_ids = set()
    for leaf in jax.tree.flatten(state)[0]:
        if not isinstance(leaf, jax.Array) or isinstance(leaf, jax.core.Tracer):
            return False
        if getattr(leaf, "weak_type", False) or leaf.is_deleted():
            return False
        i = id(leaf)
        if i in seen_ids or (avoid_ids is not None and i in avoid_ids):
            return False
        seen_ids.add(i)
    return True


def state_intact(state: Any) -> bool:
    """True when no state leaf has been deleted (post-failure fallback guard:
    an eager retry over donated-away buffers would raise a confusing
    deleted-buffer error instead of the original one)."""
    for leaf in jax.tree.flatten(state)[0]:
        if isinstance(leaf, jax.Array) and not isinstance(leaf, jax.core.Tracer) and leaf.is_deleted():
            return False
    return True


# ---------------------------------------------------------- device-time probes
class _EngineWarnOwner:
    """Warn-dedupe anchor for this module's env-knob parse warnings."""


_ENV_WARN_OWNER = _EngineWarnOwner()

#: Resolved ``METRICS_TPU_DEVICE_PROBE_EVERY`` (None = not yet read; 0 = off).
_probe_every: Optional[int] = None
_probe_countdown: List[int] = [0]


def device_probe_every() -> int:
    """The device-probe sampling period: every Nth :class:`Executable`
    dispatch is forced with ``jax.block_until_ready`` and its
    device-INCLUSIVE wall lands in the ``device-dispatch:<program>``
    latency-histogram family (``METRICS_TPU_DEVICE_PROBE_EVERY=N``).

    0 / unset (the DEFAULT) disarms the probe entirely: the dispatch path
    pays one cached-int comparison and allocates nothing — pinned by the
    ``device_probe_overhead`` bench row. A garbage value warns once (naming
    the offending value) and stays disarmed. Host dispatch is asynchronous,
    so without probes every ``engine-dispatch`` span under-measures device
    time; a probed dispatch trades one pipeline bubble for the real
    measurement the roofline ledger joins (see docs/performance.md "Where
    the time goes")."""
    global _probe_every
    if _probe_every is None:
        raw = os.environ.get("METRICS_TPU_DEVICE_PROBE_EVERY")
        if raw is None or not raw.strip():
            _probe_every = 0
        else:
            try:
                _probe_every = max(0, int(raw))
            except ValueError:
                _probe_every = 0
                _faults.warn_fault(
                    _ENV_WARN_OWNER,
                    "env:METRICS_TPU_DEVICE_PROBE_EVERY",
                    f"METRICS_TPU_DEVICE_PROBE_EVERY={raw!r} is not an integer; "
                    "device-time probes stay OFF.",
                )
    return _probe_every


def set_device_probe(every: Optional[int]) -> None:
    """Override the probe period at runtime (``0`` disarms; ``None`` drops
    the cached value so ``METRICS_TPU_DEVICE_PROBE_EVERY`` is re-read on the
    next dispatch). Takes precedence over the environment."""
    global _probe_every
    _probe_every = None if every is None else max(0, int(every))
    _probe_countdown[0] = 0


# ----------------------------------------------------------------- fingerprints
def _value_digest(value: Any, depth: int = 0) -> Any:
    """Collision-safe digest of one hyperparameter value.

    ``repr`` alone is NOT enough for arrays: numpy truncates reprs past
    1000 elements, so two metrics differing only in the middle of a long
    ``thresholds`` array would fingerprint equal and silently share a
    program baking the wrong constants. Arrays digest by full content hash;
    containers recurse (bounded); everything else falls back to repr.
    """
    if isinstance(value, enum.Enum):
        # a journal manifest rehydrates enum-valued hyperparameters as their
        # plain values (the wire format has no enum type), and EnumStr
        # compares equal to its value — the string-moded restored instance
        # traces the SAME program the enum-moded one did. Digest both forms
        # identically, or a rejoin re-enters the epoch with every program
        # key cold and the first post-restore step recompiles needlessly.
        return _value_digest(value.value, depth)
    if isinstance(value, (jax.Array, np.ndarray, np.generic)) and not isinstance(
        value, jax.core.Tracer
    ):
        host = np.asarray(value)
        return ("array", host.shape, str(host.dtype), hashlib.sha1(host.tobytes()).hexdigest())
    if depth < 3 and isinstance(value, (list, tuple)):
        return (type(value).__name__, tuple(_value_digest(v, depth + 1) for v in value))
    if depth < 3 and isinstance(value, dict):
        return (
            "dict",
            tuple(sorted((repr(k), _value_digest(v, depth + 1)) for k, v in value.items())),
        )
    return repr(value)


def config_fingerprint(metric: Any) -> tuple:
    """Hashable digest of everything a fused program bakes in.

    Covers the concrete class, every public non-state attribute (scalar
    hyperparameters by ``repr``; array-valued ones like ``thresholds`` by
    full content hash — see :func:`_value_digest`; the same surface whose
    mutation bumps ``_fused_version``), the state registry (names, reduction
    specs, default avals), and — recursively — every child metric. Two
    instances with equal fingerprints trace to the same program; an
    attribute whose repr embeds an object address simply keys a private
    cache slot (correct, just unshared). Distributed-transport knobs are
    excluded: they gate *whether* a fused path runs, never what the program
    computes.
    """
    cls = type(metric)
    skip = ("update", "compute", "compute_on_cpu", "process_group", "dist_sync_fn")
    defaults = getattr(metric, "_defaults", {})
    attrs = tuple(
        (k, _value_digest(v))
        for k, v in sorted(metric.__dict__.items())
        if not k.startswith("_") and k not in defaults and k not in skip
    )
    states = tuple(
        (
            name,
            metric._reduction_specs.get(name),
            "list"
            if isinstance(default, list)
            else (tuple(default.shape), str(default.dtype)),
        )
        for name, default in sorted(defaults.items())
    )
    children = tuple(
        (name, config_fingerprint(child)) for name, child in metric._named_child_metrics()
    )
    return (cls.__module__, cls.__qualname__, attrs, states, children)


# --------------------------------------------------------------- program cache
#: AOT-lane sentinels: ``_AOT_MISS`` is the consult's "fall through to the
#: jit twins" result; ``_JIT_TWIN`` marks a signature as deliberately served
#: by the twins (fresh-compiled here, or demoted), so later dispatches skip
#: the store probe.
_AOT_MISS = object()
_JIT_TWIN = object()


def _counters_progcache_fallback(exe: "Executable", err: BaseException) -> None:
    """A rehydrated/AOT program failed AT EXECUTION (exact-aval mismatch or
    a bad module): classify, count a demotion, warn once per kind — the
    signature falls back to the jit twin permanently for this process."""
    from metrics_tpu.ops import progcache as _pc

    _pc._counters["progcache_demotions"] += 1
    domain = _faults.classify(err, "runtime")
    _faults.note_fault(domain, site="progcache-load", owner=exe, error=err)
    _faults.warn_fault(
        exe,
        domain,
        f"progcache AOT program for kind {exe.kind!r} failed at execution "
        f"({type(err).__name__}: {err}); this signature serves from a fresh "
        "compile — results are unaffected.",
    )


class Executable:
    """A cached fused program: donated fast path plus its plain twin.

    Calling executes the donated twin when :func:`state_donatable` passes for
    this call's state tree (and the backend supports donation), else the
    plain twin — one trace, two compiled aliasing policies. ``template``
    carries the bare metric clone(s) the step closure runs on (callers
    propagate update-inferred static attrs from it); ``aux`` holds
    build-time facts like ``needs_count``.

    Every executable doubles as a **program-ledger row**: each execution
    counts toward ``hits``-style run tallies (``donated_runs`` /
    ``plain_runs``), each call that grows a twin's jit aval cache is a
    compile event (``compiles`` / ``compile_time_s`` — first-call wall:
    trace + XLA compile + dispatch) whose abstract argument signature is
    retained so :func:`program_report` can attach XLA ``cost_analysis()`` /
    ``memory_analysis()`` on demand (an AOT re-lower of the plain twin —
    paid only when a report is actually requested, never on the hot path).

    With the persistent program cache enabled
    (:mod:`metrics_tpu.ops.progcache`), each executable also carries an
    **AOT lane**: per ``(donated, signature-digest)`` compiled callables
    rehydrated from exported modules (persistent-tier hits) or built ahead
    of traffic (:meth:`precompile`). ``_dispatch`` consults the lane before
    the jit twins, so a warmed boot dispatches without a single trace or
    XLA compile; their first-call wall is attributed to
    ``cache_load_time_s`` (not ``compile_time_s``) and the row's
    ``cache_source`` reports ``fresh`` / ``persistent`` / ``aot``.
    """

    __slots__ = (
        "donated",
        "plain",
        "template",
        "aux",
        "kind",
        "key_digest",
        "probe_key",
        "hits",
        "donated_runs",
        "plain_runs",
        "compiles",
        "compile_time_s",
        "cache_load_time_s",
        "cache_source",
        "aot",
        "pc_sigs",
        "dispatch_time_s",
        "arg_structs",
        "analysis",
        "analysis_failed",
        "variant",
        "__weakref__",
    )

    def __init__(self, donated: Optional[Callable], plain: Callable, template: Any, aux: Dict[str, Any]):
        self.donated = donated
        self.plain = plain
        self.template = template
        self.aux = aux
        self.kind = "anonymous"
        self.key_digest = ""
        self.probe_key = "anonymous"
        self.hits = 0
        self.donated_runs = 0
        self.plain_runs = 0
        self.compiles = 0
        self.compile_time_s = 0.0
        self.cache_load_time_s = 0.0
        self.cache_source = "fresh"
        # the AOT lane: {(donated, sig): compiled | _JIT_TWIN} — None until
        # the persistent cache is enabled/attached, so the disabled dispatch
        # path pays exactly one `is not None` check
        self.aot: Optional[Dict[Tuple[bool, str], Any]] = None
        self.pc_sigs: Optional[set] = None
        self.dispatch_time_s = 0.0
        self.arg_structs: Optional[tuple] = None
        self.analysis: Optional[Dict[str, Any]] = None
        self.analysis_failed = False
        # the autotuner's ledger column: which kernel variants this program
        # baked at trace time (None for untuned programs — the default)
        self.variant: Optional[str] = None

    def _capture_structs(self, state: Any, args: tuple, kwargs: dict) -> None:
        """Retain the just-compiled call's abstract signature (arrays as
        ``ShapeDtypeStruct``, python leaves as-is) for the on-demand
        cost-analysis lower in :func:`program_report`."""
        try:

            def leaf(x: Any) -> Any:
                if isinstance(x, jax.core.Tracer):
                    raise TypeError("traced call")  # probes: nothing to retain
                if isinstance(x, (jax.Array, np.ndarray, np.generic)):
                    return jax.ShapeDtypeStruct(np.shape(x), x.dtype)
                return x

            self.arg_structs = jax.tree.map(leaf, (state, args, kwargs))
            # a new signature invalidates the memoized analysis (success AND
            # the memoized-failure marker — the new avals may analyze fine)
            self.analysis = None
            self.analysis_failed = False
        except Exception:  # noqa: BLE001 — the ledger never breaks a dispatch
            pass

    def _attach_cache_lane(self) -> None:
        """Arm the AOT lane (idempotent): index which signatures the
        persistent store holds for this program identity."""
        if self.aot is None:
            self.aot = {}
            self.pc_sigs = set(_progcache.stored_sigs(self.kind, self.key_digest))

    def _lanes(self) -> Tuple[bool, ...]:
        if self.donated is not None and donation_supported():
            return (False, True)
        return (False,)

    def _install_loaded(self, donated: bool, sig: str, compiled: Any, load_dur: float) -> None:
        self.aot[(donated, sig)] = compiled
        self.cache_load_time_s += load_dur
        if self.cache_source == "fresh":
            self.cache_source = "persistent"

    def _dispatch_cached(
        self, donated: bool, state: Any, args: tuple, kwargs: dict, t0: float, record_span: bool
    ) -> Any:
        """The AOT-lane consult: serve this call from a rehydrated or
        precompiled executable when one exists for its signature, returning
        ``_AOT_MISS`` to fall through to the jit twins otherwise. Loads
        demote classified on any defect — a suspect entry is never run."""
        try:
            sig = _progcache.signature_digest(state, args, kwargs)
        except Exception:  # noqa: BLE001 — undigestable call: jit twin serves
            return _AOT_MISS
        cached = self.aot.get((donated, sig))
        if cached is None:
            if self.pc_sigs and sig in self.pc_sigs:
                loaded = _progcache.load_program(
                    self.kind, self.key_digest, sig,
                    donate=donated, state=state, args=args, kwargs=kwargs,
                )
                if loaded is None:
                    self.pc_sigs.discard(sig)
                    self.aot[(donated, sig)] = _JIT_TWIN
                    return _AOT_MISS
                compiled, load_dur = loaded
                self._install_loaded(donated, sig, compiled, load_dur)
                self._capture_structs(state, args, kwargs)
                cached = compiled
            else:
                # first sight, nothing stored: mark the signature as served
                # by the jit twin so later dispatches skip the store probe
                # (the fresh-compile branch counts the miss exactly once)
                self.aot[(donated, sig)] = _JIT_TWIN
                return _AOT_MISS
        elif cached is _JIT_TWIN:
            return _AOT_MISS
        try:
            out = cached(state, *args, **kwargs)
        except Exception as err:  # noqa: BLE001 — exact-aval mismatch or a
            # failed rehydrated program: demote THIS signature to the jit
            # twin (never a wrong program). If the donated attempt consumed
            # buffers the twin raises too and the caller's ladder handles it.
            self.aot[(donated, sig)] = _JIT_TWIN
            _counters_progcache_fallback(self, err)
            return _AOT_MISS
        if donated:
            self.donated_runs += 1
        else:
            self.plain_runs += 1
        host_dur = time.perf_counter() - t0
        self.dispatch_time_s += host_dur
        if record_span and _telemetry.armed:
            _telemetry.emit(
                "engine-dispatch", self.kind, "engine", t0, host_dur,
                {"async_host_wall": True, "cache_source": self.cache_source},
            )
        return out

    def precompile(self, state: Any, args: tuple = (), kwargs: Optional[dict] = None) -> str:
        """AOT-compile this program for ONE declared abstract signature
        before traffic arrives: persistent tier first (rehydrate a stored
        entry), else export + ``.lower(...).compile()`` fresh and persist
        the entry. ``state``/``args``/``kwargs`` may be concrete arrays or
        ``ShapeDtypeStruct`` declarations. Returns where the program came
        from: ``"cached"`` (lane already warm), ``"persistent"``,
        ``"aot"``, or ``"unsupported"`` (unexportable kind — it compiles
        lazily at first dispatch instead)."""
        kwargs = kwargs or {}
        self._attach_cache_lane()
        sig = _progcache.signature_digest(state, args, kwargs)
        missing = [d for d in self._lanes() if not callable(self.aot.get((d, sig)))]
        if not missing:
            return "cached"
        if self.pc_sigs and sig in self.pc_sigs:
            for d in list(missing):
                loaded = _progcache.load_program(
                    self.kind, self.key_digest, sig,
                    donate=d, state=state, args=args, kwargs=kwargs,
                )
                if loaded is None:
                    self.pc_sigs.discard(sig)
                    break
                self._install_loaded(d, sig, loaded[0], loaded[1])
                missing.remove(d)
            if not missing:
                self._capture_structs(state, args, kwargs)
                return "persistent"
        built = _progcache.build_aot(
            self.kind, self.key_digest, self.plain,
            lanes=tuple(missing), state=state, args=args, kwargs=kwargs,
        )
        if built is None:
            for d in missing:
                self.aot.setdefault((d, sig), _JIT_TWIN)
            return "unsupported"
        compiled_by_lane, dur, _sig = built
        for d, compiled in compiled_by_lane.items():
            self.aot[(d, sig)] = compiled
        # an AOT build is real compile wall (trace + export + wrapper XLA),
        # paid at boot instead of first dispatch — attributed as compile
        # cost, NOT cache-load cost
        self.compile_time_s += dur
        self.cache_source = "aot"
        if self.pc_sigs is not None:
            self.pc_sigs.add(sig)
        self._capture_structs(state, args, kwargs)
        return "aot"

    def warm_from_store(self) -> int:
        """Eagerly rehydrate EVERY signature the persistent store holds for
        this program (both donation lanes), deriving lowering avals from
        each exported module itself — the rejoin/rolling-restart path,
        where cached executables must be live before the first post-rejoin
        dispatch. Returns the number of compiled callables installed."""
        if not _progcache.enabled():
            return 0
        self._attach_cache_lane()
        loaded = 0
        for sig in sorted(self.pc_sigs or ()):
            for d in self._lanes():
                if callable(self.aot.get((d, sig))):
                    continue
                got = _progcache.load_program(self.kind, self.key_digest, sig, donate=d)
                if got is None:
                    self.pc_sigs.discard(sig)
                    break
                self._install_loaded(d, sig, got[0], got[1])
                loaded += 1
        return loaded

    def _dispatch(
        self, fn: Callable, donated: bool, state: Any, args: tuple, kwargs: dict, record_span: bool = True
    ) -> Any:
        if not _telemetry.armed and self.aot is None:
            # disarmed (METRICS_TPU_TELEMETRY=0) with no persistent
            # program-cache lane: the documented contract is ONE compound
            # predicate on the dispatch path — no clocks, no cache-size
            # probes, no tallies (ledger capture is part of the recorder).
            # An attached cache lane overrides disarm: serving a stored
            # program instead of recompiling NEEDS the consult + the compile
            # tallies (zero-compile certification counts them), so progcache
            # buys its ledger even when the span recorder is off.
            return fn(state, *args, **kwargs)
        if not jax.core.trace_state_clean():
            # abstract tracing (eval_shape probes, nested traces) never
            # dispatches: the ledger counts real executions only.
            return fn(state, *args, **kwargs)
        t0 = time.perf_counter()
        if self.aot is not None:
            # persistent/AOT lane active: consult it BEFORE the jit twins, so
            # a stored signature never traces (a would-be jit-cache miss is
            # resolved from the rehydrated exported module instead)
            out = self._dispatch_cached(donated, state, args, kwargs, t0, record_span)
            if out is not _AOT_MISS:
                return out
        size_fn = getattr(fn, "_cache_size", None)
        before = size_fn() if size_fn is not None else -1
        out = fn(state, *args, **kwargs)
        compiled = size_fn is not None and size_fn() > before
        if donated:
            self.donated_runs += 1
        else:
            self.plain_runs += 1
        if compiled:
            # this call traced+compiled a new aval signature: a ledger
            # compile event. First-call wall lands in compile_time_s ONLY
            # here — persistent-tier rehydrations attribute theirs to
            # cache_load_time_s in _dispatch_cached, so a warmed boot's
            # ledger no longer overstates compile cost
            dur = time.perf_counter() - t0
            self.compiles += 1
            self.compile_time_s += dur
            self._capture_structs(state, args, kwargs)
            if _autotune_note is not None:
                _autotune_note(self)
            if _telemetry.armed:
                _telemetry.emit("engine-compile", self.kind, "engine", t0, dur, {"donated": donated})
            if self.aot is not None:
                # cache was consulted and had nothing usable: a miss. Export
                # + persist the fresh program so the NEXT process skips this
                # compile (classified + warn-once internally, never raises)
                _progcache.note_miss()
                sig = _progcache.store_program(
                    self.kind, self.key_digest, self.plain, state, args, kwargs
                )
                if sig is not None and self.pc_sigs is not None:
                    self.pc_sigs.add(sig)
        else:
            host_dur = time.perf_counter() - t0
            self.dispatch_time_s += host_dur
            if record_span and _telemetry.armed:
                # async_host_wall: XLA dispatch is asynchronous — this span
                # ends when the runtime ACCEPTS the dispatch, not when the
                # device finishes, so it under-measures device time (the
                # probed device-dispatch spans carry the inclusive wall)
                _telemetry.emit(
                    "engine-dispatch", self.kind, "engine", t0, host_dur,
                    {"async_host_wall": True},
                )
        # sampled device-time probe (METRICS_TPU_DEVICE_PROBE_EVERY=N): every
        # Nth dispatch blocks until the device finishes and lands the
        # device-INCLUSIVE wall in the per-program device-dispatch family.
        # Compile events are skipped — their wall is trace+XLA-compile, and
        # folding it into the device plane would poison the roofline join. A
        # probed flush chunk forces the WHOLE chunk's scan program and counts
        # as ONE probe (one dispatch = one program, however many steps it
        # stacked). Disarmed (EVERY=0, the default) this is one int compare.
        every = _probe_every if _probe_every is not None else device_probe_every()
        if every and not compiled:
            n = _probe_countdown[0] + 1
            if n >= every:
                n = 0
                jax.block_until_ready(out)
                _stats["device_probes"] += 1
                _telemetry.observe_device_dispatch(
                    self.probe_key, t0, time.perf_counter() - t0
                )
            _probe_countdown[0] = n
        return out

    def __call__(self, state: Any, *args: Any, **kwargs: Any) -> Any:
        # plain twin: trace/probe-friendly (``jax.eval_shape`` over an
        # Executable exercises exactly the math the donated twin compiles).
        # No dispatch span here — __call__ is also the pack/apply programs'
        # concrete entry, whose callers time themselves; probes are already
        # excluded wholesale by the trace-state guard in _dispatch.
        return self._dispatch(self.plain, False, state, args, kwargs, record_span=False)

    def run(
        self,
        state: Any,
        args: tuple = (),
        kwargs: Optional[dict] = None,
        *,
        donate: bool = True,
        avoid_ids: Optional[frozenset] = None,
    ) -> Any:
        """Execute with in-place state: the donated twin when safe for THIS
        call's buffers, else the plain twin — same trace either way."""
        kwargs = kwargs or {}
        if donate and self.donated is not None:
            # "donation" fault site: fires where a donated execution is
            # attempted, BEFORE any buffer is consumed — an injected
            # DonationFault leaves the state intact so callers exercise
            # their eager fallback exactly as a real donation violation
            # caught pre-dispatch would
            if _faults.armed:
                _faults.maybe_fail("donation")
            if donation_supported() and state_donatable(state, avoid_ids):
                return self._dispatch(self.donated, True, state, args, kwargs)
        return self._dispatch(self.plain, False, state, args, kwargs)

    def compiled_signatures(self) -> int:
        """Number of aval signatures compiled across both twins — lets tests
        assert a second same-config instance added zero compiles."""
        count = 0
        for fn in (self.donated, self.plain):
            size = getattr(fn, "_cache_size", None)
            if callable(size):
                count += size()
        return count


_PROGRAM_CACHE: "OrderedDict[tuple, Executable]" = OrderedDict()
_CACHE_CAP = 256
_stats = {"builds": 0, "hits": 0, "device_probes": 0, "program_analyses": 0}

#: Autotuner hooks (ops/autotune.py), armed only while METRICS_TPU_AUTOTUNE
#: is on: ``_autotune_key()`` returns the selection-table digest suffix
#: appended to every acquire key (an installed winner invalidates stale
#: traces; identical tables resolve identical persistent-cache entries), and
#: ``_autotune_note(exe)`` drains trace-time variant consults into the
#: just-compiled program's ledger row. Both None when the autotuner is off —
#: one predicate each, keys and programs byte-identical to the untuned build.
_autotune_key: Optional[Callable[[], tuple]] = None
_autotune_note: Optional[Callable[[Any], None]] = None


def acquire(
    owner: Any,
    kind: str,
    build: Callable[[], Tuple[Callable, Any, Dict[str, Any]]],
    *,
    extra_key: tuple = (),
    donate: bool = True,
) -> Executable:
    """Fetch (or build once) the fused program for ``owner``'s configuration.

    ``build()`` returns ``(step_fn, template, aux)`` where ``step_fn`` takes
    the state tree as its first argument. The compiled pair is cached under
    ``(kind, fingerprint(owner), *extra_key)`` with LRU eviction, so every
    identically-configured instance — bootstrap clones, collection members,
    re-constructions — reuses one program object and its jit aval cache.
    """
    return acquire_keyed((kind, config_fingerprint(owner)) + tuple(extra_key), build, donate=donate)


def acquire_keyed(
    key: tuple,
    build: Callable[[], Tuple[Callable, Any, Dict[str, Any]]],
    *,
    donate: bool = True,
) -> Executable:
    """:func:`acquire` for callers that assemble their own cache key —
    MetricCollection keys by its members' fingerprints, the fan-out wrappers
    by wrapper + clone fingerprints."""
    if _autotune_key is not None:
        key = key + _autotune_key()
    exe = _PROGRAM_CACHE.get(key)
    if exe is not None:
        _stats["hits"] += 1
        exe.hits += 1
        _PROGRAM_CACHE.move_to_end(key)
        return exe
    # "compile" fault site: fires only on cache misses (a cache hit means no
    # compile happens), so an injected CompileFault models trace/lowering
    # failure while building a new program — callers classify and ladder down
    if _faults.armed:
        _faults.maybe_fail("compile")
    _stats["builds"] += 1
    t0 = time.perf_counter()
    step, template, aux = build()
    exe = Executable(
        jax.jit(step, donate_argnums=(0,)) if donate else None,
        jax.jit(step),
        template,
        aux,
    )
    exe.kind = str(key[0])
    exe.key_digest = hashlib.sha1(repr(key).encode()).hexdigest()[:12]
    # the per-program device-histogram identity: kind alone collides (every
    # same-kind config shares it), so the cache-key digest disambiguates
    exe.probe_key = f"{exe.kind}:{exe.key_digest[:8]}"
    if _progcache.enabled():
        # arm the persistent/AOT lane: index which signatures the on-disk
        # store already holds for this (kind, fingerprint) identity, so the
        # first dispatch of a stored signature rehydrates instead of tracing
        exe._attach_cache_lane()
    if _telemetry.armed:
        _telemetry.emit(
            "engine-build", exe.kind, "engine", t0, time.perf_counter() - t0, {"key": exe.key_digest}
        )
    _PROGRAM_CACHE[key] = exe
    while len(_PROGRAM_CACHE) > _CACHE_CAP:
        _PROGRAM_CACHE.popitem(last=False)
    return exe


def warm_programs() -> int:
    """Rehydrate every persistent-store signature for every cached program
    into its AOT lane — the rolling-restart warm-boot step: acquire your
    suite's programs (``MetricCollection.precompile`` drives the real call
    paths), then ``warm_programs()`` turns each stored signature into a
    live compiled callable before traffic or a post-``rejoin`` compute can
    stall on it. No-op (returning 0) while the persistent cache is
    disabled. Returns the number of compiled callables installed."""
    if not _progcache.enabled():
        return 0
    loaded = 0
    for exe in list(_PROGRAM_CACHE.values()):
        loaded += exe.warm_from_store()
    return loaded


def engine_stats() -> Dict[str, Any]:
    """Cache effectiveness counters: ``builds`` (distinct programs traced),
    ``hits`` (program acquisitions served from cache), ``cached`` (live),
    plus deferral counters: ``deferred_steps`` (calls that enqueued instead
    of dispatching), ``deferred_flushes`` (stacked flush dispatches),
    ``deferred_fallbacks`` (flushes that replayed eagerly) — and the
    failure-domain telemetry from :mod:`metrics_tpu.ops.faults`: per-domain
    ``fault_<domain>`` counters, ``fault_demotions`` / ``fault_promotions``
    (degradation-ladder transitions), ``fault_injected``, and the bounded
    ``failure_log`` ring buffer (newest last) — plus the sync-protocol
    telemetry from :mod:`metrics_tpu.parallel.sync`:
    ``sync_collectives_issued`` / ``sync_shape_collectives`` /
    ``sync_payload_collectives`` (protocol collective slots),
    ``sync_bytes_gathered``, ``sync_coalesce_ratio`` (states packed per
    coalesced payload), fast-lane hit/miss counts and
    ``sync_pack_fallbacks`` — and the journal counters from
    :mod:`metrics_tpu.ops.journal` (saves, loads, bytes written, generation
    demotions) and the streaming-plane counters from
    :mod:`metrics_tpu.streaming` (window closes and the payload collectives
    they issued, ring slots packed/persisted/demoted, epoch trips mid-close,
    decay ticks, drift reports) — and the tenant-arena counters from
    :mod:`metrics_tpu.arena` (``arena_*``: tenant lifecycle, vmapped
    update/compute/reset program traffic, slab-journal saves, bytes and
    demotions) — and the persistent program cache counters from
    :mod:`metrics_tpu.ops.progcache` (``progcache_*``: entry hits, misses,
    stores/bytes, classified demotions, size-cap evictions).
    ``telemetry.snapshot()`` is the superset
    surface that adds the span-recorder counters and the program-ledger
    summary on top."""
    out: Dict[str, Any] = {
        "builds": _stats["builds"],
        "hits": _stats["hits"],
        "cached": len(_PROGRAM_CACHE),
        "deferred_steps": _stats["deferred_steps"],
        "deferred_flushes": _stats["deferred_flushes"],
        "deferred_fallbacks": _stats["deferred_fallbacks"],
        "deferred_sync_barrier_flushes": _stats["deferred_sync_barrier_flushes"],
        # the performance-attribution plane: sampled block_until_ready
        # dispatches and memoized cost-analysis lowers actually performed
        "device_probes": _stats["device_probes"],
        "program_analyses": _stats["program_analyses"],
    }
    out.update(_faults.fault_stats())
    from metrics_tpu.ops import journal as _journal
    from metrics_tpu.parallel import sync as _psync

    out.update(_psync.collective_stats())
    out.update(_journal.journal_stats())
    # the streaming plane's event counters (window closes, ring slots,
    # demotions, epoch trips, decay ticks, drift reports) — lazy like the
    # journal's: streaming imports engine for its decay programs
    from metrics_tpu import streaming as _streaming

    out.update(_streaming.streaming_stats())
    # the functional core's host-visible events (export builds/hits, api
    # calls, hand-backs) — lazy: functional_core imports engine for its
    # config fingerprints
    from metrics_tpu import functional_core as _funcore

    out.update(_funcore.funcore_stats())
    # the tenant-arena plane (lifecycle, vmapped program traffic, slab
    # journal bytes and demotions) — lazy: the arena imports engine for
    # its cached programs
    from metrics_tpu import arena as _arena

    out.update(_arena.arena_stats())
    # the persistent program cache (hits/misses/stores/demotions/evictions
    # — ops/progcache.py; imported at module level, no laziness needed)
    out.update(_progcache.progcache_stats())
    # the ingestion gateway's settlement counters (offered / admitted /
    # coalesced / shed / quarantined rows, flush traffic) — lazy: the
    # gateway imports engine through the arena it routes into
    from metrics_tpu import ingest as _ingest

    out.update(_ingest.ingest_stats())
    # the kernel autotuner (sweeps, candidates, installs, disqualifications,
    # table hits, persists/restores — ops/autotune.py; a light module, but
    # lazy to keep import order acyclic with the kernel modules that
    # register variants)
    from metrics_tpu.ops import autotune as _autotune

    out.update(_autotune.autotune_stats())
    # the FID host-f64 fallback counters (image/generative.py) — guarded:
    # the image stack is heavy and only merged when already imported
    _generative = sys.modules.get("metrics_tpu.image.generative")
    if _generative is not None:
        out.update(_generative.fid_stats())
    return out


# ------------------------------------------------------------- program ledger
def _analyze(exe: Executable) -> Optional[Dict[str, Any]]:
    """XLA cost/memory analysis for one cached program, via an AOT re-lower
    of the plain twin at its last-compiled abstract signature. MEMOIZED per
    retained signature — success caches the dict, failure caches a marker —
    so repeated ``program_report(analyze=True)`` / ``perf_report()`` calls
    never re-lower (``_capture_structs`` drops both memos when a new
    signature compiles; the ``program_analyses`` counter counts the lowers
    actually performed). Any failure (no recorded signature, a backend
    without analysis support) reports None rather than raising."""
    if exe.analysis is not None:
        return exe.analysis
    if exe.arg_structs is None or exe.analysis_failed:
        return None
    _stats["program_analyses"] += 1
    try:
        state_s, args_s, kwargs_s = exe.arg_structs
        compiled = exe.plain.lower(state_s, *args_s, **kwargs_s).compile()
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else {}
        mem = compiled.memory_analysis()
        arg_b = int(getattr(mem, "argument_size_in_bytes", 0) or 0)
        out_b = int(getattr(mem, "output_size_in_bytes", 0) or 0)
        tmp_b = int(getattr(mem, "temp_size_in_bytes", 0) or 0)
        exe.analysis = {
            "flops": float(cost.get("flops", 0.0) or 0.0),
            "bytes_accessed": float(cost.get("bytes accessed", 0.0) or 0.0),
            "argument_bytes": arg_b,
            "output_bytes": out_b,
            "temp_bytes": tmp_b,
            "generated_code_bytes": int(getattr(mem, "generated_code_size_in_bytes", 0) or 0),
            # peak live footprint of one execution: arguments + outputs +
            # scratch (donation aliases args onto outputs, so this is the
            # un-donated upper bound)
            "peak_bytes": arg_b + out_b + tmp_b,
        }
    except Exception:  # noqa: BLE001 — a report must never raise
        exe.analysis_failed = True  # memoized: no re-lower per report call
        return None
    return exe.analysis


# ----------------------------------------------------------- roofline ledger
#: Utilization floor below which a probed program is considered bound by
#: dispatch/launch latency rather than by either machine roof (neither the
#: compute nor the memory roofline explains where the wall went).
_DISPATCH_BOUND_UTILIZATION = 0.05
#: Share of the device-inclusive wall the async host dispatch must reach for
#: a program to classify host-bound (the time is python/dispatch on the
#: host, not the device at all).
_HOST_BOUND_SHARE = 0.6

_roofline_peaks: Optional[Dict[str, Any]] = None


def roofline_peaks() -> Dict[str, Any]:
    """The machine roofline this process classifies against: peak FLOP/s
    (one jitted f32 matmul chain, best-of) and peak bytes/s (one jitted
    streaming add over a 32 MiB buffer), calibrated ONCE per process and
    cached (~tens of ms, paid on the first ``analyze=True`` report — never
    on a dispatch path). ``ridge_flops_per_byte`` is their quotient: the
    arithmetic intensity where the two roofs cross. ``calibrated=False``
    rows fall back to host/dispatch-only classification. Override with
    :func:`set_roofline_peaks` (pinned CI machines, known hardware specs)."""
    global _roofline_peaks
    if _roofline_peaks is not None:
        return _roofline_peaks
    peaks: Dict[str, Any] = {
        "peak_flops_per_s": 0.0,
        "peak_bytes_per_s": 0.0,
        "ridge_flops_per_byte": 0.0,
        "calibrated": False,
    }
    try:
        import jax.numpy as jnp

        n, reps = 384, 4
        a = jnp.ones((n, n), jnp.float32)
        matmul = jax.jit(lambda x: x @ x)
        jax.block_until_ready(matmul(a))
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            out = a
            for _ in range(reps):
                out = matmul(out)
            jax.block_until_ready(out)
            best = min(best, time.perf_counter() - t0)
        peak_flops = (2.0 * n * n * n * reps) / best if best > 0 else 0.0

        m = 8 * 1024 * 1024  # 32 MiB of f32: reads + writes = 64 MiB moved
        x = jnp.ones((m,), jnp.float32)
        stream = jax.jit(lambda v: v + 1.0)
        jax.block_until_ready(stream(x))
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            out = stream(x)
            jax.block_until_ready(out)
            best = min(best, time.perf_counter() - t0)
        peak_bytes = (2.0 * 4 * m) / best if best > 0 else 0.0
        if peak_flops > 0 and peak_bytes > 0:
            peaks = {
                "peak_flops_per_s": peak_flops,
                "peak_bytes_per_s": peak_bytes,
                "ridge_flops_per_byte": peak_flops / peak_bytes,
                "calibrated": True,
            }
    except Exception:  # noqa: BLE001 — a report must never raise
        pass
    _roofline_peaks = peaks
    return peaks


def set_roofline_peaks(
    flops_per_s: Optional[float] = None, bytes_per_s: Optional[float] = None
) -> None:
    """Pin the machine roofline instead of calibrating (both None drops the
    cache so the next report re-calibrates)."""
    global _roofline_peaks
    if flops_per_s is None and bytes_per_s is None:
        _roofline_peaks = None
        return
    f = float(flops_per_s or 0.0)
    b = float(bytes_per_s or 0.0)
    _roofline_peaks = {
        "peak_flops_per_s": f,
        "peak_bytes_per_s": b,
        "ridge_flops_per_byte": (f / b) if b > 0 else 0.0,
        "calibrated": bool(f > 0 and b > 0),
    }


def _roofline_row(
    analysis: Optional[Dict[str, Any]],
    device: Optional[Dict[str, Any]],
    host_mean_s: float,
    peaks: Dict[str, Any],
) -> Dict[str, Any]:
    """Join one program's XLA cost analysis with its probed device-time
    percentiles into achieved rates and a bound classification.

    Classification (documented in docs/performance.md "Where the time
    goes"): ``unprobed`` with no device samples; ``host-bound`` when the
    async host dispatch wall is ≥ ``_HOST_BOUND_SHARE`` of the
    device-inclusive p50 (the time never reaches the device); else, against
    the calibrated machine roofline, ``dispatch-bound`` when neither
    utilization clears ``_DISPATCH_BOUND_UTILIZATION`` (the wall is launch /
    roundtrip latency); else ``compute-bound`` / ``memory-bound`` by which
    utilization is higher."""
    row: Dict[str, Any] = {
        "bound": "unprobed",
        "device_p50_s": 0.0,
        "host_dispatch_mean_s": round(host_mean_s, 9),
        "host_share": 0.0,
        "achieved_flops_per_s": 0.0,
        "achieved_bytes_per_s": 0.0,
        "arithmetic_intensity": 0.0,
        "compute_utilization": 0.0,
        "memory_utilization": 0.0,
        "probes": 0,
    }
    if not device or not device.get("count"):
        return row
    p50 = float(device.get("p50_s", 0.0)) or (
        float(device.get("sum_s", 0.0)) / max(1, int(device.get("count", 0)))
    )
    if p50 <= 0:
        return row
    row["probes"] = int(device["count"])
    row["device_p50_s"] = round(p50, 9)
    flops = float((analysis or {}).get("flops", 0.0) or 0.0)
    nbytes = float((analysis or {}).get("bytes_accessed", 0.0) or 0.0)
    row["achieved_flops_per_s"] = flops / p50
    row["achieved_bytes_per_s"] = nbytes / p50
    row["arithmetic_intensity"] = (flops / nbytes) if nbytes > 0 else 0.0
    host_share = min(1.0, host_mean_s / p50) if host_mean_s > 0 else 0.0
    row["host_share"] = round(host_share, 4)
    if host_share >= _HOST_BOUND_SHARE:
        row["bound"] = "host-bound"
        return row
    if peaks.get("calibrated"):
        u_c = row["achieved_flops_per_s"] / peaks["peak_flops_per_s"]
        u_m = row["achieved_bytes_per_s"] / peaks["peak_bytes_per_s"]
        row["compute_utilization"] = round(u_c, 6)
        row["memory_utilization"] = round(u_m, 6)
        if max(u_c, u_m) < _DISPATCH_BOUND_UTILIZATION:
            row["bound"] = "dispatch-bound"
        elif u_c >= u_m:
            row["bound"] = "compute-bound"
        else:
            row["bound"] = "memory-bound"
    else:
        # no machine roofline (calibration failed): fall back to the only
        # evidence left — a program with no analyzed work is dispatch-bound,
        # one-sided analysis decides directly, and a mixed program compares
        # its arithmetic intensity against a generic ~4 flops/byte ridge
        # (an uncalibrated peaks dict carries ridge 0.0, which must not win)
        if flops == 0 and nbytes == 0:
            row["bound"] = "dispatch-bound"
        elif nbytes == 0:
            row["bound"] = "compute-bound"
        elif flops == 0:
            row["bound"] = "memory-bound"
        else:
            ridge = peaks.get("ridge_flops_per_byte") or 4.0
            row["bound"] = (
                "compute-bound" if row["arithmetic_intensity"] >= ridge else "memory-bound"
            )
    return row


def program_report(analyze: bool = True) -> List[Dict[str, Any]]:
    """The program ledger: one row per cached executable — kind, cache-key
    digest, acquisition ``hits``, ``donated_runs`` / ``plain_runs``, compile
    events and their total wall seconds, compiled aval signatures, the
    accumulated async host dispatch wall, and the probed device-time block
    (``device``: count + percentiles from the ``device-dispatch:<program>``
    histogram family, when probes are armed). With ``analyze=True`` each row
    also carries the XLA ``cost_analysis`` / ``memory_analysis`` facts
    (FLOPs, bytes accessed, argument/output/temp bytes, peak live footprint
    — memoized per retained signature, see :func:`_analyze`) and the
    ``roofline`` join: achieved FLOP/s, achieved bytes/s, arithmetic
    intensity and a bound classification (compute- / memory- / dispatch- /
    host-bound) against the calibrated machine peaks
    (:func:`roofline_peaks`). Pass ``analyze=False`` for a counters-only
    report with zero compile/calibration cost. Joined into
    :func:`metrics_tpu.ops.telemetry.export_trace` under ``programLedger``."""
    device_stats = _telemetry.device_dispatch_stats()
    peaks = roofline_peaks() if (analyze and device_stats) else {
        "calibrated": False, "ridge_flops_per_byte": 0.0,
    }
    rows: List[Dict[str, Any]] = []
    for exe in _PROGRAM_CACHE.values():
        runs = exe.donated_runs + exe.plain_runs
        device = device_stats.get(exe.probe_key)
        row: Dict[str, Any] = {
            "kind": exe.kind,
            "key": exe.key_digest,
            "program": exe.probe_key,
            "hits": exe.hits,
            "donated_runs": exe.donated_runs,
            "plain_runs": exe.plain_runs,
            "compiles": exe.compiles,
            "compile_time_s": round(exe.compile_time_s, 6),
            # the warmed-boot attribution split: persistent-tier rehydration
            # wall lands here, never in compile_time_s
            "cache_load_time_s": round(exe.cache_load_time_s, 6),
            "cache_source": exe.cache_source,
            "compiled_signatures": exe.compiled_signatures(),
            "dispatch_time_s": round(exe.dispatch_time_s, 6),
            "device": device,
            # the autotuner's column: which kernel variants this program
            # baked at trace time (None for untuned programs)
            "variant": exe.variant,
        }
        analysis = _analyze(exe) if analyze else None
        row["analysis"] = analysis
        if analyze:
            # dispatch_time_s accumulates only on non-compile dispatches, so
            # the mean must divide by the same population (a compile run in
            # the denominator would dilute host_share and skew the bound)
            dispatch_runs = max(0, runs - exe.compiles)
            host_mean = exe.dispatch_time_s / dispatch_runs if dispatch_runs else 0.0
            row["roofline"] = _roofline_row(analysis, device, host_mean, peaks)
        rows.append(row)
    rows.sort(key=lambda r: r["compile_time_s"], reverse=True)
    return rows


def program_summary() -> Dict[str, Any]:
    """Ledger totals (the ``programs`` block of ``telemetry.snapshot()``):
    cached program count, compile events and wall seconds, acquisition hits
    and donated/plain run tallies — no per-program analysis (that is
    :func:`program_report`)."""
    out = {
        "count": len(_PROGRAM_CACHE),
        "compiles": 0,
        "compile_time_s": 0.0,
        "cache_load_time_s": 0.0,
        "hits": 0,
        "donated_runs": 0,
        "plain_runs": 0,
    }
    for exe in _PROGRAM_CACHE.values():
        out["compiles"] += exe.compiles
        out["compile_time_s"] += exe.compile_time_s
        out["cache_load_time_s"] += exe.cache_load_time_s
        out["hits"] += exe.hits
        out["donated_runs"] += exe.donated_runs
        out["plain_runs"] += exe.plain_runs
    out["compile_time_s"] = round(out["compile_time_s"], 6)
    out["cache_load_time_s"] = round(out["cache_load_time_s"], 6)
    return out


def export_trace(path: str) -> int:
    """Write the recorded telemetry spans (plus the program ledger and the
    numeric snapshot) as Chrome-trace/Perfetto JSON — see
    :func:`metrics_tpu.ops.telemetry.export_trace`. Returns the number of
    span events written."""
    return _telemetry.export_trace(path)


def _zero_engine_counters() -> None:
    _stats["builds"] = 0
    _stats["hits"] = 0
    _stats["deferred_steps"] = 0
    _stats["deferred_flushes"] = 0
    _stats["deferred_fallbacks"] = 0
    _stats["deferred_sync_barrier_flushes"] = 0
    _stats["device_probes"] = 0
    _stats["program_analyses"] = 0


_telemetry.register_reset("engine", _zero_engine_counters)


def reset_stats(reset_warnings: bool = False) -> None:
    """Zero every counter :func:`engine_stats` reports — cache, deferral,
    fault, sync-protocol and journal telemetry, the failure log AND the
    telemetry span ring — WITHOUT dropping any cached program, manifest, or
    per-owner ladder state. One registry walk
    (:func:`metrics_tpu.ops.telemetry.reset_all`): every counter-owning
    module registers its zeroing callback at import, so no per-module reset
    can drift out of this set again.

    The companion tests (and operators diffing counter windows) need:
    ``reset_engine`` throws away compiled executables to get clean counters,
    which both recompiles everything and perturbs the behavior under test.
    ``reset_stats`` isolates a counter delta in-place. The monotonic
    failure-log ``step`` index is deliberately NOT reset (monotonicity is
    what lets ``sync_health()`` order events across windows). Per-program
    ledger tallies live with the cached programs and survive likewise.

    ``reset_warnings=True`` additionally clears the ``faults.warn_fault``
    once-per-owner dedupe markers — the explicit opt-in chaos/CI sweeps use
    to re-observe warnings deterministically; the default preserves the
    warn-once lifetime exactly."""
    # import for registration side effects: every counter-owning module must
    # be on the registry before the walk (unimported == nothing to reset)
    from metrics_tpu.ops import journal as _journal  # noqa: F401
    from metrics_tpu.parallel import sync as _psync  # noqa: F401

    _telemetry.reset_all(reset_warnings=reset_warnings)


def reset_engine() -> None:
    """Drop every cached program and zero the counters (tests; and the escape
    hatch after a backend restart invalidates compiled executables)."""
    _PROGRAM_CACHE.clear()
    reset_stats()
    from metrics_tpu.parallel import bucketing as _bucketing

    _bucketing._MANIFEST_CACHE.clear()


def flush_barrier(owners) -> int:
    """Order every owner's pending deferred work before a cross-owner
    observation — the seam the coalesced sync pack (and the async
    dispatch/force split) rides: a pending queue's stacked flush MUST land
    before the pack reads state attrs (while a queue is pending, state access
    routes through the owner's barrier), and again before an async force
    applies merged rows on top (tail updates enqueued during the overlap
    window materialize first, then restore through the force's pre-apply
    snapshot). Flushes each distinct pending queue exactly once even when
    owners share one, then folds any host-side pending buffers. Returns the
    number of queues flushed (counted in ``deferred_sync_barrier_flushes``)."""
    seen = set()
    flushed = 0
    for owner in owners:
        q = owner.__dict__.get("_defer_pending")
        if q is not None and id(q) not in seen:
            seen.add(id(q))
            flushed += 1
        # ONE protocol, owned by the metric: whatever the per-owner barrier
        # grows (a new pending lane, another host hook) this seam inherits
        owner._defer_barrier()
    if flushed:
        _stats["deferred_sync_barrier_flushes"] += flushed
    return flushed


# ----------------------------------------------- deferred micro-batched dispatch
_stats.update(
    {
        "deferred_steps": 0,
        "deferred_flushes": 0,
        "deferred_fallbacks": 0,
        "deferred_sync_barrier_flushes": 0,
    }
)

_defer_enabled: Optional[bool] = None  # resolved lazily from METRICS_TPU_DEFER
_defer_max_pending: Optional[int] = None
_defer_max_age_s: Optional[float] = None


def defer_enabled() -> bool:
    """Whether eligible eager calls enqueue into a pending queue instead of
    dispatching one program per call. On by default; ``METRICS_TPU_DEFER=0``
    (or :func:`set_deferred_dispatch`) restores per-call dispatch."""
    global _defer_enabled
    if _defer_enabled is None:
        _defer_enabled = os.environ.get("METRICS_TPU_DEFER", "1") not in ("0", "false", "off")
    return _defer_enabled


def defer_max_pending() -> int:
    """Queue size that triggers an automatic flush (``METRICS_TPU_DEFER_MAX``,
    default 128 — at the measured ~0.5 ms/program backend round trip this
    amortizes the dispatch to ~4 µs/step, two orders below the eager floor)."""
    global _defer_max_pending
    if _defer_max_pending is None:
        try:
            _defer_max_pending = max(1, int(os.environ.get("METRICS_TPU_DEFER_MAX", "128")))
        except ValueError:
            _defer_max_pending = 128
    return _defer_max_pending


def defer_max_age_s() -> float:
    """Queue age that triggers a flush on the NEXT enqueue
    (``METRICS_TPU_DEFER_AGE_MS``, default 250 ms). Bounds staleness in slow
    loops; there is no background thread — age is only checked at call time,
    and observation flushes regardless."""
    global _defer_max_age_s
    if _defer_max_age_s is None:
        try:
            _defer_max_age_s = max(0.0, float(os.environ.get("METRICS_TPU_DEFER_AGE_MS", "250"))) / 1000.0
        except ValueError:
            _defer_max_age_s = 0.25
    return _defer_max_age_s


def set_deferred_dispatch(
    enabled: Optional[bool] = None,
    *,
    max_pending: Optional[int] = None,
    max_age_ms: Optional[float] = None,
) -> None:
    """Override the deferral policy at runtime (None leaves a knob unchanged;
    takes precedence over the environment variables). Live queues are not
    flushed here — disabling only stops NEW enqueues; pending work still
    flushes at its owners' next observation."""
    global _defer_enabled, _defer_max_pending, _defer_max_age_s
    if enabled is not None:
        _defer_enabled = bool(enabled)
    if max_pending is not None:
        _defer_max_pending = max(1, int(max_pending))
    if max_age_ms is not None:
        _defer_max_age_s = max(0.0, float(max_age_ms)) / 1000.0


def pow2_chunks(n: int) -> List[int]:
    """Order-preserving power-of-two bucket lengths covering ``n`` steps
    (23 → [16, 8 is too big → 4, 2, 1]): every flush chunk has a bucketed
    length, so the scan programs compile at most ~log2(max_pending) shapes
    per signature however raggedly observations land mid-queue."""
    out = []
    while n:
        c = 1 << (n.bit_length() - 1)
        out.append(c)
        n -= c
    return out


class PendingQueue:
    """A per-owner queue of deferred same-signature calls.

    ``entries`` holds the raw ``(args, kwargs)`` of each enqueued call in
    order; ``handles`` the :class:`LazyValue` issued for each forward entry
    (None for bare updates). ``backing`` maps ``id(owner) -> {state_name:
    value}`` — the state attributes popped out of each owner's ``__dict__``
    while the queue is pending, which is what makes ANY state access land in
    ``__getattr__`` and flush. ``flush_fn(queue)`` is installed by the owner
    (metric or collection) and must restore/replace the backing state and
    clear every owner's pending marker before returning.
    """

    __slots__ = (
        "kind",
        "signature",
        "entries",
        "handles",
        "backing",
        "owners",
        "flush_fn",
        "created",
        "meta",
        "_flushing",
    )

    def __init__(self, kind: str, signature: Any, flush_fn: Callable[["PendingQueue"], None]):
        self.kind = kind
        self.signature = signature
        self.entries: list = []
        self.handles: list = []
        self.backing: Dict[int, Dict[str, Any]] = {}
        self.owners: list = []
        self.flush_fn = flush_fn
        self.created = time.monotonic()
        self.meta: Any = None  # creator-owned context (e.g. a collection's member list)
        self._flushing = False

    def adopt(self, owner: Any, state_names: Any) -> None:
        """Pop ``owner``'s state attributes into the backing store and mark
        the owner pending (its ``__getattr__`` barrier now routes here)."""
        d = owner.__dict__
        taken = {}
        for name in state_names:
            if name in d:
                taken[name] = d.pop(name)
        self.backing[id(owner)] = taken
        self.owners.append(owner)
        object.__setattr__(owner, "_defer_pending", self)

    def has_state(self, owner: Any, name: str) -> bool:
        b = self.backing.get(id(owner))
        return b is not None and name in b

    def matches(self, kind: str, signature: Any) -> bool:
        return self.kind == kind and self.signature == signature

    def should_flush(self) -> bool:
        return len(self.entries) >= defer_max_pending() or (
            time.monotonic() - self.created
        ) > defer_max_age_s()

    def release(self) -> None:
        """Restore backing state attrs and clear pending markers WITHOUT
        running the queued work (flush implementations call this first, then
        write the post-flush state over the restored attrs)."""
        for owner in self.owners:
            taken = self.backing.pop(id(owner), None)
            if taken:
                for name, value in taken.items():
                    object.__setattr__(owner, name, value)
            if owner.__dict__.get("_defer_pending") is self:
                object.__setattr__(owner, "_defer_pending", None)
        self.owners = []

    def flush(self) -> None:
        """Run the queued calls as stacked scan program(s). Reentrancy-safe:
        a flush triggered from inside a flush (template construction
        deep-copies the owner, whose ``__getstate__`` barrier fires) is a
        no-op, as is flushing an already-drained queue."""
        if self._flushing:
            return
        fn = self.flush_fn
        if fn is None:
            return
        self._flushing = True
        self.flush_fn = None
        # flush span: capture the label facts BEFORE fn runs (the flush
        # implementation releases the owners and may drain the entries)
        t0 = 0.0
        if _telemetry.armed:
            t0 = time.perf_counter()
            owner_label = type(self.owners[0]).__name__ if self.owners else None
            n_entries = len(self.entries)
        try:
            fn(self)
        finally:
            self._flushing = False
            self.release()  # no-op if the flush implementation already did
            if t0 and _telemetry.armed:
                _telemetry.emit(
                    "engine-flush",
                    owner_label,
                    "defer",
                    t0,
                    time.perf_counter() - t0,
                    {"kind": self.kind, "entries": n_entries},
                )


class LazyValue:
    """Deferred ``forward`` batch value: a transparent proxy that forces its
    owner queue's flush on first read.

    Reading means any materialization — ``float()``, ``np.asarray``,
    ``jnp.asarray`` (via ``__jax_array__``), arithmetic, comparison,
    indexing, attribute access (``.shape``, ``.dtype``, ``.mean()``, …) all
    delegate to the forced value. Until then the handle is inert and the
    enqueued step costs no dispatch. Like the arrays it stands in for, a
    handle is unhashable (``==`` is elementwise).
    """

    __slots__ = ("_queue", "_chunk", "_chunk_index", "_value", "_ready")

    def __init__(self, queue: Optional[PendingQueue]):
        self._queue = queue
        self._chunk = None
        self._chunk_index = 0
        self._value = None
        self._ready = False

    # -- resolution (called by the flush implementations) ------------------
    def _set_value(self, value: Any) -> None:
        self._value = value
        self._chunk = None
        self._ready = True
        self._queue = None

    def _set_chunk(self, chunk_values: Any, index: int) -> None:
        # lazy per-step slice: only handles that are actually read pay the
        # (async) gather for their step out of the stacked chunk values
        self._chunk = chunk_values
        self._chunk_index = index
        self._ready = True
        self._queue = None

    def _force(self) -> Any:
        if not self._ready:
            q = self._queue
            if q is not None:
                q.flush()
            if not self._ready:
                raise RuntimeError(
                    "deferred forward value was never resolved (its metric's queue "
                    "was dropped without a flush — e.g. the instance was reset "
                    "through a path that bypassed the observation barrier)"
                )
        if self._chunk is not None:
            i = self._chunk_index
            self._value = jax.tree.map(lambda v: v[i], self._chunk)
            self._chunk = None
        return self._value

    # -- copy / pickle ------------------------------------------------------
    def __reduce__(self):
        # Copying or pickling a handle is an OBSERVATION: force the flush and
        # serialize the resolved value, so the copy never carries a queue
        # binding (a deep-copied queue would point at cloned owners whose
        # ids are absent from the id-keyed backing — reading such a copy
        # raised an opaque KeyError). One exception: a copy taken MID-FLUSH
        # (template construction deep-copies the owner, whose _forward_cache
        # still holds this unresolved handle) cannot force — the reentrancy
        # guard makes the nested flush a no-op — so it serializes a
        # detached stub; templates reset immediately, so that copy's value
        # is never observed, and reading it anyway raises the clear
        # "never resolved" error instead of a KeyError.
        q = self._queue
        if not self._ready and q is not None and q._flushing:
            return (LazyValue, (None,))
        return (_resolved_lazy_value, (self._force(),))

    # -- transparent delegation -------------------------------------------
    def __getattr__(self, name: str) -> Any:
        if name.startswith("__") and name.endswith("__"):
            raise AttributeError(name)
        return getattr(self._force(), name)

    def __jax_array__(self) -> jax.Array:
        import jax.numpy as jnp

        return jnp.asarray(self._force())

    def __array__(self, dtype=None, copy=None):
        arr = np.asarray(self._force())
        return arr.astype(dtype) if dtype is not None else arr

    def __float__(self):
        return float(self._force())

    def __int__(self):
        return int(self._force())

    def __bool__(self):
        return bool(self._force())

    def __index__(self):
        return self._force().__index__()

    def __len__(self):
        return len(self._force())

    def __iter__(self):
        return iter(self._force())

    def __getitem__(self, item):
        return self._force()[item]

    def __repr__(self):
        return repr(self._force())

    def __format__(self, spec):
        return format(self._force(), spec)

    __hash__ = None  # elementwise __eq__, like the arrays this stands in for

    def __eq__(self, other):
        return self._force() == other

    def __ne__(self, other):
        return self._force() != other

    def __lt__(self, other):
        return self._force() < other

    def __le__(self, other):
        return self._force() <= other

    def __gt__(self, other):
        return self._force() > other

    def __ge__(self, other):
        return self._force() >= other

    def __add__(self, other):
        return self._force() + other

    def __radd__(self, other):
        return other + self._force()

    def __sub__(self, other):
        return self._force() - other

    def __rsub__(self, other):
        return other - self._force()

    def __mul__(self, other):
        return self._force() * other

    def __rmul__(self, other):
        return other * self._force()

    def __truediv__(self, other):
        return self._force() / other

    def __rtruediv__(self, other):
        return other / self._force()

    def __floordiv__(self, other):
        return self._force() // other

    def __rfloordiv__(self, other):
        return other // self._force()

    def __mod__(self, other):
        return self._force() % other

    def __rmod__(self, other):
        return other % self._force()

    def __pow__(self, other):
        return self._force() ** other

    def __rpow__(self, other):
        return other ** self._force()

    def __matmul__(self, other):
        return self._force() @ other

    def __rmatmul__(self, other):
        return other @ self._force()

    def __neg__(self):
        return -self._force()

    def __pos__(self):
        return +self._force()

    def __abs__(self):
        return abs(self._force())


def _resolved_lazy_value(value: Any) -> "LazyValue":
    """Reconstructor for copied/pickled handles: a detached, already-resolved
    LazyValue (module-level so pickle can find it by qualified name)."""
    lv = LazyValue(None)
    lv._set_value(value)
    return lv


def note_deferred_steps(n: int) -> None:
    _stats["deferred_steps"] += n
    # hot deferred loop: one instant span per enqueue when armed (a single
    # predicate + tuple append; the telemetry_overhead bench row pins it)
    if _telemetry.armed:
        _telemetry.emit("engine-enqueue", None, "defer")


def note_deferred_flush(fallback: bool = False) -> None:
    _stats["deferred_flushes"] += 1
    if fallback:
        _stats["deferred_fallbacks"] += 1


def stack_entries(entries: List[tuple], start: int, length: int) -> Tuple[tuple, dict]:
    """Stack ``length`` consecutive queued ``(args, kwargs)`` calls into one
    chunk with a leading steps axis on every array leaf.

    Same-signature entries share a tree structure; array leaves (device or
    host-staged numpy — including 0-d scalars, which become ``(k,)`` traced
    operands) stack along a new axis 0, python leaves pass through from the
    first entry (signature equality keys python leaves by repr, so they are
    per-chunk constants). Host leaves transfer once per chunk here instead
    of once per call.
    """
    import jax.numpy as jnp

    chunk = entries[start : start + length]
    leaves0, treedef = jax.tree.flatten(chunk[0])
    if length == 1:
        cols = [(leaf,) for leaf in leaves0]
    else:
        cols = list(zip(*(jax.tree.flatten(e)[0] for e in chunk)))
    # python scalar leaves stay STATIC constants (signature equality keys
    # them by repr, so they are identical across the chunk) — stacking them
    # would turn trace-time branches on their values into tracer errors
    stacked = [jnp.stack(col) if hasattr(col[0], "shape") else col[0] for col in cols]
    return jax.tree.unflatten(treedef, stacked)
