"""COCO-style Mean Average Precision / Recall.

Parity: reference `detection/mean_ap.py:185-933` (itself a faithful
re-implementation of pycocotools evaluation): per-(image, class) IoU, greedy
score-sorted GT matching per IoU threshold, 101-point precision
interpolation, and mAP/mAR summaries over IoU .5:.95, area ranges
small/medium/large and max-detection thresholds 1/10/100.

TPU-first split:

- the FLOP-carrying part — pairwise IoU over the (det, gt) grid and dense
  boolean-mask IoU (one MXU matmul over flattened masks) — runs on device via
  :mod:`metrics_tpu.functional.detection.box_ops`; masks never round-trip
  through pycocotools RLE (`mean_ap.py:127-143`) because RLE is an I/O codec,
  not compute;
- the greedy matching and interpolation bookkeeping is tiny, shape-dynamic,
  sequential state-machine work (each detection claims the best unmatched
  GT), so it stays host-side numpy exactly like the reference's python loops
  (`mean_ap.py:543-670`), vectorized where the reference iterates (the
  zigzag-removal ``while`` loop at `mean_ap.py:854-858` becomes one reversed
  running max).
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from metrics_tpu.functional.detection.box_ops import box_convert, box_iou, mask_iou
from metrics_tpu.metric import Metric
from metrics_tpu.ops import autotune as _autotune


def _box_convert_np(boxes: np.ndarray, in_fmt: str, out_fmt: str = "xyxy") -> np.ndarray:
    """Host-side box format conversion (update appends to host lists; a device
    round trip per image would dominate on remote backends). Same conventions
    as the device kernel `functional/detection/box_ops.box_convert`."""
    if in_fmt == out_fmt:
        return boxes
    if out_fmt != "xyxy":
        raise ValueError(f"Unsupported host conversion {in_fmt}->{out_fmt}")
    if in_fmt == "xywh":
        x, y, w, h = boxes.T
        return np.stack([x, y, x + w, y + h], axis=-1)
    if in_fmt == "cxcywh":
        cx, cy, w, h = boxes.T
        return np.stack([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2], axis=-1)
    raise ValueError(f"Unsupported host conversion {in_fmt}->{out_fmt}")


def _box_iou_np(det: np.ndarray, gt: np.ndarray) -> np.ndarray:
    """Host mirror of the device `box_iou` — same float32 arithmetic, same
    (unguarded) inter/union division, so the host/device cutoff can never
    change the metric's value."""
    det = det.astype(np.float32)
    gt = gt.astype(np.float32)
    area_d = (det[:, 2] - det[:, 0]) * (det[:, 3] - det[:, 1])
    area_g = (gt[:, 2] - gt[:, 0]) * (gt[:, 3] - gt[:, 1])
    lt = np.maximum(det[:, None, :2], gt[None, :, :2])
    rb = np.minimum(det[:, None, 2:], gt[None, :, 2:])
    wh = np.clip(rb - lt, 0, None)
    inter = wh[..., 0] * wh[..., 1]
    union = area_d[:, None] + area_g[None, :] - inter
    with np.errstate(divide="ignore", invalid="ignore"):
        return inter / union


def _pow2_bucket(n: int) -> int:
    """Next power-of-two padding size (floor 8) — the device IoU kernels
    compile O(log^2) distinct shapes instead of one per ragged (nd, ng)."""
    return max(8, 1 << (int(n) - 1).bit_length())


def _box_iou_device_blocked(det: Any, gt: Any) -> jax.Array:
    """Blocked on-device alternative to the `_box_iou_np` host mirror: pad
    both operands to their power-of-two bucket, run the device `box_iou`,
    slice the live corner back out. Same f32 arithmetic as the host mirror
    (padding rows never survive the slice); the 1e-5 tolerance covers
    contraction-order drift only. Whether eating a device round-trip per
    small (image, class) cell beats host numpy is exactly what the sweep
    measures per shape class."""
    det = jnp.asarray(det, jnp.float32)
    gt = jnp.asarray(gt, jnp.float32)
    nd, ng = det.shape[0], gt.shape[0]
    det_p = jnp.pad(det, ((0, _pow2_bucket(nd) - nd), (0, 0)))
    gt_p = jnp.pad(gt, ((0, _pow2_bucket(ng) - ng), (0, 0)))
    return box_iou(det_p, gt_p)[:nd, :ng]


# The host mirror is the reference (host=True: timed eagerly, no jit) — it is
# today's small-work serving path, so the floor IS the current behavior.
_autotune.register_variant("map_box_iou", "host_numpy", _box_iou_np, reference=True, host=True)
_autotune.register_variant("map_box_iou", "device_blocked", _box_iou_device_blocked, tolerance=1e-5)


def _mask_iou_np(det: np.ndarray, gt: np.ndarray) -> np.ndarray:
    """Host mirror of the device `mask_iou` — float32 matmul, union>0 guard."""
    d = det.reshape(det.shape[0], -1).astype(np.float32)
    g = gt.reshape(gt.shape[0], -1).astype(np.float32)
    inter = d @ g.T
    union = d.sum(1)[:, None] + g.sum(1)[None, :] - inter
    return np.where(union > 0, inter / np.where(union > 0, union, 1.0), 0.0)


def _input_validator(preds: Sequence[dict], targets: Sequence[dict], iou_type: str = "bbox") -> None:
    """Validate the list-of-dict input format (reference `mean_ap.py:134-176`)."""
    if not isinstance(preds, Sequence):
        raise ValueError("Expected argument `preds` to be of type Sequence")
    if not isinstance(targets, Sequence):
        raise ValueError("Expected argument `target` to be of type Sequence")
    if len(preds) != len(targets):
        raise ValueError("Expected argument `preds` and `target` to have the same length")
    iou_attribute = "boxes" if iou_type == "bbox" else "masks"

    for k in [iou_attribute, "scores", "labels"]:
        if any(k not in p for p in preds):
            raise ValueError(f"Expected all dicts in `preds` to contain the `{k}` key")
    for k in [iou_attribute, "labels"]:
        if any(k not in p for p in targets):
            raise ValueError(f"Expected all dicts in `target` to contain the `{k}` key")

    def _n_items(value: Any) -> int:
        # masks may arrive as an RLE dict / list of RLE dicts (decoded later)
        if isinstance(value, dict):
            return 1
        if isinstance(value, (list, tuple)) and value and isinstance(value[0], dict):
            return len(value)
        # shape is metadata — works for device arrays WITHOUT a host transfer
        shape = getattr(value, "shape", None)
        if shape is None:
            shape = np.asarray(value).shape
        return shape[0] if len(shape) and int(np.prod(shape)) else 0

    for i, item in enumerate(targets):
        n_boxes = _n_items(item[iou_attribute])
        n_labels = _n_items(item["labels"])
        if n_boxes != n_labels:
            raise ValueError(
                f"Input {iou_attribute} and labels of sample {i} in targets have a"
                f" different length (expected {n_boxes} labels, got {n_labels})"
            )
    for i, item in enumerate(preds):
        n_boxes = _n_items(item[iou_attribute])
        n_labels = _n_items(item["labels"])
        n_scores = _n_items(item["scores"])
        if not (n_boxes == n_labels == n_scores):
            raise ValueError(
                f"Input {iou_attribute}, labels and scores of sample {i} in predictions have a"
                f" different length (expected {n_boxes} labels and scores,"
                f" got {n_labels} labels and {n_scores} scores)"
            )


class MeanAveragePrecision(Metric):
    """COCO mAP/mAR over accumulated detections.

    Boxes are expected in absolute image coordinates; ``box_format`` selects
    xyxy/xywh/cxcywh input. With ``iou_type="segm"``, per-instance boolean
    masks of shape ``[num_boxes, H, W]`` are evaluated with dense mask IoU.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.detection import MeanAveragePrecision
        >>> preds = [dict(
        ...     boxes=jnp.asarray([[258.0, 41.0, 606.0, 285.0]]),
        ...     scores=jnp.asarray([0.536]),
        ...     labels=jnp.asarray([0]))]
        >>> target = [dict(
        ...     boxes=jnp.asarray([[214.0, 41.0, 562.0, 285.0]]),
        ...     labels=jnp.asarray([0]))]
        >>> metric = MeanAveragePrecision()
        >>> metric.update(preds, target)
        >>> result = metric.compute()
        >>> round(float(result["map"]), 4), round(float(result["map_50"]), 4)
        (0.6, 1.0)
    """

    is_differentiable = False
    higher_is_better = None
    full_state_update = True

    def __init__(
        self,
        box_format: str = "xyxy",
        iou_type: str = "bbox",
        iou_thresholds: Optional[List[float]] = None,
        rec_thresholds: Optional[List[float]] = None,
        max_detection_thresholds: Optional[List[int]] = None,
        class_metrics: bool = False,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)

        allowed_box_formats = ("xyxy", "xywh", "cxcywh")
        allowed_iou_types = ("segm", "bbox")
        if box_format not in allowed_box_formats:
            raise ValueError(f"Expected argument `box_format` to be one of {allowed_box_formats} but got {box_format}")
        self.box_format = box_format
        self.iou_thresholds = list(iou_thresholds or np.linspace(0.5, 0.95, round((0.95 - 0.5) / 0.05) + 1))
        self.rec_thresholds = list(rec_thresholds or np.linspace(0.0, 1.00, round(1.00 / 0.01) + 1))
        self.max_detection_thresholds = sorted(max_detection_thresholds or [1, 10, 100])
        if iou_type not in allowed_iou_types:
            raise ValueError(f"Expected argument `iou_type` to be one of {allowed_iou_types} but got {iou_type}")
        self.iou_type = iou_type
        # float bounds: the 1e10 "unbounded" sentinel overflows int32 when a
        # comparison against a jax array coerces it to the array's weak type
        self.bbox_area_ranges = {
            "all": (0.0, float(1e5**2)),
            "small": (0.0, float(32**2)),
            "medium": (float(32**2), float(96**2)),
            "large": (float(96**2), float(1e5**2)),
        }
        if not isinstance(class_metrics, bool):
            raise ValueError("Expected argument `class_metrics` to be a boolean")
        self.class_metrics = class_metrics

        self.add_state("detections", default=[], dist_reduce_fx=None)
        self.add_state("detection_scores", default=[], dist_reduce_fx=None)
        self.add_state("detection_labels", default=[], dist_reduce_fx=None)
        self.add_state("groundtruths", default=[], dist_reduce_fx=None)
        self.add_state("groundtruth_labels", default=[], dist_reduce_fx=None)

    # ------------------------------------------------------------- update
    def update(self, preds: List[Dict[str, jax.Array]], target: List[Dict[str, jax.Array]]) -> None:
        """Append per-image detection/groundtruth dicts (reference `mean_ap.py:333-393`).

        Zero-sync hot path: validation reads only shape metadata, and
        device-array leaves are appended AS-IS (async — no blocking
        device→host fetch). All pending leaves are fetched in one fused
        transfer per dtype when ``compute()`` materializes the states; on
        remote/tunneled backends a per-update blocking fetch costs a full
        network round trip, which at COCO scale dominates everything else.
        """
        _input_validator(preds, target, iou_type=self.iou_type)

        for item in preds:
            self.detections.append(self._raw_or_safe_item(item))
            self.detection_labels.append(self._raw_or_host(item["labels"]))
            self.detection_scores.append(self._raw_or_host(item["scores"], np.float32))
        for item in target:
            self.groundtruths.append(self._raw_or_safe_item(item))
            self.groundtruth_labels.append(self._raw_or_host(item["labels"]))

    @staticmethod
    def _raw_or_host(value: Any, dtype: Optional[np.dtype] = None) -> Any:
        if isinstance(value, jax.Array):
            return value  # raw — zero device ops here; normalized at materialize
        out = np.asarray(value).reshape(-1)
        return out.astype(dtype) if dtype is not None else out

    def _raw_or_safe_item(self, item: Dict[str, Any]) -> Any:
        key = "boxes" if self.iou_type == "bbox" else "masks"
        value = item[key]
        if isinstance(value, jax.Array):
            # box format conversion happens HERE for device inputs too (async
            # device kernel — no blocking fetch): it is the one non-idempotent
            # normalization step, and materialize must stay idempotent because
            # base-class machinery (sync gather, astype, state_dict round
            # trips) can re-wrap already-normalized host entries as jax arrays
            if self.iou_type == "bbox" and self.box_format != "xyxy" and value.size:
                value = box_convert(value.reshape(-1, 4), in_fmt=self.box_format, out_fmt="xyxy")
            return value
        return self._get_safe_item_values(item)

    def _materialize_states(self) -> None:
        """Fetch every pending device-array leaf to host (all transfers in
        flight at once), then normalize EVERY entry. Normalization here is
        strictly idempotent (reshape + dtype casts — box format conversion
        already happened at update time), so entries that base-class machinery
        converted between numpy and jax (compute_on_cpu hook, sync gather,
        astype, checkpoint round trips) stay correct either way."""
        state_lists = (
            self.detections,
            self.detection_scores,
            self.detection_labels,
            self.groundtruths,
            self.groundtruth_labels,
        )
        normalizers = {
            id(self.detections): self._normalize_item,
            id(self.groundtruths): self._normalize_item,
            id(self.detection_scores): lambda v: v.reshape(-1).astype(np.float32),
            id(self.detection_labels): lambda v: v.reshape(-1),
            id(self.groundtruth_labels): lambda v: v.reshape(-1),
        }
        # Two passes: start EVERY device→host copy asynchronously (transfers
        # overlap in flight — no per-leaf latency wait, no device ops, no
        # compiles), then drain and normalize. Ragged per-image shapes make
        # any concat-then-fetch scheme recompile per shape combination, which
        # costs far more than the transfers themselves.
        pending: List[Tuple[list, int]] = [
            (lst, i)
            for lst in state_lists
            for i, value in enumerate(lst)
            if isinstance(value, jax.Array)
        ]
        for lst, i in pending:
            try:
                lst[i].copy_to_host_async()
            except AttributeError:  # pragma: no cover - older jax array types
                pass
        for lst in state_lists:
            normalize = normalizers[id(lst)]
            for i, value in enumerate(lst):
                lst[i] = normalize(np.asarray(value))

    def _normalize_item(self, value: np.ndarray) -> np.ndarray:
        # idempotent by construction: reshape + dtype only (box format was
        # converted exactly once at update time, on whichever side the input
        # arrived)
        if self.iou_type != "bbox":
            from metrics_tpu.functional.detection.rle import masks_from_any

            return masks_from_any(value)
        return value.reshape(-1, 4).astype(np.float32) if value.size else np.zeros((0, 4), np.float32)

    def _get_safe_item_values(self, item: Dict[str, Any]) -> np.ndarray:
        if self.iou_type == "bbox":
            boxes = np.asarray(item["boxes"], dtype=np.float32).reshape(-1, 4) if np.asarray(item["boxes"]).size else np.zeros((0, 4), np.float32)
            if boxes.size > 0 and self.box_format != "xyxy":
                boxes = _box_convert_np(boxes, in_fmt=self.box_format, out_fmt="xyxy")
            return boxes
        # segm: dense boolean masks [n, H, W], or COCO RLE dict(s) decoded on
        # host (metrics_tpu/functional/detection/rle.py)
        from metrics_tpu.functional.detection.rle import masks_from_any

        return masks_from_any(item["masks"])

    # ------------------------------------------------------------ compute
    def _get_classes(self) -> List[int]:
        if len(self.detection_labels) > 0 or len(self.groundtruth_labels) > 0:
            return sorted(
                np.unique(np.concatenate([np.asarray(x) for x in self.detection_labels + self.groundtruth_labels]))
                .astype(np.int64)
                .tolist()
            )
        return []

    def _item_area(self, items: np.ndarray) -> np.ndarray:
        if self.iou_type == "bbox":
            # O(N) host arithmetic in the device path's float32: a device
            # round-trip per ragged shape would recompile per distinct N and
            # dominate wall-clock on slow-compile backends (xyxy area,
            # reference `detection/mean_ap.py` via torchvision box_area)
            b = items.reshape(-1, 4).astype(np.float32)
            return (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
        return items.reshape(items.shape[0], -1).sum(-1).astype(np.float64)

    @staticmethod
    def _bucket(n: int) -> int:
        """Next power-of-two padding size so the device IoU kernel compiles
        O(log^2) distinct shapes instead of one per ragged (n_det, n_gt)."""
        return _pow2_bucket(n)

    def _compute_iou(self, idx: int, class_id: int, max_det: int) -> np.ndarray:
        """Device IoU between this image's class detections (score-sorted) and GTs."""
        gt = self.groundtruths[idx]
        det = self.detections[idx]
        gt_mask = np.asarray(self.groundtruth_labels[idx]) == class_id
        det_mask = np.asarray(self.detection_labels[idx]) == class_id
        if gt_mask.sum() == 0 or det_mask.sum() == 0:
            return np.zeros((0, 0))

        gt = gt[gt_mask]
        det = det[det_mask]
        scores_filtered = self.detection_scores[idx][det_mask]
        inds = np.argsort(-scores_filtered, kind="stable")
        det = det[inds][:max_det]

        nd, ng = det.shape[0], gt.shape[0]
        # Small problems: host numpy. A device dispatch per (image, class) pays
        # a round-trip latency that dwarfs the arithmetic; the device path
        # (bucket-padded so it compiles O(log^2) distinct shapes) wins once the
        # work is genuinely large. The cost model counts actual FLOPs: box IoU
        # is O(nd*ng) cells, mask IoU is O(nd*ng*H*W) — large masks go to the
        # MXU even for a handful of instances.
        work = nd * ng * (1 if self.iou_type == "bbox" else int(np.prod(det.shape[1:])))
        if work <= 65536 * (1 if self.iou_type == "bbox" else 64):
            if self.iou_type == "bbox":
                # inputs here are concrete numpy — first sight of a new
                # (nd, ng) bucket may trigger the sweep itself (off = one
                # predicate, host mirror serves as always)
                variant = _autotune.dispatch("map_box_iou", (det, gt), sweep_on_miss=True)
                if variant == "device_blocked":
                    return np.asarray(_box_iou_device_blocked(det, gt))
                return _box_iou_np(det, gt)
            return _mask_iou_np(det, gt)
        bd, bg = self._bucket(nd), self._bucket(ng)
        if self.iou_type == "bbox":
            det_p = np.zeros((bd, 4), det.dtype)
            det_p[:nd] = det
            gt_p = np.zeros((bg, 4), gt.dtype)
            gt_p[:ng] = gt
            return np.asarray(box_iou(jnp.asarray(det_p), jnp.asarray(gt_p)))[:nd, :ng]
        det_p = np.zeros((bd,) + det.shape[1:], det.dtype)
        det_p[:nd] = det
        gt_p = np.zeros((bg,) + gt.shape[1:], gt.dtype)
        gt_p[:ng] = gt
        return np.asarray(mask_iou(jnp.asarray(det_p), jnp.asarray(gt_p)))[:nd, :ng]

    def _evaluate_image(
        self, idx: int, class_id: int, area_range: Tuple[int, int], max_det: int, ious: dict
    ) -> Optional[dict]:
        """Greedy matching for one (image, class, area-range) (reference `mean_ap.py:543-642`)."""
        gt = self.groundtruths[idx]
        det = self.detections[idx]
        gt_mask = np.asarray(self.groundtruth_labels[idx]) == class_id
        det_mask = np.asarray(self.detection_labels[idx]) == class_id
        nb_iou_thrs = len(self.iou_thresholds)

        if gt_mask.sum() == 0 and det_mask.sum() == 0:
            return None

        if gt_mask.sum() > 0 and det_mask.sum() == 0:
            # some GT but no predictions (reference `mean_ap.py:475-496`)
            areas = self._item_area(gt[gt_mask])
            ignore_area = (areas < area_range[0]) | (areas > area_range[1])
            gt_ignore = np.sort(ignore_area.astype(np.uint8), kind="stable").astype(bool)
            return {
                "dtMatches": np.zeros((nb_iou_thrs, 0), dtype=bool),
                "gtMatches": np.zeros((nb_iou_thrs, len(areas)), dtype=bool),
                "dtScores": np.zeros(0),
                "gtIgnore": gt_ignore,
                "dtIgnore": np.zeros((nb_iou_thrs, 0), dtype=bool),
            }

        if gt_mask.sum() == 0:
            # some predictions but no GT (reference `mean_ap.py:498-527`)
            det = det[det_mask]
            scores_filtered = self.detection_scores[idx][det_mask]
            dtind = np.argsort(-scores_filtered, kind="stable")
            det = det[dtind][:max_det]
            scores_sorted = scores_filtered[dtind][:max_det]
            det_areas = self._item_area(det)
            det_ignore_area = (det_areas < area_range[0]) | (det_areas > area_range[1])
            det_ignore = np.repeat(det_ignore_area.reshape(1, -1), nb_iou_thrs, 0)
            return {
                "dtMatches": np.zeros((nb_iou_thrs, len(det)), dtype=bool),
                "gtMatches": np.zeros((nb_iou_thrs, 0), dtype=bool),
                "dtScores": scores_sorted,
                "gtIgnore": np.zeros(0, dtype=bool),
                "dtIgnore": det_ignore,
            }

        gt = gt[gt_mask]
        det = det[det_mask]
        areas = self._item_area(gt)
        ignore_area = (areas < area_range[0]) | (areas > area_range[1])

        # sort gt ignore-last, det score-first
        gtind = np.argsort(ignore_area.astype(np.uint8), kind="stable")
        gt_ignore = ignore_area[gtind]
        scores_filtered = self.detection_scores[idx][det_mask]
        dtind = np.argsort(-scores_filtered, kind="stable")
        det = det[dtind][:max_det]
        scores_sorted = scores_filtered[dtind][:max_det]
        iou_mat = ious[idx, class_id]
        iou_mat = iou_mat[:, gtind] if iou_mat.size > 0 else iou_mat

        nb_gt = len(gt)
        nb_det = len(det)
        gt_matches = np.zeros((nb_iou_thrs, nb_gt), dtype=bool)
        det_matches = np.zeros((nb_iou_thrs, nb_det), dtype=bool)
        det_ignore = np.zeros((nb_iou_thrs, nb_det), dtype=bool)

        if iou_mat.size > 0:
            for idx_iou, thr in enumerate(self.iou_thresholds):
                for idx_det in range(nb_det):
                    m = self._find_best_gt_match(thr, gt_matches, idx_iou, gt_ignore, iou_mat, idx_det)
                    if m == -1:
                        continue
                    det_ignore[idx_iou, idx_det] = gt_ignore[m]
                    det_matches[idx_iou, idx_det] = True
                    gt_matches[idx_iou, m] = True

        # unmatched detections outside the area range are ignored
        det_areas = self._item_area(det)
        det_ignore_area = (det_areas < area_range[0]) | (det_areas > area_range[1])
        det_ignore = det_ignore | (~det_matches & np.repeat(det_ignore_area.reshape(1, -1), nb_iou_thrs, 0))

        return {
            "dtMatches": det_matches,
            "gtMatches": gt_matches,
            "dtScores": scores_sorted,
            "gtIgnore": gt_ignore,
            "dtIgnore": det_ignore,
        }

    @staticmethod
    def _find_best_gt_match(
        thr: float, gt_matches: np.ndarray, idx_iou: int, gt_ignore: np.ndarray, ious: np.ndarray, idx_det: int
    ) -> int:
        """Best unmatched, unignored GT above threshold (reference `mean_ap.py:644-670`)."""
        remove_mask = gt_matches[idx_iou] | gt_ignore
        gt_ious = ious[idx_det] * ~remove_mask
        match_idx = int(gt_ious.argmax())
        if gt_ious[match_idx] > thr:
            return match_idx
        return -1

    def _calculate(self, class_ids: List[int]) -> Tuple[np.ndarray, np.ndarray]:
        """Precision/recall tensors [T,R,K,A,M] / [T,K,A,M] (reference `mean_ap.py:704-759`)."""
        img_ids = range(len(self.groundtruths))
        max_detections = self.max_detection_thresholds[-1]
        area_ranges = self.bbox_area_ranges.values()

        ious = {
            (idx, class_id): self._compute_iou(idx, class_id, max_detections)
            for idx in img_ids
            for class_id in class_ids
        }

        eval_imgs = [
            self._evaluate_image(img_id, class_id, area, max_detections, ious)
            for class_id in class_ids
            for area in area_ranges
            for img_id in img_ids
        ]

        nb_iou_thrs = len(self.iou_thresholds)
        nb_rec_thrs = len(self.rec_thresholds)
        nb_classes = len(class_ids)
        nb_bbox_areas = len(self.bbox_area_ranges)
        nb_max_det_thrs = len(self.max_detection_thresholds)
        nb_imgs = len(img_ids)
        precision = -np.ones((nb_iou_thrs, nb_rec_thrs, nb_classes, nb_bbox_areas, nb_max_det_thrs))
        recall = -np.ones((nb_iou_thrs, nb_classes, nb_bbox_areas, nb_max_det_thrs))
        rec_thresholds = np.asarray(self.rec_thresholds)

        for idx_cls in range(nb_classes):
            for idx_bbox_area in range(nb_bbox_areas):
                for idx_max_det_thrs, max_det in enumerate(self.max_detection_thresholds):
                    self.__calculate_recall_precision_scores(
                        recall,
                        precision,
                        idx_cls=idx_cls,
                        idx_bbox_area=idx_bbox_area,
                        idx_max_det_thrs=idx_max_det_thrs,
                        eval_imgs=eval_imgs,
                        rec_thresholds=rec_thresholds,
                        max_det=max_det,
                        nb_imgs=nb_imgs,
                        nb_bbox_areas=nb_bbox_areas,
                    )
        return precision, recall

    def __calculate_recall_precision_scores(
        self,
        recall: np.ndarray,
        precision: np.ndarray,
        idx_cls: int,
        idx_bbox_area: int,
        idx_max_det_thrs: int,
        eval_imgs: list,
        rec_thresholds: np.ndarray,
        max_det: int,
        nb_imgs: int,
        nb_bbox_areas: int,
    ) -> None:
        """101-point interpolation per threshold (reference `mean_ap.py:797-877`)."""
        nb_rec_thrs = len(rec_thresholds)
        idx_cls_pointer = idx_cls * nb_bbox_areas * nb_imgs
        idx_bbox_area_pointer = idx_bbox_area * nb_imgs
        img_eval_cls_bbox = [eval_imgs[idx_cls_pointer + idx_bbox_area_pointer + i] for i in range(nb_imgs)]
        img_eval_cls_bbox = [e for e in img_eval_cls_bbox if e is not None]
        if not img_eval_cls_bbox:
            return

        det_scores = np.concatenate([e["dtScores"][:max_det] for e in img_eval_cls_bbox])
        # mergesort to be consistent with pycocotools/Matlab (reference `mean_ap.py:826-831`)
        inds = np.argsort(-det_scores, kind="mergesort")
        det_scores_sorted = det_scores[inds]

        det_matches = np.concatenate([e["dtMatches"][:, :max_det] for e in img_eval_cls_bbox], axis=1)[:, inds]
        det_ignore = np.concatenate([e["dtIgnore"][:, :max_det] for e in img_eval_cls_bbox], axis=1)[:, inds]
        gt_ignore = np.concatenate([e["gtIgnore"] for e in img_eval_cls_bbox])
        npig = np.count_nonzero(~gt_ignore)
        if npig == 0:
            return
        tps = det_matches & ~det_ignore
        fps = ~det_matches & ~det_ignore

        tp_sum = np.cumsum(tps, axis=1).astype(float)
        fp_sum = np.cumsum(fps, axis=1).astype(float)
        for idx, (tp, fp) in enumerate(zip(tp_sum, fp_sum)):
            nd = len(tp)
            rc = tp / npig
            pr = tp / (fp + tp + np.finfo(np.float64).eps)
            prec = np.zeros((nb_rec_thrs,))

            recall[idx, idx_cls, idx_bbox_area, idx_max_det_thrs] = rc[-1] if nd else 0

            # monotone envelope from the right (replaces the reference's
            # iterative zigzag loop `mean_ap.py:852-858` with one pass)
            pr = np.maximum.accumulate(pr[::-1])[::-1]

            inds_t = np.searchsorted(rc, rec_thresholds, side="left")
            num_inds = int(inds_t.argmax()) if inds_t.max() >= nd else nb_rec_thrs
            inds_t = inds_t[:num_inds]
            prec[:num_inds] = pr[inds_t]
            precision[idx, :, idx_cls, idx_bbox_area, idx_max_det_thrs] = prec

    def _summarize(
        self,
        results: Dict[str, np.ndarray],
        avg_prec: bool = True,
        iou_threshold: Optional[float] = None,
        area_range: str = "all",
        max_dets: int = 100,
    ) -> float:
        """Mean over valid (> -1) cells of the selected slice (reference `mean_ap.py:672-702`)."""
        area_inds = [i for i, k in enumerate(self.bbox_area_ranges.keys()) if k == area_range]
        mdet_inds = [i for i, k in enumerate(self.max_detection_thresholds) if k == max_dets]
        if avg_prec:
            prec = results["precision"]
            if iou_threshold is not None:
                thr = self.iou_thresholds.index(iou_threshold)
                prec = prec[thr, :, :, area_inds, mdet_inds]
            else:
                prec = prec[:, :, :, area_inds, mdet_inds]
        else:
            prec = results["recall"]
            if iou_threshold is not None:
                thr = self.iou_thresholds.index(iou_threshold)
                prec = prec[thr, :, area_inds, mdet_inds]
            else:
                prec = prec[:, :, area_inds, mdet_inds]
        valid = prec[prec > -1]
        return -1.0 if valid.size == 0 else float(valid.mean())

    def _summarize_results(self, precisions: np.ndarray, recalls: np.ndarray) -> Tuple[dict, dict]:
        results = dict(precision=precisions, recall=recalls)
        last_max_det_thr = self.max_detection_thresholds[-1]

        map_metrics = {"map": self._summarize(results, True)}
        map_metrics["map_50"] = (
            self._summarize(results, True, iou_threshold=0.5, max_dets=last_max_det_thr)
            if 0.5 in self.iou_thresholds
            else -1.0
        )
        map_metrics["map_75"] = (
            self._summarize(results, True, iou_threshold=0.75, max_dets=last_max_det_thr)
            if 0.75 in self.iou_thresholds
            else -1.0
        )
        map_metrics["map_small"] = self._summarize(results, True, area_range="small", max_dets=last_max_det_thr)
        map_metrics["map_medium"] = self._summarize(results, True, area_range="medium", max_dets=last_max_det_thr)
        map_metrics["map_large"] = self._summarize(results, True, area_range="large", max_dets=last_max_det_thr)

        mar_metrics = {}
        for max_det in self.max_detection_thresholds:
            mar_metrics[f"mar_{max_det}"] = self._summarize(results, False, max_dets=max_det)
        mar_metrics["mar_small"] = self._summarize(results, False, area_range="small", max_dets=last_max_det_thr)
        mar_metrics["mar_medium"] = self._summarize(results, False, area_range="medium", max_dets=last_max_det_thr)
        mar_metrics["mar_large"] = self._summarize(results, False, area_range="large", max_dets=last_max_det_thr)
        return map_metrics, mar_metrics

    def compute(self) -> dict:
        """mAP/mAR summary dict (reference `mean_ap.py:879-933`)."""
        self._materialize_states()  # one fused device fetch for all pending leaves
        classes = self._get_classes()
        precisions, recalls = self._calculate(classes)
        map_val, mar_val = self._summarize_results(precisions, recalls)

        map_per_class_values = np.asarray([-1.0])
        mar_max_dets_per_class_values = np.asarray([-1.0])
        if self.class_metrics:
            map_per_class_list = []
            mar_max_dets_per_class_list = []
            for class_idx in range(len(classes)):
                cls_precisions = precisions[:, :, class_idx][:, :, None]
                cls_recalls = recalls[:, class_idx][:, None]
                cls_map, cls_mar = self._summarize_results(cls_precisions, cls_recalls)
                map_per_class_list.append(cls_map["map"])
                mar_max_dets_per_class_list.append(cls_mar[f"mar_{self.max_detection_thresholds[-1]}"])
            map_per_class_values = np.asarray(map_per_class_list, dtype=np.float32)
            mar_max_dets_per_class_values = np.asarray(mar_max_dets_per_class_list, dtype=np.float32)

        metrics = {k: jnp.asarray(v, dtype=jnp.float32) for k, v in {**map_val, **mar_val}.items()}
        metrics["map_per_class"] = jnp.asarray(map_per_class_values, dtype=jnp.float32)
        metrics[f"mar_{self.max_detection_thresholds[-1]}_per_class"] = jnp.asarray(
            mar_max_dets_per_class_values, dtype=jnp.float32
        )
        return metrics


__all__ = ["MeanAveragePrecision"]
