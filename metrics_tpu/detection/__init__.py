"""Detection module metrics (L3).

Parity target: reference `src/torchmetrics/detection/__init__.py`.
"""
from metrics_tpu.detection.mean_ap import MeanAveragePrecision

__all__ = ["MeanAveragePrecision"]
