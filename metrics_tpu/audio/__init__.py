"""Audio module metrics (L3).

Parity target: reference `src/torchmetrics/audio/__init__.py`.
"""
from metrics_tpu.audio.metrics import (
    PermutationInvariantTraining,
    PerceptualEvaluationSpeechQuality,
    ScaleInvariantSignalDistortionRatio,
    ScaleInvariantSignalNoiseRatio,
    ShortTimeObjectiveIntelligibility,
    SignalDistortionRatio,
    SignalNoiseRatio,
)

__all__ = [
    "SignalNoiseRatio",
    "ScaleInvariantSignalNoiseRatio",
    "SignalDistortionRatio",
    "ScaleInvariantSignalDistortionRatio",
    "PermutationInvariantTraining",
    "PerceptualEvaluationSpeechQuality",
    "ShortTimeObjectiveIntelligibility",
]
