"""Audio module metrics — all mean accumulators over per-clip scores.

Parity: reference `audio/{snr,sdr,pit,pesq,stoi}.py` — every audio module
keeps ``sum_<metric>`` + ``total`` sum-states and averages at compute time,
so distributed sync is a single fused psum pair.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp

from metrics_tpu.functional.audio.host import (
    perceptual_evaluation_speech_quality,
    short_time_objective_intelligibility,
)
from metrics_tpu.functional.audio.pit import permutation_invariant_training
from metrics_tpu.functional.audio.sdr import signal_distortion_ratio
from metrics_tpu.functional.audio.snr import (
    scale_invariant_signal_distortion_ratio,
    scale_invariant_signal_noise_ratio,
    signal_noise_ratio,
)
from metrics_tpu.metric import Metric
from metrics_tpu.utils.imports import _PESQ_AVAILABLE

__doctest_skip__ = ["PerceptualEvaluationSpeechQuality"]


class _MeanAudioMetric(Metric):
    """Shared sum/total plumbing for averaged audio metrics."""

    _state_name: str = "sum_value"

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state(self._state_name, default=jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("total", default=jnp.asarray(0, dtype=jnp.int32), dist_reduce_fx="sum")

    def _accumulate(self, batch_values: jax.Array) -> None:
        setattr(self, self._state_name, getattr(self, self._state_name) + batch_values.sum())
        self.total = self.total + batch_values.size

    def compute(self) -> jax.Array:
        return getattr(self, self._state_name) / self.total


class SignalNoiseRatio(_MeanAudioMetric):
    """Average SNR (reference `audio/snr.py:22-95`).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import SignalNoiseRatio
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> snr = SignalNoiseRatio()
        >>> round(float(snr(preds, target)), 2)
        16.18
    """

    full_state_update = False
    is_differentiable = True
    higher_is_better = True
    _state_name = "sum_snr"

    def __init__(self, zero_mean: bool = False, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.zero_mean = zero_mean

    def update(self, preds: jax.Array, target: jax.Array) -> None:
        self._accumulate(signal_noise_ratio(preds=preds, target=target, zero_mean=self.zero_mean))


class ScaleInvariantSignalNoiseRatio(_MeanAudioMetric):
    """Average SI-SNR (reference `audio/snr.py:97-160`).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import ScaleInvariantSignalNoiseRatio
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> si_snr = ScaleInvariantSignalNoiseRatio()
        >>> si_snr(preds, target).round(4)
        Array(15.0918, dtype=float32)
    """

    full_state_update = False
    is_differentiable = True
    higher_is_better = True
    _state_name = "sum_si_snr"

    def update(self, preds: jax.Array, target: jax.Array) -> None:
        self._accumulate(scale_invariant_signal_noise_ratio(preds=preds, target=target))


class SignalDistortionRatio(_MeanAudioMetric):
    """Average SDR (reference `audio/sdr.py:24-120`).

    Example:
        >>> import numpy as np
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import SignalDistortionRatio
        >>> rng = np.random.RandomState(1)
        >>> preds = jnp.asarray(rng.randn(8000).astype(np.float32))
        >>> target = jnp.asarray(rng.randn(8000).astype(np.float32))
        >>> sdr = SignalDistortionRatio()
        >>> float(sdr(preds, target)) < -10
        True
    """

    full_state_update = False
    is_differentiable = True
    higher_is_better = True
    _state_name = "sum_sdr"

    def __init__(
        self,
        use_cg_iter: Optional[int] = None,
        filter_length: int = 512,
        zero_mean: bool = False,
        load_diag: Optional[float] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.use_cg_iter = use_cg_iter
        self.filter_length = filter_length
        self.zero_mean = zero_mean
        self.load_diag = load_diag

    def update(self, preds: jax.Array, target: jax.Array) -> None:
        self._accumulate(
            signal_distortion_ratio(preds, target, self.use_cg_iter, self.filter_length, self.zero_mean, self.load_diag)
        )


class ScaleInvariantSignalDistortionRatio(_MeanAudioMetric):
    """Average SI-SDR (reference `audio/sdr.py:122-189`).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import ScaleInvariantSignalDistortionRatio
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> si_sdr = ScaleInvariantSignalDistortionRatio()
        >>> si_sdr(preds, target).round(4)
        Array(18.403, dtype=float32)
    """

    full_state_update = False
    is_differentiable = True
    higher_is_better = True
    _state_name = "sum_si_sdr"

    def __init__(self, zero_mean: bool = False, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.zero_mean = zero_mean

    def update(self, preds: jax.Array, target: jax.Array) -> None:
        self._accumulate(scale_invariant_signal_distortion_ratio(preds=preds, target=target, zero_mean=self.zero_mean))


class PermutationInvariantTraining(_MeanAudioMetric):
    """Average best-permutation metric (reference `audio/pit.py:22-104`).

    Extra constructor kwargs (beyond the base sync kwargs) are forwarded to
    ``metric_func``, matching the reference's kwargs split (`audio/pit.py:75-83`).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import PermutationInvariantTraining
        >>> from metrics_tpu.functional import scale_invariant_signal_distortion_ratio
        >>> preds = jnp.asarray([[[-0.0579,  0.3560, -0.9604], [-0.1719,  0.3205,  0.2951]]])
        >>> target = jnp.asarray([[[ 1.0958, -0.1648,  0.5228], [-0.4100,  1.1942, -0.5103]]])
        >>> pit = PermutationInvariantTraining(scale_invariant_signal_distortion_ratio, 'max')
        >>> round(float(pit(preds, target)), 3)
        -5.109
    """

    full_state_update = False
    is_differentiable = True
    # direction depends on eval_func, so no fixed polarity (reference `audio/pit.py:64-67`)
    higher_is_better = None
    _state_name = "sum_pit_metric"

    def __init__(self, metric_func: Callable, eval_func: str = "max", **kwargs: Any) -> None:
        base_kwargs: Dict[str, Any] = {
            k: kwargs.pop(k)
            for k in ("compute_on_cpu", "dist_sync_on_step", "process_group", "dist_sync_fn", "sync_on_compute")
            if k in kwargs
        }
        super().__init__(**base_kwargs)
        self.metric_func = metric_func
        self.eval_func = eval_func
        self.kwargs = kwargs

    def update(self, preds: jax.Array, target: jax.Array) -> None:
        pit_metric = permutation_invariant_training(preds, target, self.metric_func, self.eval_func, **self.kwargs)[0]
        self._accumulate(pit_metric)


class PerceptualEvaluationSpeechQuality(_MeanAudioMetric):
    """Average PESQ via the host ``pesq`` backend (reference `audio/pesq.py:25-117`).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import PerceptualEvaluationSpeechQuality
        >>> pesq = PerceptualEvaluationSpeechQuality(8000, 'nb')  # doctest: +SKIP
    """

    full_state_update = False
    is_differentiable = False
    higher_is_better = True
    _state_name = "sum_pesq"

    def __init__(self, fs: int, mode: str, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not _PESQ_AVAILABLE:
            raise ModuleNotFoundError(
                "PerceptualEvaluationSpeechQuality metric requires that pesq is installed."
                " Install it with `pip install pesq`."
            )
        if fs not in (8000, 16000):
            raise ValueError(f"Expected argument `fs` to either be 8000 or 16000 but got {fs}")
        self.fs = fs
        if mode not in ("wb", "nb"):
            raise ValueError(f"Expected argument `mode` to either be 'wb' or 'nb' but got {mode}")
        self.mode = mode

    def update(self, preds: jax.Array, target: jax.Array) -> None:
        self._accumulate(perceptual_evaluation_speech_quality(preds, target, self.fs, self.mode))


class ShortTimeObjectiveIntelligibility(_MeanAudioMetric):
    """Average STOI over clips (reference `audio/stoi.py:25-120`).

    Uses the native in-tree DSP implementation (`functional/audio/stoi.py`) —
    the reference requires the ``pystoi`` package; here it is only the
    optional cross-check backend.

    Example:
        >>> import jax.numpy as jnp
        >>> import numpy as np
        >>> from metrics_tpu import ShortTimeObjectiveIntelligibility
        >>> rng = np.random.RandomState(0)
        >>> target = jnp.asarray(np.sin(2 * np.pi * 440 * np.arange(16000) / 10000) * (1 + 0.5 * rng.rand(16000)))
        >>> stoi = ShortTimeObjectiveIntelligibility(10000)
        >>> float(stoi(target + 0.1 * jnp.asarray(rng.randn(16000)), target)) > 0.5
        True
    """

    full_state_update = False
    is_differentiable = False
    higher_is_better = True
    _state_name = "sum_stoi"

    def __init__(self, fs: int, extended: bool = False, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.fs = fs
        self.extended = extended

    def update(self, preds: jax.Array, target: jax.Array) -> None:
        self._accumulate(short_time_objective_intelligibility(preds, target, self.fs, self.extended))


__all__ = [
    "SignalNoiseRatio",
    "ScaleInvariantSignalNoiseRatio",
    "SignalDistortionRatio",
    "ScaleInvariantSignalDistortionRatio",
    "PermutationInvariantTraining",
    "PerceptualEvaluationSpeechQuality",
    "ShortTimeObjectiveIntelligibility",
]
