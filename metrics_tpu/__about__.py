__version__ = "0.1.0"
__author__ = "metrics-tpu developers"
__license__ = "Apache-2.0"
__docs__ = (
    "TPU-native metrics framework: 80+ machine-learning metrics as pure JAX/XLA "
    "programs with mesh-aware distributed accumulation."
)

__all__ = ["__version__", "__author__", "__license__", "__docs__"]
