"""metrics_tpu — TPU-native metrics framework (JAX/XLA).

A from-scratch re-design of the capabilities of TorchMetrics
(`/root/reference`, v0.10.0dev) for TPU: metric state is a pytree of immutable
JAX arrays, update/compute are pure jittable kernels, and distributed
accumulation lowers to fused XLA collectives over a `jax.sharding.Mesh`.
"""
import logging

_logger = logging.getLogger("metrics_tpu")
_logger.addHandler(logging.StreamHandler())
_logger.setLevel(logging.INFO)

from metrics_tpu.utils import compat as _compat  # noqa: E402

_compat.install()  # jax version-drift aliases (shard_map) before any SPMD use

from metrics_tpu.__about__ import __version__  # noqa: E402
from metrics_tpu.audio import (  # noqa: E402
    PermutationInvariantTraining,
    PerceptualEvaluationSpeechQuality,
    ScaleInvariantSignalDistortionRatio,
    ScaleInvariantSignalNoiseRatio,
    ShortTimeObjectiveIntelligibility,
    SignalDistortionRatio,
    SignalNoiseRatio,
)
from metrics_tpu.aggregation import (  # noqa: E402
    CatMetric,
    MaxMetric,
    MeanMetric,
    MinMetric,
    SumMetric,
)
from metrics_tpu.classification import (  # noqa: E402
    AUC,
    AUROC,
    Accuracy,
    AveragePrecision,
    BinnedAveragePrecision,
    BinnedPrecisionRecallCurve,
    BinnedRecallAtFixedPrecision,
    CalibrationError,
    CohenKappa,
    ConfusionMatrix,
    CoverageError,
    Dice,
    F1Score,
    FBetaScore,
    HammingDistance,
    HingeLoss,
    JaccardIndex,
    KLDivergence,
    LabelRankingAveragePrecision,
    LabelRankingLoss,
    MatthewsCorrCoef,
    Precision,
    PrecisionRecallCurve,
    Recall,
    ROC,
    Specificity,
    StatScores,
)
from metrics_tpu.collections import MetricCollection  # noqa: E402
from metrics_tpu.detection import MeanAveragePrecision  # noqa: E402
from metrics_tpu.image import (  # noqa: E402
    ErrorRelativeGlobalDimensionlessSynthesis,
    FrechetInceptionDistance,
    InceptionScore,
    KernelInceptionDistance,
    LearnedPerceptualImagePatchSimilarity,
    MultiScaleStructuralSimilarityIndexMeasure,
    PeakSignalNoiseRatio,
    SpectralAngleMapper,
    SpectralDistortionIndex,
    StructuralSimilarityIndexMeasure,
    UniversalImageQualityIndex,
)
from metrics_tpu.metric import CompositionalMetric, Metric  # noqa: E402
from metrics_tpu.regression import (  # noqa: E402
    CosineSimilarity,
    ExplainedVariance,
    MeanAbsoluteError,
    MeanAbsolutePercentageError,
    MeanSquaredError,
    MeanSquaredLogError,
    PearsonCorrCoef,
    R2Score,
    SpearmanCorrCoef,
    SymmetricMeanAbsolutePercentageError,
    TweedieDevianceScore,
    WeightedMeanAbsolutePercentageError,
)
from metrics_tpu.retrieval import (  # noqa: E402
    RetrievalFallOut,
    RetrievalHitRate,
    RetrievalMAP,
    RetrievalMetric,
    RetrievalMRR,
    RetrievalNormalizedDCG,
    RetrievalPrecision,
    RetrievalPrecisionRecallCurve,
    RetrievalRecall,
    RetrievalRecallAtFixedPrecision,
    RetrievalRPrecision,
)
from metrics_tpu.text import (  # noqa: E402
    BERTScore,
    BLEUScore,
    CharErrorRate,
    CHRFScore,
    ExtendedEditDistance,
    InfoLM,
    MatchErrorRate,
    Perplexity,
    ROUGEScore,
    SacreBLEUScore,
    SQuAD,
    TranslationEditRate,
    WordErrorRate,
    WordInfoLost,
    WordInfoPreserved,
)
from metrics_tpu.wrappers import (  # noqa: E402
    BootStrapper,
    ClasswiseWrapper,
    MetricTracker,
    MinMaxMetric,
    MultioutputWrapper,
)

from metrics_tpu import functional  # noqa: E402

# THE monitoring surface (docs/observability.md): one merged schema-stable
# snapshot dict, its Prometheus-style rendering, and the Perfetto trace export
from metrics_tpu.ops.telemetry import (  # noqa: E402
    export_trace,
    prometheus_text,
    set_telemetry,
    telemetry_snapshot,
)

# the fleet plane (docs/observability.md "Fleet plane"): cross-rank snapshot
# aggregation, straggler attribution, and the merged one-process-per-rank trace
from metrics_tpu.ops.fleetobs import (  # noqa: E402
    export_fleet_trace,
    fleet_perf_report,
    fleet_prometheus_text,
    fleet_snapshot,
)

# the performance attribution plane (docs/performance.md "Where the time
# goes"): step-latency decomposition, roofline ledger, ranked opportunities
from metrics_tpu.ops.perf import perf_report  # noqa: E402

# the model-monitoring plane (docs/observability.md "Model-monitoring
# plane"): windowed/decayed metrics over the journal ring + PSI/KS drift
from metrics_tpu.streaming import (  # noqa: E402
    Decayed,
    Windowed,
    drift_report,
)

# multi-tenant metric arenas (docs/performance.md "Tenant arenas"): N
# same-config suites stacked on a leading axis, driven by engine-cached
# vmapped donated programs with slab-bucketed shapes and slab-granular
# journal records
from metrics_tpu.arena import (  # noqa: E402
    MetricArena,
    arena_stats,
    stack_states,
    unstack_states,
)

# the overload-safe ingestion gateway (docs/robustness.md "Overload &
# admission control"): columnar staging, SLO-driven admission tiers,
# poison-payload quarantine, exact settlement accounting
from metrics_tpu.ingest import (  # noqa: E402
    IngestGateway,
    ingest_state,
    ingest_stats,
)

# world membership (docs/robustness.md "World membership"): epoch registry +
# peer-health surface behind epoch-fenced collectives and quorum compute
from metrics_tpu.parallel.sync import world_health  # noqa: E402

# the functional pytree core (docs/performance.md "Zero host round trips"):
# state-as-pytree apply_* API riding inside the user's jitted SPMD step —
# in-graph collectives, epoch-stamped state trees, the host_handoff seam
from metrics_tpu.functional_core import (  # noqa: E402
    FuncState,
    apply_compute,
    apply_update,
    funcore_stats,
    host_handoff,
)
from metrics_tpu.parallel.sharding import (  # noqa: E402
    infer_state_pspecs,
    infer_state_shardings,
)

__all__ = [
    "__version__",
    "functional",
    "FuncState",
    "apply_compute",
    "apply_update",
    "funcore_stats",
    "host_handoff",
    "infer_state_pspecs",
    "infer_state_shardings",
    "export_trace",
    "prometheus_text",
    "set_telemetry",
    "telemetry_snapshot",
    "world_health",
    "export_fleet_trace",
    "fleet_perf_report",
    "fleet_prometheus_text",
    "fleet_snapshot",
    "perf_report",
    "Decayed",
    "Windowed",
    "drift_report",
    "MetricArena",
    "arena_stats",
    "stack_states",
    "unstack_states",
    "IngestGateway",
    "ingest_state",
    "ingest_stats",
    "Metric",
    "CompositionalMetric",
    "MetricCollection",
    "CatMetric",
    "MaxMetric",
    "MeanMetric",
    "MinMetric",
    "SumMetric",
    "Accuracy",
    "AUC",
    "AUROC",
    "AveragePrecision",
    "BinnedAveragePrecision",
    "BinnedPrecisionRecallCurve",
    "BinnedRecallAtFixedPrecision",
    "CalibrationError",
    "CohenKappa",
    "ConfusionMatrix",
    "CoverageError",
    "Dice",
    "F1Score",
    "FBetaScore",
    "HammingDistance",
    "HingeLoss",
    "JaccardIndex",
    "KLDivergence",
    "LabelRankingAveragePrecision",
    "LabelRankingLoss",
    "MatthewsCorrCoef",
    "Precision",
    "PrecisionRecallCurve",
    "Recall",
    "ROC",
    "Specificity",
    "StatScores",
    "CosineSimilarity",
    "ExplainedVariance",
    "MeanAbsoluteError",
    "MeanAbsolutePercentageError",
    "MeanSquaredError",
    "MeanSquaredLogError",
    "PearsonCorrCoef",
    "R2Score",
    "SpearmanCorrCoef",
    "SymmetricMeanAbsolutePercentageError",
    "TweedieDevianceScore",
    "WeightedMeanAbsolutePercentageError",
    "MeanAveragePrecision",
    "SignalNoiseRatio",
    "ScaleInvariantSignalNoiseRatio",
    "SignalDistortionRatio",
    "ScaleInvariantSignalDistortionRatio",
    "PermutationInvariantTraining",
    "PerceptualEvaluationSpeechQuality",
    "ShortTimeObjectiveIntelligibility",
    "PeakSignalNoiseRatio",
    "FrechetInceptionDistance",
    "KernelInceptionDistance",
    "InceptionScore",
    "LearnedPerceptualImagePatchSimilarity",
    "StructuralSimilarityIndexMeasure",
    "MultiScaleStructuralSimilarityIndexMeasure",
    "UniversalImageQualityIndex",
    "ErrorRelativeGlobalDimensionlessSynthesis",
    "SpectralAngleMapper",
    "SpectralDistortionIndex",
    "RetrievalFallOut",
    "RetrievalHitRate",
    "RetrievalMAP",
    "RetrievalMetric",
    "RetrievalMRR",
    "RetrievalNormalizedDCG",
    "RetrievalPrecision",
    "RetrievalPrecisionRecallCurve",
    "RetrievalRecall",
    "RetrievalRecallAtFixedPrecision",
    "RetrievalRPrecision",
    "BootStrapper",
    "ClasswiseWrapper",
    "MetricTracker",
    "MinMaxMetric",
    "MultioutputWrapper",
    "BERTScore",
    "BLEUScore",
    "CharErrorRate",
    "CHRFScore",
    "ExtendedEditDistance",
    "InfoLM",
    "MatchErrorRate",
    "Perplexity",
    "ROUGEScore",
    "SacreBLEUScore",
    "SQuAD",
    "TranslationEditRate",
    "WordErrorRate",
    "WordInfoLost",
    "WordInfoPreserved",
]
