"""metrics_tpu — TPU-native metrics framework (JAX/XLA).

A from-scratch re-design of the capabilities of TorchMetrics
(`/root/reference`, v0.10.0dev) for TPU: metric state is a pytree of immutable
JAX arrays, update/compute are pure jittable kernels, and distributed
accumulation lowers to fused XLA collectives over a `jax.sharding.Mesh`.
"""
import logging

_logger = logging.getLogger("metrics_tpu")
_logger.addHandler(logging.StreamHandler())
_logger.setLevel(logging.INFO)

from metrics_tpu.__about__ import __version__  # noqa: E402
from metrics_tpu.aggregation import (  # noqa: E402
    CatMetric,
    MaxMetric,
    MeanMetric,
    MinMetric,
    SumMetric,
)
from metrics_tpu.classification import (  # noqa: E402
    AUC,
    AUROC,
    Accuracy,
    AveragePrecision,
    BinnedAveragePrecision,
    BinnedPrecisionRecallCurve,
    BinnedRecallAtFixedPrecision,
    CalibrationError,
    CohenKappa,
    ConfusionMatrix,
    CoverageError,
    Dice,
    F1Score,
    FBetaScore,
    HammingDistance,
    HingeLoss,
    JaccardIndex,
    KLDivergence,
    LabelRankingAveragePrecision,
    LabelRankingLoss,
    MatthewsCorrCoef,
    Precision,
    PrecisionRecallCurve,
    Recall,
    ROC,
    Specificity,
    StatScores,
)
from metrics_tpu.collections import MetricCollection  # noqa: E402
from metrics_tpu.metric import CompositionalMetric, Metric  # noqa: E402

__all__ = [
    "__version__",
    "Metric",
    "CompositionalMetric",
    "MetricCollection",
    "CatMetric",
    "MaxMetric",
    "MeanMetric",
    "MinMetric",
    "SumMetric",
    "Accuracy",
    "AUC",
    "AUROC",
    "AveragePrecision",
    "BinnedAveragePrecision",
    "BinnedPrecisionRecallCurve",
    "BinnedRecallAtFixedPrecision",
    "CalibrationError",
    "CohenKappa",
    "ConfusionMatrix",
    "CoverageError",
    "Dice",
    "F1Score",
    "FBetaScore",
    "HammingDistance",
    "HingeLoss",
    "JaccardIndex",
    "KLDivergence",
    "LabelRankingAveragePrecision",
    "LabelRankingLoss",
    "MatthewsCorrCoef",
    "Precision",
    "PrecisionRecallCurve",
    "Recall",
    "ROC",
    "Specificity",
    "StatScores",
]
