"""Core ``Metric`` base class — the L1 runtime.

Parity target: reference ``src/torchmetrics/metric.py:44-961`` (state registry via
``add_state`` `:158`, dual-purpose ``forward`` `:228-325`, state-merge table
`:327-354`, sync engine `:356-506`, compute caching `:508-536`, serialization
`:662-700`, operator composition `:743-846`).

TPU-first redesign (not a port):

- **No ``nn.Module``.** A metric is a plain object whose state is a pytree of
  immutable ``jax.Array`` leaves (tensor kind) or python lists of arrays (cat
  kind). Because arrays are immutable, the reference's snapshot/restore dance in
  ``forward`` (`metric.py:249-325`) degenerates to holding references — zero
  copies on the hot path.
- **Pure-function export.** :meth:`as_functions` exposes ``(init, update,
  compute)`` as pure functions over the state pytree, directly usable under
  ``jax.jit`` / ``shard_map`` / ``lax.scan``. The stateful API and the SPMD API
  are the same kernels.
- **Fused distributed sync.** ``dist_reduce_fx`` is kept as a *spec* so that the
  in-program path lowers "sum" to one ``lax.psum`` over ICI instead of the
  reference's barrier + all_gather + host reduce. The host (multi-process) path
  keeps the reference's uneven-shape gather protocol
  (`utilities/distributed.py:128-151`).
- **Grad-mode free.** JAX has no global autograd mode; differentiability is a
  property of the pure functions (`jax.grad` over :meth:`as_functions`), so the
  reference's ``_enable_grad`` bookkeeping disappears.
"""
from __future__ import annotations

import copy
import functools
import inspect
from abc import ABC, abstractmethod
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from metrics_tpu.ops import engine as _engine
from metrics_tpu.ops import faults as _faults
from metrics_tpu.ops import telemetry as _telemetry
from metrics_tpu.parallel import bucketing as _bucketing
from metrics_tpu.parallel.reductions import resolve_reduction
from metrics_tpu.parallel import sync as _psync
from metrics_tpu.parallel.sync import distributed_available as _dist_available
from metrics_tpu.parallel.sync import gather_all_tensors
from metrics_tpu.utils.data import _flatten, apply_to_collection, dim_zero_cat
from metrics_tpu.utils.exceptions import MetricsUserError, SyncConfigFault, SyncFault
from metrics_tpu.utils.prints import rank_zero_warn


def jit_distributed_available() -> bool:
    """Hot-path distributed probe: one memoized backend walk per process
    (``parallel.sync.distributed_available`` caches the resolution; the
    ``sync_dist_resolutions`` counter pins it) — this runs on EVERY
    ``compute()``/``sync()`` and used to re-walk the backend client per
    call."""
    return _dist_available()


_UNSET = object()  # sentinel: distinguishes "attribute absent" from "set to None"
_JITTABLE_SCALARS = (int, float, bool, complex)


def _probe_traceable(program: Callable, *args: Any, **kwargs: Any) -> bool:
    """Abstract-trace probe (no compile, no dispatch): False when the program
    cannot trace with these arguments — e.g. an update whose num_classes
    inference is eager-only. Used by every fused path to decline fusion
    SILENTLY: an untraceable configuration is supported, not an anomaly worth
    a per-instance warning; only post-probe runtime failures warn."""
    try:
        if _faults.armed:
            _faults.maybe_fail("probe")
        jax.eval_shape(program, *args, **kwargs)
        return True
    except Exception as exc:  # noqa: BLE001 — any trace failure means "decline"
        # classified for telemetry (trace domain), still silent for the user
        _faults.note_fault("trace", site="probe", error=exc)
        return False


def _leaves_jittable(tree: Any) -> bool:
    """True when every leaf can be an argument of a jitted program: arrays or
    python scalars, and nothing already traced. String batches (text metrics)
    and other host objects fail here, which keeps them off the fused-path
    bookkeeping entirely — no signature reprs, no doomed trace attempts."""
    for leaf in jax.tree.flatten(tree)[0]:
        if isinstance(leaf, jax.core.Tracer):
            return False
        if not isinstance(leaf, (jax.Array, np.ndarray, np.generic, *_JITTABLE_SCALARS)):
            return False
    return True


class _DeferProbeDecline(Exception):
    """Internal: a deferred-flush scan program failed its eval_shape probe.

    Routed to the eager-replay fallback WITHOUT a warning — an untraceable
    configuration is supported, not an anomaly (the same silent-decline
    contract as the per-call fused paths); only post-probe runtime failures
    warn."""


def _degradable_sync_failure(exc: BaseException) -> bool:
    """Whether a failed sync may drop to the opt-in quorum-degraded tier
    (``METRICS_TPU_SYNC_DEGRADED=local``): transient transport faults —
    gather/collective failures and watchdog timeouts — qualify; structural
    config errors (``SyncConfigFault``) never do, degrading would mask a bug
    the operator must fix."""
    return isinstance(exc, SyncFault) and not isinstance(exc, SyncConfigFault)


def _note_degraded_serve(owner: Any) -> None:
    """Count one local-only compute served while the owner's ``sync-degrade``
    lane is down (per-owner tally + the global ``sync_degraded_serves``
    counter in ``engine_stats()``; an instant telemetry span marks it on the
    timeline)."""
    object.__setattr__(owner, "_degraded_serves", owner.__dict__.get("_degraded_serves", 0) + 1)
    _psync.note_degraded_serve("local")
    if _telemetry.armed:
        _telemetry.emit(
            "sync-degrade-serve", owner, "sync",
            attrs={"serves": owner.__dict__.get("_degraded_serves", 0)},
        )


def _note_quorum_serve(owner: Any, survivors: List[int]) -> None:
    """Count one surviving-quorum compute served while the owner's
    ``sync-degrade`` lane is down: the value aggregated over the surviving
    subgroup instead of the full world (per-owner tally + the global
    ``sync_quorum_serves`` counter; an instant span stamps the epoch and
    the cohort on the timeline)."""
    object.__setattr__(owner, "_quorum_serves", owner.__dict__.get("_quorum_serves", 0) + 1)
    _psync.note_degraded_serve("quorum")
    if _telemetry.armed:
        _telemetry.emit(
            "sync-quorum-serve", owner, "sync",
            attrs={
                "serves": owner.__dict__.get("_quorum_serves", 0),
                "epoch": _psync.world_epoch(),
                "survivors": list(survivors),
            },
        )


def _enter_degraded(owner: Any, exc: BaseException, tier: str = "local") -> None:
    """Drop ``owner`` to the degraded compute tier: demote its
    ``sync-degrade`` ladder lane (standard recovery edge — a healed transport
    promotes back to full sync automatically), stamp the degradation onset
    for ``sync_health()``, and warn once per owner+domain. The serve itself
    (local or quorum) is counted by the caller — entering the tier and
    serving under it are separate events."""
    serves = (
        "the surviving-QUORUM aggregate (the subgroup of ranks still alive; "
        "local-only if no quorum is known)"
        if tier == "quorum"
        else "the LOCAL-ONLY value"
    )
    _faults.demote(
        owner,
        "sync-degrade",
        exc,
        default_domain="sync",
        tier="eager",
        site="sync-degrade",
        # the failure was already counted at its raise site (Metric.sync /
        # MetricCollection.sync note it before re-raising) — the demotion
        # must not double it in the counters or the failure log
        count=False,
        warn=(
            f"Distributed sync failed for `{type(owner).__name__}` and "
            f"METRICS_TPU_SYNC_DEGRADED={tier} is set: compute() now serves {serves} "
            "(staleness metadata in sync_health()) until the sync-degrade lane's "
            "recovery edge re-probes the transport."
        ),
    )
    object.__setattr__(owner, "_degraded_since_step", _faults.current_step())


_checks_cached = None


def _checks_module():
    """metrics_tpu.utils.checks, resolved once (import cycle forbids a
    top-level import; an inline ``from ... import`` costs ~2 us per call)."""
    global _checks_cached
    if _checks_cached is None:
        from metrics_tpu.utils import checks as _checks_cached_

        _checks_cached = _checks_cached_
    return _checks_cached


class Metric(ABC):
    """Base class for all metrics.

    Subclasses implement :meth:`update` and :meth:`compute` and declare their
    accumulator states with :meth:`add_state`. States come in two kinds
    (reference `metric.py:202-216`):

    - **tensor kind** — a fixed-shape ``jax.Array`` accumulator with a reduction
      spec (``"sum" | "mean" | "max" | "min"`` or a callable);
    - **list kind** — an unbounded python list of arrays with ``"cat"``/``None``
      reduction (concatenated / stacked across devices at sync time).

    Constructor kwargs (reference `metric.py:93-117`):
        compute_on_cpu: move list states to host memory after each update to
            free HBM (reference ``compute_on_cpu``, `metric.py:404-414`).
        dist_sync_on_step: sync state when computing the batch value in
            ``forward`` (expensive; reference `metric.py:96-99`).
        process_group: host-path process subset — an iterable of process
            indices whose states merge at sync (all processes still call
            sync; see ``parallel.sync.gather_all_tensors``). The SPMD path
            expresses scope as a mesh axis instead (SURVEY §2.10).
        dist_sync_fn: custom gather callable (host path injection point).
        sync_on_compute: whether ``compute()`` syncs automatically.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import Metric
        >>> class CountPositives(Metric):
        ...     full_state_update = False
        ...     def __init__(self):
        ...         super().__init__()
        ...         self.add_state("count", default=jnp.asarray(0), dist_reduce_fx="sum")
        ...     def update(self, x):
        ...         self.count = self.count + (x > 0).sum()
        ...     def compute(self):
        ...         return self.count
        >>> metric = CountPositives()
        >>> metric(jnp.asarray([1.0, -2.0, 3.0]))
        Array(2, dtype=int32)
        >>> metric.update(jnp.asarray([5.0]))
        >>> metric.compute()
        Array(3, dtype=int32)
    """

    __jit_unused_properties__: List[str] = ["update_called", "update_count"]

    is_differentiable: Optional[bool] = None
    higher_is_better: Optional[bool] = None
    full_state_update: Optional[bool] = None
    _full_state_warned: set = set()  # class names already warned about unset full_state_update

    def __init__(
        self,
        *,
        compute_on_cpu: bool = False,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
        sync_on_compute: bool = True,
        **kwargs: Any,
    ) -> None:
        if kwargs:
            raise ValueError(f"Unexpected keyword arguments: {sorted(kwargs)}")
        if not isinstance(compute_on_cpu, bool):
            raise ValueError(f"Expected `compute_on_cpu` to be a bool, got {compute_on_cpu}")
        if not isinstance(dist_sync_on_step, bool):
            raise ValueError(f"Expected `dist_sync_on_step` to be a bool, got {dist_sync_on_step}")
        if dist_sync_fn is not None and not callable(dist_sync_fn):
            raise ValueError(f"Expected `dist_sync_fn` to be callable or None, got {dist_sync_fn}")
        if not isinstance(sync_on_compute, bool):
            raise ValueError(f"Expected `sync_on_compute` to be a bool, got {sync_on_compute}")
        if process_group is not None and not isinstance(process_group, str):
            # host-path groups (iterables of process indices) are materialized
            # and structure-checked at construction — one-shot iterables would
            # otherwise be consumed here and arrive exhausted at sync. Strings
            # (or tuples of strings) name SPMD mesh axes and pass through; the
            # range check against the process count runs at sync time, since
            # metrics may be constructed before jax.distributed initializes.
            from metrics_tpu.parallel.sync import _resolve_group, distributed_available, world_size

            is_axis_names = (
                isinstance(process_group, (tuple, list))
                and len(process_group) > 0
                and all(isinstance(g, str) for g in process_group)
            )
            if not is_axis_names:
                process_group = _resolve_group(
                    process_group, world_size() if distributed_available() else None
                )

        self.compute_on_cpu = compute_on_cpu
        self.dist_sync_on_step = dist_sync_on_step
        self.process_group = process_group
        self.dist_sync_fn = dist_sync_fn
        self.sync_on_compute = sync_on_compute

        self._defaults: Dict[str, Any] = {}
        self._reductions: Dict[str, Optional[Callable]] = {}
        self._reduction_specs: Dict[str, Optional[str]] = {}
        self._persistent: Dict[str, bool] = {}

        self._update_count: int = 0
        self._computed: Any = None
        self._forward_cache: Any = None
        self._is_synced: bool = False
        self._cache: Optional[Dict[str, Any]] = None
        self._to_sync: bool = self.sync_on_compute
        self._should_unsync: bool = True

        # wrap user update/compute with bookkeeping (reference `metric.py:121-122`)
        self.update: Callable = self._wrap_update(self.update)  # type: ignore[method-assign]
        self.compute: Callable = self._wrap_compute(self.compute)  # type: ignore[method-assign]
        # resolved once: whether this class opted into the host fast lane
        # (checking the override per update call would cost two attribute
        # walks on every eager step)
        object.__setattr__(
            self,
            "_has_update_lane_hook",
            type(self)._build_update_lane is not Metric._build_update_lane,
        )
        object.__setattr__(
            self,
            "_has_host_pending_hook",
            type(self)._host_pending_flush is not Metric._host_pending_flush,
        )

        # A subclass that leaves `full_state_update` unset silently takes the
        # two-update slow path in forward AND never engages the fused
        # single-dispatch program — warn once per class with the remedy
        # (reference `metric.py:139-151` warns likewise at construction)
        cls = type(self)
        cls_key = f"{cls.__module__}.{cls.__qualname__}"
        if (
            cls.full_state_update is None
            and cls.forward is Metric.forward
            and cls_key not in Metric._full_state_warned
        ):
            Metric._full_state_warned.add(cls_key)
            rank_zero_warn(
                f"Metric `{cls.__name__}` does not set `full_state_update`, so `forward` "
                "defaults to the slow two-update path and the fused single-dispatch "
                "program never engages. Set the class attribute `full_state_update=False` "
                "if `update` does not read pre-existing state (verify with "
                "`metrics_tpu.utils.checks.check_forward_full_state_property`), "
                "or `True` to silence this warning and keep the current behaviour."
            )

    # ------------------------------------------------------------------ state
    def add_state(
        self,
        name: str,
        default: Union[jax.Array, list],
        dist_reduce_fx: Union[str, Callable, None] = None,
        persistent: bool = False,
    ) -> None:
        """Register an accumulator state (reference ``add_state`` `metric.py:158-226`)."""
        if not name.isidentifier():
            raise ValueError(f"Argument `name` must be a valid python identifier, got {name!r}")
        is_list = isinstance(default, list)
        if is_list and len(default) != 0:
            raise ValueError("State defaults of list kind must be empty lists")
        if not is_list:
            # canonicalize to a STRONG dtype: `jnp.asarray(0.0)` is weak-typed,
            # and a weak-typed default state retraces every jitted consumer
            # (fused forward, `as_functions` update) on its second call, when
            # the first update's strong-typed result replaces it — one hidden
            # ~seconds recompile per metric on remote backends
            default = jnp.asarray(default)
            if getattr(default, "weak_type", False):
                default = jax.lax.convert_element_type(default, default.dtype)

        spec, fn = resolve_reduction(dist_reduce_fx)
        self._defaults[name] = default
        self._reductions[name] = fn
        self._reduction_specs[name] = spec
        self._persistent[name] = persistent
        self._fusable_cached = None  # state set changed; re-derive on next forward
        self.__dict__.pop("_default_ids_cache", None)  # donation guard re-derives
        setattr(self, name, list(default) if is_list else default)

    @property
    def update_called(self) -> bool:
        """Whether ``update``/``forward`` has been called since the last reset."""
        return self._update_count > 0

    @property
    def update_count(self) -> int:
        return self._update_count

    @property
    def metric_state(self) -> Dict[str, Any]:
        """Current state pytree (name -> array or list of arrays).

        An observation point: any pending deferred micro-batch flushes first
        (see :meth:`_defer_barrier`), so the returned values always reflect
        every ``update``/``forward`` call issued so far.
        """
        self._defer_barrier()
        return {name: getattr(self, name) for name in self._defaults}

    def _state_snapshot(self) -> Dict[str, Any]:
        # Arrays are immutable: holding references is a valid snapshot. Lists
        # are shallow-copied because update() appends in place.
        return {k: (list(v) if isinstance(v, list) else v) for k, v in self.metric_state.items()}

    def _restore_state(self, state: Dict[str, Any]) -> None:
        for name, value in state.items():
            setattr(self, name, list(value) if isinstance(value, list) else value)

    def _canonicalize_list_states(self) -> None:
        """Bring lazily-buffered list-state rows to canonical per-row form, in place.

        Cat-state metrics defer per-row canonicalization (flatten / dtype
        cast / layout formatting) out of ``update``: appending the raw input
        costs ~1 µs, while the reshape/cast dispatches cost hundreds of µs
        per step through a remote backend (docs/performance.md). ``compute``
        canonicalizes after concatenation — one fused program — but any
        consumer that observes *individual rows* needs them canonical:
        cross-device sync (rows must share rank for the pad-to-max gather
        protocol), ``state_dict`` (checkpoint layout stability), pickling.
        Those paths call this hook; overrides MUST be idempotent. Rows that
        were offloaded to host numpy (``compute_on_cpu``) must stay on host —
        use the row's own ``reshape``/``astype`` methods, not ``jnp``.
        """

    # ----------------------------------------------------------------- update
    @abstractmethod
    def update(self, *args: Any, **kwargs: Any) -> None:
        """Accumulate batch statistics into the metric state."""

    @abstractmethod
    def compute(self) -> Any:
        """Finalise the accumulated state into the metric value."""

    def _wrap_update(self, update: Callable) -> Callable:
        @functools.wraps(update)
        def wrapped(*args: Any, **kwargs: Any) -> None:
            # host fast lane (dispatch-engine tier for append-only metrics):
            # a closure bound at the first eager-validated call per signature
            # handles the steady-state update as a list append plus one cheap
            # branch — no checks-module resolution, no fusion gating, no
            # trace annotation. The lane returns False for anything it did
            # not pre-resolve (new signature, mode change), falling through
            # to the full path below. compute_on_cpu bypasses the lane at
            # call time: its per-update host offload must keep running even
            # if the flag was toggled after a lane was installed.
            lane = self._update_lane
            if lane is not None and not self.compute_on_cpu and lane(args, kwargs):
                if _telemetry.armed:
                    _telemetry.emit("host-lane", self, "host")
                return
            # lazily-resolved module handle: a `from ... import` here costs
            # ~2 us of import machinery on EVERY update
            _checks = _checks_module()
            _get_validation_mode = _checks._get_validation_mode

            self._computed = None
            self._update_count += 1
            # set when THIS call records a demotion: the call that failed
            # must not also count itself as a clean step toward recovery
            demoted_this_call = False
            # fused bare-update: for sum/mean/max/min array-state metrics the
            # whole update runs as ONE cached jitted program per input
            # signature (same gating contract as the fused forward: first
            # call per signature is eager and fully validated; "full"
            # validation mode keeps every call eager)
            signature = None
            if (
                self._fused_update_ok
                and not self._suppress_update_fusion
                and _get_validation_mode() != "full"
                and self._fusable_states()
                and _leaves_jittable((args, kwargs))
            ):
                if self._fused_seen_signatures is None:
                    self._fused_seen_signatures = {}
                signature = ("__update__", self._forward_signature(args, kwargs))
                run_fused = False
                if signature in self._fused_seen_signatures:
                    # deferred micro-batched dispatch: an eager-validated
                    # signature enqueues instead of dispatching — the queue
                    # flushes as ONE stacked lax.scan program at the size/age
                    # threshold or at the next state observation.
                    # METRICS_TPU_DEFER=0 restores the per-call dispatch.
                    if (
                        self._defer_ok
                        and not self._defer_suspended
                        and _engine.defer_enabled()
                        and self._defer_stackable(args, kwargs)
                    ):
                        self._defer_enqueue_update(signature, args, kwargs)
                        return
                    state = {name: getattr(self, name) for name in self._defaults}
                    try:
                        program = self._fused_update_program
                        if program is None:
                            program = self._build_fused_update()
                            if _probe_traceable(program, state, *args, **kwargs):
                                self._license_fused_signature(signature)
                                object.__setattr__(self, "_fused_update_program", program)
                            else:
                                # probe declined: plain eager from here on —
                                # silent (trace domain is structural), but the
                                # ladder records the demotion for telemetry
                                self._fault_silent_decline("update")
                                object.__setattr__(self, "_fused_update_ok", False)
                                object.__setattr__(self, "_fused_update_template", None)
                                signature = None
                            run_fused = self._fused_update_program is not None
                        elif isinstance(program, _engine.Executable):
                            # each FIRST-SEEN signature is probed before it runs
                            # fused: an untraceable second signature declines
                            # silently (eager for that signature only) instead of
                            # surfacing as a runtime-failure warning
                            run_fused = self._signature_licensed(
                                signature, program, state, *args, **kwargs
                            )
                        else:
                            run_fused = True  # foreign program (tests): run as-is
                    except Exception as exc:  # noqa: BLE001 — acquire/build (compile-domain) failure
                        _faults.demote(
                            self,
                            "update",
                            exc,
                            default_domain="compile",
                            site="compile",
                            warn=(
                                f"Building the fused update program for `{type(self).__name__}` "
                                f"failed ({type(exc).__name__}: {exc}). Falling back to the "
                                "eager per-op update for this instance; the degradation "
                                "ladder re-probes the fused path after clean steps."
                            ),
                        )
                        object.__setattr__(self, "_fused_update_ok", False)
                        object.__setattr__(self, "_fused_update_program", None)
                        object.__setattr__(self, "_fused_update_template", None)
                        run_fused = False
                        demoted_this_call = True
                        signature = None  # already recorded when first licensed
                if run_fused:
                    try:
                        runner = getattr(self._fused_update_program, "run", None)
                        if runner is not None:
                            new_state = runner(
                                state, args, kwargs, avoid_ids=self._default_leaf_ids()
                            )
                        else:
                            new_state = self._fused_update_program(state, *args, **kwargs)
                    except Exception as exc:  # noqa: BLE001 — post-probe runtime failure
                        if not _engine.state_intact(state):
                            # the failing call donated the state buffers away;
                            # an eager retry would read deleted arrays — the
                            # instance cannot recover, surface that plainly
                            _faults.note_fault("donation", site="fused-update", owner=self, error=exc)
                            raise RuntimeError(
                                f"Fused update for `{type(self).__name__}` failed after "
                                f"donating its state buffers ({type(exc).__name__}: {exc}); "
                                "the accumulated state is unrecoverable — construct a "
                                "fresh instance."
                            ) from exc
                        _faults.demote(
                            self,
                            "update",
                            exc,
                            site="fused-update",
                            warn=(
                                f"Fused update for `{type(self).__name__}` raised "
                                f"{type(exc).__name__}: {exc}. Falling back to the eager "
                                "per-op update for this instance; the degradation ladder "
                                "re-probes the fused path after clean steps."
                            ),
                        )
                        object.__setattr__(self, "_fused_update_ok", False)
                        object.__setattr__(self, "_fused_update_program", None)
                        object.__setattr__(self, "_fused_update_template", None)
                        demoted_this_call = True
                    else:
                        for name, value in new_state.items():
                            object.__setattr__(self, name, value)  # state leaves: no version logic
                        _propagate_static_attrs(self._fused_update_template, self)
                        self._fault_note_clean()
                        return
            # TraceAnnotation shows up in jax.profiler / xprof timelines —
            # the analogue of the reference's TorchScript profiling markers
            # (SURVEY §5 "Tracing / profiling")
            prev_owner = _checks._check_owner
            _checks._check_owner = self  # scope "first"-mode memory per instance
            try:
                with jax.profiler.TraceAnnotation(f"{type(self).__name__}.update"):
                    update(*args, **kwargs)
            finally:
                _checks._check_owner = prev_owner
            if signature is not None:
                # recorded only AFTER the eager call validated this signature
                self._record_fused_signature(signature)
            if self.compute_on_cpu:
                if self._move_list_states_to_host():
                    demoted_this_call = True
            elif self._has_update_lane_hook and _get_validation_mode() != "full":
                # the eager pass validated this call: let the metric bind its
                # steady-state append closure for this signature
                self._install_update_lane(args, kwargs)
            # one clean step at whatever tier this call ran: demoted lanes
            # (fused update/forward, deferral, host offload) count toward
            # their recovery edge here — unless this very call demoted one
            if not demoted_this_call:
                self._fault_note_clean()

        return wrapped

    def _build_fused_update(self) -> "_engine.Executable":
        """One compiled program for a bare ``update`` call: restore state into
        a template clone, run the real update, return the new state pytree.

        Served by the dispatch engine: identically-configured instances share
        one program (and its jit aval cache), and each step donates the
        incoming state buffers so XLA updates the accumulators in place."""

        def build():
            template = self._bare_clone()

            def ustep(state: Dict[str, Any], *args: Any, **kwargs: Any) -> Dict[str, Any]:
                m = template._bare_clone()
                m._restore_state(state)
                m._inner_update(*args, **kwargs)
                _propagate_static_attrs(m, template)
                return m._state_snapshot()

            return ustep, template, {}

        exe = _engine.acquire(self, "update", build)
        object.__setattr__(self, "_fused_update_template", exe.template)
        return exe

    def _move_list_states_to_host(self) -> bool:
        """Offload list states to host RAM to free HBM (``compute_on_cpu`` analogue).

        Host-offload is its own failure domain: a failed device→host move
        demotes this owner's ``host`` lane — the rows simply STAY on device
        (numerically identical, just holding HBM) and the ladder re-probes
        the offload after clean steps. The offload is staged (convert every
        row, then assign) so a mid-move failure never leaves a state
        half-offloaded. Returns True when THIS call demoted the lane (the
        caller must not count the failing call as a clean step)."""
        if not self._host_offload_ok:
            return False  # demoted: keep rows on device until the ladder recovers
        try:
            if _faults.armed:
                _faults.maybe_fail("host-offload")
            moved = {}
            for name in self._defaults:
                value = getattr(self, name)
                if isinstance(value, list):
                    moved[name] = [np.asarray(jax.device_get(v)) for v in value]
        except Exception as exc:  # noqa: BLE001 — classified; state untouched
            _faults.demote(
                self,
                "host",
                exc,
                default_domain="host",
                site="host-offload",
                warn=(
                    f"Host offload (compute_on_cpu) for `{type(self).__name__}` raised "
                    f"{type(exc).__name__}: {exc}. Keeping list states on device for "
                    "this instance; the degradation ladder re-probes the offload "
                    "after clean steps."
                ),
            )
            object.__setattr__(self, "_host_offload_ok", False)
            return True
        for name, rows in moved.items():
            setattr(self, name, rows)
        return False

    # ---------------------------------------------------------------- forward
    def __call__(self, *args: Any, **kwargs: Any) -> Any:
        return self.forward(*args, **kwargs)

    def forward(self, *args: Any, **kwargs: Any) -> Any:
        """Compute the metric on the batch AND accumulate into global state.

        Returns the batch-local value (same contract as reference
        ``forward`` `metric.py:228-247`).
        """
        if self._is_synced:
            raise MetricsUserError(
                "The Metric shouldn't be synced when performing `forward`. "
                "HINT: Did you forget to call `unsync()`?"
            )
        if self.full_state_update or self.full_state_update is None or self.dist_sync_on_step:
            self._forward_cache = self._forward_full_state_update(*args, **kwargs)
        else:
            self._forward_cache = self._forward_reduce_state_update(*args, **kwargs)
        return self._forward_cache

    def _forward_full_state_update(self, *args: Any, **kwargs: Any) -> Any:
        """Two-update path: metrics whose update depends on pre-existing state."""
        entry_state = self._state_snapshot()
        entry_count = self._update_count
        compute_on_cpu = self.compute_on_cpu
        try:
            self.update(*args, **kwargs)
            update_count = self._update_count

            self._to_sync = self.dist_sync_on_step
            self._should_unsync = False
            self.compute_on_cpu = False

            cache = self._state_snapshot()
            self.reset()
            self.update(*args, **kwargs)
            batch_val = self.compute()

            self._restore_state(cache)
            self._update_count = update_count
        except Exception:
            # a bad batch must not corrupt accumulated history (the first
            # update may have partially mutated it, and the reset below it
            # zeroes everything): restore the entry snapshot before surfacing
            self._restore_state(entry_state)
            self._update_count = entry_count
            raise
        finally:
            self._is_synced = False
            self._should_unsync = True
            self._to_sync = self.sync_on_compute
            self._computed = None
            self.compute_on_cpu = compute_on_cpu
        return batch_val

    # class-level defaults so unpickled/copied instances lazily rebuild
    _fused_forward: Optional[Callable] = None
    _fused_template: Optional["Metric"] = None
    _fused_forward_ok: bool = True
    # fused BARE-update path (no batch compute/merge): `metric.update(...)`
    # loops pay one program dispatch per step instead of the eager
    # canonicalization op-stream; health tracked independently of forward
    _fused_update_program: Optional[Callable] = None
    _fused_update_template: Optional["Metric"] = None
    _fused_update_ok: bool = True
    # set by the batched-step eager loop: its per-step update calls must not
    # register per-step signatures or compile the single-step program the
    # scan path will never use (same hygiene as force_reduce_eager)
    _suppress_update_fusion: bool = False
    _fused_needs_count: bool = True  # set on build; True passes update_count
    _fused_seen_signatures: Optional[dict] = None
    _fused_version: int = 0  # bumped on invalidation; lets collections detect staleness
    _FUSED_SIG_CAP = 4096
    # per-signature eval_shape verdicts for engine programs: a signature that
    # fails to trace declines fusion silently for ITSELF without poisoning
    # signatures already licensed (round-5 silent-decline contract)
    _fused_probe_results: Optional[dict] = None
    # host fast lane (see _wrap_update): closure bound per signature by
    # metrics that override _build_update_lane
    _update_lane: Optional[Callable] = None
    _has_update_lane_hook: bool = False

    # deferred micro-batched dispatch (engine.PendingQueue): while a queue is
    # pending the state attributes live in the queue's backing store, not in
    # __dict__, so ANY state access lands in __getattr__ and flushes — the
    # observation barrier is total by construction. _defer_ok is the
    # per-instance health flag (a failed flush replays eagerly and disables
    # deferral permanently, degrading to the PR-1 per-call fused dispatch);
    # _defer_suspended blocks re-enqueueing while a flush is replaying.
    _defer_pending: Optional["_engine.PendingQueue"] = None
    _defer_ok: bool = True
    _defer_suspended: bool = False

    # host-offload health (compute_on_cpu device→host moves): its own ladder
    # lane — a failed offload keeps rows on device instead of failing updates
    _host_offload_ok: bool = True

    _fusable_cached: Optional[bool] = None

    # --------------------------------------------------- failure-domain ladder
    # Per-lane degradation state (ops.faults.Ladder) replaces the old
    # "fail once → warn forever" flags semantics: the boolean flags above
    # still gate the hot paths (zero new cost per step), but every demotion
    # is recorded with its classified domain, warnings dedupe per
    # owner+domain, and recoverable domains (compile/runtime/donation — the
    # transient ones) earn a recovery edge: after N clean steps the lane's
    # flag is re-armed and the path re-probes (exponential backoff on
    # repeated failures). Trace-domain declines stay silent and permanent
    # (the round-5 silent-decline contract).
    def _fault_silent_decline(self, lane: str) -> None:
        """Record a probe decline: trace domain, no warning, no recovery."""
        _faults.ladder(self, lane).demote("trace")

    def _fault_note_clean(self, n: int = 1) -> None:
        """Count ``n`` clean steps for every demoted lane; re-arm the lanes
        whose recovery edge fires. Costs one dict lookup when no lane was
        ever demoted."""
        ladders = self.__dict__.get("_fault_ladders")
        if not ladders:
            return
        for lane, lad in list(ladders.items()):
            if lad.demoted and lad.note_clean(n):
                self._fault_repromote(lane, lad)

    def _fault_repromote(self, lane: str, lad: "_faults.Ladder") -> None:
        """The recovery edge: re-arm the demoted path so the next eligible
        call re-probes it (cached programs may still exist in the engine —
        re-entry costs a cache hit plus one ``eval_shape``)."""
        lad.promote()
        if lane == "update":
            object.__setattr__(self, "_fused_update_ok", True)
            object.__setattr__(self, "_fused_update_program", None)
            object.__setattr__(self, "_fused_update_template", None)
        elif lane == "forward":
            object.__setattr__(self, "_fused_forward_ok", True)
            object.__setattr__(self, "_fused_forward", None)
            object.__setattr__(self, "_fused_template", None)
        elif lane == "defer":
            object.__setattr__(self, "_defer_ok", True)
        elif lane == "many":
            object.__setattr__(self, "_many_ok", True)
            object.__setattr__(self, "_many_program_vals", None)
            object.__setattr__(self, "_many_program_novals", None)
            object.__setattr__(self, "_many_template_vals", None)
            object.__setattr__(self, "_many_template_novals", None)
        elif lane == "host":
            object.__setattr__(self, "_host_offload_ok", True)
        elif lane.startswith("fanout:"):
            _, ok_attr, program_attr = lane.split(":", 2)
            object.__setattr__(self, ok_attr, True)
            object.__setattr__(self, program_attr, None)
        # probe verdicts were issued for the pre-failure regime; the re-armed
        # path must re-probe before it is trusted again
        object.__setattr__(self, "_fused_probe_results", None)
        probed = self.__dict__.get("_defer_probed")
        if probed is not None:
            probed.clear()

    # ------------------------------------------- deferred dispatch barriers
    def _defer_barrier(self) -> None:
        """Flush any pending deferred micro-batch, then fold any host-side
        pending buffer (:meth:`_host_pending_flush`) — the ONE observation
        hook every state-materializing surface routes through."""
        q = self.__dict__.get("_defer_pending")
        if q is not None:
            q.flush()
        if self._has_host_pending_hook:
            self._host_pending_flush()

    def _host_pending_flush(self) -> None:
        """Hook: fold host-staged pending accumulation into device state.

        Append-only metrics that buffer host scalars between observations
        (``SQuAD``'s EM/F1 counters) override this; the base class is a
        no-op. Runs at every observation barrier — must be idempotent.
        """

    # resolved per class like _has_update_lane_hook: avoids a no-op method
    # call on every barrier for the ~all metrics that don't buffer host state
    _has_host_pending_hook: bool = False

    def __getattr__(self, name: str) -> Any:
        # Only reached for MISSING attributes — zero cost on every normal
        # lookup. While a deferred queue is pending, the state attributes are
        # popped out of __dict__ into the queue's backing store, so ANY state
        # read (compute, sync, a user's `metric.total`, state_dict, pickle)
        # lands here and flushes in enqueue order.
        d = self.__dict__
        # bounded loop: a flush's eager replay may legitimately re-enqueue
        # (a collection flush replaying through member updates), installing a
        # fresh queue that pops the state again — flush until settled
        for _ in range(8):
            q = d.get("_defer_pending")
            if q is None or not q.has_state(self, name):
                break
            q.flush()
            if name in d:
                return d[name]
        raise AttributeError(f"{type(self).__name__!r} object has no attribute {name!r}")

    def _record_fused_signature(self, signature: tuple) -> None:
        """Record an eager-validated input signature in the FIFO-capped cache
        (single source of truth for the cap/eviction policy)."""
        self._fused_seen_signatures[signature] = None
        while len(self._fused_seen_signatures) > self._FUSED_SIG_CAP:
            # FIFO: evict the OLDEST signature (set.pop would be arbitrary
            # and could flap the hot signature out of the cache)
            self._fused_seen_signatures.pop(next(iter(self._fused_seen_signatures)))

    def _license_fused_signature(self, signature: tuple) -> None:
        """Mark a signature as probe-licensed for the fused program."""
        results = self._fused_probe_results
        if results is None:
            results = {}
            object.__setattr__(self, "_fused_probe_results", results)
        results[signature] = True

    def _signature_licensed(self, signature: tuple, program: Callable, *probe_args: Any, **probe_kwargs: Any) -> bool:
        """Probe verdict for a signature against an engine program (cached).

        Every FIRST-SEEN signature gets its own ``jax.eval_shape`` probe
        before running fused; an untraceable one is recorded as declined —
        the call (and every later call with that signature) takes the eager
        path quietly, while licensed signatures keep their fused program.
        """
        results = self._fused_probe_results
        if results is None:
            results = {}
            object.__setattr__(self, "_fused_probe_results", results)
        ok = results.get(signature)
        if ok is None:
            ok = _probe_traceable(program, *probe_args, **probe_kwargs)
            results[signature] = ok
            while len(results) > self._FUSED_SIG_CAP:
                results.pop(next(iter(results)))
        return ok

    def _default_leaf_ids(self) -> frozenset:
        """ids of the registered default-state arrays — buffers that must
        NEVER be donated: ``reset()`` hands the default object back as live
        state, and donating it would delete the template every future reset
        restores. Cached; ``add_state`` invalidates via ``_fusable_cached``'s
        companion slot."""
        ids = self.__dict__.get("_default_ids_cache")
        if ids is None:
            ids = frozenset(id(leaf) for leaf in jax.tree.flatten(self._defaults)[0])
            object.__setattr__(self, "_default_ids_cache", ids)
        return ids

    # ------------------------------------------- deferred micro-batched dispatch
    @staticmethod
    def _defer_stackable(args: tuple, kwargs: dict) -> bool:
        """At least one array leaf to stack along the steps axis — calls made
        of python scalars only have nothing to scan over and keep the
        per-call dispatch path."""
        return any(hasattr(leaf, "shape") for leaf in jax.tree.flatten((args, kwargs))[0])

    def _defer_probe(self, kind: str, layout, program, *probe_args) -> None:
        """eval_shape the flush program once per (kind, layout); an
        untraceable one raises :class:`_DeferProbeDecline` so the flush
        replays eagerly with no warning."""
        probed = self.__dict__.get("_defer_probed")
        if probed is None:
            probed = set()
            object.__setattr__(self, "_defer_probed", probed)
        key = (kind, layout)
        if key in probed:
            return
        if not _probe_traceable(program, *probe_args):
            raise _DeferProbeDecline()
        probed.add(key)

    def _defer_enqueue_update(self, signature: tuple, args: tuple, kwargs: dict) -> None:
        """Enqueue one bare ``update`` call (count/cache bookkeeping already
        done by the wrapper). A kind- or signature-mismatched pending queue
        flushes first, so mixed call streams stay in enqueue order."""
        q = self.__dict__.get("_defer_pending")
        if q is not None and not q.matches("update", signature):
            q.flush()
            q = None
        if q is None:
            q = _engine.PendingQueue("update", signature, self._flush_update_queue)
            q.adopt(self, self._defaults)
        q.entries.append((args, kwargs))
        q.handles.append(None)
        _engine.note_deferred_steps(1)
        if q.should_flush():
            q.flush()

    def _defer_enqueue_forward(self, signature: tuple, args: tuple, kwargs: dict) -> Any:
        """Enqueue one reduce-path ``forward`` call and return its
        :class:`engine.LazyValue` handle — the flush runs only when the
        handle (or any state) is actually read."""
        q = self.__dict__.get("_defer_pending")
        if q is not None and not q.matches("forward", signature):
            q.flush()
            q = None
        if q is None:
            q = _engine.PendingQueue("forward", signature, self._flush_forward_queue)
            q.adopt(self, self._defaults)
        handle = _engine.LazyValue(q)
        q.entries.append((args, kwargs))
        q.handles.append(handle)
        _engine.note_deferred_steps(1)
        self._update_count += 1
        self._is_synced = False
        self._should_unsync = True
        self._to_sync = self.sync_on_compute
        self._computed = None
        object.__setattr__(self, "_forward_cache", handle)
        if q.should_flush():
            q.flush()
        return handle

    def _deferred_chunks(self, entries: list):
        """Yield ``(offset, chunk_len, scan pieces)`` for the queued calls in
        power-of-two buckets — the scan programs compile at most
        ~log2(max_pending) step-axis shapes per signature, however raggedly
        an observation lands mid-queue."""
        offset = 0
        for chunk_index, chunk_len in enumerate(_engine.pow2_chunks(len(entries))):
            # "flush-chunk" fault site (indexed: flush-chunk-<k>): fires while
            # PREPARING chunk k, i.e. BETWEEN applied chunks — the exact spot
            # the applied-chunks counters exist to protect (a fallback must
            # never replay an already-applied chunk)
            if _faults.armed:
                _faults.maybe_fail("flush-chunk", index=chunk_index)
            a_s, k_s = _engine.stack_entries(entries, offset, chunk_len)
            python_leaves, treedef, scanned_idx, aconst_idx, scanned, aconsts = (
                self._split_many_leaves(a_s, k_s)
            )
            layout = (treedef, tuple(scanned_idx), tuple(aconst_idx), repr(python_leaves))
            yield offset, chunk_len, layout, python_leaves, treedef, scanned_idx, aconst_idx, scanned, aconsts
            offset += chunk_len

    def _flush_update_queue(self, q: "_engine.PendingQueue") -> None:
        """Run a pending bare-update queue as stacked scan program(s).

        Bit-exact by construction: the scan body is exactly the fused
        bare-update step (restore state → ``_inner_update`` → snapshot), so a
        flushed queue equals the same calls dispatched one-by-one. On any
        trace/compile failure the remaining entries replay eagerly and
        deferral is disabled for this instance (degrades to PR-1 per-call
        dispatch)."""
        entries = q.entries
        backing = q.backing.get(id(self), {})
        state = {name: backing[name] for name in self._defaults}
        # `applied` advances only AFTER a chunk's program ran: a failure while
        # PREPARING the next chunk (stacking, probing) must not make the
        # fallback replay an already-applied chunk
        applied = 0
        template = None
        object.__setattr__(self, "_defer_suspended", True)
        try:
            try:
                for (offset, chunk_len, layout, python_leaves, treedef, scanned_idx,
                     aconst_idx, scanned, aconsts) in self._deferred_chunks(entries):
                    program = _engine.acquire(
                        self,
                        "deferred-update",
                        self._build_deferred_update(python_leaves, treedef, scanned_idx, aconst_idx),
                        extra_key=(layout,),
                    )
                    self._defer_probe("update", layout, program, state, scanned, aconsts)
                    template = program.template
                    state = program.run(
                        state, (scanned, aconsts), avoid_ids=self._default_leaf_ids()
                    )
                    applied = offset + chunk_len
            except Exception as exc:  # noqa: BLE001 — scan decline → eager replay
                if not _engine.state_intact(state):
                    _faults.note_fault("donation", site="deferred-flush", owner=self, error=exc)
                    raise RuntimeError(
                        f"Deferred update flush for `{type(self).__name__}` failed after "
                        f"donating its state buffers ({type(exc).__name__}: {exc}); the "
                        "accumulated state is unrecoverable — construct a fresh instance."
                    ) from exc
                q.release()
                for name, value in state.items():
                    object.__setattr__(self, name, value)
                object.__setattr__(self, "_defer_ok", False)
                if isinstance(exc, _DeferProbeDecline):
                    self._fault_silent_decline("defer")
                else:
                    _faults.demote(
                        self,
                        "defer",
                        exc,
                        tier="chunked",
                        site="deferred-flush",
                        warn=(
                            f"Deferred update flush for `{type(self).__name__}` raised "
                            f"{type(exc).__name__}: {exc}. Replaying the queue eagerly and "
                            "disabling deferred dispatch for this instance; the degradation "
                            "ladder re-probes deferral after clean steps."
                        ),
                    )
                _engine.note_deferred_flush(fallback=True)
                done = applied
                try:
                    for a, k in entries[applied:]:
                        self._inner_update(*a, **k)
                        done += 1
                except Exception:
                    # entries past the failing one never applied: their
                    # enqueue-time count increments are rolled back so the
                    # count matches eager semantics at the raise point
                    self._update_count -= len(entries) - done - 1
                    raise
                return
            q.release()
            for name, value in state.items():
                object.__setattr__(self, name, value)
            if template is not None:
                _propagate_static_attrs(template, self)
            _engine.note_deferred_flush()
            # a fully-applied flush = len(entries) clean steps toward any
            # demoted lane's recovery edge
            self._fault_note_clean(len(entries))
        finally:
            object.__setattr__(self, "_defer_suspended", False)

    def _build_deferred_update(self, python_leaves, treedef, scanned_idx, aconst_idx):
        """Engine build closure: a ``lax.scan`` over the fused bare-update
        step — the deferred-queue analogue of ``_build_fused_update``."""

        def build():
            template = self._bare_clone()

            def scan_program(state: Dict[str, Any], xs, const_vals):
                def body(st, xs_leaves):
                    step_leaves = list(python_leaves)
                    for i, leaf in zip(scanned_idx, xs_leaves):
                        step_leaves[i] = leaf
                    for i, leaf in zip(aconst_idx, const_vals):
                        step_leaves[i] = leaf
                    a, k = jax.tree.unflatten(treedef, step_leaves)
                    m = template._bare_clone()
                    m._restore_state(st)
                    m._inner_update(*a, **k)
                    _propagate_static_attrs(m, template)
                    return m._state_snapshot(), 0

                final, _ = jax.lax.scan(body, state, xs)
                return final

            return scan_program, template, {}

        return build

    def _flush_forward_queue(self, q: "_engine.PendingQueue") -> None:
        """Run a pending forward queue through the SAME donated-state scan
        programs ``forward_many`` compiles (shared engine cache keys), fill
        each entry's :class:`engine.LazyValue` with its per-step batch value,
        and write the merged state back."""
        entries = q.entries
        handles = q.handles
        count0 = self._update_count - len(entries)
        backing = q.backing.get(id(self), {})
        state = {name: backing[name] for name in self._defaults}
        applied = 0  # advanced only after a chunk's program ran (see update flush)
        template = None
        object.__setattr__(self, "_defer_suspended", True)
        try:
            try:
                for (offset, chunk_len, layout, python_leaves, treedef, scanned_idx,
                     aconst_idx, scanned, aconsts) in self._deferred_chunks(entries):
                    program = self._acquire_many_program(
                        True, layout, python_leaves, treedef, scanned_idx, aconst_idx
                    )
                    self._defer_probe(
                        "forward", layout, program, state, count0 + offset, scanned, aconsts
                    )
                    template = program.template
                    state, values = program.run(
                        state,
                        (count0 + offset, scanned, aconsts),
                        avoid_ids=self._default_leaf_ids(),
                    )
                    for j in range(chunk_len):
                        handles[offset + j]._set_chunk(values, j)
                    applied = offset + chunk_len
            except Exception as exc:  # noqa: BLE001 — scan decline → eager replay
                if not _engine.state_intact(state):
                    _faults.note_fault("donation", site="deferred-flush", owner=self, error=exc)
                    raise RuntimeError(
                        f"Deferred forward flush for `{type(self).__name__}` failed after "
                        f"donating its state buffers ({type(exc).__name__}: {exc}); the "
                        "accumulated state is unrecoverable — construct a fresh instance."
                    ) from exc
                q.release()
                for name, value in state.items():
                    object.__setattr__(self, name, value)
                object.__setattr__(self, "_defer_ok", False)
                # replay re-runs the eager forward per entry, which
                # re-increments the count from the replay point
                self._update_count = count0 + applied
                if isinstance(exc, _DeferProbeDecline):
                    self._fault_silent_decline("defer")
                else:
                    _faults.demote(
                        self,
                        "defer",
                        exc,
                        tier="chunked",
                        site="deferred-flush",
                        warn=(
                            f"Deferred forward flush for `{type(self).__name__}` raised "
                            f"{type(exc).__name__}: {exc}. Replaying the queue eagerly and "
                            "disabling deferred dispatch for this instance; the degradation "
                            "ladder re-probes deferral after clean steps."
                        ),
                    )
                _engine.note_deferred_flush(fallback=True)
                for j in range(applied, len(entries)):
                    a, k = entries[j]
                    handles[j]._set_value(self._forward_reduce_state_update_eager(*a, **k))
                return
            q.release()
            for name, value in state.items():
                object.__setattr__(self, name, value)
            if template is not None:
                _propagate_static_attrs(template, self)
            _engine.note_deferred_flush()
            # a fully-applied flush = len(entries) clean steps toward any
            # demoted lane's recovery edge
            self._fault_note_clean(len(entries))
        finally:
            object.__setattr__(self, "_defer_suspended", False)

    # ----------------------------------------------------- host fast lane
    def _build_update_lane(self, args: tuple, kwargs: dict) -> Optional[Callable]:
        """Hook: return a bound closure handling steady-state updates for the
        just-validated ``(args, kwargs)`` signature, or None.

        The closure receives ``(args, kwargs)`` and returns True when it
        fully handled the update (including ``_update_count``/``_computed``
        bookkeeping), False to fall through to the full path. Append-only
        metrics (CatMetric, retrieval, raw-state curves, SQuAD) override
        this; the base class opts out.
        """
        return None

    def _install_update_lane(self, args: tuple, kwargs: dict) -> None:
        try:
            lane = self._build_update_lane(args, kwargs)
        except Exception:  # noqa: BLE001 — a lane is an optimization, never a failure
            lane = None
        if lane is not None:
            object.__setattr__(self, "_update_lane", lane)

    def _lane_guard(self) -> Callable[[], bool]:
        """Shared lane-invalidation check: a validation-mode change (any
        ``set_validation_mode`` call bumps the generation) must kill every
        installed lane so "full" mode regains per-call checks."""
        checks = _checks_module()
        generation = checks._cache_generation

        def still_valid() -> bool:
            if checks._cache_generation != generation:
                object.__setattr__(self, "_update_lane", None)
                return False
            return True

        return still_valid

    def _fusable_states(self) -> bool:
        """True when every state merges by sum/mean/max/min (no list states).

        Cached after first evaluation (states are declared in ``__init__``
        via ``add_state``, which clears the cache) — this sits on the
        per-step forward hot path.
        """
        if self._fusable_cached is None:
            # a metric with NO own states (child-holding wrappers) must never
            # count as fusable: its exported update is an empty-state no-op
            # that XLA dead-code-eliminates, silently dropping child updates
            self._fusable_cached = bool(self._defaults) and not any(
                isinstance(v, list) for v in self._defaults.values()
            ) and all(
                self._reduction_specs[name] in ("sum", "mean", "max", "min") for name in self._defaults
            )
        return self._fusable_cached

    @staticmethod
    def _forward_signature(args: tuple, kwargs: dict) -> tuple:
        """Key a forward call by its input shapes/dtypes (arrays) and values
        (python leaves).

        Known limitation: a NON-array leaf that varies per call (a step
        counter passed as a python int, a changing string) yields a new
        signature every step, so such a metric never takes the fused path and
        churns the FIFO signature cache — which is also semantically correct:
        a python leaf is baked into the trace as a constant, so every distinct
        value would force a retrace anyway. Pass per-step-varying values as
        0-d ``jax.Array``s to make them traced inputs instead. Long reprs are
        reduced to their hash (not retained); a hash collision between two
        long reprs would skip the one-time eager validation pass for the
        second one — validation mode "full" validates every call regardless.
        """

        def leaf(a: Any):
            if hasattr(a, "shape") and hasattr(a, "dtype"):
                # the dtype OBJECT is hashable and cheap; stringifying it costs
                # ~10 us per leaf through numpy's name machinery — measurable
                # on the per-step hot path
                return (tuple(a.shape), a.dtype)
            r = repr(a)
            # long non-array reprs are hashed, not retained (the signature
            # set would otherwise pin arbitrarily large strings)
            return r if len(r) <= 64 else hash(r)

        return tuple(leaf(a) for a in args) + tuple((k, leaf(v)) for k, v in sorted(kwargs.items()))

    def _build_fused_step(self) -> Tuple["Metric", Callable]:
        """(template, UNJITTED step fn) for the fused forward — also composed
        by MetricCollection into one whole-suite program."""
        if not self._fusable_states():
            raise TypeError("only sum/mean/max/min array states fuse")
        template = self._bare_clone()
        specs = {name: self._reduction_specs[name] for name in self._defaults}
        # resolve the merge table OUTSIDE the closure: engine-cached programs
        # outlive their first acquiring instance, and a `self` cell in the
        # step would pin that instance (and its accumulated state buffers)
        # in the global cache for the program's whole lifetime
        merge_leaf = self._merge_leaf

        def step(state: Dict[str, Any], update_count: jax.Array, *args: Any, **kwargs: Any):
            m = template._bare_clone()
            m._inner_update(*args, **kwargs)
            _propagate_static_attrs(m, template)
            batch_state = m._state_snapshot()
            batch_value = m._inner_compute()
            merged = {
                name: merge_leaf(spec, state[name], batch_state[name], update_count)
                for name, spec in specs.items()
            }
            return merged, batch_value

        return template, step

    def _build_fused_forward(self) -> Callable:
        """One jitted program for the whole reduce-path forward: batch update
        from the default state + batch compute + merge into the global state.

        The eager forward issues ~20-30 tiny device ops per step (snapshot,
        reset, update, compute, merge) — each a dispatch round trip, which is
        what per-step overhead IS on remote/tunneled backends. Fused, a step
        is ONE dispatch. Only simple reductions fuse (sum/mean/max/min over
        array states); list/cat states grow (retrace per step) and custom
        reductions may not be traceable, so those metrics keep the eager path.

        Served by the dispatch engine: the program is shared across every
        identically-configured instance and donates the incoming global-state
        buffers per step (the merged state is written in place).
        """

        def build():
            template, step = self._build_fused_step()
            needs_count = any(spec == "mean" for spec in self._reduction_specs.values())
            if needs_count:
                fn = step
            else:
                # only "mean" merges read update_count; eliding the argument
                # saves a per-step host->device scalar canonicalization+
                # transfer on the dispatch hot path (measured ~0.2 ms/step on
                # the tunneled backend)
                def fn(state, *args, **kwargs):
                    return step(state, 0, *args, **kwargs)

            return fn, template, {"needs_count": needs_count}

        exe = _engine.acquire(self, "forward", build)
        self._fused_template = exe.template
        self._fused_needs_count = exe.aux["needs_count"]
        return exe

    # ------------------------------------------------- batched-step (scan) API
    # Even the fused forward pays one dispatch round trip per step, and on
    # remote/tunneled backends a D2H value read (any `float(metric.compute())`)
    # permanently stops the backend overlapping dependent dispatches — each
    # step then costs a full round trip (~ms). `update_many`/`forward_many`
    # take inputs with a leading steps axis and run ALL steps as one
    # `lax.scan` program: one dispatch per chunk, amortizing the round trip
    # to chunk_len⁻¹ of a step. This is the per-step-overhead hot path for
    # train loops that keep the module API (reference integration surface,
    # `src/torchmetrics/metric.py:228-325`, which has no batched analogue).
    _many_program_vals: Optional[Callable] = None
    _many_program_novals: Optional[Callable] = None
    # one template PER program: each trace populates its template's inferred
    # static attrs, and propagating attrs from the other program's template
    # would cross-contaminate (e.g. a mode inferred from different inputs)
    _many_template_vals: Optional["Metric"] = None
    _many_template_novals: Optional["Metric"] = None
    _many_ok: bool = True  # batched-path health; independent of _fused_forward_ok

    @staticmethod
    def _split_many_leaves(args: tuple, kwargs: dict):
        """Partition (args, kwargs) leaves for the scan program.

        Three kinds: **scanned** leaves (arrays with the leading steps axis,
        ndim>=1 — `lax.scan` xs), **array constants** (0-d arrays — traced
        per-chunk operands, so their values stay out of the program cache
        key), and **python constants** (scalars/strings — baked into the
        trace; the chunk signature keys on their repr, so a changed value
        retraces). The eager loop applies the same slicing rule.
        """
        leaves, treedef = jax.tree.flatten((args, kwargs))
        scanned_idx, aconst_idx = [], []
        python_leaves = []
        for i, x in enumerate(leaves):
            if hasattr(x, "shape") and getattr(x, "ndim", 0) >= 1:
                scanned_idx.append(i)
                python_leaves.append(None)  # replaced per step; not retained
            elif hasattr(x, "shape"):
                aconst_idx.append(i)
                python_leaves.append(None)  # replaced per call; not retained
            else:
                python_leaves.append(x)
        scanned = tuple(leaves[i] for i in scanned_idx)
        array_consts = tuple(leaves[i] for i in aconst_idx)
        if not scanned:
            raise ValueError(
                "update_many/forward_many need at least one array argument with a leading steps axis"
            )
        lengths = {int(x.shape[0]) for x in scanned}
        if lengths == {0}:
            raise ValueError("update_many/forward_many got a zero-length steps axis (empty chunk)")
        if len(lengths) != 1:
            # silent length mismatch would be worse than an error: jnp gather
            # CLAMPS out-of-bounds indices, so the eager slicing loop would
            # quietly reuse the last step of the short array
            raise ValueError(
                f"All chunked (ndim>=1) arguments must share the same leading steps-axis "
                f"length; got lengths {sorted(lengths)}. Pass per-chunk constants as "
                f"python scalars or 0-d arrays."
            )
        return python_leaves, treedef, scanned_idx, aconst_idx, scanned, array_consts

    def _acquire_many_program(
        self, with_values: bool, layout, python_leaves, treedef, scanned_idx, aconst_idx
    ) -> "_engine.Executable":
        """Fetch (or build once) the batched-step scan program for one call
        layout — shared by ``update_many``/``forward_many`` AND the deferred
        micro-batch flush (same engine cache key, one compiled program)."""

        def build():
            template, step = self._build_fused_step()

            def scan_program(state, update_count, xs, const_vals):
                def body(carry, xs_leaves):
                    st, cnt = carry
                    cnt = cnt + 1
                    step_leaves = list(python_leaves)
                    for i, leaf in zip(scanned_idx, xs_leaves):
                        step_leaves[i] = leaf
                    for i, leaf in zip(aconst_idx, const_vals):
                        step_leaves[i] = leaf
                    a, k = jax.tree.unflatten(treedef, step_leaves)
                    new_st, val = step(st, cnt, *a, **k)
                    return (new_st, cnt), (val if with_values else 0)

                (final, _), vals = jax.lax.scan(
                    body, (state, jnp.asarray(update_count, jnp.int32)), xs
                )
                return final, vals

            return scan_program, template, {}

        return _engine.acquire(self, "many", build, extra_key=(with_values, layout))

    def update_many(self, *args: Any, **kwargs: Any) -> None:
        """Accumulate a CHUNK of update calls in one dispatch.

        Every array argument carries a leading ``steps`` axis: calling
        ``update_many(preds, target)`` with shapes ``(n, *batch_shape)`` is
        equivalent to ``n`` sequential ``update(preds[i], target[i])`` calls.
        """
        self._run_many(False, args, kwargs)

    def forward_many(self, *args: Any, **kwargs: Any) -> Any:
        """``forward`` over a chunk of steps in one dispatch.

        Returns the per-step batch values stacked along a leading axis —
        ``forward_many(preds, target)[i]`` equals what
        ``forward(preds[i], target[i])`` would have returned at that step.
        """
        return self._run_many(True, args, kwargs)

    def _run_many(self, with_values: bool, args: tuple, kwargs: dict) -> Any:
        _get_validation_mode = _checks_module()._get_validation_mode

        # observation barrier: a chunk call must apply AFTER any deferred
        # per-step calls already enqueued (order is the semantics)
        self._defer_barrier()
        if self._is_synced:
            # same guard as forward (reference `metric.py:240-244`): merging
            # batch state into globally-reduced state double-counts at resync
            raise MetricsUserError(
                "The Metric shouldn't be synced when performing `forward_many`/`update_many`. "
                "HINT: Did you forget to call `unsync()`?"
            )
        fusable = (
            self._many_ok
            and self._fused_forward_ok
            and _get_validation_mode() != "full"
            and self._fusable_states()
            and not (self.full_state_update or self.full_state_update is None or self.dist_sync_on_step)
            # a subclass overriding forward() defines its own step semantics;
            # the scan program is built from update/compute and would bypass it
            and type(self).forward is Metric.forward
        )
        if not fusable:
            return self._run_many_eager(with_values, args, kwargs)
        if self._fused_seen_signatures is None:
            self._fused_seen_signatures = {}
        signature = ("__many__", with_values, self._forward_signature(args, kwargs))
        if signature not in self._fused_seen_signatures:
            # first sight of a chunk signature: eager per-step forwards (full
            # validation; the scan program would have to trace anyway). The
            # per-step REDUCE-eager path is forced so the chunk does not also
            # register the single-step signature and jit-compile the
            # single-step fused program the scan path will never use. The
            # signature is recorded only AFTER the chunk validates — a failed
            # chunk must not license the unvalidated scan path for a retry
            # (same contract as the single-step path below).
            result = self._run_many_eager(with_values, args, kwargs, force_reduce_eager=True)
            self._record_fused_signature(signature)
            return result
        state = None
        try:
            program = self._many_program_vals if with_values else self._many_program_novals
            python_leaves, treedef, scanned_idx, aconst_idx, scanned, array_consts = (
                self._split_many_leaves(args, kwargs)
            )
            # the program closure bakes in the call LAYOUT (tree structure,
            # leaf-kind partition) and the python-constant VALUES; a call with
            # a different layout or changed python constants must rebuild —
            # jax.jit would otherwise reuse a trace with stale baked values
            # (the aval-keyed jit cache cannot see python-leaf changes)
            layout = (treedef, tuple(scanned_idx), tuple(aconst_idx), repr(python_leaves))
            layout_attr = "_many_layout_vals" if with_values else "_many_layout_novals"
            if program is not None and getattr(self, layout_attr, None) != layout:
                program = None
            if program is None:
                # engine-cached per (config, flavor, call layout): a second
                # same-config instance reuses the compiled scan — the most
                # expensive program in the library — and each chunk donates
                # the incoming state buffers. The deferred flush acquires
                # through the same key, so a forward_many user and a deferred
                # eager loop share ONE compiled program per layout.
                program = self._acquire_many_program(
                    with_values, layout, python_leaves, treedef, scanned_idx, aconst_idx
                )
                if with_values:
                    self._many_program_vals = program
                    self._many_template_vals = program.template
                else:
                    self._many_program_novals = program
                    self._many_template_novals = program.template
                object.__setattr__(self, layout_attr, layout)
            template = self._many_template_vals if with_values else self._many_template_novals
            state = {name: getattr(self, name) for name in self._defaults}
            n_steps = int(scanned[0].shape[0])
            runner = getattr(program, "run", None)
            if runner is not None:
                merged, values = runner(
                    state,
                    (self._update_count, scanned, array_consts),
                    avoid_ids=self._default_leaf_ids(),
                )
            else:
                merged, values = program(state, self._update_count, scanned, array_consts)
        except Exception as exc:
            if state is not None and not _engine.state_intact(state):
                _faults.note_fault("donation", site="batched-many", owner=self, error=exc)
                raise RuntimeError(
                    f"Batched-step program for `{type(self).__name__}` failed after "
                    f"donating its state buffers ({type(exc).__name__}: {exc}); the "
                    "accumulated state is unrecoverable — construct a fresh instance."
                ) from exc
            # eager fallback; if it succeeds, only the BATCHED path is deemed
            # untraceable — the single-step fused forward keeps its own flag
            # (one bad chunk must not cost every later forward() its fast
            # path). If the fallback raises too, the input was bad: surface
            # it and keep the batched path enabled.
            result = self._run_many_eager(with_values, args, kwargs)
            _faults.demote(
                self,
                "many",
                exc,
                tier="chunked",
                site="batched-many",
                warn=(
                    f"Batched-step program for `{type(self).__name__}` raised "
                    f"{type(exc).__name__}: {exc}. Falling back to per-step eager "
                    "forwards for this instance's batched API; recoverable "
                    "failures re-probe after clean steps."
                ),
            )
            self._many_ok = False
            self._many_program_vals = None
            self._many_program_novals = None
            self._many_template_vals = None
            self._many_template_novals = None
            return result
        for name, value in merged.items():
            setattr(self, name, value)
        _propagate_static_attrs(template, self)
        self._update_count += n_steps
        self._is_synced = False
        self._should_unsync = True
        self._to_sync = self.sync_on_compute
        self._computed = None
        self._fault_note_clean(n_steps)
        if with_values:
            # keep the forward contract: _forward_cache is the LAST step's
            # batch value, exactly as n sequential forward calls would leave it
            self._forward_cache = jax.tree.map(lambda v: v[-1], values)
            return values
        return None

    def _run_many_eager(
        self, with_values: bool, args: tuple, kwargs: dict, force_reduce_eager: bool = False
    ) -> Any:
        # the same partition (and length validation) as the scan path — the
        # first-chunk-eager licensing contract requires both paths to slice
        # identically
        _, _, _, _, scanned, _ = self._split_many_leaves(args, kwargs)
        n_steps = int(scanned[0].shape[0])
        values = []
        object.__setattr__(self, "_suppress_update_fusion", True)
        try:
            for i in range(n_steps):
                # array leaves carry the steps axis; python scalars/strings and
                # 0-d arrays are per-chunk constants and pass through to every step
                a, k = jax.tree.map(
                    lambda x: x[i] if hasattr(x, "shape") and getattr(x, "ndim", 0) >= 1 else x,
                    (args, kwargs),
                )
                if not with_values:
                    # update_many semantics are n sequential update() calls; the
                    # forward dance (snapshot/reset/compute/merge) would compute
                    # and discard a batch value per step
                    self.update(*a, **k)
                elif force_reduce_eager:
                    self._forward_cache = self._forward_reduce_state_update_eager(*a, **k)
                    values.append(self._forward_cache)
                else:
                    values.append(self.forward(*a, **k))
        finally:
            object.__setattr__(self, "_suppress_update_fusion", False)
        if not with_values:
            return None
        return jax.tree.map(lambda *xs: jnp.stack(xs), *values)

    def _forward_reduce_state_update(self, *args: Any, **kwargs: Any) -> Any:
        """Single-update fast path: batch state is merged into global state.

        The first call PER INPUT SIGNATURE is always eager and fully
        validated (preserving validation mode "first"'s per-signature
        contract — and costing nothing, since a new signature would retrace
        the fused program anyway); subsequent same-signature calls on metrics
        with fusable states run the whole step as one jitted program — unless
        the validation mode is "full", which asks for per-update value checks
        that a traced program cannot perform.
        """
        _get_validation_mode = _checks_module()._get_validation_mode

        fusable = (
            self._fused_forward_ok
            and _get_validation_mode() != "full"
            and self._fusable_states()
            and _leaves_jittable((args, kwargs))
        )
        if not fusable:
            # permanently-unfusable metrics (and mode "full", and host-object
            # inputs like string batches) skip the signature bookkeeping
            # entirely — no repr of text batches, no retained signature
            # strings, just the eager path
            return self._forward_reduce_state_update_eager(*args, **kwargs)
        if self._fused_seen_signatures is None:
            self._fused_seen_signatures = {}  # insertion-ordered → FIFO eviction
        signature = self._forward_signature(args, kwargs)
        seen = signature in self._fused_seen_signatures
        if (
            seen
            and self._defer_ok
            and not self._defer_suspended
            and _engine.defer_enabled()
            and self._defer_stackable(args, kwargs)
        ):
            # deferred micro-batched dispatch: enqueue and hand back a lazy
            # handle — the stacked scan flush runs at the size/age threshold
            # or when the handle/state is actually read
            return self._defer_enqueue_forward(signature, args, kwargs)
        if seen and self._fused_forward is None:
            try:
                program = self._build_fused_forward()
            except Exception as exc:  # noqa: BLE001 — acquire/build (compile-domain) failure
                _faults.demote(
                    self,
                    "forward",
                    exc,
                    default_domain="compile",
                    site="compile",
                    warn=(
                        f"Building the fused forward program for `{type(self).__name__}` "
                        f"failed ({type(exc).__name__}: {exc}). Falling back to the eager "
                        "per-op path for this instance; the degradation ladder re-probes "
                        "the fused path after clean steps."
                    ),
                )
                self._fused_forward_ok = False
                self._fused_template = None
                return self._forward_reduce_state_update_eager(*args, **kwargs)
            state = {name: getattr(self, name) for name in self._defaults}
            probe_args = (
                (state, self._update_count + 1, *args) if self._fused_needs_count else (state, *args)
            )
            if _probe_traceable(program, *probe_args, **kwargs):
                self._license_fused_signature(signature)
                self._fused_forward = program
            else:
                # probe declined: permanently eager, and the signature is
                # already recorded — return the eager result directly
                self._fault_silent_decline("forward")
                self._fused_forward_ok = False
                self._fused_template = None
                return self._forward_reduce_state_update_eager(*args, **kwargs)
        if seen and isinstance(self._fused_forward, _engine.Executable):
            # every first-seen signature is probed before running fused; an
            # untraceable one declines quietly (eager for that signature)
            # without disturbing the licensed ones
            state = {name: getattr(self, name) for name in self._defaults}
            probe_args = (
                (state, self._update_count + 1, *args) if self._fused_needs_count else (state, *args)
            )
            if not self._signature_licensed(signature, self._fused_forward, *probe_args, **kwargs):
                return self._forward_reduce_state_update_eager(*args, **kwargs)
        if seen:
            try:
                state = {name: getattr(self, name) for name in self._defaults}
                call_args = (self._update_count + 1, *args) if self._fused_needs_count else args
                runner = getattr(self._fused_forward, "run", None)
                if runner is not None:
                    merged, batch_val = runner(
                        state, call_args, kwargs, avoid_ids=self._default_leaf_ids()
                    )
                else:
                    merged, batch_val = self._fused_forward(state, *call_args, **kwargs)
            except Exception as exc:
                # fall back; if the eager path then succeeds, the metric is
                # genuinely unfusable — stop re-tracing every step. If eager
                # raises too, the input itself was bad: surface that error and
                # keep the fused path enabled.
                if not _engine.state_intact(state):
                    _faults.note_fault("donation", site="fused-forward", owner=self, error=exc)
                    raise RuntimeError(
                        f"Fused forward for `{type(self).__name__}` failed after donating "
                        f"its state buffers ({type(exc).__name__}: {exc}); the accumulated "
                        "state is unrecoverable — construct a fresh instance."
                    ) from exc
                result = self._forward_reduce_state_update_eager(*args, **kwargs)
                _faults.demote(
                    self,
                    "forward",
                    exc,
                    site="fused-forward",
                    warn=(
                        f"Fused forward for `{type(self).__name__}` raised "
                        f"{type(exc).__name__}: {exc}. Falling back to the eager "
                        "per-op path for this instance — expect higher per-step "
                        "overhead; the degradation ladder re-probes the fused "
                        "path after clean steps."
                    ),
                )
                self._fused_forward_ok = False
                self._fused_forward = None
                self._fused_template = None
                return result
            for name, value in merged.items():
                # state names never reach the version logic in __setattr__;
                # skip its dispatch entirely on the per-step hot path
                object.__setattr__(self, name, value)
            # writes via object.__setattr__, so it cannot re-trigger the
            # fused-program invalidation in our __setattr__
            _propagate_static_attrs(self._fused_template, self)
            self._update_count += 1
            self._is_synced = False
            self._should_unsync = True
            self._to_sync = self.sync_on_compute
            self._computed = None
            # clean fused step: demoted sibling lanes (defer, many, host)
            # count toward their recovery edge
            self._fault_note_clean()
            return batch_val
        result = self._forward_reduce_state_update_eager(*args, **kwargs)
        self._record_fused_signature(signature)
        return result

    def _forward_reduce_state_update_eager(self, *args: Any, **kwargs: Any) -> Any:
        global_state = self._state_snapshot()
        update_count = self._update_count
        self.reset()

        self._to_sync = self.dist_sync_on_step
        self._should_unsync = False
        compute_on_cpu, self.compute_on_cpu = self.compute_on_cpu, False

        try:
            self.update(*args, **kwargs)
            batch_val = self.compute()
            self._update_count = update_count + 1
            self._reduce_states(global_state)
        except Exception:
            # a bad batch must not destroy accumulated history: the reset
            # above zeroed the states, so put the snapshot back before
            # surfacing the error (callers that catch and continue keep a
            # consistent metric)
            self._restore_state(global_state)
            self._update_count = update_count
            raise
        finally:
            self._is_synced = False
            self._should_unsync = True
            self._to_sync = self.sync_on_compute
            self._computed = None
            self.compute_on_cpu = compute_on_cpu
        return batch_val

    @staticmethod
    def _merge_leaf(spec: str, incoming: Any, local: Any, update_count: Any) -> Any:
        """The sum/mean/max/min merge table — single source of truth shared by
        the eager `_reduce_states` and the fused forward program."""
        if spec == "sum":
            return incoming + local
        if spec == "mean":
            return ((update_count - 1) * incoming + local) / update_count
        if spec == "max":
            return jnp.maximum(incoming, local)
        return jnp.minimum(incoming, local)

    def _reduce_states(self, incoming_state: Dict[str, Any]) -> None:
        """Merge an incoming state into the current one (reference `metric.py:327-354`)."""
        for name in self._defaults:
            local = getattr(self, name)
            incoming = incoming_state[name]
            spec = self._reduction_specs[name]
            if spec in ("sum", "mean", "max", "min"):
                reduced = self._merge_leaf(spec, incoming, local, self._update_count)
            elif spec == "cat":
                reduced = incoming + local if isinstance(incoming, list) else jnp.concatenate([incoming, local])
            elif spec is None and isinstance(incoming, list):
                reduced = _flatten([incoming, local])
            elif spec is None:
                reduced = jnp.stack([incoming, local])
            else:  # custom callable
                reduced = self._reductions[name](jnp.stack([jnp.asarray(incoming), jnp.asarray(local)]))
            setattr(self, name, reduced)

    # ------------------------------------------------------------------- sync
    def _sync_children(self) -> List["Metric"]:
        """Child metrics whose states must sync with this one.

        Derived from :meth:`_named_child_metrics` so sync and checkpointing
        share ONE child-discovery mechanism — a wrapper whose children sync
        must also have them persisted, and vice versa.
        """
        return [child for _, child in self._named_child_metrics()]

    def _sync_dist(self, dist_sync_fn: Callable = gather_all_tensors, process_group: Optional[Any] = None) -> None:
        input_dict = {name: getattr(self, name) for name in self._reductions}
        for name, spec in self._reduction_specs.items():
            # pre-concatenate list states: one collective per state
            if spec == "cat" and isinstance(input_dict[name], list) and len(input_dict[name]) > 1:
                input_dict[name] = [dim_zero_cat(input_dict[name])]

        output_dict = apply_to_collection(
            input_dict, (jax.Array, np.ndarray), dist_sync_fn, group=process_group or self.process_group
        )

        # the per-state stack+reduce tail runs as ONE engine-cached program
        # (list-of-list gathers and empties keep their host branches; any
        # program failure replays the state-by-state loop bit-exactly)
        _bucketing.apply_gathered_states(self, output_dict)

    def _sync_coalesced(self, dist_sync_fn: Callable, process_group: Optional[Any]) -> bool:
        """Try the coalesced bucketed protocol for this metric's whole tree.

        One packed payload collective (plus at most one shape exchange — see
        :mod:`metrics_tpu.parallel.bucketing`) replaces the 2-per-state walk,
        and children are marked synced with their own snapshots so
        ``unsync`` behaves exactly like the recursive path. Returns False to
        fall back to the per-state protocol (custom ``dist_sync_fn``,
        ``METRICS_TPU_SYNC_COALESCE=0``, a demoted ``sync-pack`` lane,
        un-coalescible states, or a classified pack failure — which demotes
        the lane, bit-exact fallback); transport faults raise to the caller's
        snapshot/restore like the per-state gather would.
        """
        if dist_sync_fn is not gather_all_tensors:
            return False  # custom gather: the injected protocol owns the walk
        if not _bucketing.coalesce_enabled():
            return False
        lad = self.__dict__.get("_fault_ladders", {}).get("sync-pack")
        if lad is not None and lad.demoted:
            return False  # clean per-state syncs advance the recovery edge
        nodes = _bucketing.tree_nodes(self)
        if any(n._is_synced for n in nodes[1:]):
            return False  # the recursive path raises its documented error
        if process_group is None and any(
            n.process_group != self.process_group for n in nodes[1:]
        ):
            return False  # per-node groups: each child must gather its own
        if not _bucketing.coalescible(nodes):
            return False
        snaps = []
        for n in nodes[1:]:
            n._defer_barrier()
            n._canonicalize_list_states()
            snaps.append((n, n._state_snapshot()))
        try:
            _bucketing.coalesced_sync_nodes(nodes, group=process_group or self.process_group)
        except _bucketing.CoalesceError as err:
            if not _bucketing.should_fallback(err):
                # live multi-process world, rank-LOCAL failure: a unilateral
                # protocol switch cannot pair with the other ranks'
                # collectives — surface classified instead (the caller's
                # handler restores; sync stays retryable)
                for n, snap in snaps:
                    n._restore_state(snap)
                raise err.original from err
            _bucketing.handle_coalesce_failure(
                self,
                snaps,
                err,
                warn=(
                    f"Coalesced sync failed for {type(self).__name__}; falling back to the"
                    " per-state gather protocol (bit-exact, one collective pair per state)."
                ),
            )
            return False
        except Exception:
            for n, snap in snaps:
                n._restore_state(snap)
            raise  # the caller's handler restores self and classifies
        for n, snap in snaps:
            n._cache = snap
            n._is_synced = True
        return True

    def _sync_note_clean(self) -> None:
        """One clean sync at the per-state tier: advance the ``sync-pack``
        recovery edge (the coalesced path re-probes once it fires)."""
        lad = self.__dict__.get("_fault_ladders", {}).get("sync-pack")
        if lad is not None and lad.demoted and lad.note_clean():
            lad.promote()

    def sync(
        self,
        dist_sync_fn: Optional[Callable] = None,
        process_group: Optional[Any] = None,
        should_sync: bool = True,
        distributed_available: Optional[Callable] = jit_distributed_available,
    ) -> None:
        """Manually sync state across processes (reference `metric.py:416-450`)."""
        if should_sync and self.__dict__.get("_pending_sync") is not None:
            raise MetricsUserError(
                "A sync is already in flight for this Metric (sync_async); force it"
                " with wait() or compute() before syncing again."
            )
        if should_sync:
            # collectives pair by issue order: OTHER owners' in-flight async
            # syncs must land BEFORE this protocol snapshots or issues (a
            # drain mid-protocol would apply merged rows to state the pack
            # then double-merges). Self's future raised above.
            _psync.drain_inflight()
        if self._is_synced and should_sync:
            raise MetricsUserError("The Metric has already been synced.")

        is_distributed = distributed_available() if callable(distributed_available) else None
        if not should_sync or not is_distributed:
            return

        if dist_sync_fn is None:
            dist_sync_fn = self.dist_sync_fn or gather_all_tensors

        group = process_group or self.process_group
        if isinstance(group, (list, tuple)) and group and not all(isinstance(g, str) for g in group):
            # the range check deferred at construction (metrics may be built
            # before jax.distributed initializes — see __init__) runs HERE
            # against the LIVE world size, raising the classified SyncConfigFault
            from metrics_tpu.parallel.sync import validate_group_live

            validate_group_live(group)

        self._defer_barrier()
        self._canonicalize_list_states()
        self._cache = self._state_snapshot()
        try:
            if self._sync_coalesced(dist_sync_fn, process_group):
                self._is_synced = True
            else:
                self._sync_dist(dist_sync_fn, process_group=process_group)
                self._is_synced = True
                # wrappers/compositions hold their accumulators in child metrics, not
                # in their own state registry — sync recurses so the wrapper's
                # distributed value equals the reference's module-tree sync
                # (reference wrappers' child states are registered submodule states)
                for child in self._sync_children():
                    child.sync(
                        dist_sync_fn=dist_sync_fn,
                        process_group=process_group,
                        should_sync=should_sync,
                        distributed_available=distributed_available,
                    )
                # a clean per-state sync counts toward the sync-pack recovery
                # edge: a demoted coalescer re-probes after N clean syncs
                self._sync_note_clean()
        except Exception as exc:
            # a failed sync must leave local state INTACT and retryable: a
            # mid-gather failure may have overwritten some states with merged
            # values and not others — restore the entry snapshot, roll back
            # any children that synced before the failure, and surface the
            # classified error (compute() then raises instead of returning a
            # half-synced value)
            self._restore_state(self._cache)
            self._cache = None
            self._is_synced = False
            for child in self._sync_children():
                if child._is_synced:
                    try:
                        child.unsync()
                    except Exception:  # noqa: BLE001 — best-effort rollback
                        pass
            _faults.note_fault(_faults.classify(exc, "sync"), site="sync", owner=self, error=exc)
            raise
        # a completed FULL-WORLD sync is the tree's "last good" health marker:
        # stamp the monotonic fault/sync step index on every node
        # (sync_health() reports it as last_good_sync_step) and clear any
        # degradation onset. A group-scoped sync — the quorum tier's
        # surviving-subgroup merge — deliberately stamps nothing: its served
        # values still exclude dead ranks, and reporting fresh full-world
        # health would contradict the membership registry.
        if _psync.is_full_world_group(process_group or self.process_group):
            step = _faults.tick()
            for n in _bucketing.tree_nodes(self):
                object.__setattr__(n, "_last_good_sync_step", step)
                if n.__dict__.get("_degraded_since_step") is not None:
                    object.__setattr__(n, "_degraded_since_step", None)

    def sync_async(
        self,
        dist_sync_fn: Optional[Callable] = None,
        process_group: Optional[Any] = None,
        should_sync: bool = True,
        distributed_available: Optional[Callable] = jit_distributed_available,
    ) -> Optional["_psync.SyncFuture"]:
        """Dispatch this metric tree's sync WITHOUT blocking: hide the wire.

        The packed payload collective (the coalesced protocol's single
        all-gather) is handed to the dispatcher thread and runs OVERLAPPED
        with whatever the caller does next — subsequent ``update``/``forward``
        compute, other metrics' work — while local state stays untouched (the
        pack is a snapshot of the dispatch point; jax arrays are immutable).
        Returns a :class:`~metrics_tpu.parallel.sync.SyncFuture`; force it
        with ``wait()`` or let ``compute()`` auto-force it. The force
        re-checks the epoch fence, so an in-flight future from a dead world
        classifies as ``EpochFault`` instead of pairing stale rows. Returns
        ``None`` when there is nothing to sync (non-distributed world or
        ``should_sync=False``). When the tree cannot ride the packed protocol
        (custom gather, un-coalescible states, a demoted ``sync-pack`` lane,
        ``METRICS_TPU_SYNC_COALESCE=0``) the BLOCKING protocol runs here and
        an already-completed future returns, so callers treat both uniformly.

        Updates issued while the sync is in flight accumulate locally: the
        forced (merged) value reflects the dispatch point, and the tail
        restores through ``unsync`` — the same visibility a blocking
        ``sync()`` at the dispatch point would have given."""
        if self.__dict__.get("_pending_sync") is not None:
            raise MetricsUserError(
                "A sync is already in flight for this Metric; force it with wait()"
                " or compute() before dispatching another."
            )
        if self._is_synced and should_sync:
            raise MetricsUserError("The Metric has already been synced.")
        is_distributed = distributed_available() if callable(distributed_available) else None
        if not should_sync or not is_distributed:
            return None
        resolved_fn = dist_sync_fn or self.dist_sync_fn or gather_all_tensors
        lad = self.__dict__.get("_fault_ladders", {}).get("sync-pack")
        nodes = _bucketing.tree_nodes(self)
        eligible = (
            resolved_fn is gather_all_tensors
            and _bucketing.coalesce_enabled()
            and not (lad is not None and lad.demoted)
            and not any(n._is_synced for n in nodes)
            and (
                process_group is not None
                or not any(n.process_group != self.process_group for n in nodes[1:])
            )
        )
        if eligible:
            for n in nodes:
                n._defer_barrier()
                n._canonicalize_list_states()
            eligible = _bucketing.coalescible(nodes)
        def _blocking_fallback() -> "_psync.SyncFuture":
            # the async lane requires the packed protocol (one in-flight
            # buffer to force); everything else syncs blocking right here.
            # The completed future is REGISTERED like a live one, so the
            # compute() auto-force path unsyncs after serving — both lanes
            # leave the metric in the same state (note: like a blocking
            # sync, updates issued after this point land on the merged
            # state and restore away at unsync — the tail-preservation
            # contract belongs to the truly-in-flight lane only)
            _psync._bump("sync_async_fallbacks")
            self.sync(
                dist_sync_fn=dist_sync_fn,
                process_group=process_group,
                should_sync=should_sync,
                distributed_available=distributed_available,
            )
            done_fut = _psync.SyncFuture.completed(self)
            object.__setattr__(self, "_pending_sync", done_fut)
            return done_fut

        if not eligible:
            return _blocking_fallback()
        group = process_group or self.process_group
        try:
            disp = _bucketing.dispatch_coalesced_sync(nodes, group=group, owner=self)
        except _bucketing.CoalesceError as err:
            # pack/program failure at dispatch: same demote-and-replay the
            # blocking paths run — the lane heals itself instead of the raw
            # CoalesceError recurring on every dispatch
            if not _bucketing.should_fallback(err):
                _faults.note_fault(
                    _faults.classify(err.original, "sync"), site="sync", owner=self, error=err.original
                )
                raise err.original from err
            _bucketing.handle_coalesce_failure(
                self,
                [(n, n._state_snapshot()) for n in nodes],
                err,
                warn=(
                    f"Async coalesced sync failed at dispatch for {type(self).__name__};"
                    " the blocking per-state protocol runs instead (bit-exact)."
                ),
            )
            return _blocking_fallback()
        if disp is None:
            return _psync.SyncFuture.completed(self)  # all-empty tree: nothing in flight

        def _force() -> None:
            object.__setattr__(self, "_pending_sync", None)
            try:
                snaps = _bucketing.force_coalesced_sync(disp)
            except _bucketing.CoalesceError as err:
                if not _bucketing.should_fallback(err):
                    # live world, rank-LOCAL failure: surface classified — a
                    # unilateral protocol switch cannot pair with the other
                    # ranks' collectives (local state is intact: nothing was
                    # applied)
                    _faults.note_fault(
                        _faults.classify(err.original, "sync"), site="sync", owner=self, error=err.original
                    )
                    raise err.original from err
                _bucketing.handle_coalesce_failure(
                    self,
                    [(n, n._state_snapshot()) for n in nodes],
                    err,
                    warn=(
                        f"Async coalesced sync failed at force for {type(self).__name__};"
                        " replaying the blocking per-state protocol (bit-exact)."
                    ),
                )
                self.sync(
                    dist_sync_fn=dist_sync_fn,
                    process_group=process_group,
                    should_sync=True,
                    distributed_available=distributed_available,
                )
                return
            except Exception as exc:
                _faults.note_fault(_faults.classify(exc, "sync"), site="sync", owner=self, error=exc)
                raise
            # success: the pre-apply snapshots become the unsync caches (they
            # carry any overlap-window tail updates), the tree marks synced,
            # and a full-world force stamps the health marker like sync()
            for n, snap in snaps:
                n._cache = snap
                n._is_synced = True
            if _psync.is_full_world_group(group):
                step = _faults.tick()
                for n in nodes:
                    object.__setattr__(n, "_last_good_sync_step", step)
                    if n.__dict__.get("_degraded_since_step") is not None:
                        object.__setattr__(n, "_degraded_since_step", None)

        fut = _psync.SyncFuture(self, _force, done=disp.done, quant_tier=disp.ctx.quant_tier)
        object.__setattr__(self, "_pending_sync", fut)
        return fut

    def unsync(self, should_unsync: bool = True) -> None:
        """Restore pre-sync local state (reference `metric.py:452-472`)."""
        if not should_unsync:
            return
        # a SPENT pending future (completed blocking-fallback, forced, or
        # cancelled) must not block the next sync once the cycle closes here
        fut = self.__dict__.get("_pending_sync")
        if fut is not None and (fut._forced or fut._cancelled):
            object.__setattr__(self, "_pending_sync", None)
        if not self._is_synced:
            raise MetricsUserError("The Metric has already been un-synced.")
        if self._cache is None:
            raise MetricsUserError("The internal cache should exist to unsync the Metric.")
        self._restore_state(self._cache)
        self._is_synced = False
        self._cache = None
        for child in self._sync_children():
            if child._is_synced:
                child.unsync(should_unsync)

    class _SyncContext:
        def __init__(self, metric: "Metric", **kwargs: Any) -> None:
            self.metric = metric
            self.kwargs = kwargs
            self.should_unsync = kwargs.pop("should_unsync", True)

        def __enter__(self) -> "Metric":
            # in-flight async syncs land BEFORE the presynced read: a drain
            # later (mid-protocol) would flip _is_synced under the context —
            # e.g. a member computing while its collection's future is in
            # flight must see itself presynced by the forced suite rows
            if self.kwargs.get("should_sync", True):
                _psync.drain_inflight()
            # a metric synced before entering (e.g. a wrapper's child, synced
            # by the parent's recursion) just computes on the merged state —
            # double-syncing would raise, and unsyncing on exit would undo
            # the parent's sync from under it
            self._presynced = self.metric._is_synced
            if not self._presynced:
                self.metric.sync(**self.kwargs)
            return self.metric

        def __exit__(self, *exc: Any) -> None:
            self.metric.unsync(
                should_unsync=self.should_unsync and self.metric._is_synced and not self._presynced
            )

    def sync_context(
        self,
        dist_sync_fn: Optional[Callable] = None,
        process_group: Optional[Any] = None,
        should_sync: bool = True,
        should_unsync: bool = True,
        distributed_available: Optional[Callable] = jit_distributed_available,
    ) -> "Metric._SyncContext":
        """Context manager: sync on enter, restore local state on exit."""
        return Metric._SyncContext(
            self,
            dist_sync_fn=dist_sync_fn,
            process_group=process_group,
            should_sync=should_sync,
            should_unsync=should_unsync,
            distributed_available=distributed_available,
        )

    # ------------------------------------------------------------- durability
    def sync_health(self) -> Dict[str, Any]:
        """Staleness metadata for this metric's distributed value.

        The explicit tag on every quorum-degraded compute
        (``METRICS_TPU_SYNC_DEGRADED=local``): whether the value currently
        served is local-only, the monotonic step index of the last completed
        sync (stamped by :meth:`sync`; ``None`` if this tree never synced),
        when the degradation began, how many local-only values were served,
        and the per-domain fault counts folded out of ``engine_stats()``'s
        ``failure_log`` ring (each ring entry carries the same monotonic
        ``step`` index, so the log orders against ``last_good_sync_step``).
        """
        lad = self.__dict__.get("_fault_ladders", {}).get("sync-degrade")
        domain_counts: Dict[str, int] = {}
        for entry in _faults.fault_stats()["failure_log"]:
            domain_counts[entry["domain"]] = domain_counts.get(entry["domain"], 0) + 1
        fut = self.__dict__.get("_pending_sync")
        return {
            "degraded": bool(lad is not None and lad.demoted),
            "degraded_tier": _psync.sync_degraded_tier(),
            "epoch": _psync.world_epoch(),
            "last_good_sync_step": self.__dict__.get("_last_good_sync_step"),
            "degraded_since_step": self.__dict__.get("_degraded_since_step"),
            "degraded_serves": self.__dict__.get("_degraded_serves", 0),
            "quorum_serves": self.__dict__.get("_quorum_serves", 0),
            # the in-flight async sync, if any: age in monotonic steps, the
            # epoch it was dispatched at (behind the live epoch => the force
            # WILL fence-trip), the quant tier it shipped under, and whether
            # the wire has already landed (forcing will not block)
            "inflight": None
            if fut is None
            else {
                "age_steps": fut.age_steps(),
                "dispatch_epoch": fut.dispatch_epoch,
                "dispatch_step": fut.dispatch_step,
                "quant_tier": fut.quant_tier,
                "done": fut.done(),
            },
            "fault_domain_counts": domain_counts,
        }

    def save_state(self, path: str) -> int:
        """Snapshot this metric tree's reduce-path states into the
        crash-consistent journal at ``path`` (CRC-checksummed single byte
        record, atomic write, bounded generation ring — see
        :mod:`metrics_tpu.ops.journal`). Returns the record size in bytes.
        Flushes any pending deferred micro-batch first (an observation
        point), reusing the coalesced-sync pack machinery so the record is
        bit-exact vs the live state by construction."""
        from metrics_tpu.ops import journal as _journal

        return _journal.save_nodes(self, _bucketing.tree_nodes(self), path)

    def load_state(self, path: str) -> int:
        """Restore this metric tree from the newest good journal generation
        at ``path``; returns the generation index restored (0 = newest). A
        torn or checksum-failed generation records a classified ``journal``
        fault and demotes to the previous good one; restore is all-or-nothing
        (a bad record leaves live state untouched)."""
        from metrics_tpu.ops import journal as _journal

        return _journal.load_nodes(self, _bucketing.tree_nodes(self), path)

    def _journal_extra(self) -> Optional[Dict[str, Any]]:
        """Hook: JSON-serializable HOST-side state (beyond the packed
        reduce-path states and public scalar hyperparameters) that a
        crash-consistent restore needs to reproduce future behavior exactly —
        e.g. ``BootStrapper``'s numpy RNG stream, whose post-restore draws
        must match the uninterrupted run's. Default: nothing."""
        return None

    def _journal_restore_extra(self, extra: Dict[str, Any]) -> None:
        """Apply what :meth:`_journal_extra` recorded. Default: no-op."""

    # ---------------------------------------------------------------- compute
    def _wrap_compute(self, compute: Callable) -> Callable:
        @functools.wraps(compute)
        def wrapped(*args: Any, **kwargs: Any) -> Any:
            if self._update_count == 0:
                rank_zero_warn(
                    f"The ``compute`` method of metric {self.__class__.__name__} was called before the ``update``"
                    " method which may lead to errors, as metric states have not yet been updated.",
                    UserWarning,
                )
            if self._computed is not None:
                return self._computed

            self._defer_barrier()
            # compute() is the force point of an in-flight async sync: block
            # (under the watchdog deadline), re-check the fence, apply. A
            # classified force failure rides the SAME degraded tier a
            # blocking sync failure would — local state is intact either way.
            pending = self.__dict__.get("_pending_sync")
            forced_async = False
            if pending is not None:
                pending_tier = _psync.sync_degraded_tier()
                try:
                    pending.wait()
                    _psync._bump("sync_async_auto_forces")
                    forced_async = self._is_synced
                except Exception as exc:  # noqa: BLE001 — degradable sync faults only
                    if not (
                        pending_tier is not None
                        and _degradable_sync_failure(exc)
                        and not self._is_synced
                    ):
                        raise
                    _enter_degraded(self, exc, pending_tier)
            should_sync = self._to_sync
            # degraded compute tier (METRICS_TPU_SYNC_DEGRADED=local|quorum,
            # default off — one env read only when a sync is actually
            # pending): while the sync-degrade lane is down, compute() serves
            # the LOCAL-ONLY value ("local") or the merge over the SURVIVING
            # subgroup ("quorum", when the membership registry knows who
            # survived — the group-scoped gather path). Each serve is one
            # clean step toward the recovery edge, whose firing re-probes the
            # FULL world on this very call — a healed transport (or a
            # rejoined rank) promotes automatically.
            degraded_tier = _psync.sync_degraded_tier() if should_sync else None
            quorum_group: Optional[List[int]] = None
            if degraded_tier is not None:
                lad = self.__dict__.get("_fault_ladders", {}).get("sync-degrade")
                if lad is not None and lad.demoted:
                    if lad.note_clean():
                        lad.promote()
                    else:
                        quorum_group = (
                            _psync.surviving_members() if degraded_tier == "quorum" else None
                        )
                        if quorum_group is None:
                            should_sync = False
                            _note_degraded_serve(self)

            def _compute_under_sync(do_sync: bool, group: Optional[List[int]] = None) -> Any:
                with self.sync_context(
                    dist_sync_fn=self.dist_sync_fn,
                    process_group=group,
                    should_sync=do_sync,
                    should_unsync=self._should_unsync,
                ):
                    with jax.profiler.TraceAnnotation(f"{type(self).__name__}.compute"):
                        value = compute(*args, **kwargs)
                    self._computed = self._decouple_from_state(_squeeze_scalar(value))
                return self._computed

            try:
                value = _compute_under_sync(should_sync, quorum_group)
                if quorum_group is not None:
                    _note_quorum_serve(self, quorum_group)
                if forced_async and self._should_unsync and self._is_synced:
                    # the auto-forced sync mirrors the blocking auto-sync's
                    # exit: restore local state (incl. any overlap-window
                    # tail updates) once the value is computed and cached
                    self.unsync()
                return value
            except Exception as exc:  # noqa: BLE001 — only degradable sync faults caught
                if not (
                    degraded_tier is not None
                    and should_sync
                    and _degradable_sync_failure(exc)
                    and not self._is_synced
                ):
                    raise
                # the sync failed classified past its retries and restored
                # local state (Metric.sync's snapshot/restore): drop to the
                # degraded tier and serve instead of raising
                _enter_degraded(self, exc, degraded_tier)
                if quorum_group is None and degraded_tier == "quorum":
                    survivors = _psync.surviving_members()
                    if survivors is not None:
                        # a quorum is known (peers declared dead, epoch
                        # bumped): aggregate over the survivors before
                        # falling all the way back to local-only
                        try:
                            value = _compute_under_sync(True, survivors)
                            _note_quorum_serve(self, survivors)
                            return value
                        except Exception as exc2:  # noqa: BLE001 — degradable only
                            if not (_degradable_sync_failure(exc2) and not self._is_synced):
                                raise
                            _enter_degraded(self, exc2, degraded_tier)
                _note_degraded_serve(self)
                return _compute_under_sync(False)

        return wrapped

    def _decouple_from_state(self, value: Any) -> Any:
        """Donation safety for compute results that ARE live state buffers.

        ``SumMetric.compute`` (and kin) return the state leaf itself; the
        next donated fused step would delete that buffer out from under the
        caller's held result. Copy any result leaf whose buffer is a current
        state leaf — one tiny async op, only at compute time, only for
        metrics whose states can be donated at all.
        """
        if not self._fusable_states() or not _engine.donation_supported():
            return value
        state_ids = {
            id(v) for v in self.metric_state.values() if isinstance(v, jax.Array)
        }
        if not state_ids:
            return value

        def leaf(x: Any) -> Any:
            if isinstance(x, jax.Array) and not isinstance(x, jax.core.Tracer) and id(x) in state_ids:
                return jnp.copy(x)
            return x

        return jax.tree.map(leaf, value)

    def reset(self) -> None:
        """Reset state to defaults (reference `metric.py:547-562`).

        An observation point: pending deferred calls flush first, so lazy
        ``forward`` handles issued before the reset keep their values (eager
        semantics — their batches ran before the reset). An in-flight async
        sync is CANCELLED — merged rows landing on top of a reset would
        resurrect the cleared accumulators."""
        self._defer_barrier()
        fut = self.__dict__.get("_pending_sync")
        if fut is not None:
            fut.cancel()
            object.__setattr__(self, "_pending_sync", None)
        self._update_count = 0
        self._forward_cache = None
        self._computed = None
        for name, default in self._defaults.items():
            setattr(self, name, list(default) if isinstance(default, list) else default)
        self._cache = None
        self._is_synced = False

    # ---------------------------------------------------- functional export
    def as_functions(self) -> tuple:
        """Export ``(init, update, compute)`` as pure functions over the state pytree.

        These are the kernels for jit/shard_map use::

            init, update_fn, compute_fn = metric.as_functions()
            state = init()
            state = jax.jit(update_fn)(state, preds, target)
            value = compute_fn(state, axis_name="dp")   # inside shard_map: fused sync

        The update must be trace-safe (all device math; true for every metric
        whose reference kernel is pure tensor ops). ``compute_fn`` with
        ``axis_name`` lowers each state's reduction spec to a single XLA
        collective (psum/pmax/all_gather) — the TPU-native replacement for the
        reference's ``_sync_dist`` gather path.

        Delegates to :mod:`metrics_tpu.functional_core` (the one functional
        implementation the ``apply_*`` methods also ride), which caches the
        export per config fingerprint — repeated calls reuse the template.
        """
        from metrics_tpu import functional_core as _funcore

        return _funcore.metric_functions(self)

    def init(self) -> "Any":
        """A fresh epoch-stamped functional state tree
        (:class:`metrics_tpu.functional_core.FuncState`). See
        :func:`metrics_tpu.functional_core.init`."""
        from metrics_tpu import functional_core as _funcore

        return _funcore.init(self)

    def apply_update(self, state: Any, *args: Any, **kwargs: Any) -> Any:
        """Pure update over an explicit state tree — jit/``shard_map`` this
        freely. See :func:`metrics_tpu.functional_core.apply_update`."""
        from metrics_tpu import functional_core as _funcore

        return _funcore.apply_update(self, state, *args, **kwargs)

    def apply_compute(self, state: Any, *, axis_name: Optional[str] = None) -> Any:
        """Pure compute; with ``axis_name`` the cross-device merge is ONE
        in-graph XLA collective per state (zero host round trips). See
        :func:`metrics_tpu.functional_core.apply_compute`."""
        from metrics_tpu import functional_core as _funcore

        return _funcore.apply_compute(self, state, axis_name=axis_name)

    def host_handoff(self, state: Any, *, merged: bool = True) -> "Metric":
        """Land an in-graph state tree back into this stateful shell without
        double-merging. See :func:`metrics_tpu.functional_core.host_handoff`."""
        from metrics_tpu import functional_core as _funcore

        return _funcore.host_handoff(self, state, merged=merged)

    def _inner_update(self, *args: Any, **kwargs: Any) -> None:
        self.update.__wrapped__(*args, **kwargs)  # type: ignore[attr-defined]

    def _inner_compute(self) -> Any:
        return _squeeze_scalar(self.compute.__wrapped__())  # type: ignore[attr-defined]

    def _bare_clone(self) -> "Metric":
        """A reset deep copy used as a pure-function template."""
        m = copy.deepcopy(self)
        m.reset()
        return m

    # -------------------------------------------------------- serialization
    def clone(self) -> "Metric":
        return copy.deepcopy(self)

    # Export/jit machinery template attributes, matched by exact name so a
    # future Metric-valued attribute that merely *starts* with "_fused"/"_many"
    # still participates in sync, state_dict, and persistent recursion.
    _CHILD_SKIP_ATTRS = frozenset(
        {
            "_fused_template",
            "_fused_templates",
            "_fused_update_template",
            "_many_template_vals",
            "_many_template_novals",
            "_many_templates",
        }
    )

    def _named_child_metrics(self) -> List[tuple]:
        """(dotted-name, child) pairs for Metric-valued attributes.

        Wrappers and compositions hold their children as plain attributes
        (``self.metric``, ``self._base_metric``, ``self.metrics`` lists); the
        reference gets recursive ``state_dict`` for free from ``nn.Module``
        registration, so child discovery here is the equivalent surface.
        Fused-forward templates are machinery, not children, and are skipped.
        """
        out = []
        for attr in sorted(self.__dict__):
            if attr in self._CHILD_SKIP_ATTRS:
                continue
            value = self.__dict__[attr]
            if isinstance(value, Metric):
                out.append((attr, value))
            elif isinstance(value, (list, tuple)):
                out.extend((f"{attr}.{i}", v) for i, v in enumerate(value) if isinstance(v, Metric))
        return out

    def state_dict(self, prefix: str = "", keep_vars: bool = False) -> Dict[str, Any]:
        """Persistent states as host numpy arrays (checkpointable pytree leaves).

        Parity: reference ``state_dict`` `metric.py:662-680`; the result is a
        plain dict so it drops into orbax/flax checkpoints. Child metrics
        (wrappers, compositions) recurse under dotted prefixes, matching the
        reference's ``nn.Module`` hierarchy.
        """
        destination: Dict[str, Any] = {}
        self._defer_barrier()
        self._canonicalize_list_states()
        for name in self._defaults:
            if not self._persistent[name]:
                continue
            value = getattr(self, name)
            if isinstance(value, list):
                destination[prefix + name] = [np.asarray(jax.device_get(v)) for v in value]
            else:
                destination[prefix + name] = np.asarray(jax.device_get(value))
        for child_name, child in self._named_child_metrics():
            destination.update(child.state_dict(prefix=f"{prefix}{child_name}."))
        return destination

    def load_state_dict(self, state_dict: Dict[str, Any], prefix: str = "", strict: bool = True) -> None:
        for name in self._defaults:
            key = prefix + name
            if key in state_dict:
                value = state_dict[key]
                if isinstance(value, list):
                    setattr(self, name, [jnp.asarray(v) for v in value])
                else:
                    setattr(self, name, jnp.asarray(value))
            elif strict and self._persistent[name]:
                raise KeyError(f"Missing key {key!r} in state_dict")
        for child_name, child in self._named_child_metrics():
            child.load_state_dict(state_dict, prefix=f"{prefix}{child_name}.", strict=strict)

    def persistent(self, mode: bool = False) -> None:
        """Toggle the persistent flag on all states, children included
        (reference `metric.py:657-660`)."""
        for name in self._persistent:
            self._persistent[name] = mode
        for _, child in self._named_child_metrics():
            child.persistent(mode)

    def __getstate__(self) -> Dict[str, Any]:
        # drop the wrapped bound methods (re-wrapped on unpickle, reference
        # `metric.py:568-577`) and the fused-forward machinery (jit closures
        # don't pickle/deepcopy; rebuilt lazily on first fused call).
        # Serialization is an observation: pending deferred calls flush first
        # (no-op when called from inside a flush building its template).
        self._defer_barrier()
        self._canonicalize_list_states()
        drop = (
            "update",
            "compute",
            "_defer_pending",
            "_defer_probed",
            "_fused_forward",
            "_fused_template",
            "_fused_update_program",
            "_fused_update_template",
            "_many_program_vals",
            "_many_program_novals",
            "_many_template_vals",
            "_many_template_novals",
            "_many_layout_vals",
            "_many_layout_novals",
            "_update_lane",
            "_fused_probe_results",
            "_default_ids_cache",
            # the functional-core export cache: closures over a template
            # clone, rebuilt lazily keyed by config fingerprint
            "_funcore_export",
            # fault-ladder state is per-process health bookkeeping, not
            # metric state: a restored/cloned instance starts healthy
            "_fault_ladders",
            "_fault_warned",
        )
        return {k: v for k, v in self.__dict__.items() if k not in drop}

    def __setstate__(self, state: Dict[str, Any]) -> None:
        self.__dict__.update(state)
        self.update = self._wrap_update(type(self).update.__get__(self))  # type: ignore[method-assign]
        self.compute = self._wrap_compute(type(self).compute.__get__(self))  # type: ignore[method-assign]

    def __setattr__(self, name: str, value: Any) -> None:
        if name in ("higher_is_better", "is_differentiable", "full_state_update"):
            raise RuntimeError(f"Can't change const `{name}`.")
        # deferred-queue barrier: overwriting a state value (load_state_dict,
        # user assignment) or mutating a public hyperparameter must apply
        # AFTER the queued calls — they were enqueued under the old values.
        # Private bookkeeping writes (and the flush's own state write-back,
        # which uses object.__setattr__) skip this.
        q = self.__dict__.get("_defer_pending")
        if q is not None and (not name.startswith("_") or q.has_state(self, name)):
            q.flush()
        # mutating a non-state attribute (a hyperparameter like `threshold`)
        # invalidates the fused forward program: its trace baked in the old
        # value, and the next fused call would both ignore the change and
        # overwrite it from the stale template. States and private attrs
        # mutate every step and are part of the program's inputs, not its
        # constants. (The program's own static-attr write-back uses
        # object.__setattr__ and never reaches this guard.)
        if (
            not name.startswith("_")
            and name not in self.__dict__.get("_defaults", {})
            # compute_on_cpu only gates list-state host moves, which fusable
            # metrics don't have — and the eager forward toggles it per call,
            # so counting it would invalidate suite programs constantly
            and name not in ("update", "compute", "compute_on_cpu")
        ):
            # the version counter always moves (a MetricCollection's fused
            # whole-suite program watches it even when this metric never
            # built its own); the member-level program is dropped if present.
            # Re-assigning the SAME value (metrics that recompute an inferred
            # hyperparameter like `mode` inside update) is not a change and
            # must not churn the suite program — compare only python scalars,
            # where == is cheap and unambiguous (arrays are never equal by
            # identity semantics worth trusting here).
            # only immutable scalar types qualify: a mutable container
            # re-assigned after in-place mutation is identical by `is` yet its
            # baked-in trace constants are stale, so it must still invalidate
            old = self.__dict__.get(name, _UNSET)
            unchanged = (
                old is not _UNSET
                and isinstance(value, (bool, int, float, str, bytes, type(None)))
                and type(old) is type(value)
                and (old is value or old == value)
            )
            if not unchanged:
                object.__setattr__(self, "_fused_version", self.__dict__.get("_fused_version", 0) + 1)
                if self.__dict__.get("_fused_forward") is not None:
                    object.__setattr__(self, "_fused_forward", None)
                    object.__setattr__(self, "_fused_template", None)
                if self.__dict__.get("_fused_update_program") is not None:
                    object.__setattr__(self, "_fused_update_program", None)
                    object.__setattr__(self, "_fused_update_template", None)
                if self.__dict__.get("_update_lane") is not None:
                    # the lane baked this hyperparameter's behavior (e.g. a
                    # nan_strategy gate) into its closure — rebind lazily
                    object.__setattr__(self, "_update_lane", None)
                if self.__dict__.get("_fused_probe_results") is not None:
                    # probe verdicts were against the OLD program's constants
                    object.__setattr__(self, "_fused_probe_results", None)
                if (
                    self.__dict__.get("_many_program_vals") is not None
                    or self.__dict__.get("_many_program_novals") is not None
                ):
                    object.__setattr__(self, "_many_program_vals", None)
                    object.__setattr__(self, "_many_program_novals", None)
                    object.__setattr__(self, "_many_template_vals", None)
                    object.__setattr__(self, "_many_template_novals", None)
        object.__setattr__(self, name, value)

    def __hash__(self) -> int:
        # states are mutable accumulators; identity hash like the reference (`metric.py:724-737`)
        hash_vals: List[Any] = [self.__class__.__name__, id(self)]
        return hash(tuple(hash_vals))

    # --------------------------------------------------------- device moves
    def to_device(self, device: Any) -> "Metric":
        """Move all states to ``device`` (replaces torch ``.to()``)."""
        for name in self._defaults:
            value = getattr(self, name)
            if isinstance(value, list):
                setattr(self, name, [jax.device_put(v, device) for v in value])
            else:
                setattr(self, name, jax.device_put(value, device))
        return self

    def astype(self, dtype: Any) -> "Metric":
        """Cast floating-point states to ``dtype`` (bf16 for HBM-light accumulation)."""
        def _cast(x: jax.Array) -> jax.Array:
            return x.astype(dtype) if jnp.issubdtype(x.dtype, jnp.floating) else x

        for name in self._defaults:
            value = getattr(self, name)
            if isinstance(value, list):
                setattr(self, name, [_cast(jnp.asarray(v)) for v in value])
            else:
                setattr(self, name, _cast(jnp.asarray(value)))
        return self

    # ------------------------------------------------------------- plumbing
    def _filter_kwargs(self, **kwargs: Any) -> Dict[str, Any]:
        """Keep only kwargs accepted by this metric's update (reference `metric.py:702-722`)."""
        sig = inspect.signature(type(self).update)
        params = sig.parameters
        has_var_kw = any(p.kind == inspect.Parameter.VAR_KEYWORD for p in params.values())
        if has_var_kw:
            return kwargs
        return {
            k: v
            for k, v in kwargs.items()
            if k in params and params[k].kind not in (inspect.Parameter.VAR_POSITIONAL,)
        }

    def type(self, dtype: Any) -> "Metric":
        return self.astype(dtype)

    def float(self) -> "Metric":
        return self.astype(jnp.float32)

    def double(self) -> "Metric":
        return self.astype(jnp.float64)

    def half(self) -> "Metric":
        return self.astype(jnp.float16)

    def bfloat16(self) -> "Metric":
        return self.astype(jnp.bfloat16)

    def __repr__(self) -> str:
        return f"{self.__class__.__name__}()"

    # ------------------------------------------------------- composition ops
    def __add__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(_op_add, self, other)

    def __radd__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(_op_add, other, self)

    def __sub__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(_op_sub, self, other)

    def __rsub__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(_op_sub, other, self)

    def __mul__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(_op_mul, self, other)

    def __rmul__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(_op_mul, other, self)

    def __truediv__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(_op_div, self, other)

    def __rtruediv__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(_op_div, other, self)

    def __floordiv__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(_op_floordiv, self, other)

    def __rfloordiv__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(_op_floordiv, other, self)

    def __mod__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(_op_mod, self, other)

    def __rmod__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(_op_mod, other, self)

    def __pow__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(_op_pow, self, other)

    def __rpow__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(_op_pow, other, self)

    def __matmul__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(_op_matmul, self, other)

    def __rmatmul__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(_op_matmul, other, self)

    def __and__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(_op_and, self, other)

    def __rand__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(_op_and, other, self)

    def __or__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(_op_or, self, other)

    def __ror__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(_op_or, other, self)

    def __xor__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(_op_xor, self, other)

    def __rxor__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(_op_xor, other, self)

    def __eq__(self, other: Any) -> "CompositionalMetric":  # type: ignore[override]
        return CompositionalMetric(_op_eq, self, other)

    def __ne__(self, other: Any) -> "CompositionalMetric":  # type: ignore[override]
        return CompositionalMetric(_op_ne, self, other)

    def __lt__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(_op_lt, self, other)

    def __le__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(_op_le, self, other)

    def __gt__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(_op_gt, self, other)

    def __ge__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(_op_ge, self, other)

    def __abs__(self) -> "CompositionalMetric":
        return CompositionalMetric(_op_abs, self, None)

    def __neg__(self) -> "CompositionalMetric":
        return CompositionalMetric(_neg, self, None)

    def __pos__(self) -> "CompositionalMetric":
        return CompositionalMetric(_op_abs, self, None)

    def __invert__(self) -> "CompositionalMetric":
        return CompositionalMetric(_op_not, self, None)

    def __getitem__(self, idx: Any) -> "CompositionalMetric":
        return CompositionalMetric(functools.partial(_op_getitem, idx=idx), self, None)

    def __getnewargs__(self) -> tuple:
        return tuple()


_STATIC_ATTR_SCALARS = (int, float, bool, str, bytes, type(None))


def _is_static_value(value: Any) -> bool:
    if isinstance(value, _STATIC_ATTR_SCALARS):
        return True
    if isinstance(value, (tuple, frozenset)):
        return all(_is_static_value(v) for v in value)
    return False


def _propagate_static_attrs(src: "Metric", dst: "Metric") -> None:
    """Copy update-inferred static hyperparameters back to the export template.

    Several metrics infer shape-derived hyperparameters from their first batch
    and cache them on the instance for ``compute`` — e.g. ``num_classes`` /
    ``pos_label`` on the curve family, ``mode`` on Accuracy/AUROC (mirroring
    reference `classification/avg_precision.py` / `accuracy.py` behavior). In
    the pure-function export the update runs on a throwaway clone, so those
    attributes must flow back to the template for ``compute_fn``'s clone to see
    them. Only plain static python values are copied (they derive from shapes,
    so this is a trace-time effect — consistent across retraces of the same
    shapes); states, arrays, and private bookkeeping are never touched.

    This runs once per fused step, so the NAME filter (public, non-state) is
    cached on the source keyed by its public-key tuple; values — including
    whether each is currently static — are still re-read fresh every call.
    """
    public_keys = tuple(k for k in src.__dict__ if not k.startswith("_"))
    cache = src.__dict__.get("_static_attr_names")
    if cache is None or cache[0] != public_keys:
        state_names = set(src._reduction_specs)
        names = tuple(k for k in public_keys if k not in state_names)
        cache = (public_keys, names)
        object.__setattr__(src, "_static_attr_names", cache)
    for name in cache[1]:
        value = src.__dict__.get(name, _UNSET)
        if value is _UNSET or not _is_static_value(value):
            continue
        if dst.__dict__.get(name, object()) != value:
            object.__setattr__(dst, name, value)


# Module-level named operator wrappers: CompositionalMetric stores its
# operator on the instance, and `jnp.add`-style ufunc objects do not pickle
# (their qualified name resolves to a different wrapper object). Named
# functions pickle by reference, keeping composed metrics checkpointable
# like the reference's torch.add-built ones.
def _op_add(a, b):
    return jnp.add(a, b)


def _op_sub(a, b):
    return jnp.subtract(a, b)


def _op_mul(a, b):
    return jnp.multiply(a, b)


def _op_div(a, b):
    return jnp.divide(a, b)


def _op_floordiv(a, b):
    return jnp.floor_divide(a, b)


def _op_mod(a, b):
    return jnp.mod(a, b)


def _op_pow(a, b):
    return jnp.power(a, b)


def _op_matmul(a, b):
    return jnp.matmul(a, b)


def _op_and(a, b):
    return jnp.bitwise_and(a, b)


def _op_or(a, b):
    return jnp.bitwise_or(a, b)


def _op_xor(a, b):
    return jnp.bitwise_xor(a, b)


def _op_eq(a, b):
    return jnp.equal(a, b)


def _op_ne(a, b):
    return jnp.not_equal(a, b)


def _op_lt(a, b):
    return jnp.less(a, b)


def _op_le(a, b):
    return jnp.less_equal(a, b)


def _op_gt(a, b):
    return jnp.greater(a, b)


def _op_ge(a, b):
    return jnp.greater_equal(a, b)


def _op_abs(x):
    return jnp.abs(x)


def _op_not(x):
    return jnp.logical_not(x)


def _op_getitem(x, idx):
    return x[idx]


def _neg(x: jax.Array) -> jax.Array:
    return -jnp.abs(x)


def _squeeze_scalar(value: Any) -> Any:
    """Squeeze 1-element arrays to scalars like reference `metric.py:531-532`."""
    if isinstance(value, jax.Array) and value.ndim == 1 and value.shape[0] == 1:
        return jnp.squeeze(value)
    return value


class CompositionalMetric(Metric):
    """Lazy arithmetic composition of metrics (reference `metric.py:853-961`).

    Example:
        >>> from metrics_tpu import MeanMetric, SumMetric
        >>> ratio = MeanMetric() / SumMetric()
        >>> type(ratio).__name__
        'CompositionalMetric'
        >>> ratio.update([2.0, 4.0])
        >>> ratio.compute()
        Array(0.5, dtype=float32)
    """

    full_state_update: Optional[bool] = True

    def __init__(
        self,
        operator: Callable,
        metric_a: Union[Metric, float, int, jax.Array, None],
        metric_b: Union[Metric, float, int, jax.Array, None],
    ) -> None:
        super().__init__()
        self.op = operator
        self.metric_a = metric_a if isinstance(metric_a, Metric) else _maybe_asarray(metric_a)
        self.metric_b = metric_b if isinstance(metric_b, Metric) else _maybe_asarray(metric_b)

    def _sync_dist(self, dist_sync_fn: Optional[Callable] = None, process_group: Optional[Any] = None) -> None:
        pass  # no own states; components sync via _sync_children recursion

    def update(self, *args: Any, **kwargs: Any) -> None:
        if isinstance(self.metric_a, Metric):
            self.metric_a.update(*args, **self.metric_a._filter_kwargs(**kwargs))
        if isinstance(self.metric_b, Metric):
            self.metric_b.update(*args, **self.metric_b._filter_kwargs(**kwargs))

    def compute(self) -> Any:
        val_a = self.metric_a.compute() if isinstance(self.metric_a, Metric) else self.metric_a
        val_b = self.metric_b.compute() if isinstance(self.metric_b, Metric) else self.metric_b
        if val_b is None:
            return self.op(val_a)
        return self.op(val_a, val_b)

    def forward(self, *args: Any, **kwargs: Any) -> Any:
        val_a = (
            self.metric_a(*args, **self.metric_a._filter_kwargs(**kwargs))
            if isinstance(self.metric_a, Metric)
            else self.metric_a
        )
        val_b = (
            self.metric_b(*args, **self.metric_b._filter_kwargs(**kwargs))
            if isinstance(self.metric_b, Metric)
            else self.metric_b
        )
        if val_a is None:
            self._forward_cache = None
        elif val_b is None:
            # unary op when metric_b was never given; None if child returned None
            self._forward_cache = None if isinstance(self.metric_b, Metric) else self.op(val_a)
        else:
            self._forward_cache = self.op(val_a, val_b)
        return self._forward_cache

    def reset(self) -> None:
        if isinstance(self.metric_a, Metric):
            self.metric_a.reset()
        if isinstance(self.metric_b, Metric):
            self.metric_b.reset()

    def __repr__(self) -> str:
        _op_metrics = f"(\n  {self.op.__name__ if hasattr(self.op, '__name__') else self.op}(\n    {self.metric_a!r},\n    {self.metric_b!r}\n  )\n)"
        return self.__class__.__name__ + _op_metrics

    def _wrap_compute(self, compute: Callable) -> Callable:
        # no caching/sync wrapping: children handle their own (reference `metric.py:957-961`)
        return compute

    def _inner_compute(self) -> Any:
        # compute is unwrapped (no __wrapped__); components' own wrapped
        # computes run inside it
        return _squeeze_scalar(self.compute())

    def as_functions(self) -> tuple:
        # the composition registers no states of its own — the base export
        # would produce an empty state dict and silently compute on reset
        # components
        raise NotImplementedError(
            "CompositionalMetric holds no states of its own; export each component's "
            "as_functions() and compose the computed values instead."
        )


def _maybe_asarray(value: Any) -> Any:
    if value is None:
        return None
    return jnp.asarray(value)


__all__ = ["Metric", "CompositionalMetric", "jit_distributed_available"]
