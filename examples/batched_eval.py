"""Chunked per-step evaluation with the batched module API.

The per-step hot path for train/eval loops that want a metric VALUE every
step without paying a device dispatch (or, on remote backends, a blocking
sync round trip) per step: inputs for a whole chunk of steps are stacked on
a leading axis and the suite runs them as ONE `lax.scan` program —
``forward_many`` returns the per-step values, state accumulates exactly as
n sequential ``forward`` calls would (docs/performance.md "Batched steps").

    python examples/batched_eval.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
# chunked steps trace once per chunk signature; first-signature validation
# keeps misuse protection without per-step value checks
os.environ.setdefault("METRICS_TPU_VALIDATION", "first")

import jax
import jax.numpy as jnp
import numpy as np

import metrics_tpu as mt


def main() -> None:
    num_classes, batch, chunk_len, n_chunks = 8, 512, 32, 4
    rng = np.random.RandomState(0)

    suite = mt.MetricCollection(
        {
            "acc": mt.Accuracy(num_classes=num_classes, average="macro"),
            "f1": mt.F1Score(num_classes=num_classes, average="macro"),
            "confmat": mt.ConfusionMatrix(num_classes=num_classes),
        }
    )

    for c in range(n_chunks):
        # a dataloader / model would produce these already stacked (and, on
        # TPU, already device-resident)
        logits = rng.randn(chunk_len, batch, num_classes).astype(np.float32)
        labels = rng.randint(0, num_classes, (chunk_len, batch))
        probs = jnp.asarray(np.exp(logits) / np.exp(logits).sum(-1, keepdims=True))
        # chunk 1 runs an eager validated pass; chunks 2+ are ONE dispatch each
        vals = suite.forward_many(probs, jnp.asarray(labels))
        print(
            f"chunk {c}: {chunk_len} steps in one dispatch — "
            f"acc[first]={float(jnp.asarray(vals['acc'])[0]):.3f} "
            f"acc[last]={float(jnp.asarray(vals['acc'])[-1]):.3f}"
        )

    totals = suite.compute()
    print(
        f"epoch: acc={float(totals['acc']):.4f} f1={float(totals['f1']):.4f} "
        f"confmat.sum={int(jnp.asarray(totals['confmat']).sum())} "
        f"({n_chunks * chunk_len} steps x {batch} samples)"
    )

    # the same chunks through a single metric's batched API
    m = mt.MeanSquaredError()
    preds = jnp.asarray(rng.randn(chunk_len, batch).astype(np.float32))
    target = preds + 0.1
    m.update_many(preds, target)
    m.update_many(preds, target)  # scan program from the second chunk on
    print(f"MSE over 2 chunks: {float(m.compute()):.6f}")


if __name__ == "__main__":
    main()
