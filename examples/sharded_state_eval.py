"""Class-sharded metric STATE over a device mesh.

The accumulator arrays themselves are partitioned over a mesh axis — here a
binned PR curve's ``(num_classes, n_thresholds)`` TP/FP/FN counts over the
class axis — so long-tail class counts whose state exceeds one chip's HBM
evaluate with ``1/n_devices`` per-device memory. No metric code changes:
the ``as_functions()`` kernels run sharded or replicated, and XLA keeps the
placement through jitted accumulation (docs/distributed.md "Sharding the
state itself").

Runs on whatever devices JAX sees; to demo an N-way mesh without N real
chips, ask for virtual CPU devices (an env var the example applies itself,
before backend init — exporting JAX_PLATFORMS in the shell is not enough on
hosts whose site config pins a platform):

    FORCE_CPU_DEVICES=8 python examples/sharded_state_eval.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_n_cpu = os.environ.get("FORCE_CPU_DEVICES")
if _n_cpu:
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "") + f" --xla_force_host_platform_device_count={_n_cpu}"
    ).strip()

import jax

if _n_cpu:
    jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import metrics_tpu as mt
from metrics_tpu.parallel import shard_states


def main() -> None:
    devices = jax.devices()
    num_classes = 1024 * len(devices)  # class axis divides the mesh
    n_thresholds, batch, n_batches = 128, 256, 4

    mesh = Mesh(np.array(devices), ("c",))
    metric = mt.BinnedPrecisionRecallCurve(num_classes=num_classes, thresholds=n_thresholds)
    init, update, compute = metric.as_functions()

    states = shard_states(init(), mesh, {name: P("c", None) for name in ("TPs", "FPs", "FNs")})
    update = jax.jit(update, donate_argnums=0)

    rng = np.random.RandomState(0)
    for _ in range(n_batches):
        # a multi-label head's sigmoid scores in [0, 1], with labels drawn
        # Bernoulli(score): every class sweeps the threshold grid and
        # precision at threshold t concentrates near (1 + t) / 2
        scores = rng.rand(batch, num_classes).astype(np.float32)
        labels = (rng.rand(batch, num_classes) < scores).astype(np.int32)
        states = update(states, jnp.asarray(scores), jnp.asarray(labels))

    shard = states["TPs"].addressable_shards[0].data.shape
    full = states["TPs"].shape
    assert states["TPs"].sharding.is_equivalent_to(NamedSharding(mesh, P("c", None)), ndim=2)
    print(f"devices: {len(devices)}; state {full} held as per-device {shard} slices")

    # read ONE class's curve straight from the sharded counts — full compute()
    # would materialize num_classes python lists just to print four numbers
    tps, fps = states["TPs"][0], states["FPs"][0]
    precision0 = np.asarray((tps + 1e-6) / (tps + fps + 1e-6))
    print(f"class-0 precision across thresholds (head): {[round(float(v), 4) for v in precision0[:4]]}")
    del compute  # full curves: precisions, recalls, thresholds = compute(states)


if __name__ == "__main__":
    main()
