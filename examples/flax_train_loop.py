"""End-to-end sharded training with in-loop metric accumulation.

The Lightning-integration analogue (reference
`tests/integrations/test_lightning.py`, SURVEY §7 step 11): a Flax MLP
classifier trained with optax under `shard_map` on a (dp, tp) device mesh,
with a metric suite accumulated ON DEVICE every step — state synced across
the dp axis by a single fused collective per state (no host round-trips) —
plus an epoch-end evaluation through the stateful module API.

Runs on any platform; on a CPU-only host it builds a virtual 8-device mesh:

    python examples/flax_train_loop.py
"""
import os
import sys

# runnable from a clean checkout without installing: put the repo root first
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

if "--real-devices" not in sys.argv and "XLA_FLAGS" not in os.environ:
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import linen as nn
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import metrics_tpu as mt

BATCH_PER_DEVICE, DIN, HIDDEN, NUM_CLASSES, STEPS = 32, 32, 64, 10, 200


class MLP(nn.Module):
    @nn.compact
    def __call__(self, x):
        x = nn.relu(nn.Dense(HIDDEN)(x))
        return nn.Dense(NUM_CLASSES)(x)


def main():
    devices = np.array(jax.devices())
    dp = len(devices) // 2 if len(devices) % 2 == 0 else len(devices)
    tp = len(devices) // dp
    mesh = Mesh(devices.reshape(dp, tp), ("dp", "tp"))
    print(f"mesh: dp={dp} tp={tp} on {jax.default_backend()}")

    rng = np.random.RandomState(0)
    model = MLP()
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, DIN)))
    opt = optax.adam(3e-3)
    opt_state = opt.init(params)

    # metric kernels as pure functions — jit/shard_map-ready
    acc = mt.Accuracy(num_classes=NUM_CLASSES, average="macro")
    loss_mean = mt.MeanMetric()
    acc_init, acc_upd, acc_cmp = acc.as_functions()
    lm_init, lm_upd, lm_cmp = loss_mean.as_functions()

    def train_step(params, opt_state, acc_state, lm_state, xb, yb):
        def loss_fn(p):
            logits = model.apply(p, xb)
            losses = optax.softmax_cross_entropy_with_integer_labels(logits, yb)
            return losses.mean(), logits

        (loss, logits), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        # data parallelism: average grads/loss over the dp axis
        loss = jax.lax.pmean(loss, "dp")
        grads = jax.tree.map(lambda g: jax.lax.pmean(g, "dp"), grads)
        updates, opt_state = opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)

        # device-side metric accumulation; state shards live per-device and
        # only sync (one psum per state) inside compute at epoch end
        acc_state = acc_upd(acc_state, jax.nn.softmax(logits), yb)
        lm_state = lm_upd(lm_state, loss)
        return params, opt_state, acc_state, lm_state, loss

    sharded_step = jax.jit(
        jax.shard_map(
            train_step,
            mesh=mesh,
            in_specs=(P(), P(), P(), P(), P("dp", None), P("dp")),
            out_specs=(P(), P(), P(), P(), P()),
            check_vma=False,
        )
    )
    # epoch-end: fused collective sync over dp, computed ON the mesh
    epoch_metrics = jax.jit(
        jax.shard_map(
            lambda a_st, l_st: (acc_cmp(a_st, axis_name="dp"), lm_cmp(l_st, axis_name="dp")),
            mesh=mesh,
            in_specs=(P(), P()),
            out_specs=(P(), P()),
            check_vma=False,
        )
    )

    acc_state, lm_state = acc_init(), lm_init()
    put = lambda a, spec: jax.device_put(a, NamedSharding(mesh, spec))
    w_true = rng.randn(DIN, NUM_CLASSES).astype(np.float32)

    for step in range(STEPS):
        x = rng.randn(BATCH_PER_DEVICE * dp, DIN).astype(np.float32)
        y = (x @ w_true).argmax(-1)
        params, opt_state, acc_state, lm_state, loss = sharded_step(
            params, opt_state, acc_state, lm_state, put(x, P("dp", None)), put(y, P("dp"))
        )
    epoch_acc, epoch_loss = epoch_metrics(acc_state, lm_state)
    print(f"train: loss={float(epoch_loss):.4f} macro-acc={float(epoch_acc):.4f}")

    # ---- evaluation through the stateful module API (host-driven loop) ----
    suite = mt.MetricCollection(
        {
            "acc": mt.Accuracy(num_classes=NUM_CLASSES),
            "f1": mt.F1Score(num_classes=NUM_CLASSES, average="macro"),
            "confmat": mt.ConfusionMatrix(num_classes=NUM_CLASSES),
        }
    )
    for _ in range(5):
        x = rng.randn(64, DIN).astype(np.float32)
        y = (x @ w_true).argmax(-1)
        logits = model.apply(params, jnp.asarray(x))
        suite.update(jax.nn.softmax(logits), jnp.asarray(y))
    results = suite.compute()
    print(f"eval: acc={float(results['acc']):.4f} f1={float(results['f1']):.4f}")
    assert float(results["acc"]) > 0.3, "training failed to beat chance"
    print("ok")


if __name__ == "__main__":
    main()
