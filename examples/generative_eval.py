"""Generative-model evaluation: FID / KID / IS / LPIPS end-to-end.

The model-backed image metrics run their feature extractors as jitted Flax
forwards on the accelerator; distribution statistics finish in float64 (on
device where f64 is native, on host LAPACK on TPU — see
docs/performance.md). With converted torch-fidelity weights the numbers are
parity-grade; without (as here, deterministic random init) the pipeline is
identical and the values demonstrate shape/flow only.

    python examples/generative_eval.py
    python examples/generative_eval.py --weights inception.npz   # converted via
    # tools/convert_inception_weights.py for published-number parity
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main() -> None:
    import metrics_tpu as mt

    npz = None
    if "--weights" in sys.argv:
        npz = sys.argv[sys.argv.index("--weights") + 1]

    rng = np.random.RandomState(0)
    # stand-ins for dataloader batches: uint8 NCHW images
    real_batches = [rng.randint(0, 255, (32, 3, 299, 299), dtype=np.uint8) for _ in range(2)]
    fake_batches = [
        np.clip(b.astype(np.int32) + rng.randint(-40, 40, b.shape), 0, 255).astype(np.uint8)
        for b in real_batches
    ]

    # without --weights this is an API demo on random-init extractors —
    # scores are meaningless vs published numbers, hence the explicit waiver
    kwargs = {"npz_path": npz} if npz else {"allow_random_weights": True}
    fid = mt.image.FrechetInceptionDistance(feature=2048, **kwargs)
    kid = mt.image.KernelInceptionDistance(feature=2048, subsets=4, subset_size=32, **kwargs)
    iscore = mt.image.InceptionScore(**kwargs)

    for real, fake in zip(real_batches, fake_batches):
        fid.update(real, real=True)
        fid.update(fake, real=False)
        kid.update(real, real=True)
        kid.update(fake, real=False)
        iscore.update(fake)

    print(f"FID: {float(fid.compute()):.4f}")
    kid_mean, kid_std = kid.compute()
    print(f"KID: {float(kid_mean):.6f} +- {float(kid_std):.6f}")
    is_mean, is_std = iscore.compute()
    print(f"IS:  {float(is_mean):.4f} +- {float(is_std):.4f}")

    # LPIPS expects float images in [-1, 1]
    lpips = mt.image.LearnedPerceptualImagePatchSimilarity(net_type="alex", allow_random_weights=True)
    for real, fake in zip(real_batches, fake_batches):
        lpips.update(
            (real[:8].astype(np.float32) / 127.5 - 1.0),
            (fake[:8].astype(np.float32) / 127.5 - 1.0),
        )
    print(f"LPIPS: {float(lpips.compute()):.4f}")

    # reset_real_features=False pattern: keep real statistics across evals
    fid.reset()  # fake side cleared; real side kept when reset_real_features=False


if __name__ == "__main__":
    main()
