"""Global test config: force an 8-device virtual CPU mesh.

The suite must behave identically whether launched on a TPU host or a plain CPU
box, and must exercise multi-device SPMD sync without real chips
(SURVEY §4 "What to replicate on TPU"). We therefore pin the CPU backend with 8
virtual devices *before* any JAX backend initialisation. ``bench.py`` does NOT
import this and runs on the real accelerator.
"""
import os

_FLAG = "--xla_force_host_platform_device_count=8"
if _FLAG not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") + " " + _FLAG).strip()

# The suite runs in validation mode "first" — the documented throughput
# configuration, which is what engages the fused one-program paths AND the
# deferred micro-batched dispatch queue, so the whole tier-1 surface
# exercises queue-flushed execution against its eager oracles. (The LIBRARY
# default is "full"; tests that pin the out-of-the-box default clear this
# env var and reset the cached mode themselves.)
os.environ.setdefault("METRICS_TPU_VALIDATION", "first")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _seed():
    from tests.helpers import seed_all

    seed_all(42)
    yield


def pytest_configure(config):
    assert jax.device_count() >= 8, f"expected >=8 virtual cpu devices, got {jax.devices()}"
