"""COCO mAP — differential tests against the reference MeanAveragePrecision.

The reference needs three torchvision box ops at runtime; torchvision is not
installed here, so pure-torch stand-ins are injected into the reference
module (they are ~15 lines of tensor math, defined below from the published
op semantics, not copied code).
"""
import jax.numpy as jnp
import numpy as np
import pytest

from metrics_tpu.detection import MeanAveragePrecision
from metrics_tpu.functional.detection import box_area, box_convert, box_iou, mask_iou
from tests.helpers.reference_oracle import get_reference

_ref = get_reference()
needs_ref = pytest.mark.skipif(_ref is None, reason="reference implementation not importable")


def _make_reference_map(**kwargs):
    """Reference MeanAveragePrecision with torch box-op stand-ins injected."""
    import torch

    import torchmetrics.detection.mean_ap as ref_map

    def t_box_area(boxes):
        return (boxes[:, 2] - boxes[:, 0]) * (boxes[:, 3] - boxes[:, 1])

    def t_box_iou(b1, b2):
        a1, a2 = t_box_area(b1), t_box_area(b2)
        lt = torch.max(b1[:, None, :2], b2[None, :, :2])
        rb = torch.min(b1[:, None, 2:], b2[None, :, 2:])
        wh = (rb - lt).clamp(min=0)
        inter = wh[..., 0] * wh[..., 1]
        return inter / (a1[:, None] + a2[None, :] - inter)

    def t_box_convert(boxes, in_fmt, out_fmt):
        if in_fmt == "xywh":
            x, y, w, h = boxes.unbind(-1)
            boxes = torch.stack([x, y, x + w, y + h], dim=-1)
        elif in_fmt == "cxcywh":
            cx, cy, w, h = boxes.unbind(-1)
            boxes = torch.stack([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2], dim=-1)
        if out_fmt == "xyxy":
            return boxes
        raise NotImplementedError

    ref_map._TORCHVISION_GREATER_EQUAL_0_8 = True
    ref_map.box_area = t_box_area
    ref_map.box_iou = t_box_iou
    ref_map.box_convert = t_box_convert
    return ref_map.MeanAveragePrecision(**kwargs)


def _random_scenario(rng, n_images=6, n_classes=4, max_boxes=8, seed_scale=500.0):
    """Random det/gt dicts with overlapping boxes across size categories."""
    preds, targets = [], []
    for _ in range(n_images):
        n_gt = rng.randint(0, max_boxes)
        n_det = rng.randint(0, max_boxes)
        # anchor some detections near GT boxes so matches exist
        gt_xy = rng.rand(n_gt, 2) * seed_scale
        gt_wh = rng.rand(n_gt, 2) * 100 + 2
        gt_boxes = np.concatenate([gt_xy, gt_xy + gt_wh], axis=1).astype(np.float32)
        det_boxes = []
        for j in range(n_det):
            if n_gt > 0 and rng.rand() < 0.7:
                base = gt_boxes[rng.randint(n_gt)]
                jitter = rng.randn(4) * 5
                det_boxes.append(base + jitter)
            else:
                xy = rng.rand(2) * seed_scale
                wh = rng.rand(2) * 100 + 2
                det_boxes.append(np.concatenate([xy, xy + wh]))
        det_boxes = np.asarray(det_boxes, dtype=np.float32).reshape(n_det, 4)
        det_boxes[:, 2:] = np.maximum(det_boxes[:, 2:], det_boxes[:, :2] + 1)

        preds.append(
            dict(
                boxes=det_boxes,
                scores=rng.rand(n_det).astype(np.float32),
                labels=rng.randint(0, n_classes, n_det),
            )
        )
        targets.append(dict(boxes=gt_boxes, labels=rng.randint(0, n_classes, n_gt)))
    return preds, targets


def _to_jnp(dicts):
    return [{k: jnp.asarray(v) for k, v in d.items()} for d in dicts]


def _to_torch(dicts):
    import torch

    return [{k: torch.from_numpy(np.asarray(v)) for k, v in d.items()} for d in dicts]


def _assert_results_close(got, ref, atol=1e-5):
    for key, ref_val in ref.items():
        np.testing.assert_allclose(
            np.asarray(got[key]), ref_val.numpy(), atol=atol, err_msg=f"mismatch for {key}"
        )


@needs_ref
class TestMeanAveragePrecision:
    def test_docstring_example(self):
        preds = [dict(boxes=jnp.asarray([[258.0, 41.0, 606.0, 285.0]]), scores=jnp.asarray([0.536]), labels=jnp.asarray([0]))]
        target = [dict(boxes=jnp.asarray([[214.0, 41.0, 562.0, 285.0]]), labels=jnp.asarray([0]))]
        metric = MeanAveragePrecision()
        metric.update(preds, target)
        result = metric.compute()
        assert round(float(result["map"]), 4) == 0.6
        assert float(result["map_50"]) == 1.0
        assert float(result["map_75"]) == 1.0
        assert float(result["map_small"]) == -1.0
        assert round(float(result["mar_1"]), 4) == 0.6

    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_random_scenarios(self, seed):
        rng = np.random.RandomState(seed)
        preds, targets = _random_scenario(rng)

        metric = MeanAveragePrecision()
        metric.update(_to_jnp(preds), _to_jnp(targets))
        got = metric.compute()

        ref_metric = _make_reference_map()
        ref_metric.update(_to_torch(preds), _to_torch(targets))
        ref = ref_metric.compute()
        _assert_results_close(got, ref)

    def test_class_metrics(self):
        rng = np.random.RandomState(7)
        preds, targets = _random_scenario(rng)

        metric = MeanAveragePrecision(class_metrics=True)
        metric.update(_to_jnp(preds), _to_jnp(targets))
        got = metric.compute()

        ref_metric = _make_reference_map(class_metrics=True)
        ref_metric.update(_to_torch(preds), _to_torch(targets))
        ref = ref_metric.compute()
        _assert_results_close(got, ref)

    @pytest.mark.parametrize("box_format", ["xywh", "cxcywh"])
    def test_box_formats(self, box_format):
        rng = np.random.RandomState(3)
        preds, targets = _random_scenario(rng)
        # re-express xyxy boxes in the alternative format
        def conv(d):
            out = dict(d)
            b = np.asarray(d["boxes"], dtype=np.float32).reshape(-1, 4)
            if box_format == "xywh":
                out["boxes"] = np.concatenate([b[:, :2], b[:, 2:] - b[:, :2]], axis=1)
            else:
                out["boxes"] = np.concatenate([(b[:, :2] + b[:, 2:]) / 2, b[:, 2:] - b[:, :2]], axis=1)
            return out

        metric = MeanAveragePrecision(box_format=box_format)
        metric.update(_to_jnp([conv(p) for p in preds]), _to_jnp([conv(t) for t in targets]))
        got = metric.compute()

        ref_metric = _make_reference_map()
        ref_metric.update(_to_torch(preds), _to_torch(targets))
        ref = ref_metric.compute()
        _assert_results_close(got, ref)

    def test_custom_thresholds(self):
        rng = np.random.RandomState(11)
        preds, targets = _random_scenario(rng)
        kwargs = dict(iou_thresholds=[0.3, 0.6], max_detection_thresholds=[2, 5])

        metric = MeanAveragePrecision(**kwargs)
        metric.update(_to_jnp(preds), _to_jnp(targets))
        got = metric.compute()

        ref_metric = _make_reference_map(**kwargs)
        ref_metric.update(_to_torch(preds), _to_torch(targets))
        ref = ref_metric.compute()
        _assert_results_close(got, ref)
        assert "mar_5_per_class" in got

    def test_two_rank_merge(self):
        """Emulated 2-rank accumulation: list states concatenate across ranks."""
        rng = np.random.RandomState(5)
        preds, targets = _random_scenario(rng, n_images=8)

        m0 = MeanAveragePrecision()
        m1 = MeanAveragePrecision()
        m0.update(_to_jnp(preds[:4]), _to_jnp(targets[:4]))
        m1.update(_to_jnp(preds[4:]), _to_jnp(targets[4:]))
        # merge rank-1 lists into rank-0 (the None-reduction gather semantics)
        for name in m0._defaults:
            getattr(m0, name).extend(getattr(m1, name))
        got = m0.compute()

        ref_metric = _make_reference_map()
        ref_metric.update(_to_torch(preds), _to_torch(targets))
        ref = ref_metric.compute()
        _assert_results_close(got, ref)

    def test_empty_preds_and_gt(self):
        metric = MeanAveragePrecision()
        metric.update(
            [dict(boxes=jnp.zeros((0, 4)), scores=jnp.zeros((0,)), labels=jnp.zeros((0,), dtype=jnp.int32))],
            [dict(boxes=jnp.zeros((0, 4)), labels=jnp.zeros((0,), dtype=jnp.int32))],
        )
        result = metric.compute()
        assert float(result["map"]) == -1.0

    def test_input_validation(self):
        metric = MeanAveragePrecision()
        with pytest.raises(ValueError, match="same length"):
            metric.update([], [dict(boxes=jnp.zeros((0, 4)), labels=jnp.zeros((0,)))])
        with pytest.raises(ValueError, match="`scores`"):
            metric.update([dict(boxes=jnp.zeros((0, 4)), labels=jnp.zeros((0,)))], [dict(boxes=jnp.zeros((0, 4)), labels=jnp.zeros((0,)))])
        with pytest.raises(ValueError, match="box_format"):
            MeanAveragePrecision(box_format="abcd")
        with pytest.raises(ValueError, match="iou_type"):
            MeanAveragePrecision(iou_type="abcd")


class TestSegmIoU:
    def test_mask_map_perfect_match(self):
        rng = np.random.RandomState(0)
        masks = rng.rand(3, 32, 32) > 0.5
        preds = [dict(masks=jnp.asarray(masks), scores=jnp.asarray([0.9, 0.8, 0.7]), labels=jnp.asarray([0, 1, 0]))]
        target = [dict(masks=jnp.asarray(masks), labels=jnp.asarray([0, 1, 0]))]
        metric = MeanAveragePrecision(iou_type="segm")
        metric.update(preds, target)
        result = metric.compute()
        assert float(result["map"]) == 1.0
        assert float(result["mar_100"]) == 1.0

    def test_mask_map_disjoint(self):
        m1 = np.zeros((1, 16, 16), dtype=bool)
        m1[:, :8] = True
        m2 = ~m1
        preds = [dict(masks=jnp.asarray(m1), scores=jnp.asarray([0.9]), labels=jnp.asarray([0]))]
        target = [dict(masks=jnp.asarray(m2), labels=jnp.asarray([0]))]
        metric = MeanAveragePrecision(iou_type="segm")
        metric.update(preds, target)
        assert float(metric.compute()["map"]) == 0.0


class TestBoxOps:
    def test_box_iou_values(self):
        b1 = jnp.asarray([[0.0, 0.0, 10.0, 10.0]])
        b2 = jnp.asarray([[5.0, 5.0, 15.0, 15.0], [0.0, 0.0, 10.0, 10.0], [20.0, 20.0, 30.0, 30.0]])
        iou = np.asarray(box_iou(b1, b2))
        np.testing.assert_allclose(iou[0], [25 / 175, 1.0, 0.0], atol=1e-6)

    def test_box_convert_roundtrip(self):
        rng = np.random.RandomState(0)
        xy = rng.rand(5, 2) * 100
        wh = rng.rand(5, 2) * 50 + 1
        xyxy = jnp.asarray(np.concatenate([xy, xy + wh], axis=1).astype(np.float32))
        for fmt in ("xywh", "cxcywh"):
            other = box_convert(xyxy, "xyxy", fmt)
            back = box_convert(other, fmt, "xyxy")
            np.testing.assert_allclose(np.asarray(back), np.asarray(xyxy), atol=1e-4)

    def test_box_area(self):
        assert float(box_area(jnp.asarray([[0.0, 0.0, 4.0, 5.0]]))[0]) == 20.0

    def test_mask_iou(self):
        a = np.zeros((1, 4, 4), dtype=bool)
        a[:, :2] = True
        b = np.zeros((1, 4, 4), dtype=bool)
        b[:, 1:3] = True
        iou = np.asarray(mask_iou(jnp.asarray(a), jnp.asarray(b)))
        np.testing.assert_allclose(iou, [[4 / 12]], atol=1e-6)


class TestDeferredMaterialization:
    """The zero-sync update path defers device fetches to compute(); these pin
    the review-found hazards: base-class machinery converting state entries
    numpy<->jax must never skip or repeat normalization."""

    @staticmethod
    def _xywh_pair():
        import jax.numpy as jnp

        boxes_xywh = jnp.asarray([[10.0, 10.0, 20.0, 20.0], [40.0, 40.0, 20.0, 20.0]])
        boxes_xyxy = jnp.asarray([[10.0, 10.0, 30.0, 30.0], [40.0, 40.0, 60.0, 60.0]])
        labels = jnp.asarray([0, 1])
        scores = jnp.asarray([0.9, 0.8])
        return boxes_xywh, boxes_xyxy, labels, scores

    def test_compute_on_cpu_still_converts_boxes(self):
        import metrics_tpu as mt

        boxes_xywh, boxes_xyxy, labels, scores = self._xywh_pair()
        metric = mt.MeanAveragePrecision(box_format="xywh", compute_on_cpu=True)
        metric.update(
            [dict(boxes=boxes_xywh, scores=scores, labels=labels)],
            [dict(boxes=boxes_xywh, labels=labels)],
        )
        assert float(metric.compute()["map"]) == pytest.approx(1.0)

    def test_astype_round_trip_does_not_double_convert(self):
        import metrics_tpu as mt

        boxes_xywh, boxes_xyxy, labels, scores = self._xywh_pair()
        metric = mt.MeanAveragePrecision(box_format="xywh")
        # numpy inputs: normalized (converted to xyxy) at update time
        metric.update(
            [dict(boxes=np.asarray(boxes_xywh), scores=np.asarray(scores), labels=np.asarray(labels))],
            [dict(boxes=np.asarray(boxes_xywh), labels=np.asarray(labels))],
        )
        metric.float()  # re-wraps host state entries as jax arrays
        assert float(metric.compute()["map"]) == pytest.approx(1.0)

    def test_device_and_host_inputs_agree(self):
        import jax.numpy as jnp

        import metrics_tpu as mt

        rng = np.random.RandomState(4)
        n = 12
        xy = rng.rand(n, 2).astype(np.float32) * 100
        wh = 10 + rng.rand(n, 2).astype(np.float32) * 40
        boxes = np.concatenate([xy, wh], 1)
        labels = rng.randint(0, 3, n)
        scores = rng.rand(n).astype(np.float32)
        gxy = rng.rand(5, 2).astype(np.float32) * 100
        gwh = 10 + rng.rand(5, 2).astype(np.float32) * 40
        gboxes = np.concatenate([gxy, gwh], 1)
        glabels = rng.randint(0, 3, 5)

        host = mt.MeanAveragePrecision(box_format="xywh")
        host.update(
            [dict(boxes=boxes, scores=scores, labels=labels)], [dict(boxes=gboxes, labels=glabels)]
        )
        device = mt.MeanAveragePrecision(box_format="xywh")
        device.update(
            [dict(boxes=jnp.asarray(boxes), scores=jnp.asarray(scores), labels=jnp.asarray(labels))],
            [dict(boxes=jnp.asarray(gboxes), labels=jnp.asarray(glabels))],
        )
        for key, value in host.compute().items():
            np.testing.assert_allclose(
                np.asarray(value), np.asarray(device.compute()[key]), atol=1e-6, err_msg=key
            )
