"""The mAP option grid vs the mounted reference.

Densifies tests/detection/test_mean_ap.py's sampled options into a grid:
seeds x iou_thresholds x max_detection_thresholds x class_metrics, plus
degenerate-image cells (no detections / no ground truth / both empty mixed
into a normal stream). Every cell runs identical data through both stacks
(reference `detection/mean_ap.py:543-877` greedy matching + 101-pt interp).
"""
from __future__ import annotations

import numpy as np
import pytest

from metrics_tpu.detection import MeanAveragePrecision
from tests.detection.test_mean_ap import (
    _assert_results_close,
    _make_reference_map,
    _random_scenario,
    _to_jnp,
    _to_torch,
)
from tests.helpers import cell_seed
from tests.helpers.reference_oracle import get_reference

_ref = get_reference()
pytestmark = pytest.mark.skipif(_ref is None, reason="reference implementation not importable")


def _run_cell(preds, targets, **kwargs):
    metric = MeanAveragePrecision(**kwargs)
    metric.update(_to_jnp(preds), _to_jnp(targets))
    got = metric.compute()
    ref_metric = _make_reference_map(**kwargs)
    ref_metric.update(_to_torch(preds), _to_torch(targets))
    _assert_results_close(got, ref_metric.compute())


class TestOptionGrid:
    @pytest.mark.parametrize("seed", (0, 1))
    @pytest.mark.parametrize(
        "iou_thresholds", (None, [0.5], [0.35, 0.55, 0.75]), ids=("coco", "single", "custom")
    )
    @pytest.mark.parametrize("max_detection_thresholds", (None, [1, 3, 6]), ids=("coco", "custom"))
    @pytest.mark.parametrize("class_metrics", (False, True))
    def test_cell(self, seed, iou_thresholds, max_detection_thresholds, class_metrics):
        rng = np.random.RandomState(cell_seed("map", seed, str(iou_thresholds), str(max_detection_thresholds)))
        preds, targets = _random_scenario(rng)
        _run_cell(
            preds,
            targets,
            iou_thresholds=iou_thresholds,
            max_detection_thresholds=max_detection_thresholds,
            class_metrics=class_metrics,
        )

    @pytest.mark.parametrize("rec_thresholds", ([0.0, 0.5, 1.0],), ids=("coarse",))
    def test_rec_thresholds(self, rec_thresholds):
        rng = np.random.RandomState(cell_seed("map-rec"))
        preds, targets = _random_scenario(rng)
        _run_cell(preds, targets, rec_thresholds=rec_thresholds)


class TestDegenerateImages:
    """Empty-side images interleaved into a normal stream."""

    def _scenario_with_empties(self, seed):
        rng = np.random.RandomState(seed)
        preds, targets = _random_scenario(rng, n_images=4)
        empty_det = dict(
            boxes=np.zeros((0, 4), np.float32), scores=np.zeros((0,), np.float32), labels=np.zeros((0,), np.int64)
        )
        empty_gt = dict(boxes=np.zeros((0, 4), np.float32), labels=np.zeros((0,), np.int64))
        full_det, full_gt = preds[0], targets[0]
        preds += [empty_det, full_det, empty_det]
        targets += [full_gt, empty_gt, empty_gt]
        return preds, targets

    @pytest.mark.parametrize("class_metrics", (False, True))
    def test_empties(self, class_metrics):
        preds, targets = self._scenario_with_empties(cell_seed("map-empty", class_metrics))
        _run_cell(preds, targets, class_metrics=class_metrics)

    def test_all_images_empty(self):
        empty_det = dict(
            boxes=np.zeros((0, 4), np.float32), scores=np.zeros((0,), np.float32), labels=np.zeros((0,), np.int64)
        )
        empty_gt = dict(boxes=np.zeros((0, 4), np.float32), labels=np.zeros((0,), np.int64))
        _run_cell([empty_det] * 3, [empty_gt] * 3)
