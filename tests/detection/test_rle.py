"""COCO RLE codec tests: round-trips, column-major convention, and the
MeanAveragePrecision segm path accepting RLE inputs."""
import jax.numpy as jnp
import numpy as np
import pytest

from metrics_tpu.functional.detection.rle import (
    _decode_compressed_counts,
    _encode_compressed_counts,
    masks_from_any,
    rle_decode,
    rle_encode,
)


class TestRleCodec:
    @pytest.mark.parametrize("seed", [0, 1, 2])
    @pytest.mark.parametrize("compress", [False, True])
    def test_round_trip(self, seed, compress):
        rng = np.random.RandomState(seed)
        mask = rng.rand(23, 17) > 0.6
        rle = rle_encode(mask, compress=compress)
        np.testing.assert_array_equal(rle_decode(rle), mask)

    def test_column_major_convention(self):
        # a single set pixel at (row=1, col=0) in a 3x2 mask: column-major
        # offset = 1 -> counts [1, 1, 4]
        mask = np.zeros((3, 2), dtype=bool)
        mask[1, 0] = True
        rle = rle_encode(mask, compress=False)
        assert rle["counts"] == [1, 1, 4]
        np.testing.assert_array_equal(rle_decode(rle), mask)

    def test_counts_string_round_trip(self):
        counts = [0, 5, 3, 2, 40, 1, 9]
        assert _decode_compressed_counts(_encode_compressed_counts(counts)) == counts

    def test_all_ones_and_all_zeros(self):
        ones = np.ones((4, 4), dtype=bool)
        zeros = np.zeros((4, 4), dtype=bool)
        for m in (ones, zeros):
            np.testing.assert_array_equal(rle_decode(rle_encode(m)), m)

    def test_bad_counts_raises(self):
        with pytest.raises(ValueError, match="counts sum"):
            rle_decode({"size": [4, 4], "counts": [3]})

    def test_masks_from_any_forms(self):
        rng = np.random.RandomState(3)
        dense = rng.rand(2, 8, 8) > 0.5
        rles = [rle_encode(m) for m in dense]
        np.testing.assert_array_equal(masks_from_any(rles), dense)
        np.testing.assert_array_equal(masks_from_any(rles[0]), dense[:1])
        np.testing.assert_array_equal(masks_from_any(dense), dense)
        np.testing.assert_array_equal(masks_from_any(dense[0]), dense[:1])


def test_mean_ap_accepts_rle_masks():
    from metrics_tpu import MeanAveragePrecision

    rng = np.random.RandomState(0)
    gt_mask = np.zeros((16, 16), dtype=bool)
    gt_mask[2:10, 2:10] = True
    det_mask = np.zeros((16, 16), dtype=bool)
    det_mask[3:11, 3:11] = True

    m_rle = MeanAveragePrecision(iou_type="segm")
    m_rle.update(
        [{"masks": [rle_encode(det_mask)], "scores": jnp.asarray([0.9]), "labels": jnp.asarray([0])}],
        [{"masks": [rle_encode(gt_mask)], "labels": jnp.asarray([0])}],
    )
    m_dense = MeanAveragePrecision(iou_type="segm")
    m_dense.update(
        [{"masks": det_mask[None], "scores": jnp.asarray([0.9]), "labels": jnp.asarray([0])}],
        [{"masks": gt_mask[None], "labels": jnp.asarray([0])}],
    )
    r1, r2 = m_rle.compute(), m_dense.compute()
    np.testing.assert_allclose(float(r1["map"]), float(r2["map"]), atol=1e-6)
