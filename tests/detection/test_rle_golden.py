"""Golden COCO RLE vectors (spec-derived) for the segm path.

The reference defers segm mask I/O to pycocotools ``mask_utils``
(`/root/reference/src/torchmetrics/detection/mean_ap.py:127-143`); this repo
ships its own codec (`functional/detection/rle.py`). Previously the codec was
tested only round-trip against itself — these fixtures pin it to the
PUBLISHED encoding: `tests/fixtures/coco_rle_golden.json` holds hand-derived
counts arrays, compressed strings (each derivation documented in the file),
and analytically-known mask IoUs, so an encoding drift from the COCO spec
fails here even though pycocotools itself is not installed.
"""
from __future__ import annotations

import json
import os

import jax.numpy as jnp
import numpy as np
import pytest

from metrics_tpu.functional.detection.rle import rle_decode, rle_encode

_FIXTURE = os.path.join(os.path.dirname(__file__), "..", "fixtures", "coco_rle_golden.json")


def _load():
    with open(_FIXTURE) as handle:
        return json.load(handle)


def _dense_from_counts(size, counts) -> np.ndarray:
    """Independent decoder: expand counts column-major with plain python."""
    h, w = size
    flat = []
    bit = 0
    for run in counts:
        flat.extend([bit] * run)
        bit ^= 1
    assert len(flat) == h * w
    return np.asarray(flat, dtype=bool).reshape((w, h)).T


_CASES = {c["name"]: c for c in _load()["cases"]}
_IOU_CASES = {c["name"]: c for c in _load()["iou_cases"]}


class TestGoldenVectors:
    @pytest.mark.parametrize("name", sorted(_CASES))
    def test_decode_compressed_matches_golden_mask(self, name):
        case = _CASES[name]
        want = _dense_from_counts(case["size"], case["counts_uncompressed"])
        if "mask" in case:  # the human-readable form must agree with counts
            rows = np.asarray([[ch == "1" for ch in row] for row in case["mask"]])
            np.testing.assert_array_equal(rows, want)
        got = rle_decode({"size": case["size"], "counts": case["counts_compressed"]})
        np.testing.assert_array_equal(got, want)

    @pytest.mark.parametrize("name", sorted(_CASES))
    def test_decode_uncompressed_matches_golden_mask(self, name):
        case = _CASES[name]
        want = _dense_from_counts(case["size"], case["counts_uncompressed"])
        got = rle_decode({"size": case["size"], "counts": case["counts_uncompressed"]})
        np.testing.assert_array_equal(got, want)

    @pytest.mark.parametrize("name", sorted(_CASES))
    def test_encode_produces_golden_counts_and_string(self, name):
        case = _CASES[name]
        mask = _dense_from_counts(case["size"], case["counts_uncompressed"])
        assert rle_encode(mask, compress=False)["counts"] == case["counts_uncompressed"]
        got = rle_encode(mask, compress=True)["counts"]
        got = got.decode("ascii") if isinstance(got, bytes) else got
        assert got == case["counts_compressed"]


class TestGoldenIoU:
    @pytest.mark.parametrize("name", sorted(_IOU_CASES))
    def test_mask_iou_matches_analytic(self, name):
        from metrics_tpu.functional.detection.box_ops import mask_iou

        case = _IOU_CASES[name]
        a = _dense_from_counts(case["size"], case["a"]["counts"])
        b = _dense_from_counts(case["size"], case["b"]["counts"])
        assert int((a & b).sum()) == case["intersection"]
        assert int((a | b).sum()) == case["union"]
        got = float(np.asarray(mask_iou(jnp.asarray(a[None]), jnp.asarray(b[None])))[0, 0])
        assert got == pytest.approx(case["iou"], abs=1e-6)


class TestSegmMapGolden:
    """Analytic segm-mAP anchors through the full metric."""

    def _run(self, det_mask, gt_mask):
        from metrics_tpu import MeanAveragePrecision

        metric = MeanAveragePrecision(iou_type="segm")
        metric.update(
            [{
                "masks": [rle_encode(det_mask)],
                "scores": jnp.asarray([0.9]),
                "labels": jnp.asarray([0]),
            }],
            [{"masks": [rle_encode(gt_mask)], "labels": jnp.asarray([0])}],
        )
        return float(metric.compute()["map"])

    def test_perfect_prediction_is_one(self):
        mask = _dense_from_counts([16, 16], [32, 64, 160])
        assert self._run(mask, mask) == pytest.approx(1.0, abs=1e-6)

    def test_052_overlap_matches_one_threshold(self):
        """IoU = 13/25 = 0.52: above 0.50 only, so exactly one of the ten
        COCO thresholds matches -> mAP 0.1 (values chosen away from
        threshold-equality so float rounding cannot flip the comparison)."""
        gt = _dense_from_counts([1, 25], [0, 19, 6])    # cols 0-18
        det = _dense_from_counts([1, 25], [6, 19])      # cols 6-24
        inter, union = 13, 25
        assert int((gt & det).sum()) == inter and int((gt | det).sum()) == union
        assert self._run(det, gt) == pytest.approx(0.1, abs=1e-6)
