"""Text metrics — differential tests against the mounted reference implementation.

The reference (pure-python torch) is the authoritative oracle for text metrics:
tokenization conventions and shift/jump heuristics are hard to pin with
third-party oracles. Skips gracefully if the mount is absent.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from metrics_tpu import (
    BLEUScore,
    CharErrorRate,
    CHRFScore,
    ExtendedEditDistance,
    MatchErrorRate,
    Perplexity,
    ROUGEScore,
    SacreBLEUScore,
    SQuAD,
    TranslationEditRate,
    WordErrorRate,
    WordInfoLost,
    WordInfoPreserved,
)
from metrics_tpu.functional import (
    bleu_score,
    char_error_rate,
    chrf_score,
    extended_edit_distance,
    match_error_rate,
    perplexity,
    rouge_score,
    sacre_bleu_score,
    squad,
    translation_edit_rate,
    word_error_rate,
    word_information_lost,
    word_information_preserved,
)
from tests.helpers.reference_oracle import get_reference

_PREDS = [
    "the cat is on the mat",
    "hello world how are you",
    "this is a completely different sentence with many words",
    "short one",
]
_TARGETS_SINGLE = [
    "there is a cat on the mat",
    "hello world how do you do",
    "this is a rather different sentence with several words",
    "a short one",
]
_TARGETS_MULTI = [[t, "an alternative reference sentence"] for t in _TARGETS_SINGLE]

_ref = get_reference()
needs_ref = pytest.mark.skipif(_ref is None, reason="reference implementation not importable")


def _ref_val(x):
    import torch

    return x.numpy() if hasattr(x, "numpy") else np.asarray(x)


@needs_ref
class TestAgainstReference:
    def test_bleu(self):
        ref = _ref.functional.bleu_score(_PREDS, _TARGETS_MULTI)
        res = bleu_score(_PREDS, _TARGETS_MULTI)
        np.testing.assert_allclose(np.asarray(res), _ref_val(ref), atol=1e-5)

    def test_bleu_smooth(self):
        ref = _ref.functional.bleu_score(_PREDS, _TARGETS_MULTI, smooth=True)
        res = bleu_score(_PREDS, _TARGETS_MULTI, smooth=True)
        np.testing.assert_allclose(np.asarray(res), _ref_val(ref), atol=1e-5)

    @pytest.mark.parametrize("tokenize", ["13a", "char", "none", "intl"])
    def test_sacre_bleu(self, tokenize):
        ref = _ref.functional.sacre_bleu_score(_PREDS, _TARGETS_MULTI, tokenize=tokenize)
        res = sacre_bleu_score(_PREDS, _TARGETS_MULTI, tokenize=tokenize)
        np.testing.assert_allclose(np.asarray(res), _ref_val(ref), atol=1e-5)

    @pytest.mark.parametrize(
        "fn_name, my_fn",
        [
            ("word_error_rate", word_error_rate),
            ("char_error_rate", char_error_rate),
            ("match_error_rate", match_error_rate),
            ("word_information_lost", word_information_lost),
            ("word_information_preserved", word_information_preserved),
        ],
    )
    def test_error_rates(self, fn_name, my_fn):
        ref = getattr(_ref.functional, fn_name)(_PREDS, _TARGETS_SINGLE)
        res = my_fn(_PREDS, _TARGETS_SINGLE)
        np.testing.assert_allclose(np.asarray(res), _ref_val(ref), atol=1e-5)

    @pytest.mark.parametrize("accumulate", ["best", "avg"])
    def test_rouge(self, accumulate, monkeypatch):
        # rougeLsum excluded: the reference's Lsum needs an nltk download
        # (unavailable offline); ours follows rouge_score's newline convention.
        # The reference calls its punkt-backed _split_sentence even for
        # non-Lsum keys, so stub it with a newline split for the unused path.
        import torchmetrics.functional.text.rouge as ref_rouge

        monkeypatch.setattr(ref_rouge, "_split_sentence", lambda x: x.split("\n"))
        keys = ("rouge1", "rouge2", "rougeL")
        ref = _ref.functional.rouge_score(_PREDS, _TARGETS_MULTI, accumulate=accumulate, rouge_keys=keys)
        res = rouge_score(_PREDS, _TARGETS_MULTI, accumulate=accumulate, rouge_keys=keys)
        for key in ref:
            np.testing.assert_allclose(
                np.asarray(res[key]), _ref_val(ref[key]), atol=1e-5, err_msg=f"mismatch on {key}"
            )

    def test_rouge_lsum_self(self):
        pred = "the cat is here\nthe dog is there"
        tgt = "a cat is here\nthe dog was there"
        res = rouge_score(pred, tgt, rouge_keys="rougeLsum")
        assert 0.0 < float(res["rougeLsum_fmeasure"]) <= 1.0
        same = rouge_score(pred, pred, rouge_keys="rougeLsum")
        np.testing.assert_allclose(np.asarray(same["rougeLsum_fmeasure"]), 1.0, atol=1e-6)

    def test_chrf(self):
        ref = _ref.functional.chrf_score(_PREDS, _TARGETS_MULTI)
        res = chrf_score(_PREDS, _TARGETS_MULTI)
        np.testing.assert_allclose(np.asarray(res), _ref_val(ref), atol=1e-5)

    def test_chrf_plain_no_word_order(self):
        ref = _ref.functional.chrf_score(_PREDS, _TARGETS_SINGLE, n_word_order=0)
        res = chrf_score(_PREDS, _TARGETS_SINGLE, n_word_order=0)
        np.testing.assert_allclose(np.asarray(res), _ref_val(ref), atol=1e-5)

    def test_ter(self):
        ref = _ref.functional.translation_edit_rate(_PREDS, _TARGETS_MULTI)
        res = translation_edit_rate(_PREDS, _TARGETS_MULTI)
        np.testing.assert_allclose(np.asarray(res), _ref_val(ref), atol=1e-5)

    def test_ter_options(self):
        ref = _ref.functional.translation_edit_rate(_PREDS, _TARGETS_SINGLE, normalize=True, lowercase=False)
        res = translation_edit_rate(_PREDS, _TARGETS_SINGLE, normalize=True, lowercase=False)
        np.testing.assert_allclose(np.asarray(res), _ref_val(ref), atol=1e-5)

    def test_eed(self):
        ref = _ref.functional.extended_edit_distance(_PREDS, _TARGETS_SINGLE)
        res = extended_edit_distance(_PREDS, _TARGETS_SINGLE)
        np.testing.assert_allclose(np.asarray(res), _ref_val(ref), atol=1e-5)

    def test_squad(self):
        preds = [{"prediction_text": "1976", "id": "id1"}, {"prediction_text": "the big apple", "id": "id2"}]
        target = [
            {"answers": {"answer_start": [97], "text": ["1976"]}, "id": "id1"},
            {"answers": {"answer_start": [1], "text": ["The Big Apple", "New York"]}, "id": "id2"},
        ]
        ref = _ref.functional.squad(preds, target)
        res = squad(preds, target)
        for key in ("exact_match", "f1"):
            np.testing.assert_allclose(np.asarray(res[key]), _ref_val(ref[key]), atol=1e-4)

    def test_perplexity(self):
        rng = np.random.RandomState(3)
        logits = rng.randn(2, 8, 5).astype(np.float32)
        labels = rng.randint(0, 5, (2, 8))
        import torch

        ref = _ref.functional.perplexity(torch.tensor(logits), torch.tensor(labels), ignore_index=None)
        res = perplexity(jnp.asarray(logits), jnp.asarray(labels))
        np.testing.assert_allclose(np.asarray(res), _ref_val(ref), atol=1e-3)


class TestModules:
    def test_bleu_module_accumulates(self):
        m = BLEUScore()
        m.update(_PREDS[:2], _TARGETS_MULTI[:2])
        m.update(_PREDS[2:], _TARGETS_MULTI[2:])
        np.testing.assert_allclose(
            np.asarray(m.compute()), np.asarray(bleu_score(_PREDS, _TARGETS_MULTI)), atol=1e-6
        )

    def test_wer_module_accumulates(self):
        m = WordErrorRate()
        m.update(_PREDS[:2], _TARGETS_SINGLE[:2])
        m.update(_PREDS[2:], _TARGETS_SINGLE[2:])
        np.testing.assert_allclose(
            np.asarray(m.compute()), np.asarray(word_error_rate(_PREDS, _TARGETS_SINGLE)), atol=1e-6
        )

    def test_rouge_module(self):
        m = ROUGEScore(rouge_keys="rouge1")
        for p, t in zip(_PREDS, _TARGETS_MULTI):
            m.update(p, [t])
        out = m.compute()
        ref = rouge_score(_PREDS, _TARGETS_MULTI, rouge_keys="rouge1")
        np.testing.assert_allclose(np.asarray(out["rouge1_fmeasure"]), np.asarray(ref["rouge1_fmeasure"]), atol=1e-6)

    def test_perplexity_module_jit(self):
        m = Perplexity(ignore_index=-100)
        init, upd, cmp = m.as_functions()
        rng = np.random.RandomState(5)
        logits = jnp.asarray(rng.randn(2, 8, 5).astype(np.float32))
        labels = jnp.asarray(rng.randint(0, 5, (2, 8)))
        state = jax.jit(upd)(init(), logits, labels)
        eager = perplexity(logits, labels, ignore_index=-100)
        np.testing.assert_allclose(np.asarray(cmp(state)), np.asarray(eager), atol=1e-5)

    def test_squad_module(self):
        m = SQuAD()
        m.update(
            {"prediction_text": "1976", "id": "a"},
            {"answers": {"answer_start": [1], "text": ["1976"]}, "id": "a"},
        )
        out = m.compute()
        assert float(out["exact_match"]) == 100.0

    def test_chrf_module_matches_functional(self):
        m = CHRFScore()
        m.update(_PREDS[:2], _TARGETS_MULTI[:2])
        m.update(_PREDS[2:], _TARGETS_MULTI[2:])
        np.testing.assert_allclose(
            np.asarray(m.compute()), np.asarray(chrf_score(_PREDS, _TARGETS_MULTI)), atol=1e-6
        )

    def test_ter_module(self):
        m = TranslationEditRate()
        m.update(_PREDS[:2], _TARGETS_MULTI[:2])
        m.update(_PREDS[2:], _TARGETS_MULTI[2:])
        np.testing.assert_allclose(
            np.asarray(m.compute()), np.asarray(translation_edit_rate(_PREDS, _TARGETS_MULTI)), atol=1e-6
        )

    def test_eed_module(self):
        m = ExtendedEditDistance()
        m.update(_PREDS[:2], _TARGETS_SINGLE[:2])
        m.update(_PREDS[2:], _TARGETS_SINGLE[2:])
        np.testing.assert_allclose(
            np.asarray(m.compute()), np.asarray(extended_edit_distance(_PREDS, _TARGETS_SINGLE)), atol=1e-6
        )

    def test_wil_wip_modules(self):
        for cls, fn in ((WordInfoLost, word_information_lost), (WordInfoPreserved, word_information_preserved)):
            m = cls()
            m.update(_PREDS, _TARGETS_SINGLE)
            np.testing.assert_allclose(np.asarray(m.compute()), np.asarray(fn(_PREDS, _TARGETS_SINGLE)), atol=1e-6)

    def test_cer_mer_modules(self):
        for cls, fn in ((CharErrorRate, char_error_rate), (MatchErrorRate, match_error_rate)):
            m = cls()
            m.update(_PREDS, _TARGETS_SINGLE)
            np.testing.assert_allclose(np.asarray(m.compute()), np.asarray(fn(_PREDS, _TARGETS_SINGLE)), atol=1e-6)

    def test_sacre_bleu_module(self):
        m = SacreBLEUScore(tokenize="13a")
        m.update(_PREDS, _TARGETS_MULTI)
        np.testing.assert_allclose(
            np.asarray(m.compute()), np.asarray(sacre_bleu_score(_PREDS, _TARGETS_MULTI)), atol=1e-6
        )


class TestBertInfoLM:
    def test_bert_score_with_user_forward_fn(self):
        """BERTScore pipeline with a toy hash-embedding forward (offline path)."""

        def toy_forward(sentences):
            # like a transformers tokenizer, emit [CLS] tokens [SEP]: the
            # matcher zeroes the first and last real position (reference
            # user-path contract, `functional/text/bert.py` user_tokenizer doc)
            max_len = 12
            dim = 16
            emb = np.zeros((len(sentences), max_len, dim), dtype=np.float32)
            mask = np.zeros((len(sentences), max_len), dtype=np.float32)
            for i, s in enumerate(sentences):
                emb[i, 0] = np.random.RandomState(0).randn(dim)  # [CLS]
                mask[i, 0] = 1.0
                words = s.split()[: max_len - 2]
                for j, tok in enumerate(words, start=1):
                    rng = np.random.RandomState(abs(hash(tok)) % (2**31))
                    emb[i, j] = rng.randn(dim)
                    mask[i, j] = 1.0
                emb[i, len(words) + 1] = np.random.RandomState(1).randn(dim)  # [SEP]
                mask[i, len(words) + 1] = 1.0
            return jnp.asarray(emb), jnp.asarray(mask)

        from metrics_tpu.functional import bert_score

        out = bert_score(_PREDS, _TARGETS_SINGLE, user_forward_fn=toy_forward)
        assert set(out) == {"precision", "recall", "f1"}
        assert len(out["f1"]) == len(_PREDS)
        # identical sentences must score 1.0
        out_same = bert_score(_PREDS, _PREDS, user_forward_fn=toy_forward)
        np.testing.assert_allclose(out_same["f1"], 1.0, atol=1e-5)

    def test_infolm_measures(self):
        """All nine information measures on synthetic distributions."""
        from metrics_tpu.functional.text.infolm import _InformationMeasure

        rng = np.random.RandomState(1)
        p = rng.rand(4, 50).astype(np.float32)
        p /= p.sum(-1, keepdims=True)
        q = rng.rand(4, 50).astype(np.float32)
        q /= q.sum(-1, keepdims=True)
        pj, qj = jnp.asarray(p), jnp.asarray(q)

        kl = _InformationMeasure("kl_divergence")(pj, qj)
        ref_kl = np.sum(p * (np.log(p) - np.log(q)), -1)
        np.testing.assert_allclose(np.asarray(kl), ref_kl, atol=1e-5)

        l1 = _InformationMeasure("l1_distance")(pj, qj)
        np.testing.assert_allclose(np.asarray(l1), np.abs(p - q).sum(-1), atol=1e-6)
        l2 = _InformationMeasure("l2_distance")(pj, qj)
        np.testing.assert_allclose(np.asarray(l2), np.sqrt(((p - q) ** 2).sum(-1)), atol=1e-6)
        linf = _InformationMeasure("l_infinity_distance")(pj, qj)
        np.testing.assert_allclose(np.asarray(linf), np.abs(p - q).max(-1), atol=1e-6)
        fr = _InformationMeasure("fisher_rao_distance")(pj, qj)
        np.testing.assert_allclose(np.asarray(fr), 2 * np.arccos(np.clip((np.sqrt(p * q)).sum(-1), 0, 1)), atol=1e-5)
        for name, kwargs in [
            ("alpha_divergence", {"alpha": 0.5}),
            ("beta_divergence", {"beta": 0.5}),
            ("ab_divergence", {"alpha": 0.5, "beta": 0.5}),
            ("renyi_divergence", {"alpha": 0.5}),
        ]:
            out = _InformationMeasure(name, **kwargs)(pj, qj)
            assert np.all(np.isfinite(np.asarray(out)))

    def test_infolm_invalid_params(self):
        from metrics_tpu.functional.text.infolm import _InformationMeasure

        with pytest.raises(ValueError, match="cannot be 0 or 1"):
            _InformationMeasure("alpha_divergence", alpha=1.0)
        with pytest.raises(ValueError):
            _InformationMeasure("not_a_measure")


class TestPackedStringSync:
    """CHRF/BERTScore/InfoLM sentence states must survive the cross-rank gather
    (review finding: plain-attribute string lists were invisible to sync)."""

    def test_chrf_two_rank_sync_matches_single_corpus(self):
        from tests.helpers.testers import _FakeGather

        from metrics_tpu import CHRFScore

        preds = ["the cat is on the mat", "a dog runs fast", "hello world", "jax on tpu"]
        targets = [["there is a cat on the mat"], ["the dog runs quickly"], ["hello there world"], ["jax runs on tpu"]]

        ranks = [CHRFScore(), CHRFScore()]
        ranks[0].update(preds[:2], targets[:2])
        ranks[1].update(preds[2:], targets[2:])
        gather = _FakeGather(ranks)
        synced = ranks[0]
        synced.sync(dist_sync_fn=gather, distributed_available=lambda: True)
        two_rank = synced.compute.__wrapped__()
        synced.unsync()

        full = CHRFScore()
        full.update(preds, targets)
        np.testing.assert_allclose(np.asarray(two_rank), np.asarray(full.compute()), atol=1e-6)

    def test_chrf_empty_reference_raises(self):
        from metrics_tpu.functional.text.chrf import chrf_score

        with pytest.raises(ValueError, match="at least one reference"):
            chrf_score(["a"], [[]])

    def test_bleu_weights_length_mismatch_raises(self):
        from metrics_tpu.functional.text.bleu import bleu_score

        with pytest.raises(ValueError, match="weights"):
            bleu_score(["the cat"], [["the cat"]], n_gram=4, weights=[0.5, 0.5])

    def test_ter_corpus_size_mismatch_raises(self):
        from metrics_tpu.functional.text.ter import translation_edit_rate

        with pytest.raises(ValueError, match="Corpus has different size"):
            translation_edit_rate(["pred a", "pred b"], [["ref a"]])

    def test_bert_score_model_without_tokenizer_raises(self):
        from metrics_tpu.functional.text.bert import bert_score

        with pytest.raises(ValueError, match="user_tokenizer"):
            bert_score(["a"], ["a"], model=object())


def test_bert_score_baseline_rescale(tmp_path):
    """rescale_with_baseline applies (x - b)/(1 - b) from a local csv in the
    bert_score file format (reference `functional/text/bert.py:166-229`)."""
    import numpy as np

    from metrics_tpu.functional import bert_score

    def toy_forward(sentences):
        rng = np.random.RandomState(0)
        emb = np.stack([rng.rand(4, 8) + len(s) for s in sentences])
        return emb.astype(np.float32), np.ones((len(sentences), 4), np.float32)

    csv_file = tmp_path / "baseline.csv"
    csv_file.write_text("LAYER,P,R,F\n0,0.1,0.2,0.3\n1,0.4,0.5,0.6\n")

    plain = bert_score(["ab", "abcd"], ["ab", "abc"], user_forward_fn=toy_forward)
    scaled = bert_score(
        ["ab", "abcd"], ["ab", "abc"], user_forward_fn=toy_forward,
        rescale_with_baseline=True, baseline_path=str(csv_file), num_layers=1,
    )
    for k, b in zip(("precision", "recall", "f1"), (0.4, 0.5, 0.6)):
        np.testing.assert_allclose(
            np.asarray(scaled[k]), (np.asarray(plain[k]) - b) / (1 - b), atol=1e-6
        )

    with pytest.raises(ValueError, match="baseline_path"):
        bert_score(["a"], ["a"], user_forward_fn=toy_forward, rescale_with_baseline=True)
