"""The full text parametrization grid vs the mounted reference.

The reference enumerates each text metric over its whole option space
(`tests/unittests/text/`, ~2.5k LoC: BLEU n_gram x smooth, SacreBLEU's five
tokenizers x lowercase, CHRF orders x beta x whitespace, ROUGE keys x stemmer
x accumulate, TER/EED normalization grids); the in-repo text tests sample it.
This file enumerates those grids on two fixed corpora — one Latin-script with
punctuation/case/numbers, one with CJK segments for the zh/intl/char
tokenizers and `asian_support` — every cell differentially checked against
the reference on identical data.
"""
from __future__ import annotations

import numpy as np
import pytest
import torch

from tests.helpers import cell_seed as _cell_seed
from tests.helpers.reference_oracle import get_reference

_ref = get_reference()
pytestmark = pytest.mark.skipif(_ref is None, reason="reference mount unavailable")

import metrics_tpu as mt  # noqa: E402

# corpus 1: Latin script, punctuation, casing, numerals, repeated n-grams
PREDS_EN = [
    "the cat sat on the Mat, twice.",
    "It is a truth universally acknowledged!",
    "42 grams of flour; mix well",
    "the the the the",
]
TARGET_EN = [
    ["the cat sat on the mat twice", "a cat sat twice on the mat."],
    ["It is a truth universally acknowledged.", "Universally, it is an acknowledged truth!"],
    ["42 grams of flour, mixed well", "mix 42 grams of flour well"],
    ["the cat", "the dog"],
]
# corpus 2: CJK + mixed-width punctuation for zh/intl/char tokenizers
PREDS_ZH = ["猫坐在垫子上。", "天气很好 today", "他读了 3 本书"]
TARGET_ZH = [["猫坐在垫子上"], ["今天天气很好", "the weather is fine today"], ["他读了三本书。"]]

CORPORA = {"en": (PREDS_EN, TARGET_EN), "zh": (PREDS_ZH, TARGET_ZH)}
# single-reference flat corpora for the error-rate family
FLAT = {
    "en": ([p for p in PREDS_EN], [t[0] for t in TARGET_EN]),
    "zh": ([p for p in PREDS_ZH], [t[0] for t in TARGET_ZH]),
}


def _assert_cell(name, kwargs, preds, target, atol=1e-5):
    ours = getattr(mt, name)(**kwargs)
    ref = getattr(_ref, name)(**kwargs)
    # stream in two chunks to cross the accumulation path
    half = max(1, len(preds) // 2)
    for sl in (slice(0, half), slice(half, None)):
        if len(preds[sl]) == 0:
            continue
        ours.update(preds[sl], target[sl])
        ref.update(preds[sl], target[sl])
    ours_val, ref_val = ours.compute(), ref.compute()
    _assert_value(ours_val, ref_val, atol)


def _assert_value(ours_val, ref_val, atol):
    if isinstance(ours_val, dict):
        assert set(ours_val) == set(ref_val)
        for k in ours_val:
            _assert_value(ours_val[k], ref_val[k], atol)
    elif isinstance(ours_val, (tuple, list)):
        assert len(ours_val) == len(ref_val)
        for o, r in zip(ours_val, ref_val):
            _assert_value(o, r, atol)
    else:
        np.testing.assert_allclose(np.asarray(ours_val), np.asarray(ref_val), atol=atol)


class TestBleuGrid:
    @pytest.mark.parametrize("n_gram", (1, 2, 3, 4))
    @pytest.mark.parametrize("smooth", (False, True))
    @pytest.mark.parametrize("corpus", ("en", "zh"))
    def test_bleu(self, n_gram, smooth, corpus):
        preds, target = CORPORA[corpus]
        _assert_cell("BLEUScore", {"n_gram": n_gram, "smooth": smooth}, preds, target)

    @pytest.mark.parametrize("n_gram", (2, 4))
    def test_bleu_custom_weights(self, n_gram):
        weights = [1.0 / n_gram + (0.1 if i == 0 else -0.1 / (n_gram - 1)) for i in range(n_gram)]
        _assert_cell("BLEUScore", {"n_gram": n_gram, "weights": weights}, PREDS_EN, TARGET_EN)

    @pytest.mark.parametrize("tokenize", ("none", "13a", "intl", "char", "zh"))
    @pytest.mark.parametrize("lowercase", (False, True))
    @pytest.mark.parametrize("corpus", ("en", "zh"))
    def test_sacre_bleu(self, tokenize, lowercase, corpus):
        preds, target = CORPORA[corpus]
        _assert_cell("SacreBLEUScore", {"tokenize": tokenize, "lowercase": lowercase}, preds, target)


class TestChrfGrid:
    @pytest.mark.parametrize("n_char_order", (1, 3, 6))
    @pytest.mark.parametrize("n_word_order", (0, 1, 2))
    @pytest.mark.parametrize("corpus", ("en", "zh"))
    def test_orders(self, n_char_order, n_word_order, corpus):
        preds, target = CORPORA[corpus]
        _assert_cell(
            "CHRFScore", {"n_char_order": n_char_order, "n_word_order": n_word_order}, preds, target
        )

    @pytest.mark.parametrize("beta", (0.5, 1.0, 3.0))
    @pytest.mark.parametrize("lowercase", (False, True))
    @pytest.mark.parametrize("whitespace", (False, True))
    def test_flags(self, beta, lowercase, whitespace):
        _assert_cell(
            "CHRFScore",
            {"beta": beta, "lowercase": lowercase, "whitespace": whitespace},
            PREDS_EN,
            TARGET_EN,
        )

    def test_sentence_level(self):
        _assert_cell("CHRFScore", {"return_sentence_level_score": True}, PREDS_EN, TARGET_EN)

    @pytest.mark.parametrize("whitespace", (False, True))
    def test_edge_whitespace(self, whitespace):
        """Leading/trailing tabs/newlines: stripped when whitespace=False."""
        preds = ["hello world\n", "\tthe cat  sat "]
        target = [["\thello world"], ["the cat sat\n"]]
        _assert_cell("CHRFScore", {"whitespace": whitespace}, preds, target)


class TestRougeGrid:
    @pytest.mark.parametrize("rouge_keys", ("rouge1", "rouge2", "rougeL", "rougeLsum", ("rouge1", "rougeL")))
    @pytest.mark.parametrize("use_stemmer", (False, True))
    @pytest.mark.parametrize("accumulate", ("best", "avg"))
    def test_rouge(self, rouge_keys, use_stemmer, accumulate, monkeypatch):
        # The reference's module class lives behind the nltk gate in
        # torchmetrics.text.rouge; its punkt-backed _split_sentence needs an
        # offline-unavailable download, so stub it with the newline convention
        # both stacks share (same convention as tests/text/test_text.py).
        import torchmetrics.functional.text.rouge as ref_rouge_fn
        from torchmetrics.text.rouge import ROUGEScore as RefROUGEScore

        monkeypatch.setattr(ref_rouge_fn, "_split_sentence", lambda x: x.split("\n"))
        kwargs = {"rouge_keys": rouge_keys, "use_stemmer": use_stemmer, "accumulate": accumulate}
        ours = mt.ROUGEScore(**kwargs)
        ref = RefROUGEScore(**kwargs)
        half = len(PREDS_EN) // 2
        for sl in (slice(0, half), slice(half, None)):
            ours.update(PREDS_EN[sl], TARGET_EN[sl])
            ref.update(PREDS_EN[sl], TARGET_EN[sl])
        _assert_value(ours.compute(), ref.compute(), 1e-5)


class TestTerGrid:
    @pytest.mark.parametrize("normalize", (False, True))
    @pytest.mark.parametrize("no_punctuation", (False, True))
    @pytest.mark.parametrize("lowercase", (False, True))
    @pytest.mark.parametrize("corpus", ("en", "zh"))
    def test_flags(self, normalize, no_punctuation, lowercase, corpus):
        preds, target = CORPORA[corpus]
        _assert_cell(
            "TranslationEditRate",
            {"normalize": normalize, "no_punctuation": no_punctuation, "lowercase": lowercase},
            preds,
            target,
        )

    @pytest.mark.parametrize("asian_support", (False, True))
    def test_asian_support(self, asian_support):
        _assert_cell(
            "TranslationEditRate", {"asian_support": asian_support, "normalize": True}, PREDS_ZH, TARGET_ZH
        )

    def test_sentence_level(self):
        _assert_cell("TranslationEditRate", {"return_sentence_level_score": True}, PREDS_EN, TARGET_EN)


class TestEedGrid:
    @pytest.mark.parametrize("language", ("en", "ja"))
    @pytest.mark.parametrize("corpus", ("en", "zh"))
    def test_language(self, language, corpus):
        preds, target = CORPORA[corpus]
        _assert_cell("ExtendedEditDistance", {"language": language}, preds, target)

    @pytest.mark.parametrize(
        "alpha,rho,deletion,insertion",
        [(2.0, 0.3, 0.2, 1.0), (1.0, 0.5, 0.0, 0.5), (3.0, 0.1, 1.0, 2.0)],
    )
    def test_costs(self, alpha, rho, deletion, insertion):
        _assert_cell(
            "ExtendedEditDistance",
            {"alpha": alpha, "rho": rho, "deletion": deletion, "insertion": insertion},
            PREDS_EN,
            TARGET_EN,
        )

    def test_sentence_level(self):
        _assert_cell("ExtendedEditDistance", {"return_sentence_level_score": True}, PREDS_EN, TARGET_EN)


class TestErrorRateGrid:
    @pytest.mark.parametrize(
        "name", ["WordErrorRate", "CharErrorRate", "MatchErrorRate", "WordInfoLost", "WordInfoPreserved"]
    )
    @pytest.mark.parametrize("corpus", ("en", "zh"))
    def test_corpus(self, name, corpus):
        preds, target = FLAT[corpus]
        _assert_cell(name, {}, preds, target)


class TestPerplexityGrid:
    @pytest.mark.parametrize("ignore_index", (None, -100))
    def test_perplexity(self, ignore_index):
        import jax.numpy as jnp

        rng = np.random.RandomState(7)
        logits = rng.randn(2, 6, 5).astype(np.float32)
        target = rng.randint(0, 5, size=(2, 6))
        if ignore_index is not None:
            target[0, :2] = ignore_index
        ours = mt.Perplexity(ignore_index=ignore_index)
        ref = _ref.Perplexity(ignore_index=ignore_index)
        ours.update(jnp.asarray(logits), jnp.asarray(target))
        ref.update(torch.tensor(logits), torch.tensor(target))
        np.testing.assert_allclose(np.asarray(ours.compute()), np.asarray(ref.compute()), atol=1e-4)
