"""Constructed text corner cases vs the mounted reference.

Degenerate strings built on purpose: empty hypotheses/references,
whitespace-only input, single characters, exact matches, unicode,
repetition (n-gram clipping), and hypotheses longer/shorter than every
reference (brevity penalty edges) — identical data through both stacks.
"""
from __future__ import annotations

import numpy as np
import pytest

from tests.helpers.reference_oracle import get_reference

_ref = get_reference()
pytestmark = pytest.mark.skipif(_ref is None, reason="reference mount unavailable")

import metrics_tpu.functional as F  # noqa: E402


def _close(ours, theirs, atol=1e-5):
    np.testing.assert_allclose(np.asarray(ours, np.float64), float(theirs), atol=atol, equal_nan=True)


class TestBleuEdges:
    def test_empty_hypothesis(self):
        _close(F.bleu_score([""], [["the cat sat"]]), _ref.functional.bleu_score([""], [["the cat sat"]]))

    def test_empty_reference(self):
        _close(F.bleu_score(["the cat"], [[""]]), _ref.functional.bleu_score(["the cat"], [[""]]))

    def test_exact_match_is_one(self):
        sent = ["the quick brown fox jumps over the lazy dog"]
        ours = F.bleu_score(sent, [[sent[0]]])
        _close(ours, _ref.functional.bleu_score(sent, [[sent[0]]]))
        assert float(np.asarray(ours)) == pytest.approx(1.0)

    def test_hypothesis_shorter_than_ngram_order(self):
        """2-word hypothesis under the default 4-gram order."""
        _close(F.bleu_score(["the cat"], [["the cat sat on the mat"]]),
               _ref.functional.bleu_score(["the cat"], [["the cat sat on the mat"]]))

    @pytest.mark.parametrize("smooth", [False, True])
    def test_repetition_clipping(self, smooth):
        """'the the the...' exercises modified-precision clipping."""
        preds = ["the the the the the the the"]
        target = [["the cat is on the mat"]]
        _close(F.bleu_score(preds, target, smooth=smooth),
               _ref.functional.bleu_score(preds, target, smooth=smooth))

    def test_brevity_penalty_long_hypothesis(self):
        preds = ["a b c d e f g h i j k l m n o p"]
        target = [["a b c d"]]
        _close(F.bleu_score(preds, target), _ref.functional.bleu_score(preds, target))

    @pytest.mark.parametrize("weights", [[1.0], [0.5, 0.5], [0.25, 0.25, 0.25, 0.25]])
    def test_custom_weights(self, weights):
        preds = ["the cat sat on the mat"]
        target = [["a cat sat on the mat"]]
        _close(F.bleu_score(preds, target, n_gram=len(weights), weights=weights),
               _ref.functional.bleu_score(preds, target, n_gram=len(weights), weights=weights))


class TestEditDistanceEdges:
    @pytest.mark.parametrize("fn", ["word_error_rate", "match_error_rate", "word_information_lost", "char_error_rate"])
    def test_exact_match_is_zero(self, fn):
        sent = ["the quick brown fox"]
        _close(getattr(F, fn)(sent, sent), getattr(_ref.functional, fn)(sent, sent))

    @pytest.mark.parametrize("fn", ["word_error_rate", "char_error_rate"])
    def test_empty_hypothesis(self, fn):
        _close(getattr(F, fn)([""], ["the cat"]), getattr(_ref.functional, fn)([""], ["the cat"]))

    def test_single_characters(self):
        _close(F.char_error_rate(["a"], ["b"]), _ref.functional.char_error_rate(["a"], ["b"]))

    def test_unicode(self):
        preds = ["caffè résumé 日本語"]
        target = ["caffé résumé 日本語 テスト"]
        _close(F.char_error_rate(preds, target), _ref.functional.char_error_rate(preds, target))
        _close(F.word_error_rate(preds, target), _ref.functional.word_error_rate(preds, target))

    def test_completely_disjoint(self):
        """WER above 1.0 when the hypothesis is longer and fully wrong."""
        preds = ["x y z w v u"]
        target = ["a b"]
        _close(F.word_error_rate(preds, target), _ref.functional.word_error_rate(preds, target))


class TestChrfEdges:
    def test_empty_hypothesis(self):
        _close(F.chrf_score([""], [["the cat"]]), _ref.functional.chrf_score([""], [["the cat"]]))

    def test_whitespace_handling(self):
        preds = ["  the   cat  "]
        target = [["the cat"]]
        _close(F.chrf_score(preds, target), _ref.functional.chrf_score(preds, target))

    @pytest.mark.parametrize("beta", [0.5, 1.0, 3.0])
    def test_beta_sweep(self, beta):
        preds = ["the cat sat on a mat"]
        target = [["the cat sat on the mat"]]
        _close(F.chrf_score(preds, target, beta=beta), _ref.functional.chrf_score(preds, target, beta=beta))

    def test_lowercase(self):
        preds = ["The CAT Sat"]
        target = [["the cat sat"]]
        _close(F.chrf_score(preds, target, lowercase=True),
               _ref.functional.chrf_score(preds, target, lowercase=True))
        _close(F.chrf_score(preds, target, lowercase=False),
               _ref.functional.chrf_score(preds, target, lowercase=False))


def _ref_rouge(*args, **kwargs):
    """The reference's rouge update sentence-splits unconditionally, which
    needs the punkt nltk corpus — not downloadable here; skip like the rest
    of the suite when the offline data is missing."""
    try:
        return _ref.functional.rouge_score(*args, **kwargs)
    except LookupError:
        pytest.skip("reference ROUGE needs nltk data unavailable offline")


class TestRougeEdges:
    KEYS = ("rouge1", "rouge2", "rougeL")

    def test_empty_hypothesis(self):
        theirs = _ref_rouge([""], ["the cat sat"], rouge_keys=self.KEYS)
        ours = F.rouge_score([""], ["the cat sat"], rouge_keys=self.KEYS)
        for key in ("rouge1_fmeasure", "rougeL_fmeasure"):
            _close(ours[key], float(theirs[key]))

    def test_single_word(self):
        theirs = _ref_rouge(["cat"], ["cat"], rouge_keys=self.KEYS)
        ours = F.rouge_score(["cat"], ["cat"], rouge_keys=self.KEYS)
        for key in ("rouge1_fmeasure", "rouge2_fmeasure", "rougeL_fmeasure"):
            _close(ours[key], float(theirs[key]))

    def test_punctuation_tokenization(self):
        preds = ["the cat, sat. on; the mat!"]
        target = ["the cat sat on the mat"]
        theirs = _ref_rouge(preds, target, rouge_keys=self.KEYS)
        ours = F.rouge_score(preds, target, rouge_keys=self.KEYS)
        for key in ("rouge1_fmeasure", "rougeL_fmeasure"):
            _close(ours[key], float(theirs[key]))


class TestTerEdges:
    def test_exact_match_is_zero(self):
        sent = ["the quick brown fox"]
        _close(F.translation_edit_rate(sent, [[sent[0]]]),
               _ref.functional.translation_edit_rate(sent, [[sent[0]]]))

    def test_shift_heavy_case(self):
        """A pure reordering exercises the shift heuristics."""
        preds = ["d c b a"]
        target = [["a b c d"]]
        _close(F.translation_edit_rate(preds, target),
               _ref.functional.translation_edit_rate(preds, target))

    @pytest.mark.parametrize("kwargs", [{"normalize": True}, {"lowercase": False}, {"no_punctuation": True}])
    def test_flag_parity(self, kwargs):
        preds = ["The CAT, sat on-the mat."]
        target = [["the cat sat on the mat"]]
        _close(F.translation_edit_rate(preds, target, **kwargs),
               _ref.functional.translation_edit_rate(preds, target, **kwargs))


class TestSquadEdges:
    def test_articles_and_punctuation_normalization(self):
        preds = [{"prediction_text": "The  Eiffel-Tower!", "id": "1"}]
        target = [{"answers": {"answer_start": [0], "text": ["eiffel tower"]}, "id": "1"}]
        ours = F.squad(preds, target)
        theirs = _ref.functional.squad(preds, target)
        for key in ("exact_match", "f1"):
            _close(ours[key], float(theirs[key]))

    def test_multiple_gold_answers_takes_max(self):
        preds = [{"prediction_text": "blue whale", "id": "1"}]
        target = [{"answers": {"answer_start": [0, 0], "text": ["a whale", "the blue whale"]}, "id": "1"}]
        ours = F.squad(preds, target)
        theirs = _ref.functional.squad(preds, target)
        for key in ("exact_match", "f1"):
            _close(ours[key], float(theirs[key]))
