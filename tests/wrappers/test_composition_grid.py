"""Wrapper COMPOSITION cells vs the mounted reference.

The per-wrapper behavior is covered by the edge matrix and parity files; the
cells here cross wrappers with the composition layer the way training code
does — wrappers inside `MetricCollection`, trackers over whole collections
with per-metric `maximize` lists, wrappers wrapping wrappers — on identical
data both stacks (reference `tests/unittests/wrappers/`, nesting scenarios).
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np
import pytest
import torch

from tests.helpers import assert_tree_close as _assert_tree
from tests.helpers import cell_seed
from tests.helpers.reference_oracle import get_reference

_ref = get_reference()
pytestmark = pytest.mark.skipif(_ref is None, reason="reference mount unavailable")

import metrics_tpu as mt  # noqa: E402

N_CLASSES = 4


def _cls_batches(seed, n_batches=3, batch=24):
    rng = np.random.RandomState(seed)
    return [
        (rng.randint(0, N_CLASSES, size=batch), rng.randint(0, N_CLASSES, size=batch))
        for _ in range(n_batches)
    ]


def _reg_batches(seed, n_batches=3, batch=24):
    rng = np.random.RandomState(seed)
    out = []
    for _ in range(n_batches):
        p = rng.randn(batch).astype(np.float32)
        out.append((p, (p + 0.3 * rng.randn(batch)).astype(np.float32)))
    return out





class TestClasswiseInCollection:
    @pytest.mark.parametrize("prefix", (None, "val_"))
    def test_naming_and_values(self, prefix):
        kwargs = {} if prefix is None else {"prefix": prefix}
        ours = mt.MetricCollection(
            {
                "acc_cw": mt.ClasswiseWrapper(mt.Accuracy(num_classes=N_CLASSES, average=None)),
                "rec": mt.Recall(num_classes=N_CLASSES, average="macro"),
            },
            **kwargs,
        )
        ref = _ref.MetricCollection(
            {
                "acc_cw": _ref.ClasswiseWrapper(_ref.Accuracy(num_classes=N_CLASSES, average=None)),
                "rec": _ref.Recall(num_classes=N_CLASSES, average="macro"),
            },
            **kwargs,
        )
        for p, t in _cls_batches(cell_seed("cw-col", prefix)):
            ours.update(jnp.asarray(p), jnp.asarray(t))
            ref.update(torch.tensor(p), torch.tensor(t))
        _assert_tree(ours.compute(), ref.compute())


class TestTrackerOverCollection:
    @pytest.mark.parametrize("maximize", (True, [False, True]), ids=("scalar", "list"))
    def test_best_across_steps(self, maximize):
        def build(ns):
            return ns.MetricTracker(
                ns.MetricCollection([ns.MeanSquaredError(), ns.ExplainedVariance()]), maximize=maximize
            )

        ours, ref = build(mt), build(_ref)
        for step in range(3):
            ours.increment()
            ref.increment()
            for p, t in _reg_batches(cell_seed("tracker", step), n_batches=2):
                ours.update(jnp.asarray(p), jnp.asarray(t))
                ref.update(torch.tensor(p), torch.tensor(t))
        _assert_tree(ours.compute_all(), ref.compute_all())
        our_best, our_step = ours.best_metric(return_step=True)
        ref_best, ref_step = ref.best_metric(return_step=True)
        _assert_tree(our_best, ref_best)
        assert our_step == ref_step

    def test_single_metric_minimize_divergence_pinned(self):
        """Documented divergence (README ledger): the reference unpacks
        ``torch.min(t, 0)`` as ``idx, best`` — `(values, indices)` in torch —
        so its no-arg ``best_metric()`` returns the argmin INDEX. Ours returns
        the actual best value. The exact relationship is pinned here."""
        ours = mt.MetricTracker(mt.MeanSquaredError(), maximize=False)
        ref = _ref.MetricTracker(_ref.MeanSquaredError(), maximize=False)
        for step in range(3):
            ours.increment()
            ref.increment()
            for p, t in _reg_batches(cell_seed("tracker-min", step), n_batches=1):
                ours.update(jnp.asarray(p), jnp.asarray(t))
                ref.update(torch.tensor(p), torch.tensor(t))
        ref_val_swapped, ref_step_swapped = ref.best_metric(return_step=True)
        our_val, our_step = ours.best_metric(return_step=True)
        np.testing.assert_allclose(our_val, ref_val_swapped, atol=1e-6)  # same (value, step) order
        assert our_step == ref_step_swapped
        assert ours.best_metric() == pytest.approx(our_val)
        assert ref.best_metric() == ref_step_swapped  # the reference returns the INDEX


class TestNestedWrappers:
    def test_minmax_across_epochs(self):
        """MinMax extrema of a plain metric across two epochs of updates."""

        def run(ns, to_tensor):
            metric = ns.MinMaxMetric(ns.Accuracy(num_classes=N_CLASSES))
            vals = []
            for step in range(2):
                for p, t in _cls_batches(cell_seed("minmax", step), n_batches=2):
                    metric.update(to_tensor(p), to_tensor(t))
                vals.append({k: float(v) for k, v in metric.compute().items()})
            return vals

        ours = run(mt, lambda x: jnp.asarray(x))
        theirs = run(_ref, lambda x: torch.tensor(x))
        _assert_tree(ours, theirs)

    def test_minmax_inside_collection(self):
        """MinMax as a COLLECTION member, updated through the collection."""

        def build(ns):
            return ns.MetricCollection(
                {
                    "acc_minmax": ns.MinMaxMetric(ns.Accuracy(num_classes=N_CLASSES)),
                    "acc": ns.Accuracy(num_classes=N_CLASSES),
                }
            )

        ours, ref = build(mt), build(_ref)
        for p, t in _cls_batches(cell_seed("minmax-col")):
            ours.update(jnp.asarray(p), jnp.asarray(t))
            ref.update(torch.tensor(p), torch.tensor(t))
        _assert_tree(ours.compute(), ref.compute())

    def test_multioutput_in_collection(self):
        def build(ns):
            return ns.MetricCollection({"r2_multi": ns.MultioutputWrapper(ns.R2Score(), num_outputs=2)})

        ours, ref = build(mt), build(_ref)
        rng = np.random.RandomState(cell_seed("mo-col"))
        for _ in range(2):
            p = rng.randn(16, 2).astype(np.float32)
            t = (p + 0.2 * rng.randn(16, 2)).astype(np.float32)
            ours.update(jnp.asarray(p), jnp.asarray(t))
            ref.update(torch.tensor(p), torch.tensor(t))
        _assert_tree(ours.compute(), ref.compute())


class TestBootstrapperSurfaceGrid:
    """RNG paths differ by design; the contract is keys/shapes across the
    mean x std x quantile x raw option grid, plus mean's convergence to the
    base metric on degenerate (constant) inputs where resampling is a no-op."""

    @pytest.mark.parametrize("mean", (True, False))
    @pytest.mark.parametrize("std", (True, False))
    @pytest.mark.parametrize("raw", (True, False))
    def test_output_surface(self, mean, std, raw):
        if not (mean or std or raw):
            pytest.skip("empty output")
        kwargs = dict(num_bootstraps=4, mean=mean, std=std, raw=raw)
        ours = mt.BootStrapper(mt.MeanSquaredError(), **kwargs)
        ref = _ref.BootStrapper(_ref.MeanSquaredError(), **kwargs)
        for p, t in _reg_batches(cell_seed("boot-surface"), n_batches=1):
            ours.update(jnp.asarray(p), jnp.asarray(t))
            ref.update(torch.tensor(p), torch.tensor(t))
        o, r = ours.compute(), ref.compute()
        assert set(o) == set(r)
        for k in o:
            assert np.asarray(o[k]).shape == np.asarray(r[k]).shape

    def test_constant_input_exact(self):
        """On constant data every resample sees the same rows: both stacks
        must produce the base metric's exact value with zero std."""
        ours = mt.BootStrapper(mt.MeanSquaredError(), num_bootstraps=4)
        ref = _ref.BootStrapper(_ref.MeanSquaredError(), num_bootstraps=4)
        p, t = np.full(16, 2.0, np.float32), np.full(16, 3.0, np.float32)
        ours.update(jnp.asarray(p), jnp.asarray(t))
        ref.update(torch.tensor(p), torch.tensor(t))
        o, r = ours.compute(), ref.compute()
        np.testing.assert_allclose(float(o["mean"]), float(r["mean"]), atol=1e-6)
        np.testing.assert_allclose(float(o["std"]), 0.0, atol=1e-6)
        np.testing.assert_allclose(float(r["std"]), 0.0, atol=1e-6)
