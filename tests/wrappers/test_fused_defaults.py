"""The reference-DEFAULT wrapper configs run fused (round-5 contract).

Round 4 fused only the non-default configs (multinomial bootstrap,
``remove_nans=False``); the reference defaults — ``BootStrapper(poisson)``,
``MultioutputWrapper(remove_nans=True)``, ``MinMaxMetric`` — stayed on the
eager per-clone path (the 0.01×–0.19× sweep rows). These tests pin the
round-5 fast paths:

- poisson bootstrap as ONE program (counts as row weights over per-row state
  deltas), certified against the eager chunked path on its first fused step;
- ``remove_nans=True`` as in-program zero-weighting of NaN rows (no
  data-dependent host gather), certified the same way;
- MinMaxMetric forward as one program (child batch state + extrema), exactly
  reproducing the eager two-update dance's semantics.

Each case asserts BOTH engagement (the program exists) and value equality
with a force-eager twin on identical data/seeds.
"""
from __future__ import annotations

import pickle

import jax.numpy as jnp
import numpy as np
import pytest

import metrics_tpu as mt
from metrics_tpu.utils import checks


@pytest.fixture(autouse=True)
def _first_mode():
    prev = checks._get_validation_mode()
    checks.set_validation_mode("first")
    yield
    checks.set_validation_mode(prev)


def _pair(factory, force_eager_attr):
    fused = factory()
    eager = factory()
    object.__setattr__(eager, force_eager_attr, False)
    return fused, eager


class TestPoissonBootstrap:
    def _run(self, base_factory, batches, seed=3):
        fused, eager = _pair(
            lambda: mt.BootStrapper(base_factory(), num_bootstraps=4, sampling_strategy="poisson"),
            "_boot_ok",
        )
        fused._rng = np.random.RandomState(seed)
        eager._rng = np.random.RandomState(seed)
        for b in batches:
            fused.update(*b)
            eager.update(*b)
        return fused, eager

    def test_fused_equals_eager_same_seed(self):
        rng = np.random.RandomState(0)
        batches = [
            (jnp.asarray(rng.rand(48).astype(np.float32)), jnp.asarray(rng.rand(48).astype(np.float32)))
            for _ in range(4)
        ]
        fused, eager = self._run(mt.MeanSquaredError, batches)
        assert fused._boot_program is not None, "poisson fused path never engaged"
        assert fused._poisson_cert_done > 0
        for key in ("mean", "std"):
            np.testing.assert_allclose(
                float(fused.compute()[key]), float(eager.compute()[key]), rtol=1e-4, atol=1e-6
            )
        # per-clone states match the eager chunked resample exactly
        for mf, me in zip(fused.metrics, eager.metrics):
            for name in mf._defaults:
                np.testing.assert_allclose(
                    np.asarray(getattr(mf, name)), np.asarray(getattr(me, name)), rtol=1e-4, atol=1e-6
                )
            assert mf._update_count == me._update_count

    def test_accuracy_base_fuses(self):
        rng = np.random.RandomState(1)
        batches = [
            (jnp.asarray(rng.rand(32).astype(np.float32)), jnp.asarray(rng.randint(0, 2, 32)))
            for _ in range(3)
        ]
        fused, eager = self._run(mt.Accuracy, batches)
        assert fused._boot_program is not None
        np.testing.assert_allclose(
            float(fused.compute()["mean"]), float(eager.compute()["mean"]), rtol=1e-4
        )

    @pytest.mark.parametrize("strategy", ["poisson", "multinomial"])
    def test_shape_churn_keeps_seeded_stream_parity(self, strategy):
        """The lookahead prefetch must be RNG-unobservable: on a batch-size
        change the pending draw rewinds the stream (pre-draw snapshot), so a
        fused run's states equal a force-eager run's on the same seed even
        with varying shapes — for both sampling strategies (both prefetch
        their next draw matrix)."""
        rng = np.random.RandomState(0)
        sizes = [32, 32, 48, 48, 32, 48, 32]
        batches = [
            (jnp.asarray(rng.rand(s).astype(np.float32)), jnp.asarray(rng.rand(s).astype(np.float32)))
            for s in sizes
        ]
        fused, eager = _pair(
            lambda: mt.BootStrapper(mt.MeanSquaredError(), num_bootstraps=4, sampling_strategy=strategy),
            "_boot_ok",
        )
        fused._rng = np.random.RandomState(9)
        eager._rng = np.random.RandomState(9)
        for b in batches:
            fused.update(*b)
            eager.update(*b)
        assert fused._boot_program is not None
        for mf, me in zip(fused.metrics, eager.metrics):
            for name in mf._defaults:
                np.testing.assert_allclose(
                    np.asarray(getattr(mf, name)), np.asarray(getattr(me, name)), rtol=1e-4
                )

    def test_non_sum_linear_base_stays_eager(self):
        # MaxMetric's state reduces by "max": weights cannot express resampling
        rng = np.random.RandomState(2)
        batches = [(jnp.asarray(rng.rand(16).astype(np.float32)),) for _ in range(3)]
        fused, eager = self._run(mt.MaxMetric, batches)
        assert fused._boot_program is None  # gate rejected, no fused attempt
        np.testing.assert_allclose(
            float(fused.compute()["mean"]), float(eager.compute()["mean"]), rtol=1e-5
        )

    def test_full_mode_stays_eager(self):
        checks.set_validation_mode("full")
        rng = np.random.RandomState(4)
        batches = [
            (jnp.asarray(rng.rand(16).astype(np.float32)), jnp.asarray(rng.rand(16).astype(np.float32)))
            for _ in range(3)
        ]
        fused, _ = self._run(mt.MeanSquaredError, batches)
        assert fused._boot_program is None


class TestMultioutputRemoveNans:
    def _data(self, with_nans=True):
        rng = np.random.RandomState(5)
        p = rng.rand(24, 3).astype(np.float32)
        t = rng.rand(24, 3).astype(np.float32)
        if with_nans:
            p[rng.rand(24) < 0.25, 0] = np.nan
            t[rng.rand(24) < 0.25, 2] = np.nan
        return jnp.asarray(p), jnp.asarray(t)

    def test_fused_equals_eager_with_nan_rows(self):
        p, t = self._data()
        fused, eager = _pair(
            lambda: mt.MultioutputWrapper(mt.MeanSquaredError(), num_outputs=3), "_mo_ok"
        )
        assert fused.remove_nans  # the reference default config is what fuses
        for _ in range(3):
            fused.update(p, t)
            eager.update(p, t)
        assert fused._mo_program is not None, "remove_nans fused path never engaged"
        assert fused._mo_cert_done > 0
        np.testing.assert_allclose(
            [float(v) for v in fused.compute()],
            [float(v) for v in eager.compute()],
            rtol=1e-5,
        )

    def test_all_nan_column_matches_eager(self):
        rng = np.random.RandomState(6)
        p = rng.rand(8, 2).astype(np.float32)
        t = rng.rand(8, 2).astype(np.float32)
        p[:, 1] = np.nan  # every row of column 1 filtered
        p, t = jnp.asarray(p), jnp.asarray(t)
        fused, eager = _pair(
            lambda: mt.MultioutputWrapper(mt.MeanSquaredError(), num_outputs=2), "_mo_ok"
        )
        for _ in range(3):
            fused.update(p, t)
            eager.update(p, t)
        assert fused._mo_program is not None
        a, b = fused.compute(), eager.compute()
        np.testing.assert_allclose(float(a[0]), float(b[0]), rtol=1e-5)
        # column 1 never saw a sample in either path: both divide 0/0
        assert np.isnan(float(a[1])) == np.isnan(float(b[1]))

    def test_cat_state_base_stays_eager(self):
        p, t = self._data(with_nans=False)
        fused, _ = _pair(
            lambda: mt.MultioutputWrapper(mt.SpearmanCorrCoef(), num_outputs=3), "_mo_ok"
        )
        for _ in range(3):
            fused.update(p[:, :1].repeat(3, 1), t[:, :1].repeat(3, 1))
        assert fused._mo_program is None  # cat states: not fusable

    def test_pickle_drops_program(self):
        p, t = self._data()
        w = mt.MultioutputWrapper(mt.MeanSquaredError(), num_outputs=3)
        for _ in range(3):
            w.update(p, t)
        assert w._mo_program is not None
        w2 = pickle.loads(pickle.dumps(w))
        assert w2._mo_program is None
        np.testing.assert_allclose(
            [float(v) for v in w.compute()], [float(v) for v in w2.compute()], rtol=1e-6
        )


class TestMinMaxFusedForward:
    def test_fused_equals_eager(self):
        rng = np.random.RandomState(7)
        batches = [
            (jnp.asarray(rng.rand(16).astype(np.float32)), jnp.asarray(rng.randint(0, 2, 16)))
            for _ in range(4)
        ]
        fused, eager = _pair(lambda: mt.MinMaxMetric(mt.Accuracy()), "_mm_ok")
        for p, t in batches:
            rf = fused(p, t)
            re_ = eager(p, t)
            np.testing.assert_allclose(float(rf["raw"]), float(re_["raw"]), rtol=1e-6)
        assert fused._mm_program is not None, "minmax fused forward never engaged"
        cf, ce = fused.compute(), eager.compute()
        for key in ("raw", "max", "min"):
            np.testing.assert_allclose(float(cf[key]), float(ce[key]), rtol=1e-6)
        # the eager dance leaves the child holding only the last batch —
        # the fused program must reproduce that exactly (reference behavior)
        for name in fused._base_metric._defaults:
            np.testing.assert_allclose(
                np.asarray(getattr(fused._base_metric, name)),
                np.asarray(getattr(eager._base_metric, name)),
            )
        assert fused._update_count == eager._update_count
        assert fused._base_metric._update_count == eager._base_metric._update_count

    def test_extrema_persist_across_forwards(self):
        fused = mt.MinMaxMetric(mt.MeanMetric())
        vals = [2.0, 5.0, 1.0, 3.0]
        for v in vals:
            fused(jnp.asarray([v]))
        out = fused.compute()
        assert float(out["max"]) == 5.0 and float(out["min"]) == 1.0
        assert fused._mm_program is not None

    def test_pickle_drops_program(self):
        m = mt.MinMaxMetric(mt.MeanMetric())
        for v in (1.0, 2.0, 3.0):
            m(jnp.asarray([v]))
        assert m._mm_program is not None
        m2 = pickle.loads(pickle.dumps(m))
        assert m2._mm_program is None
        assert float(m2.compute()["max"]) == float(m.compute()["max"])

    def test_program_is_stable_across_steps(self):
        """The extrema write-back must not bump the config-drift version —
        a rebuild per step would retrace + recompile every forward (review
        regression)."""
        m = mt.MinMaxMetric(mt.MeanMetric())
        for v in (1.0, 2.0):
            m(jnp.asarray([v]))
        prog = m._mm_program
        assert prog is not None
        for v in (3.0, 4.0, 5.0):
            m(jnp.asarray([v]))
            assert m._mm_program is prog
        m.compute()  # compute's extrema advance must not invalidate it either
        m(jnp.asarray([6.0]))
        assert m._mm_program is prog
