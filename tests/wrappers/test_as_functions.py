"""Pure-function export contract for wrapper metrics.

Child-holding wrappers register no states of their own, so the base
``as_functions`` export would be an empty state dict whose update XLA
dead-code-eliminates — every export here must either compose the child
kernels (ClasswiseWrapper, MultioutputWrapper without NaN removal) or raise
with guidance (stateful-compute MinMax, host-RNG BootStrapper, tracker).
The reference has no functional counterpart for wrappers; the module-API
behavior these exports must match is `wrappers/*.py` (reference
`classwise.py:8-78`, `multioutput.py:24-145`).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from metrics_tpu import (
    Accuracy,
    MetricCollection,
    BootStrapper,
    ClasswiseWrapper,
    MeanSquaredError,
    MetricTracker,
    MinMaxMetric,
    MultioutputWrapper,
)

_rng = np.random.RandomState(7)


class TestClasswiseExport:
    def test_matches_module_api(self):
        preds = jnp.asarray(_rng.rand(64, 3).astype(np.float32))
        target = jnp.asarray(_rng.randint(0, 3, 64))

        module = ClasswiseWrapper(Accuracy(num_classes=3, average=None))
        module.update(preds, target)
        expected = module.compute()

        init, upd, cmp = ClasswiseWrapper(Accuracy(num_classes=3, average=None)).as_functions()
        state = jax.jit(upd)(init(), preds, target)
        got = cmp(state)
        assert set(got) == set(expected)
        for key in expected:
            np.testing.assert_allclose(np.asarray(got[key]), np.asarray(expected[key]), atol=1e-6)

    def test_labels_respected(self):
        wrapper = ClasswiseWrapper(Accuracy(num_classes=2, average=None), labels=["cat", "dog"])
        init, upd, cmp = wrapper.as_functions()
        state = upd(init(), jnp.asarray([0, 1]), jnp.asarray([0, 0]))
        assert set(cmp(state)) == {"accuracy_cat", "accuracy_dog"}

    def test_update_is_jittable_with_donation(self):
        init, upd, _ = ClasswiseWrapper(Accuracy(num_classes=3, average=None)).as_functions()
        fused = jax.jit(upd, donate_argnums=(0,))
        state = fused(init(), jnp.asarray([0, 1, 2]), jnp.asarray([0, 1, 1]))
        state = fused(state, jnp.asarray([0, 1, 2]), jnp.asarray([0, 1, 1]))
        assert state  # non-empty: the child's states flow through


class TestMultioutputExport:
    def test_matches_module_api(self):
        preds = jnp.asarray(_rng.rand(32, 4).astype(np.float32))
        target = jnp.asarray(_rng.rand(32, 4).astype(np.float32))

        module = MultioutputWrapper(MeanSquaredError(), num_outputs=4, remove_nans=False)
        module.update(preds, target)
        expected = [float(v) for v in module.compute()]

        init, upd, cmp = MultioutputWrapper(
            MeanSquaredError(), num_outputs=4, remove_nans=False
        ).as_functions()
        state = jax.jit(upd)(init(), preds, target)
        got = [float(v) for v in cmp(state)]
        np.testing.assert_allclose(got, expected, atol=1e-6)

    def test_remove_nans_raises(self):
        with pytest.raises(NotImplementedError, match="remove_nans"):
            MultioutputWrapper(MeanSquaredError(), num_outputs=2).as_functions()

    def test_streaming_accumulation(self):
        init, upd, cmp = MultioutputWrapper(
            MeanSquaredError(), num_outputs=2, remove_nans=False
        ).as_functions()
        fused = jax.jit(upd, donate_argnums=(0,))
        p1 = jnp.asarray([[1.0, 2.0], [3.0, 4.0]])
        t1 = jnp.asarray([[1.0, 2.0], [3.0, 4.0]])
        p2 = jnp.asarray([[0.0, 0.0], [0.0, 0.0]])
        t2 = jnp.asarray([[2.0, 2.0], [2.0, 2.0]])
        state = fused(init(), p1, t1)
        state = fused(state, p2, t2)
        vals = [float(v) for v in cmp(state)]
        np.testing.assert_allclose(vals, [2.0, 2.0], atol=1e-6)


class TestCollectionWithWrapperMembers:
    def test_collection_with_classwise_member_exports(self):
        coll = MetricCollection(
            {"acc": Accuracy(num_classes=3), "cw": ClasswiseWrapper(Accuracy(num_classes=3, average=None))}
        )
        init, upd, cmp = coll.as_functions()
        p = jnp.asarray(_rng.rand(32, 3).astype(np.float32))
        t = jnp.asarray(_rng.randint(0, 3, 32))
        out = cmp(jax.jit(upd)(init(), p, t))
        assert "acc" in out and any(k.startswith("accuracy_") for k in out)

    def test_collection_with_minmax_member_raises_from_export(self):
        coll = MetricCollection({"acc": Accuracy(num_classes=3), "mm": MinMaxMetric(Accuracy(num_classes=3))})
        with pytest.raises(NotImplementedError, match="stateful compute"):
            coll.as_functions()
        # the module API is unaffected: eager fan-out still works
        coll.update(jnp.asarray(_rng.rand(8, 3).astype(np.float32)), jnp.asarray(_rng.randint(0, 3, 8)))
        assert set(coll.compute()) >= {"acc", "raw", "max", "min"}


class TestNonExportableWrappersRaise:
    def test_minmax_raises_with_guidance(self):
        with pytest.raises(NotImplementedError, match="stateful compute"):
            MinMaxMetric(Accuracy()).as_functions()

    def test_bootstrapper_raises_with_guidance(self):
        with pytest.raises(NotImplementedError, match="RNG"):
            BootStrapper(MeanSquaredError(), num_bootstraps=4).as_functions()

    def test_tracker_has_no_export(self):
        # MetricTracker is a bookkeeping container, not a Metric subclass —
        # there is deliberately no as_functions surface to misuse
        assert not hasattr(MetricTracker(Accuracy()), "as_functions")

    def test_child_holding_wrappers_are_not_fusable(self):
        # defense-in-depth: an empty-state wrapper must never look fusable to
        # the fused-forward machinery (a fused no-op would drop child updates)
        assert not MinMaxMetric(Accuracy())._fusable_states()
        assert not BootStrapper(MeanSquaredError())._fusable_states()
