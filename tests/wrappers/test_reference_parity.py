"""Differential tests for the L4 wrappers vs the mounted reference."""
import jax.numpy as jnp
import numpy as np
import pytest
import torch

from tests.helpers.reference_oracle import get_reference

_ref = get_reference()
pytestmark = pytest.mark.skipif(_ref is None, reason="reference mount unavailable")

import metrics_tpu as mt  # noqa: E402

_rng = np.random.RandomState(3)
_PREDS = _rng.rand(4, 32, 5).astype(np.float32)
_PREDS /= _PREDS.sum(-1, keepdims=True)
_TARGET = _rng.randint(0, 5, (4, 32))
_REG_P = _rng.randn(4, 32, 2).astype(np.float32)
_REG_T = (_REG_P + 0.3 * _rng.randn(4, 32, 2)).astype(np.float32)


def test_classwise_wrapper_parity():
    ours = mt.ClasswiseWrapper(mt.Accuracy(num_classes=5, average="none"))
    ref = _ref.ClasswiseWrapper(_ref.Accuracy(num_classes=5, average="none"))
    for i in range(4):
        ours.update(jnp.asarray(_PREDS[i]), jnp.asarray(_TARGET[i]))
        ref.update(torch.tensor(_PREDS[i]), torch.tensor(_TARGET[i]))
    ov, rv = ours.compute(), ref.compute()
    assert set(ov) == set(rv)
    for k in ov:
        np.testing.assert_allclose(np.asarray(ov[k]), rv[k].numpy(), atol=1e-6)


def test_minmax_parity():
    ours = mt.MinMaxMetric(mt.Accuracy(num_classes=5))
    ref = _ref.MinMaxMetric(_ref.Accuracy(num_classes=5))
    for i in range(4):
        ours(jnp.asarray(_PREDS[i]), jnp.asarray(_TARGET[i]))
        ref(torch.tensor(_PREDS[i]), torch.tensor(_TARGET[i]))
    ov, rv = ours.compute(), ref.compute()
    for k in ("raw", "min", "max"):
        np.testing.assert_allclose(np.asarray(ov[k]), rv[k].numpy(), atol=1e-6)


def test_multioutput_parity():
    ours = mt.MultioutputWrapper(mt.MeanSquaredError(), num_outputs=2)
    ref = _ref.MultioutputWrapper(_ref.MeanSquaredError(), num_outputs=2)
    for i in range(4):
        ours.update(jnp.asarray(_REG_P[i]), jnp.asarray(_REG_T[i]))
        ref.update(torch.tensor(_REG_P[i]), torch.tensor(_REG_T[i]))
    ov = np.asarray(ours.compute())
    rv = torch.stack(list(ref.compute())).numpy() if isinstance(ref.compute(), (list, tuple)) else ref.compute().numpy()
    np.testing.assert_allclose(ov.reshape(-1), rv.reshape(-1), atol=1e-6)


def test_tracker_parity():
    ours = mt.MetricTracker(mt.Accuracy(num_classes=5), maximize=True)
    ref = _ref.MetricTracker(_ref.Accuracy(num_classes=5), maximize=True)
    for step in range(3):
        ours.increment()
        ref.increment()
        for i in range(2):
            ours.update(jnp.asarray(_PREDS[(step + i) % 4]), jnp.asarray(_TARGET[(step + i) % 4]))
            ref.update(torch.tensor(_PREDS[(step + i) % 4]), torch.tensor(_TARGET[(step + i) % 4]))
    np.testing.assert_allclose(
        np.asarray(ours.compute_all()), ref.compute_all().numpy(), atol=1e-6
    )
    ob, oi = ours.best_metric(return_step=True)
    rb, ri = ref.best_metric(return_step=True)
    np.testing.assert_allclose(float(ob), float(rb), atol=1e-6)
    assert int(oi) == int(ri)


def test_bootstrapper_statistics():
    """Bootstrap RNG streams differ; the bootstrap MEAN must agree within
    sampling error and std must be positive for a non-degenerate metric."""
    base_val = None
    ours = mt.BootStrapper(mt.MeanSquaredError(), num_bootstraps=50, mean=True, std=True)
    ref = _ref.BootStrapper(_ref.MeanSquaredError(), num_bootstraps=50, mean=True, std=True)
    torch.manual_seed(0)
    for i in range(4):
        ours.update(jnp.asarray(_REG_P[i, :, 0]), jnp.asarray(_REG_T[i, :, 0]))
        ref.update(torch.tensor(_REG_P[i, :, 0]), torch.tensor(_REG_T[i, :, 0]))
        base_val = float(mt.functional.mean_squared_error(
            jnp.asarray(_REG_P[: i + 1, :, 0].ravel()), jnp.asarray(_REG_T[: i + 1, :, 0].ravel())
        ))
    ov, rv = ours.compute(), ref.compute()
    assert abs(float(ov["mean"]) - base_val) < 0.1 * base_val + 0.05
    assert abs(float(rv["mean"]) - base_val) < 0.1 * base_val + 0.05
    assert float(ov["std"]) > 0
