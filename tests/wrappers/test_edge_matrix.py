"""Constructed wrapper corner cases vs the mounted reference.

The composition layer's deliberate edges: NaN-row removal in
MultioutputWrapper, ClasswiseWrapper label naming, BootStrapper
mean/std/quantile/raw output surface, MinMax around a moving value, and
MetricTracker across increments with per-metric maximize flags — identical
data through both stacks.
"""
from __future__ import annotations

import numpy as np
import pytest
import torch

import jax.numpy as jnp

from tests.helpers.reference_oracle import get_reference

_ref = get_reference()
pytestmark = pytest.mark.skipif(_ref is None, reason="reference mount unavailable")

import metrics_tpu as mt  # noqa: E402

RNG = np.random.RandomState(31)


class TestMultioutputEdges:
    def test_nan_row_removal(self):
        """remove_nans drops rows where ANY output is NaN, per output column."""
        preds = RNG.randn(16, 3).astype(np.float32)
        target = RNG.randn(16, 3).astype(np.float32)
        target[2, 0] = np.nan
        target[5, 1] = np.nan
        preds[9, 2] = np.nan
        ours = mt.MultioutputWrapper(mt.MeanSquaredError(), num_outputs=3, remove_nans=True)
        ref = _ref.MultioutputWrapper(_ref.MeanSquaredError(), num_outputs=3, remove_nans=True)
        ours.update(jnp.asarray(preds), jnp.asarray(target))
        ref.update(torch.tensor(preds), torch.tensor(target))
        np.testing.assert_allclose(
            np.asarray(ours.compute()).reshape(-1),
            np.asarray([float(v) for v in ref.compute()]),
            atol=1e-5,
        )

    def test_squeeze_outputs_single_column(self):
        preds = RNG.randn(8, 1).astype(np.float32)
        target = RNG.randn(8, 1).astype(np.float32)
        ours = mt.MultioutputWrapper(mt.MeanAbsoluteError(), num_outputs=1)
        ref = _ref.MultioutputWrapper(_ref.MeanAbsoluteError(), num_outputs=1)
        ours.update(jnp.asarray(preds), jnp.asarray(target))
        ref.update(torch.tensor(preds), torch.tensor(target))
        np.testing.assert_allclose(
            np.asarray(ours.compute()).reshape(-1),
            np.asarray([float(v) for v in ref.compute()]).reshape(-1),
            atol=1e-5,
        )


class TestClasswiseEdges:
    def _data(self):
        preds = RNG.rand(64, 4).astype(np.float32)
        preds /= preds.sum(1, keepdims=True)
        target = RNG.randint(0, 4, 64)
        return preds, target

    def test_default_keys(self):
        preds, target = self._data()
        ours = mt.ClasswiseWrapper(mt.Accuracy(num_classes=4, average="none"))
        ref = _ref.ClasswiseWrapper(_ref.Accuracy(num_classes=4, average="none"))
        ours.update(jnp.asarray(preds), jnp.asarray(target))
        ref.update(torch.tensor(preds), torch.tensor(target))
        from tests.helpers.testers import assert_dict_outputs_equal

        assert_dict_outputs_equal(ours.compute(), {k: v.numpy() for k, v in ref.compute().items()})

    def test_custom_labels(self):
        preds, target = self._data()
        labels = ["cat", "dog", "bird", "fish"]
        ours = mt.ClasswiseWrapper(mt.Recall(num_classes=4, average="none"), labels=labels)
        ref = _ref.ClasswiseWrapper(_ref.Recall(num_classes=4, average="none"), labels=labels)
        ours.update(jnp.asarray(preds), jnp.asarray(target))
        ref.update(torch.tensor(preds), torch.tensor(target))
        from tests.helpers.testers import assert_dict_outputs_equal

        ours_out, ref_out = ours.compute(), ref.compute()
        assert_dict_outputs_equal(ours_out, {k: v.numpy() for k, v in ref_out.items()})
        assert "recall_cat" in ours_out


class TestBootstrapperSurface:
    def test_output_keys_and_shapes(self):
        """mean/std/quantile/raw output surface (values are resample-random;
        the contract is keys, shapes, and plausibility)."""
        preds = RNG.rand(128).astype(np.float32)
        target = RNG.rand(128).astype(np.float32)
        ours = mt.BootStrapper(
            mt.MeanSquaredError(), num_bootstraps=16, mean=True, std=True, quantile=0.95, raw=True
        )
        ours.update(jnp.asarray(preds), jnp.asarray(target))
        out = ours.compute()
        assert set(out) == {"mean", "std", "quantile", "raw"}
        assert np.asarray(out["raw"]).shape == (16,)
        base = float((np.asarray(preds) - np.asarray(target)) ** 2 @ np.ones(128) / 128)
        assert abs(float(out["mean"]) - base) < 0.05
        assert float(out["std"]) >= 0

    def test_reference_surface_matches(self):
        ref = _ref.BootStrapper(
            _ref.MeanSquaredError(), num_bootstraps=4, mean=True, std=True, quantile=0.9, raw=True
        )
        ref.update(torch.rand(32), torch.rand(32))
        assert set(ref.compute()) == {"mean", "std", "quantile", "raw"}

    def test_invalid_sampling_strategy_rejected_in_both(self):
        with pytest.raises(ValueError):
            mt.BootStrapper(mt.MeanSquaredError(), sampling_strategy="bogus")
        with pytest.raises(ValueError):
            _ref.BootStrapper(_ref.MeanSquaredError(), sampling_strategy="bogus")


class TestMinMaxEdges:
    def test_extrema_around_moving_value(self):
        """MinMax around a value that dips then recovers: raw tracks the
        current value, min/max keep the running extrema — both stacks."""
        target = RNG.randn(32).astype(np.float32)
        ours = mt.MinMaxMetric(mt.MeanSquaredError())
        ref = _ref.MinMaxMetric(_ref.MeanSquaredError())
        for noise in (0.8, 0.1, 0.5):
            preds = (target + noise * RNG.randn(32)).astype(np.float32)
            ours(jnp.asarray(preds), jnp.asarray(target))
            ref(torch.tensor(preds), torch.tensor(target))
        ours_out, ref_out = ours.compute(), ref.compute()
        for key in ("raw", "min", "max"):
            np.testing.assert_allclose(float(ours_out[key]), float(ref_out[key]), atol=1e-5, err_msg=key)


class TestTrackerEdges:
    def test_best_across_increments(self):
        """Three training epochs of decreasing MSE; best_metric and which_epoch."""
        ours = mt.MetricTracker(mt.MeanSquaredError(), maximize=False)
        ref = _ref.MetricTracker(_ref.MeanSquaredError(), maximize=False)
        target = RNG.randn(32).astype(np.float32)
        for noise in (1.0, 0.5, 0.1):
            preds = (target + noise * RNG.randn(32)).astype(np.float32)
            ours.increment()
            ref.increment()
            ours.update(jnp.asarray(preds), jnp.asarray(target))
            ref.update(torch.tensor(preds), torch.tensor(target))
        np.testing.assert_allclose(
            np.asarray(ours.compute_all()).reshape(-1), ref.compute_all().numpy().reshape(-1), atol=1e-5
        )
        # documented divergence: our bare best_metric() returns the VALUE (the
        # reference returns the argmax index due to an upstream unpacking
        # bug); with return_step=True the reference yields the correct
        # (value, step) pair, so THAT is the differential oracle
        ref_value, ref_step = ref.best_metric(return_step=True)
        ours_value = ours.best_metric()
        np.testing.assert_allclose(float(ours_value), float(ref_value), atol=1e-5)
        np.testing.assert_allclose(float(ours_value), float(min(np.asarray(ours.compute_all()))), atol=1e-6)
        assert ref_step == 2  # lowest-noise epoch

    def test_n_steps_and_guard(self):
        ours = mt.MetricTracker(mt.MeanSquaredError())
        ref = _ref.MetricTracker(_ref.MeanSquaredError())
        with pytest.raises(ValueError):
            ours.update(jnp.zeros(4), jnp.zeros(4))  # before increment()
        with pytest.raises(ValueError):
            ref.update(torch.zeros(4), torch.zeros(4))
        ours.increment()
        ref.increment()
        assert ours.n_steps == ref.n_steps == 1
