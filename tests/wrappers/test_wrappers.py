"""Wrapper metrics: BootStrapper, ClasswiseWrapper, MinMaxMetric, MultioutputWrapper, MetricTracker."""
import jax.numpy as jnp
import numpy as np
import pytest

from metrics_tpu import (
    Accuracy,
    BootStrapper,
    ClasswiseWrapper,
    MeanSquaredError,
    MetricTracker,
    MetricCollection,
    MinMaxMetric,
    MultioutputWrapper,
    Precision,
    Recall,
    SumMetric,
)
from tests.helpers.testers import NUM_CLASSES

_rng = np.random.RandomState(13)


class TestBootStrapper:
    def test_mean_close_to_base(self):
        base = MeanSquaredError()
        boot = BootStrapper(MeanSquaredError(), num_bootstraps=20)
        p = jnp.asarray(_rng.rand(256).astype(np.float32))
        t = jnp.asarray(_rng.rand(256).astype(np.float32))
        base.update(p, t)
        boot.update(p, t)
        out = boot.compute()
        assert set(out) == {"mean", "std"}
        assert abs(float(out["mean"]) - float(base.compute())) < 0.02
        assert float(out["std"]) > 0

    def test_quantile_and_raw(self):
        boot = BootStrapper(SumMetric(), num_bootstraps=5, quantile=0.5, raw=True)
        boot.update(jnp.asarray([1.0, 2.0, 3.0]))
        out = boot.compute()
        assert out["raw"].shape == (5,)
        assert "quantile" in out

    def test_invalid_args(self):
        with pytest.raises(ValueError, match="base metric"):
            BootStrapper("not a metric")
        with pytest.raises(ValueError, match="sampling_strategy"):
            BootStrapper(SumMetric(), sampling_strategy="bogus")

    def test_raising_child_update_does_not_count(self):
        # base-Metric failure contract: an update that raises is not counted,
        # so a caller that catches and retries does not double-count the draw
        class Exploding(MeanSquaredError):
            calls = 0

            def update(self, p, t):
                Exploding.calls += 1
                if Exploding.calls >= 3:  # raise mid-chunk-loop
                    raise RuntimeError("boom")
                super().update(p, t)

        boot = BootStrapper(Exploding(), num_bootstraps=1, sampling_strategy="poisson")
        boot._rng = np.random.RandomState(0)
        p = jnp.asarray(_rng.rand(100).astype(np.float32))
        with pytest.raises(RuntimeError, match="boom"):
            boot.update(p, p)
        assert boot.metrics[0]._update_count == 0

    @pytest.mark.parametrize("strategy", ["poisson", "multinomial"])
    def test_chunked_update_equals_one_shot_draw(self, strategy):
        # the wrapper splits poisson draws into power-of-two chunks (bounded
        # compile cache); the result must equal feeding each FULL draw to a
        # fresh clone in one update — same seed, same indices, same numbers
        from metrics_tpu.wrappers.bootstrapping import _bootstrap_sampler

        p = jnp.asarray(_rng.rand(100).astype(np.float32))  # non-power-of-two
        t = jnp.asarray(_rng.rand(100).astype(np.float32))

        boot = BootStrapper(MeanSquaredError(), num_bootstraps=3, raw=True, sampling_strategy=strategy)
        boot._rng = np.random.RandomState(1234)
        boot.update(p, t)
        boot.update(p, t)
        chunked = np.asarray(boot.compute()["raw"])

        rng = np.random.RandomState(1234)
        clones = [MeanSquaredError() for _ in range(3)]
        for _ in range(2):  # two updates, draw order matches the wrapper's
            for clone in clones:
                idx = jnp.asarray(_bootstrap_sampler(100, strategy, rng))
                if idx.size:
                    clone.update(jnp.take(p, idx), jnp.take(t, idx))
        expected = np.asarray([np.asarray(c.compute()) for c in clones])
        np.testing.assert_allclose(chunked, expected, atol=1e-6)
        # chunking is bookkept as ONE update per draw
        assert all(m._update_count == 2 for m in boot.metrics)


class TestBootStrapperFused:
    def test_fused_multinomial_matches_eager_seeded(self):
        """The one-program multinomial path replays the eager per-clone RNG
        stream: seeded runs use identical resamples, and clone states agree
        with the eager path up to XLA float reassociation (rtol ~1e-6)."""
        from metrics_tpu.utils import checks

        batches = [
            (jnp.asarray(_rng.randn(64).astype(np.float32)), jnp.asarray(_rng.randn(64).astype(np.float32)))
            for _ in range(4)
        ]

        def run(mode):
            checks.set_validation_mode(mode)
            checks._seen_check_keys.clear()
            b = BootStrapper(MeanSquaredError(), num_bootstraps=5, sampling_strategy="multinomial")
            b._rng = np.random.RandomState(42)
            for p, t in batches:
                b.update(p, t)
            return b

        prev_mode = checks._get_validation_mode()
        try:
            fused = run("first")
            eager = run("full")
        finally:
            checks.set_validation_mode(prev_mode)
        assert fused._boot_program is not None, "fused bootstrap never engaged"
        assert eager._boot_program is None
        for fm, em in zip(fused.metrics, eager.metrics):
            np.testing.assert_allclose(
                np.asarray(fm.sum_squared_error), np.asarray(em.sum_squared_error), rtol=1e-6
            )
            assert fm._update_count == em._update_count == len(batches)
        np.testing.assert_allclose(
            np.asarray(fused.compute()["mean"]), np.asarray(eager.compute()["mean"]), rtol=1e-6
        )

    def test_fused_multinomial_cat_state_base_stays_eager(self):
        """A cat-state base metric would retrace the program every step as
        its lists grow (unbounded compile cache) — the gate must keep it on
        the eager path."""
        from metrics_tpu import SpearmanCorrCoef
        from metrics_tpu.utils import checks

        prev_mode = checks._get_validation_mode()
        try:
            checks.set_validation_mode("first")
            b = BootStrapper(SpearmanCorrCoef(), num_bootstraps=3, sampling_strategy="multinomial")
            p = jnp.asarray(_rng.rand(32).astype(np.float32))
            t = jnp.asarray(_rng.rand(32).astype(np.float32))
            for _ in range(3):
                b.update(p, t)
            assert b._boot_program is None
            assert b._boot_ok  # gated, not failed
            assert all(m._update_count == 3 for m in b.metrics)
        finally:
            checks.set_validation_mode(prev_mode)

    def test_fused_multinomial_clone_mutation_falls_back(self):
        """Mutating one clone's hyperparameters de-uniformizes the clone set:
        the baked program would apply clone 0's config, so the path must
        drop to eager (which honors each clone's own config)."""
        from metrics_tpu.utils import checks

        p = jnp.asarray(_rng.rand(32).astype(np.float32))
        t = jnp.asarray(_rng.rand(32).astype(np.float32))
        prev_mode = checks._get_validation_mode()
        try:
            checks.set_validation_mode("first")
            b = BootStrapper(MeanSquaredError(), num_bootstraps=3, sampling_strategy="multinomial")
            b.update(p, t)
            b.update(p, t)
            assert b._boot_program is not None
            b.metrics[1].squared = False  # version bump on one clone only
            with pytest.warns(UserWarning, match="no longer identically configured"):
                b.update(p, t)
            assert b._boot_ok is False  # divergent configs: fast path disabled
            assert all(m._update_count == 3 for m in b.metrics)
        finally:
            checks.set_validation_mode(prev_mode)

    def test_fused_multinomial_divergent_uniform_bumps_fall_back(self):
        """Every clone mutated ONCE to a DIFFERENT value keeps the version
        counters uniform — the gate must compare actual configs, not bump
        counts, and honor each clone's own config (review regression)."""
        from metrics_tpu import Accuracy
        from metrics_tpu.utils import checks

        rng = np.random.RandomState(5)
        p = jnp.asarray(rng.rand(64).astype(np.float32))
        t = jnp.asarray(rng.randint(0, 2, 64))
        prev_mode = checks._get_validation_mode()
        try:
            checks.set_validation_mode("first")

            def run(fused):
                checks._seen_check_keys.clear()
                b = BootStrapper(Accuracy(), num_bootstraps=3, sampling_strategy="multinomial")
                b._rng = np.random.RandomState(11)
                b.update(p, t)
                b.update(p, t)
                if fused:
                    assert b._boot_program is not None
                for i, thr in enumerate((0.1, 0.2, 0.9)):
                    b.metrics[i].threshold = thr  # uniform bump, divergent values
                if not fused:
                    object.__setattr__(b, "_boot_ok", False)  # force eager truth
                import warnings

                with warnings.catch_warnings():
                    warnings.simplefilter("ignore")
                    b.update(p, t)
                return b

            got = run(fused=True)
            want = run(fused=False)
            assert got._boot_ok is False  # divergence detected and disabled
            for gm, wm in zip(got.metrics, want.metrics):
                np.testing.assert_allclose(np.asarray(gm.tp), np.asarray(wm.tp))
        finally:
            checks.set_validation_mode(prev_mode)


class TestMultioutputFused:
    def test_fused_columns_match_eager(self):
        """remove_nans=False runs all column clones as ONE program; values
        must match the per-column eager path."""
        from metrics_tpu.utils import checks

        rng = np.random.RandomState(6)
        p = rng.randn(64, 8).astype(np.float32)
        t = (p + 0.3 * rng.randn(64, 8)).astype(np.float32)

        def run(mode):
            checks.set_validation_mode(mode)
            checks._seen_check_keys.clear()
            m = MultioutputWrapper(MeanSquaredError(), num_outputs=8, remove_nans=False)
            for _ in range(3):
                m.update(jnp.asarray(p), jnp.asarray(t))
            return m

        prev_mode = checks._get_validation_mode()
        try:
            fused = run("first")
            eager = run("full")
        finally:
            checks.set_validation_mode(prev_mode)
        assert fused._mo_program is not None, "fused column fan-out never engaged"
        assert eager._mo_program is None
        np.testing.assert_allclose(
            [float(v) for v in fused.compute()], [float(v) for v in eager.compute()], rtol=1e-6
        )
        assert all(m._update_count == 3 for m in fused.metrics)

    def test_output_dim_mutation_rebuilds_program(self):
        """The program bakes output_dim; mutating it must trigger a rebuild
        (wrapper-level version is part of the staleness key), not silently
        slice the wrong axis (review regression)."""
        from metrics_tpu.utils import checks

        rng = np.random.RandomState(8)
        p = rng.randn(8, 8).astype(np.float32)  # square: wrong axis = silent corruption
        t = (p + 0.3 * rng.randn(8, 8)).astype(np.float32)
        prev_mode = checks._get_validation_mode()
        try:
            checks.set_validation_mode("first")
            m = MultioutputWrapper(MeanSquaredError(), num_outputs=8, remove_nans=False)
            m.update(jnp.asarray(p), jnp.asarray(t))
            m.update(jnp.asarray(p), jnp.asarray(t))
            assert m._mo_program is not None
            m.output_dim = 0
            m.update(jnp.asarray(p), jnp.asarray(t))
            want = MultioutputWrapper(MeanSquaredError(), num_outputs=8, output_dim=0, remove_nans=False)
            object.__setattr__(want, "_mo_ok", False)  # eager truth
            want.update(jnp.asarray(p), jnp.asarray(t))
            # last update must have sliced ROWS (axis 0): compare against one
            # eager row-sliced update on top of two column-sliced ones
            base = MultioutputWrapper(MeanSquaredError(), num_outputs=8, remove_nans=False)
            object.__setattr__(base, "_mo_ok", False)
            base.update(jnp.asarray(p), jnp.asarray(t))
            base.update(jnp.asarray(p), jnp.asarray(t))
            base.output_dim = 0
            base.update(jnp.asarray(p), jnp.asarray(t))
            np.testing.assert_allclose(
                [float(v) for v in m.compute()], [float(v) for v in base.compute()], rtol=1e-6
            )
        finally:
            checks.set_validation_mode(prev_mode)

    def test_remove_nans_default_fuses_with_masking(self):
        """remove_nans=True fuses for sum-linear bases by zero-weighting NaN
        rows INSIDE the program (round-5 contract; value parity pinned in
        tests/wrappers/test_fused_defaults.py)."""
        from metrics_tpu.utils import checks

        rng = np.random.RandomState(7)
        p = rng.randn(32, 4).astype(np.float32)
        p[0, 0] = np.nan
        t = rng.randn(32, 4).astype(np.float32)
        prev_mode = checks._get_validation_mode()
        try:
            checks.set_validation_mode("first")
            m = MultioutputWrapper(MeanSquaredError(), num_outputs=4)
            for _ in range(3):
                m.update(jnp.asarray(p), jnp.asarray(t))
            assert m._mo_program is not None
            assert m._mo_cert_done > 0
            assert np.isfinite(float(m.compute()[0]))  # nan row removed
        finally:
            checks.set_validation_mode(prev_mode)


class TestClasswiseWrapper:
    def test_names_and_values(self):
        metric = ClasswiseWrapper(Accuracy(average="none", num_classes=NUM_CLASSES))
        p = jnp.asarray(_rng.randint(0, NUM_CLASSES, 64))
        t = jnp.asarray(_rng.randint(0, NUM_CLASSES, 64))
        metric.update(p, t)
        out = metric.compute()
        assert set(out) == {f"accuracy_{i}" for i in range(NUM_CLASSES)}

    def test_custom_labels(self):
        metric = ClasswiseWrapper(Recall(average="none", num_classes=3), labels=["horse", "fish", "dog"])
        p = jnp.asarray(_rng.randint(0, 3, 32))
        t = jnp.asarray(_rng.randint(0, 3, 32))
        out = metric(p, t)
        assert set(out) == {"recall_horse", "recall_fish", "recall_dog"}

    def test_invalid(self):
        with pytest.raises(ValueError, match="metric"):
            ClasswiseWrapper("nope")
        with pytest.raises(ValueError, match="labels"):
            ClasswiseWrapper(Recall(average="none", num_classes=3), labels=[1, 2, 3])


class TestMinMax:
    def test_tracks_extrema(self):
        mm = MinMaxMetric(SumMetric())
        mm.update(jnp.asarray([2.0]))
        out1 = mm.compute()
        assert float(out1["raw"]) == 2.0 and float(out1["min"]) == 2.0 and float(out1["max"]) == 2.0
        mm.update(jnp.asarray([3.0]))
        out2 = mm.compute()
        assert float(out2["raw"]) == 5.0 and float(out2["max"]) == 5.0 and float(out2["min"]) == 2.0
        mm.reset()
        mm.update(jnp.asarray([1.0]))
        out3 = mm.compute()
        # extrema survive reset (reference contract: running extrema are
        # unregistered attributes, reset only clears the base metric)
        assert float(out3["min"]) == 1.0 and float(out3["max"]) == 5.0

    def test_invalid_base(self):
        with pytest.raises(ValueError, match="base metric"):
            MinMaxMetric("nope")


class TestMultioutput:
    def test_per_output_mse(self):
        mo = MultioutputWrapper(MeanSquaredError(), num_outputs=2)
        p = jnp.asarray(_rng.rand(32, 2).astype(np.float32))
        t = jnp.asarray(_rng.rand(32, 2).astype(np.float32))
        mo.update(p, t)
        out = mo.compute()
        assert len(out) == 2
        for i in range(2):
            ref = np.mean((np.asarray(p)[:, i] - np.asarray(t)[:, i]) ** 2)
            np.testing.assert_allclose(np.asarray(out[i]), ref, atol=1e-6)

    def test_nan_removal(self):
        mo = MultioutputWrapper(MeanSquaredError(), num_outputs=2, remove_nans=True)
        p = np.asarray(_rng.rand(8, 2), dtype=np.float32)
        t = np.asarray(_rng.rand(8, 2), dtype=np.float32)
        t[0, 0] = np.nan
        mo.update(jnp.asarray(p), jnp.asarray(t))
        out = mo.compute()
        ref0 = np.mean((p[1:, 0] - t[1:, 0]) ** 2)
        np.testing.assert_allclose(np.asarray(out[0]), ref0, atol=1e-6)


class TestTracker:
    def test_single_metric_history(self):
        tracker = MetricTracker(SumMetric(), maximize=True)
        for vals in ([1.0], [5.0], [3.0]):
            tracker.increment()
            tracker.update(jnp.asarray(vals))
        all_vals = tracker.compute_all()
        np.testing.assert_allclose(np.asarray(all_vals), [1.0, 5.0, 3.0])
        best, idx = tracker.best_metric(return_step=True)
        assert best == 5.0 and idx == 1
        assert tracker.n_steps == 3  # one per increment(), like the reference

    def test_collection_history(self):
        col = MetricCollection([Precision(num_classes=3, average="macro"), Recall(num_classes=3, average="macro")])
        tracker = MetricTracker(col, maximize=[True, True])
        for _ in range(2):
            tracker.increment()
            tracker.update(jnp.asarray(_rng.randint(0, 3, 32)), jnp.asarray(_rng.randint(0, 3, 32)))
        allv = tracker.compute_all()
        assert set(allv) == {"Precision", "Recall"}
        assert allv["Precision"].shape == (2,)
        best = tracker.best_metric()
        assert set(best) == {"Precision", "Recall"}

    def test_update_before_increment_raises(self):
        tracker = MetricTracker(SumMetric())
        with pytest.raises(ValueError, match="increment"):
            tracker.update(jnp.asarray([1.0]))

    def test_invalid_args(self):
        with pytest.raises(TypeError, match="need to be an instance"):
            MetricTracker("nope")
        with pytest.raises(ValueError, match="single bool"):
            MetricTracker(SumMetric(), maximize=[True])
