"""The full audio option grid vs the mounted reference.

Enumerates SNR zero_mean, SDR's solver grid (filter_length x zero_mean x
load_diag x use_cg_iter), SI-SNR/SI-SDR, and PIT metric_func x eval_func on
seeded multi-batch signals, every cell differentially checked against the
reference on identical data (reference `tests/unittests/audio/`, ~1k LoC).
PESQ/STOI are excluded: the reference hard-requires the pesq/pystoi packages,
absent here — our native STOI has its own golden-vector suite
(tests/audio/test_stoi.py).
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np
import pytest
import torch

from tests.helpers import cell_seed as _cell_seed
from tests.helpers.reference_oracle import get_reference

_ref = get_reference()
pytestmark = pytest.mark.skipif(_ref is None, reason="reference mount unavailable")

import metrics_tpu as mt  # noqa: E402

N_BATCHES, BATCH, T = 2, 3, 256


def _make_batches(seed: int, shape=None):
    rng = np.random.RandomState(seed)
    shape = shape or (BATCH, T)
    return [
        (rng.randn(*shape).astype(np.float32), rng.randn(*shape).astype(np.float32))
        for _ in range(N_BATCHES)
    ]


def _run_cell(name, kwargs, seed, shape=None, atol=1e-4):
    ours = getattr(mt, name)(**kwargs)
    ref = getattr(_ref, name)(**kwargs)
    for preds, target in _make_batches(seed, shape):
        ours.update(jnp.asarray(preds), jnp.asarray(target))
        ref.update(torch.tensor(preds), torch.tensor(target))
    np.testing.assert_allclose(np.asarray(ours.compute()), np.asarray(ref.compute()), atol=atol, rtol=1e-4)


class TestSnrGrid:
    @pytest.mark.parametrize("zero_mean", (False, True))
    def test_snr(self, zero_mean):
        _run_cell("SignalNoiseRatio", {"zero_mean": zero_mean}, _cell_seed("snr", zero_mean))

    def test_si_snr(self):
        _run_cell("ScaleInvariantSignalNoiseRatio", {}, _cell_seed("sisnr"))

    @pytest.mark.parametrize("zero_mean", (False, True))
    def test_si_sdr(self, zero_mean):
        _run_cell("ScaleInvariantSignalDistortionRatio", {"zero_mean": zero_mean}, _cell_seed("sisdr", zero_mean))


class TestSdrGrid:
    @pytest.mark.parametrize("filter_length", (128, 512))
    @pytest.mark.parametrize("zero_mean", (False, True))
    def test_filter_zero_mean(self, filter_length, zero_mean):
        _run_cell(
            "SignalDistortionRatio",
            {"filter_length": filter_length, "zero_mean": zero_mean},
            _cell_seed("sdr", filter_length, zero_mean),
            shape=(2, 1024),
            atol=1e-2,
        )

    @pytest.mark.parametrize("load_diag", (None, 1e-3))
    def test_load_diag(self, load_diag):
        _run_cell(
            "SignalDistortionRatio",
            {"filter_length": 128, "load_diag": load_diag},
            _cell_seed("sdr-diag", load_diag),
            shape=(2, 1024),
            atol=1e-2,
        )

    def test_use_cg_iter(self):
        """use_cg_iter=10: ours runs a real 10-step CG solve
        (functional/audio/sdr.py), the reference falls back to its exact
        torch solve because fast-bss-eval is absent here — the loose atol
        bounds CG-vs-exact disagreement on this system size."""
        _run_cell(
            "SignalDistortionRatio",
            {"filter_length": 128, "use_cg_iter": 10},
            _cell_seed("sdr-cg"),
            shape=(2, 1024),
            atol=1e-2,
        )


class TestPitGrid:
    N_SPK = 3

    @pytest.mark.parametrize("metric_key", ("si_sdr", "snr"))
    @pytest.mark.parametrize("eval_func", ("max", "min"))
    def test_pit(self, metric_key, eval_func):
        import metrics_tpu.functional as F
        import torchmetrics.functional as ref_f

        our_fn = {"si_sdr": F.scale_invariant_signal_distortion_ratio, "snr": F.signal_noise_ratio}[metric_key]
        ref_fn = {
            "si_sdr": ref_f.scale_invariant_signal_distortion_ratio,
            "snr": ref_f.signal_noise_ratio,
        }[metric_key]
        rng = np.random.RandomState(_cell_seed("pit", metric_key, eval_func))
        ours = mt.PermutationInvariantTraining(our_fn, eval_func=eval_func)
        ref = _ref.PermutationInvariantTraining(ref_fn, eval_func=eval_func)
        for _ in range(N_BATCHES):
            preds = rng.randn(2, self.N_SPK, T).astype(np.float32)
            target = rng.randn(2, self.N_SPK, T).astype(np.float32)
            ours.update(jnp.asarray(preds), jnp.asarray(target))
            ref.update(torch.tensor(preds), torch.tensor(target))
        np.testing.assert_allclose(np.asarray(ours.compute()), np.asarray(ref.compute()), atol=1e-4, rtol=1e-4)
