"""Audio metrics — differential tests against the mounted reference implementation."""
import jax.numpy as jnp
import numpy as np
import pytest

from metrics_tpu import (
    PermutationInvariantTraining,
    ScaleInvariantSignalDistortionRatio,
    ScaleInvariantSignalNoiseRatio,
    SignalDistortionRatio,
    SignalNoiseRatio,
)
from metrics_tpu.functional import (
    permutation_invariant_training,
    pit_permutate,
    scale_invariant_signal_distortion_ratio,
    scale_invariant_signal_noise_ratio,
    signal_distortion_ratio,
    signal_noise_ratio,
)
from tests.helpers.reference_oracle import get_reference
from tests.helpers.testers import NUM_BATCHES, MetricTester

_ref = get_reference()
needs_ref = pytest.mark.skipif(_ref is None, reason="reference implementation not importable")

_rng = np.random.RandomState(21)
_preds = jnp.asarray(_rng.randn(NUM_BATCHES, 4, 500).astype(np.float32))
_target = jnp.asarray(_rng.randn(NUM_BATCHES, 4, 500).astype(np.float32))
# multi-speaker inputs for PIT: [batch, spk, time]
_preds_spk = jnp.asarray(_rng.randn(NUM_BATCHES, 3, 2, 100).astype(np.float32))
_target_spk = jnp.asarray(_rng.randn(NUM_BATCHES, 3, 2, 100).astype(np.float32))


def _torch_mean(fn, **fixed):
    """Reference functional evaluated per-clip then averaged (module semantics)."""
    import torch

    def wrapped(preds, target):
        return fn(torch.from_numpy(np.asarray(preds)), torch.from_numpy(np.asarray(target)), **fixed).mean().numpy()

    return wrapped


def _torch_raw(fn, **fixed):
    import torch

    def wrapped(preds, target):
        return fn(torch.from_numpy(np.asarray(preds)), torch.from_numpy(np.asarray(target)), **fixed).numpy()

    return wrapped


@needs_ref
class TestSNR(MetricTester):
    atol = 1e-4

    @pytest.mark.parametrize("zero_mean", [False, True])
    def test_functional(self, zero_mean):
        self.run_functional_metric_test(
            _preds,
            _target,
            signal_noise_ratio,
            _torch_raw(_ref.functional.signal_noise_ratio, zero_mean=zero_mean),
            metric_args={"zero_mean": zero_mean},
        )

    @pytest.mark.parametrize("ddp", [False, True])
    def test_class(self, ddp):
        self.run_class_metric_test(
            _preds, _target, SignalNoiseRatio, _torch_mean(_ref.functional.signal_noise_ratio), ddp=ddp
        )

    def test_spmd(self):
        self.run_spmd_test(
            _preds, _target, SignalNoiseRatio, _torch_mean(_ref.functional.signal_noise_ratio)
        )


@needs_ref
class TestSiSNR(MetricTester):
    atol = 1e-4

    def test_functional(self):
        self.run_functional_metric_test(
            _preds,
            _target,
            scale_invariant_signal_noise_ratio,
            _torch_raw(_ref.functional.scale_invariant_signal_noise_ratio),
        )

    @pytest.mark.parametrize("ddp", [False, True])
    def test_class(self, ddp):
        self.run_class_metric_test(
            _preds,
            _target,
            ScaleInvariantSignalNoiseRatio,
            _torch_mean(_ref.functional.scale_invariant_signal_noise_ratio),
            ddp=ddp,
        )


@needs_ref
class TestSiSDR(MetricTester):
    atol = 1e-4

    @pytest.mark.parametrize("zero_mean", [False, True])
    def test_functional(self, zero_mean):
        self.run_functional_metric_test(
            _preds,
            _target,
            scale_invariant_signal_distortion_ratio,
            _torch_raw(_ref.functional.scale_invariant_signal_distortion_ratio, zero_mean=zero_mean),
            metric_args={"zero_mean": zero_mean},
        )

    @pytest.mark.parametrize("ddp", [False, True])
    def test_class(self, ddp):
        self.run_class_metric_test(
            _preds,
            _target,
            ScaleInvariantSignalDistortionRatio,
            _torch_mean(_ref.functional.scale_invariant_signal_distortion_ratio),
            ddp=ddp,
        )


@needs_ref
class TestSDR(MetricTester):
    # reference solves in float64; our CPU-test path is float32 with unit-norm
    # conditioning — dB-scale agreement to ~1e-2 is the expected precision gap
    atol = 5e-2

    @pytest.mark.parametrize("zero_mean", [False, True])
    def test_functional(self, zero_mean):
        self.run_functional_metric_test(
            _preds,
            _target,
            signal_distortion_ratio,
            _torch_raw(_ref.functional.signal_distortion_ratio, zero_mean=zero_mean, filter_length=64),
            metric_args={"zero_mean": zero_mean, "filter_length": 64},
        )

    def test_load_diag(self):
        import torch

        got = signal_distortion_ratio(_preds[0], _target[0], filter_length=64, load_diag=1e-3)
        ref = _ref.functional.signal_distortion_ratio(
            torch.from_numpy(np.asarray(_preds[0])), torch.from_numpy(np.asarray(_target[0])),
            filter_length=64, load_diag=1e-3,
        ).numpy()
        np.testing.assert_allclose(np.asarray(got), ref, atol=5e-2)

    def test_cg_close_to_direct(self):
        # the matrix-free CG path converges to the direct solve
        direct = signal_distortion_ratio(_preds[0], _target[0], filter_length=64)
        cg = signal_distortion_ratio(_preds[0], _target[0], filter_length=64, use_cg_iter=100)
        np.testing.assert_allclose(np.asarray(cg), np.asarray(direct), atol=1e-2)

    @pytest.mark.parametrize("ddp", [False, True])
    def test_class(self, ddp):
        self.run_class_metric_test(
            _preds,
            _target,
            SignalDistortionRatio,
            _torch_mean(_ref.functional.signal_distortion_ratio, filter_length=64),
            metric_args={"filter_length": 64},
            ddp=ddp,
        )


@needs_ref
class TestPIT(MetricTester):
    atol = 1e-4

    def test_functional(self):
        import torch

        for i in range(NUM_BATCHES):
            best_metric, best_perm = permutation_invariant_training(
                _preds_spk[i], _target_spk[i], scale_invariant_signal_distortion_ratio, "max"
            )
            ref_metric, ref_perm = _ref.functional.permutation_invariant_training(
                torch.from_numpy(np.asarray(_preds_spk[i])),
                torch.from_numpy(np.asarray(_target_spk[i])),
                _ref.functional.scale_invariant_signal_distortion_ratio,
                "max",
            )
            np.testing.assert_allclose(np.asarray(best_metric), ref_metric.numpy(), atol=1e-4)
            np.testing.assert_array_equal(np.asarray(best_perm), ref_perm.numpy())

    def test_permutate(self):
        import torch

        _, best_perm = permutation_invariant_training(
            _preds_spk[0], _target_spk[0], scale_invariant_signal_distortion_ratio, "max"
        )
        got = pit_permutate(_preds_spk[0], best_perm)
        ref = _ref.functional.pit_permutate(
            torch.from_numpy(np.asarray(_preds_spk[0])), torch.from_numpy(np.asarray(best_perm))
        )
        np.testing.assert_allclose(np.asarray(got), ref.numpy(), atol=0)

    def test_min_eval(self):
        import torch

        best_metric, best_perm = permutation_invariant_training(
            _preds_spk[0], _target_spk[0], scale_invariant_signal_distortion_ratio, "min"
        )
        ref_metric, ref_perm = _ref.functional.permutation_invariant_training(
            torch.from_numpy(np.asarray(_preds_spk[0])),
            torch.from_numpy(np.asarray(_target_spk[0])),
            _ref.functional.scale_invariant_signal_distortion_ratio,
            "min",
        )
        np.testing.assert_allclose(np.asarray(best_metric), ref_metric.numpy(), atol=1e-4)
        np.testing.assert_array_equal(np.asarray(best_perm), ref_perm.numpy())

    def test_three_speakers_matches_lsa(self):
        import torch

        preds = jnp.asarray(_rng.randn(2, 3, 50).astype(np.float32))
        target = jnp.asarray(_rng.randn(2, 3, 50).astype(np.float32))
        best_metric, best_perm = permutation_invariant_training(
            preds, target, scale_invariant_signal_distortion_ratio, "max"
        )
        ref_metric, ref_perm = _ref.functional.permutation_invariant_training(
            torch.from_numpy(np.asarray(preds)),
            torch.from_numpy(np.asarray(target)),
            _ref.functional.scale_invariant_signal_distortion_ratio,
            "max",
        )
        np.testing.assert_allclose(np.asarray(best_metric), ref_metric.numpy(), atol=1e-4)
        np.testing.assert_array_equal(np.asarray(best_perm), ref_perm.numpy())

    @pytest.mark.parametrize("ddp", [False, True])
    def test_class(self, ddp):
        import torch

        def ref(preds, target):
            return (
                _ref.functional.permutation_invariant_training(
                    torch.from_numpy(preds),
                    torch.from_numpy(target),
                    _ref.functional.scale_invariant_signal_distortion_ratio,
                    "max",
                )[0]
                .mean()
                .numpy()
            )

        self.run_class_metric_test(
            _preds_spk,
            _target_spk,
            PermutationInvariantTraining,
            ref,
            metric_args={"metric_func": scale_invariant_signal_distortion_ratio, "eval_func": "max"},
            ddp=ddp,
        )


def test_pit_invalid_eval_func():
    with pytest.raises(ValueError, match="eval_func"):
        permutation_invariant_training(
            jnp.zeros((2, 2, 10)), jnp.zeros((2, 2, 10)), scale_invariant_signal_distortion_ratio, "mean"
        )


def test_pit_shape_mismatch():
    with pytest.raises(RuntimeError, match="same shape"):
        permutation_invariant_training(
            jnp.zeros((2, 2, 10)), jnp.zeros((2, 3, 10)), scale_invariant_signal_distortion_ratio, "max"
        )


def test_pesq_batch_path_with_fake_backend(monkeypatch):
    """Exercise the ndim>1 host round-trip with a stub backend (arg order + reshape)."""
    import sys
    import types

    import metrics_tpu.functional.audio.host as host

    calls = []
    fake = types.ModuleType("pesq")

    def fake_pesq(fs, target, preds, mode):
        calls.append((fs, target.copy(), preds.copy(), mode))
        return float(target[0])  # echo to check target/preds ordering and slicing

    fake.pesq = fake_pesq
    monkeypatch.setitem(sys.modules, "pesq", fake)
    monkeypatch.setattr(host, "_PESQ_AVAILABLE", True)

    preds = jnp.arange(2 * 3 * 16, dtype=jnp.float32).reshape(2, 3, 16)
    target = preds + 1000.0
    out = host.perceptual_evaluation_speech_quality(preds, target, 8000, "nb")
    assert out.shape == (2, 3)
    assert len(calls) == 6
    # clip (i, j) must be scored with its own target/preds rows in (fs, target, preds, mode) order
    np.testing.assert_allclose(np.asarray(out), np.asarray(target[..., 0]))
    np.testing.assert_allclose(calls[1][2], np.asarray(preds[0, 1]))


def test_pesq_gated():
    from metrics_tpu.utils.imports import _PESQ_AVAILABLE

    if not _PESQ_AVAILABLE:
        with pytest.raises(ModuleNotFoundError, match="pesq"):
            from metrics_tpu import PerceptualEvaluationSpeechQuality

            PerceptualEvaluationSpeechQuality(8000, "nb")


def test_pit_survives_abstract_trace_before_real_call():
    """Regression: the lru-cached permutation table must be host numpy. A jnp
    table built under an active trace (jax.eval_shape / jit) is a TRACER;
    caching it poisoned every later real PIT call with
    UnexpectedTracerError (found by the sweep's eval_shape mode probe)."""
    import jax

    from metrics_tpu.functional.audio.pit import _permutation_table

    _permutation_table.cache_clear()
    p = jnp.asarray(np.random.RandomState(0).randn(3, 2, 200).astype(np.float32))
    t = jnp.asarray(np.random.RandomState(1).randn(3, 2, 200).astype(np.float32))

    def fn(a, b):
        return permutation_invariant_training(a, b, scale_invariant_signal_distortion_ratio, "max")[0]

    # abstract trace FIRST (this is what populates the cache under a trace)
    jax.eval_shape(fn, p, t)
    # then the real call must still work and produce finite values
    metric, perm = permutation_invariant_training(p, t, scale_invariant_signal_distortion_ratio, "max")
    assert np.isfinite(np.asarray(metric)).all()
    assert perm.shape == (3, 2)
