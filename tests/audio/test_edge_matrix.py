"""Constructed audio corner cases vs the mounted reference.

Degenerate signals built on purpose: perfect reconstruction (infinite
ratios), zero targets/estimates, scaled copies (scale invariance), DC
offsets under zero_mean, permuted speakers for PIT, and single-sample
signals — identical data through both stacks.
"""
from __future__ import annotations

import numpy as np
import pytest
import torch

import jax.numpy as jnp

from tests.helpers.reference_oracle import get_reference

_ref = get_reference()
pytestmark = pytest.mark.skipif(_ref is None, reason="reference mount unavailable")

import metrics_tpu.functional as F  # noqa: E402

RNG = np.random.RandomState(43)
SIG = RNG.randn(2, 4000).astype(np.float32)
NOISY = (SIG + 0.1 * RNG.randn(2, 4000)).astype(np.float32)


def _close(ours, theirs, atol=1e-4):
    np.testing.assert_allclose(
        np.asarray(ours, np.float64), theirs.numpy().astype(np.float64), atol=atol, rtol=1e-4, equal_nan=True
    )


class TestPerfectAndDegenerate:
    @pytest.mark.parametrize("fn", ["signal_noise_ratio", "scale_invariant_signal_noise_ratio",
                                    "scale_invariant_signal_distortion_ratio"])
    def test_perfect_reconstruction(self, fn):
        ours = getattr(F, fn)(jnp.asarray(SIG), jnp.asarray(SIG))
        theirs = getattr(_ref.functional, fn)(torch.tensor(SIG), torch.tensor(SIG))
        # both should be effectively infinite (or the same huge eps-clamped value)
        assert np.all(np.asarray(ours) > 50) and bool((theirs > 50).all())

    def test_scale_invariance_of_si_snr(self):
        """SI-SNR of a scaled estimate equals the unscaled one in both stacks."""
        for scale in (0.1, 7.3):
            ours = F.scale_invariant_signal_noise_ratio(jnp.asarray(NOISY * scale), jnp.asarray(SIG))
            theirs = _ref.functional.scale_invariant_signal_noise_ratio(
                torch.tensor(NOISY * scale), torch.tensor(SIG)
            )
            _close(ours, theirs, atol=1e-3)

    @pytest.mark.parametrize("zero_mean", [False, True])
    def test_dc_offset(self, zero_mean):
        offset = (NOISY + 3.0).astype(np.float32)
        ours = F.signal_noise_ratio(jnp.asarray(offset), jnp.asarray(SIG), zero_mean=zero_mean)
        theirs = _ref.functional.signal_noise_ratio(torch.tensor(offset), torch.tensor(SIG), zero_mean=zero_mean)
        _close(ours, theirs, atol=1e-3)

    def test_anti_signal(self):
        """Estimate = -target: SNR of a doubled-magnitude error."""
        ours = F.signal_noise_ratio(jnp.asarray(-SIG), jnp.asarray(SIG))
        theirs = _ref.functional.signal_noise_ratio(torch.tensor(-SIG), torch.tensor(SIG))
        _close(ours, theirs, atol=1e-3)

    def test_sdr_just_above_filter_length(self):
        """600 samples vs the default 512-tap distortion filter: the Toeplitz
        solve is barely determined and both stacks agree."""
        short_t = RNG.randn(1, 600).astype(np.float32)
        short_p = (short_t + 0.2 * RNG.randn(1, 600)).astype(np.float32)
        ours = F.signal_distortion_ratio(jnp.asarray(short_p), jnp.asarray(short_t))
        theirs = _ref.functional.signal_distortion_ratio(torch.tensor(short_p), torch.tensor(short_t))
        _close(ours, theirs, atol=1e-2)

    def test_sdr_below_filter_length_does_not_crash(self):
        """Signals SHORTER than the filter length underdetermine the Toeplitz
        solve — numerically undefined territory in BOTH stacks (the zero
        residual of a perfectly overfit filter gives inf; near-singular
        systems give NaN or absurd dB values, data-dependent). The only
        contract worth pinning is that the call completes and returns the
        right shape; users needing short clips should lower filter_length."""
        for seed in (7, 43, 99):
            local = np.random.RandomState(seed)
            short_t = local.randn(1, 256).astype(np.float32)
            short_p = (short_t + 0.2 * local.randn(1, 256)).astype(np.float32)
            ours = np.asarray(F.signal_distortion_ratio(jnp.asarray(short_p), jnp.asarray(short_t)))
            assert ours.shape == (1,), seed


class TestPitEdges:
    def _speakers(self):
        target = RNG.randn(1, 3, 1000).astype(np.float32)
        # estimate = a known permutation of the targets plus noise
        perm = [2, 0, 1]
        preds = (target[:, perm] + 0.05 * RNG.randn(1, 3, 1000)).astype(np.float32)
        return preds, target, perm

    def test_recovers_known_permutation(self):
        preds, target, perm = self._speakers()
        ours_val, ours_perm = F.permutation_invariant_training(
            jnp.asarray(preds), jnp.asarray(target), F.scale_invariant_signal_noise_ratio, "max"
        )
        ref_val, ref_perm = _ref.functional.permutation_invariant_training(
            torch.tensor(preds), torch.tensor(target), _ref.functional.scale_invariant_signal_noise_ratio, "max"
        )
        _close(ours_val, ref_val, atol=1e-3)
        np.testing.assert_array_equal(np.asarray(ours_perm)[0], ref_perm.numpy()[0])
        # estimate row i holds target row perm[i], so best_perm[perm[i]] == i...
        # just pin that both stacks found the SAME permutation and that
        # permuting the preds with it reconstructs target order
        reordered = np.asarray(
            _ref.functional.pit_permutate(torch.tensor(preds), ref_perm).numpy()
        )
        np.testing.assert_allclose(reordered, target, atol=0.5)

    def test_identical_speakers_tie(self):
        """All speakers identical: every permutation scores the same."""
        one = RNG.randn(1, 1000).astype(np.float32)
        target = np.stack([one, one], axis=1)
        preds = (target + 0.1 * RNG.randn(1, 2, 1000)).astype(np.float32)
        ours_val, _ = F.permutation_invariant_training(
            jnp.asarray(preds), jnp.asarray(target), F.scale_invariant_signal_noise_ratio, "max"
        )
        ref_val, _ = _ref.functional.permutation_invariant_training(
            torch.tensor(preds), torch.tensor(target), _ref.functional.scale_invariant_signal_noise_ratio, "max"
        )
        _close(ours_val, ref_val, atol=1e-3)

    def test_min_mode(self):
        preds, target, _ = self._speakers()
        ours_val, _ = F.permutation_invariant_training(
            jnp.asarray(preds), jnp.asarray(target), F.scale_invariant_signal_noise_ratio, "min"
        )
        ref_val, _ = _ref.functional.permutation_invariant_training(
            torch.tensor(preds), torch.tensor(target), _ref.functional.scale_invariant_signal_noise_ratio, "min"
        )
        _close(ours_val, ref_val, atol=1e-3)
