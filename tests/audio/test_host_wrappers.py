"""PESQ wrapper glue, executed in CI against a stub backend (VERDICT #7).

(STOI is now a NATIVE implementation, tested in test_stoi.py.) The real
``pesq`` package is standards-locked C code absent
from this environment, so their import-gated tests skip. What CAN be locked
is every line of OUR glue: argument order into the backend (target first —
reference `functional/audio/pesq.py:79`), batch flattening/reshaping,
per-clip iteration, dtype/device handling, validation errors, and the module
metrics' mean accumulation. Stub modules with deterministic pseudo-scores
are injected into ``sys.modules`` and the availability flags monkeypatched,
so these paths execute even without the real backends.
"""
from __future__ import annotations

import sys
import types

import jax.numpy as jnp
import numpy as np
import pytest


def _pseudo_score(ref: np.ndarray, deg: np.ndarray) -> float:
    """Deterministic stand-in score: depends on BOTH signals and is
    asymmetric, so swapped target/preds argument order fails the tests."""
    return float(np.mean(ref) * 2.0 + np.mean(deg) + 1.0)


@pytest.fixture()
def stub_backends(monkeypatch):
    calls = {"pesq": []}

    pesq_mod = types.ModuleType("pesq")

    def fake_pesq(fs, ref, deg, mode):
        calls["pesq"].append((fs, np.asarray(ref).copy(), np.asarray(deg).copy(), mode))
        return _pseudo_score(np.asarray(ref), np.asarray(deg))

    pesq_mod.pesq = fake_pesq

    monkeypatch.setitem(sys.modules, "pesq", pesq_mod)
    import metrics_tpu.audio.metrics as audio_metrics
    import metrics_tpu.functional.audio.host as host

    monkeypatch.setattr(host, "_PESQ_AVAILABLE", True)
    monkeypatch.setattr(audio_metrics, "_PESQ_AVAILABLE", True)
    return calls


RNG = np.random.RandomState(3)
PREDS_1D = RNG.randn(256).astype(np.float32)
TARGET_1D = RNG.randn(256).astype(np.float32)
PREDS_3D = RNG.randn(2, 3, 256).astype(np.float32)
TARGET_3D = RNG.randn(2, 3, 256).astype(np.float32)


class TestPesqGlue:
    def test_single_clip_arg_order(self, stub_backends):
        from metrics_tpu.functional.audio.host import perceptual_evaluation_speech_quality

        out = perceptual_evaluation_speech_quality(jnp.asarray(PREDS_1D), jnp.asarray(TARGET_1D), 16000, "wb")
        assert out.shape == ()
        assert float(out) == pytest.approx(_pseudo_score(TARGET_1D, PREDS_1D), abs=1e-6)
        (fs, ref, deg, mode), = stub_backends["pesq"]
        assert fs == 16000 and mode == "wb"
        np.testing.assert_array_equal(ref, TARGET_1D)  # target FIRST, like the reference
        np.testing.assert_array_equal(deg, PREDS_1D)

    def test_batch_reshape(self, stub_backends):
        from metrics_tpu.functional.audio.host import perceptual_evaluation_speech_quality

        out = perceptual_evaluation_speech_quality(jnp.asarray(PREDS_3D), jnp.asarray(TARGET_3D), 8000, "nb")
        assert out.shape == (2, 3)
        assert len(stub_backends["pesq"]) == 6  # one backend call per clip
        want = np.asarray(
            [[_pseudo_score(TARGET_3D[i, j], PREDS_3D[i, j]) for j in range(3)] for i in range(2)]
        )
        np.testing.assert_allclose(np.asarray(out), want, atol=1e-6)

    def test_validation(self, stub_backends):
        from metrics_tpu.functional.audio.host import perceptual_evaluation_speech_quality

        with pytest.raises(ValueError, match="8000 or 16000"):
            perceptual_evaluation_speech_quality(jnp.zeros(8), jnp.zeros(8), 44100, "wb")
        with pytest.raises(ValueError, match="'wb' or 'nb'"):
            perceptual_evaluation_speech_quality(jnp.zeros(8), jnp.zeros(8), 8000, "xx")
        with pytest.raises(RuntimeError):
            perceptual_evaluation_speech_quality(jnp.zeros(8), jnp.zeros(9), 8000, "wb")

    def test_module_metric_mean(self, stub_backends):
        from metrics_tpu import PerceptualEvaluationSpeechQuality

        metric = PerceptualEvaluationSpeechQuality(8000, "nb")
        metric.update(jnp.asarray(PREDS_3D[0]), jnp.asarray(TARGET_3D[0]))
        metric.update(jnp.asarray(PREDS_1D), jnp.asarray(TARGET_1D))
        scores = [_pseudo_score(TARGET_3D[0, j], PREDS_3D[0, j]) for j in range(3)]
        scores.append(_pseudo_score(TARGET_1D, PREDS_1D))
        assert float(metric.compute()) == pytest.approx(np.mean(scores), abs=1e-5)

    def test_gated_without_backend(self):
        from metrics_tpu.functional.audio.host import _PESQ_AVAILABLE

        if _PESQ_AVAILABLE:
            pytest.skip("real pesq installed")
        from metrics_tpu.functional.audio.host import perceptual_evaluation_speech_quality

        with pytest.raises(ModuleNotFoundError, match="pip install pesq"):
            perceptual_evaluation_speech_quality(jnp.zeros(8), jnp.zeros(8), 8000, "nb")
