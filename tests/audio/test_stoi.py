"""Native STOI/ESTOI (Taal et al. 2010 / Jensen & Taal 2016).

No reference DSP package exists in this environment, so correctness rests on
four independent legs: the published constants/tables (band-matrix golden),
analytic invariants (identity scores, clean monotonic degradation with noise,
silence invariance), pinned regression values on a vendored deterministic
signal (guards drift), and — when ``pystoi`` IS installed — a direct
cross-check against it.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np
import pytest

import metrics_tpu as mt
from metrics_tpu.functional.audio.stoi import (
    FS,
    N_SEG,
    NUMBAND,
    _remove_silent_frames,
    _third_octave_band_matrix,
    native_stoi,
)
from metrics_tpu.utils.imports import _PYSTOI_AVAILABLE


def _speech_like(n: int = 30000, seed: int = 0) -> np.ndarray:
    """Amplitude-modulated multi-tone burst train — wide-band, non-silent,
    speech-shaped enough that STOI behaves in its designed regime."""
    rng = np.random.RandomState(seed)
    t = np.arange(n) / FS
    sig = np.zeros(n)
    for f0 in (220.0, 450.0, 900.0, 1800.0, 3600.0):
        sig += np.sin(2 * np.pi * f0 * t + rng.rand() * 6.28) * (0.5 + 0.5 * np.sin(2 * np.pi * 3.1 * t))
    sig += 0.05 * rng.randn(n)
    return sig.astype(np.float64)


CLEAN = _speech_like()


class TestBandMatrixGolden:
    """The published one-third-octave analysis table."""

    def test_centers_and_shape(self):
        obm, cf = _third_octave_band_matrix()
        assert obm.shape == (NUMBAND, 257)
        np.testing.assert_allclose(cf, 150.0 * 2.0 ** (np.arange(15) / 3.0))
        assert cf[0] == 150.0
        np.testing.assert_allclose(cf[-1], 150.0 * 2 ** (14 / 3), rtol=1e-12)

    def test_bands_are_disjoint_contiguous_selections(self):
        obm, _ = _third_octave_band_matrix()
        # each FFT bin belongs to at most one band; every band is non-empty
        assert obm.max() == 1.0
        assert (obm.sum(axis=0) <= 1.0).all()
        assert (obm.sum(axis=1) > 0).all()
        # edges snap to the published 2^(+-1/6) rule around each center
        f = np.linspace(0, FS, 512 + 1)[:257]
        _, cf = _third_octave_band_matrix()
        for i in range(NUMBAND):
            bins = np.flatnonzero(obm[i])
            lo, hi = f[bins[0]], f[bins[-1]]
            assert lo >= cf[i] * 2 ** (-1 / 6) - (FS / 512)
            assert hi <= cf[i] * 2 ** (1 / 6) + (FS / 512)


class TestInvariants:
    def test_identity_is_one(self):
        assert float(native_stoi(jnp.asarray(CLEAN), jnp.asarray(CLEAN), FS)) == pytest.approx(1.0, abs=1e-6)

    def test_extended_identity_is_one(self):
        val = float(native_stoi(jnp.asarray(CLEAN), jnp.asarray(CLEAN), FS, extended=True))
        assert val == pytest.approx(1.0, abs=1e-6)

    @pytest.mark.parametrize("extended", [False, True], ids=["stoi", "estoi"])
    def test_monotonic_in_noise(self, extended):
        rng = np.random.RandomState(7)
        noise = rng.randn(len(CLEAN))
        scores = [
            float(native_stoi(jnp.asarray(CLEAN + lvl * noise), jnp.asarray(CLEAN), FS, extended=extended))
            for lvl in (0.0, 0.3, 1.0, 3.0)
        ]
        assert all(a > b for a, b in zip(scores, scores[1:])), scores
        assert scores[0] == pytest.approx(1.0, abs=1e-6)

    def test_silence_padding_invariant(self):
        """Appended digital silence is removed by the 40 dB VAD; the score
        must not change."""
        base = float(native_stoi(jnp.asarray(CLEAN * 0.9), jnp.asarray(CLEAN), FS))
        padded_p = np.concatenate([CLEAN * 0.9, np.zeros(4000)])
        padded_t = np.concatenate([CLEAN, np.zeros(4000)])
        padded = float(native_stoi(jnp.asarray(padded_p), jnp.asarray(padded_t), FS))
        assert padded == pytest.approx(base, abs=1e-3)

    def test_resampling_path(self):
        """A 16 kHz signal runs through the polyphase resampler and scores in
        the same ballpark as its native-rate rendition."""
        rng = np.random.RandomState(3)
        t16 = np.arange(48000) / 16000
        clean16 = sum(np.sin(2 * np.pi * f0 * t16) for f0 in (300.0, 800.0, 2000.0)) + 0.05 * rng.randn(48000)
        noisy16 = clean16 + 0.5 * rng.randn(48000)
        val = float(native_stoi(jnp.asarray(noisy16), jnp.asarray(clean16), 16000))
        assert 0.0 < val < 1.0

    def test_batch_shapes(self):
        batch_t = np.stack([CLEAN[:12000], CLEAN[8000:20000]])
        batch_p = batch_t + 0.4 * np.random.RandomState(1).randn(*batch_t.shape)
        out = native_stoi(jnp.asarray(batch_p), jnp.asarray(batch_t), FS)
        assert out.shape == (2,)
        assert (np.asarray(out) < 1.0).all() and (np.asarray(out) > 0.0).all()

    def test_too_short_warns_and_returns_degenerate_score(self):
        """pystoi-backend parity: too few non-silent frames -> warn + 1e-5,
        not an exception that aborts the caller's eval loop."""
        with pytest.warns(UserWarning, match="384 ms"):
            val = native_stoi(jnp.asarray(CLEAN[:2000]), jnp.asarray(CLEAN[:2000]), FS)
        assert float(val) == pytest.approx(1e-5)
        with pytest.warns(UserWarning, match="384 ms"):  # sub-frame clip, same path
            val = native_stoi(jnp.asarray(CLEAN[:100]), jnp.asarray(CLEAN[:100]), FS)
        assert float(val) == pytest.approx(1e-5)

    def test_vad_drops_silent_frames(self):
        x = np.concatenate([CLEAN[:5000], np.zeros(5000), CLEAN[5000:10000]])
        x_out, y_out = _remove_silent_frames(x, x.copy(), 40.0, 256, 128)
        assert len(x_out) < len(x)  # the silent middle was dropped
        np.testing.assert_allclose(x_out, y_out)


class TestRegressionPins:
    """Pinned values on a vendored deterministic signal — guards numerical
    drift of this implementation (NOT an external golden; the cross-check
    below provides that when pystoi is present)."""

    def test_pinned_scores(self):
        rng = np.random.RandomState(11)
        noisy = CLEAN + 0.8 * rng.randn(len(CLEAN))
        stoi_val = float(native_stoi(jnp.asarray(noisy), jnp.asarray(CLEAN), FS))
        estoi_val = float(native_stoi(jnp.asarray(noisy), jnp.asarray(CLEAN), FS, extended=True))
        assert 0.0 < estoi_val < stoi_val < 1.0
        # exact regression pins (update deliberately if the algorithm changes)
        assert stoi_val == pytest.approx(0.4954, abs=2e-3)
        assert estoi_val == pytest.approx(0.2492, abs=2e-3)


class TestModuleMetric:
    def test_mean_accumulation_and_sync_states(self):
        metric = mt.ShortTimeObjectiveIntelligibility(FS)
        rng = np.random.RandomState(5)
        vals = []
        for lvl in (0.2, 0.6):
            noisy = CLEAN + lvl * rng.randn(len(CLEAN))
            metric.update(jnp.asarray(noisy), jnp.asarray(CLEAN))
            vals.append(float(native_stoi(jnp.asarray(noisy), jnp.asarray(CLEAN), FS)))
        assert float(metric.compute()) == pytest.approx(np.mean(vals), abs=1e-6)
        assert metric.total == 2

    def test_extended_flag_flows(self):
        m = mt.ShortTimeObjectiveIntelligibility(FS, extended=True)
        m.update(jnp.asarray(CLEAN), jnp.asarray(CLEAN))
        assert float(m.compute()) == pytest.approx(1.0, abs=1e-6)


@pytest.mark.skipif(not _PYSTOI_AVAILABLE, reason="pystoi not installed (cross-check path)")
@pytest.mark.parametrize("extended", [False, True])
@pytest.mark.parametrize("fs", [10000, 16000])
def test_cross_check_vs_pystoi(extended, fs):
    from pystoi import stoi as pystoi_backend

    rng = np.random.RandomState(21)
    n = 3 * fs
    t = np.arange(n) / fs
    clean = sum(np.sin(2 * np.pi * f0 * t) for f0 in (250.0, 700.0, 1500.0)) + 0.05 * rng.randn(n)
    noisy = clean + 0.7 * rng.randn(n)
    ours = float(native_stoi(jnp.asarray(noisy), jnp.asarray(clean), fs, extended))
    theirs = float(pystoi_backend(clean, noisy, fs, extended))
    assert ours == pytest.approx(theirs, abs=2e-3)
