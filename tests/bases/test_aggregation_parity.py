"""Aggregation metrics vs the mounted reference: nan strategies × dtypes ×
scalar/array/weighted inputs on identical data."""
from __future__ import annotations

import warnings

import jax.numpy as jnp
import numpy as np
import pytest
import torch

from tests.helpers.reference_oracle import get_reference

_ref = get_reference()
pytestmark = pytest.mark.skipif(_ref is None, reason="reference mount unavailable")

import metrics_tpu as mt  # noqa: E402

RNG = np.random.RandomState(23)
CLEAN = [RNG.randn(8).astype(np.float32) for _ in range(3)]
WITH_NAN = [np.where(RNG.rand(8) < 0.25, np.nan, v).astype(np.float32) for v in CLEAN]

_AGGREGATORS = ["MeanMetric", "SumMetric", "MaxMetric", "MinMetric"]


def _run_pair(name, batches, our_kwargs=None, weights=None):
    our_kwargs = our_kwargs or {}
    ours = getattr(mt, name)(**our_kwargs)
    ref = getattr(_ref, name)(**our_kwargs)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        for batch in batches:
            if weights is not None:
                ours.update(jnp.asarray(batch), jnp.asarray(weights))
                ref.update(torch.tensor(batch), torch.tensor(weights))
            else:
                ours.update(jnp.asarray(batch))
                ref.update(torch.tensor(batch))
        np.testing.assert_allclose(
            np.asarray(ours.compute(), np.float64),
            np.asarray(ref.compute().numpy(), np.float64),
            atol=1e-5,
            rtol=1e-5,
            equal_nan=True,
        )


@pytest.mark.parametrize("name", _AGGREGATORS)
def test_clean_arrays(name):
    _run_pair(name, CLEAN)


@pytest.mark.parametrize("name", _AGGREGATORS)
@pytest.mark.parametrize("strategy", ["warn", "ignore", 0.0, 2.5])
def test_nan_strategies(name, strategy):
    _run_pair(name, WITH_NAN, {"nan_strategy": strategy})


@pytest.mark.parametrize("name", _AGGREGATORS)
def test_nan_strategy_error_raises_in_both(name):
    ours = getattr(mt, name)(nan_strategy="error")
    ref = getattr(_ref, name)(nan_strategy="error")
    with pytest.raises(RuntimeError):
        ours.update(jnp.asarray(WITH_NAN[0]))
    with pytest.raises(RuntimeError):
        ref.update(torch.tensor(WITH_NAN[0]))


def test_scalar_updates():
    _run_pair("MeanMetric", [1.0, 2.5, -3.0])
    _run_pair("SumMetric", [1.0, 2.5, -3.0])


def test_weighted_mean():
    weights = RNG.rand(8).astype(np.float32)
    _run_pair("MeanMetric", CLEAN, weights=weights)


def test_weighted_mean_with_nan_values():
    """Divergence in our favor: the reference crashes here (it drops NaN
    values but broadcasts the unfiltered weights against the filtered shape,
    `aggregation.py:352`). We drop the weight rows alongside their values;
    pin that against a manual oracle."""
    weights = RNG.rand(8).astype(np.float32)
    metric = mt.MeanMetric(nan_strategy="ignore")
    total_num = total_den = 0.0
    for batch in WITH_NAN:
        metric.update(jnp.asarray(batch), jnp.asarray(weights))
        keep = ~np.isnan(batch)
        total_num += float((batch[keep] * weights[keep]).sum())
        total_den += float(weights[keep].sum())
    np.testing.assert_allclose(float(metric.compute()), total_num / total_den, atol=1e-5)

    ref = _ref.MeanMetric(nan_strategy="ignore")
    with pytest.raises(RuntimeError):
        ref.update(torch.tensor(WITH_NAN[0]), torch.tensor(weights))


def test_cat_metric_preserves_order():
    ours = mt.CatMetric()
    ref = _ref.CatMetric()
    for batch in CLEAN:
        ours.update(jnp.asarray(batch))
        ref.update(torch.tensor(batch))
    np.testing.assert_allclose(np.asarray(ours.compute()), ref.compute().numpy(), atol=1e-6)


def test_int_dtype_inputs():
    batches = [np.asarray([1, 2, 3]), np.asarray([4, 5, 6])]
    for name in ("SumMetric", "MaxMetric", "MinMetric"):
        _run_pair(name, batches)
