"""Round-4 advisor findings, pinned (see ADVICE.md round 4).

Each test reproduces the reported edge exactly and asserts the fixed
behavior: host-numpy bare-array states in the cat helpers, mixed-rank binary
AUROC rows under raw-row buffering, and static-attr propagation through the
fused fan-out write-back.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np
import pytest

import metrics_tpu as mt
from metrics_tpu.utils import checks
from metrics_tpu.utils.data import dim_zero_cat, dim_zero_cat_ravel


class TestBareHostArrayStates:
    def test_cat_ravel_accepts_bare_numpy(self):
        # post-reduction/restored states can be bare HOST arrays; the
        # multi-element truthiness crash was the advisor finding
        out = dim_zero_cat_ravel(np.asarray([[1.0, 2.0], [3.0, 4.0]], np.float32))
        np.testing.assert_array_equal(np.asarray(out), [1.0, 2.0, 3.0, 4.0])

    def test_cat_accepts_bare_numpy(self):
        x = np.asarray([1.0, 2.0, 3.0], np.float32)
        assert dim_zero_cat(x) is x  # type-preserving passthrough


class TestAurocMixedRankBinaryRows:
    def test_flat_then_column_rows_concat_and_compute(self):
        """(N,) then (M, 1) binary rows must canonicalize to a shared rank
        for concat — and for the pad-to-max sync gather."""
        rng = np.random.RandomState(0)
        m = mt.AUROC(pos_label=1)
        p1, t1 = rng.rand(12).astype(np.float32), rng.randint(0, 2, 12)
        p2, t2 = rng.rand(8, 1).astype(np.float32), rng.randint(0, 2, (8, 1))
        m.update(jnp.asarray(p1), jnp.asarray(t1))
        m.update(jnp.asarray(p2), jnp.asarray(t2))
        m._canonicalize_list_states()
        assert all(v.ndim == 1 for v in m.preds)
        got = float(m.compute())
        flat = mt.AUROC(pos_label=1)
        flat.update(
            jnp.asarray(np.concatenate([p1, p2.ravel()])),
            jnp.asarray(np.concatenate([t1, t2.ravel()])),
        )
        assert got == pytest.approx(float(flat.compute()), abs=1e-6)


class TestFanoutStaticAttrPropagation:
    def test_clones_see_inferred_attrs_after_fused_steps(self):
        """Accuracy infers `mode` in update; after fused fan-out steps every
        clone must carry it (the eager first pass sets clone attrs, and the
        fused write-back must keep propagating — advisor finding)."""
        prev = checks._get_validation_mode()
        checks.set_validation_mode("first")
        try:
            rng = np.random.RandomState(1)
            boot = mt.BootStrapper(mt.Accuracy(), num_bootstraps=3, sampling_strategy="multinomial")
            p = jnp.asarray(rng.rand(32).astype(np.float32))
            t = jnp.asarray(rng.randint(0, 2, 32))
            for _ in range(3):
                boot.update(p, t)
            assert boot._boot_program is not None
            modes = [m.__dict__.get("mode") for m in boot.metrics]
            assert all(v is not None for v in modes), modes
            assert len({str(v) for v in modes}) == 1
        finally:
            checks.set_validation_mode(prev)
