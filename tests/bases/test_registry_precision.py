"""EVERY exported module metric accepts bf16 inputs — the TPU-native dtype.

The reference runs fp16 precision tests per metric
(`tests/unittests/helpers/testers.py:478-534` run_precision_test_cpu/gpu);
the TPU equivalent is bfloat16, the MXU's native input dtype. This module
auto-enumerates the same registry SPEC as the distributed contract: every
metric whose canned inputs carry float arrays is fed the identical data cast
to bf16 and must (a) run, (b) produce finite values, (c) agree with its own
f32 result to bf16-appropriate tolerance. Metrics with no float inputs
(label-pair, text, SQuAD) have nothing to cast and are skipped by detection,
not by hand.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import metrics_tpu as mt
from tests.bases.test_registry_distributed import SPEC
from tests.helpers import assert_tree_close

# bf16 has ~8 mantissa bits: elementwise accumulations land within ~1e-2
# relative; these metrics amplify input rounding beyond that and get a
# documented looser bound instead of a skip.
LOOSE = {
    "SpearmanCorrCoef": 0.12,  # rank transform: ties created by rounding reorder ranks
    "PearsonCorrCoef": 5e-2,  # variance cancellation on correlated streams
    "R2Score": 5e-2,
    "ExplainedVariance": 5e-2,
    "KLDivergence": 5e-2,  # log of rounded ratios
    "SignalDistortionRatio": 0.6,  # Toeplitz solve conditioning, dB scale
    "ScaleInvariantSignalDistortionRatio": 0.25,  # log10 of residual ratios, dB scale
    "SignalNoiseRatio": 0.25,
    "ScaleInvariantSignalNoiseRatio": 0.25,
    "PermutationInvariantTraining": 0.25,
    "PeakSignalNoiseRatio": 0.12,  # log10 of bf16-rounded MSE, dB scale
    "MeanSquaredLogError": 5e-2,
    "MeanAbsolutePercentageError": 5e-2,
    "SymmetricMeanAbsolutePercentageError": 5e-2,
    "WeightedMeanAbsolutePercentageError": 5e-2,
    "TweedieDevianceScore": 5e-2,
    "CosineSimilarity": 5e-2,
    "ErrorRelativeGlobalDimensionlessSynthesis": 0.35,  # per-band RMSE/mean ratios
    "SpectralAngleMapper": 5e-2,
    "SpectralDistortionIndex": 5e-2,
    "UniversalImageQualityIndex": 5e-2,
    "StructuralSimilarityIndexMeasure": 5e-2,
    "MultiScaleStructuralSimilarityIndexMeasure": 5e-2,
    "MultioutputWrapper": 5e-2,  # wraps R2Score
    "MinMaxMetric": 5e-2,
    "BinnedRecallAtFixedPrecision": 0.25,  # threshold selection flips a whole bin
    "MeanAveragePrecision": 0.15,  # IoU threshold crossings flip matches
}
DEFAULT_RTOL = 2e-2

# Exact curves emit one point per DISTINCT score: bf16 rounding merges
# nearby scores, so the output length itself legitimately changes. The
# contract for them is finiteness + same area to loose tolerance, not
# pointwise equality.
EXACT_CURVES = {"ROC", "PrecisionRecallCurve"}


def _curve_area(xs, ys) -> float:
    order = np.argsort(xs)
    return float(np.trapezoid(np.asarray(ys, np.float64)[order], np.asarray(xs, np.float64)[order]))


def _is_float_array(x) -> bool:
    return isinstance(x, jax.Array) and bool(jnp.issubdtype(x.dtype, jnp.floating))


def _cast_tree_bf16(x):
    return jax.tree_util.tree_map(lambda v: v.astype(jnp.bfloat16) if _is_float_array(v) else v, x)


def _has_float_array(x) -> bool:
    return any(_is_float_array(v) for v in jax.tree_util.tree_leaves(x))


def _split(batch):
    # retrieval batches end in an {"indexes": ...} kwargs dict; detection
    # batches are (preds_list, target_list) and fall through as plain args
    if isinstance(batch[-1], dict) and "indexes" in batch[-1]:
        return batch[:-1], batch[-1]
    return batch, {}


def _run(factory, batches, cast):
    metric = factory()
    for batch in batches:
        args, kwargs = _split(batch)
        if cast:
            args = _cast_tree_bf16(args)
            kwargs = _cast_tree_bf16(kwargs)
        metric.update(*args, **kwargs)
    return metric.compute()


def _finite(tree):
    if isinstance(tree, dict):
        for v in tree.values():
            _finite(v)
    elif isinstance(tree, (list, tuple)):
        for v in tree:
            _finite(v)
    else:
        arr = np.asarray(tree, np.float64)
        assert np.all(np.isfinite(arr)), f"non-finite bf16 result: {arr}"


@pytest.mark.parametrize("name", sorted(SPEC))
def test_bf16_inputs(name):
    factory, batches, _ = SPEC[name]
    if not any(_has_float_array(b) for b in batches):
        pytest.skip("no float inputs to cast")
    f32 = _run(factory, batches, cast=False)
    bf16 = _run(factory, batches, cast=True)
    _finite(bf16)
    if name in EXACT_CURVES:
        (bx, by, _), (fx, fy, _) = bf16, f32
        np.testing.assert_allclose(_curve_area(bx, by), _curve_area(fx, fy), atol=5e-2)
        return
    rtol = LOOSE.get(name, DEFAULT_RTOL)
    assert_tree_close(bf16, f32, atol=rtol, rtol=rtol)


def test_state_dtype_stays_accumulation_grade():
    """bf16 INPUTS must not demote the accumulator dtypes: states are where
    rounding compounds over thousands of updates, so they stay f32/int."""
    metric = mt.MeanSquaredError()
    metric.update(jnp.ones(8, jnp.bfloat16), jnp.zeros(8, jnp.bfloat16))
    assert metric.sum_squared_error.dtype == jnp.float32
    acc = mt.Accuracy()
    acc.update(jnp.asarray([0.9, 0.2], jnp.bfloat16), jnp.asarray([1, 0]))
    assert acc.correct.dtype in (jnp.int32, jnp.float32)
