"""Load-bearing warnings asserted once, here.

The reference treats its warnings as API (`tests/integrations/test_lightning.py`
asserts on them); this file is the analogue. Each warning asserted here is
silenced in `pyproject.toml`'s suite-wide filter so registry sweeps do not
repeat it per metric — the contract that it *fires* lives in this module, so
removing the warning breaks a test rather than silently changing the API.
"""
from __future__ import annotations

import warnings

import jax.numpy as jnp
import numpy as np
import pytest

import metrics_tpu as mt
from metrics_tpu.metric import Metric


def _catch(match: str):
    """pytest.warns that tolerates the suite-wide ignore filter."""
    return pytest.warns(UserWarning, match=match)


class TestBufferWarning:
    """`_CatImageMetric` subclasses warn at construction that they buffer
    every input (reference `image/ssim.py` emits the same text)."""

    def test_ssim_warns_on_construction(self):
        with _catch("will save all targets and predictions in buffer"):
            mt.StructuralSimilarityIndexMeasure()

    def test_uqi_warns_on_construction(self):
        with _catch("will save all targets and predictions in buffer"):
            mt.UniversalImageQualityIndex()

    def test_fid_warns_on_construction(self):
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            mt.FrechetInceptionDistance(feature=64, allow_random_weights=True)
        messages = [str(w.message) for w in caught]
        assert any("will save all extracted features in buffer" in m for m in messages)
        # the random-weights waiver is its own load-bearing warning
        assert any("NOT comparable to published numbers" in m for m in messages)


class TestBatchedFallbackWarning:
    """Host-callback metrics cannot be traced under `lax.scan`; the batched
    API must warn once and fall back to per-step eager forwards permanently."""

    def test_stoi_update_many_warns_and_falls_back(self):
        from metrics_tpu.utils import checks

        fs = 10000
        rng = np.random.RandomState(0)
        # (steps, time): each scan step feeds one 1-D clip, mirroring the
        # registry chunk shape that drives STOI onto its host segmentation path
        target = jnp.asarray(rng.randn(3, 6000).astype(np.float32))
        preds = target + 0.1 * jnp.asarray(rng.randn(3, 6000).astype(np.float32))
        stoi = mt.ShortTimeObjectiveIntelligibility(fs)
        prev_mode = checks._get_validation_mode()
        checks.set_validation_mode("first")
        try:
            stoi.update_many(preds, target)  # first chunk: eager-validated
            with _catch("Falling back to per-step eager forwards"):
                stoi.update_many(preds, target)  # scan attempt -> fallback
        finally:
            checks.set_validation_mode(prev_mode)
        # the fallback is permanent and the eager path still accumulates
        assert stoi._many_ok is False
        stoi.update_many(preds, target)
        assert stoi._update_count > 0
        assert jnp.isfinite(stoi.compute())

    def test_stoi_fused_update_declines_silently(self):
        """The fused bare-update path hits the host-DSP trace wall: since
        round 5 the eval_shape probe declines fusion with NO warning (an
        untraceable update is a supported configuration) and the eager path
        keeps accumulating permanently."""
        from metrics_tpu.ops import engine
        from metrics_tpu.utils import checks

        fs = 10000
        rng = np.random.RandomState(1)
        target = jnp.asarray(rng.randn(6000).astype(np.float32))
        preds = target + 0.1 * jnp.asarray(rng.randn(6000).astype(np.float32))
        stoi = mt.ShortTimeObjectiveIntelligibility(fs)
        prev_mode = checks._get_validation_mode()
        checks.set_validation_mode("first")
        try:
            engine.set_deferred_dispatch(False)  # pin the per-call probe path
            stoi.update(preds, target)  # first signature call: eager
            with warnings.catch_warnings():
                warnings.simplefilter("error")  # a fused-fallback warning fails here
                stoi.update(preds, target)  # probe declines quietly
            assert stoi._fused_update_ok is False
            stoi.update(preds, target)

            # the DEFERRED flush declines just as silently: enqueued calls
            # hit the eval_shape probe at flush and replay eagerly, no warning
            engine.set_deferred_dispatch(True)
            stoi2 = mt.ShortTimeObjectiveIntelligibility(fs)
            stoi2.update(preds, target)
            with warnings.catch_warnings():
                warnings.simplefilter("error")
                stoi2.update(preds, target)
                stoi2.update(preds, target)
                _ = stoi2.metric_state  # observation: probe + silent replay
            assert stoi2._defer_ok is False
            assert stoi2._update_count == 3
        finally:
            engine.set_deferred_dispatch(True)
            checks.set_validation_mode(prev_mode)
        assert stoi._update_count == 3
        assert jnp.isfinite(stoi.compute())


class TestEmptyCorpusWarning:
    def test_bert_score_empty_inputs_warn(self):
        """Empty preds+references warn and return the zero triple (reference
        `functional/text/bert.py` emits the same text). The warning fires
        before any model work, so placeholder model objects suffice."""
        from metrics_tpu.functional.text.bert import bert_score

        with _catch("Predictions and references are empty"):
            out = bert_score([], [], model=object(), user_tokenizer=object())
        assert out == {"precision": [0.0], "recall": [0.0], "f1": [0.0]}


class TestComputeBeforeUpdateWarning:
    def test_compute_before_update_warns(self):
        m = mt.MeanMetric()
        with _catch("was called before the ``update``"):
            m.compute()


class TestFaultWarningDedupe:
    """ISSUE 4 satellite: fallback warnings dedupe per owner+domain — with
    the recovery edge a pathological demote/recover loop could otherwise
    emit one warning per flush; only the FIRST failure in a domain warns,
    later ones count in engine_stats()['failure_log'] only."""

    def test_deferred_flush_warning_dedupes_per_owner_domain(self):
        from metrics_tpu.ops import engine, faults
        from metrics_tpu.utils import checks

        checks.set_validation_mode("first")
        engine.set_deferred_dispatch(True)
        faults.set_recovery_policy(steps=1)  # recover after ONE clean step
        try:
            a = jnp.asarray(np.random.RandomState(3).rand(8).astype(np.float32))
            m = mt.MeanMetric()
            m.update(a)  # eager-validated
            with warnings.catch_warnings(record=True) as caught:
                warnings.simplefilter("always")
                with faults.inject_faults("flush-chunk", count=100):
                    for _ in range(3):  # fail -> recover -> fail again ...
                        m.update(a)
                        m.update(a)
                        _ = m.metric_state  # flush fails, replays eagerly
                        m.update(a)  # clean step: defer lane re-promotes
            msgs = [str(w.message) for w in caught if "Replaying the queue eagerly" in str(w.message)]
            assert len(msgs) == 1, msgs
            assert "suppressed" in msgs[0]
            # the loop really did refail (dedupe, not a single failure)
            from metrics_tpu.ops.engine import engine_stats

            assert sum(
                1 for e in engine_stats()["failure_log"] if e["site"] == "deferred-flush"
            ) >= 2
            # a DIFFERENT owner still gets its own first warning
            m2 = mt.MeanMetric()
            m2.update(a)
            m2.update(a)
            m2.update(a)
            with _catch("Replaying the queue eagerly"):
                with faults.inject_faults("flush-chunk", count=10):
                    _ = m2.metric_state
        finally:
            faults.set_recovery_policy(steps=8)
            engine.set_deferred_dispatch(True)

    def test_donation_decline_warning_dedupes_per_owner_domain(self):
        from metrics_tpu.ops import engine, faults
        from metrics_tpu.utils import checks

        checks.set_validation_mode("first")
        engine.set_deferred_dispatch(False)  # pin the per-call fused path
        faults.set_recovery_policy(steps=1)
        try:
            rng = np.random.RandomState(4)
            p = jnp.asarray(rng.rand(16).astype(np.float32))
            t = jnp.asarray(rng.randint(0, 2, 16))
            m = mt.Accuracy()
            m(p, t)
            m(p, t)  # licensed + fused
            with warnings.catch_warnings(record=True) as caught:
                warnings.simplefilter("always")
                with faults.inject_faults("donation", count=10) as plan:
                    m(p, t)  # donation faults: demote + FIRST warning
                    m(p, t)  # clean eager step: demoted lanes re-promote
                    m(p, t)  # fused again -> more donation faults: deduped
            assert plan.fired >= 2  # the loop genuinely refailed
            # one warning TOTAL for this owner's donation domain — the fused
            # forward and fused update fallbacks share the dedupe key
            msgs = [str(w.message) for w in caught if "DonationFault" in str(w.message)]
            assert len(msgs) == 1, msgs
            assert "suppressed" in msgs[0]
        finally:
            faults.set_recovery_policy(steps=8)
            engine.set_deferred_dispatch(True)


class TestFullStateUpdateWarning:
    def test_unset_full_state_update_warns_once_per_class(self):
        class Unset(Metric):
            def update(self, x):
                pass

            def compute(self):
                return jnp.asarray(0.0)

        with _catch("does not set `full_state_update`"):
            Unset()
        # second construction of the same class is silent
        with warnings.catch_warnings():
            warnings.simplefilter("error")
            Unset()
