"""Composition layer under the 8-device mesh rank-sync engine.

Runs the same certification `dryrun_multichip` performs (one emulated rank
per device): MetricCollection with MERGED compute groups, BootStrapper's
recursive clone-fleet sync, and a raw-row cat state canonicalized MID-BUFFER
by sync — each against a single-device all-data oracle. The assertions live
in `__graft_entry__.composition_sync_certification`; this test pins them in
the CI tier so the dryrun can never silently rot.
"""
from __future__ import annotations

import jax


def test_composition_layer_sync_certification():
    from __graft_entry__ import composition_sync_certification

    out = composition_sync_certification(jax.devices())
    assert set(out) == {"collection", "bootstrap", "raw_cat"}
    assert set(out["collection"]) == {"prec", "rec", "acc"}
    assert set(out["bootstrap"]) >= {"mean", "std"}
