"""The validation mode governs EVERY value-dependent check.

Round 4 gated the two remaining unconditional device->host reads — the
retrieval binary-target bound and the aggregators' NaN inspection — behind
`METRICS_TPU_VALIDATION` (each read costs a ~100 ms blocking sync through a
tunneled backend; see docs/performance.md "Input validation cost"). These
tests pin the mode contract for both: "full" = reference parity on every
update, "first" = first update per input signature only, with values staying
reference-exact for the reduction aggregators even when the check (and its
warning) is gated off.
"""
from __future__ import annotations

import warnings

import jax.numpy as jnp
import numpy as np
import pytest

import metrics_tpu as mt
from metrics_tpu.utils import checks


@pytest.fixture()
def mode():
    """Set-and-restore validation mode, clearing the seen-signature cache."""
    prev = checks._get_validation_mode()

    def _set(value):
        checks._seen_check_keys.clear()
        checks.set_validation_mode(value)

    yield _set
    checks._seen_check_keys.clear()
    checks.set_validation_mode(prev)


class TestRetrievalBinaryBound:
    BAD = (jnp.asarray([0.5, 0.2]), jnp.asarray([2, 0]), jnp.asarray([0, 0]))
    OK = (jnp.asarray([0.5, 0.2]), jnp.asarray([1, 0]), jnp.asarray([0, 0]))

    def test_full_mode_checks_every_update(self, mode):
        mode("full")
        m = mt.RetrievalMAP()
        m.update(*self.OK)
        with pytest.raises(ValueError, match="binary"):
            m.update(*self.BAD)  # not the first update — still checked

    def test_first_mode_checks_first_signature_only(self, mode):
        mode("first")
        m = mt.RetrievalMAP()
        with pytest.raises(ValueError, match="binary"):
            m.update(*self.BAD)  # first update of the signature: checked
        m.update(*self.OK)
        # same signature again, bad values: gated off by contract
        m.update(*self.BAD)

    def test_off_mode_never_checks(self, mode):
        mode("off")
        m = mt.RetrievalMAP()
        m.update(*self.BAD)


class TestAggregatorNanGate:
    def test_full_mode_warns_every_update(self, mode):
        mode("full")
        m = mt.SumMetric()
        for _ in range(2):
            with pytest.warns(UserWarning, match="nan"):
                m.update(jnp.asarray([1.0, float("nan")]))
        assert float(m.compute()) == 2.0

    def test_first_mode_values_stay_exact_without_warning(self, mode):
        """The warning is gated off after the first signature, but masked
        removal keeps the VALUES reference-exact for reduction aggregators."""
        mode("first")
        m = mt.SumMetric()
        with pytest.warns(UserWarning, match="nan"):
            m.update(jnp.asarray([1.0, float("nan")]))
        with warnings.catch_warnings():
            warnings.simplefilter("error")
            m.update(jnp.asarray([2.0, float("nan")]))  # no warning, no read
        assert float(m.compute()) == 3.0  # nans dropped both times

    @pytest.mark.parametrize(
        "ctor, batches, expected",
        [
            (mt.MaxMetric, ([1.0, float("nan")], [5.0, float("nan")]), 5.0),
            (mt.MinMetric, ([1.0, float("nan")], [-3.0, float("nan")]), -3.0),
            (mt.MeanMetric, ([2.0, float("nan")], [4.0, float("nan")]), 3.0),
        ],
    )
    def test_first_mode_masking_matches_removal(self, mode, ctor, batches, expected):
        mode("first")
        m = ctor()
        with pytest.warns(UserWarning, match="nan"):
            m.update(jnp.asarray(batches[0]))
        m.update(jnp.asarray(batches[1]))  # gated off; masked on device
        assert float(m.compute()) == pytest.approx(expected)

    def test_cat_metric_gated_off_still_removes_at_compute(self, mode):
        """CatMetric "warn"/"ignore" removal is deferred to compute(): a
        gated-off batch buffers its NaNs raw, but the concatenated result
        drops them — reference-exact values in every validation mode."""
        mode("first")
        m = mt.CatMetric()
        with pytest.warns(UserWarning, match="nan"):
            m.update(jnp.asarray([1.0, float("nan")]))  # first: checked + warned
        m.update(jnp.asarray([2.0, float("nan")]))  # gated: raw append
        np.testing.assert_array_equal(np.asarray(m.compute()), [1.0, 2.0])

    def test_cat_metric_ignore_never_reads_values(self, mode):
        """nan_strategy='ignore' needs no per-update device read at all —
        removal happens once at compute()."""
        mode("full")  # even full mode: no value check is *needed* for ignore
        m = mt.CatMetric(nan_strategy="ignore")
        m.update(jnp.asarray([1.0, float("nan"), 3.0]))
        m.update(jnp.asarray([float("nan"), 2.0]))
        np.testing.assert_array_equal(np.asarray(m.compute()), [1.0, 3.0, 2.0])

    def test_cat_metric_error_gated_off_keeps_nan_visible(self, mode):
        mode("off")
        m = mt.CatMetric(nan_strategy="error")
        m.update(jnp.asarray([1.0, float("nan")]))
        assert np.isnan(np.asarray(m.compute())).any()

    def test_error_strategy_gated_off_poisons_not_drops(self, mode):
        mode("off")
        m = mt.SumMetric(nan_strategy="error")
        m.update(jnp.asarray([1.0, float("nan")]))
        assert np.isnan(float(m.compute()))  # visible, not silently dropped

    def test_ignore_strategy_never_needs_the_read(self, mode):
        mode("full")  # even in full mode, ignore is pure device masking
        m = mt.MeanMetric(nan_strategy="ignore")
        m.update(jnp.asarray([1.0, float("nan"), 3.0]))
        assert float(m.compute()) == pytest.approx(2.0)


class TestDefaultModeAndEvictions:
    def test_default_mode_is_full(self, mode, monkeypatch):
        """Out of the box, EVERY update is value-checked: with no env var
        set the mode resolves to "full", so a later invalid batch (e.g. a
        NaN reaching CatMetric(nan_strategy='error')) raises on the
        offending call. "first" — the benched fast-path mode — is an
        explicit opt-in via METRICS_TPU_VALIDATION=first."""
        monkeypatch.delenv("METRICS_TPU_VALIDATION", raising=False)
        checks._validation_mode = None  # force re-resolution from env
        try:
            assert checks._get_validation_mode() == "full"
            monkeypatch.setenv("METRICS_TPU_VALIDATION", "first")
            checks._validation_mode = None
            assert checks._get_validation_mode() == "first"
        finally:
            checks._validation_mode = None
            mode("first")  # fixture restore path needs a concrete mode

    def test_default_mode_catches_later_invalid_batch(self, mode, monkeypatch):
        """The advisor round-5 regression scenario: under the out-of-the-box
        default, a NaN arriving on the SECOND batch (same signature as a
        clean first batch) still raises on the offending call."""
        monkeypatch.delenv("METRICS_TPU_VALIDATION", raising=False)
        checks._validation_mode = None
        try:
            m = mt.CatMetric(nan_strategy="error")
            m.update(jnp.asarray([1.0, 2.0]))
            with pytest.raises(RuntimeError, match="Encounted `nan`"):
                m.update(jnp.asarray([1.0, float("nan")]))
        finally:
            checks._validation_mode = None
            mode("first")

    def test_eviction_counter_warns_once_on_churn(self, mode, monkeypatch):
        mode("first")
        monkeypatch.setattr(checks, "_SEEN_KEYS_CAP", 8)
        arrs = [jnp.zeros(n) for n in range(1, 26)]
        with pytest.warns(UserWarning, match="evicted more than"):
            for a in arrs:
                checks._should_value_check(a, a)
        assert checks._eviction_count > 8
        # one-shot: further churn stays silent
        with warnings.catch_warnings():
            warnings.simplefilter("error")
            for a in [jnp.zeros((n, 2)) for n in range(1, 20)]:
                checks._should_value_check(a, a)


class TestFusedCountElision:
    @pytest.fixture(autouse=True)
    def _per_call_dispatch(self):
        # count elision is a property of the PER-CALL fused program
        # (the METRICS_TPU_DEFER=0 path); deferred loops never build it
        from metrics_tpu.ops import engine

        engine.set_deferred_dispatch(False)
        yield
        engine.set_deferred_dispatch(True)

    def test_mean_reduced_state_metric_keeps_count_path(self, mode):
        """PSNR's data_range state reduces by 'mean' — the fused program must
        keep the update_count argument and stay value-equal to eager."""
        mode("first")
        rng = np.random.RandomState(0)
        p = jnp.asarray(rng.rand(2, 8, 8).astype(np.float32))
        t = jnp.asarray(rng.rand(2, 8, 8).astype(np.float32))
        fused = mt.PeakSignalNoiseRatio(data_range=1.0)
        for _ in range(3):
            fused(p, t)
        assert fused._fused_needs_count is True
        mode("full")
        eager = mt.PeakSignalNoiseRatio(data_range=1.0)
        for _ in range(3):
            eager(p, t)
        np.testing.assert_allclose(float(fused.compute()), float(eager.compute()), rtol=1e-6)

    def test_sum_reduced_metric_elides_count(self, mode):
        mode("first")
        rng = np.random.RandomState(0)
        p = jnp.asarray(rng.rand(64).astype(np.float32))
        t = jnp.asarray(rng.randint(0, 2, 64))
        fused = mt.Accuracy()
        for _ in range(3):
            fused(p, t)
        assert fused._fused_needs_count is False
        mode("full")
        eager = mt.Accuracy()
        for _ in range(3):
            eager(p, t)
        np.testing.assert_allclose(float(fused.compute()), float(eager.compute()), rtol=1e-6)
