"""Fused bare-update contract.

`metric.update(...)` on sum/mean/max/min array-state metrics runs as ONE
cached jitted program per input signature (after the first, eager-validated
call per signature) — the bare-update analogue of the fused forward
(`tests/bases/test_fused_forward.py`), for epoch loops that update per step
and compute once at the end. Pins: fused == eager values, first-call eager
validation, permanent per-instance fallback on trace failure (host/string
metrics), hyperparameter invalidation, pickle hygiene, and tracer bypass.
"""
from __future__ import annotations

import pickle
import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import metrics_tpu as mt
from metrics_tpu.ops import engine
from metrics_tpu.utils import checks

RNG = np.random.RandomState(3)
BATCHES = [
    (jnp.asarray(RNG.rand(64).astype(np.float32)), jnp.asarray(RNG.randint(0, 2, 64)))
    for _ in range(5)
]


@pytest.fixture(autouse=True)
def _first_mode():
    # this file pins the PER-CALL fused dispatch contract — exactly the
    # behavior METRICS_TPU_DEFER=0 preserves; the deferred-queue analogues
    # live in tests/bases/test_deferred_dispatch.py
    checks.set_validation_mode("first")
    engine.set_deferred_dispatch(False)
    yield
    engine.set_deferred_dispatch(True)
    checks.set_validation_mode("first")


@pytest.mark.parametrize(
    "factory",
    [
        lambda: mt.Accuracy(),
        lambda: mt.MeanMetric(),
        lambda: mt.MaxMetric(),
        lambda: mt.MeanSquaredError(),
        lambda: mt.F1Score(num_classes=1, average="macro"),
    ],
    ids=["Accuracy", "MeanMetric", "MaxMetric", "MSE", "F1"],
)
def test_fused_update_equals_eager(factory):
    fused = factory()
    for p, t in BATCHES:
        if isinstance(fused, (mt.MeanMetric, mt.MaxMetric)):
            fused.update(p)
        else:
            fused.update(p, t)
    assert fused._fused_update_program is not None, "fused update never engaged"

    checks.set_validation_mode("full")  # forces the eager path throughout
    eager = factory()
    for p, t in BATCHES:
        if isinstance(eager, (mt.MeanMetric, mt.MaxMetric)):
            eager.update(p)
        else:
            eager.update(p, t)
    assert eager._fused_update_program is None
    np.testing.assert_allclose(
        np.asarray(fused.compute()), np.asarray(eager.compute()), rtol=1e-6
    )
    assert fused._update_count == eager._update_count == len(BATCHES)


def test_first_signature_call_stays_eager():
    m = mt.Accuracy()
    p, t = BATCHES[0]
    m.update(p, t)
    assert m._fused_update_program is None  # first call validated eagerly
    m.update(p, t)
    assert m._fused_update_program is not None
    # a NEW signature drops to eager once, then fuses again
    m.update(p[:32], t[:32])
    m.update(p[:32], t[:32])
    assert m._update_count == 4


def test_host_string_metric_never_enters_fusion_bookkeeping():
    """String batches are gated out BEFORE any signature/trace work: no
    doomed fused attempt, no warning, no retained signature reprs (round-5
    contract — the old path warned + permanently disabled per instance)."""
    w = mt.WordErrorRate()
    with warnings.catch_warnings():
        warnings.simplefilter("error")  # any fused-fallback warning fails here
        for _ in range(3):
            w.update(["hello world"], ["hello there"])
    assert w._fused_update_ok is True  # never attempted, never disabled
    assert w._fused_seen_signatures is None  # zero bookkeeping for host inputs
    assert round(float(w.compute()), 4) == 0.5


def test_untraceable_config_declines_fusion_silently():
    """Accuracy with label inputs and no num_classes cannot infer classes
    under tracing — the eval_shape probe declines fusion with NO warning and
    values keep flowing through the eager path (round-5 contract)."""
    m = mt.Accuracy()
    t = jnp.asarray(RNG.randint(0, 5, 64))
    p = jnp.asarray(RNG.randint(0, 5, 64))
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        for _ in range(3):
            m.update(p, t)
    assert m._fused_update_ok is False  # probe declined quietly
    assert m._fused_update_program is None
    assert 0.0 <= float(m.compute()) <= 1.0


def test_hyperparameter_mutation_invalidates_program():
    m = mt.Accuracy()
    p, t = BATCHES[0]
    m.update(p, t)
    m.update(p, t)
    assert m._fused_update_program is not None
    m.threshold = 0.7
    assert m._fused_update_program is None
    m.update(p, t)  # rebuilds against the new constant without error
    m.update(p, t)
    assert m._fused_update_program is not None


def test_pickle_drops_program_and_resumes():
    m = mt.MeanMetric()
    for p, _ in BATCHES:
        m.update(p)
    m2 = pickle.loads(pickle.dumps(m))
    assert m2._fused_update_program is None
    m2.update(BATCHES[0][0])
    np.testing.assert_allclose(
        float(m2.compute()),
        float(np.mean([np.asarray(p).mean() for p, _ in BATCHES] + [np.asarray(BATCHES[0][0]).mean()])),
        rtol=1e-6,
    )


def test_traced_update_bypasses_fusion():
    m = mt.SumMetric()
    m.update(jnp.ones(8))
    m.update(jnp.ones(8))  # fused from here on for this signature

    @jax.jit
    def step(x):
        inner = mt.SumMetric()
        inner.update(x)  # tracer input: must run inline, not dispatch a program
        return inner.value

    out = step(jnp.ones(8))
    assert float(out) == 8.0
    assert float(m.compute()) == 16.0


def test_weighted_kwargs_fuse():
    m = mt.MeanMetric()
    for v in range(4):
        m.update(jnp.asarray([float(v)]), weight=jnp.asarray([2.0]))
    assert m._fused_update_program is not None
    assert float(m.compute()) == 1.5


def test_post_probe_runtime_failure_warns_and_falls_back():
    """The eval_shape probe only vets TRACEABILITY; a program that passes it
    but fails at execution (compile/runtime) must still warn once and fall
    back permanently — the warning contract for genuine anomalies."""
    m = mt.Accuracy()
    p, t = BATCHES[0]
    m.update(p, t)
    m.update(p, t)  # licensed + probed + run: program exists
    assert m._fused_update_program is not None

    def boom(state, *a, **k):
        raise RuntimeError("simulated post-probe failure")

    object.__setattr__(m, "_fused_update_program", boom)
    with pytest.warns(UserWarning, match="Fused update for `Accuracy`"):
        m.update(p, t)
    assert m._fused_update_ok is False
    m.update(p, t)  # eager path keeps accumulating
    assert m._update_count == 4
