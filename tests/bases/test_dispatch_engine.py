"""Dispatch-engine contract (`metrics_tpu/ops/engine.py`).

Pins the three tentpole properties:

1. **Donated-state parity** — results on the donated fused paths are
   bit-identical to the pre-donation eager path across shape churn, and a
   fused step actually consumes (deletes) the previous state buffers when
   the backend supports donation.
2. **Cross-instance program cache** — a second instance of the same metric
   class + config acquires the SAME compiled program and triggers ZERO new
   program builds and ZERO new XLA compiles (counted via the shared jitted
   callable's compiled-signature counter).
3. **Donation safety rails** — registered default buffers are never donated
   (reset() must stay restorable), compute() results that alias state
   survive later donated steps, and aliased buffers (compute-group style)
   fall back to the plain twin instead of tripping XLA's duplicate-donation
   error.
"""
from __future__ import annotations

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import metrics_tpu as mt
from metrics_tpu.ops import engine
from metrics_tpu.utils import checks

RNG = np.random.RandomState(7)


@pytest.fixture(autouse=True)
def _first_mode():
    # this file pins the PER-CALL fused dispatch contract — exactly the
    # behavior METRICS_TPU_DEFER=0 preserves; the deferred-queue analogues
    # live in tests/bases/test_deferred_dispatch.py
    checks.set_validation_mode("first")
    engine.set_deferred_dispatch(False)
    yield
    engine.set_deferred_dispatch(True)
    checks.set_validation_mode("first")


def _batches(n=6):
    out = []
    for i in range(n):
        # shape churn: alternate between two batch sizes
        size = 64 if i % 2 == 0 else 48
        out.append(
            (
                jnp.asarray(RNG.rand(size).astype(np.float32)),
                jnp.asarray(RNG.randint(0, 2, size)),
            )
        )
    return out


@pytest.mark.parametrize(
    "factory,unary",
    [
        (lambda: mt.Accuracy(), False),
        (lambda: mt.MeanMetric(), True),
        (lambda: mt.SumMetric(), True),
        (lambda: mt.MeanSquaredError(), False),
    ],
    ids=["Accuracy", "MeanMetric", "SumMetric", "MSE"],
)
def test_donated_path_bitwise_equals_pre_donation_path(factory, unary, monkeypatch):
    """Donation is an aliasing policy, not a math change: the donated run
    must be BIT-identical to the same sequence through the plain (pre-
    donation) twin, across shape churn."""
    batches = _batches()

    def run(m):
        for p, t in batches:
            for _ in range(2):  # second same-signature call runs fused
                m.update(p) if unary else m.update(p, t)
        return np.asarray(m.compute())

    donated = run(factory())

    engine.reset_engine()
    monkeypatch.setattr(engine, "_donation_supported", False)  # plain twins only
    plain = run(factory())
    np.testing.assert_array_equal(donated, plain)

    # and the values agree with the fully-eager reference arm
    checks.set_validation_mode("full")
    eager = factory()
    for p, t in batches:
        for _ in range(2):
            eager.update(p) if unary else eager.update(p, t)
    assert eager._fused_update_program is None
    np.testing.assert_allclose(donated, np.asarray(eager.compute()), rtol=1e-6)


@pytest.mark.parametrize("api", ["update", "forward"])
def test_fused_step_donates_state_buffers(api):
    if not engine.donation_supported():
        pytest.skip("backend does not consume donated buffers")
    m = mt.SumMetric()
    x = jnp.asarray(RNG.rand(32).astype(np.float32))
    step = m.update if api == "update" else m
    step(x)
    step(x)  # signature licensed; next step runs fused
    held = m.value
    step(x)  # donates `held`
    assert held.is_deleted(), "fused step did not donate the previous state buffer"
    expected = 4 * float(np.asarray(x).sum()) if api == "update" else 4 * float(np.asarray(x).sum())
    step(x)
    np.testing.assert_allclose(float(m.compute()), expected, rtol=1e-5)


def test_compute_result_survives_later_donated_steps():
    m = mt.SumMetric()
    x = jnp.asarray(RNG.rand(16).astype(np.float32))
    m.update(x)
    m.update(x)
    v = m.compute()  # would alias the raw state buffer without decoupling
    m.update(x)  # donated step deletes the old state buffer
    m.update(x)
    np.testing.assert_allclose(float(v), 2 * float(np.asarray(x).sum()), rtol=1e-5)


def test_default_buffers_never_donated_reset_survives():
    m = mt.SumMetric()
    x = jnp.asarray(RNG.rand(16).astype(np.float32))
    m.update(x)
    m.update(x)
    for _ in range(3):
        m.reset()
        # first post-reset update holds the DEFAULT buffer as live state and
        # the signature is already licensed → the fused program runs at once;
        # donating the default would delete it for every later reset
        m.update(x)
        m.update(x)
    np.testing.assert_allclose(float(m.compute()), 2 * float(np.asarray(x).sum()), rtol=1e-5)


class TestCrossInstanceCache:
    def test_second_instance_compiles_zero_new_programs(self):
        engine.reset_engine()
        p = jnp.asarray(RNG.rand(64).astype(np.float32))
        t = jnp.asarray(RNG.randint(0, 2, 64))

        a = mt.Accuracy()
        a.update(p, t)
        a.update(p, t)
        exe = a._fused_update_program
        assert isinstance(exe, engine.Executable)
        builds_after_first = engine.engine_stats()["builds"]
        compiled_after_first = exe.compiled_signatures()
        assert compiled_after_first >= 1

        b = mt.Accuracy()  # same class + config
        b.update(p, t)
        b.update(p, t)
        assert b._fused_update_program is exe, "second instance did not share the program"
        assert engine.engine_stats()["builds"] == builds_after_first, "second instance built a new program"
        assert exe.compiled_signatures() == compiled_after_first, "second instance triggered a new XLA compile"
        # both instances accumulated independently through the shared program
        assert float(a.compute()) == float(b.compute())

    def test_different_config_gets_different_program(self):
        engine.reset_engine()
        p = jnp.asarray(RNG.rand(64, 4).astype(np.float32))
        t = jnp.asarray(RNG.randint(0, 4, 64))
        a = mt.Accuracy(num_classes=4, average="macro")
        b = mt.Accuracy(num_classes=4, average="micro")
        for _ in range(2):
            a.update(p, t)
            b.update(p, t)
        assert a._fused_update_program is not b._fused_update_program

    def test_collection_members_share_member_programs_across_suites(self):
        engine.reset_engine()
        p = jnp.asarray(RNG.rand(64).astype(np.float32))
        t = jnp.asarray(RNG.randn(64).astype(np.float32))

        def build():
            return mt.MetricCollection({"mse": mt.MeanSquaredError(), "mae": mt.MeanAbsoluteError()})

        c1 = build()
        for _ in range(3):
            c1(p, t)
        builds = engine.engine_stats()["builds"]
        c2 = build()
        for _ in range(3):
            c2(p, t)
        assert engine.engine_stats()["builds"] == builds, "identical suite rebuilt its whole-suite program"
        assert c2._fused_program is c1._fused_program
        for k in c1.compute():
            assert float(c1.compute()[k]) == float(c2.compute()[k])

    def test_bootstrap_clone_fleet_shares_one_program(self):
        engine.reset_engine()
        p = jnp.asarray(RNG.randn(128).astype(np.float32))
        t = jnp.asarray(RNG.randn(128).astype(np.float32))
        b1 = mt.BootStrapper(mt.MeanSquaredError(), num_bootstraps=4, sampling_strategy="multinomial")
        for _ in range(3):
            b1.update(p, t)
        builds = engine.engine_stats()["builds"]
        assert b1._boot_program is not None
        b2 = mt.BootStrapper(mt.MeanSquaredError(), num_bootstraps=4, sampling_strategy="multinomial")
        for _ in range(3):
            b2.update(p, t)
        assert b2._boot_program is b1._boot_program
        assert engine.engine_stats()["builds"] == builds

    def test_hyperparameter_change_changes_fingerprint(self):
        m1 = mt.Accuracy(threshold=0.5)
        m2 = mt.Accuracy(threshold=0.7)
        assert engine.config_fingerprint(m1) != engine.config_fingerprint(m2)
        m2.threshold = 0.5
        assert engine.config_fingerprint(m1) == engine.config_fingerprint(m2)

    def test_long_array_hyperparameters_fingerprint_by_content(self):
        """repr() truncates numpy arrays past 1000 elements — two metrics
        differing only mid-array must NOT share a program (review finding:
        the shared program would bake the first instance's thresholds)."""
        t1 = np.linspace(0, 1, 2000).astype(np.float32)
        t2 = t1.copy()
        t2[1000] = 0.123456
        m1 = mt.BinnedPrecisionRecallCurve(num_classes=1, thresholds=jnp.asarray(t1))
        m2 = mt.BinnedPrecisionRecallCurve(num_classes=1, thresholds=jnp.asarray(t2))
        assert engine.config_fingerprint(m1) != engine.config_fingerprint(m2)
        m3 = mt.BinnedPrecisionRecallCurve(num_classes=1, thresholds=jnp.asarray(t1.copy()))
        assert engine.config_fingerprint(m3) == engine.config_fingerprint(m1)

    def test_cached_program_does_not_pin_acquiring_instance(self):
        """Engine-cached step closures must not capture `self`: the global
        cache would otherwise keep discarded instances (and their state
        buffers) alive for the program's whole lifetime."""
        import gc
        import weakref

        engine.reset_engine()
        a = mt.Accuracy()
        p = jnp.asarray(RNG.rand(64).astype(np.float32))
        t = jnp.asarray(RNG.randint(0, 2, 64))
        for _ in range(3):
            a(p, t)  # fused forward built + cached through the engine
        assert isinstance(a._fused_forward, engine.Executable)
        ref = weakref.ref(a)
        del a
        gc.collect()
        assert ref() is None, "cached program kept the dropped instance alive"
        assert engine.engine_stats()["cached"] > 0  # the program itself survives


class TestDonationSafetyRails:
    def test_duplicate_buffers_take_plain_twin(self):
        # compute-group style aliasing: the same buffer at two tree positions
        # must NOT be donated (XLA raises on duplicate donation) — run() must
        # silently fall back to the plain twin and produce correct values
        leaf = jnp.asarray(3.0)
        state = {"a": leaf, "b": leaf}
        exe = engine.acquire_keyed(
            ("test-dup", object()),  # unique key: never shared
            lambda: (lambda st: {k: v + 1 for k, v in st.items()}, None, {}),
        )
        out = exe.run(state)
        assert float(out["a"]) == 4.0 and float(out["b"]) == 4.0
        assert not leaf.is_deleted()

    def test_avoid_ids_blocks_donation(self):
        if not engine.donation_supported():
            pytest.skip("backend does not consume donated buffers")
        leaf = jnp.asarray(1.0)
        state = {"a": leaf}
        exe = engine.acquire_keyed(
            ("test-avoid", object()),
            lambda: (lambda st: {k: v + 1 for k, v in st.items()}, None, {}),
        )
        exe.run(state, avoid_ids=frozenset([id(leaf)]))
        assert not leaf.is_deleted()
        exe.run({"a": jnp.asarray(2.0)})  # fresh strong-typed buffer: donatable

    def test_state_intact_detects_deleted(self):
        if not engine.donation_supported():
            pytest.skip("backend does not consume donated buffers")
        x = jnp.zeros((), jnp.float32)
        f = jax.jit(lambda s: s + 1, donate_argnums=(0,))
        f(x)
        assert not engine.state_intact({"a": x})
        assert engine.state_intact({"a": jnp.zeros((), jnp.float32)})


def test_second_untraceable_signature_declines_silently():
    """Silent-decline contract (round-5 ADVICE): once a fused program is
    licensed for one signature, a SECOND signature that cannot trace must
    decline quietly — no runtime-failure warning, fused path kept for the
    licensed signature."""
    import warnings

    class _Picky(mt.Metric):
        full_state_update = False

        def __init__(self):
            super().__init__()
            self.add_state("total", jnp.asarray(0.0), dist_reduce_fx="sum")

        def update(self, x):
            if x.ndim == 2:
                # value read: fine eagerly, untraceable under eval_shape
                self.total = self.total + float(np.asarray(x).sum())
            else:
                self.total = self.total + x.sum()

        def compute(self):
            return self.total

    m = _Picky()
    vec = jnp.asarray(RNG.rand(16).astype(np.float32))
    mat = jnp.asarray(RNG.rand(4, 4).astype(np.float32))
    m.update(vec)
    m.update(vec)  # 1-D signature licensed + fused
    assert m._fused_update_program is not None
    with warnings.catch_warnings():
        warnings.simplefilter("error")  # ANY warning fails the test
        m.update(mat)  # first sight: eager (validates)
        m.update(mat)  # would fuse; probe declines silently for THIS signature
    # the licensed signature keeps its fused program and health flag
    assert m._fused_update_ok is True
    assert m._fused_update_program is not None
    m.update(vec)  # still fused, still correct
    assert m._update_count == 5
    np.testing.assert_allclose(
        float(m.compute()),
        3 * float(np.asarray(vec).sum()) + 2 * float(np.asarray(mat).sum()),
        rtol=1e-5,
    )


def test_lane_metrics_skip_program_cache_entirely():
    """Append-only metrics ride the host fast lane, not the program cache."""
    engine.reset_engine()
    cm = mt.CatMetric()
    x = jnp.asarray(RNG.rand(8).astype(np.float32))
    for _ in range(5):
        cm.update(x)
    assert cm._update_lane is not None
    assert engine.engine_stats()["builds"] == 0
